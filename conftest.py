"""Repo-level pytest config: force a deterministic 8-device CPU mesh.

Sharding / halo-exchange logic is tested without TPU hardware via XLA's
host-platform device virtualization (SURVEY.md §4: "CPU tests with
xla_force_host_platform_device_count=8"). The hosting environment pins
JAX_PLATFORMS to its TPU plugin and pre-imports jax from a
sitecustomize, so setting env vars is not enough — we must also flip
the platform via jax.config before any backend initialization.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("MPLBACKEND", "Agg")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
