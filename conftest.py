"""Repo-level pytest config: force a deterministic 8-device CPU mesh.

Sharding / halo-exchange logic is tested without TPU hardware via
XLA's host-platform device virtualization (SURVEY.md §4: "CPU tests
with xla_force_host_platform_device_count=8"). Must run before jax
initializes, hence env vars set at conftest import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("MPLBACKEND", "Agg")
