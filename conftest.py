"""Repo-level pytest config: force a deterministic 8-device CPU mesh.

Sharding / halo-exchange logic is tested without TPU hardware via XLA's
host-platform device virtualization (SURVEY.md §4: "CPU tests with
xla_force_host_platform_device_count=8"). The hosting environment pins
JAX_PLATFORMS to its TPU plugin and pre-imports jax from a
sitecustomize, so setting env vars is not enough — we must also flip
the platform via jax.config before any backend initialization.

The ``cpu_mesh4`` fixture below is the CPU-mesh test rig (ISSUE 7):
a session-scoped 4-device channel-sharding mesh over the virtualized
host devices, so sharded == single-device byte-identity runs in
tier-1 on any CPU box.  Tests that need a different layout call
``tpudas.parallel.mesh.make_mesh`` themselves under the same 8
virtual devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("MPLBACKEND", "Agg")

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def cpu_mesh4():
    """Session-scoped 4-device channel mesh (``{'time': 1, 'ch': 4}``)
    over the CPU-virtualized devices — what the realtime sharded ==
    single-device equivalence tests run on."""
    if len(jax.devices()) < 4:
        pytest.skip(
            "needs >= 4 devices (XLA_FLAGS "
            "--xla_force_host_platform_device_count)"
        )
    from tpudas.parallel.mesh import make_mesh

    return make_mesh(4)
