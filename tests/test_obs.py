"""tpudas.obs: metrics registry, span tracing, edge health snapshot.

Pins the ISSUE 2 contracts:
- registry: thread-safe counters/gauges/histograms with labels, name
  validation, Prometheus exposition golden format;
- spans: nesting/parenting, ring-buffer eviction, registry feed,
  log_event export;
- health: atomic ``health.json`` (torn primary falls back to the
  previous good snapshot), ``metrics.prom`` exposition, and the
  realtime driver producing BOTH every round under ``TPUDAS_HEALTH=1``
  (schema-checked);
- satellites: ``log_event`` drop counting, Counters-to-registry
  mirroring, ``device_trace`` env-var logdir.
"""

import json
import os
import threading

import numpy as np
import pytest

from tpudas.obs.health import (
    HEALTH_FILENAME,
    PROM_FILENAME,
    read_health,
    validate_health,
    write_health,
    write_prom,
)
from tpudas.obs.registry import (
    DEFAULT_BUCKETS,
    MetricsRegistry,
    get_registry,
    headline,
    use_registry,
)
from tpudas.obs.trace import clear_spans, get_spans, span

T0 = np.datetime64("2023-03-22T00:00:00")


def _payload(**over):
    base = {
        "rounds": 3,
        "polls": 4,
        "mode": "stateful",
        "realtime_factor": 120.5,
        "round_realtime_factor": 118.0,
        "head_lag_seconds": 12.0,
        "redundant_ratio": 0.0,
        "carry_resume_count": 1,
        "last_round_wall_seconds": 0.25,
        "consecutive_failures": 0,
        "quarantined_files": 0,
        "degraded": False,
        "integrity_fallbacks": 0,
        "resource_degraded": False,
        "last_error": None,
    }
    base.update(over)
    return base


class TestRegistry:
    def test_counter_gauge_histogram_basics(self):
        reg = MetricsRegistry()
        c = reg.counter("tpudas_test_total", "t")
        c.inc()
        c.inc(2.5)
        assert c.value() == 3.5
        g = reg.gauge("tpudas_test_gauge", "t")
        g.set(7)
        g.inc()
        g.dec(0.5)
        assert g.value() == 7.5
        h = reg.histogram("tpudas_test_seconds", "t", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        snap = h.snapshot()
        assert snap["count"] == 3
        assert snap["buckets"] == {0.1: 1, 1.0: 2}
        assert snap["sum"] == pytest.approx(5.55)

    def test_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("tpudas_test_total", "t", labelnames=("engine",))
        c.inc(engine="fft")
        c.inc(3, engine="cascade")
        assert c.value(engine="fft") == 1
        assert c.value(engine="cascade") == 3
        with pytest.raises(ValueError):
            c.inc()  # missing declared label
        with pytest.raises(ValueError):
            c.inc(wrong="x")

    def test_name_and_type_validation(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("not_tpudas_name")
        with pytest.raises(ValueError):
            reg.counter("tpudas_Bad_Case")
        reg.counter("tpudas_test_total")
        with pytest.raises(TypeError):
            reg.gauge("tpudas_test_total")
        with pytest.raises(ValueError):
            reg.counter("tpudas_test_total", labelnames=("engine",))
        with pytest.raises(ValueError):
            reg.counter("tpudas_test_total").inc(-1)

    def test_concurrent_increments_one_counter(self):
        """The ISSUE-named concurrency contract: N threads hammering
        one counter lose no increments."""
        reg = MetricsRegistry()
        c = reg.counter("tpudas_test_total", "t")
        n_threads, per_thread = 8, 2000

        def work():
            for _ in range(per_thread):
                c.inc()

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == n_threads * per_thread

    def test_use_registry_scopes_process_registry(self):
        reg = MetricsRegistry()
        with use_registry(reg):
            get_registry().counter("tpudas_test_total", "t").inc(5)
        assert reg.value("tpudas_test_total") == 5
        # out of scope: the process registry is a different object
        assert get_registry() is not reg

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv("TPUDAS_OBS", "0")
        reg = get_registry()
        reg.counter("anything goes here").inc()  # no validation, no-op
        assert reg.snapshot() == {}
        assert reg.to_prometheus() == ""
        monkeypatch.setenv("TPUDAS_OBS", "1")
        assert get_registry() is not reg

    def test_explicit_scope_overrides_kill_switch(self, monkeypatch):
        """The bench.py pattern: a caller that installed its own
        registry asked for measurements — TPUDAS_OBS=0 must not hand
        it silent zeros (code-review finding on the e2e headline)."""
        from tpudas.utils.profiling import Counters

        monkeypatch.setenv("TPUDAS_OBS", "0")
        reg = MetricsRegistry()
        with use_registry(reg):
            assert get_registry() is reg
            Counters().add_measured(1_000_000, 10.0, 2.0)
            with span("stream.round"):
                pass
        h = headline(reg)
        assert h["channel_samples"] == 1_000_000
        assert h["realtime_factor"] == pytest.approx(5.0)
        assert reg.get("tpudas_span_seconds").snapshot(
            name="stream.round"
        )["count"] == 1
        # scope closed: the kill-switch applies again
        assert get_registry().snapshot() == {}

    def test_prometheus_exposition_golden(self):
        """Exposition format pinned token-for-token (a scraper parses
        this; drift is a breaking change)."""
        reg = MetricsRegistry()
        reg.counter(
            "tpudas_test_total", "events so far", labelnames=("mode",)
        ).inc(3, mode="stateful")
        reg.gauge("tpudas_test_lag_seconds", "head lag").set(12.5)
        h = reg.histogram(
            "tpudas_test_seconds", "round time", buckets=(0.1, 1.0)
        )
        h.observe(0.05)
        h.observe(0.7)
        expected = (
            "# HELP tpudas_test_lag_seconds head lag\n"
            "# TYPE tpudas_test_lag_seconds gauge\n"
            "tpudas_test_lag_seconds 12.5\n"
            "# HELP tpudas_test_seconds round time\n"
            "# TYPE tpudas_test_seconds histogram\n"
            'tpudas_test_seconds_bucket{le="0.1"} 1\n'
            'tpudas_test_seconds_bucket{le="1"} 2\n'
            'tpudas_test_seconds_bucket{le="+Inf"} 2\n'
            "tpudas_test_seconds_sum 0.75\n"
            "tpudas_test_seconds_count 2\n"
            "# HELP tpudas_test_total events so far\n"
            "# TYPE tpudas_test_total counter\n"
            'tpudas_test_total{mode="stateful"} 3\n'
        )
        assert reg.to_prometheus() == expected

    def test_prometheus_label_escaping(self):
        reg = MetricsRegistry()
        reg.counter(
            "tpudas_test_total", "t", labelnames=("path",)
        ).inc(path='a"b\\c\nd')
        text = reg.to_prometheus()
        assert '{path="a\\"b\\\\c\\nd"}' in text

    def test_headline_derivation(self):
        reg = MetricsRegistry()
        with use_registry(reg):
            from tpudas.utils.profiling import Counters

            ctr = Counters()
            ctr.add_measured(1000, 10.0, 2.0)
            ctr.add_redundant(100)
        h = headline(reg)
        assert h["channel_samples"] == 1000
        assert h["realtime_factor"] == pytest.approx(5.0)
        assert h["channel_samples_per_sec"] == pytest.approx(500.0)
        assert h["redundant_ratio"] == pytest.approx(0.1)
        # instance accumulator and registry agree (the "can never
        # disagree" satellite)
        assert ctr.realtime_factor == pytest.approx(h["realtime_factor"])


class TestSpans:
    def setup_method(self):
        clear_spans()

    def test_nesting_and_attrs(self):
        reg = MetricsRegistry()
        with use_registry(reg):
            with span("outer", round=1) as outer:
                with span("inner") as inner:
                    assert inner["depth"] == 1
                    assert inner["parent"] == outer["id"]
        recs = get_spans()
        # inner finishes (and lands in the ring) first
        assert [r["name"] for r in recs] == ["inner", "outer"]
        assert recs[1]["attrs"] == {"round": 1}
        assert recs[0]["duration_s"] >= 0
        # both fed the span histogram
        snap = reg.get("tpudas_span_seconds").snapshot(name="outer")
        assert snap["count"] == 1

    def test_exception_recorded_and_propagated(self):
        with use_registry(MetricsRegistry()):
            with pytest.raises(RuntimeError):
                with span("boom"):
                    raise RuntimeError("kaput")
        (rec,) = get_spans("boom")
        assert "RuntimeError" in rec["error"]

    def test_ring_eviction_bounded(self, monkeypatch):
        monkeypatch.setenv("TPUDAS_SPAN_RING", "16")
        clear_spans()
        reg = MetricsRegistry()
        with use_registry(reg):
            for i in range(40):
                with span("tick", i=i):
                    pass
        recs = get_spans()
        assert len(recs) == 16  # bounded
        # newest survive, oldest evicted
        assert [r["attrs"]["i"] for r in recs] == list(range(24, 40))
        assert reg.value("tpudas_spans_evicted_total") == 24
        monkeypatch.delenv("TPUDAS_SPAN_RING")
        clear_spans()

    def test_log_event_export(self):
        from tpudas.utils.logging import set_log_handler

        events = []
        set_log_handler(events.append)
        try:
            with use_registry(MetricsRegistry()):
                with span("exported", mode="test"):
                    pass
        finally:
            set_log_handler(None)
        (ev,) = [e for e in events if e["event"] == "span"]
        assert ev["span"] == "exported"
        assert ev["mode"] == "test"
        assert ev["duration_s"] >= 0

    def test_disabled_under_kill_switch(self, monkeypatch):
        clear_spans()
        monkeypatch.setenv("TPUDAS_OBS", "0")
        with span("invisible"):
            pass
        monkeypatch.delenv("TPUDAS_OBS")
        assert get_spans("invisible") == []


class TestHealth:
    def test_write_read_roundtrip(self, tmp_path):
        with use_registry(MetricsRegistry()):
            path = write_health(str(tmp_path), _payload())
        assert path == str(tmp_path / HEALTH_FILENAME)
        got = read_health(str(tmp_path))
        assert got["rounds"] == 3
        assert got["schema"] == 3
        assert got["written_at"] > 0
        # no stray tmp file left behind
        assert sorted(os.listdir(tmp_path)) == [HEALTH_FILENAME]

    def test_torn_primary_falls_back_to_previous_good(self, tmp_path):
        with use_registry(MetricsRegistry()):
            write_health(str(tmp_path), _payload(rounds=1))
            write_health(str(tmp_path), _payload(rounds=2))
        # simulate a torn/partial read of the primary (non-atomic copy
        # mid-write): truncated JSON
        primary = tmp_path / HEALTH_FILENAME
        primary.write_text(primary.read_text()[: 17])
        got = read_health(str(tmp_path))
        assert got is not None and got["rounds"] == 1  # last GOOD
        # both unreadable -> None
        (tmp_path / (HEALTH_FILENAME + ".prev")).write_text("{not json")
        assert read_health(str(tmp_path)) is None

    def test_invalid_payload_counted_not_raised(self, tmp_path):
        reg = MetricsRegistry()
        with use_registry(reg):
            assert write_health(str(tmp_path), {"rounds": 1}) is None
        assert reg.value("tpudas_health_write_errors_total") == 1
        assert read_health(str(tmp_path)) is None

    def test_validate_schema(self):
        validate_health({**_payload(), "schema": 3, "written_at": 0.0})
        with pytest.raises(ValueError):
            validate_health(
                {**_payload(), "schema": 99, "written_at": 0.0}
            )

    def test_write_prom(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("tpudas_test_total", "t").inc(2)
        with use_registry(reg):
            path = write_prom(str(tmp_path))
        assert path == str(tmp_path / PROM_FILENAME)
        text = (tmp_path / PROM_FILENAME).read_text()
        assert "tpudas_test_total 2\n" in text
        assert "# TYPE tpudas_test_total counter" in text


class TestRealtimeHealth:
    def test_stateful_run_writes_health_and_prom_each_round(
        self, tmp_path, monkeypatch
    ):
        """The acceptance criterion: a stateful realtime run with
        TPUDAS_HEALTH=1 drops a schema-valid health.json + parseable
        metrics.prom after EVERY processing round."""
        from tpudas.proc.streaming import run_lowpass_realtime
        from tpudas.testing import make_synthetic_spool

        monkeypatch.setenv("TPUDAS_HEALTH", "1")
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=3, file_duration=30.0, fs=100.0, n_ch=6,
            noise=0.01,
        )
        from tpudas.testing import synthetic_patch
        from tpudas.io.registry import write_patch

        state = {"fed": 0}

        def fake_sleep(_):
            if state["fed"] < 1:
                state["fed"] += 1
                t0 = T0.astype("datetime64[ns]")
                step = np.timedelta64(int(round(1e9 / 100.0)), "ns")
                n = int(30.0 * 100.0)
                for i in range(3, 5):
                    p = synthetic_patch(
                        t0=t0 + i * n * step, duration=30.0, fs=100.0,
                        n_ch=6, seed=i, phase_origin=t0, noise=0.01,
                    )
                    write_patch(
                        p, os.path.join(src, f"raw2_{i:04d}.h5")
                    )

        seen = []

        def on_round(rounds, lfp):
            got = read_health(out)
            assert got is not None, f"no health.json after round {rounds}"
            seen.append(got)
            prom = open(os.path.join(out, PROM_FILENAME)).read()
            assert "tpudas_stream_rounds_total" in prom
            assert "tpudas_proc_channel_samples_total" in prom

        reg = MetricsRegistry()
        with use_registry(reg):
            rounds = run_lowpass_realtime(
                source=src,
                output_folder=out,
                start_time=str(T0),
                output_sample_interval=1.0,
                edge_buffer=8.0,
                process_patch_size=40,
                poll_interval=0.0,
                file_duration=0.0,
                sleep_fn=fake_sleep,
                on_round=on_round,
            )
        assert rounds == 2
        assert len(seen) == 2
        last = seen[-1]
        assert last["mode"] == "stateful"
        assert last["rounds"] == 2
        assert last["last_error"] is None
        assert last["realtime_factor"] > 0
        assert last["head_lag_seconds"] is not None
        assert last["redundant_ratio"] == 0.0
        # registry saw the same run
        assert reg.value(
            "tpudas_stream_rounds_total", mode="stateful"
        ) == 2
        assert reg.value("tpudas_stream_carry_saves_total") >= 2
        assert headline(reg)["realtime_factor"] == pytest.approx(
            last["realtime_factor"], abs=0.01
        )

    def test_crash_writes_last_error(self, tmp_path, monkeypatch):
        from tpudas.proc.streaming import run_lowpass_realtime

        monkeypatch.setenv("TPUDAS_HEALTH", "1")
        out = str(tmp_path / "results")
        os.makedirs(out)

        def boom_sleep(_):
            raise RuntimeError("interrogator unplugged")

        with use_registry(MetricsRegistry()):
            with pytest.raises(Exception):
                run_lowpass_realtime(
                    source=str(tmp_path / "missing"),
                    output_folder=out,
                    start_time=str(T0),
                    output_sample_interval=1.0,
                    edge_buffer=8.0,
                    process_patch_size=40,
                    poll_interval=0.0,
                    file_duration=0.0,
                    sleep_fn=boom_sleep,
                )
        got = read_health(out)
        assert got is not None
        assert got["last_error"] is not None


class TestSatellites:
    def test_log_event_drops_counted_and_warned(self, capsys):
        from tpudas.utils import logging as tlog

        reg = MetricsRegistry()

        def bad_handler(event):
            raise ValueError("broken pipe")

        tlog.set_log_handler(bad_handler)
        drops0 = tlog.event_drops()
        try:
            with use_registry(reg):
                tlog.log_event("round_done", n=1)
                tlog.log_event("round_done", n=2)
        finally:
            tlog.set_log_handler(None)
        assert tlog.event_drops() == drops0 + 2
        assert reg.value("tpudas_log_event_drops_total") == 2
        # the one-time stderr warning (process-lifetime latch: only
        # assert it names the counter if it fired in THIS test run)
        err = capsys.readouterr().err
        if err:
            assert "tpudas_log_event_drops_total" in err

    def test_device_trace_env_logdir(self, tmp_path, monkeypatch):
        from tpudas.utils.profiling import device_trace

        monkeypatch.delenv("TPUDAS_TRACE_DIR", raising=False)
        with pytest.raises(ValueError):
            with device_trace():
                pass
        monkeypatch.setenv("TPUDAS_TRACE_DIR", str(tmp_path / "tr"))
        ran = []
        with device_trace():
            ran.append(True)  # block runs whatever the backend does
        assert ran == [True]

    def test_counters_measure_mirrors_registry(self):
        from tpudas.utils.profiling import Counters

        reg = MetricsRegistry()
        with use_registry(reg):
            ctr = Counters()
            with ctr.measure(500, 5.0):
                pass
        assert reg.value("tpudas_proc_channel_samples_total") == 500
        assert reg.value("tpudas_proc_data_seconds_total") == 5.0
        assert reg.value("tpudas_proc_wall_seconds_total") > 0
