"""tpudas.fleet: the multi-array round engine (ISSUE 8).

N=3 interleaved streams through one FleetEngine: byte-identity of
every stream against its own single-stream control, deficit
round-robin fairness under one stalled spool, mid-fleet
KeyboardInterrupt crash + resume byte-identity, fleet fsck
classify/repair across stream roots, `/s/<id>/...` routing +
`/fleet/healthz` aggregation, deterministic poll jitter, and the
driver-parity lint (tools/check_driver_parity.py) wired into tier-1.
"""

import hashlib
import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from tpudas.core.timeutils import to_datetime64
from tpudas.fleet import (
    FleetEngine,
    PollJitter,
    StreamConfig,
    StreamSpec,
)
from tpudas.io.registry import write_patch
from tpudas.testing import (
    FaultPlan,
    FaultSpec,
    install_fault_plan,
    synthetic_patch,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_driver_parity  # noqa: E402

FS = 100.0
FILE_SEC = 30.0
NCH = 6
T0 = "2023-03-22T00:00:00"


def _feed(directory, start_index, count, noise=0.01):
    """Append ``count`` contiguous files (one stream's interrogator
    cadence); ``noise`` differentiates stream content."""
    os.makedirs(directory, exist_ok=True)
    t0 = to_datetime64(T0).astype("datetime64[ns]")
    step = np.timedelta64(int(round(1e9 / FS)), "ns")
    n = int(FILE_SEC * FS)
    for i in range(start_index, start_index + count):
        p = synthetic_patch(
            t0=t0 + i * n * step, duration=FILE_SEC, fs=FS, n_ch=NCH,
            seed=i, phase_origin=t0, noise=noise,
        )
        write_patch(p, os.path.join(directory, f"raw_{i:04d}.h5"))


def _lowpass_config(**overrides):
    base = dict(
        kind="lowpass",
        start_time=T0,
        output_sample_interval=1.0,
        edge_buffer=8.0,
        process_patch_size=40,
        poll_interval=0.0,
    )
    base.update(overrides)
    return StreamConfig(**base)


def _run_control(source, out, feed_fn=None, **overrides):
    """One single-stream control via the legacy driver (the shim —
    i.e. the same runner code, driven alone)."""
    from tpudas.proc.streaming import run_lowpass_realtime

    state = {"called": False}

    def sleep(_):
        if not state["called"]:
            state["called"] = True
            if feed_fn is not None:
                feed_fn()

    kwargs = dict(
        source=source,
        output_folder=out,
        start_time=T0,
        output_sample_interval=1.0,
        edge_buffer=8.0,
        process_patch_size=40,
        poll_interval=0.0,
        sleep_fn=sleep,
    )
    kwargs.update(overrides)
    return run_lowpass_realtime(**kwargs)


def _output_shas(folder) -> dict:
    """{name: sha256} of the emitted .h5 product files."""
    out = {}
    for name in sorted(os.listdir(folder)):
        if name.startswith("LFDAS_") and name.endswith(".h5"):
            with open(os.path.join(folder, name), "rb") as fh:
                out[name] = hashlib.sha256(fh.read()).hexdigest()
    return out


def _pyramid_shas(folder) -> dict:
    """{relpath: sha256} of the tile pyramid (``.prev``/tmp excluded —
    append-schedule dependent, same rule as tools/crash_drill.py)."""
    from tpudas.serve.tiles import TILE_DIRNAME
    from tpudas.utils.atomicio import is_tmp_name

    tiles = os.path.join(folder, TILE_DIRNAME)
    out = {}
    for dirpath, _d, filenames in os.walk(tiles):
        for name in sorted(filenames):
            if ".prev" in name or is_tmp_name(name):
                continue
            path = os.path.join(dirpath, name)
            with open(path, "rb") as fh:
                out[os.path.relpath(path, tiles)] = hashlib.sha256(
                    fh.read()
                ).hexdigest()
    return out


class TestConfig:
    def test_lowpass_requires_core_fields(self):
        with pytest.raises(ValueError, match="start_time"):
            StreamConfig(kind="lowpass")

    def test_rolling_requires_window_step(self):
        with pytest.raises(ValueError, match="window and step"):
            StreamConfig(kind="rolling")

    def test_joint_params_need_rolling_folder(self):
        with pytest.raises(ValueError, match="rolling_output_folder"):
            _lowpass_config(rolling_window=3.0)

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="kind"):
            StreamConfig(kind="median")

    def test_stream_id_alphabet(self):
        cfg = StreamConfig(kind="rolling", window=1.0, step=1.0)
        with pytest.raises(ValueError, match="stream_id"):
            StreamSpec(stream_id="../escape", source=".", config=cfg)
        with pytest.raises(ValueError, match="stream_id"):
            StreamSpec(stream_id=".hidden", source=".", config=cfg)

    def test_duplicate_stream_ids_rejected(self, tmp_path):
        cfg = StreamConfig(kind="rolling", window=1.0, step=1.0)
        specs = [
            StreamSpec(stream_id="a", source=".", config=cfg),
            StreamSpec(stream_id="a", source=".", config=cfg),
        ]
        with pytest.raises(ValueError, match="duplicate"):
            FleetEngine(str(tmp_path / "root"), specs)


class TestDriverParityLint:
    def test_repo_is_clean(self):
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "check_driver_parity.py"),
            ],
            capture_output=True,
            text=True,
            timeout=240,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "check_driver_parity: OK" in proc.stdout

    def test_lint_reports_empty(self):
        assert check_driver_parity.lint() == []


class TestPollJitter:
    def test_deterministic_per_stream(self):
        a1 = [PollJitter("s0", 0.1).next_unit() for _ in range(1)]
        a2 = [PollJitter("s0", 0.1).next_unit() for _ in range(1)]
        assert a1 == a2
        j1, j2 = PollJitter("s0", 0.1), PollJitter("s1", 0.1)
        seq1 = [j1.next_unit() for _ in range(8)]
        seq2 = [j2.next_unit() for _ in range(8)]
        assert seq1 != seq2  # distinct streams de-synchronize

    def test_stretch_bounds(self):
        j = PollJitter("anything", 0.25)
        for _ in range(64):
            s = j.stretch()
            assert 1.0 <= s < 1.25

    def test_zero_fraction_is_identity(self):
        j = PollJitter("s0", 0.0)
        assert j.stretch() == 1.0

    def test_lowpass_driver_exposes_poll_jitter(self, tmp_path):
        from tpudas.proc.streaming import run_lowpass_realtime

        src = tmp_path / "empty"
        src.mkdir()
        out = str(tmp_path / "outj")
        sleeps = []
        run_lowpass_realtime(
            source=str(src),
            output_folder=out,
            start_time=T0,
            output_sample_interval=1.0,
            edge_buffer=8.0,
            process_patch_size=40,
            poll_interval=0.0,
            sleep_fn=sleeps.append,
            max_rounds=3,
            poll_jitter=0.25,
        )
        # clamp floor 125 s, stretched by the folder-seeded LCG
        from tpudas.proc.streaming import _shim_stream_id

        expected = 125.0 * PollJitter(
            _shim_stream_id(out), 0.25
        ).stretch()
        assert sleeps and sleeps[0] == pytest.approx(expected)
        assert sleeps[0] > 125.0

    def test_rolling_driver_exposes_poll_jitter(self, tmp_path):
        from tpudas.proc.streaming import run_rolling_realtime
        from tpudas.core.units import s as sec

        src = str(tmp_path / "raw")
        _feed(src, 0, 1)
        out = str(tmp_path / "rollj")
        sleeps = []
        run_rolling_realtime(
            source=src,
            output_folder=out,
            window=1.0 * sec,
            step=1.0 * sec,
            poll_interval=20.0,
            sleep_fn=sleeps.append,
            max_rounds=3,
            poll_jitter=0.5,
        )
        from tpudas.proc.streaming import _shim_stream_id

        expected = 20.0 * PollJitter(
            _shim_stream_id(out), 0.5
        ).stretch()
        assert sleeps and sleeps[0] == pytest.approx(expected)


class TestFleetByteIdentity:
    @pytest.mark.slow
    def test_three_streams_match_single_stream_controls(self, tmp_path):
        """The acceptance core, in-process: a fleet of 3 streams
        (distinct content per stream, one mid-run feed) produces
        outputs and pyramids byte-identical to 3 independent
        single-stream driver runs over the same per-stream feed
        schedule."""
        root = str(tmp_path / "root")
        noises = {"s0": 0.005, "s1": 0.01, "s2": 0.02}
        sources = {}
        specs = []
        for sid, noise in noises.items():
            src = str(tmp_path / f"src_{sid}")
            _feed(src, 0, 2, noise=noise)
            sources[sid] = src
            specs.append(
                StreamSpec(
                    stream_id=sid, source=src,
                    config=_lowpass_config(pyramid=True),
                )
            )
        fed = {"done": False}

        def fleet_sleep(_):
            if not fed["done"]:
                fed["done"] = True
                for sid, src in sources.items():
                    _feed(src, 2, 1, noise=noises[sid])

        summary = FleetEngine(root, specs, sleep_fn=fleet_sleep).run()
        assert summary["rounds_total"] == 6  # 2 rounds per stream
        assert summary["parked"] == []
        for sid in noises:
            assert summary["streams"][sid]["status"] == "terminated"
            assert summary["streams"][sid]["rounds"] == 2
        # controls: same feed schedule, one stream at a time, via the
        # legacy driver (identical runner code, driven alone)
        for sid, noise in noises.items():
            ctrl_src = str(tmp_path / f"ctrl_src_{sid}")
            _feed(ctrl_src, 0, 2, noise=noise)
            ctrl_out = str(tmp_path / f"ctrl_out_{sid}")
            _run_control(
                ctrl_src, ctrl_out,
                feed_fn=lambda s=ctrl_src, n=noise: _feed(s, 2, 1, noise=n),
                pyramid=True,
            )
            got = _output_shas(os.path.join(root, sid))
            want = _output_shas(ctrl_out)
            assert got == want, f"stream {sid} outputs differ"
            assert got  # non-vacuous
            assert _pyramid_shas(os.path.join(root, sid)) == (
                _pyramid_shas(ctrl_out)
            ), f"stream {sid} pyramid differs"
        # distinct content per stream: the controls differ pairwise
        shas = [_output_shas(os.path.join(root, sid)) for sid in noises]
        assert shas[0] != shas[1] != shas[2]


class TestFleetFairness:
    @pytest.mark.slow
    def test_stalled_spool_cannot_starve_the_rest(self, tmp_path):
        """One stream's index updates stall (an NFS-slow spool); the
        deficit round-robin serves the healthy streams first in every
        later scheduling window, and they complete all their rounds."""
        root = str(tmp_path / "root")
        specs = []
        for sid in ("slow", "fast1", "fast2"):
            src = str(tmp_path / f"src_{sid}")
            _feed(src, 0, 2)
            specs.append(
                StreamSpec(
                    stream_id=sid, source=src,
                    config=_lowpass_config(poll_jitter=0.0),
                )
            )
        fed = {"n": 0}

        def fleet_sleep(_):
            # two mid-run feeds -> 3 processing rounds per stream
            if fed["n"] < 2:
                fed["n"] += 1
                for sid in ("slow", "fast1", "fast2"):
                    _feed(
                        str(tmp_path / f"src_{sid}"), 1 + fed["n"], 1
                    )

        plan = FaultPlan(
            FaultSpec(
                "index.update", action="delay", seconds=0.6,
                at=1, times=50, match="src_slow",
            )
        )
        eng = FleetEngine(root, specs, sleep_fn=fleet_sleep)
        with install_fault_plan(plan):
            summary = eng.run()
        for sid in ("fast1", "fast2"):
            assert summary["streams"][sid]["status"] == "terminated"
            assert summary["streams"][sid]["rounds"] == 3
        assert summary["streams"]["slow"]["rounds"] == 3
        # zero jitter -> every poll window has all three streams due
        # at once; after the slow stream's first expensive step its
        # deficit debt puts it LAST in every later window
        log = [sid for sid, _status, _w in eng.service_log]
        windows = [log[i : i + 3] for i in range(0, len(log), 3)]
        assert all(len(w) == 3 for w in windows)
        for w in windows[1:]:
            assert set(w) == {"slow", "fast1", "fast2"}
            assert w[-1] == "slow", f"slow not served last: {windows}"
        # the ledger of wall debt agrees
        assert (
            eng.streams["slow"].wall_seconds
            > eng.streams["fast1"].wall_seconds
        )

    def test_fatal_stream_parks_not_the_fleet(self, tmp_path):
        """A fatal per-stream failure parks that stream; the fleet
        finishes the others and reports the parked one."""
        root = str(tmp_path / "root")
        specs = []
        for sid in ("s0", "s1", "s2"):
            src = str(tmp_path / f"src_{sid}")
            _feed(src, 0, 1)
            specs.append(
                StreamSpec(
                    stream_id=sid, source=src,
                    config=_lowpass_config(poll_jitter=0.0),
                )
            )
        # hit 2 of round.body = the second stream served in window 0;
        # ValueError classifies fatal -> parked, not retried
        plan = FaultPlan(
            FaultSpec(
                "round.body", exc=ValueError("bad config"), at=2
            )
        )
        eng = FleetEngine(root, specs, sleep_fn=lambda _s: None)
        with install_fault_plan(plan):
            summary = eng.run()
        assert summary["parked"] == ["s1"]
        assert summary["streams"]["s1"]["status"] == "parked"
        assert "bad config" in summary["streams"]["s1"]["error"]
        for sid in ("s0", "s2"):
            assert summary["streams"][sid]["status"] == "terminated"
            assert summary["streams"][sid]["rounds"] == 1


class TestFleetCrashResume:
    @pytest.mark.parametrize(
        "site,at", [("carry.save", 2), ("round.body", 5)]
    )
    @pytest.mark.slow
    def test_ki_mid_fleet_resumes_byte_identical(
        self, tmp_path, site, at
    ):
        """KeyboardInterrupt mid-fleet (the in-process stand-in for
        SIGKILL — tools/crash_drill.py --streams drills the real
        signal) kills the whole engine with streams at different
        progress points; a fresh engine over the same folders resumes
        every stream to a state byte-identical to its uninterrupted
        single-stream control."""
        root = str(tmp_path / "root")
        noises = {"s0": 0.005, "s1": 0.01, "s2": 0.02}
        specs = []
        for sid, noise in noises.items():
            src = str(tmp_path / f"src_{sid}")
            _feed(src, 0, 2, noise=noise)
            specs.append(
                StreamSpec(
                    stream_id=sid,
                    source=str(tmp_path / f"src_{sid}"),
                    config=_lowpass_config(
                        pyramid=True, poll_jitter=0.0
                    ),
                )
            )
        plan = FaultPlan(FaultSpec(site, exc=KeyboardInterrupt, at=at))
        with install_fault_plan(plan):
            with pytest.raises(KeyboardInterrupt):
                FleetEngine(
                    root, specs, sleep_fn=lambda _s: None
                ).run()
        # restart over the same folders: per-stream startup audit +
        # carry resume do the recovery
        summary = FleetEngine(
            root, specs, sleep_fn=lambda _s: None
        ).run()
        assert summary["parked"] == []
        for sid, noise in noises.items():
            ctrl_src = str(tmp_path / f"ctrl_src_{sid}")
            _feed(ctrl_src, 0, 2, noise=noise)
            ctrl_out = str(tmp_path / f"ctrl_out_{sid}")
            _run_control(ctrl_src, ctrl_out, pyramid=True)
            assert _output_shas(os.path.join(root, sid)) == (
                _output_shas(ctrl_out)
            ), f"stream {sid} outputs differ after crash-resume"
            assert _pyramid_shas(os.path.join(root, sid)) == (
                _pyramid_shas(ctrl_out)
            ), f"stream {sid} pyramid differs after crash-resume"


class TestAuditFleet:
    def test_classify_repair_across_stream_roots(self, tmp_path):
        from tpudas.integrity.audit import audit_fleet, fleet_stream_dirs

        root = str(tmp_path / "root")
        for sid in ("a", "b"):
            src = str(tmp_path / f"src_{sid}")
            _feed(src, 0, 1)
            _run_control(src, os.path.join(root, sid))
        # fleet bookkeeping dot-dirs are not streams
        os.makedirs(os.path.join(root, ".xla_cache"))
        assert [s for s, _p in fleet_stream_dirs(root)] == ["a", "b"]
        # damage stream a's carry primary (torn; .prev survives) and
        # drop a crashed writer's tmp into stream b
        from tpudas.proc.stream import CARRY_FILENAME

        carry = os.path.join(root, "a", CARRY_FILENAME)
        with open(carry, "r+b") as fh:
            fh.write(b"\x00garbage\x00")
        with open(os.path.join(root, "b", "junk.tmp"), "wb") as fh:
            fh.write(b"half a write")
        report = audit_fleet(root, repair=True)
        assert set(report["streams"]) == {"a", "b"}
        assert report["clean"] is True  # everything repaired
        assert report["issues_total"] >= 2
        assert report["repaired_total"] >= 2
        arts = {
            it["artifact"] for it in report["streams"]["a"]["issues"]
        }
        assert "carry" in arts
        assert any(
            it["status"] == "stale_tmp"
            for it in report["streams"]["b"]["issues"]
        )
        # idempotence: a second audit finds nothing
        again = audit_fleet(root, repair=True)
        assert again["clean"] and again["issues_total"] == 0
        # the repaired carry still resumes its stream
        from tpudas.proc.stream import load_carry

        assert load_carry(os.path.join(root, "a")) is not None

    @pytest.mark.slow
    def test_fsck_cli_fleet_flag(self, tmp_path):
        root = str(tmp_path / "root")
        src = str(tmp_path / "src")
        _feed(src, 0, 1)
        _run_control(src, os.path.join(root, "only"))
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "fsck.py"),
                root, "--fleet",
            ],
            capture_output=True,
            text=True,
            timeout=240,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        rep = json.loads(proc.stdout)
        assert rep["clean"] is True
        assert set(rep["streams"]) == {"only"}


class TestFleetServer:
    def test_routes_and_fleet_healthz(self, tmp_path):
        from tpudas.serve.http import DASServer
        from tpudas.serve.query import QueryEngine

        root = str(tmp_path / "root")
        specs = []
        for sid in ("s0", "s1"):
            src = str(tmp_path / f"src_{sid}")
            _feed(src, 0, 2)
            specs.append(
                StreamSpec(
                    stream_id=sid, source=src,
                    config=_lowpass_config(pyramid=True, health=True),
                )
            )
        FleetEngine(root, specs, sleep_fn=lambda _s: None).run()
        t0 = "2023-03-22T00:00:10"
        t1 = "2023-03-22T00:00:40"
        with DASServer.for_fleet(root) as srv:
            u = srv.base_url
            # per-stream query == the offline engine over that folder
            r = urllib.request.urlopen(
                f"{u}/s/s0/query?t0={t0}&t1={t1}", timeout=30
            )
            assert r.status == 200
            import io as _io

            got = np.load(_io.BytesIO(r.read()))
            ref = QueryEngine(os.path.join(root, "s0")).query(t0, t1)
            np.testing.assert_array_equal(got, ref.data)
            assert got.size > 0
            # per-stream healthz reads that stream's snapshot
            h = json.loads(
                urllib.request.urlopen(
                    f"{u}/s/s1/healthz", timeout=30
                ).read()
            )
            assert h["status"] in ("ok", "degraded")
            assert h["rounds"] == 1
            # the aggregate view covers every mounted stream
            fh = json.loads(
                urllib.request.urlopen(
                    f"{u}/fleet/healthz", timeout=30
                ).read()
            )
            assert set(fh["streams"]) == {"s0", "s1"}
            assert fh["counts"]["ok"] + fh["counts"]["degraded"] == 2
            assert fh["status"] in ("ok", "degraded")
            # unknown stream -> 404 naming the known ones
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"{u}/s/nope/query?t0={t0}&t1={t1}", timeout=30
                )
            assert err.value.code == 404
            body = json.loads(err.value.read())
            assert body["streams"] == ["s0", "s1"]
            # fleet-only server: bare data endpoints point at /s/...
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"{u}/query?t0={t0}&t1={t1}", timeout=30
                )
            assert err.value.code == 404
            # merged /metrics stays process-wide (control plane)
            text = urllib.request.urlopen(
                f"{u}/metrics", timeout=30
            ).read().decode()
            assert "tpudas_serve_requests_total" in text

    def test_single_folder_server_unchanged(self, tmp_path):
        """The pre-fleet surface: DASServer(folder) still serves the
        bare endpoints (regression guard for the mount refactor)."""
        from tpudas.serve.http import DASServer

        src = str(tmp_path / "src")
        _feed(src, 0, 1)
        out = str(tmp_path / "out")
        _run_control(src, out, pyramid=True)
        with DASServer(out) as srv:
            r = urllib.request.urlopen(
                srv.base_url
                + "/query?t0=2023-03-22T00:00:10&t1=2023-03-22T00:00:20",
                timeout=30,
            )
            assert r.status == 200
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    srv.base_url + "/fleet/healthz", timeout=30
                )
            assert err.value.code == 503  # no streams mounted

    def test_server_requires_some_mount(self):
        from tpudas.serve.http import DASServer

        with pytest.raises(ValueError, match="folder, streams"):
            DASServer()


class TestFleetDrillSmoke:
    @pytest.mark.slow
    def test_fleet_crash_drill_small(self, tmp_path):
        """Subprocess SIGKILL smoke of the fleet drill (2 streams, 2
        cycles); the full --streams 4 acceptance run is recorded in
        BENCH_pr08.json by tools/fleet_bench.py."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import crash_drill

        rep = crash_drill.run_fleet_drill(
            engine="cascade", streams=2, cycles=2, seed=0,
            workdir=str(tmp_path),
        )
        assert rep["ok"], rep
        assert rep["audit_clean"]
        assert all(
            s["ok"] for s in rep["streams_match"].values()
        )


class TestFleetUnpark:
    def test_parked_stream_rejoins_after_probe(self, tmp_path):
        """ISSUE 12 satellite: with unpark_probe set, a stream parked
        on a transient-looking fatal is re-probed on a doubling
        schedule, rebuilt from disk, and finishes — the fleet summary
        shows it terminated, not parked."""
        root = str(tmp_path / "root")
        specs = []
        for sid in ("s0", "s1", "s2"):
            src = str(tmp_path / f"src_{sid}")
            _feed(src, 0, 1)
            specs.append(
                StreamSpec(
                    stream_id=sid, source=src,
                    config=_lowpass_config(
                        poll_jitter=0.0, health=True
                    ),
                )
            )
        # hit 2 of round.body = the second stream served in window 0;
        # ONE fatal hit — the unpark probe's rebuilt runner runs clean
        plan = FaultPlan(
            FaultSpec(
                "round.body", exc=ValueError("transient-looking"), at=2
            )
        )
        eng = FleetEngine(
            root, specs, sleep_fn=lambda _s: None, unpark_probe=1.0
        )
        with install_fault_plan(plan):
            summary = eng.run()
        assert summary["parked"] == []
        assert summary["unparked_total"] == 1
        for sid in ("s0", "s1", "s2"):
            assert summary["streams"][sid]["status"] == "terminated"
            assert summary["streams"][sid]["rounds"] == 1
        unparked = [
            sid for sid, s in summary["streams"].items()
            if s["unparks"]
        ]
        assert len(unparked) == 1
        # the park/unpark transition is visible in health.json
        health_path = os.path.join(root, unparked[0], "health.json")
        with open(health_path) as fh:
            payload = json.load(fh)
        assert payload["fleet"]["event"] == "unparked"
        assert payload["fleet"]["unparks"] == 1

    def test_probes_exhaust_to_terminal_park(self, tmp_path):
        """A stream that keeps dying fatally exhausts its probe
        budget (doubling intervals, bounded attempts) and stays
        parked — run() still terminates."""
        root = str(tmp_path / "root")
        src = str(tmp_path / "src")
        _feed(src, 0, 1)
        specs = [
            StreamSpec(
                stream_id="s0", source=src,
                config=_lowpass_config(poll_jitter=0.0, health=True),
            )
        ]
        plan = FaultPlan(
            FaultSpec(
                "round.body", exc=ValueError("still broken"), at=1,
                times=1000,
            )
        )
        eng = FleetEngine(
            root, specs, sleep_fn=lambda _s: None,
            unpark_probe=0.5, unpark_max_probes=2,
        )
        with install_fault_plan(plan):
            summary = eng.run()
        assert summary["parked"] == ["s0"]
        assert summary["streams"]["s0"]["unparks"] == 2
        assert "still broken" in summary["streams"]["s0"]["error"]
        # the terminal health snapshot records the park event
        with open(os.path.join(root, "s0", "health.json")) as fh:
            payload = json.load(fh)
        assert payload["fleet"]["event"] == "parked"

    def test_default_park_stays_terminal(self, tmp_path):
        """Without unpark_probe (the default) parking keeps its
        pre-ISSUE-12 terminal semantics."""
        root = str(tmp_path / "root")
        src = str(tmp_path / "src")
        _feed(src, 0, 1)
        specs = [
            StreamSpec(
                stream_id="s0", source=src,
                config=_lowpass_config(poll_jitter=0.0),
            )
        ]
        plan = FaultPlan(
            FaultSpec("round.body", exc=ValueError("fatal"), at=1)
        )
        eng = FleetEngine(root, specs, sleep_fn=lambda _s: None)
        with install_fault_plan(plan):
            summary = eng.run()
        assert summary["parked"] == ["s0"]
        assert summary["unparked_total"] == 0
