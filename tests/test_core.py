"""Core data layer: FrozenDict, time utils, units, attrs aliases, Patch."""

import numpy as np
import pytest

from tpudas.core.attrs import PatchAttrs
from tpudas.core.mapping import FrozenDict
from tpudas.core import units
from tpudas.core.timeutils import (
    build_time_grid,
    quantize_step,
    to_datetime64,
    to_float_seconds,
    to_timedelta64,
)
from tpudas.core.patch import Patch
from tpudas.testing import synthetic_patch


class TestFrozenDict:
    def test_mapping(self):
        fd = FrozenDict(a=1, b=2)
        assert fd["a"] == 1 and len(fd) == 2 and set(fd) == {"a", "b"}

    def test_immutable(self):
        fd = FrozenDict(a=1)
        with pytest.raises(TypeError):
            fd["a"] = 2  # type: ignore[index]

    def test_updated(self):
        fd = FrozenDict(a=1).updated(b=2)
        assert dict(fd) == {"a": 1, "b": 2}


class TestTimeUtils:
    def test_float_seconds_roundtrip(self):
        t = to_datetime64(1234.5)
        assert to_float_seconds(t) == 1234.5

    def test_negative_seconds(self):
        # the impulse probe builds a time axis centred on zero
        t = to_datetime64(np.array([-2.0, -1.0, 0.0, 1.0]))
        assert np.all(np.diff(t) == np.timedelta64(1_000_000_000, "ns"))
        assert to_float_seconds(t)[0] == -2.0

    def test_string_parse(self):
        t = to_datetime64("2023-03-22 03:00:00")
        assert t == np.datetime64("2023-03-22T03:00:00", "ns")

    def test_timedelta(self):
        assert to_timedelta64(0.001) == np.timedelta64(1_000_000, "ns")
        assert to_timedelta64(10 * units.s) == np.timedelta64(10, "s")

    def test_quantize_step_ms_contract(self):
        # reference grid step: timedelta64(int(dt*1000), "ms")
        assert quantize_step(10.0) == np.timedelta64(10000, "ms")
        assert quantize_step(0.5) == np.timedelta64(500, "ms")

    def test_build_time_grid(self):
        grid = build_time_grid("2023-01-01", "2023-01-01T00:01:00", 10.0)
        assert len(grid) == 6
        assert grid[1] - grid[0] == np.timedelta64(10, "s")


class TestUnits:
    def test_quantity_seconds(self):
        q = 10.0 * units.s
        assert q.to_seconds() == 10.0
        assert units.get_seconds(q) == 10.0

    def test_get_seconds_passthrough(self):
        assert units.get_seconds(2.5) == 2.5
        assert units.get_seconds(np.timedelta64(1500, "ms")) == 1.5
        assert units.get_seconds(None, 7) == 7


class TestAttrsAliases:
    def test_three_generations(self):
        # the 3 spellings the notebooks use (SURVEY.md §2.3)
        a = PatchAttrs({"d_time": 0.001, "d_distance": 5.0})
        assert a["time_step"] == np.timedelta64(1_000_000, "ns")
        assert a["step_time"] == a["d_time"] == a["time_step"]
        assert a["distance_step"] == a["step_distance"] == 5.0

    def test_notebook_sampling_rate_idiom(self):
        a = PatchAttrs({"time_step": np.timedelta64(1, "ms")})
        rate = 1 / (a["time_step"] / np.timedelta64(1, "s"))
        assert rate == 1000.0

    def test_update_via_alias(self):
        a = PatchAttrs({"time_step": 0.001}).updated(d_time=10.0)
        assert a["step_time"] == np.timedelta64(10, "s")


class TestPatch:
    def make(self, n=100, c=4):
        return synthetic_patch(duration=n / 200.0, fs=200.0, n_ch=c)

    def test_construction_derives_attrs(self):
        p = self.make()
        assert p.attrs["time_min"] == p.coords["time"][0]
        assert p.attrs["time_max"] == p.coords["time"][-1]
        assert p.attrs["time_step"] == np.timedelta64(5_000_000, "ns")
        assert p.attrs["distance_step"] == 5.0
        assert p.attrs["gauge_length"] == 10.0

    def test_immutable(self):
        p = self.make()
        with pytest.raises(TypeError):
            p.data = None  # type: ignore[misc]

    def test_new_data(self):
        p = self.make()
        q = p.new(data=p.host_data() * 2)
        assert np.allclose(q.host_data(), p.host_data() * 2)
        assert q.attrs["gauge_length"] == p.attrs["gauge_length"]

    def test_update_attrs_keeps_coord_extrema(self):
        p = self.make()
        q = p.update_attrs(d_time=10.0)
        assert q.attrs["time_step"] == np.timedelta64(10, "s")
        assert q.attrs["time_min"] == p.attrs["time_min"]

    def test_select_time_inclusive(self):
        p = self.make()
        t = p.coords["time"]
        q = p.select(time=(t[10], t[20]))
        assert q.shape[0] == 11
        assert q.attrs["time_min"] == t[10]

    def test_select_distance(self):
        p = self.make()
        d = p.coords["distance"]
        q = p.select(distance=(d[1], d[2]))
        assert q.shape[1] == 2

    def test_select_string_time(self):
        p = self.make()
        q = p.select(time=("2023-03-22T00:00:00.1", None))
        assert q.shape[0] < p.shape[0]

    def test_pipe(self):
        p = self.make()
        out = p.pipe(lambda patch, k: patch.new(data=patch.host_data() * k), k=3)
        assert np.allclose(out.host_data(), p.host_data() * 3)

    def test_dropna(self):
        p = self.make()
        data = p.host_data().copy()
        data[:5] = np.nan
        q = p.new(data=data).dropna("time")
        assert q.shape[0] == p.shape[0] - 5
        assert q.attrs["time_min"] == p.coords["time"][5]

    def test_coords_indexing_idiom(self):
        # notebooks do patch.coords['distance'][ch] and len(coords['time'])
        p = self.make()
        assert p.coords["distance"][2] == 10.0
        assert len(p.coords["time"]) == p.shape[0]
