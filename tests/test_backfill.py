"""tpudas.backfill: crash-only cluster backfill (ISSUE 12).

Lease claim/renew/steal determinism, exactly-once commit (idempotent
double-commit, commit-wins), KI-kill at the new ``backfill.claim`` /
``backfill.commit`` sites plus ``round.body`` with the drained +
stitched result byte-identical to an uninterrupted control AND to a
plain sequential realtime run, fatal-shard park, ENOSPC shedding
inside a shard, drain-mode engine hooks (time cap + bounded ingest
rounds), and ``audit_backfill`` classify/repair.
"""

import json
import os
import sys

import numpy as np
import pytest

from tpudas.backfill import (
    BackfillQueue,
    LeaseLostError,
    load_plan,
    plan_backfill,
    run_worker,
    stitch_backfill,
)
from tpudas.backfill.queue import (
    DONE_DIRNAME,
    LEASES_DIRNAME,
    RESULT_DONE_FILENAME,
    SHARDS_DIRNAME,
)
from tpudas.integrity.audit import audit_backfill
from tpudas.testing import (
    FaultPlan,
    FaultSpec,
    enospc_error,
    install_fault_plan,
    make_synthetic_spool,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.crash_drill import (  # noqa: E402
    _content_hash,
    _detect_state,
    _pyramid_tree,
)

T0 = "2023-03-22T00:00:00"
FS = 50.0
FILE_SEC = 20.0
N_CH = 4
DT = 1.0
EDGE = 5.0
N_FILES = 6  # 120 s archive
SHARD_SEC = 60.0
DETECT_OPS = (
    ("stalta", {"sta": 2.0, "lta": 10.0, "on": 2.0, "off": 1.2}),
    ("rms", {"window": 5.0, "step": 2.0, "thresh": 1.5,
             "baseline": 20.0}),
)


def _t_end():
    return np.datetime64(T0) + np.timedelta64(
        int(N_FILES * FILE_SEC * 1e9), "ns"
    )


def _plan(root, src, **overrides):
    kwargs = dict(
        shard_seconds=SHARD_SEC,
        output_sample_interval=DT,
        edge_buffer=EDGE,
        process_patch_size=20,
        pyramid=False,
        detect=False,
        ingest_limit_sec=35.0,
    )
    kwargs.update(overrides)
    return plan_backfill(root, src, T0, _t_end(), **kwargs)


class FakeClock:
    def __init__(self, t=1_000_000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, sec):
        self.t += float(sec)


@pytest.fixture(scope="module")
def archive(tmp_path_factory):
    src = str(tmp_path_factory.mktemp("bf_archive") / "src")
    make_synthetic_spool(
        src, n_files=N_FILES, file_duration=FILE_SEC, fs=FS,
        n_ch=N_CH, noise=0.01, start=np.datetime64(T0),
    )
    return src


@pytest.fixture(scope="module")
def sequential_ref(archive, tmp_path_factory):
    """The oracle: a plain realtime run over the archive with
    pyramid + detect on."""
    from tpudas.proc.streaming import run_lowpass_realtime

    out = str(tmp_path_factory.mktemp("bf_seq") / "out")
    run_lowpass_realtime(
        source=archive, output_folder=out, start_time=T0,
        output_sample_interval=DT, edge_buffer=EDGE,
        process_patch_size=20, poll_interval=0.0,
        sleep_fn=lambda _s: None, pyramid=True, detect=True,
        detect_operators=DETECT_OPS,
    )
    return out


@pytest.fixture(scope="module")
def control(archive, tmp_path_factory):
    """The 1-worker uninterrupted control over a full-feature plan."""
    root = str(tmp_path_factory.mktemp("bf_ctrl") / "root")
    _plan(root, archive, pyramid=True, detect=True,
          detect_operators=DETECT_OPS)
    tally = run_worker(root, worker="ctrl", settle=0.0, max_wall=300)
    assert tally["stitched"]
    return root


class TestPlan:
    def test_shard_grid_and_remainder(self, archive, tmp_path):
        plan = _plan(str(tmp_path / "q"), archive, shard_seconds=50.0)
        shards = plan["shards"]
        assert [s["id"] for s in shards] == [
            f"sh{k:05d}" for k in range(len(shards))
        ]
        # contiguous tiling of [t0, t1)
        assert shards[0]["t0_ns"] == plan["t0_ns"]
        assert shards[-1]["t1_ns"] == plan["t1_ns"]
        for a, b in zip(shards, shards[1:]):
            assert a["t1_ns"] == b["t0_ns"]
        # leads are plan-derived, grid-rounded, positive
        assert plan["lead_seconds"] % DT == 0
        assert plan["tail_seconds"] % DT == 0
        assert plan["lead_seconds"] > 0 and plan["tail_seconds"] > 0

    def test_plan_is_immutable(self, archive, tmp_path):
        root = str(tmp_path / "q")
        _plan(root, archive)
        with pytest.raises(FileExistsError):
            _plan(root, archive)

    def test_unknown_config_key_rejected(self, archive, tmp_path):
        with pytest.raises(ValueError, match="unknown backfill config"):
            _plan(str(tmp_path / "q"), archive, bogus_knob=1)

    def test_torn_plan_refused(self, archive, tmp_path):
        root = str(tmp_path / "q")
        _plan(root, archive)
        path = os.path.join(root, "backfill.json")
        with open(path) as fh:
            payload = json.load(fh)
        payload["t1_ns"] += 1  # stamp now mismatches
        with open(path, "w") as fh:
            json.dump(payload, fh)
        with pytest.raises(ValueError, match="crc32"):
            load_plan(root)


class TestLease:
    def _queue(self, root, worker, clock, ttl=30.0):
        return BackfillQueue(
            root, worker=worker, lease_ttl=ttl, settle=0.0, clock=clock
        )

    def test_claim_renew_release(self, archive, tmp_path):
        root = str(tmp_path / "q")
        _plan(root, archive)
        clock = FakeClock()
        qa = self._queue(root, "wa", clock)
        qb = self._queue(root, "wb", clock)
        lease = qa.try_claim("sh00000")
        assert lease is not None and lease.worker == "wa"
        assert qa.shard_state("sh00000") == "leased"
        # a live lease is not claimable by anyone else
        assert qb.try_claim("sh00000") is None
        before = qa.read_lease("sh00000")["deadline_ns"]
        clock.advance(10.0)
        qa.renew(lease)
        assert qa.read_lease("sh00000")["deadline_ns"] > before
        qa.release(lease)
        assert qa.shard_state("sh00000") == "open"

    def test_stale_lease_is_stolen_and_renew_raises(
        self, archive, tmp_path
    ):
        root = str(tmp_path / "q")
        _plan(root, archive)
        clock = FakeClock()
        qa = self._queue(root, "wa", clock, ttl=5.0)
        qb = self._queue(root, "wb", clock, ttl=5.0)
        lease_a = qa.try_claim("sh00000")
        assert lease_a is not None
        assert qb.try_claim("sh00000") is None
        clock.advance(6.0)  # past wa's deadline
        assert qb.shard_state("sh00000") == "stale"
        lease_b = qb.try_claim("sh00000")
        assert lease_b is not None and lease_b.worker == "wb"
        # the dead worker's resurrection must notice the theft
        with pytest.raises(LeaseLostError):
            qa.renew(lease_a)
        # and its release must not clobber the thief's lease
        qa.release(lease_a)
        assert qb.read_lease("sh00000")["worker"] == "wb"

    def test_claim_next_walks_plan_order(self, archive, tmp_path):
        root = str(tmp_path / "q")
        _plan(root, archive)
        clock = FakeClock()
        qa = self._queue(root, "wa", clock)
        claimed = [qa.claim_next().shard for _ in range(2)]
        assert claimed == ["sh00000", "sh00001"]

    def test_settle_reread_detects_lost_race(self, archive, tmp_path):
        """Two claimers racing one shard: the loser's settle re-read
        sees the winner's token and backs off (simulated by writing
        the winner's lease inside the loser's settle window via a
        zero-settle interleave)."""
        root = str(tmp_path / "q")
        _plan(root, archive)
        clock = FakeClock()
        qa = self._queue(root, "wa", clock)
        qb = self._queue(root, "wb", clock)
        lease_a = qa.try_claim("sh00000")
        assert lease_a is not None
        # wb writes over wa's lease directly (the last-write-wins
        # race), then wa's next renew acts as its settle re-read
        from tpudas.integrity.checksum import write_json_checksummed

        now = int(clock() * 1e9)
        write_json_checksummed(
            os.path.join(root, LEASES_DIRNAME, "sh00000.json"),
            {
                "shard": "sh00000", "worker": "wb", "pid": 1,
                "token": "wb.1.0", "heartbeat_ns": now,
                "deadline_ns": now + 30_000_000_000, "stolen": False,
            },
        )
        with pytest.raises(LeaseLostError):
            qa.renew(lease_a)


class TestExecuteAndStitch:
    def test_single_worker_matches_sequential_run(
        self, control, sequential_ref
    ):
        """THE tentpole claim, in-process: a backfill drain + stitch
        is byte-identical to a single sequential realtime run —
        merged output content, pyramid tree file-by-file, events
        ledger bytes, score tiles, parsed detect carry."""
        res = os.path.join(control, "result")
        assert _content_hash(res) == _content_hash(sequential_ref)
        assert _pyramid_tree(res) == _pyramid_tree(sequential_ref)
        assert _detect_state(res) == _detect_state(sequential_ref)

    def test_drain_uses_bounded_rounds(self, archive, control):
        """ingest_limit_sec chunks the drain into multiple bounded
        rounds (the lease-renewal cadence) — visible in the done
        markers' round counts."""
        from tpudas.integrity.checksum import read_json_verified

        done = os.path.join(control, DONE_DIRNAME)
        rounds = []
        for name in sorted(os.listdir(done)):
            payload, _ = read_json_verified(
                os.path.join(done, name), "backfill_done"
            )
            rounds.append(payload.get("rounds", 0))
        assert rounds and all(r >= 1 for r in rounds)

    def test_kill_at_claim_commit_round_then_resume_identical(
        self, archive, control, tmp_path
    ):
        """KeyboardInterrupt (the in-process SIGKILL stand-in — it
        bypasses every ``except Exception``) at backfill.claim,
        backfill.commit, and round.body in three successive worker
        incarnations; a fourth clean worker drains what is left.  The
        stitched result must be byte-identical to the uninterrupted
        control."""
        root = str(tmp_path / "q")
        _plan(root, archive, pyramid=True, detect=True,
              detect_operators=DETECT_OPS)
        clock = FakeClock()
        kill_sites = ("backfill.claim", "backfill.commit", "round.body")
        for i, site in enumerate(kill_sites):
            plan = FaultPlan(
                FaultSpec(site, exc=KeyboardInterrupt, at=i + 1)
            )
            with install_fault_plan(plan):
                with pytest.raises(KeyboardInterrupt):
                    run_worker(
                        root, worker=f"w{i}", settle=0.0,
                        lease_ttl=5.0, clock=clock, max_wall=300,
                    )
            assert plan.fired, site
            clock.advance(6.0)  # the dead worker's lease goes stale
        tally = run_worker(
            root, worker="wfinal", settle=0.0, lease_ttl=5.0,
            clock=clock, max_wall=300,
        )
        assert tally["stitched"], tally
        report = audit_backfill(root, repair=True, clock=clock)
        assert report["clean"], report["issues"]
        res = os.path.join(root, "result")
        ctrl_res = os.path.join(control, "result")
        assert _content_hash(res) == _content_hash(ctrl_res)
        assert _pyramid_tree(res) == _pyramid_tree(ctrl_res)
        assert _detect_state(res) == _detect_state(ctrl_res)

    def test_double_commit_is_idempotent(self, archive, tmp_path):
        """Worker A drains a shard and dies just before its commit;
        worker B reclaims, re-executes, commits.  A's resurrected
        commit must LOSE (commit-wins), discard its staging, and
        leave B's done marker byte-identical."""
        from tpudas.backfill.runner import execute_shard

        root = str(tmp_path / "q")
        _plan(root, archive)
        clock = FakeClock()
        qa = BackfillQueue(
            root, worker="wa", settle=0.0, lease_ttl=5.0, clock=clock
        )
        lease_a = qa.try_claim("sh00000")
        plan = FaultPlan(
            FaultSpec("backfill.commit", exc=KeyboardInterrupt, at=1)
        )
        with install_fault_plan(plan):
            with pytest.raises(KeyboardInterrupt):
                execute_shard(qa, lease_a, sleep_fn=lambda _s: None)
        staging_a = qa.staging_dir(lease_a)
        assert os.path.isdir(staging_a)  # fully drained, uncommitted
        clock.advance(6.0)
        qb = BackfillQueue(
            root, worker="wb", settle=0.0, lease_ttl=5.0, clock=clock
        )
        lease_b = qb.try_claim("sh00000")
        assert lease_b is not None
        assert execute_shard(
            qb, lease_b, sleep_fn=lambda _s: None
        ) == "committed"
        done_path = os.path.join(root, DONE_DIRNAME, "sh00000.json")
        with open(done_path, "rb") as fh:
            marker_before = fh.read()
        # A comes back from the dead and retries ITS commit
        outcome = qa.commit(lease_a, staging_a)
        assert outcome == "lost"
        assert not os.path.isdir(staging_a)  # discarded, not merged
        with open(done_path, "rb") as fh:
            assert fh.read() == marker_before  # B's commit stands
        assert qb.is_done("sh00000")

    def test_fatal_shard_parks_queue_still_drains(
        self, archive, tmp_path
    ):
        """A fatal failure inside one shard's drain parks THAT shard
        (counted, fsck-able); the worker commits the rest and the
        stitch refuses until an operator clears the park."""
        root = str(tmp_path / "q")
        _plan(root, archive)
        clock = FakeClock()
        plan = FaultPlan(
            FaultSpec("round.body", exc=ValueError("bad shard"), at=1)
        )
        with install_fault_plan(plan):
            tally = run_worker(
                root, worker="w0", settle=0.0, lease_ttl=5.0,
                clock=clock, max_wall=300,
            )
        assert tally["parked"] == 1
        assert tally["committed"] == 1  # the other shard drained
        assert not tally["stitched"]
        assert tally.get("stitch_status") is None
        queue = BackfillQueue(root, worker="chk", clock=clock)
        counts = queue.counts()
        assert counts["parked"] == 1 and counts["done"] == 1
        result = stitch_backfill(root, queue=queue)
        assert result["status"] == "unstitchable"
        report = audit_backfill(root, repair=True, clock=clock)
        assert report["parked"] == ["sh00000"]
        # operator repair: clear the park, re-drain, stitch lands
        os.remove(os.path.join(root, ".parked", "sh00000.json"))
        tally2 = run_worker(
            root, worker="w1", settle=0.0, lease_ttl=5.0,
            clock=clock, max_wall=300,
        )
        assert tally2["committed"] == 1 and tally2["stitched"]

    def test_enospc_inside_shard_sheds_then_commits(
        self, archive, control, tmp_path
    ):
        """A full disk mid-shard (injected at the carry save) rides
        the resource retry ladder — crash-equivalent retry, shed
        writers — and the shard still commits with the stitched bytes
        matching the control."""
        root = str(tmp_path / "q")
        _plan(root, archive, pyramid=True, detect=True,
              detect_operators=DETECT_OPS)
        clock = FakeClock()
        plan = FaultPlan(
            FaultSpec("carry.save", exc=enospc_error(), at=1, times=2)
        )
        with install_fault_plan(plan):
            tally = run_worker(
                root, worker="w0", settle=0.0, lease_ttl=30.0,
                clock=clock, max_wall=300, sleep_fn=lambda _s: None,
            )
        assert plan.fired
        assert tally["stitched"], tally
        assert tally["parked"] == 0
        res = os.path.join(root, "result")
        ctrl_res = os.path.join(control, "result")
        assert _content_hash(res) == _content_hash(ctrl_res)
        assert _pyramid_tree(res) == _pyramid_tree(ctrl_res)

    def test_adoption_finishes_a_crashed_commit(self, archive, tmp_path):
        """The crash window between the commit rename and the done
        marker: the next claimer adopts the committed directory
        instead of re-executing."""
        root = str(tmp_path / "q")
        _plan(root, archive)
        clock = FakeClock()
        run_worker(
            root, worker="w0", settle=0.0, lease_ttl=5.0, clock=clock,
            stitch=False, max_wall=300,
        )
        # simulate the crash window: drop one done marker
        os.remove(os.path.join(root, DONE_DIRNAME, "sh00001.json"))
        queue = BackfillQueue(
            root, worker="w1", settle=0.0, clock=clock
        )
        assert queue.shard_state("sh00001") == "adoptable"
        tally = run_worker(
            root, worker="w1", settle=0.0, lease_ttl=5.0, clock=clock,
            max_wall=300,
        )
        assert tally["adopted"] == 1
        assert queue.is_done("sh00001")


class TestDrainModeHooks:
    def test_time_range_caps_ingest(self, archive, tmp_path):
        """The engine's drain-mode cap: a runner with time_range set
        never emits past the cap (plus the held-back edge)."""
        from tpudas.backfill.runner import shard_spec
        from tpudas.fleet.engine import LowpassStreamRunner, drive

        root = str(tmp_path / "q")
        plan = _plan(root, archive)
        out = str(tmp_path / "out")
        cap_ns = plan["shards"][0]["t1_ns"]
        runner = LowpassStreamRunner(
            shard_spec(plan, plan["shards"][0]), out
        )
        runner.time_range = (None, np.datetime64(int(cap_ns), "ns"))
        drive(runner, sleep_fn=lambda _s: None)
        sp_hash_rows = []
        from tpudas.io.spool import spool as make_spool

        sp = make_spool(out).sort("time").update()
        for p in sp.chunk(time=None):
            ts = (
                np.asarray(p.coords["time"])
                .astype("datetime64[ns]")
                .astype(np.int64)
            )
            sp_hash_rows.append(ts)
        assert sp_hash_rows, "shard drain emitted nothing"
        assert int(np.concatenate(sp_hash_rows).max()) < cap_ns

    def test_ingest_limit_bounds_rounds(self, archive, tmp_path):
        """ingest_limit_sec chunks a static-archive drain into
        multiple rounds instead of one unbounded one, and the
        no-growth terminate still fires at the end."""
        from tpudas.backfill.runner import shard_spec
        from tpudas.fleet.engine import LowpassStreamRunner, drive

        root = str(tmp_path / "q")
        plan = _plan(root, archive)
        out = str(tmp_path / "out")
        runner = LowpassStreamRunner(
            shard_spec(plan, plan["shards"][0]), out
        )
        runner.ingest_limit_sec = 30.0
        drive(runner, sleep_fn=lambda _s: None)
        assert runner.rounds >= 2  # the 60 s shard took >= 2 bites


class TestAuditBackfill:
    def _drained(self, archive, tmp_path, name="q"):
        root = str(tmp_path / name)
        _plan(root, archive)
        clock = FakeClock()
        run_worker(
            root, worker="w0", settle=0.0, lease_ttl=5.0, clock=clock,
            max_wall=300,
        )
        return root, clock

    def test_stale_lease_and_orphan_staging_swept(
        self, archive, tmp_path
    ):
        root, clock = self._drained(archive, tmp_path)
        # fabricate a dead worker's leftovers: a stale lease + staging
        from tpudas.integrity.checksum import write_json_checksummed

        now = int(clock() * 1e9)
        write_json_checksummed(
            os.path.join(root, LEASES_DIRNAME, "sh00001.json"),
            {
                "shard": "sh00001", "worker": "dead", "pid": 1,
                "token": "dead.1.0", "heartbeat_ns": now,
                "deadline_ns": now - 1, "stolen": False,
            },
        )
        orphan = os.path.join(
            root, SHARDS_DIRNAME, "sh00001.work.dead.1.0"
        )
        os.makedirs(orphan)
        report = audit_backfill(root, repair=True, clock=clock)
        assert report["clean"], report["issues"]
        statuses = {
            (i["artifact"], i["status"]) for i in report["issues"]
        }
        assert ("backfill_lease", "stale_lease") in statuses
        assert ("backfill_staging", "orphan") in statuses
        assert not os.path.isdir(orphan)
        # second audit: nothing left
        report2 = audit_backfill(root, repair=True, clock=clock)
        assert report2["clean"] and not report2["issues"]

    def test_live_lease_and_its_staging_left_alone(
        self, archive, tmp_path
    ):
        root, clock = self._drained(archive, tmp_path)
        from tpudas.integrity.checksum import write_json_checksummed

        os.remove(os.path.join(root, DONE_DIRNAME, "sh00001.json"))
        import shutil

        shutil.rmtree(os.path.join(root, SHARDS_DIRNAME, "sh00001"))
        now = int(clock() * 1e9)
        write_json_checksummed(
            os.path.join(root, LEASES_DIRNAME, "sh00001.json"),
            {
                "shard": "sh00001", "worker": "alive", "pid": 1,
                "token": "alive.1.0", "heartbeat_ns": now,
                "deadline_ns": now + 60_000_000_000, "stolen": False,
            },
        )
        live = os.path.join(
            root, SHARDS_DIRNAME, "sh00001.work.alive.1.0"
        )
        os.makedirs(live)
        report = audit_backfill(root, repair=True, clock=clock)
        assert os.path.isdir(live)  # a live claim's staging survives
        paths = {i["path"] for i in report["issues"]}
        assert live not in paths

    def test_commit_crash_window_adopted(self, archive, tmp_path):
        root, clock = self._drained(archive, tmp_path)
        os.remove(os.path.join(root, DONE_DIRNAME, "sh00000.json"))
        report = audit_backfill(root, repair=True, clock=clock)
        assert report["clean"], report["issues"]
        actions = {i["action"] for i in report["issues"]}
        assert "adopted_commit" in actions
        queue = BackfillQueue(root, worker="chk", clock=clock)
        assert queue.is_done("sh00000")

    def test_torn_done_marker_removed_then_adopted(
        self, archive, tmp_path
    ):
        root, clock = self._drained(archive, tmp_path)
        path = os.path.join(root, DONE_DIRNAME, "sh00000.json")
        with open(path, "r+") as fh:
            fh.seek(0)
            fh.write('{"shard": "XX"')  # torn mid-write
        report = audit_backfill(root, repair=True, clock=clock)
        assert report["clean"], report["issues"]
        queue = BackfillQueue(root, worker="chk", clock=clock)
        assert queue.is_done("sh00000")  # re-adopted from the bytes

    def test_half_stitched_result_removed(self, archive, tmp_path):
        root, clock = self._drained(archive, tmp_path)
        clock2 = clock
        stitch_backfill(
            root,
            queue=BackfillQueue(
                root, worker="st", settle=0.0, clock=clock2
            ),
        )
        # the crash window between the result rename and its marker
        os.remove(os.path.join(root, RESULT_DONE_FILENAME))
        report = audit_backfill(root, repair=True, clock=clock)
        assert report["clean"], report["issues"]
        assert not os.path.isdir(os.path.join(root, "result"))
        # a re-stitch rebuilds it deterministically
        result = stitch_backfill(
            root,
            queue=BackfillQueue(
                root, worker="st2", settle=0.0, clock=clock2
            ),
        )
        assert result["status"] == "committed"

    def test_unreadable_plan_is_not_clean(self, tmp_path):
        root = str(tmp_path / "q")
        os.makedirs(root)
        with open(os.path.join(root, "backfill.json"), "w") as fh:
            fh.write("{")
        report = audit_backfill(root, repair=True)
        assert not report["clean"]
        assert "unreadable backfill plan" in report["error"]


class TestCommitWindowRegression:
    """Review findings (PR 12): the stitch crash window must be
    adoptable, and a live lease over a committed directory must not
    be clobbered by a concurrent adopter."""

    def test_marker_less_result_adopted_not_lost(
        self, archive, tmp_path
    ):
        root = str(tmp_path / "q")
        _plan(root, archive)
        clock = FakeClock()
        run_worker(
            root, worker="w0", settle=0.0, lease_ttl=5.0, clock=clock,
            max_wall=300,
        )
        # the crash window: rename landed, marker write never did
        os.remove(os.path.join(root, RESULT_DONE_FILENAME))
        result = stitch_backfill(
            root,
            queue=BackfillQueue(
                root, worker="st", settle=0.0, clock=clock
            ),
        )
        assert result["status"] == "committed"
        assert result.get("adopted") is True
        assert os.path.isfile(os.path.join(root, RESULT_DONE_FILENAME))
        # and the queue reads as fully stitched from here on
        again = stitch_backfill(
            root,
            queue=BackfillQueue(
                root, worker="st2", settle=0.0, clock=clock
            ),
        )
        assert again["status"] == "already"

    def test_live_lease_protects_commit_window(self, archive, tmp_path):
        """A committed directory whose lease is still LIVE is a worker
        inside its commit (between rename and marker): it must read
        as leased, never adoptable."""
        root = str(tmp_path / "q")
        _plan(root, archive)
        clock = FakeClock()
        qa = BackfillQueue(
            root, worker="wa", settle=0.0, lease_ttl=30.0, clock=clock
        )
        lease = qa.try_claim("sh00000")
        assert lease is not None
        os.makedirs(qa.shard_dir("sh00000"))
        qb = BackfillQueue(
            root, worker="wb", settle=0.0, lease_ttl=30.0, clock=clock
        )
        assert qb.shard_state("sh00000") == "leased"
        assert qb.try_claim("sh00000") is None
        # once the lease expires the window is adoptable
        clock.advance(31.0)
        assert qb.shard_state("sh00000") == "adoptable"
