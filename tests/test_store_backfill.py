"""tpudas.backfill.objqueue: backfill with NO shared filesystem.

The object-store queue's exactly-once machinery under the race
matrix: create-only plan, CAS lease claim/steal/renew, the three-step
upload-then-mark commit (double-commit race, lost conditional put on
the done marker, crashed-commit adoption, mid-upload re-execution),
torn uploads classified and aborted by the store fsck
(``audit_backfill_store`` + ``tools/fsck.py --store``), and the
acceptance leg: two workers sharing nothing but a fake object store
drain + stitch a job byte-identical to a plain sequential realtime
run.
"""

import json
import os
import sys
import threading

import numpy as np
import pytest

from tpudas.backfill.objqueue import (
    DONE_PREFIX,
    LEASES_PREFIX,
    RESULT_DONE_KEY,
    RESULT_PREFIX,
    StoreBackfillQueue,
    load_plan_store,
    plan_backfill_store,
    run_store_worker,
    stitch_store_backfill,
)
from tpudas.backfill.queue import LeaseLostError
from tpudas.integrity.audit import audit_backfill_store
from tpudas.obs.registry import MetricsRegistry, use_registry
from tpudas.store import (
    FakeObjectStore,
    FaultInjector,
    FaultRule,
    RetryingStore,
    StoreNetworkError,
    store_from_url,
)
from tpudas.testing import make_synthetic_spool

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.crash_drill import _content_hash  # noqa: E402

T0 = "2023-03-22T00:00:00"
FS = 50.0
FILE_SEC = 20.0
N_CH = 4
DT = 1.0
EDGE = 5.0
N_FILES = 6  # 120 s archive
SHARD_SEC = 60.0


def _t_end():
    return np.datetime64(T0) + np.timedelta64(
        int(N_FILES * FILE_SEC * 1e9), "ns"
    )


def _plan(store, prefix, src, **overrides):
    kwargs = dict(
        shard_seconds=SHARD_SEC,
        output_sample_interval=DT,
        edge_buffer=EDGE,
        process_patch_size=20,
        pyramid=False,
        detect=False,
        ingest_limit_sec=35.0,
    )
    kwargs.update(overrides)
    return plan_backfill_store(store, prefix, src, T0, _t_end(), **kwargs)


class FakeClock:
    def __init__(self, t=1_000_000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, sec):
        self.t += float(sec)


@pytest.fixture(scope="module")
def archive(tmp_path_factory):
    src = str(tmp_path_factory.mktemp("sbf_archive") / "src")
    make_synthetic_spool(
        src, n_files=N_FILES, file_duration=FILE_SEC, fs=FS,
        n_ch=N_CH, noise=0.01, start=np.datetime64(T0),
    )
    return src


@pytest.fixture(scope="module")
def sequential_ref(archive, tmp_path_factory):
    """The oracle: one uninterrupted realtime run over the archive."""
    from tpudas.proc.streaming import run_lowpass_realtime

    out = str(tmp_path_factory.mktemp("sbf_seq") / "out")
    run_lowpass_realtime(
        source=archive, output_folder=out, start_time=T0,
        output_sample_interval=DT, edge_buffer=EDGE,
        process_patch_size=20, poll_interval=0.0,
        sleep_fn=lambda _s: None, pyramid=False,
    )
    return out


def _queue(store, prefix, tmp_path, worker, **kw):
    return StoreBackfillQueue(
        store, prefix, scratch=str(tmp_path / f"scratch-{worker}"),
        worker=worker, **kw,
    )


def _fabricate_staging(tmp_path, name):
    """A tiny deterministic staging directory standing in for a
    drained shard (the commit protocol never looks inside the
    bytes)."""
    staging = tmp_path / name
    staging.mkdir(parents=True)
    (staging / "rows.npy").write_bytes(b"rows-bytes-v1")
    sub = staging / "sub"
    sub.mkdir()
    (sub / "extra.bin").write_bytes(b"extra-bytes-v1")
    return str(staging)


class TestPlan:
    def test_plan_is_create_only(self, archive, tmp_path):
        store = FakeObjectStore()
        plan = _plan(store, "job", archive)
        assert len(plan["shards"]) == 2
        with pytest.raises(FileExistsError):
            _plan(store, "job", archive)
        loaded = load_plan_store(store, "job")
        assert loaded["shards"] == plan["shards"]

    def test_torn_plan_refused(self, archive, tmp_path):
        from tpudas.backfill.objqueue import _dumps

        store = FakeObjectStore()
        _plan(store, "job", archive)
        # flip payload bytes under the stamp: the crc gate refuses
        data, _tok = store.get("job/backfill.json")
        torn = data.replace(b'"shard_seconds"', b'"shard_SECONDS"')
        assert torn != data
        store.put("job/backfill.json", torn)
        with pytest.raises(ValueError, match="crc32"):
            load_plan_store(store, "job")
        # an unstamped alien object is refused on version instead
        store.put("job2/backfill.json", _dumps({"version": -9}))
        with pytest.raises(ValueError, match="version"):
            load_plan_store(store, "job2")


class TestLeaseProtocol:
    def test_claim_is_exclusive(self, archive, tmp_path):
        store = FakeObjectStore()
        _plan(store, "job", archive)
        q1 = _queue(store, "job", tmp_path, "w1")
        q2 = _queue(store, "job", tmp_path, "w2")
        lease = q1.claim_next()
        assert lease is not None
        assert q2.try_claim(lease.shard) is None
        assert q2.shard_state(lease.shard) == "leased"

    def test_stale_lease_stolen_by_cas_and_renew_loses(
        self, archive, tmp_path
    ):
        store = FakeObjectStore()
        _plan(store, "job", archive)
        clock = FakeClock()
        q1 = _queue(store, "job", tmp_path, "w1",
                    lease_ttl=10.0, clock=clock)
        q2 = _queue(store, "job", tmp_path, "w2",
                    lease_ttl=10.0, clock=clock)
        lease1 = q1.claim_next()
        clock.advance(30.0)  # w1 wedged past its deadline
        assert q2.shard_state(lease1.shard) == "stale"
        lease2 = q2.try_claim(lease1.shard)
        assert lease2 is not None
        # the steal was an atomic CAS: w1's renew loses definitively
        with pytest.raises(LeaseLostError):
            q1.renew(lease1)
        q2.renew(lease2)  # the thief's lease renews fine

    def test_torn_lease_protects_nothing(self, archive, tmp_path):
        store = FakeObjectStore()
        _plan(store, "job", archive)
        q = _queue(store, "job", tmp_path, "w1")
        shard = q.plan["shards"][0]["id"]
        store.put(
            f"job/{LEASES_PREFIX}/{shard}.json", b"{garbage torn"
        )
        assert q.claim_next() is not None  # claimed straight over it


class TestCommitRaces:
    def test_double_commit_race_exactly_once(self, archive, tmp_path):
        """Two workers hold (stale-stolen) leases on the same shard
        and both run the full commit protocol; exactly one create-only
        marker put wins."""
        store = FakeObjectStore()
        _plan(store, "job", archive)
        clock = FakeClock()
        q1 = _queue(store, "job", tmp_path, "w1",
                    lease_ttl=10.0, clock=clock)
        q2 = _queue(store, "job", tmp_path, "w2",
                    lease_ttl=10.0, clock=clock)
        lease1 = q1.claim_next()
        clock.advance(30.0)
        lease2 = q2.try_claim(lease1.shard)
        s1 = _fabricate_staging(tmp_path, "stage1")
        s2 = _fabricate_staging(tmp_path, "stage2")
        assert q2.commit(lease2, s2) == "committed"
        assert q1.commit(lease1, s1) == "lost"
        # the winner's marker stands; the shard is done exactly once
        marker = q1._get_verified(q1._done_key(lease1.shard))[0]
        assert marker["worker"] == "w2"
        assert q1.shard_state(lease1.shard) == "done"
        assert q1.manifest_verifies(lease1.shard)

    def test_lost_done_marker_cas_recovered(self, archive, tmp_path):
        """Race-matrix leg: the done marker's conditional put applies
        but the response drops.  The retry layer's token re-read must
        recognize its OWN marker — commit reports committed, not
        lost."""
        raw = FakeObjectStore(FaultInjector(
            FaultRule(kind="lost", op="cas", match=f"{DONE_PREFIX}/"),
        ))
        store = RetryingStore(raw, sleep_fn=lambda _s: None)
        _plan(store, "job", archive)
        q = _queue(store, "job", tmp_path, "w1")
        lease = q.claim_next()
        staging = _fabricate_staging(tmp_path, "stage")
        with use_registry(MetricsRegistry()) as reg:
            assert q.commit(lease, staging) == "committed"
            assert reg.counter(
                "tpudas_store_cas_recovered_total", "",
                labelnames=("backend",),
            ).value(backend="fake") == 1
        assert q.is_done(lease.shard)

    def test_crashed_commit_adopted(self, archive, tmp_path):
        """Uploads + manifest landed, the marker didn't (crash inside
        the commit window): the next claimer adopts instead of
        re-draining."""
        store = FakeObjectStore()
        _plan(store, "job", archive)
        q1 = _queue(store, "job", tmp_path, "w1")
        lease = q1.claim_next()
        q1._upload_staging(
            lease.shard, _fabricate_staging(tmp_path, "stage")
        )
        q1.release(lease)  # worker dies before _write_done

        q2 = _queue(store, "job", tmp_path, "w2")
        assert q2.shard_state(lease.shard) == "adoptable"
        lease2 = q2.try_claim(lease.shard)
        assert q2.manifest_verifies(lease2.shard)
        assert q2.adopt(lease2) == "committed"
        marker = q2._get_verified(q2._done_key(lease2.shard))[0]
        assert marker["adopted"] is True

    def test_mid_upload_crash_reexecutes(self, archive, tmp_path):
        """A manifest that does NOT verify (crash mid-step-1/2, or a
        corrupt object) re-executes: adopt refuses and clears the
        manifest so the re-run commits cleanly over the debris."""
        store = FakeObjectStore()
        _plan(store, "job", archive)
        q = _queue(store, "job", tmp_path, "w1")
        lease = q.claim_next()
        q._upload_staging(
            lease.shard, _fabricate_staging(tmp_path, "stage")
        )
        # one object's bytes rot under the manifest's token
        store.put(
            f"{q.shard_prefix(lease.shard)}/rows.npy", b"corrupted"
        )
        assert not q.manifest_verifies(lease.shard)
        assert q.adopt(lease) == "failed"
        assert q.shard_manifest(lease.shard) is None
        assert q.shard_state(lease.shard) == "open"


class TestStoreFsck:
    def test_torn_upload_classified_and_aborted(
        self, archive, tmp_path
    ):
        tag = "fsck-torn"
        raw = store_from_url(f"fake:{tag}", retry=False)
        _plan(raw, "job", archive)
        q = _queue(raw, "job", tmp_path, "w1")
        lease = q.claim_next()
        raw.injector.add(FaultRule(
            kind="torn", op="put", match="rows.npy",
        ))
        with pytest.raises(StoreNetworkError):
            q._upload_staging(
                lease.shard, _fabricate_staging(tmp_path, "stage")
            )
        q.release(lease)
        assert raw.list_uploads("job") != []

        from tools.fsck import main as fsck_main

        out = tmp_path / "report.json"
        rc = fsck_main([
            "job", "--store", f"fake:{tag}", "--out", str(out),
        ])
        assert rc == 0
        report = json.loads(out.read_text())
        assert report["clean"]
        assert any(
            i["artifact"] == "store_upload" and i["status"] == "torn"
            and i["action"] == "aborted"
            for i in report["issues"]
        )
        assert raw.list_uploads("job") == []

    def test_audit_classifies_and_repairs_the_matrix(
        self, archive, tmp_path
    ):
        """Fabricated debris across the classification matrix: torn
        done marker, stale lease, done-without-manifest, orphan
        object, torn result marker — one repair pass leaves the job
        clean and re-runnable."""
        store = FakeObjectStore()
        _plan(store, "job", archive)
        clock = FakeClock()
        q = _queue(store, "job", tmp_path, "w1",
                   lease_ttl=10.0, clock=clock)
        s_a, s_b = (sh["id"] for sh in q.plan["shards"])
        # shard A: committed, then its done marker torn + an orphan
        lease = q.try_claim(s_a)
        q.commit(lease, _fabricate_staging(tmp_path, "stage"))
        store.put(f"job/{DONE_PREFIX}/{s_a}.json", b"{torn")
        store.put(f"{q.shard_prefix(s_a)}/stray.bin", b"stray")
        # shard B: a lease whose worker died long ago
        q.try_claim(s_b)
        clock.advance(1e6)
        # result: a torn stitch marker
        store.put(f"job/{RESULT_DONE_KEY}", b"{also torn")

        report = audit_backfill_store(
            store, "job", repair=True, clock=clock,
        )
        assert report["clean"]
        seen = {
            (i["artifact"], i["status"], i["action"])
            for i in report["issues"]
        }
        assert ("backfill_done", "torn", "removed") in seen
        assert (
            "backfill_commit", "torn", "adopted_commit"
        ) in seen  # the torn marker's verifying manifest re-adopted
        assert ("backfill_lease", "stale_lease", "removed") in seen
        assert ("store_object", "orphan", "removed") in seen
        assert ("backfill_result", "torn", "removed") in seen
        # shard A's verifying manifest was re-adopted, not re-executed
        assert q.shard_state(s_a) == "done"
        # second pass: nothing left to say
        again = audit_backfill_store(
            store, "job", repair=True, clock=clock,
        )
        assert again["clean"] and again["issues"] == []


class TestEndToEnd:
    def test_two_workers_no_shared_fs_byte_identical(
        self, archive, sequential_ref, tmp_path
    ):
        """The acceptance leg: two workers coordinate ONLY through
        the object store (private scratch dirs each), and the stitched
        result is byte-identical to the sequential oracle."""
        store = store_from_url("fake:e2e-two-workers")
        _plan(store, "job", archive)
        results = {}

        def _run(name):
            results[name] = run_store_worker(
                store, "job",
                scratch=str(tmp_path / f"scratch-{name}"),
                worker=name, max_wall=300, idle_poll=0.01,
                sleep_fn=lambda _s: None,
            )

        threads = [
            threading.Thread(target=_run, args=(f"w{i}",))
            for i in (1, 2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        done = sum(
            r["committed"] + r["adopted"] for r in results.values()
        )
        assert done == 2  # every shard exactly once across the fleet
        assert any(r["stitched"] for r in results.values())

        # materialize the stitched result and compare content hashes
        q = _queue(store, "job", tmp_path, "reader")
        dest = str(tmp_path / "result")
        os.makedirs(dest)
        manifest = q._get_verified("job/result.json")[0]
        for rel, _tok in manifest["objects"].items():
            data, _t = store.get(f"job/{RESULT_PREFIX}/{rel}")
            path = os.path.join(dest, *rel.split("/"))
            os.makedirs(os.path.dirname(path) or dest, exist_ok=True)
            with open(path, "wb") as fh:
                fh.write(data)
        assert _content_hash(dest) == _content_hash(sequential_ref)

        # the job audits clean afterwards
        report = audit_backfill_store(store, "job", repair=False)
        assert report["clean"]

    def test_stitch_is_commit_wins(self, archive, tmp_path):
        store = store_from_url("fake:e2e-stitch-race")
        _plan(store, "job", archive)
        tally = run_store_worker(
            store, "job", scratch=str(tmp_path / "scratch"),
            worker="w1", max_wall=300, idle_poll=0.01,
            sleep_fn=lambda _s: None,
        )
        assert tally["stitched"]
        second = stitch_store_backfill(
            store, "job", worker="w2",
            scratch=str(tmp_path / "scratch2"),
        )
        assert second["status"] == "already"
