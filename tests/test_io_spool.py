"""IO round-trip, scanning, directory indexing, spool semantics."""

import os

import numpy as np
import pytest

from tpudas import spool
from tpudas.io.registry import read_file, scan_file, write_patch
from tpudas.io.spool import MemorySpool, merge_patches
from tpudas.proc.lfproc import check_merge
from tpudas.testing import make_synthetic_spool, synthetic_patch


@pytest.fixture
def spool_dir(tmp_path):
    d = tmp_path / "raw"
    make_synthetic_spool(d, n_files=4, file_duration=30.0, fs=100.0, n_ch=8)
    return str(d)


class TestDasdaeIO:
    def test_roundtrip(self, tmp_path):
        p = synthetic_patch(duration=5, fs=100.0, n_ch=8, noise=0.1)
        path = str(tmp_path / "x.h5")
        p.io.write(path, "dasdae")
        q = read_file(path)[0]
        assert np.allclose(q.host_data(), p.host_data())
        assert np.array_equal(q.coords["time"], p.coords["time"])
        assert np.array_equal(q.coords["distance"], p.coords["distance"])
        assert q.attrs["gauge_length"] == 10.0
        assert q.attrs["time_step"] == p.attrs["time_step"]

    def test_scan_metadata_only(self, tmp_path):
        p = synthetic_patch(duration=5, fs=100.0, n_ch=8)
        path = str(tmp_path / "x.h5")
        write_patch(p, path)
        info = scan_file(path)[0]
        assert info["time_min"] == p.attrs["time_min"]
        assert info["time_max"] == p.attrs["time_max"]
        assert info["ntime"] == 500 and info["ndistance"] == 8
        assert info["distance_max"] == 35.0

    def test_range_sliced_read(self, tmp_path):
        p = synthetic_patch(duration=10, fs=100.0, n_ch=8)
        path = str(tmp_path / "x.h5")
        write_patch(p, path)
        t = p.coords["time"]
        q = read_file(path, time=(t[100], t[199]), distance=(10.0, 20.0))[0]
        assert q.shape == (100, 3)
        assert q.attrs["time_min"] == t[100]

    def test_unknown_format_raises(self, tmp_path):
        p = synthetic_patch(duration=1, fs=100.0, n_ch=2)
        with pytest.raises(ValueError, match="unknown IO format"):
            p.io.write(str(tmp_path / "x.h5"), "not_a_format")


class TestFormatSniffing:
    def test_read_file_sniffs_each_format(self, tmp_path):
        from tpudas.io.registry import sniff_format

        p = synthetic_patch(duration=5, fs=100.0, n_ch=4, noise=0.1)
        # extensions deliberately lie: sniffing must go by magic bytes
        h5_path = str(tmp_path / "mislabeled.dat")
        tdas_path = str(tmp_path / "other.bin")
        write_patch(p, h5_path, format="dasdae")
        write_patch(p, tdas_path, format="tdas")
        assert sniff_format(h5_path) == "dasdae"
        assert sniff_format(tdas_path) == "tdas"
        for path in (h5_path, tdas_path):
            q = read_file(path)[0]
            assert np.allclose(q.host_data(), p.host_data(), atol=1e-6)

    def test_spool_on_single_tdas_file(self, tmp_path):
        # dc.spool(path) accepts any supported file (SURVEY.md §2.3);
        # before sniffing, a .tdas file was parsed as HDF5 and failed
        p = synthetic_patch(duration=5, fs=100.0, n_ch=4)
        path = str(tmp_path / "one.tdas")
        write_patch(p, path, format="tdas")
        sp = spool(path)
        assert len(sp) == 1
        assert np.array_equal(sp[0].host_data(), p.host_data())

    def test_unsniffable_file_raises(self, tmp_path):
        junk = tmp_path / "junk.bin"
        junk.write_bytes(b"definitely not a DAS file")
        with pytest.raises(ValueError, match="magic bytes"):
            read_file(str(junk))

    def test_scan_file_sniffs(self, tmp_path):
        p = synthetic_patch(duration=5, fs=100.0, n_ch=4)
        path = str(tmp_path / "x.tdas")
        write_patch(p, path, format="tdas")
        assert scan_file(path)[0]["format"] == "tdas"

    def test_reregister_replaces_sniffer(self, tmp_path):
        from tpudas.io import registry

        before = list(registry._SNIFFERS)
        try:
            reader = lambda path, **kw: []  # noqa: E731
            registry.register_format(
                "fmtx", reader, None, None, sniff=lambda head: False
            )
            # a corrected predicate must REPLACE the old one, not queue
            # behind it in first-match-wins order
            registry.register_format(
                "fmtx", reader, None, None,
                sniff=lambda head: head[:4] == b"FMTX",
            )
            names = [n for n, _ in registry._SNIFFERS]
            assert names.count("fmtx") == 1
            probe = tmp_path / "probe.bin"
            probe.write_bytes(b"FMTX rest of file")
            assert registry.sniff_format(str(probe)) == "fmtx"
        finally:
            registry._SNIFFERS[:] = before
            registry._FORMATS.pop("fmtx", None)


class TestDirectorySpool:
    def test_update_and_len(self, spool_dir):
        sp = spool(spool_dir).sort("time").update()
        assert len(sp) == 4

    def test_lazy_index_without_update(self, spool_dir):
        # notebook cell 11: dc.spool(output).chunk(time=None) w/o update()
        sp = spool(spool_dir)
        assert len(sp) == 4

    def test_incremental_update_picks_up_new_files(self, spool_dir):
        sp = spool(spool_dir).update()
        assert len(sp) == 4
        make_synthetic_spool(
            spool_dir, n_files=6, file_duration=30.0, fs=100.0, n_ch=8
        )
        sp2 = spool(spool_dir).update()
        assert len(sp2) == 6

    def test_getitem_negative(self, spool_dir):
        sp = spool(spool_dir).sort("time").update()
        last = sp[-1]
        first = sp[0]
        assert last.attrs["time_min"] > first.attrs["time_min"]

    def test_get_contents_dataframe(self, spool_dir):
        df = spool(spool_dir).update().get_contents()
        assert len(df) == 4
        assert {"time_min", "time_max"} <= set(df.columns)

    def test_select_time_filters_files(self, spool_dir):
        sp = spool(spool_dir).update()
        t0 = sp[0].attrs["time_min"]
        sub = sp.select(time=(t0, t0 + np.timedelta64(35, "s")))
        assert len(sub) == 2  # only first two files overlap

    def test_select_distance_trims(self, spool_dir):
        sp = spool(spool_dir).update()
        sub = sp.select(distance=(10.0, 20.0))
        assert sub[0].shape[1] == 3

    def test_select_string_times(self, spool_dir):
        sp = spool(spool_dir).update()
        sub = sp.select(time=("2023-03-22T00:00:00", "2023-03-22T00:00:29"))
        assert len(sub) >= 1

    def test_chunk_merges_contiguous(self, spool_dir):
        merged = spool(spool_dir).update().chunk(time=None)
        assert len(merged) == 1
        p = check_merge(list(merged))
        assert p.shape == (4 * 3000, 8)
        # time axis strictly increasing, uniform
        steps = np.diff(p.coords["time"].astype(np.int64))
        assert np.all(steps == steps[0])

    def test_gap_detection(self, tmp_path):
        d = tmp_path / "gappy"
        make_synthetic_spool(d, n_files=2, file_duration=30.0, fs=100.0, n_ch=4)
        make_synthetic_spool(
            d, n_files=1, file_duration=30.0, fs=100.0, n_ch=4,
            start="2023-03-22T01:00:00",
        )
        merged = spool(str(d)).update().chunk(time=None)
        assert len(merged) == 2
        with pytest.raises(Exception, match="Gap in data exists"):
            check_merge(list(merged))

    def test_spool_of_spool_passthrough(self, spool_dir):
        sp = spool(spool_dir)
        assert spool(sp) is sp

    def test_ignores_foreign_files(self, spool_dir):
        with open(os.path.join(spool_dir, "notes.txt"), "w") as fh:
            fh.write("not das data")
        with open(os.path.join(spool_dir, "junk.h5"), "wb") as fh:
            fh.write(b"not hdf5 at all")
        assert len(spool(spool_dir).update()) == 4


class TestMemorySpoolAndMerge:
    def test_memory_spool_select(self):
        p = synthetic_patch(duration=30, fs=100.0, n_ch=8)
        sp = MemorySpool([p])
        t = p.coords["time"]
        sub = sp.select(time=(t[100], t[400]))
        assert sub[0].shape[0] == 301

    def test_merge_overlapping_patches_dedupes(self):
        p = synthetic_patch(duration=30, fs=100.0, n_ch=4)
        t = p.coords["time"]
        a = p.select(time=(t[0], t[1999]))
        b = p.select(time=(t[1500], t[2999]))  # overlaps a by 500
        merged = merge_patches([a, b])
        assert len(merged) == 1
        assert merged[0].shape[0] == 3000
        assert np.allclose(merged[0].host_data(), p.host_data())

    def test_chunk_segments(self):
        p = synthetic_patch(duration=30, fs=100.0, n_ch=4)
        segs = MemorySpool([p]).chunk(time=10.0)
        assert len(segs) == 3
        assert all(s.shape[0] == 1000 for s in segs)


class TestContentsColumns:
    def test_memory_spool_identity_columns(self):
        p = synthetic_patch(duration=5, fs=100.0, n_ch=4)
        q = p.update_attrs(network="XX", station="WELL1", tag="raw")
        df = MemorySpool([q]).get_contents()
        for col in ("network", "station", "tag", "instrument_id",
                    "data_units", "dims", "time_min", "time_step"):
            assert col in df.columns, col
        assert df.loc[0, "network"] == "XX"
        assert df.loc[0, "station"] == "WELL1"
        assert df.loc[0, "dims"] == "time,distance"
        assert df.loc[0, "instrument_id"] == ""  # absent -> empty string

    def test_directory_spool_identity_columns(self, spool_dir):
        df = spool(spool_dir).update().get_contents()
        for col in ("network", "station", "tag", "instrument_id",
                    "data_units", "dims", "path", "format"):
            assert col in df.columns, col
