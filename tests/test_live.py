"""tpudas.live: the push subscription plane (ISSUE 19).

Covers the acceptance set: bounded per-client queues (never exceed
depth), deterministic degrade→drop ladder, snapshot-then-delta
byte-consistency against a pull ``/query`` of the same window,
``Last-Event-ID`` sequence-gap resume (ring replay vs snapshot
fallback), crash-only parity (a fault — or a KI-kill, slow leg — at
``live.emit`` leaves the round loop's durable products byte-identical
to a no-subscriber control), fleet ``/s/<id>/live`` routing with
unknown-id 404, and the ``LFProc.add_emit_listener`` hardening
satellite (a raising listener is counted and skipped, never poisoning
the commit path).
"""

import base64
import glob
import hashlib
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tpudas import spool
from tpudas.codec import decode_tile
from tpudas.live import find_hub, register_hub, reset_hubs
from tpudas.live.hub import DEGRADE_FACTOR, LiveFrame, LiveHub
from tpudas.live.protocol import delta_event, resume_frames
from tpudas.obs.registry import MetricsRegistry, use_registry
from tpudas.proc.lfproc import LFProc
from tpudas.serve.http import DASServer
from tpudas.testing import (
    FaultPlan,
    FaultSpec,
    install_fault_plan,
    make_synthetic_spool,
)

# same stream fixture vocabulary as tests/test_serve.py
from test_serve import FS, FILE_SEC, NCH, T0, _append_files, _run_stream


@pytest.fixture(autouse=True)
def _fresh_hubs():
    reset_hubs()
    yield
    reset_hubs()


def _frame(seq, rnd=None, rows=16, nch=4, seed=0):
    rng = np.random.default_rng(seed + seq)
    t0 = np.int64(1_700_000_000_000_000_000) + seq * rows * 10**9
    times = t0 + np.arange(rows, dtype=np.int64) * 10**9
    data = rng.standard_normal((rows, nch)).astype(np.float32)
    return LiveFrame(seq, rnd if rnd is not None else seq, times, data,
                     [], 10**9)


def _publish_n(hub, n, start=1, **kw):
    for i in range(start, start + n):
        fr = _frame(i, **kw)
        with hub._lock:
            hub.seq = fr.seq
            hub._ring.append(fr)
        hub._fanout(fr)


def _sse_events(raw: str):
    """[(event, id_or_None, data_dict_or_None)] from an SSE stream,
    complete blocks only."""
    out = []
    complete = raw.rsplit("\n\n", 1)[0]
    for block in complete.split("\n\n"):
        ev = ident = data = None
        for line in block.splitlines():
            if line.startswith("event: "):
                ev = line[7:]
            elif line.startswith("id: "):
                ident = int(line[4:])
            elif line.startswith("data: "):
                data = json.loads(line[6:])
        if ev is not None:
            out.append((ev, ident, data))
    return out


def _read_sse(url, want_events=1, timeout=15.0, headers=()):
    req = urllib.request.Request(url)
    for k, v in headers:
        req.add_header(k, v)
    resp = urllib.request.urlopen(req, timeout=timeout)
    buf = b""
    deadline = time.time() + timeout
    while time.time() < deadline:
        chunk = resp.read(512)
        if not chunk:
            break
        buf += chunk
        if len(_sse_events(buf.decode())) >= want_events:
            break
    resp.close()
    return _sse_events(buf.decode())


def _h5_digests(folder):
    return {
        os.path.basename(f): hashlib.sha256(
            open(f, "rb").read()
        ).hexdigest()
        for f in sorted(glob.glob(os.path.join(folder, "*.h5")))
    }


class TestBoundedQueue:
    def test_queue_never_exceeds_depth(self):
        hub = LiveHub("s", queue_depth=3, max_level=1, ring=8)
        sub = hub.subscribe()
        for i in range(1, 20):
            _publish_n(hub, 1, start=i)
            assert sub.qsize() <= 3
        # never drained at max level → the ladder dropped it
        assert sub.dropped == "slow"
        assert hub.n_subscribers() == 0

    def test_degrade_then_drop_ladder_is_deterministic(self):
        """Depth D, max level M: a never-reading client gets exactly
        D queued, M degrade steps (each shedding one oldest frame),
        then the drop — nothing about timing or rates involved."""
        reg = MetricsRegistry()
        with use_registry(reg):
            hub = LiveHub("s", queue_depth=2, max_level=2, ring=16)
            sub = hub.subscribe()
            outcomes = []
            for i in range(1, 7):
                fr = _frame(i)
                outcomes.append(sub.offer(fr))
            assert outcomes == [
                "queued", "queued",          # D = 2
                "degraded", "degraded",      # M = 2 ladder rungs
                "dropped",                   # ladder exhausted
                "dead",                      # already gone
            ]
            assert sub.level == 2
            assert sub.dropped == "slow"
            assert sub.degrades == 2
            # each degrade shed exactly one oldest frame; the drop
            # cleared the rest
            assert sub.qsize() == 0

    def test_subscriber_cap_sheds_with_reason(self):
        reg = MetricsRegistry()
        with use_registry(reg):
            hub = LiveHub("s", max_subscribers=1)
            assert hub.subscribe() is not None
            assert hub.subscribe() is None
            assert reg.value(
                "tpudas_live_subscribers_dropped_total",
                reason="capacity",
            ) == 1

    def test_degrade_level_rows_match_block_mean(self):
        fr = _frame(1, rows=10, nch=3)
        lvl1 = fr.level_array(1)
        f = DEGRADE_FACTOR
        expect = np.concatenate([
            fr.data[:8].reshape(2, f, 3).mean(axis=1),
            fr.data[8:].mean(axis=0, keepdims=True),
        ]).astype(np.float32)
        np.testing.assert_array_equal(lvl1, expect)
        assert fr.level_times(1).size == lvl1.shape[0]
        # payload cache: same (level, codec) object is reused
        assert fr.payload(1) is fr.payload(1)
        np.testing.assert_array_equal(decode_tile(fr.payload(1)), lvl1)


class TestResume:
    def test_gap_inside_ring_replays(self):
        reg = MetricsRegistry()
        with use_registry(reg):
            hub = LiveHub("s", ring=16)
            _publish_n(hub, 5)
            frames = resume_frames(hub, 2)
            assert [f.seq for f in frames] == [3, 4, 5]
            assert reg.value(
                "tpudas_live_resumes_total", result="replay"
            ) == 1

    def test_gap_beyond_ring_falls_back_to_snapshot(self):
        reg = MetricsRegistry()
        with use_registry(reg):
            hub = LiveHub("s", ring=2)
            _publish_n(hub, 6)
            assert resume_frames(hub, 1) is None
            assert reg.value(
                "tpudas_live_resumes_total", result="snapshot"
            ) == 1

    def test_up_to_date_client_replays_nothing(self):
        hub = LiveHub("s", ring=4)
        _publish_n(hub, 3)
        assert resume_frames(hub, 3) == []


class TestListenerHardening:
    """ISSUE 19 satellite: LFProc.add_emit_listener — a raising
    listener is counted (``tpudas_lfproc_listener_errors_total``) and
    skipped for the round's remaining emissions instead of poisoning
    the commit path."""

    def test_raising_listener_is_counted_and_skipped(self, tmp_path):
        src = str(tmp_path / "raw")
        out = str(tmp_path / "out")
        make_synthetic_spool(
            src, n_files=4, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        reg = MetricsRegistry()
        with use_registry(reg):
            lfp = LFProc(spool(src).sort("time").update())
            lfp.update_processing_parameter(
                output_sample_interval=1.0,
                process_patch_size=40,
                edge_buff_size=8,
            )
            lfp.set_output_folder(out, delete_existing=True)
            good, bad = [], []

            def raising(patch):
                bad.append(patch)
                raise RuntimeError("broken consumer")

            lfp.add_emit_listener(raising)
            lfp.add_emit_listener(good.append)
            t0 = np.datetime64(T0)
            lfp.process_time_range(
                t0, t0 + np.timedelta64(int(2 * FILE_SEC), "s")
            )
            # output committed, good listener saw every emission
            assert glob.glob(os.path.join(out, "*.h5"))
            assert len(good) >= 1
            # the raising listener fired ONCE, then was skipped
            assert len(bad) == 1
            assert reg.value(
                "tpudas_lfproc_listener_errors_total"
            ) == 1
            # re-armed for the next round by the driver
            lfp.clear_emit_failures()
            assert lfp._failed_listeners == set()


class TestEndToEnd:
    @pytest.fixture()
    def live_streamed(self, tmp_path):
        """3 + 2 + 2 files over 3 rounds with live + pyramid on."""
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        rounds = _run_stream(
            src, out, feed_batches=[(3, 2), (5, 2)], max_rounds=3,
            live=True, pyramid=True,
        )
        assert rounds == 3
        hub = find_hub(folder=out)
        assert hub is not None and hub.seq >= 2
        return src, out, hub

    def test_snapshot_then_delta_matches_pull_query(
        self, live_streamed
    ):
        """The lossless snapshot + replayed deltas reconstruct exactly
        what GET /query serves for the same windows."""
        _src, out, hub = live_streamed
        with DASServer(out, port=0) as srv:
            events = _read_sse(
                srv.base_url + "/live?window=30&heartbeat=0.2",
                want_events=2, timeout=20,
            )
            kinds = [e[0] for e in events]
            assert kinds[0] == "hello"
            assert events[0][2]["seq"] == hub.seq
            # snapshot vs /query of the SAME window
            snap = next(d for ev, _i, d in events if ev == "snapshot")
            t0_ns = snap["t0_ns"]
            n = snap["rows"]
            step = snap["step_ns"]
            q = urllib.request.Request(
                srv.base_url + "/query?"
                + f"t0={t0_ns}&t1={t0_ns + n * step}&format=npy"
            )
            buf = urllib.request.urlopen(q, timeout=30).read()
            import io

            pulled = np.load(io.BytesIO(buf))
            pushed = decode_tile(base64.b64decode(snap["blob"]))
            assert pushed.dtype == np.float32
            np.testing.assert_array_equal(
                pushed, np.asarray(pulled, np.float32)
            )
            # deltas replayed from seq 0 are byte-identical to the
            # hub's ring frames (lossless default codec)
            deltas = _read_sse(
                srv.base_url + "/live?window=0&heartbeat=0.2&last_id=0",
                want_events=1 + hub.seq, timeout=20,
            )
            ring = {f.seq: f for f in list(hub._ring)}
            n_checked = 0
            for ev, ident, data in deltas:
                if ev != "delta":
                    continue
                assert ident == data["seq"]
                got = decode_tile(base64.b64decode(data["blob"]))
                np.testing.assert_array_equal(
                    got, ring[data["seq"]].level_array(data["level"])
                )
                n_checked += 1
            assert n_checked >= 2

    def test_sequence_gap_resume_over_http(self, live_streamed):
        _src, out, hub = live_streamed
        with DASServer(out, port=0) as srv:
            # gap inside the ring: Last-Event-ID header wins, missed
            # deltas replay in order with their ids
            events = _read_sse(
                srv.base_url + "/live?window=0&heartbeat=0.2",
                want_events=hub.seq,  # hello + deltas 2..seq
                timeout=20,
                headers=(("Last-Event-ID", "1"),),
            )
            ids = [i for ev, i, _d in events if ev == "delta"]
            assert ids == list(range(2, hub.seq + 1))

    def test_flight_record_and_slo_carry_live_block(
        self, live_streamed
    ):
        from tpudas.obs.collect import live_entry, slo_status
        from tpudas.obs.flight import read_flight

        _src, out, hub = live_streamed
        rounds = read_flight(out, kind="round")
        blocks = [r["live"] for r in rounds if "live" in r]
        assert blocks, "round records carry no live block"
        folded = live_entry(rounds)
        assert folded["published"] == hub.published
        assert "live" in slo_status(out)

    def test_fault_at_live_emit_keeps_outputs_byte_identical(
        self, tmp_path
    ):
        """The fast crash-only leg: every live publish raising (the
        ``live.emit`` fault site) changes NOTHING durable — outputs
        byte-identical to a control run with no live plane at all."""

        def run(leg, live, plan=None):
            src = str(tmp_path / f"raw_{leg}")
            out = str(tmp_path / f"out_{leg}")
            make_synthetic_spool(
                src, n_files=3, file_duration=FILE_SEC, fs=FS,
                n_ch=NCH, noise=0.01,
            )
            reg = MetricsRegistry()
            with use_registry(reg), install_fault_plan(
                plan or FaultPlan()
            ):
                rounds = _run_stream(
                    src, out, feed_batches=[(3, 2)], max_rounds=2,
                    live=live, pyramid=True,
                )
            assert rounds == 2
            return out, reg

        plan = FaultPlan(
            FaultSpec("live.emit", action="raise", at=1, times=99,
                      exc=RuntimeError)
        )
        out_control, _ = run("control", live=False)
        out_faulted, reg = run("faulted", live=True, plan=plan)
        assert reg.value("tpudas_live_publish_errors_total") >= 2
        assert _h5_digests(out_faulted) == _h5_digests(out_control)

    def test_subscribers_never_change_outputs(self, tmp_path):
        """Attached (and never-reading, ladder-dropped) subscribers
        leave the round loop's durable products byte-identical to the
        no-subscriber control."""

        def run(leg, live, attach=False):
            src = str(tmp_path / f"raw_{leg}")
            out = str(tmp_path / f"out_{leg}")
            make_synthetic_spool(
                src, n_files=3, file_duration=FILE_SEC, fs=FS,
                n_ch=NCH, noise=0.01,
            )
            subs = []

            def on_round(rnd, lfp):
                if attach and not subs:
                    hub = find_hub(folder=out)
                    # stalled client: subscribes, never reads
                    subs.append(
                        hub.subscribe(depth=1)
                    )

            reg = MetricsRegistry()
            with use_registry(reg):
                _run_stream(
                    src, out, feed_batches=[(3, 2)], max_rounds=3,
                    live=live, pyramid=True, on_round=on_round,
                )
            return out, subs

        out_control, _ = run("nosub", live=False)
        out_live, subs = run("stalled", live=True, attach=True)
        assert _h5_digests(out_live) == _h5_digests(out_control)
        # and the stalled client went down the ladder, not the loop
        assert subs and (
            subs[0].dropped == "slow" or subs[0].degrades > 0
            or subs[0].qsize() <= 1
        )


class TestFleetRouting:
    def test_stream_mount_and_unknown_id(self, tmp_path):
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        _run_stream(src, out, max_rounds=1, live=True)
        hub = find_hub(folder=out)
        assert hub is not None
        register_hub("sA")  # also reachable by the fleet stream id
        with DASServer(streams={"sA": out}, port=0) as srv:
            events = _read_sse(
                srv.base_url + "/s/sA/live?window=0&heartbeat=0.2"
                + "&last_id=0",
                want_events=2, timeout=20,
            )
            assert events[0][0] == "hello"
            # unknown stream id: 404 with the stream list
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    srv.base_url + "/s/nope/live", timeout=10
                )
            assert ei.value.code == 404
            # bare /live on a fleet-only server: route hint 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    srv.base_url + "/live", timeout=10
                )
            assert ei.value.code == 404

    def test_no_producer_is_503(self, tmp_path):
        out = str(tmp_path / "results")
        os.makedirs(out)
        reset_hubs()
        with DASServer(out, port=0) as srv:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    srv.base_url + "/live", timeout=10
                )
            assert ei.value.code == 503
            assert ei.value.headers.get("Retry-After")


class TestBridge:
    def test_bridge_mirrors_frames_across_registries(self):
        from tpudas.live.sse import BridgeSubscriber, LiveBridge

        hub = register_hub("bstream")
        bridge = LiveBridge().start()
        try:
            addr = bridge.address
            reset_hubs()  # simulate the worker process's empty registry
            sub = BridgeSubscriber(addr, retry_s=0.1).start()
            try:
                deadline = time.time() + 10
                # frames broadcast only to connections that exist at
                # publish time — wait for the worker to attach first
                while time.time() < deadline and not bridge._conns:
                    time.sleep(0.02)
                assert bridge._conns, "worker never connected"
                _publish_n(hub, 3)
                mirror = None
                while time.time() < deadline:
                    mirror = find_hub(stream_id="bstream")
                    if mirror is not None and mirror.seq >= 3:
                        break
                    time.sleep(0.05)
                assert mirror is not None and mirror.seq == 3
                a = mirror.latest_frame()
                b = hub.latest_frame()
                assert a.seq == b.seq
                np.testing.assert_array_equal(
                    a.level_array(0), b.level_array(0)
                )
                # the mirrored frame reuses the producer's encoding
                assert a.payload(0) == b.payload(0)
            finally:
                sub.stop()
        finally:
            bridge.stop()


_KILL_CHILD = r"""
import os, signal, sys
sys.path.insert(0, {repo!r})
sys.path.insert(0, {tests!r})
os.environ["JAX_PLATFORMS"] = "cpu"
from tpudas.resilience.faults import FaultPlan, FaultSpec
from tpudas.resilience import faults as _faults
from test_serve import _run_stream

def _kill9(_seconds):
    os.kill(os.getpid(), signal.SIGKILL)

plan = FaultPlan(
    FaultSpec("live.emit", action="delay", at=1, seconds=0.0,
              sleep_fn=_kill9)
)
_faults._PLAN = plan
_run_stream({src!r}, {out!r}, max_rounds=2, live=True, pyramid=True)
raise SystemExit("unreachable: the kill never fired")
"""


class TestKillAtLiveEmit:
    @pytest.mark.slow
    def test_sigkill_at_live_emit_then_resume_matches_control(
        self, tmp_path
    ):
        """The real KI-kill leg: SIGKILL the producer process exactly
        at the first ``live.emit`` (after the round's commit, before
        its health write), resume the stream to completion, and the
        durable products are byte-identical to an untouched control
        run — the push plane held nothing the disk did not."""
        repo = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        src = str(tmp_path / "raw")
        out = str(tmp_path / "out_killed")
        make_synthetic_spool(
            src, n_files=5, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        script = _KILL_CHILD.format(
            repo=repo, tests=os.path.join(repo, "tests"),
            src=src, out=out,
        )
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, timeout=600,
        )
        assert proc.returncode == -9, (
            f"child was not SIGKILLed: rc={proc.returncode} "
            f"stderr={proc.stderr[-2000:]}"
        )
        # resume: the restarted stream re-derives its position from
        # disk and finishes the work
        rounds = _run_stream(src, out, max_rounds=2, live=True,
                             pyramid=True)
        assert rounds >= 1
        # control: same source bytes, straight through, live off
        src_c = str(tmp_path / "raw_control")
        out_c = str(tmp_path / "out_control")
        import shutil

        shutil.copytree(src, src_c)
        _run_stream(src_c, out_c, max_rounds=3, live=False,
                    pyramid=True)
        assert _h5_digests(out) == _h5_digests(out_c)
