"""Cluster observability (ISSUE 13): the crash-surviving flight
recorder, the round-phase timeline, and the fleet/backfill rollup.

Covers: record/flush/read roundtrip, bounded segment rotation,
torn-tail recovery (byte-truncate = SIGKILL mid-segment-write →
readable prefix + audit truncate-repair, clean second audit), KI-kill
at the ``obs.flight_write`` site, ENOSPC shedding, scoped span
capture + drop counters, phase-timeline completeness (every processed
round emits all phases exactly once), the `/trace` + `/slo` + enriched
`/fleet/healthz` endpoints, and the ``obs_report`` rollup over a
4-stream fleet and a 2-worker backfill run.
"""

import json
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from tpudas.obs.collect import (
    SLOPolicy,
    backfill_rollup,
    cluster_snapshot,
    fleet_rollup,
    slo_status,
)
from tpudas.obs.flight import (
    FlightRecorder,
    capture,
    read_flight,
    scan_segment,
    segment_paths,
)
from tpudas.obs.phases import PHASES, RoundPhases, phase_seconds_snapshot
from tpudas.obs.registry import MetricsRegistry, use_registry
from tpudas.obs.trace import add_span_sink, remove_span_sink, span
from tpudas.testing import (
    FaultPlan,
    FaultSpec,
    install_fault_plan,
    make_synthetic_spool,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

T0 = "2023-03-22T00:00:00"
FS = 50.0
FILE_SEC = 30.0
N_CH = 4


def _run_stream(src, out, rounds=1, feed=None, **kw):
    from tpudas.proc.streaming import run_lowpass_realtime

    state = {"fed": 0}

    def fake_sleep(_):
        if feed is not None and state["fed"] < rounds - 1:
            state["fed"] += 1
            feed(state["fed"])

    kwargs = dict(
        source=src, output_folder=out, start_time=T0,
        output_sample_interval=1.0, edge_buffer=5.0,
        process_patch_size=20, poll_interval=0.0,
        sleep_fn=fake_sleep, max_rounds=rounds + 2,
        health=True, pyramid=False, detect=False, flight=True,
    )
    kwargs.update(kw)
    return run_lowpass_realtime(**kwargs)


def _feed_files(src, first, count):
    make_synthetic_spool(
        src, n_files=count, file_duration=FILE_SEC, fs=FS, n_ch=N_CH,
        noise=0.01,
        start=np.datetime64(T0)
        + np.timedelta64(int(first * FILE_SEC * 1e9), "ns"),
        prefix=f"raw{first:04d}",
    )


# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_record_flush_read_roundtrip(self, tmp_path):
        reg = MetricsRegistry()
        with use_registry(reg):
            rec = FlightRecorder(tmp_path)
            rec.record("round", round=1, phases={"poll": 0.1})
            rec.record("span", name="stream.round", dur_s=0.5, round=1)
            rec.record("fault", fault_kind="transient", attempt=1)
            assert rec.flush() == 3
            rec.close()
        recs = read_flight(tmp_path)
        assert [r["kind"] for r in recs] == ["round", "span", "fault"]
        assert recs[0]["phases"] == {"poll": 0.1}
        # filters
        assert len(read_flight(tmp_path, kind="span")) == 1
        assert read_flight(tmp_path, kind="span", name="stream.round")
        assert read_flight(tmp_path, limit=2) == recs[-2:]
        assert reg.value(
            "tpudas_obs_flight_records_total", kind="span"
        ) == 1.0
        assert reg.value("tpudas_obs_flight_bytes_total") > 0

    def test_ring_rotation_is_bounded(self, tmp_path):
        rec = FlightRecorder(
            tmp_path, max_segment_bytes=4096, max_segments=3
        )
        for i in range(400):
            rec.record("round", round=i, pad="x" * 64)
            rec.flush()
        rec.close()
        segs = segment_paths(tmp_path)
        assert 1 < len(segs) <= 3
        for p in segs:
            # rotation happens at the flush AFTER crossing the bound,
            # so a segment may exceed it by at most one record
            assert os.path.getsize(p) < 4096 + 256
        # the ring kept the NEWEST records
        rounds = [r["round"] for r in read_flight(tmp_path, kind="round")]
        assert rounds[-1] == 399 and rounds[0] > 0
        assert rounds == sorted(rounds)

    def test_torn_tail_readable_prefix_and_audit_repair(self, tmp_path):
        from tpudas.integrity.audit import audit

        rec = FlightRecorder(tmp_path)
        for i in range(10):
            rec.record("round", round=i)
        rec.flush()
        rec.close()
        seg = segment_paths(tmp_path)[-1]
        with open(seg, "rb") as fh:
            data = fh.read()
        with open(seg, "wb") as fh:
            fh.write(data[:-15])  # SIGKILL mid-segment-write
        reg = MetricsRegistry()
        with use_registry(reg):
            rounds = [
                r["round"] for r in read_flight(tmp_path, kind="round")
            ]
        assert rounds == list(range(9))  # the verified prefix
        assert reg.value("tpudas_obs_flight_torn_records_total") == 1.0
        rep = audit(str(tmp_path), repair=True)
        assert rep["clean"]
        assert [(i["artifact"], i["status"], i["action"])
                for i in rep["issues"]] == [("flight", "torn", "truncated")]
        rep2 = audit(str(tmp_path), repair=True)
        assert rep2["clean"] and not rep2["issues"]
        # the repaired ring resumes appending
        rec2 = FlightRecorder(tmp_path)
        rec2.record("round", round=99)
        rec2.flush()
        rec2.close()
        assert read_flight(tmp_path, kind="round")[-1]["round"] == 99

    def test_torn_tail_then_append_rotates_no_record_lost(self, tmp_path):
        """Resume over an UNAUDITED torn segment: appending onto the
        torn line would merge it into our first record and silently
        lose it — the recorder must rotate to a fresh segment."""
        rec = FlightRecorder(tmp_path)
        for i in range(5):
            rec.record("round", round=i)
        rec.flush()
        rec.close()
        seg = segment_paths(tmp_path)[-1]
        with open(seg, "rb") as fh:
            data = fh.read()
        with open(seg, "wb") as fh:
            fh.write(data[:-9])  # crash mid-write, NO audit yet
        rec2 = FlightRecorder(tmp_path)
        rec2.record("round", round=100)
        rec2.record("round", round=101)
        rec2.flush()
        rec2.close()
        rounds = [r["round"] for r in read_flight(tmp_path, kind="round")]
        assert rounds == [0, 1, 2, 3, 100, 101]  # only the torn line lost
        assert len(segment_paths(tmp_path)) == 2  # rotated, not appended

    def test_corrupt_middle_line_skipped_not_fatal(self, tmp_path):
        rec = FlightRecorder(tmp_path)
        for i in range(5):
            rec.record("round", round=i)
        rec.flush()
        rec.close()
        seg = segment_paths(tmp_path)[-1]
        lines = open(seg).read().splitlines()
        lines[2] = lines[2].replace('"round":2', '"round":7')  # bit rot
        with open(seg, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        records, good_lines, bad = scan_segment(seg)
        assert bad == 1
        assert [r["round"] for r in records] == [0, 1, 3, 4]

    def test_ki_kill_at_flush_site_leaves_verified_prefix(self, tmp_path):
        from tpudas.integrity.audit import audit

        rec = FlightRecorder(tmp_path)
        rec.record("round", round=1)
        rec.flush()
        rec.record("round", round=2)
        plan = FaultPlan(
            FaultSpec("obs.flight_write", exc=KeyboardInterrupt)
        )
        with install_fault_plan(plan):
            with pytest.raises(KeyboardInterrupt):
                rec.flush()
        assert plan.fired
        rounds = [r["round"] for r in read_flight(tmp_path, kind="round")]
        assert rounds == [1]
        assert audit(str(tmp_path), repair=True)["clean"]

    def test_enospc_shed_drops_counted_never_raises(self, tmp_path):
        from tpudas.integrity import resource as _resource

        reg = MetricsRegistry()
        with use_registry(reg):
            rec = FlightRecorder(tmp_path)
            rec.record("round", round=1)
            _resource.note_pressure("test", None)
            try:
                assert rec.flush() == 0  # shed, not written
            finally:
                _resource.clear_pressure("test done")
            assert reg.value(
                "tpudas_obs_flight_drops_total", reason="shed"
            ) == 1.0
            assert reg.value(
                "tpudas_obs_events_dropped_total", reason="flight_shed"
            ) == 1.0
            rec.close()
        assert read_flight(tmp_path) == []

    def test_write_failure_drops_counted_never_raises(self, tmp_path):
        # .flight exists as a FILE: every flush write must fail softly
        open(os.path.join(tmp_path, ".flight"), "w").close()
        reg = MetricsRegistry()
        with use_registry(reg):
            rec = FlightRecorder(tmp_path)
            rec.record("round", round=1)
            assert rec.flush() == 0
            assert reg.value(
                "tpudas_obs_flight_drops_total", reason="error"
            ) == 1.0


class TestSpanCapture:
    def test_capture_scopes_spans_to_recorder(self, tmp_path):
        reg = MetricsRegistry()
        with use_registry(reg):
            rec = FlightRecorder(tmp_path)
            with span("outside.scope"):
                pass
            with capture(rec):
                with span("stream.round", round=3):
                    with span("stream.increment"):
                        with span("op.cascade_stream"):  # depth 2: capped
                            pass
            with span("outside.after"):
                pass
            rec.flush()
            rec.close()
        names = [r["name"] for r in read_flight(tmp_path, kind="span")]
        assert "stream.round" in names and "stream.increment" in names
        assert "outside.scope" not in names
        assert "outside.after" not in names
        assert "op.cascade_stream" not in names  # depth cap (default 2)
        rec3 = read_flight(tmp_path, kind="span", name="stream.round")[0]
        assert rec3["round"] == 3 and rec3["dur_s"] >= 0.0

    def test_capture_none_is_noop(self):
        with capture(None):
            with span("whatever"):
                pass

    def test_raising_sink_counted_not_fatal(self):
        reg = MetricsRegistry()

        def bad_sink(rec):
            raise RuntimeError("boom")

        add_span_sink(bad_sink)
        try:
            with use_registry(reg):
                with span("sink.victim"):
                    pass
        finally:
            remove_span_sink(bad_sink)
        assert reg.value(
            "tpudas_obs_spans_dropped_total", reason="sink_error"
        ) >= 1.0

    def test_log_event_drops_counted_obs_wide(self):
        from tpudas.utils.logging import log_event, set_log_handler

        reg = MetricsRegistry()

        def bad_handler(event):
            raise ValueError("nope")

        set_log_handler(bad_handler)
        try:
            with use_registry(reg):
                log_event("doomed")
        finally:
            set_log_handler(None)
        assert reg.value(
            "tpudas_obs_events_dropped_total", reason="handler"
        ) == 1.0


# ---------------------------------------------------------------------------


class TestPhases:
    def test_round_phases_accumulate_and_finish(self):
        reg = MetricsRegistry()
        ph = RoundPhases()
        with ph.measure("poll"):
            pass
        ph.add("host_wait", 0.25)
        ph.add("host_wait", 0.25)
        out = ph.finish(reg)
        assert sorted(out) == sorted(PHASES)
        assert out["host_wait"] == 0.5
        snap = phase_seconds_snapshot(reg)
        assert set(snap) == set(PHASES)  # every phase observed once
        for p in PHASES:
            assert snap[p]["count"] == 1

    def test_realtime_rounds_emit_all_phases_exactly_once(self, tmp_path):
        src = str(tmp_path / "src")
        out = str(tmp_path / "out")
        _feed_files(src, 0, 2)
        rounds = 3
        reg = MetricsRegistry()
        with use_registry(reg):
            n = _run_stream(
                src, out, rounds=rounds,
                feed=lambda r: _feed_files(src, 1 + r, 1),
            )
        assert n == rounds
        # registry: every phase observed exactly once per round
        snap = phase_seconds_snapshot(reg)
        assert set(snap) == set(PHASES)
        for p in PHASES:
            assert snap[p]["count"] == rounds
        # flight: each round record carries the full phase dict
        recs = read_flight(out, kind="round")
        assert [r["round"] for r in recs] == list(range(1, rounds + 1))
        for r in recs:
            assert sorted(r["phases"]) == sorted(PHASES)
            # the former "compute" phase is now split (ISSUE 17):
            # device_execute + host_wait together carry the round's
            # processing residual
            assert (r["phases"]["device_execute"]
                    + r["phases"]["host_wait"]) > 0.0
        # a round's spans precede it durably (the drill's replay claim)
        spans = read_flight(out, kind="span", name="stream.round")
        assert {s["round"] for s in spans} == {1, 2, 3}


# ---------------------------------------------------------------------------


class TestSLO:
    def _ring(self, folder, lags, target_now=None):
        from tpudas.obs.health import write_health

        rec = FlightRecorder(folder)
        for i, lag in enumerate(lags):
            rec.record("round", round=i + 1, head_lag=lag, phases={})
        rec.flush()
        rec.close()
        if target_now is not None:
            write_health(str(folder), {
                "rounds": len(lags), "polls": len(lags),
                "mode": "stateful", "realtime_factor": 10.0,
                "round_realtime_factor": 10.0,
                "head_lag_seconds": target_now, "redundant_ratio": 0.0,
                "carry_resume_count": 0,
                "last_round_wall_seconds": 0.1,
                "consecutive_failures": 0, "quarantined_files": 0,
                "degraded": False, "integrity_fallbacks": 0,
                "resource_degraded": False, "last_error": None,
            })

    def test_ok_vs_violating_vs_burn(self, tmp_path):
        pol = SLOPolicy(head_lag_target_s=100.0, objective=0.9,
                        window=50)
        a = tmp_path / "a"
        a.mkdir()
        self._ring(a, [10.0] * 20, target_now=10.0)
        assert slo_status(a, pol)["status"] == "ok"
        b = tmp_path / "b"
        b.mkdir()
        self._ring(b, [10.0] * 20, target_now=500.0)
        assert slo_status(b, pol)["status"] == "violating"
        # burn: 20% of rounds over target >> 10% budget, current ok
        c = tmp_path / "c"
        c.mkdir()
        self._ring(c, [10.0] * 16 + [500.0] * 4, target_now=10.0)
        s = slo_status(c, pol)
        assert s["status"] == "at_risk"
        assert s["error_budget_burn"] == pytest.approx(2.0)
        d = tmp_path / "d"
        d.mkdir()
        assert slo_status(d, pol)["status"] == "unknown"


# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_root(tmp_path_factory):
    """A real 4-stream fleet run (tiny): health + flight per stream."""
    from tpudas.fleet import FleetEngine, StreamConfig, StreamSpec

    root = str(tmp_path_factory.mktemp("obs_fleet") / "root")
    src_root = str(tmp_path_factory.mktemp("obs_fleet_src"))
    config = StreamConfig(
        kind="lowpass", start_time=T0, output_sample_interval=1.0,
        edge_buffer=5.0, process_patch_size=20, poll_interval=0.0,
        health=True, pyramid=False, detect=False,
    )
    specs = []
    for i in range(4):
        src = os.path.join(src_root, f"s{i:02d}")
        _feed_files(src, 0, 2)
        specs.append(StreamSpec(
            stream_id=f"s{i:02d}", source=src, config=config,
        ))
    summary = FleetEngine(
        root, specs, max_rounds=3, sleep_fn=lambda _s: None,
    ).run()
    assert summary["rounds_total"] >= 4
    return root


@pytest.fixture(scope="module")
def backfill_root(tmp_path_factory):
    """A tiny 2-worker backfill run over a 2-shard plan."""
    from tpudas.backfill import plan_backfill, run_worker

    src = str(tmp_path_factory.mktemp("obs_bf") / "src")
    root = str(tmp_path_factory.mktemp("obs_bf") / "root")
    make_synthetic_spool(
        src, n_files=4, file_duration=FILE_SEC, fs=FS, n_ch=N_CH,
        noise=0.01, start=np.datetime64(T0),
    )
    t_end = np.datetime64(T0) + np.timedelta64(
        int(4 * FILE_SEC * 1e9), "ns"
    )
    plan_backfill(
        root, src, T0, t_end, shard_seconds=60.0,
        output_sample_interval=1.0, edge_buffer=5.0,
        process_patch_size=20, pyramid=False, detect=False,
    )
    tallies = [
        run_worker(root, worker=f"w{i}", settle=0.0, max_wall=300)
        for i in range(2)
    ]
    assert any(t["stitched"] for t in tallies)
    return root


class TestRollup:
    @pytest.mark.slow
    def test_fleet_rollup_over_4_stream_run(self, fleet_root):
        roll = fleet_rollup(fleet_root)
        assert sorted(roll["streams"]) == [f"s{i:02d}" for i in range(4)]
        assert roll["status"] == "ok"
        for entry in roll["streams"].values():
            assert entry["status"] == "ok"
            assert entry["rounds"] >= 1
            assert entry["realtime_factor"] > 0
            assert entry["slo"]["status"] == "ok"
            assert entry["flight"]["last_round"] >= 1
            assert sorted(entry["flight"]["phases"]) == sorted(PHASES)

    @pytest.mark.slow
    def test_backfill_rollup_after_2_worker_run(self, backfill_root):
        roll = backfill_rollup(backfill_root)
        assert roll["status"] == "done"
        assert roll["result_done"]
        assert roll["shards"]["done"] == roll["shards_total"] == 2
        assert roll["done_fraction"] == 1.0
        assert roll["parked"] == []

    def test_backfill_rollup_unreadable_root(self, tmp_path):
        roll = backfill_rollup(str(tmp_path / "nope"))
        assert roll["status"] == "unreadable"

    def test_cluster_snapshot_combines_planes(self, fleet_root,
                                              backfill_root):
        snap = cluster_snapshot(
            fleet_root=fleet_root, backfill_root=backfill_root,
        )
        assert snap["status"] == "ok"
        assert len(snap["fleet"]["streams"]) == 4
        assert snap["backfill"]["status"] == "done"
        # pool: unreachable is a status, not an exception
        snap2 = cluster_snapshot(
            fleet_root=fleet_root,
            pool_url="http://127.0.0.1:1/nope",
        )
        assert snap2["pool"]["status"] == "unreachable"
        assert snap2["status"] != "ok"

    def test_obs_report_cli(self, fleet_root, backfill_root, tmp_path,
                            capsys):
        import obs_report

        out = str(tmp_path / "report.json")
        rc = obs_report.main([
            "--fleet", fleet_root, "--backfill", backfill_root,
            "--out", out, "--strict",
        ])
        assert rc == 0
        text = capsys.readouterr().out
        assert "cluster status: ok" in text
        assert "s00" in text and "backfill: done" in text
        with open(out) as fh:
            snap = json.load(fh)
        assert len(snap["fleet"]["streams"]) == 4

    def test_obs_report_cli_json_single_stream(self, fleet_root,
                                               capsys):
        import obs_report

        stream = os.path.join(fleet_root, "s00")
        # --strict must pass on a healthy single stream: the overall
        # status is recomputed from the merged entry, not left at the
        # empty snapshot's "unknown" placeholder
        rc = obs_report.main(["--stream", stream, "--json", "--strict"])
        assert rc == 0
        snap = json.loads(capsys.readouterr().out)
        assert "s00" in snap["fleet"]["streams"]
        assert snap["status"] == "ok"
        assert snap["fleet"]["counts"] == {"ok": 1}
        assert snap["fleet"]["slo_counts"] == {"ok": 1}


# ---------------------------------------------------------------------------


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read().decode())


class TestServeEndpoints:
    def test_trace_slo_and_fleet_healthz(self, fleet_root):
        from tpudas.serve.http import DASServer

        with DASServer.for_fleet(fleet_root) as server:
            base = server.base_url
            # /trace over one stream's flight ring
            tr = _get_json(f"{base}/s/s00/trace?limit=50")
            assert tr["source"] == "flight" and tr["count"] >= 1
            assert all(r["kind"] == "span" for r in tr["records"])
            rounds = _get_json(f"{base}/s/s00/trace?kind=round")
            assert rounds["records"][-1]["phases"]
            named = _get_json(
                f"{base}/s/s00/trace?name=stream.round&limit=5"
            )
            assert all(
                r["name"] == "stream.round" for r in named["records"]
            )
            # /slo: per-stream and aggregate
            slo = _get_json(f"{base}/s/s01/slo")
            assert slo["status"] == "ok"
            agg = _get_json(f"{base}/slo?target=150")
            assert set(agg["streams"]) == {
                f"s{i:02d}" for i in range(4)
            }
            # /fleet/healthz now carries slo + freshness per stream
            fh = _get_json(f"{base}/fleet/healthz")
            assert fh["status"] == "ok"
            for entry in fh["streams"].values():
                assert entry["slo"]["status"] == "ok"
                assert entry["realtime_factor"] > 0
                assert "head_lag_seconds" in entry
            assert fh["slo_counts"] == {"ok": 4}
            # unknown stream still 404s
            with pytest.raises(urllib.error.HTTPError) as err:
                _get_json(f"{base}/s/zz/trace")
            assert err.value.code == 404

    def test_trace_ring_fallback_without_flight(self, tmp_path):
        from tpudas.obs.trace import clear_spans
        from tpudas.serve.http import DASServer

        folder = str(tmp_path / "plain")
        os.makedirs(folder)
        clear_spans()
        with span("ring.only", tag=1):
            pass
        with DASServer(folder) as server:
            tr = _get_json(f"{server.base_url}/trace?name=ring.only")
            assert tr["source"] == "ring"
            assert tr["count"] == 1

    def test_fleet_park_event_timestamps(self, tmp_path):
        """A parked stream's health carries the park event with
        wall-clock timestamps, and the rollup surfaces it."""
        from tpudas.fleet import FleetEngine, StreamConfig, StreamSpec

        root = str(tmp_path / "root")
        src = str(tmp_path / "src")
        _feed_files(src, 0, 2)
        good = StreamConfig(
            kind="lowpass", start_time=T0, output_sample_interval=1.0,
            edge_buffer=5.0, process_patch_size=20, poll_interval=0.0,
            health=True, pyramid=False, detect=False,
        )
        # "bad" listed first: the deficit round-robin serves spec
        # order on the all-equal first pass, so the site's FIRST
        # round.body hit (the injected fatal) lands on it
        specs = [
            StreamSpec(stream_id="bad", source=src, config=good),
            StreamSpec(stream_id="good", source=src, config=good),
        ]
        plan = FaultPlan(FaultSpec(
            "round.body", exc=ValueError("fatal config"), at=1,
        ))
        import time as _t

        t_before = _t.time()
        with install_fault_plan(plan):
            summary = FleetEngine(
                root, specs, max_rounds=2, sleep_fn=lambda _s: None,
            ).run()
        assert summary["streams"]["bad"]["status"] == "parked"
        assert summary["streams"]["bad"]["parked_at"] >= t_before
        roll = fleet_rollup(root)
        ev = roll["streams"]["bad"].get("fleet")
        assert ev is not None and ev["event"] == "parked"
        assert ev["parked_at"] >= t_before and ev["unparked_at"] is None
