"""ISSUE 10: the fused O(1) streaming cascade kernel.

Pins the fused-engine contracts:
- ops level: the fused-xla scan is BYTE-IDENTICAL to the per-stage
  reference cascade — outputs and every carry leaf — across uneven
  block schedules, and the fused-pallas v3 kernel (interpret mode on
  CPU) matches within the pinned tolerance with a NaN set no wider
  than the reference's;
- the carry layout is shared, so a stream crosses cascade <-> fused
  mid-run (ops level and full-driver level, both directions) with no
  seam and byte-identity against a single-engine control;
- serialized carry: save/load round-trips the fused stream's carry
  bit-exactly and resumes seam-free;
- mesh: the fused step under a 4-device CPU channel mesh is
  byte-identical to the single-device fused step (and therefore to
  the reference cascade);
- the stale-knob fix: TPUDAS_FUSED_* / TPUDAS_PALLAS_* /
  TPUDAS_STREAM_PALLAS changes apply mid-process with no cache clear
  (every dispatch cache keys on tpudas.ops.fir.knob_fingerprint).
"""

import os

import numpy as np
import pytest

from tpudas.ops.fir import (
    cascade_decimate_stream,
    cascade_stream_init,
    design_cascade,
    fused_chunk_outputs,
    fused_intermediate_bytes,
    knob_fingerprint,
    resolve_stream_engine,
    stream_carry_sizes,
)

# the fused-pallas v3 kernel runs exact-f32 VPU arithmetic but groups
# the per-tap sums by shifted frames, so it is tolerance-pinned (the
# fused-XLA scan is byte-identical and asserted as such); measured
# interpret-mode worst case 2.3e-7 relative (PERF.md §11)
PALLAS_RTOL = 5e-7

PLANS = [(100.0, 100), (200.0, 40), (50.0, 7)]


def _run_stream(plan, blocks, engine, n_ch, mesh=None):
    carry = cascade_stream_init(plan, n_ch)
    outs = []
    for b in blocks:
        y, carry = cascade_decimate_stream(b, carry, plan, engine,
                                           mesh=mesh)
        outs.append(np.asarray(y))
    from tpudas.parallel.sharding import gather_leaves

    return np.concatenate(outs), gather_leaves(carry, n_ch)


def _blocks(plan, seed=0, n_ch=5, nan_gap=False):
    rng = np.random.default_rng(seed)
    blocks = [
        rng.standard_normal((n * plan.ratio, n_ch)).astype(np.float32)
        for n in (50, 13, 1, 27, 40)
    ]
    if nan_gap:
        # gap-fill style NaN runs, one spanning a block seam
        blocks[1][plan.ratio : 2 * plan.ratio, 2] = np.nan
        blocks[3][-plan.ratio // 2 :, 0] = np.nan
        blocks[4][: plan.ratio // 2, 0] = np.nan
    return blocks


class TestFusedOps:
    @pytest.mark.parametrize("fs,ratio", PLANS)
    @pytest.mark.parametrize("nan_gap", [False, True])
    @pytest.mark.slow
    def test_fused_xla_byte_identical(self, fs, ratio, nan_gap):
        """The fused scan replays the per-stage arithmetic chunk by
        chunk: outputs AND every carry leaf byte-identical to the
        reference cascade, NaN-gap blocks included."""
        plan = design_cascade(fs, ratio, 0.45 * fs / ratio, 4)
        blocks = _blocks(plan, nan_gap=nan_gap)
        y0, c0 = _run_stream(plan, blocks, "xla", 5)
        y1, c1 = _run_stream(plan, blocks, "fused-xla", 5)
        np.testing.assert_array_equal(y0, y1)
        for a, b in zip(c0, c1):
            np.testing.assert_array_equal(a, b)

    @pytest.mark.parametrize("fs,ratio", PLANS)
    @pytest.mark.slow
    def test_fused_pallas_pinned_tolerance(self, fs, ratio):
        """The v3 kernel (interpret mode on CPU = exact f32 dots)
        matches the reference within PALLAS_RTOL, outputs and carry —
        the recorded tolerance of PERF.md §11."""
        plan = design_cascade(fs, ratio, 0.45 * fs / ratio, 4)
        blocks = _blocks(plan)
        y0, c0 = _run_stream(plan, blocks, "xla", 5)
        y2, c2 = _run_stream(plan, blocks, "fused-pallas", 5)
        scale = np.abs(y0).max()
        assert np.abs(y0 - y2).max() / scale < PALLAS_RTOL
        for a, b in zip(c0, c2):
            if a.size:
                s = max(np.abs(a).max(), scale)
                assert np.abs(a - b).max() / s < PALLAS_RTOL

    def test_fused_pallas_nan_subset(self):
        """NaN-gap blocks through the v3 kernel: the NaN set is a
        SUBSET of the reference's (the kernel's tap window is exactly
        the receptive field — the polyphase formulation additionally
        smears NaN through its zero-padded tap slack) and all
        mutually-finite samples agree within tolerance."""
        plan = design_cascade(100.0, 100, 0.45, 4)
        blocks = _blocks(plan, seed=2, nan_gap=True)
        y0, _ = _run_stream(plan, blocks, "xla", 5)
        y2, _ = _run_stream(plan, blocks, "fused-pallas", 5)
        n0, n2 = np.isnan(y0), np.isnan(y2)
        assert n0.any()  # the gap actually produced NaNs
        assert np.all(~n2 | n0), "kernel smeared NaN wider than the ref"
        both = ~n0 & ~n2
        scale = np.nanmax(np.abs(y0))
        assert np.abs(y0[both] - y2[both]).max() / scale < PALLAS_RTOL

    def test_ops_level_crossover_mid_stream(self):
        """The carry tuple moves between engines freely: alternating
        per-stage / fused steps equals the pure reference run
        byte-for-byte."""
        plan = design_cascade(100.0, 100, 0.45, 4)
        blocks = _blocks(plan, seed=3)
        y0, c0 = _run_stream(plan, blocks, "xla", 5)
        engines = ["xla", "fused-xla", "fused-xla", "xla", "fused-xla"]
        carry = cascade_stream_init(plan, 5)
        outs = []
        for b, eng in zip(blocks, engines):
            y, carry = cascade_decimate_stream(b, carry, plan, eng)
            outs.append(np.asarray(y))
        np.testing.assert_array_equal(y0, np.concatenate(outs))
        for a, b in zip(c0, carry):
            np.testing.assert_array_equal(a, np.asarray(b))

    def test_resolver_threshold_and_literals(self, monkeypatch):
        plan = design_cascade(100.0, 100, 0.45, 4)
        with pytest.raises(ValueError, match="stream engine"):
            resolve_stream_engine("warp", plan, 100, 4)
        monkeypatch.setenv("TPUDAS_FUSED_MIN_ELEMS", "1000000")
        # below threshold: a "fused" request degrades to the chain
        assert resolve_stream_engine("fused", plan, 100, 4) == "xla"
        # explicit variants are forced regardless of size
        assert (
            resolve_stream_engine("fused-xla", plan, 100, 4)
            == "fused-xla"
        )
        monkeypatch.setenv("TPUDAS_FUSED_MIN_ELEMS", "1")
        assert resolve_stream_engine("fused", plan, 100, 4) == "fused-xla"

    def test_chunking_divides_blocks(self, monkeypatch):
        plan = design_cascade(1000.0, 1000, 0.45, 4)
        assert fused_chunk_outputs(plan, 20) in (4, 5, 8, 10, 20)
        for n_out in (1, 7, 20, 64, 40):
            c = fused_chunk_outputs(plan, n_out)
            assert n_out % c == 0
        monkeypatch.setenv("TPUDAS_FUSED_CHUNK", "4")
        assert fused_chunk_outputs(plan, 20) == 4

    def test_intermediate_bytes_proxy(self):
        plan = design_cascade(1000.0, 1000, 0.45, 4)  # R = 8,5,5,5
        T, C = 8000, 10
        # stage outputs at 1000, 200, 40 rows are the intermediates
        assert fused_intermediate_bytes(plan, T, C) == (
            (1000 + 200 + 40) * C * 4
        )


class TestKnobFingerprint:
    """The stale-knob fix: env changes take effect mid-process."""

    def test_fingerprint_tracks_env(self, monkeypatch):
        monkeypatch.delenv("TPUDAS_FUSED_CHUNK", raising=False)
        a = knob_fingerprint()
        monkeypatch.setenv("TPUDAS_FUSED_CHUNK", "16")
        b = knob_fingerprint()
        assert a != b

    def test_stream_pallas_selector_applies_live(self, monkeypatch):
        """TPUDAS_STREAM_PALLAS flips the per-stage kernel routing
        with NO cache clear or restart — the mid-process-change
        footgun the knob fingerprint closes."""
        from tpudas.ops.fir import stream_stage_engines

        plan = design_cascade(100.0, 100, 0.45, 4)
        monkeypatch.setenv("TPUDAS_STREAM_PALLAS", "1")
        monkeypatch.setenv("TPUDAS_PALLAS_MIN_ELEMS", "1")
        # small taps stages fit the kernel sub-block -> pallas routed
        eng_on = stream_stage_engines(plan, 100 * 128, 4, "pallas")
        assert "pallas" in eng_on
        monkeypatch.setenv("TPUDAS_STREAM_PALLAS", "0")
        eng_off = stream_stage_engines(plan, 100 * 128, 4, "pallas")
        assert "pallas" not in eng_off

    def test_fused_threshold_applies_live_through_dispatch(
        self, monkeypatch
    ):
        """A retuned TPUDAS_FUSED_MIN_ELEMS changes what an engine
        "fused" DISPATCH actually runs, mid-process: the compiled-fn
        caches key on the fingerprint, so no stale executable is
        reused."""
        from tpudas.obs.registry import MetricsRegistry, use_registry

        plan = design_cascade(100.0, 100, 0.45, 4)
        blocks = _blocks(plan, seed=4, n_ch=3)[:1]
        reg = MetricsRegistry()
        with use_registry(reg):
            monkeypatch.setenv("TPUDAS_FUSED_MIN_ELEMS", str(1 << 40))
            _run_stream(plan, blocks, "fused", 3)
            assert reg.value(
                "tpudas_fir_fused_rounds_total", engine="fused-xla"
            ) == 0.0
            monkeypatch.setenv("TPUDAS_FUSED_MIN_ELEMS", "1")
            _run_stream(plan, blocks, "fused", 3)
            assert reg.value(
                "tpudas_fir_fused_rounds_total", engine="fused-xla"
            ) == 1.0
        # and the bytes-saved proxy counted the eliminated traffic
        assert reg.value(
            "tpudas_fir_fused_intermediate_bytes_saved_total"
        ) == fused_intermediate_bytes(plan, blocks[0].shape[0], 3)

    def test_pallas_geometry_reads_call_time(self, monkeypatch):
        from tpudas.ops.pallas_fir import (
            channel_block,
            kernel_quantum,
            pallas_p,
        )

        monkeypatch.delenv("TPUDAS_PALLAS_P", raising=False)
        monkeypatch.delenv("TPUDAS_PALLAS_CB", raising=False)
        assert pallas_p() == 4
        assert kernel_quantum() == 512
        assert channel_block() == 128
        monkeypatch.setenv("TPUDAS_PALLAS_P", "2")
        monkeypatch.setenv("TPUDAS_PALLAS_CB", "256")
        assert pallas_p() == 2
        assert kernel_quantum() == 256
        assert channel_block() == 256


@pytest.mark.usefixtures("cpu_mesh4")
class TestFusedMesh:
    @pytest.mark.slow
    def test_mesh_fused_byte_identical(self, cpu_mesh4):
        """4-device CPU-mesh equivalence: the fused step under a
        channel mesh == single-device fused == reference cascade,
        byte-identically, with the returned carry leaves sharded
        device arrays fed back verbatim."""
        plan = design_cascade(100.0, 100, 0.45, 4)
        blocks = _blocks(plan, seed=5, n_ch=6, nan_gap=True)
        y0, c0 = _run_stream(plan, blocks, "xla", 6)
        y1, c1 = _run_stream(plan, blocks, "fused-xla", 6,
                             mesh=cpu_mesh4)
        np.testing.assert_array_equal(y0, y1)
        for a, b in zip(c0, c1):
            np.testing.assert_array_equal(a, b)

    def test_mesh_fused_carry_stays_device_resident(self, cpu_mesh4):
        from tpudas.parallel.sharding import is_device_resident

        plan = design_cascade(100.0, 100, 0.45, 4)
        carry = cascade_stream_init(plan, 6)
        x = np.zeros((20 * plan.ratio, 6), np.float32)
        _y, carry = cascade_decimate_stream(
            x, carry, plan, "fused-xla", mesh=cpu_mesh4
        )
        assert all(is_device_resident(b) for b in carry)
        # feed the sharded leaves back verbatim: no re-placement
        _y, carry = cascade_decimate_stream(
            x, carry, plan, "fused-xla", mesh=cpu_mesh4
        )
        assert all(is_device_resident(b) for b in carry)


FS = 100.0
FILE_SEC = 30.0
NCH = 6
T0 = np.datetime64("2023-03-22T00:00:00")


def _append_files(directory, start_index, count):
    from tpudas.io.registry import write_patch
    from tpudas.testing import synthetic_patch

    t0 = T0.astype("datetime64[ns]")
    step = np.timedelta64(int(round(1e9 / FS)), "ns")
    n = int(FILE_SEC * FS)
    for i in range(start_index, start_index + count):
        p = synthetic_patch(
            t0=t0 + i * n * step, duration=FILE_SEC, fs=FS, n_ch=NCH,
            seed=i, phase_origin=t0, noise=0.01,
        )
        write_patch(p, os.path.join(directory, f"raw_{i:04d}.h5"))


def _drive(src, out, engine):
    from tpudas.proc.streaming import run_lowpass_realtime

    return run_lowpass_realtime(
        source=src,
        output_folder=out,
        start_time=str(T0),
        output_sample_interval=1.0,
        edge_buffer=8.0,
        process_patch_size=40,
        poll_interval=0.0,
        file_duration=0.0,
        sleep_fn=lambda _: None,
        stateful=True,
        engine=engine,
    )


@pytest.fixture()
def fused_env(monkeypatch):
    """The realtime tests run tiny streams — clear the fused size
    threshold so engine='fused' really exercises the fused path."""
    monkeypatch.setenv("TPUDAS_FUSED_MIN_ELEMS", "0")


class TestFusedRealtime:
    @pytest.fixture()
    def source(self, tmp_path):
        from tpudas.testing import make_synthetic_spool

        src = str(tmp_path / "src")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        return src

    def _merged(self, out):
        from tpudas.io.spool import spool

        merged = spool(out).update().chunk(time=None)
        assert len(merged) == 1, "stream has a seam"
        return (
            merged[0].host_data(),
            np.asarray(merged[0].coords["time"]),
        )

    @pytest.mark.slow
    def test_driver_fused_matches_cascade(self, source, tmp_path,
                                          fused_env):
        """Full realtime driver under engine='fused': outputs
        byte-identical to engine='cascade' over the same feed, and
        the fused path really ran (fused rounds counted)."""
        from tpudas.obs.registry import MetricsRegistry, use_registry

        outs = {}
        reg = MetricsRegistry()
        for eng in ("cascade", "fused"):
            out = str(tmp_path / eng)
            if eng == "fused":
                with use_registry(reg):
                    assert _drive(source, out, eng) == 1
            else:
                assert _drive(source, out, eng) == 1
            outs[eng] = self._merged(out)
        np.testing.assert_array_equal(outs["cascade"][0],
                                      outs["fused"][0])
        np.testing.assert_array_equal(outs["cascade"][1],
                                      outs["fused"][1])
        assert reg.value(
            "tpudas_fir_fused_rounds_total", engine="fused-xla"
        ) > 0

    def test_serialized_carry_roundtrip_and_resume(self, source,
                                                   tmp_path, fused_env):
        """Kill/resume on the fused engine: the persisted carry
        round-trips bit-exactly and a fresh process resumes seam-free,
        byte-identical to an uninterrupted cascade control."""
        from tpudas.proc.stream import load_carry

        out = str(tmp_path / "fused")
        assert _drive(source, out, "fused") == 1
        c = load_carry(out)
        assert c is not None and c.engine_req == "fused"
        assert c.kind == "cascade"
        # round-trip: the serialized leaves reload bit-exactly
        c2 = load_carry(out)
        for a, b in zip(c.bufs, c2.bufs):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        plan_sizes = stream_carry_sizes(
            design_cascade(FS, 100, 0.45, 4)
        )
        assert tuple(int(np.shape(b)[0]) for b in c.bufs) == plan_sizes
        # two more files arrive while "down"; a fresh driver resumes
        _append_files(source, 3, 2)
        assert _drive(source, out, "fused") == 1
        got = self._merged(out)
        ctrl = str(tmp_path / "ctrl")
        assert _drive(source, ctrl, "cascade") == 1
        want = self._merged(ctrl)
        np.testing.assert_array_equal(got[0], want[0])
        np.testing.assert_array_equal(got[1], want[1])

    @pytest.mark.parametrize("first,second", [("cascade", "fused"),
                                              ("fused", "cascade")])
    @pytest.mark.slow
    def test_driver_crossover_both_directions(self, source, tmp_path,
                                              first, second, fused_env):
        """Resume a cascade carry under fused and vice versa: the
        shared carry layout makes the crossover seam-free and
        byte-identical to a single-engine control."""
        out = str(tmp_path / "xover")
        assert _drive(source, out, first) == 1
        _append_files(source, 3, 2)
        assert _drive(source, out, second) == 1
        got = self._merged(out)
        ctrl = str(tmp_path / "ctrl")
        assert _drive(source, ctrl, "cascade") == 1
        want = self._merged(ctrl)
        np.testing.assert_array_equal(got[0], want[0])
        np.testing.assert_array_equal(got[1], want[1])

    def test_fft_carry_cannot_resume_under_fused(self, tmp_path,
                                                 fused_env):
        """An FFT-kind carry (auto on a non-aligned grid) must reject
        a fused resume instead of silently reinterpreting state."""
        from tpudas.testing import make_synthetic_spool

        src = str(tmp_path / "src")
        make_synthetic_spool(
            src, n_files=2, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        out = str(tmp_path / "out")

        def drive(engine):
            from tpudas.proc.streaming import run_lowpass_realtime

            return run_lowpass_realtime(
                source=src, output_folder=out, start_time=str(T0),
                output_sample_interval=1.1,  # ratio 110 = 2*5*11: fft
                edge_buffer=8.0, process_patch_size=40,
                poll_interval=0.0, file_duration=0.0,
                sleep_fn=lambda _: None, stateful=True, engine=engine,
            )

        assert drive("auto") == 1
        from tpudas.proc.stream import load_carry

        assert load_carry(out).kind == "fft"
        _append_files(src, 2, 1)
        with pytest.raises(ValueError, match="start_time or processing"):
            drive("fused")
