"""tpudas.serve: tile pyramid, query engine, HTTP server.

Covers the ISSUE 4 acceptance set: query edge cases (empty window, gap
window, pyramid/full-res straddle), single-flight coalescing of
concurrent identical loads, restart-resumes-pyramid byte-identity,
deterministic 503 load shed via the ``serve.queue_full`` fault site,
and the end-to-end demo — realtime rounds with the pyramid enabled,
then HTTP ``/query`` / ``/waterfall`` payloads byte-identical to an
offline recomputation from the raw output files.
"""

import glob
import io
import json
import os
import shutil
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tpudas.core.timeutils import to_datetime64
from tpudas.io.index import DirectoryIndex, INDEX_FILENAME
from tpudas.io.registry import write_patch
from tpudas.obs.health import read_health
from tpudas.obs.registry import MetricsRegistry, use_registry
from tpudas.proc.streaming import run_lowpass_realtime
from tpudas.serve.http import start_server
from tpudas.serve.query import QueryEngine
from tpudas.serve.tiles import TileStore, block_reduce, sync_pyramid
from tpudas.testing import (
    FaultPlan,
    FaultSpec,
    install_fault_plan,
    make_synthetic_spool,
    synthetic_patch,
)

FS = 100.0
FILE_SEC = 30.0
NCH = 6
T0 = "2023-03-22T00:00:00"


def _append_files(directory, start_index, count):
    t0 = to_datetime64(T0).astype("datetime64[ns]")
    step = np.timedelta64(int(round(1e9 / FS)), "ns")
    n = int(FILE_SEC * FS)
    for i in range(start_index, start_index + count):
        p = synthetic_patch(
            t0=t0 + i * n * step, duration=FILE_SEC, fs=FS, n_ch=NCH,
            seed=i, phase_origin=t0, noise=0.01,
        )
        write_patch(p, os.path.join(directory, f"raw_{i:04d}.h5"))


def _run_stream(src, out, feed_batches=(), **kwargs):
    """Drive the realtime low-pass driver; ``feed_batches`` is a list
    of (start_index, count) appended one batch per sleep."""
    state = {"i": 0}

    def fake_sleep(_):
        if state["i"] < len(feed_batches):
            _append_files(src, *feed_batches[state["i"]])
            state["i"] += 1

    return run_lowpass_realtime(
        source=src,
        output_folder=out,
        start_time=T0,
        output_sample_interval=1.0,
        edge_buffer=8.0,
        process_patch_size=40,
        poll_interval=0.0,
        file_duration=0.0,
        sleep_fn=fake_sleep,
        **kwargs,
    )


@pytest.fixture
def streamed(tmp_path):
    """3 + 2 files streamed in two rounds with the pyramid enabled."""
    src = str(tmp_path / "raw")
    out = str(tmp_path / "results")
    make_synthetic_spool(
        src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH, noise=0.01
    )
    rounds = _run_stream(src, out, feed_batches=[(3, 2)], pyramid=True)
    assert rounds == 2
    return src, out


def _pyramid_arrays(folder):
    """{(level, agg): contiguous array} over the whole pyramid."""
    store = TileStore.open(folder)
    assert store is not None
    out = {}
    for lvl in range(store.n_levels):
        for agg in ("mean", "min", "max"):
            out[(lvl, agg)] = store.read(lvl, 0, store.n(lvl), agg=agg)
    return out


class TestTileStore:
    def test_append_cascade_and_read(self, tmp_path):
        store = TileStore.create(
            str(tmp_path), factor=4, tile_len=8
        )
        t0 = to_datetime64(T0).astype("datetime64[ns]")
        step = np.timedelta64(1, "s")
        times = t0 + np.arange(64) * step
        rng = np.random.default_rng(0)
        data = rng.standard_normal((64, 3)).astype(np.float32)
        store.append(times, data)
        assert store.levels == [64, 16, 4, 1]
        # level 0 is the data itself
        np.testing.assert_array_equal(store.read(0, 0, 64), data)
        # level-1 aggregates match direct groupwise reductions
        g = data.reshape(16, 4, 3).astype(np.float64)
        np.testing.assert_allclose(
            store.read(1, 0, 16, agg="mean"),
            g.mean(axis=1).astype(np.float32), rtol=0, atol=0,
        )
        np.testing.assert_array_equal(
            store.read(1, 0, 16, agg="min"),
            g.min(axis=1).astype(np.float32),
        )
        np.testing.assert_array_equal(
            store.read(1, 0, 16, agg="max"),
            g.max(axis=1).astype(np.float32),
        )

    def test_incremental_equals_oneshot(self, tmp_path):
        """Chunked appends produce the same pyramid as one big append
        (the cascade only ever reduces complete groups)."""
        t0 = to_datetime64(T0).astype("datetime64[ns]")
        step = np.timedelta64(1, "s")
        rng = np.random.default_rng(1)
        data = rng.standard_normal((100, 3)).astype(np.float32)
        times = t0 + np.arange(100) * step

        a = TileStore.create(str(tmp_path / "a"), factor=4, tile_len=8)
        pos = 0
        for chunk in (7, 13, 1, 29, 50):
            a.append(times[pos : pos + chunk], data[pos : pos + chunk])
            pos += chunk
        b = TileStore.create(str(tmp_path / "b"), factor=4, tile_len=8)
        b.append(times, data)
        assert a.levels == b.levels
        for lvl in range(len(a.levels)):
            for agg in ("mean", "min", "max"):
                assert (
                    a.read(lvl, 0, a.n(lvl), agg=agg).tobytes()
                    == b.read(lvl, 0, b.n(lvl), agg=agg).tobytes()
                )

    def test_gap_becomes_nan_and_propagates(self, tmp_path):
        store = TileStore.create(str(tmp_path), factor=4, tile_len=8)
        t0 = to_datetime64(T0).astype("datetime64[ns]")
        step = np.timedelta64(1, "s")
        ones = np.ones((8, 2), np.float32)
        store.append(t0 + np.arange(8) * step, ones)
        # 4-sample hole, then 8 more rows
        store.append(t0 + (12 + np.arange(8)) * step, ones)
        assert store.levels[0] == 20
        lvl0 = store.read(0, 0, 20)
        assert np.isnan(lvl0[8:12]).all() and np.isfinite(lvl0[:8]).all()
        # the hole's level-1 group is NaN, neighbours are finite
        lvl1 = store.read(1, 0, store.n(1), agg="mean")
        assert np.isnan(lvl1[2]).all()
        assert np.isfinite(lvl1[:2]).all()

    def test_off_grid_append_raises(self, tmp_path):
        store = TileStore.create(str(tmp_path))
        t0 = to_datetime64(T0).astype("datetime64[ns]")
        step = np.timedelta64(1, "s")
        store.append(t0 + np.arange(4) * step, np.ones((4, 2), np.float32))
        with pytest.raises(ValueError, match="grid"):
            store.append(
                t0 + np.arange(4) * step + np.timedelta64(137, "ms"),
                np.ones((4, 2), np.float32),
            )

    def test_manifest_torn_read_falls_back_to_prev(self, tmp_path):
        store = TileStore.create(str(tmp_path), factor=4, tile_len=8)
        t0 = to_datetime64(T0).astype("datetime64[ns]")
        step = np.timedelta64(1, "s")
        ones = np.ones((8, 2), np.float32)
        store.append(t0 + np.arange(8) * step, ones)
        store.append(t0 + (8 + np.arange(8)) * step, ones)
        # two manifest saves -> .prev exists; tear the primary
        with open(store.manifest_path, "w") as fh:
            fh.write('{"version": 1, "t0_ns": 12')  # torn mid-write
        reopened = TileStore.open(str(tmp_path))
        assert reopened is not None
        # .prev is one save behind at most; here both saves saw 16 rows
        # (append saves once per call, distance save adds another)
        assert reopened.levels[0] in (8, 16)

    def test_crashed_append_surplus_rows_invisible(self, tmp_path):
        """Tail-tile rows beyond the manifest count (a crash between
        tile write and manifest write) are sliced off at read time and
        rewritten byte-identically by the next append."""
        t0 = to_datetime64(T0).astype("datetime64[ns]")
        step = np.timedelta64(1, "s")
        rng = np.random.default_rng(2)
        data = rng.standard_normal((12, 2)).astype(np.float32)
        times = t0 + np.arange(12) * step

        store = TileStore.create(str(tmp_path / "x"), factor=4, tile_len=8)
        store.append(times[:6], data[:6])
        manifest_before = open(store.manifest_path).read()
        # simulate the crashed second append: tiles on disk advanced,
        # manifest did not (we restore it)
        store.append(times[6:], data[6:])
        with open(store.manifest_path, "w") as fh:
            fh.write(manifest_before)

        resumed = TileStore.open(str(tmp_path / "x"))
        assert resumed.levels[0] == 6
        np.testing.assert_array_equal(resumed.read(0, 0, 6), data[:6])
        resumed.append(times[6:], data[6:])
        oracle = TileStore.create(str(tmp_path / "y"), factor=4, tile_len=8)
        oracle.append(times, data)
        for lvl in range(len(oracle.levels)):
            assert (
                resumed.read(lvl, 0, resumed.n(lvl)).tobytes()
                == oracle.read(lvl, 0, oracle.n(lvl)).tobytes()
            )


class TestStreamPyramid:
    def test_restart_resumes_pyramid_byte_identity(self, streamed, tmp_path):
        """Incremental round-by-round appends == one-shot offline
        rebuild from the same output files, across every level and
        aggregate (the manifest-resume discipline)."""
        _, out = streamed
        offline = str(tmp_path / "offline")
        os.makedirs(offline)
        for f in glob.glob(os.path.join(out, "*.h5")):
            shutil.copy(f, offline)
        sync_pyramid(offline)
        live, oracle = _pyramid_arrays(out), _pyramid_arrays(offline)
        assert live.keys() == oracle.keys()
        for key in live:
            assert live[key].tobytes() == oracle[key].tobytes(), key

    def test_pyramid_failure_does_not_kill_stream(self, tmp_path):
        """A fault in the tile read inside the per-round append is
        swallowed (counted), not propagated into the round."""
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
        )
        reg = MetricsRegistry()
        # round 1 backfills purely from the write-through cache (no
        # disk tile reads); round 2's append loads the partial tail
        # tile from disk — that read is the injected failure
        plan = FaultPlan(
            FaultSpec(site="serve.tile_read", action="raise", at=1,
                      times=99)
        )
        with use_registry(reg), install_fault_plan(plan):
            rounds = _run_stream(
                src, out, feed_batches=[(3, 2)], pyramid=True,
                max_rounds=3,
            )
        assert rounds == 2
        assert reg.value("tpudas_serve_pyramid_errors_total") >= 1
        # outputs unharmed
        assert glob.glob(os.path.join(out, "*.h5"))


class TestQueryEngine:
    def test_empty_window(self, streamed):
        _, out = streamed
        eng = QueryEngine(out)
        store = eng.store
        # a window wedged between two grid samples: no sample time
        # falls inside it
        t0 = store.t0_ns + store.step_ns // 4
        t1 = store.t0_ns + store.step_ns // 2
        r = eng.query(
            np.datetime64(t0, "ns"), np.datetime64(t1, "ns")
        )
        assert r.n_samples == 0 and r.source == "empty"
        # entirely beyond the head
        head = store.head_ns
        r = eng.query(
            np.datetime64(head + 10 * store.step_ns, "ns"),
            np.datetime64(head + 20 * store.step_ns, "ns"),
        )
        assert r.n_samples == 0 and r.source == "empty"

    def test_window_spanning_data_gap(self, tmp_path):
        """A hole in the output files shows up as NaN rows, at full
        resolution and at coarse levels."""
        out = str(tmp_path / "gap_out")
        os.makedirs(out)
        t0 = to_datetime64(T0).astype("datetime64[ns]")
        step = np.timedelta64(1, "s")
        for start in (0, 40):  # [0, 20) and [40, 60): hole [20, 40)
            times = t0 + (start + np.arange(20)) * step
            p = synthetic_patch(
                t0=times[0], duration=20.0, fs=1.0, n_ch=NCH, seed=start,
            )
            write_patch(p, os.path.join(out, f"LFDAS_{start:04d}.h5"))
        sync_pyramid(out)
        eng = QueryEngine(out)
        r = eng.query(t0, t0 + 59 * step)
        assert r.n_samples == 60
        assert np.isnan(r.data[20:40]).all()
        assert np.isfinite(r.data[:20]).all()
        r4 = eng.query(t0, t0 + 59 * step, resolution=4.0)
        assert r4.level >= 1
        assert np.isnan(r4.data).any() and np.isfinite(r4.data).any()

    def test_straddle_pyramid_fullres_boundary(self, streamed, tmp_path):
        """A pyramid anchored mid-stream (legacy prefix stays
        full-res-only): a window crossing the anchor is served from
        files + tiles on ONE grid and matches an all-files oracle."""
        _, out = streamed
        full = QueryEngine(out)
        store = full.store
        n0 = store.levels[0]
        anchor_ns = store.t0_ns + (n0 // 2) * store.step_ns
        late = str(tmp_path / "late")
        os.makedirs(late)
        for f in glob.glob(os.path.join(out, "*.h5")):
            shutil.copy(f, late)
        sync_pyramid(late, since=np.datetime64(anchor_ns, "ns"))
        late_store = TileStore.open(late)
        assert late_store.t0_ns == anchor_ns  # anchored mid-stream
        eng = QueryEngine(late)
        lo = np.datetime64(store.t0_ns, "ns")
        hi = np.datetime64(store.head_ns - store.step_ns, "ns")
        r = eng.query(lo, hi)
        assert r.source == "mixed"
        oracle = full.query(lo, hi)
        assert oracle.source == "tiles"
        assert r.n_samples == oracle.n_samples
        np.testing.assert_array_equal(r.times, oracle.times)
        np.testing.assert_array_equal(r.data, oracle.data)

    def test_level_selection(self, streamed):
        _, out = streamed
        eng = QueryEngine(out)
        store = eng.store
        lo = np.datetime64(store.t0_ns, "ns")
        hi = np.datetime64(store.head_ns - store.step_ns, "ns")
        assert eng.query(lo, hi).level == 0
        r = eng.query(lo, hi, resolution=store.step_ns * 4 / 1e9)
        assert r.level == 1
        # max_samples budget: coarsest level fitting the budget
        r = eng.query(lo, hi, max_samples=5)
        assert r.level == store.n_levels - 1 or r.n_samples <= 5 * 4

    def test_concurrent_identical_queries_coalesce(self, streamed):
        """N identical cold window reads share ONE disk tile load:
        the first becomes the single-flight leader (held open by the
        injected delay until every follower has latched on), the rest
        coalesce."""
        _, out = streamed
        reg = MetricsRegistry()
        n_threads = 4

        def hold_leader(_):
            deadline = time.time() + 10.0
            while (
                reg.value("tpudas_serve_singleflight_coalesced_total")
                < n_threads - 1
                and time.time() < deadline
            ):
                time.sleep(0.002)

        plan = FaultPlan(
            FaultSpec(site="serve.tile_read", action="delay", at=1,
                      times=1, seconds=0.0, sleep_fn=hold_leader)
        )
        results, errors = [], []
        with use_registry(reg), install_fault_plan(plan):
            eng = QueryEngine(out)
            store = eng.store
            lo = np.datetime64(store.t0_ns, "ns")
            hi = np.datetime64(
                store.t0_ns + 10 * store.step_ns, "ns"
            )  # well inside one tile

            def worker():
                try:
                    results.append(eng.query(lo, hi).data)
                except Exception as exc:  # surfaced via the errors list
                    errors.append(exc)

            threads = [
                threading.Thread(target=worker) for _ in range(n_threads)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert not errors
            assert reg.value("tpudas_serve_tile_loads_total") == 1
            assert (
                reg.value("tpudas_serve_singleflight_coalesced_total")
                == n_threads - 1
            )
            assert reg.value("tpudas_serve_cache_misses_total") == 1
            for d in results[1:]:
                assert d.tobytes() == results[0].tobytes()
            # warm repeat: pure cache hit, no new loads
            eng.query(lo, hi)
            assert reg.value("tpudas_serve_tile_loads_total") == 1
            assert reg.value("tpudas_serve_cache_hits_total") >= 1

    def test_beyond_head_falls_back_to_files(self, streamed, tmp_path):
        """A pyramid that lags the outputs (failing/stale appends)
        must DEGRADE to the files for the newest data, not hide it —
        and still trim to truly-empty beyond all data."""
        _, out = streamed
        lagging = str(tmp_path / "lagging")
        os.makedirs(lagging)
        files = sorted(glob.glob(os.path.join(out, "*.h5")))
        for f in files[:-1]:
            shutil.copy(f, lagging)
        sync_pyramid(lagging)  # pyramid built WITHOUT the last file
        shutil.copy(files[-1], lagging)  # outputs move ahead
        store = TileStore.open(lagging)
        full = QueryEngine(out)
        oracle_store = full.store
        lo = np.datetime64(oracle_store.t0_ns, "ns")
        hi = np.datetime64(
            oracle_store.head_ns - oracle_store.step_ns, "ns"
        )
        r = QueryEngine(lagging).query(lo, hi)
        oracle = full.query(lo, hi)
        assert r.source == "mixed"  # tiles + beyond-head files
        assert r.n_samples == oracle.n_samples > store.levels[0]
        np.testing.assert_array_equal(r.data, oracle.data)
        # a window entirely beyond all data is still empty, not NaN
        far = QueryEngine(lagging).query(
            np.datetime64(oracle_store.head_ns + 10 ** 10, "ns"),
            np.datetime64(oracle_store.head_ns + 2 * 10 ** 10, "ns"),
        )
        assert far.n_samples == 0 and far.source == "empty"

    def test_files_only_folder(self, streamed, tmp_path):
        """No pyramid at all: the legacy read path serves raw rows."""
        _, out = streamed
        legacy = str(tmp_path / "legacy")
        os.makedirs(legacy)
        for f in glob.glob(os.path.join(out, "*.h5")):
            shutil.copy(f, legacy)
        eng = QueryEngine(legacy)
        store = TileStore.open(out)
        lo = np.datetime64(store.t0_ns, "ns")
        hi = np.datetime64(store.head_ns - store.step_ns, "ns")
        r = eng.query(lo, hi)
        assert r.source == "files"
        oracle = QueryEngine(out).query(lo, hi)
        assert r.data.tobytes() == oracle.data.tobytes()


class TestHTTP:
    def test_end_to_end_demo(self, streamed, tmp_path):
        """The acceptance demo: realtime rounds with the pyramid on,
        then /query and /waterfall payloads byte-identical to an
        offline recomputation from the raw output files."""
        _, out = streamed
        offline = str(tmp_path / "offline")
        os.makedirs(offline)
        for f in glob.glob(os.path.join(out, "*.h5")):
            shutil.copy(f, offline)
        sync_pyramid(offline)
        off_eng = QueryEngine(offline)
        store = TileStore.open(out)
        t0s = str(np.datetime64(store.t0_ns, "ns"))
        t1s = str(np.datetime64(store.head_ns - store.step_ns, "ns"))
        with start_server(out) as srv:
            u = srv.base_url
            r = urllib.request.urlopen(
                f"{u}/query?t0={t0s}&t1={t1s}", timeout=30
            )
            served = r.read()
            assert r.headers["X-Tpudas-Source"] == "tiles"
            oracle = off_eng.query(t0s, t1s)
            buf = io.BytesIO()
            np.save(buf, np.ascontiguousarray(oracle.data))
            assert served == buf.getvalue()

            r = urllib.request.urlopen(
                f"{u}/waterfall?t0={t0s}&t1={t1s}&max_px=8", timeout=30
            )
            served_wf = r.read()
            assert int(r.headers["X-Tpudas-Level"]) >= 1
            wf_oracle = off_eng.query(t0s, t1s, max_samples=8)
            buf = io.BytesIO()
            np.save(buf, np.ascontiguousarray(wf_oracle.data))
            assert served_wf == buf.getvalue()

    @pytest.mark.slow
    def test_healthz_serves_live_health_json(self, streamed):
        _, out = streamed
        on_disk = read_health(out)
        assert on_disk is None  # health was off for this run
        with start_server(out) as srv:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    srv.base_url + "/healthz", timeout=30
                )
            assert err.value.code == 503  # no snapshot -> unhealthy
        # now with a real snapshot: the endpoint serves its fields
        from tpudas.obs.health import write_health

        write_health(out, {
            "rounds": 2, "polls": 3, "mode": "stateful",
            "realtime_factor": 10.0, "round_realtime_factor": 9.0,
            "head_lag_seconds": 1.0, "redundant_ratio": 0.0,
            "carry_resume_count": 0, "last_round_wall_seconds": 0.1,
            "consecutive_failures": 0, "quarantined_files": 0,
            "degraded": False, "integrity_fallbacks": 0,
            "resource_degraded": False, "last_error": None,
        })
        with start_server(out) as srv:
            r = urllib.request.urlopen(srv.base_url + "/healthz",
                                       timeout=30)
            body = json.loads(r.read())
            assert r.status == 200
            assert body["status"] == "ok" and body["rounds"] == 2
            # the file snapshot stays the source of truth
            assert read_health(out)["rounds"] == 2

    def test_metrics_live_exposition(self, streamed):
        _, out = streamed
        with start_server(out) as srv:
            urllib.request.urlopen(
                srv.base_url
                + "/query?t0=2023-03-22T00:00:10&t1=2023-03-22T00:00:20",
                timeout=30,
            ).read()
            body = urllib.request.urlopen(
                srv.base_url + "/metrics", timeout=30
            ).read().decode()
        assert "# TYPE tpudas_serve_requests_total counter" in body
        assert 'endpoint="/query"' in body

    def test_load_shed_503_when_queue_full(self, streamed):
        """Deterministic saturation via the serve.queue_full fault
        site: the data plane sheds with 503 + Retry-After, the control
        plane (/metrics) still answers."""
        _, out = streamed
        reg = MetricsRegistry()
        plan = FaultPlan(
            FaultSpec(site="serve.queue_full", action="raise", at=1,
                      times=1)
        )
        with use_registry(reg), install_fault_plan(plan), \
                start_server(out) as srv:
            u = srv.base_url
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    u + "/query?t0=2023-03-22T00:00:10"
                        "&t1=2023-03-22T00:00:20",
                    timeout=30,
                )
            assert err.value.code == 503
            assert err.value.headers["Retry-After"] == "1"
            # control plane bypasses the gate
            r = urllib.request.urlopen(u + "/metrics", timeout=30)
            assert r.status == 200
            # the fault fired once; the retried request succeeds
            r = urllib.request.urlopen(
                u + "/query?t0=2023-03-22T00:00:10"
                    "&t1=2023-03-22T00:00:20",
                timeout=30,
            )
            assert r.status == 200
        assert reg.value("tpudas_serve_shed_total") == 1
        assert reg.value(
            "tpudas_serve_requests_total", endpoint="/query", status="503"
        ) == 1

    def test_real_saturation_sheds(self, streamed):
        """A genuinely full gate (max_inflight=1, leader parked inside
        a tile read) sheds the second concurrent data request."""
        _, out = streamed
        release = threading.Event()
        entered = threading.Event()

        def park(_):
            entered.set()
            release.wait(timeout=30)

        plan = FaultPlan(
            FaultSpec(site="serve.tile_read", action="delay", at=1,
                      times=1, seconds=0.0, sleep_fn=park)
        )
        codes = []
        with install_fault_plan(plan), start_server(
            out, max_inflight=1, cache_tiles=4
        ) as srv:
            url = (
                srv.base_url
                + "/query?t0=2023-03-22T00:00:10&t1=2023-03-22T00:00:20"
            )

            def slow():
                codes.append(urllib.request.urlopen(url, timeout=30).status)

            t = threading.Thread(target=slow)
            t.start()
            assert entered.wait(timeout=30)
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(url, timeout=30)
            assert err.value.code == 503
            release.set()
            t.join(timeout=30)
        assert codes == [200]


class TestIndexConcurrency:
    def test_cache_double_buffer_survives_torn_primary(self, tmp_path):
        src = str(tmp_path / "raw")
        make_synthetic_spool(src, n_files=2, file_duration=5.0, fs=20.0,
                             n_ch=4)
        idx = DirectoryIndex(src)
        idx.update()
        _append_files_simple(src, 2)
        idx.update()  # second save -> .prev exists
        cache = os.path.join(src, INDEX_FILENAME)
        assert os.path.isfile(cache + ".prev")
        with open(cache, "w") as fh:
            fh.write('{"version": 3, "files": {"ra')  # torn mid-write
        fresh = DirectoryIndex(src)
        fresh._load_cache()
        assert fresh._records  # recovered from .prev, not empty

    def test_time_range_records(self, tmp_path):
        src = str(tmp_path / "raw")
        make_synthetic_spool(src, n_files=3, file_duration=10.0, fs=20.0,
                             n_ch=4)
        idx = DirectoryIndex(src)
        idx.update()
        t0 = to_datetime64(T0).astype("datetime64[ns]")
        recs = idx.time_range_records(
            t0 + np.timedelta64(12, "s"), t0 + np.timedelta64(15, "s")
        )
        assert len(recs) == 1  # only the second file overlaps
        assert recs[0]["time_min"] <= t0 + np.timedelta64(15, "s")
        all_recs = idx.time_range_records(None, None)
        assert len(all_recs) == 3
        mins = [r["time_min"] for r in all_recs]
        assert mins == sorted(mins)


def _append_files_simple(directory, start_index):
    p = synthetic_patch(
        t0=to_datetime64(T0).astype("datetime64[ns]")
        + np.timedelta64(600, "s"),
        duration=5.0, fs=20.0, n_ch=4, seed=start_index,
    )
    write_patch(p, os.path.join(directory, f"raw_{start_index:04d}.h5"))


class TestWaterfallPyramid:
    def test_budget_reads_from_pyramid(self, streamed):
        from tpudas import spool
        from tpudas.viz.waterfall import patch_waterfall

        _, out = streamed
        merged = spool(out).update().chunk(time=None)
        assert len(merged) == 1
        patch = merged[0]
        n_t = patch.coords["time"].size
        ax = patch_waterfall(patch, pyramid=out, max_px=max(n_t // 4, 2))
        coarse = np.asarray(ax.images[-1].get_array())
        assert coarse.shape[1] <= max(n_t // 4, 2)  # time axis shrank
        ax2 = patch_waterfall(patch)
        full = np.asarray(ax2.images[-1].get_array())
        assert full.shape[1] == n_t

    def test_below_budget_identical_and_no_pyramid_fallback(
        self, streamed, tmp_path
    ):
        from tpudas import spool
        from tpudas.viz.waterfall import patch_waterfall

        _, out = streamed
        patch = spool(out).update().chunk(time=None)[0]
        n_t = patch.coords["time"].size
        # below the budget: identical with and without the pyramid
        a = patch_waterfall(patch, pyramid=out, max_px=n_t + 10)
        b = patch_waterfall(patch)
        np.testing.assert_array_equal(
            np.asarray(a.images[-1].get_array()),
            np.asarray(b.images[-1].get_array()),
        )
        # no pyramid: budget exceeded but the full-res path runs
        legacy = str(tmp_path / "legacy")
        os.makedirs(legacy)
        for f in glob.glob(os.path.join(out, "*.h5")):
            shutil.copy(f, legacy)
        c = patch_waterfall(patch, pyramid=legacy, max_px=2)
        assert (
            np.asarray(c.images[-1].get_array()).shape[1] == n_t
        )


class TestToolingLint:
    def test_serve_metrics_are_required(self):
        """The lint enforces the serve metric set exists in the
        sources — deleting one fails tier-1."""
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        sys.path.insert(0, os.path.join(repo, "tools"))
        import check_metrics

        problems = check_metrics.lint(
            {"f.py": ""}, catalog_text="", require=True
        )
        assert any("tpudas_serve_shed_total" in p for p in problems)
        assert any("serve.request" in p for p in problems)
        # default (partial-source) mode stays quiet
        assert check_metrics.lint({"f.py": ""}, catalog_text="") == []


class TestServePoolRespawn:
    @pytest.mark.slow
    def test_dead_worker_is_respawned(self, streamed):
        """ISSUE 12 satellite: a SIGKILLed data-plane worker is
        respawned by the supervision loop (bounded restarts, counted)
        instead of permanently shrinking the pool — /pool/healthz
        goes degraded during the gap and back to ok after."""
        import signal
        import time as _t
        import urllib.request

        from tpudas.serve.pool import ServePool, has_reuse_port

        if not has_reuse_port():
            pytest.skip("SO_REUSEPORT unavailable on this platform")
        _src, out = streamed
        pool = ServePool(
            out, port=0, workers=2, restart_backoff=0.05
        )
        with pool:
            assert pool.health()["status"] == "ok"
            victim_pid = pool.worker_info[0]["pid"]
            os.kill(victim_pid, signal.SIGKILL)
            deadline = _t.time() + 30
            while _t.time() < deadline:
                h = pool.health()
                if (
                    h["status"] == "ok"
                    and h["workers"]["0"]["pid"] != victim_pid
                ):
                    break
                _t.sleep(0.1)
            else:
                pytest.fail(f"worker 0 never respawned: {pool.health()}")
            assert pool.restart_counts().get(0, 0) >= 1
            # the respawned worker serves on the shared port again
            body = urllib.request.urlopen(
                pool.control_url + "/metrics", timeout=30
            ).read().decode()
            assert "tpudas_serve_pool_worker_restarts_total" in body

    @pytest.mark.slow
    def test_restarts_are_bounded(self, tmp_path):
        """A worker that can never come up stops being respawned
        after max_restarts (the pool reports degraded, not a spawn
        storm)."""
        from tpudas.serve.pool import ServePool, has_reuse_port

        if not has_reuse_port():
            pytest.skip("SO_REUSEPORT unavailable on this platform")
        out = str(tmp_path / "store")
        os.makedirs(out)
        pool = ServePool(
            out, port=0, workers=1, restart_backoff=0.01,
            max_restarts=2,
        )
        with pool:
            # kill it repeatedly until the restart budget is spent
            import signal
            import time as _t

            deadline = _t.time() + 30
            while _t.time() < deadline:
                if pool.restart_counts().get(0, 0) >= 2:
                    break
                pid = pool.worker_info[0]["pid"]
                try:
                    os.kill(pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                _t.sleep(0.1)
            # give the monitor a beat: count must CAP at max_restarts
            _t.sleep(0.6)
            assert pool.restart_counts().get(0, 0) == 2
