"""Property-based tests (hypothesis) for host-side invariants.

Generalizes the hand-rolled fixed-size checks in test_lfproc /
test_tdas across the whole valid parameter space: the overlap-save
scheduler's tiling algebra, the reference filename contract, and the
tdas round-trip including int16 quantization error bounds (SURVEY.md
§4 test strategy: property tests for the chunking/seam logic).
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property sweeps need the hypothesis extra"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from tpudas.proc.lfproc import schedule_windows
from tpudas.proc.naming import get_filename, get_timestr

pytestmark = pytest.mark.slow


class TestScheduleProperties:
    @settings(max_examples=200, deadline=None)
    @given(
        n=st.integers(30, 5000),
        ps=st.integers(10, 800),
        buff=st.integers(1, 100),
    )
    def test_overlap_save_tiling(self, n, ps, buff):
        # the scheduler clamps the patch to the grid before validating
        eff_ps = min(ps, n - 1)
        if eff_ps <= 2 * buff:
            with pytest.raises(ValueError):
                schedule_windows(n, ps, buff)
            return
        wins = schedule_windows(n, ps, buff)
        if not wins:
            return
        # emitted interiors start at buff and tile contiguously
        assert wins[0][2] == buff
        for (sl, sh, el, eh), (nsl, nsh, nel, neh) in zip(wins, wins[1:]):
            assert nel == eh, "seam between consecutive windows"
        for sl, sh, el, eh in wins:
            # selections stay inside the grid, emits inside selections
            assert 0 <= sl < sh < n
            assert sl <= el < eh or el == eh
            assert eh <= sh
            # the halo guarantee: every emitted point has >= buff
            # points of selected context on the left; on the right the
            # stream end may truncate (the tail window emits to the
            # final grid point, matching the reference's loop)
            assert el - sl >= buff
        # no window selects more than the configured patch size
        assert all(sh - sl <= ps for sl, sh, _, _ in wins)

    @settings(max_examples=100, deadline=None)
    @given(
        n=st.integers(30, 5000),
        ps=st.integers(10, 800),
        buff=st.integers(1, 100),
    )
    def test_emitted_points_unique_and_sorted(self, n, ps, buff):
        if min(ps, n - 1) <= 2 * buff:
            return
        wins = schedule_windows(n, ps, buff)
        emitted = [i for _, _, el, eh in wins for i in range(el, eh)]
        assert emitted == sorted(set(emitted)), "overlap or disorder"


class TestCascadeDesignProperties:
    @settings(max_examples=40, deadline=None)
    @given(
        ratio=st.sampled_from(
            [2, 4, 5, 8, 10, 20, 25, 40, 50, 100, 125, 200, 400, 500,
             1000, 2000]
        ),
        fs_exp=st.floats(1.7, 3.3),  # fs in ~[50, 2000] Hz
        corner_frac=st.floats(0.2, 0.45),
    )
    def test_response_matches_butter2_across_design_space(
        self, ratio, fs_exp, corner_frac
    ):
        """The cascade's engine-parity contract — composite magnitude
        equals the Butterworth-squared target on the retained band to
        ~1e-4 — holds across the whole (fs, ratio, corner) space the
        engine can be configured with, not just the three hand-picked
        test points."""
        from tpudas.ops.fir import (
            butter2_mag,
            design_cascade,
            impulse_response,
        )

        fs = 10.0 ** fs_exp
        corner = corner_frac * fs / ratio
        plan = design_cascade(fs, ratio, corner, 4)
        h = impulse_response(plan)
        nfft = max(1 << 16, 1 << int(np.ceil(np.log2(len(h) * 4))))
        H = np.abs(np.fft.rfft(h, nfft))
        freqs = np.arange(nfft // 2 + 1) / nfft * fs
        band = freqs <= 0.5 * fs / ratio
        err = np.abs(H[band] - butter2_mag(freqs[band], corner, 4))
        assert err.max() < 2e-4, (fs, ratio, corner, err.max())
        # zero-phase contract: integer composite delay, symmetric h
        d = plan.delay
        w = min(d, len(h) - 1 - d)
        assert np.abs(h[d - w : d] - h[d + 1 : d + 1 + w][::-1]).max() < 1e-10


class TestNamingProperties:
    @settings(max_examples=200, deadline=None)
    @given(ms=st.integers(0, 4_102_444_800_000))  # epoch .. 2100-01-01
    def test_timestr_contract_everywhere(self, ms):
        t = np.datetime64(ms, "ms")
        s = get_timestr(t)
        # the reference contract (lf_das.py:23-26): str()[:21] with
        # colons removed -> 19 chars, one sub-second digit
        assert len(s) == 19
        assert ":" not in s
        assert s == str(t)[:21].replace(":", "")
        name = get_filename(t, t + np.timedelta64(100, "s"))
        assert name.startswith("LFDAS_") and name.endswith(".h5")


def _patch_from_data(data):
    from tpudas.core.patch import Patch

    t, c = data.shape
    times = np.datetime64("2023-03-22T00:00:00", "ns") + np.arange(
        t
    ) * np.timedelta64(10_000_000, "ns")
    dists = np.arange(c, dtype=np.float64) * 5.0
    return Patch(
        data=data,
        coords={"time": times, "distance": dists},
        dims=("time", "distance"),
        attrs={"d_time": 0.01, "d_distance": 5.0},
    )


class TestTdasRoundTrip:
    @settings(max_examples=25, deadline=None)
    @given(
        t=st.integers(4, 200),
        c=st.integers(1, 32),
        seed=st.integers(0, 2**31 - 1),
    )
    def test_float32_lossless(self, tmp_path_factory, t, c, seed):
        from tpudas.io.registry import read_file, write_patch

        rng = np.random.default_rng(seed)
        data = rng.standard_normal((t, c)).astype(np.float32)
        path = str(tmp_path_factory.mktemp("tdas") / "p.tdas")
        write_patch(_patch_from_data(data), path, format="tdas")
        (back,) = read_file(path, format="tdas")
        assert np.array_equal(back.host_data(), data)

    @settings(max_examples=25, deadline=None)
    @given(
        t=st.integers(4, 200),
        c=st.integers(1, 16),
        scale_exp=st.integers(-6, -1),
        seed=st.integers(0, 2**31 - 1),
    )
    def test_int16_quantization_error_bounded(
        self, tmp_path_factory, t, c, scale_exp, seed
    ):
        from tpudas.io.registry import read_file, write_patch

        rng = np.random.default_rng(seed)
        scale = 10.0 ** scale_exp
        # keep data inside the representable range scale * 32767
        data = (
            rng.uniform(-0.9, 0.9, size=(t, c)) * scale * 32000
        ).astype(np.float32)
        path = str(tmp_path_factory.mktemp("tdas") / "q.tdas")
        write_patch(
            _patch_from_data(data), path, format="tdas",
            dtype="int16", scale=scale,
        )
        back = read_file(path, format="tdas")[0].host_data()
        # half a code step, plus float32 ulp slack for the writer's
        # round-at-.5 boundary and the decode multiply
        bound = scale * 0.5 + np.abs(data).max() * 1e-6
        assert np.abs(back - data).max() <= bound


class TestCrashResumeProperty:
    """The crash-only contract (lf_das.py:214-217,
    low_pass_dascore_edge.ipynb:228-231) fuzzed over kill points: a run
    killed after ANY window, resumed via the output-folder state +
    rewind, must produce the same contiguous output as an uninterrupted
    run — not just at round granularity (the fixed tests) but at every
    window boundary."""

    FS = 100.0
    DT = 1.0
    BUFF = 5
    PATCH = 40
    T1, T2 = "2023-03-22T00:00:00", "2023-03-22T00:03:00"

    @pytest.fixture(scope="class")
    def crash_spool(self, tmp_path_factory):
        from tpudas.testing import make_synthetic_spool

        d = tmp_path_factory.mktemp("crashraw")
        make_synthetic_spool(
            d, n_files=6, file_duration=30.0, fs=self.FS, n_ch=4,
            noise=0.01,
        )
        return str(d)

    @pytest.fixture(scope="class")
    def full_run(self, crash_spool, tmp_path_factory):
        out = tmp_path_factory.mktemp("full") / "out"
        self._run(crash_spool, out, self.T1, self.T2)
        from tpudas import spool

        return spool(str(out)).update().chunk(time=None)[0]

    def _run(self, src, out_dir, t1, t2, crash_after=None):
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc

        lfp = LFProc(spool(src).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=self.DT,
            process_patch_size=self.PATCH,
            edge_buff_size=self.BUFF,
        )
        lfp.set_output_folder(
            str(out_dir), delete_existing=crash_after is not None
        )
        if crash_after is None:
            lfp.process_time_range(np.datetime64(t1), np.datetime64(t2))
            return lfp

        real = LFProc._emit_window_output
        calls = {"n": 0}

        def dying(self_, *a, **kw):
            if calls["n"] >= crash_after:
                raise KeyboardInterrupt("synthetic crash")
            calls["n"] += 1
            return real(self_, *a, **kw)

        LFProc._emit_window_output = dying
        try:
            with pytest.raises(KeyboardInterrupt):
                lfp.process_time_range(
                    np.datetime64(t1), np.datetime64(t2)
                )
        finally:
            LFProc._emit_window_output = real
        assert calls["n"] == crash_after
        return lfp

    @settings(max_examples=8, deadline=None)
    @given(k=st.integers(1, 7))
    def test_kill_after_any_window_resumes_seamlessly(
        self, k, crash_spool, full_run, tmp_path_factory
    ):
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc, schedule_windows

        n_wins = len(schedule_windows(181, self.PATCH, self.BUFF))
        k = min(k, n_wins - 1)  # crash strictly before the last window
        out = tmp_path_factory.mktemp(f"crash{k}") / "out"
        self._run(crash_spool, out, self.T1, self.T2, crash_after=k)

        # resume exactly as the real-time loop does: output folder IS
        # the state; rewind (buff-1) output steps before the last
        # processed time
        lfp2 = LFProc(spool(crash_spool).sort("time").update())
        lfp2.update_processing_parameter(
            output_sample_interval=self.DT,
            process_patch_size=self.PATCH,
            edge_buff_size=self.BUFF,
        )
        lfp2.set_output_folder(str(out), delete_existing=False)
        t_last = lfp2.get_last_processed_time()
        rewind = int((self.BUFF - 1) * self.DT)
        lfp2.process_time_range(
            t_last - np.timedelta64(rewind, "s"), np.datetime64(self.T2)
        )

        merged = spool(str(out)).update().chunk(time=None)
        assert len(merged) == 1, "resume left a seam or a hole"
        got = merged[0]
        ref = full_run
        ta, tb = got.coords["time"], ref.coords["time"]
        lo = max(ta[0], tb[0])
        hi = min(ta[-1], tb[-1])
        gsel = got.select(time=(lo, hi)).host_data()
        rsel = ref.select(time=(lo, hi)).host_data()
        scale = np.abs(rsel).max()
        assert np.abs(gsel - rsel).max() < 5e-3 * scale


class TestGapFillProperties:
    """merge_patches(max_fill=...) over arbitrary hole layouts: output
    is always a single regular-grid patch when every hole is on-grid
    and under the tolerance, original samples survive byte-identical,
    and fill rows are the linear bridge of their bounding samples."""

    @settings(max_examples=60, deadline=None)
    @given(
        seg_lens=st.lists(st.integers(2, 40), min_size=2, max_size=5),
        holes=st.lists(st.integers(1, 30), min_size=1, max_size=4),
        fs=st.sampled_from([10.0, 100.0, 250.0]),
    )
    def test_on_grid_holes_fill_to_one_regular_patch(
        self, seg_lens, holes, fs
    ):
        from tpudas.core.patch import Patch
        from tpudas.io.spool import merge_patches

        step_ns = int(round(1e9 / fs))
        n_seg = len(seg_lens)
        holes = (holes * n_seg)[: n_seg - 1]
        t0 = np.datetime64("2023-01-01T00:00:00", "ns")
        patches, cursor = [], 0
        pos = t0
        vals = []
        for i, n in enumerate(seg_lens):
            data = (
                np.arange(cursor, cursor + n, dtype=np.float32)[:, None]
                * np.array([1.0, -2.0], np.float32)[None, :]
            )
            times = pos + np.arange(n) * np.timedelta64(step_ns, "ns")
            patches.append(
                Patch(
                    data=data,
                    coords={"time": times,
                            "distance": np.array([0.0, 5.0])},
                    dims=("time", "distance"),
                    attrs={"d_time": 1.0 / fs, "d_distance": 5.0},
                )
            )
            vals.append(data)
            cursor += n  # the value ramp runs on across segments
            if i < n_seg - 1:
                k = holes[i]  # k missing samples, on-grid
                pos = times[-1] + (k + 1) * np.timedelta64(step_ns, "ns")
        max_fill = (max(holes) + 1) / fs  # tolerate every hole
        out = merge_patches(patches, max_fill=max_fill)
        assert len(out) == 1
        taxis = out[0].coords["time"]
        steps = np.diff(taxis).astype("timedelta64[ns]").astype(np.int64)
        assert (steps == step_ns).all(), "output grid not regular"
        total = sum(seg_lens) + sum(holes)
        assert taxis.size == total
        merged = out[0].host_data()
        # original samples byte-identical; fill rows linear between
        # their bounding samples
        idx = 0
        for i, n in enumerate(seg_lens):
            np.testing.assert_array_equal(
                merged[idx : idx + n], vals[i]
            )
            idx += n
            if i < n_seg - 1:
                k = holes[i]
                a, b = merged[idx - 1], merged[idx + k]
                w = (np.arange(1, k + 1, dtype=np.float64) / (k + 1))[
                    :, None
                ]
                np.testing.assert_allclose(
                    merged[idx : idx + k],
                    (a * (1 - w) + b * w).astype(np.float32),
                    rtol=1e-6, atol=1e-7,
                )
                idx += k

    @settings(max_examples=40, deadline=None)
    @given(
        k=st.integers(1, 20),
        off_grid_ns=st.sampled_from([3_000_000, 5_000_000, -4_000_000]),
    )
    def test_off_grid_holes_never_fill(self, k, off_grid_ns):
        """A hole that does not land on the sampling grid (within 0.1
        step) must split, never fabricate a shifted axis."""
        from tpudas.core.patch import Patch
        from tpudas.io.spool import merge_patches

        fs = 100.0  # step 10 ms; offsets above are 0.3-0.5 steps
        step_ns = int(round(1e9 / fs))
        t0 = np.datetime64("2023-01-01T00:00:00", "ns")

        def mk(start, n):
            times = start + np.arange(n) * np.timedelta64(step_ns, "ns")
            return Patch(
                data=np.zeros((n, 1), np.float32),
                coords={"time": times, "distance": np.array([0.0])},
                dims=("time", "distance"),
                attrs={"d_time": 1.0 / fs, "d_distance": 1.0},
            )

        a = mk(t0, 10)
        gap = (k + 1) * step_ns + off_grid_ns
        b = mk(
            a.coords["time"][-1] + np.timedelta64(gap, "ns"), 10
        )
        out = merge_patches([a, b], max_fill=10.0)
        assert len(out) == 2


class TestJointCrashResumeProperty:
    """Crash-ordering contract of the joint pipeline: the rolling file
    of a window is written BEFORE its LF file, and resume state is the
    LF folder — so a kill at ANY window boundary (including between
    the two writes of one window) leaves a stream that resume heals
    into outputs equal to an uninterrupted run, for BOTH products."""

    FS = 100.0
    DT = 1.0
    BUFF = 5
    PATCH = 40
    T1, T2 = "2023-03-22T00:00:00", "2023-03-22T00:03:00"

    def _mk(self, src, out_lf, out_roll, delete=True):
        from tpudas import spool
        from tpudas.proc.joint import JointProc

        jp = JointProc(spool(src).sort("time").update())
        jp.update_processing_parameter(
            output_sample_interval=self.DT,
            process_patch_size=self.PATCH,
            edge_buff_size=self.BUFF,
            rolling_window=3.0,
            rolling_step=1.0,
        )
        jp.set_output_folder(str(out_lf), delete_existing=delete)
        jp.set_rolling_output_folder(str(out_roll), delete_existing=delete)
        return jp

    @pytest.fixture(scope="class")
    def joint_spool(self, tmp_path_factory):
        from tpudas.testing import make_synthetic_spool

        d = tmp_path_factory.mktemp("jcrashraw")
        make_synthetic_spool(
            d, n_files=6, file_duration=30.0, fs=self.FS, n_ch=4,
            noise=0.01,
        )
        return str(d)

    @pytest.fixture(scope="class")
    def joint_full(self, joint_spool, tmp_path_factory):
        from tpudas import spool

        base = tmp_path_factory.mktemp("jfull")
        jp = self._mk(joint_spool, base / "lf", base / "roll")
        jp.process_time_range(np.datetime64(self.T1), np.datetime64(self.T2))
        return (
            spool(str(base / "lf")).update().chunk(time=None)[0],
            spool(str(base / "roll")).update().chunk(time=None)[0],
        )

    @settings(max_examples=6, deadline=None)
    @given(k=st.integers(1, 6), between=st.booleans())
    def test_kill_any_window_both_products_heal(
        self, k, between, joint_spool, joint_full, tmp_path_factory
    ):
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc, schedule_windows

        n_wins = len(schedule_windows(181, self.PATCH, self.BUFF))
        k = min(k, n_wins - 1)
        base = tmp_path_factory.mktemp(f"jcrash{k}{int(between)}")
        jp = self._mk(joint_spool, base / "lf", base / "roll")

        real = LFProc._emit_window_output
        calls = {"n": 0}

        def dying(self_, *a, **kw):
            # crash either before this window's LF write (the rolling
            # file for it is already on disk — `between`) or after it
            if calls["n"] >= k and between:
                raise KeyboardInterrupt("between the two writes")
            r = real(self_, *a, **kw)
            calls["n"] += 1
            if calls["n"] >= k and not between:
                raise KeyboardInterrupt("after the window")
            return r

        LFProc._emit_window_output = dying
        try:
            with pytest.raises(KeyboardInterrupt):
                jp.process_time_range(
                    np.datetime64(self.T1), np.datetime64(self.T2)
                )
        finally:
            LFProc._emit_window_output = real

        # resume exactly like the real-time loop
        jp2 = self._mk(joint_spool, base / "lf", base / "roll",
                       delete=False)
        t_last = jp2.get_last_processed_time()
        rewind = int((self.BUFF - 1) * self.DT)
        jp2.process_time_range(
            t_last - np.timedelta64(rewind, "s"), np.datetime64(self.T2)
        )
        full_lf, full_roll = joint_full
        for folder, ref in (("lf", full_lf), ("roll", full_roll)):
            merged = spool(str(base / folder)).update().chunk(time=None)
            assert len(merged) == 1, f"{folder}: seam or hole after resume"
            got = merged[0]
            ta, tb = got.coords["time"], ref.coords["time"]
            lo, hi = max(ta[0], tb[0]), min(ta[-1], tb[-1])
            a = got.select(time=(lo, hi)).host_data()
            b = ref.select(time=(lo, hi)).host_data()
            scale = max(float(np.abs(b).max()), 1e-30)
            assert np.abs(a - b).max() < 5e-3 * scale, folder
