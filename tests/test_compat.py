"""API-compat: the reference notebooks' exact call patterns must run
unchanged against the tpudas engine via the `dascore` + `lf_das` shims.

Each test replays a condensed version of one notebook's code cells
(same calls, same spellings — SURVEY.md §2.3) on a synthetic spool.
"""

import numpy as np
import pytest

import dascore as dc
from dascore.units import s
from dascore.utils.mapping import FrozenDict
from lf_das import (
    LFProc,
    _check_merge,
    _down_sample_processing,
    _get_filename,
    _get_timestr,
    get_edge_effect_time,
    get_patch_time,
    waterfall_plot,
)
from tpudas.testing import make_synthetic_spool

FS = 100.0


@pytest.fixture(scope="module")
def data_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("compat_raw")
    make_synthetic_spool(
        d, n_files=6, file_duration=30.0, fs=FS, n_ch=12, noise=0.01
    )
    return str(d)


def test_lf_das_public_surface():
    # every name the notebooks import from lf_das exists
    for obj in (
        LFProc,
        get_edge_effect_time,
        get_patch_time,
        waterfall_plot,
        _get_filename,
        _get_timestr,
        _check_merge,
        _down_sample_processing,
    ):
        assert callable(obj)
    assert isinstance(LFProc().parameters, FrozenDict)


@pytest.mark.slow
def test_batch_low_pass_notebook_flow(data_path, tmp_path):
    """low_pass_dascore.ipynb cells 3-11 condensed."""
    output_data_folder = str(tmp_path / "results")

    sp = dc.spool(data_path).sort("time").update()
    content_df = sp.get_contents()
    assert len(content_df) == 6

    patch_0 = sp[0]
    gauge_length = patch_0.attrs["gauge_length"]
    channel_spacing = patch_0.attrs["distance_step"]
    sampling_interval = patch_0.attrs["time_step"]
    sampling_rate = 1 / (sampling_interval / np.timedelta64(1, "s"))
    assert sampling_rate == FS and gauge_length == 10.0 and channel_spacing == 5.0

    ch_start, ch_end = 2, 10
    d_1 = patch_0.coords["distance"][ch_start]
    d_2 = patch_0.coords["distance"][ch_end]
    t_1 = "2023-03-22 00:00:00"
    t_2 = "2023-03-22 00:03:00"
    sub_sp = sp.select(distance=(d_1, d_2), time=(t_1, t_2))

    patch_length = 60.0
    d_t = 1.0
    tolerance = 1e-3
    edge_buffer = get_edge_effect_time(
        sampling_interval=1 / sampling_rate,
        total_T=patch_length,
        tol=tolerance,
        freq=1 / d_t,
    )
    assert 0 < edge_buffer < patch_length / 2

    lfp = LFProc(sub_sp)
    lfp.update_processing_parameter(
        output_sample_interval=d_t,
        process_patch_size=int(patch_length / d_t),
        edge_buff_size=int(np.ceil(edge_buffer / d_t)),
    )
    lfp.set_output_folder(output_data_folder, delete_existing=False)
    lfp.process_time_range(
        np.datetime64("2023-03-22T00:00:00"), np.datetime64("2023-03-22T00:03:00")
    )

    sp_result = dc.spool(output_data_folder)
    sp_result = sp_result.chunk(time=None)
    assert len(sp_result) == 1
    result = sp_result[0]
    assert result.data.shape[1] == ch_end - ch_start + 1
    assert result.attrs["time_step"] == np.timedelta64(1, "s")

    # viz recipe (cell 22): select → chunk → new → viz.waterfall
    scale_iDAS = float((116 * sampling_rate / gauge_length) / 1e9)
    filtered_data = sp_result[0].data
    mean_array = np.mean(np.asarray(filtered_data)[:, 0:2], axis=1).reshape(-1, 1)
    demeaned = (np.asarray(filtered_data) - mean_array) * scale_iDAS
    patch_viz = sp_result[0].new(data=demeaned)
    ax = patch_viz.viz.waterfall(scale=0.01)
    assert ax is not None


@pytest.mark.slow
def test_waterfall_plot_signature(data_path, tmp_path):
    """lf_das.waterfall_plot with the notebook's (channel x time) input."""
    rng = np.random.default_rng(0)
    data = rng.standard_normal((32, 300))
    waterfall_plot(
        data, 0, 200, 0, 30, 100, 1.0, 50.0, 1.0,
        "test title", str(tmp_path), "qc_plot",
    )
    assert (tmp_path / "qc_plot.jpeg").exists()


def test_rolling_mean_notebook_flow(data_path, tmp_path):
    """rolling_mean_dascore.ipynb cells 5-9 condensed."""
    output = str(tmp_path / "rolling_results")
    import os

    os.makedirs(output, exist_ok=True)

    sp = dc.spool(data_path).sort("time").update()
    patch_0 = sp[0]
    gauge_length = patch_0.attrs["gauge_length"]
    sampling_interval = patch_0.attrs["d_time"]
    sampling_rate = 1 / (sampling_interval / np.timedelta64(1, "s"))

    d_t = 1.0
    window = d_t * s
    step = d_t * s
    scale_iDAS = float((116 * sampling_rate / gauge_length) / 1e9)

    sub_sp = sp.select(distance=(0.0, 25.0))
    for i, patch in enumerate(sub_sp):
        rolling_mean_patch = patch.rolling(
            time=window, step=step, engine="numpy"
        ).mean()
        new_scaled_patch = rolling_mean_patch.new(
            data=rolling_mean_patch.data * scale_iDAS
        )
        filename = _get_filename(
            new_scaled_patch.attrs["time_min"], new_scaled_patch.attrs["time_max"]
        )
        new_scaled_patch.io.write(output + "/" + filename, "dasdae")

    rolling_spool = dc.spool(output).chunk(time=None)
    rolling_merged_patch = rolling_spool[0]
    data = rolling_merged_patch.data
    n_samples = data.shape[0]

    # NaN warm-up prefix exists and dropna strips it (cell 9 assert)
    time_axis = np.linspace(0, int(n_samples * d_t), n_samples, endpoint=False)
    time_axis[np.isnan(np.asarray(data)[:, 0])] = np.nan
    time_no_nans = time_axis[~np.isnan(time_axis)]
    no_nans = rolling_merged_patch.dropna("time")
    assert time_no_nans.shape[0] == no_nans.data.shape[0]


def test_edge_notebook_resume_idiom(data_path, tmp_path):
    """low_pass_dascore_edge.ipynb cell 11 resume arithmetic."""
    output = str(tmp_path / "edge_results")
    d_t = 1.0
    edge_buffer = 8.0

    sp = dc.spool(data_path).update()
    sub_sp = sp.select(distance=(0.0, 55.0))
    lfp = LFProc(sub_sp)
    lfp.update_processing_parameter(
        output_sample_interval=d_t,
        process_patch_size=40,
        edge_buff_size=int(np.ceil(edge_buffer / d_t)),
    )
    lfp.set_output_folder(output, delete_existing=False)

    t_1 = np.datetime64("2023-03-22T00:00:00")
    t_2 = np.datetime64(sub_sp[-1].attrs["time_max"])
    lfp.process_time_range(t_1, t_2)

    t_2b = lfp.get_last_processed_time()
    assert isinstance(t_2b, np.datetime64)
    buffer = int((np.ceil(edge_buffer / d_t) - 1) * d_t)
    t_1b = t_2b - np.timedelta64(buffer, "s")
    assert t_1b < t_2b


def test_down_sample_processing_pipeline(data_path):
    """_down_sample_processing: corner 0.4/dt + uniform-grid resample."""
    sp = dc.spool(data_path).update()
    patch = sp[0]
    out = patch.pipe(_down_sample_processing, freq=1.0)
    assert out.attrs["time_step"] == np.timedelta64(1, "s")
    assert out.data.shape[1] == patch.data.shape[1]
