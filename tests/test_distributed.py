"""Multi-host (DCN) init hooks: env parsing, arg precedence,
idempotence, and the process-0 coordinator case — with
``jax.distributed.initialize`` mocked (no cluster needed, SURVEY.md
§2.4 DCN row)."""

import jax
import pytest

import tpudas.parallel.distributed as dist


@pytest.fixture(autouse=True)
def reset_state(monkeypatch):
    monkeypatch.setattr(dist, "_initialized", False)
    for var in ("COORDINATOR_ADDRESS", "NUM_PROCESSES", "PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )
    yield calls


class TestInitializeMultihost:
    def test_noop_without_config(self, reset_state):
        assert dist.initialize_multihost() is False
        assert reset_state == []

    def test_env_parsing(self, reset_state, monkeypatch):
        monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.1:8476")
        monkeypatch.setenv("NUM_PROCESSES", "8")
        monkeypatch.setenv("PROCESS_ID", "3")
        assert dist.initialize_multihost() is True
        assert reset_state == [
            {
                "coordinator_address": "10.0.0.1:8476",
                "num_processes": 8,
                "process_id": 3,
            }
        ]

    def test_explicit_args_beat_env(self, reset_state, monkeypatch):
        monkeypatch.setenv("COORDINATOR_ADDRESS", "env:1")
        monkeypatch.setenv("NUM_PROCESSES", "2")
        monkeypatch.setenv("PROCESS_ID", "1")
        assert dist.initialize_multihost("arg:2", 4, 2) is True
        (call,) = reset_state
        assert call["coordinator_address"] == "arg:2"
        assert call["num_processes"] == 4
        assert call["process_id"] == 2

    def test_process_zero_is_not_dropped(self, reset_state):
        # `process_id or env` would lose the coordinator (id 0)
        assert (
            dist.initialize_multihost("10.0.0.1:8476", 2, 0) is True
        )
        assert reset_state[0]["process_id"] == 0

    def test_idempotent(self, reset_state):
        assert dist.initialize_multihost("10.0.0.1:8476", 2, 0) is True
        assert dist.initialize_multihost("10.0.0.1:8476", 2, 0) is False
        assert len(reset_state) == 1

    def test_partial_config_is_noop(self, reset_state, monkeypatch):
        monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.1:8476")
        # NUM_PROCESSES / PROCESS_ID missing
        assert dist.initialize_multihost() is False
        assert reset_state == []


class TestQueries:
    def test_single_process(self):
        assert dist.is_distributed() is False
        assert len(dist.global_mesh_devices()) == len(jax.devices())
