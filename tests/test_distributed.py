"""Multi-host (DCN) init hooks: env parsing, arg precedence,
idempotence, and the process-0 coordinator case — with
``jax.distributed.initialize`` mocked (no cluster needed, SURVEY.md
§2.4 DCN row)."""

import jax
import pytest

import tpudas.parallel.distributed as dist


@pytest.fixture(autouse=True)
def reset_state(monkeypatch):
    monkeypatch.setattr(dist, "_initialized", False)
    for var in ("COORDINATOR_ADDRESS", "NUM_PROCESSES", "PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )
    yield calls


class TestInitializeMultihost:
    def test_noop_without_config(self, reset_state):
        assert dist.initialize_multihost() is False
        assert reset_state == []

    def test_env_parsing(self, reset_state, monkeypatch):
        monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.1:8476")
        monkeypatch.setenv("NUM_PROCESSES", "8")
        monkeypatch.setenv("PROCESS_ID", "3")
        assert dist.initialize_multihost() is True
        assert reset_state == [
            {
                "coordinator_address": "10.0.0.1:8476",
                "num_processes": 8,
                "process_id": 3,
            }
        ]

    def test_explicit_args_beat_env(self, reset_state, monkeypatch):
        monkeypatch.setenv("COORDINATOR_ADDRESS", "env:1")
        monkeypatch.setenv("NUM_PROCESSES", "2")
        monkeypatch.setenv("PROCESS_ID", "1")
        assert dist.initialize_multihost("arg:2", 4, 2) is True
        (call,) = reset_state
        assert call["coordinator_address"] == "arg:2"
        assert call["num_processes"] == 4
        assert call["process_id"] == 2

    def test_process_zero_is_not_dropped(self, reset_state):
        # `process_id or env` would lose the coordinator (id 0)
        assert (
            dist.initialize_multihost("10.0.0.1:8476", 2, 0) is True
        )
        assert reset_state[0]["process_id"] == 0

    def test_idempotent(self, reset_state):
        assert dist.initialize_multihost("10.0.0.1:8476", 2, 0) is True
        assert dist.initialize_multihost("10.0.0.1:8476", 2, 0) is False
        assert len(reset_state) == 1

    def test_partial_config_is_noop(self, reset_state, monkeypatch):
        monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.1:8476")
        # NUM_PROCESSES / PROCESS_ID missing
        assert dist.initialize_multihost() is False
        assert reset_state == []


class TestQueries:
    def test_single_process(self):
        assert dist.is_distributed() is False
        assert len(dist.global_mesh_devices()) == len(jax.devices())


@pytest.mark.slow
class TestRealTwoProcessDCN:
    def test_two_process_mesh_collectives(self):
        """The real thing, no mocks: two spawned processes call
        jax.distributed.initialize (via initialize_multihost env
        config), build one global (2, 4) mesh whose time axis spans the
        process boundary, and run psum + ppermute-halo collectives
        across it (BASELINE config 5's DCN direction, VERDICT r3 #7)."""
        import os
        import socket
        import subprocess
        import sys

        import __graft_entry__ as g

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        worker = os.path.join(os.path.dirname(__file__), "dcn_worker.py")
        procs = []
        for pid in range(2):
            env = g._clean_cpu_env(4)  # 4 virtual devices per process
            env.update(
                COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                NUM_PROCESSES="2",
                PROCESS_ID=str(pid),
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, worker],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                )
            )
        outs = []
        for p in procs:
            try:
                out, err = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("DCN worker timed out (coordinator hang?)")
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0, err[-1500:]
            assert "DCN_WORKER_OK" in out, (out, err[-500:])
