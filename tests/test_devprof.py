"""Device telemetry plane (ISSUE 17): ``tpudas.obs.devprof``.

Pins the accounting semantics the bench and the operator runbook
lean on: cold vs warm builder-key counters, recompile attribution by
what changed (shape vs knob fingerprint), stacked 1/N vs solo launch
attribution, the compile-seconds exclusion from device-execute
brackets, the per-round delta collection, the flight-record
``devprof`` roundtrip through :func:`tpudas.obs.collect.devprof_entry`,
the ``GET /devprof`` / ``GET /profile`` control-plane endpoints
(profiler-unavailable = 501, never a crash; ENOSPC shed parity with
every other non-essential writer), and the BENCH-trajectory gate in
``tools/bench_history.py``.
"""

import importlib.util
import json
import os
import time
import urllib.error
import urllib.request

import pytest

from tpudas.integrity import resource
from tpudas.obs import devprof
from tpudas.obs.collect import devprof_entry
from tpudas.obs.flight import FlightRecorder, read_flight
from tpudas.obs.registry import MetricsRegistry, use_registry
from tpudas.serve.http import start_server

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh_devprof():
    devprof.reset()
    yield
    devprof.reset()


def _compile_event(secs=0.25):
    """Simulate the jax monitoring hook firing for a backend compile
    (the real listener keys on this suffix)."""
    devprof._on_compile_duration(
        "/jax/core/compile/backend_compile_duration", secs
    )


class TestCompileAttribution:
    def test_cold_then_warm_key(self):
        reg = MetricsRegistry()
        with use_registry(reg):
            devprof.note_kernel("cascade", (64, 2000), ("xla",))
            _compile_event(0.5)
            # warm: the exact key again — no trigger, and a stray
            # compile on this thread reads unattributed
            devprof.note_kernel("cascade", (64, 2000), ("xla",))
            _compile_event(0.125)
            snap = devprof.devprof_snapshot(calibrate=False)
        by_trigger = snap["compile"]["by_trigger"]
        assert by_trigger.get("first") == 1
        assert by_trigger.get("unattributed") == 1
        assert snap["compile"]["count"] == 2
        assert snap["compile"]["seconds"] == pytest.approx(0.625)
        assert reg.value(
            "tpudas_devprof_compiles_total", trigger="first"
        ) == 1.0
        assert reg.value(
            "tpudas_devprof_compile_seconds_total"
        ) == pytest.approx(0.625)

    def test_shape_vs_knob_fingerprint(self):
        with use_registry(MetricsRegistry()):
            devprof.note_kernel("fused", (64, 2000), ("knobA",))
            _compile_event()
            # same knobs, new geometry -> shape
            devprof.note_kernel("fused", (128, 2000), ("knobA",))
            _compile_event()
            # same geometry, the env fingerprint moved -> knobs
            devprof.note_kernel("fused", (128, 2000), ("knobB",))
            _compile_event()
            snap = devprof.devprof_snapshot(calibrate=False)
        assert snap["compile"]["by_trigger"] == {
            "first": 1, "shape": 1, "knobs": 1
        }
        kinds = [k["trigger"] for k in snap["compile"]["kernels"]]
        assert kinds == ["first", "shape", "knobs"]

    def test_cold_starts_never_storm(self, monkeypatch):
        """A fleet cold start compiles every kernel once — 'first'
        triggers must not trip the recompile-storm alarm."""
        monkeypatch.setenv("TPUDAS_DEVPROF_STORM", "3/60")
        with use_registry(MetricsRegistry()):
            for i in range(6):
                devprof.note_kernel("k%d" % i, (8,), ("x",))
                _compile_event(0.01)
            snap = devprof.devprof_snapshot(calibrate=False)
            assert snap["compile"]["storms"] == 0
            assert snap["compile"]["storm_active"] is False
            # but genuine shape churn on one kernel does storm
            for i in range(4):
                devprof.note_kernel("churn", (8 + i,), ("x",))
                _compile_event(0.01)
            snap = devprof.devprof_snapshot(calibrate=False)
        assert snap["compile"]["storms"] == 1
        assert snap["compile"]["storm_active"] is True

    def test_compile_excluded_from_device_seconds(self):
        """A cold key's synchronous compile lands in compile
        accounting, never in the launch bracket's device seconds."""
        with use_registry(MetricsRegistry()):
            with devprof.stream_scope("s0"):
                devprof.note_kernel("k", (8,), ("x",))
                t0 = time.perf_counter()
                _compile_event(3600.0)  # absurd compile inside bracket
                devprof.note_launch("xla", t0, out=None)
            stats = devprof.classify_stream("s0", calibrate=False)
        assert stats["launches"] == 1.0
        # the 3600 s never reached device_s: bracket clamped to ~0
        assert stats["device_seconds"] < 1.0


class TestLaunchAttribution:
    def test_solo_vs_stacked_keys(self):
        reg = MetricsRegistry()
        with use_registry(reg):
            with devprof.stream_scope("solo"):
                devprof.note_launch("xla", time.perf_counter(), None)
            with devprof.wave_scope(["a", "b", "c", "d"]):
                devprof.note_launch(
                    "xla", time.perf_counter(), None, stacked=True
                )
            snap = devprof.devprof_snapshot(calibrate=False)
        assert reg.value(
            "tpudas_devprof_launches_total",
            engine="xla", stacked="0", stream="solo",
        ) == 1.0
        # 1/N per member: the sum over members is ONE launch
        for m in ("a", "b", "c", "d"):
            assert reg.value(
                "tpudas_devprof_launches_total",
                engine="xla", stacked="1", stream=m,
            ) == pytest.approx(0.25)
        keys = {(e["engine"], e["stacked"], e["stream"])
                for e in snap["launches"]}
        assert ("xla", "0", "solo") in keys
        assert ("xla", "1", "a") in keys

    def test_round_collect_deltas(self):
        with use_registry(MetricsRegistry()):
            with devprof.stream_scope("s1"):
                for _ in range(3):
                    devprof.note_launch(
                        "xla", time.perf_counter(), None
                    )
                d1 = devprof.round_collect()
                d2 = devprof.round_collect()
        assert d1["launches"] == 3.0
        assert d1["device_execute_s"] >= 0.0
        assert "utilization" in d1 and "bound" in d1
        # second boundary with no new launches: zero delta
        assert d2["launches"] == 0.0
        assert d2["device_execute_s"] == 0.0

    def test_classification_thresholds(self, monkeypatch):
        """Utilization-first verdict; launch-floor ratio only as the
        no-cost-data fallback."""
        monkeypatch.setenv("TPUDAS_DEVPROF_PEAK_FLOPS", "1e9")
        monkeypatch.setenv("TPUDAS_DEVPROF_PEAK_BYTES", "1e9")
        with use_registry(MetricsRegistry()):
            with devprof.stream_scope("hot"):
                # 1 s of device time explained by 0.9e9 flops at a
                # 1e9 flops/s peak -> utilization 0.9 -> compute_bound
                devprof.note_launch(
                    "xla", time.perf_counter() - 1.0, None,
                    cost={"flops": 0.9e9, "bytes": 0.0},
                )
            with devprof.stream_scope("idle"):
                # same wall, trivial kernel -> utilization ~0
                devprof.note_launch(
                    "xla", time.perf_counter() - 1.0, None,
                    cost={"flops": 1e3, "bytes": 1e3},
                )
            hot = devprof.classify_stream("hot")
            idle = devprof.classify_stream("idle")
        assert hot["bound"] == "compute_bound"
        assert hot["utilization"] == pytest.approx(0.9, abs=0.05)
        assert idle["bound"] == "launch_bound"
        assert idle["utilization"] < 0.01

    def test_disabled_is_total_noop(self, monkeypatch):
        monkeypatch.setenv("TPUDAS_DEVPROF", "0")
        with use_registry(MetricsRegistry()):
            with devprof.stream_scope("off"):
                devprof.note_kernel("k", (8,), ("x",))
                devprof.note_launch("xla", time.perf_counter(), None)
                assert devprof.round_collect() == {}
            stats = devprof.classify_stream("off", calibrate=False)
        assert stats["launches"] == 0.0


class TestFlightRoundtrip:
    def test_round_record_carries_devprof(self, tmp_path):
        folder = str(tmp_path)
        with use_registry(MetricsRegistry()):
            rec = FlightRecorder(folder)
            for i in range(4):
                rec.record(
                    "round", stream="s", round=i,
                    phases={"device_execute": 0.004, "host_wait": 0.006,
                            "read_decode": 0.01},
                    realtime_factor=100.0, head_lag=1.0,
                    devprof={"launches": 2.0,
                             "device_execute_s": 0.004,
                             "bound": "launch_bound",
                             "utilization": 0.3},
                )
            rec.flush()
        rounds = read_flight(folder, kind="round")
        assert len(rounds) == 4
        assert rounds[-1]["devprof"]["bound"] == "launch_bound"
        entry = devprof_entry(rounds)
        assert entry["rounds"] == 4
        assert entry["launches_per_round"] == pytest.approx(2.0)
        assert entry["device_execute_s"] == pytest.approx(0.016)
        assert entry["bound"] == "launch_bound"
        assert entry["utilization"] == pytest.approx(0.3)
        # device-busy fraction = device seconds / phase wall
        assert 0.0 < entry["device_busy_fraction"] <= 1.0

    def test_entry_none_without_devprof_records(self):
        assert devprof_entry([]) is None
        assert devprof_entry([{"kind": "round", "phases": {}}]) is None


class TestEndpoints:
    def test_devprof_endpoint(self, tmp_path):
        with use_registry(MetricsRegistry()):
            with devprof.stream_scope("web"):
                devprof.note_launch("xla", time.perf_counter(), None)
            with start_server(str(tmp_path)) as srv:
                r = urllib.request.urlopen(
                    srv.base_url + "/devprof?calibrate=0", timeout=10
                )
                doc = json.loads(r.read())
        assert r.status == 200
        assert doc["enabled"] is True
        assert "web" in doc["streams"]
        assert doc["streams"]["web"]["launches"] == 1.0
        assert set(doc["calibration"]) >= {
            "launch_floor_s", "util_bound_threshold",
            "launch_ratio_threshold",
        }

    def test_profile_status_bare(self, tmp_path):
        with use_registry(MetricsRegistry()):
            with start_server(str(tmp_path)) as srv:
                r = urllib.request.urlopen(
                    srv.base_url + "/profile", timeout=10
                )
                assert r.status == 200
                assert json.loads(r.read()) is None

    def test_profile_unavailable_is_501(self, tmp_path, monkeypatch):
        monkeypatch.setattr(
            devprof, "profiler_available", lambda: False
        )
        with use_registry(MetricsRegistry()):
            with start_server(str(tmp_path)) as srv:
                with pytest.raises(urllib.error.HTTPError) as exc:
                    urllib.request.urlopen(
                        srv.base_url + "/profile?seconds=1", timeout=10
                    )
        assert exc.value.code == 501
        assert "profiler" in json.loads(exc.value.read())["error"]

    def test_profile_bad_seconds_is_400(self, tmp_path):
        with use_registry(MetricsRegistry()):
            with start_server(str(tmp_path)) as srv:
                with pytest.raises(urllib.error.HTTPError) as exc:
                    urllib.request.urlopen(
                        srv.base_url + "/profile?seconds=nope",
                        timeout=10,
                    )
        assert exc.value.code == 400


class TestProfileShedParity:
    def test_enospc_sheds_profile(self, tmp_path, monkeypatch):
        """A deep capture is a non-essential writer: under disk
        pressure it sheds exactly like the pyramid/prom writers."""
        monkeypatch.setenv("TPUDAS_PROFILE_DIR", str(tmp_path))
        with use_registry(MetricsRegistry()):
            resource.note_pressure("test", None)
            try:
                assert resource.is_degraded()
                with pytest.raises(RuntimeError, match="shed"):
                    devprof.start_profile(1.0)
            finally:
                resource.clear_pressure("test done")

    def test_bad_duration_and_missing_dir(self, monkeypatch):
        monkeypatch.delenv("TPUDAS_PROFILE_DIR", raising=False)
        monkeypatch.delenv("TPUDAS_TRACE_DIR", raising=False)
        with pytest.raises(ValueError, match="seconds"):
            devprof.start_profile(-1.0)
        with pytest.raises(ValueError, match="directory"):
            devprof.start_profile(1.0)


def _load_bench_history():
    spec = importlib.util.spec_from_file_location(
        "bench_history", os.path.join(REPO, "tools", "bench_history.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchHistoryGate:
    def test_gate_passes_and_fails(self, tmp_path):
        bh = _load_bench_history()
        old = {"bench": {"speedup": 4.0, "overhead_pct": 0.5,
                         "rounds": 8}}
        (tmp_path / "BENCH_pr90.json").write_text(json.dumps(old))
        # regression: speedup down 50%, overhead up 4x
        bad = {"bench": {"speedup": 2.0, "overhead_pct": 2.0,
                         "rounds": 8}}
        (tmp_path / "BENCH_pr91.json").write_text(json.dumps(bad))
        cmp_bad = bh.compare_headlines(bad, old, tolerance=0.15)
        assert not cmp_bad["passed"]
        regressed = {r["path"] for r in cmp_bad["regressions"]}
        assert "bench.speedup" in regressed
        assert "bench.overhead_pct" in regressed
        # structural numerics (rounds) are never compared
        assert not any("rounds" in k for k in regressed)
        # within tolerance: passes
        ok = {"bench": {"speedup": 3.8, "overhead_pct": 0.55,
                        "rounds": 8}}
        assert bh.compare_headlines(ok, old, tolerance=0.15)["passed"]

    def test_gate_cli(self, tmp_path):
        import subprocess
        import sys

        old = {"x": {"speedup": 4.0}}
        new = {"x": {"speedup": 4.2}}
        p_old = tmp_path / "BENCH_pr90.json"
        p_new = tmp_path / "BENCH_pr91.json"
        p_old.write_text(json.dumps(old))
        p_new.write_text(json.dumps(new))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "bench_history.py"),
             "--root", str(tmp_path), "--gate", str(p_new)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr + proc.stdout
        assert "PASS" in proc.stdout
