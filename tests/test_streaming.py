"""Streaming drivers: polling, incremental processing, resume-with-
overlap seam-freeness, termination — with injected sleep/clock."""

import os

import numpy as np
import pytest

from tpudas import spool
from tpudas.core.units import s as sec
from tpudas.io.registry import write_patch
from tpudas.core.timeutils import to_datetime64
from tpudas.proc.streaming import (
    clamp_poll_interval,
    run_lowpass_realtime,
    run_rolling_realtime,
)
from tpudas.testing import make_synthetic_spool, synthetic_patch

FS = 100.0
FILE_SEC = 30.0
NCH = 6


def _append_files(directory, start_index, count):
    t0 = to_datetime64("2023-03-22T00:00:00").astype("datetime64[ns]")
    step = np.timedelta64(int(round(1e9 / FS)), "ns")
    n = int(FILE_SEC * FS)
    for i in range(start_index, start_index + count):
        p = synthetic_patch(
            t0=t0 + i * n * step, duration=FILE_SEC, fs=FS, n_ch=NCH,
            seed=i, phase_origin=t0, noise=0.01,
        )
        write_patch(p, os.path.join(directory, f"raw_{i:04d}.h5"))


class TestClamp:
    def test_reference_cadence_guard(self):
        # max(125, requested, file_len, 3x edge buffer) — the 125 s
        # floor is absolute (low_pass_dascore_edge.ipynb:165-173), even
        # when the caller requests a faster cadence
        assert clamp_poll_interval(125, 30, 10) == 125
        assert clamp_poll_interval(125, 300, 10) == 300
        assert clamp_poll_interval(10, 5, 40) == 125.0
        assert clamp_poll_interval(5, 1, 1) == 125.0
        assert clamp_poll_interval(500, 30, 10) == 500.0
        assert clamp_poll_interval(10, 30, 60) == 180.0


class TestCoveredWorkload:
    def test_nan_index_cells_degrade_not_crash(self):
        """A legacy/heterogeneous index row with NaN ntime/ndistance
        must degrade the round metric to zero samples for that file,
        never crash the processing loop (round-2 advisor finding)."""
        import pandas as pd

        from tpudas.proc.streaming import _covered_workload

        t0 = np.datetime64("2023-03-22T00:00:00")
        contents = pd.DataFrame(
            [
                {
                    "time_min": t0,
                    "time_max": t0 + np.timedelta64(30, "s"),
                    "ntime": 3000,
                    "ndistance": 6,
                },
                {
                    "time_min": t0 + np.timedelta64(30, "s"),
                    "time_max": t0 + np.timedelta64(60, "s"),
                    "ntime": float("nan"),
                    "ndistance": float("nan"),
                },
                {
                    "time_min": t0 + np.timedelta64(60, "s"),
                    "time_max": t0 + np.timedelta64(90, "s"),
                    "ntime": None,
                    "ndistance": 6,
                },
            ]
        )
        data_sec, samples = _covered_workload(
            contents, t0, t0 + np.timedelta64(90, "s")
        )
        assert np.isfinite(samples)
        int(samples)  # what the realtime loop does with it
        assert data_sec == 90.0
        # only the well-formed first file contributes samples
        assert samples == pytest.approx(30.0 * (2999 / 30.0) * 6)


class TestLowpassRealtime:
    def test_rounds_resume_and_terminate(self, tmp_path):
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH, noise=0.01
        )
        # feed two more files between rounds via the injected sleep
        state = {"fed": 0}

        def fake_sleep(_):
            if state["fed"] < 1:
                _append_files(src, 3, 2)
                state["fed"] += 1

        rounds = run_lowpass_realtime(
            source=src,
            output_folder=out,
            start_time="2023-03-22T00:00:00",
            output_sample_interval=1.0,
            edge_buffer=8.0,
            process_patch_size=40,
            poll_interval=0.0,
            file_duration=0.0,
            sleep_fn=fake_sleep,
        )
        assert rounds == 2  # initial + one resume, then clean termination
        merged = spool(out).update().chunk(time=None)
        assert len(merged) == 1  # resumed output is seam-free
        p = merged[0]
        steps = np.diff(p.coords["time"].astype(np.int64))
        assert np.all(steps == 1_000_000_000)
        # covers (nearly) the whole 150 s stream minus edges
        assert p.shape[0] > 120

    @pytest.mark.slow
    def test_fractional_dt_resume_is_seam_free(self, tmp_path):
        # regression: the resume rewind must stay on the output grid
        # for non-integer-second output intervals
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=2, file_duration=FILE_SEC, fs=FS, n_ch=NCH, noise=0.01
        )
        state = {"fed": 0}

        def fake_sleep(_):
            if state["fed"] < 1:
                _append_files(src, 2, 1)
                state["fed"] += 1

        rounds = run_lowpass_realtime(
            source=src,
            output_folder=out,
            start_time="2023-03-22T00:00:00",
            output_sample_interval=0.5,
            edge_buffer=8.0,
            process_patch_size=60,
            poll_interval=0.0,
            sleep_fn=fake_sleep,
        )
        assert rounds == 2
        merged = spool(out).update().chunk(time=None)
        assert len(merged) == 1
        steps = np.diff(merged[0].coords["time"].astype(np.int64))
        assert np.all(steps == 500_000_000)

    @pytest.mark.slow
    def test_engine_and_gap_params_plumbed_with_rt_events(self, tmp_path):
        # VERDICT r1 weak #4: the streaming driver must reach the
        # cascade engine and report per-round real-time factor
        from tpudas.utils.logging import set_log_handler
        from tpudas.utils.profiling import Counters

        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        events = []
        set_log_handler(events.append)
        counters = Counters()
        try:
            rounds = run_lowpass_realtime(
                src,
                out,
                "2023-03-22T00:00:00",
                output_sample_interval=1.0,
                edge_buffer=10.0,
                process_patch_size=40,
                sleep_fn=lambda _: None,
                max_rounds=3,
                engine="cascade",
                on_gap="split",
                filter_order=6,
                counters=counters,
            )
        finally:
            set_log_handler(None)
        assert rounds >= 1
        # ground truth: the cascade engine actually ran the windows
        # (window_engine now names the sub-engine: cascade-xla on CPU)
        ran = [e for e in events if e["event"] == "window_engine"]
        assert ran and all(e["engine"] == "cascade-xla" for e in ran)
        # per-round real-time factor is reported and accumulated
        rts = [
            e for e in events if e["event"] == "realtime_round"
        ]
        assert rts and all(e["realtime_factor"] > 0 for e in rts)
        assert all(e["engine"] == "cascade" for e in rts)
        # engine_counts ride along each round event (ground truth for
        # operators without the log handler)
        assert all(
            sum(e["engine_counts"].values()) > 0
            and e["engine_counts"]["fft"] == 0
            for e in rts
        )
        assert counters.realtime_factor > 0
        assert counters.wall_seconds > 0

    def test_empty_source_terminates_with_max_rounds(self, tmp_path):
        src = tmp_path / "empty_raw"
        src.mkdir()
        rounds = run_lowpass_realtime(
            source=str(src),
            output_folder=str(tmp_path / "out"),
            start_time="2023-03-22T00:00:00",
            output_sample_interval=1.0,
            edge_buffer=8.0,
            process_patch_size=40,
            poll_interval=0.0,
            sleep_fn=lambda _: None,
            max_rounds=3,
        )
        assert rounds == 0

    def test_max_rounds_cap(self, tmp_path):
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH
        )
        rounds = run_lowpass_realtime(
            source=src,
            output_folder=out,
            start_time="2023-03-22T00:00:00",
            output_sample_interval=1.0,
            edge_buffer=8.0,
            process_patch_size=40,
            poll_interval=0.0,
            sleep_fn=lambda _: None,
            max_rounds=1,
        )
        assert rounds == 1


class TestRollingRealtime:
    def test_processes_only_new_patches(self, tmp_path):
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=2, file_duration=FILE_SEC, fs=FS, n_ch=NCH
        )
        state = {"fed": 0}

        def fake_sleep(_):
            if state["fed"] < 1:
                _append_files(src, 2, 1)
                state["fed"] += 1

        rounds = run_rolling_realtime(
            source=src,
            output_folder=out,
            window=1.0 * sec,
            step=1.0 * sec,
            scale=2.0,
            poll_interval=0.0,
            sleep_fn=fake_sleep,
        )
        assert rounds == 2
        outs = [f for f in os.listdir(out) if f.endswith(".h5")]
        assert len(outs) == 3  # one output file per input patch
        # stateless per-file: each output has its own NaN warm-up row
        for p in spool(out).update():
            host = p.host_data()
            assert np.isnan(host[0]).all() and np.isfinite(host[1:]).all()

    def test_out_of_order_arrival_still_processed(self, tmp_path):
        # regression: a late-arriving file with an EARLIER timestamp
        # must be processed (positional high-water marks skip it)
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        os.makedirs(src)
        _append_files(src, 2, 1)  # only the third file exists initially
        state = {"fed": 0}

        def fake_sleep(_):
            if state["fed"] < 1:
                _append_files(src, 0, 2)  # earlier files arrive late
                state["fed"] += 1

        rounds = run_rolling_realtime(
            source=src,
            output_folder=out,
            window=1.0 * sec,
            step=1.0 * sec,
            poll_interval=0.0,
            sleep_fn=fake_sleep,
        )
        assert rounds == 2
        outs = [f for f in os.listdir(out) if f.endswith(".h5")]
        assert len(outs) == 3  # all three inputs processed exactly once


class TestTerminationAndRecovery:
    def test_empty_source_terminates_without_max_rounds(self, tmp_path):
        """A source that never produces files must end the loop on the
        second empty poll, not spin forever (reference semantics: the
        loop ends when the spool stops growing)."""
        src = tmp_path / "raw"
        src.mkdir()
        polls = {"n": 0}

        def guarded_sleep(_):
            polls["n"] += 1
            if polls["n"] > 5:
                raise AssertionError("realtime loop failed to terminate")

        rounds = run_lowpass_realtime(
            source=str(src),
            output_folder=str(tmp_path / "out"),
            start_time="2023-03-22T00:00:00",
            output_sample_interval=1.0,
            edge_buffer=5.0,
            process_patch_size=40,
            poll_interval=0.0,
            sleep_fn=guarded_sleep,
        )
        assert rounds == 0

    def test_resume_after_round_with_no_output(self, tmp_path):
        """A round that completes without emitting files (stream still
        behind start_time) must not crash the next round's resume — it
        retries from start_time instead (crash-only contract)."""
        src = str(tmp_path / "raw")
        out = str(tmp_path / "out")
        # file 0 covers 00:00:00-00:00:30, far before start_time
        make_synthetic_spool(
            src, n_files=1, file_duration=FILE_SEC, fs=FS, n_ch=NCH
        )

        def feed_late(_):
            # file at index 20 covers 00:10:00-00:10:30 (= start_time)
            if not any(f.startswith("raw_0020") for f in os.listdir(src)):
                _append_files(src, 20, 1)

        rounds = run_lowpass_realtime(
            source=src,
            output_folder=out,
            start_time="2023-03-22T00:10:00",
            output_sample_interval=1.0,
            edge_buffer=3.0,
            process_patch_size=20,
            poll_interval=0.0,
            sleep_fn=feed_late,
        )
        assert rounds == 2
        produced = [f for f in os.listdir(out) if f.endswith(".h5")]
        assert produced  # the second round recovered and emitted output


class TestJointRealtime:
    @pytest.mark.slow
    def test_joint_streaming_rolls_and_resumes(self, tmp_path):
        """The realtime loop with a rolling_output_folder emits BOTH
        products each round (config 5, streaming form); across resumed
        rounds the rolling product stays seam-free and matches a batch
        JointProc run over the full stream interior."""
        from tpudas.proc.joint import JointProc

        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        roll = str(tmp_path / "rolling")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        state = {"fed": 0}

        def fake_sleep(_):
            if state["fed"] < 1:
                _append_files(src, 3, 2)
                state["fed"] += 1

        rounds = run_lowpass_realtime(
            source=src,
            output_folder=out,
            start_time="2023-03-22T00:00:00",
            output_sample_interval=1.0,
            edge_buffer=8.0,
            process_patch_size=40,
            poll_interval=0.0,
            file_duration=0.0,
            sleep_fn=fake_sleep,
            rolling_output_folder=roll,
            rolling_window=3.0,
            rolling_step=1.0,
        )
        assert rounds == 2
        merged = spool(roll).update().chunk(time=None)
        assert len(merged) == 1, "streamed rolling product has a seam"
        got = merged[0]
        assert np.isfinite(got.host_data()).all()
        steps = np.diff(got.coords["time"].astype(np.int64))
        assert np.all(steps == 1_000_000_000)
        # batch joint run over the same (final) stream for comparison
        jp = JointProc(spool(src).sort("time").update())
        jp.update_processing_parameter(
            output_sample_interval=1.0,
            process_patch_size=40,
            edge_buff_size=8,
            rolling_window=3.0,
            rolling_step=1.0,
        )
        jp.set_output_folder(str(tmp_path / "blf"), delete_existing=True)
        jp.set_rolling_output_folder(
            str(tmp_path / "broll"), delete_existing=True
        )
        jp.process_time_range(
            np.datetime64("2023-03-22T00:00:00"),
            np.datetime64(
                spool(src).update().get_contents()["time_max"].max()
            ),
        )
        ref = spool(str(tmp_path / "broll")).update().chunk(time=None)[0]
        ta, tb = got.coords["time"], ref.coords["time"]
        lo, hi = max(ta[0], tb[0]), min(ta[-1], tb[-1])
        a = got.select(time=(lo, hi)).host_data()
        b = ref.select(time=(lo, hi)).host_data()
        assert a.shape == b.shape
        assert np.abs(a - b).max() < 1e-6 * np.abs(b).max() + 1e-7

    def test_rolling_params_without_folder_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="rolling_output_folder"):
            run_lowpass_realtime(
                source=str(tmp_path),
                output_folder=str(tmp_path / "out"),
                start_time="2023-03-22T00:00:00",
                output_sample_interval=1.0,
                edge_buffer=8.0,
                process_patch_size=40,
                rolling_window=3.0,
            )
