"""tpudas.store.replica: the replicated object-store plane (ISSUE 20).

Primary + N-mirror composition through ``store_from_url``, the
write-through fan-out with its crc-stamped hinted-handoff journal
(idempotent token-compare drain, zero re-uploads), CAS pinned to the
primary, the read failover ladder (primary → mirrors → the NVMe
cache's stale-but-verified rung, divergence counted and never
silently served), the anti-entropy scrubber, primary promotion, and
the in-process replication drill smoke.
"""

import json
import os
import sys

import pytest

from tpudas.integrity.checksum import stamp_json
from tpudas.obs.registry import MetricsRegistry, use_registry
from tpudas.store import (
    CASConflictError,
    FakeObjectStore,
    ObjectNotFoundError,
    ReadThroughCache,
    ReplicatedStore,
    RetryingStore,
    StoreError,
    StoreNetworkError,
    find_replicated,
    store_from_url,
)
from tpudas.store.replica import HandoffJournal, ScrubLoop, promote

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _registry():
    return MetricsRegistry()


def _replicated(tmp_path, n_mirrors=2):
    """(repl, raw_fakes): a ReplicatedStore over bare fakes (no retry
    wrapper — faults must fire exactly once) with a journal in
    tmp_path."""
    raws = [FakeObjectStore() for _ in range(n_mirrors + 1)]
    repl = ReplicatedStore(
        raws[0], raws[1:], journal_dir=str(tmp_path / "journal")
    )
    return repl, raws


class TestComposition:
    def test_store_from_url_replica_spec(self, tmp_path):
        url = (
            f"replica:fake:tsr-p,file://{tmp_path}/m1,fake:tsr-m2"
        )
        store = store_from_url(url)
        assert isinstance(store, ReplicatedStore)
        assert find_replicated(store) is store
        # members are individually retry-wrapped; the composite is not
        assert isinstance(store.primary, RetryingStore)
        assert all(isinstance(m, RetryingStore) for m in store.mirrors)
        assert len(store.mirrors) == 2
        assert store.backend.startswith("replica(")

    def test_replica_spec_needs_two_members(self):
        with pytest.raises(StoreError):
            store_from_url("replica:fake:only-one")

    def test_find_replicated_through_wrappers(self, tmp_path):
        repl, _ = _replicated(tmp_path)
        assert find_replicated(repl) is repl
        assert find_replicated(FakeObjectStore()) is None
        assert find_replicated(None) is None

    def test_journal_dir_env(self, tmp_path, monkeypatch):
        jd = tmp_path / "env-journal"
        monkeypatch.setenv("TPUDAS_REPLICA_JOURNAL", str(jd))
        store = store_from_url("replica:fake:tje-p,fake:tje-m")
        assert store.journal.dir == str(jd)
        assert os.path.isdir(str(jd))


class TestWriteFanOut:
    def test_put_reaches_every_replica(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        token = repl.put("a/k", b"payload")
        for raw in raws:
            assert raw.get("a/k") == (b"payload", token)
        assert repl.verify_identical()

    def test_delete_fans_out(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        repl.put("a/k", b"x")
        assert repl.delete("a/k") is True
        for raw in raws:
            assert raw.head("a/k") is None

    def test_down_mirror_journals_not_fails(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        rule = raws[1].injector.partition()
        with use_registry(_registry()) as reg:
            token = repl.put("a/k", b"x")
            assert reg.counter(
                "tpudas_store_replica_handoff_journaled_total", "",
                labelnames=("mirror",),
            ).value(mirror="m0") == 1
        assert token == repl.token_for(b"x")  # caller unaffected
        assert raws[0].get("a/k")[0] == b"x"  # primary landed
        assert raws[2].get("a/k")[0] == b"x"  # healthy mirror landed
        assert repl.journal.pending(0, "a/k")
        assert repl.journal.pending_counts() == {0: 1, 1: 0}
        raws[1].injector.heal(rule)

    def test_drain_is_idempotent_by_token(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        rule = raws[1].injector.partition()
        repl.put("a/k", b"x")
        repl.put("a/j", b"y")
        raws[1].injector.heal(rule)
        # the mirror already holds one key's exact bytes (an earlier
        # drain that crashed after copying, say): zero re-uploads
        raws[1].put("a/k", b"x")
        drained = repl.drain_handoff()
        assert drained["copied"] == 1
        assert drained["already_synced"] == 1
        assert drained["failed"] == 0
        assert repl.journal.pending_counts() == {0: 0, 1: 0}
        # and a second drain has nothing at all to do
        assert all(
            v == 0 for v in repl.drain_handoff().values()
        )
        assert repl.verify_identical()

    def test_drain_of_deleted_key_deletes_mirror_copy(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        repl.put("a/k", b"x")
        rule = raws[1].injector.partition()
        repl.delete("a/k")
        raws[1].injector.heal(rule)
        drained = repl.drain_handoff()
        assert drained["deleted"] == 1
        assert raws[1].head("a/k") is None
        assert repl.verify_identical()

    def test_drain_against_still_down_mirror_keeps_entry(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        raws[1].injector.partition()
        repl.put("a/k", b"x")
        drained = repl.drain_handoff()
        assert drained["failed"] == 1
        assert repl.journal.pending(0, "a/k")  # still owed


class TestCASPinning:
    def test_cas_commits_on_primary_then_mirrors_catch_up(
            self, tmp_path):
        repl, raws = _replicated(tmp_path)
        token = repl.put_if("m/lease", b"mine", if_absent=True)
        assert raws[0].get("m/lease") == (b"mine", token)
        # mirrors got the post-CAS bytes as plain copies
        for raw in raws[1:]:
            assert raw.get("m/lease")[0] == b"mine"
        with pytest.raises(CASConflictError):
            repl.put_if("m/lease", b"rival", if_absent=True)

    def test_cas_conflict_never_touches_mirrors(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        repl.put_if("m/lease", b"mine", if_absent=True)
        with pytest.raises(CASConflictError):
            repl.put_if("m/lease", b"rival", if_absent=True)
        for raw in raws:
            assert raw.get("m/lease")[0] == b"mine"

    def test_cas_with_primary_down_is_unavailable_not_split_brain(
            self, tmp_path):
        """While the primary is unreachable, coordination is DOWN —
        a mirror never takes the CAS, so two sides of a partition
        cannot both win a lease."""
        repl, raws = _replicated(tmp_path)
        raws[0].injector.partition()
        with pytest.raises(StoreNetworkError):
            repl.put_if("m/lease", b"mine", if_absent=True)
        for raw in raws[1:]:
            assert raw.head("m/lease") is None

    def test_mirror_down_during_cas_journals_the_copy(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        rule = raws[1].injector.partition()
        repl.put_if("m/lease", b"mine", if_absent=True)
        assert repl.journal.pending(0, "m/lease")
        raws[1].injector.heal(rule)
        assert repl.drain_handoff()["copied"] == 1
        assert raws[1].get("m/lease")[0] == b"mine"


class TestReadLadder:
    def test_absence_from_primary_is_definitive(self, tmp_path):
        repl, _raws = _replicated(tmp_path)
        with pytest.raises(ObjectNotFoundError):
            repl.get("a/missing")
        assert repl.head("a/missing") is None
        assert repl.exists("a/missing") is False

    def test_failover_to_mirror_counted(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        repl.put("a/k", b"x")
        healthy = repl.get("a/k")
        raws[0].injector.partition()
        with use_registry(_registry()) as reg:
            assert repl.get("a/k") == healthy  # byte-identical
            assert repl.head("a/k") == healthy[1]
            assert repl.list("a") == ["a/k"]
            assert reg.counter(
                "tpudas_store_replica_failover_reads_total", "",
                labelnames=("op", "backend"),
            ).value(op="get", backend="fake") == 1

    def test_known_behind_mirror_skipped_divergence_counted(
            self, tmp_path):
        """A mirror owed a journal entry for the key is known
        divergent: the ladder must skip it, not serve its stale
        bytes."""
        repl, raws = _replicated(tmp_path)
        repl.put("a/k", b"v1")
        rule = raws[1].injector.partition()
        repl.put("a/k", b"v2")  # mirror 0 still holds v1
        raws[1].injector.heal(rule)
        raws[0].injector.partition()  # now force the ladder down
        with use_registry(_registry()) as reg:
            data, _tok = repl.get("a/k")
            assert data == b"v2"  # mirror 1 (in sync), NOT mirror 0
            assert reg.counter(
                "tpudas_store_replica_divergence_total", "",
                labelnames=("why",),
            ).value(why="journal_pending") == 1

    def test_mirror_missing_key_is_not_absence(self, tmp_path):
        """Primary down + a mirror that never got the key: the ladder
        keeps descending (another mirror may hold it) and, when no
        rung can serve, reports UNAVAILABLE — never 'not found' from
        a replica that may be behind."""
        repl, raws = _replicated(tmp_path)
        repl.put("a/k", b"x")
        raws[1]._objects.pop("a/k")  # silently lost on mirror 0
        raws[0].injector.partition()
        assert repl.get("a/k")[0] == b"x"  # mirror 1 serves
        raws[2].injector.partition()
        with pytest.raises(StoreNetworkError):
            repl.get("a/k")
        with pytest.raises(StoreNetworkError):
            repl.head("a/k")

    def test_torn_debris_unioned_across_replicas(self, tmp_path):
        from tpudas.store import FaultInjector, FaultRule

        repl, raws = _replicated(tmp_path, n_mirrors=1)
        raws[1].injector.add(
            FaultRule(kind="torn", op="put", match="a/")
        )
        repl.put("a/k", b"x")  # mirror's copy tears -> journaled
        assert repl.list_uploads() == ["a/k"]
        assert repl.abort_upload("a/k") is True
        assert repl.list_uploads() == []


class TestCacheLadderUnderReplication:
    """Satellite 4: every rung of primary → mirror → NVMe
    stale-but-verified serves byte-identical data and is counted
    distinctly."""

    def _rig(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        cache = ReadThroughCache(str(tmp_path / "cache"))
        repl.put("t/obj", b"tile-bytes")
        return repl, raws, cache

    def test_three_rungs_byte_identical_and_counted(self, tmp_path):
        repl, raws, cache = self._rig(tmp_path)
        with use_registry(_registry()) as reg:
            # rung 1: primary serves (cache miss -> fetch)
            healthy = cache.get_through(repl, "t/obj")
            assert healthy[0] == b"tile-bytes"
            # rung 2: primary severed -> mirror serves, cache reuses
            # the probe path; bytes identical
            raws[0].injector.partition()
            cache.invalidate_prefix("t")  # force a real refetch
            assert cache.get_through(repl, "t/obj") == healthy
            failovers = reg.counter(
                "tpudas_store_replica_failover_reads_total", "",
                labelnames=("op", "backend"),
            )
            # head probe + get both failed over
            assert failovers.value(op="get", backend="fake") >= 1
            # rung 3: EVERYTHING severed -> the cache's verified copy
            for raw in raws[1:]:
                raw.injector.partition()
            stale = cache.get_through(repl, "t/obj")
            assert stale == healthy
            assert cache.degraded() is True
            assert reg.counter(
                "tpudas_store_cache_stale_served_total", ""
            ).value() >= 1
            # heal -> the ladder comes back up, cache un-degrades
            for raw in raws:
                raw.injector.heal(None)
            assert cache.get_through(repl, "t/obj") == healthy
            assert cache.degraded() is False

    def test_no_rung_never_serves_silently_wrong(self, tmp_path):
        """A key the cache has never verified + every replica down =
        an error, not a fabrication."""
        repl, raws, cache = self._rig(tmp_path)
        for raw in raws:
            raw.injector.partition()
        with pytest.raises(StoreNetworkError):
            cache.get_through(repl, "t/obj")


class TestJournal:
    def test_lines_are_crc_stamped_and_torn_tail_skipped(
            self, tmp_path):
        j = HandoffJournal(str(tmp_path / "j"), 1)
        j.record(0, "a/k", "put", "deadbeef-3")
        path = j._my_file(0)
        with open(path) as fh:
            obj = json.loads(fh.readline())
        assert "_crc32" in obj
        # torn tail: a half-written line and garbage must not poison
        # the fold
        with open(path, "a") as fh:
            fh.write('{"key": "a/torn", "op": "pu')
        with open(path, "a") as fh:
            fh.write("\nnot json at all\n")
        pending = HandoffJournal(str(tmp_path / "j"), 1).load_pending(0)
        assert list(pending) == ["a/k"]

    def test_tampered_line_rejected(self, tmp_path):
        j = HandoffJournal(str(tmp_path / "j"), 1)
        entry = {"key": "a/evil", "op": "put", "token": None, "ts": 0}
        stamped = stamp_json(dict(entry))
        stamped["key"] = "a/other"  # bytes no longer match the stamp
        with open(j._my_file(0), "a") as fh:
            fh.write(json.dumps(stamped) + "\n")
        assert HandoffJournal(
            str(tmp_path / "j"), 1
        ).load_pending(0) == {}

    def test_folds_other_processes_files(self, tmp_path):
        """A worker that died mid-debt leaves m<i>-<pid>.jsonl behind;
        any other process's drain must see those entries."""
        jdir = str(tmp_path / "j")
        dead = HandoffJournal(jdir, 1)
        dead.record(0, "a/dead", "put", "cafebabe-4")
        # pose as a DIFFERENT process: rename the file to a foreign pid
        os.rename(
            dead._my_file(0), os.path.join(jdir, "m0-99999.jsonl")
        )
        mine = HandoffJournal(jdir, 1)
        assert "a/dead" in mine.load_pending(0)
        mine.clear(0, ["a/dead"])
        assert mine.load_pending(0) == {}
        # the foreign file was compacted away
        assert not os.path.exists(os.path.join(jdir, "m0-99999.jsonl"))

    def test_last_entry_per_key_wins(self, tmp_path):
        j = HandoffJournal(str(tmp_path / "j"), 1)
        j.record(0, "a/k", "put", "11111111-1")
        j.record(0, "a/k", "delete", None)
        pending = j.load_pending(0)
        assert pending["a/k"]["op"] == "delete"


class TestScrubAndPromotion:
    def test_scrub_repairs_missing_mismatch_extra(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        repl.put("a/k1", b"one")
        repl.put("a/k2", b"two")
        # fabricate divergence BEHIND the journal's back (a crashed
        # worker whose journal never made it to disk)
        raws[1]._objects.pop("a/k1")              # missing
        raws[1]._objects["a/k2"] = b"stale"       # mismatch
        raws[2]._objects["a/extra"] = b"lost"     # primary lost it
        report = repl.scrub("", repair=True)
        assert report["clean"]
        # the restored "a/extra" is then copied to the OTHER mirror too,
        # so it shows up once as "restored" and once as "missing"
        assert report["repairs"] == {
            "missing": 2, "mismatch": 1, "restored": 1,
            "torn_swept": 0,
        }
        assert repl.verify_identical()
        assert raws[0].get("a/extra")[0] == b"lost"  # restored

    def test_scrub_no_repair_reports_only(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        repl.put("a/k", b"x")
        raws[1]._objects.pop("a/k")
        report = repl.scrub("", repair=False)
        assert not report["clean"]
        assert report["matrix"][0]["missing"] == 1
        assert report["matrix"][0]["repaired"] == 0
        assert raws[1].head("a/k") is None  # untouched

    def test_scrub_sweeps_torn_debris_everywhere(self, tmp_path):
        repl, raws = _replicated(tmp_path, n_mirrors=1)
        raws[1]._uploads.add("a/torn")
        report = repl.scrub("", repair=True)
        assert report["repairs"]["torn_swept"] == 1
        assert raws[1].list_uploads() == []
        assert report["clean"]

    def test_scrub_unreachable_mirror_not_clean(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        raws[1].injector.partition()
        repl.put("a/k", b"x")
        report = repl.scrub("", repair=True)
        assert not report["clean"]
        assert report["matrix"][0]["unreachable"]
        assert not report["matrix"][1]["unreachable"]

    def test_scrub_runs_in_background_loop(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        repl.put("a/k", b"x")
        raws[1]._objects.pop("a/k")
        loop = ScrubLoop(repl, interval_s=0.02).start()
        try:
            deadline = 200
            while loop.last_report is None and deadline:
                import time

                time.sleep(0.01)
                deadline -= 1
            assert loop.last_report is not None
            assert repl.verify_identical()
        finally:
            loop.stop()

    def test_promote_reconciles_onto_target(self, tmp_path):
        """DR: the primary is LOST; the chosen mirror absorbs what
        the other survivors hold, keeps its own copy on conflicts."""
        repl, raws = _replicated(tmp_path)
        repl.put("a/common", b"everywhere")
        # mirror 1 (raws[2]) saw a write mirror 0 missed, and they
        # disagree on one key
        raws[2]._objects["a/late"] = b"only-on-m1"
        raws[1]._objects["a/contested"] = b"target-copy"
        raws[2]._objects["a/contested"] = b"other-copy"
        report = promote(raws[1], [raws[2]])
        assert report["copied"] == 1  # a/late came over
        assert raws[1].get("a/late")[0] == b"only-on-m1"
        assert report["conflicts_total"] == 1
        assert raws[1].get("a/contested")[0] == b"target-copy"  # kept

    def test_promote_sweeps_target_debris(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        raws[1]._uploads.add("a/torn")
        report = promote(raws[1], [raws[2]])
        assert report["torn_swept"] == 1

    def test_audit_backfill_store_carries_replication_block(
            self, tmp_path):
        """fsck --store with a replica: URL folds the scrub verdict
        into clean."""
        from tpudas.backfill.objqueue import plan_backfill_store
        from tpudas.integrity.audit import audit_backfill_store
        from tpudas.testing import make_synthetic_spool

        import numpy as np

        src = str(tmp_path / "src")
        make_synthetic_spool(
            src, n_files=2, file_duration=20.0, fs=50.0, n_ch=4,
            noise=0.01, start=np.datetime64("2023-03-22T00:00:00"),
        )
        repl, raws = _replicated(tmp_path)
        plan_backfill_store(
            repl, "job", src, "2023-03-22T00:00:00",
            "2023-03-22T00:00:40", shard_seconds=40.0,
            output_sample_interval=1.0, edge_buffer=5.0,
            process_patch_size=20,
        )
        raws[1]._objects.pop(sorted(raws[1]._objects)[0])  # diverge
        report = audit_backfill_store(repl, "job", repair=True)
        assert "replication" in report
        assert report["replication"]["clean"]
        assert report["clean"]
        assert repl.verify_identical()

    def test_snapshot_shape(self, tmp_path):
        repl, raws = _replicated(tmp_path)
        raws[1].injector.partition()
        repl.put("a/k", b"x")
        repl.scrub("", repair=False)
        snap = repl.snapshot()
        assert snap["mirrors"] == ["fake", "fake"]
        assert snap["handoff_pending"] == {0: 1, 1: 0}
        assert snap["last_scrub"]["clean"] is False
        assert "failover_reads" in snap and "divergence" in snap


class TestReplicaDrillSmoke:
    def test_in_process_replica_drill(self, tmp_path):
        """Tier-1 smoke of the full story: sever one mirror mid-job,
        drain the job with two workers, heal, drain the journal,
        scrub — replica trees byte-identical to a single-store
        control, zero re-uploads, zero CAS commits lost or doubled."""
        from tools.backfill_drill import run_replica_drill

        rep = run_replica_drill(
            shards=2, workers=2, workdir=str(tmp_path / "drill")
        )
        assert rep["ok"], {
            k: v for k, v in rep.items() if k != "workdir"
        }

    @pytest.mark.slow
    def test_subprocess_replica_drill(self, tmp_path):
        """The full subprocess matrix: SIGKILLs + a posix mirror
        severed for the kill window (out of the tier-1 budget)."""
        from tools.backfill_drill import run_store_backfill_drill

        rep = run_store_backfill_drill(
            workers=2, kills=2, shards=2, replicas=2,
            workdir=str(tmp_path / "drill"),
        )
        assert rep["ok"], {
            k: v for k, v in rep.items() if k != "workdir"
        }
        assert rep["replication"]["replicas_identical"]
