"""tools/check_excepts.py wired into tier-1: no NEW silent broad
``except`` blocks can land — a handler that catches Exception and
neither re-raises nor logs must be allowlisted with a justification
(tools/except_allowlist.txt)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_excepts  # noqa: E402


def test_repo_is_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_excepts.py")],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "check_excepts: OK" in proc.stdout


def _lint(src, allowed=()):
    return check_excepts.lint_source("f.py", src, set(allowed))


def test_silent_broad_handler_flagged():
    src = "def g():\n    try:\n        x()\n    except Exception:\n        pass\n"
    problems = _lint(src)
    assert problems and "silent broad except" in problems[0]
    assert "f.py::g" in problems[0]


def test_bare_and_baseexception_and_tuple_flagged():
    assert _lint("try:\n    x()\nexcept:\n    pass\n")
    assert _lint("try:\n    x()\nexcept BaseException:\n    pass\n")
    assert _lint("try:\n    x()\nexcept (ValueError, Exception):\n    a = 1\n")


def test_narrow_handler_not_flagged():
    assert _lint("try:\n    x()\nexcept (OSError, ValueError):\n    pass\n") == []


def test_reraise_and_logging_not_flagged():
    assert _lint("try:\n    x()\nexcept Exception:\n    raise\n") == []
    assert _lint(
        "try:\n    x()\nexcept Exception as e:\n    log_event('x', err=e)\n"
    ) == []
    assert _lint(
        "try:\n    x()\nexcept Exception:\n    print('boom')\n"
    ) == []
    assert _lint(
        "try:\n    x()\nexcept Exception:\n"
        "    reg.counter('tpudas_x_total').inc()\n"
    ) == []
    # conditional re-raise deep in the body still counts
    assert _lint(
        "try:\n    x()\nexcept Exception as e:\n"
        "    if bad(e):\n        raise\n"
    ) == []


def test_allowlist_keyed_by_qualname():
    src = (
        "class C:\n"
        "    def m(self):\n"
        "        try:\n"
        "            x()\n"
        "        except Exception:\n"
        "            pass\n"
    )
    assert _lint(src)
    assert _lint(src, allowed={"f.py::C.m"}) == []


def test_module_level_handler_qualname():
    src = "try:\n    x()\nexcept Exception:\n    pass\n"
    problems = _lint(src)
    assert problems and "f.py::<module>" in problems[0]
    assert _lint(src, allowed={"f.py::<module>"}) == []
