"""tpudas.codec + the compressed serve stack (ISSUE 11).

Covers the acceptance set: codec roundtrip property tests (lossless
byte-exact, lossy within its ``max_error`` bound, NaN-gap blocks,
empty/partial tiles), the compressed tile store (chunked == one-shot
== raw for lossless codecs, deterministic lossy builds, crashed-append
resume, mixed raw+compressed stores, ``TPUDAS_CODEC``), HTTP caching
(strong ETags, conditional GET/304, ``Cache-Control: immutable`` on
full-tile windows, ``Accept-Encoding`` negotiation, the ``/tile``
endpoint), byte-identical ``/query``/``/waterfall`` responses between
a compressed and a raw store, fsck repair of torn compressed tiles,
and the SO_REUSEPORT worker pool's shared data port + merged control
plane.
"""

import glob
import io
import json
import os
import shutil
import urllib.error
import urllib.request
import zlib

import numpy as np
import pytest

from tpudas.codec import (
    CodecError,
    codec_ids,
    decode_tile,
    encode_tile,
    get_codec,
    parse_codec_spec,
    read_tile_header,
    verify_tile_blob,
)
from tpudas.core.timeutils import to_datetime64
from tpudas.integrity.audit import audit
from tpudas.io.registry import write_patch
from tpudas.obs.registry import MetricsRegistry, use_registry
from tpudas.serve.query import QueryEngine
from tpudas.serve.tiles import TileStore, rebuild_pyramid, sync_pyramid
from tpudas.testing import synthetic_patch

T0 = "2023-03-22T00:00:00"
LOSSLESS = tuple(c for c in codec_ids() if get_codec(c).lossless)
LOSSY = tuple(c for c in codec_ids() if not get_codec(c).lossless)

# the roundtrip matrix's shape vocabulary: a full level-0 tile, a
# coarse (3, rows, ch) aggregate stack, a partial tile, a single row,
# and the empty tile
SHAPES = [(64, 16), (3, 32, 8), (5, 3), (1, 7), (0, 4)]


def _grid(n):
    t0 = to_datetime64(T0).astype("datetime64[ns]")
    return t0 + np.arange(n) * np.timedelta64(1, "s")


def _tile_data(shape, seed, nan_block=True):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal(shape).astype(np.float32)
    if nan_block and a.size:
        a.flat[:: max(a.size // 7, 1)] = np.nan
    return a


class TestCodecRoundtrip:
    @pytest.mark.parametrize("codec", LOSSLESS)
    @pytest.mark.parametrize("shape", SHAPES)
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_lossless_byte_exact(self, codec, shape, seed):
        a = _tile_data(shape, seed)
        blob = encode_tile(a, codec)
        assert verify_tile_blob(blob) == "ok"
        d = decode_tile(blob)
        assert d.dtype == a.dtype and d.shape == a.shape
        assert d.tobytes() == a.tobytes()

    @pytest.mark.parametrize("codec", LOSSLESS)
    def test_lossless_int_dtypes(self, codec):
        rng = np.random.default_rng(3)
        for dtype in (np.int16, np.int32, np.float64):
            a = rng.integers(-1000, 1000, (33, 9)).astype(dtype)
            assert decode_tile(encode_tile(a, codec)).tobytes() == (
                a.tobytes()
            )

    @pytest.mark.parametrize("codec", LOSSY)
    @pytest.mark.parametrize("max_error", [1e-1, 1e-3, 1e-5])
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_lossy_within_bound(self, codec, max_error, seed):
        a = _tile_data((48, 12), seed)
        blob = encode_tile(a, codec, max_error=max_error)
        d = decode_tile(blob)
        assert d.dtype == a.dtype and d.shape == a.shape
        # NaN gaps survive EXACTLY — gap honesty is not negotiable
        assert (np.isnan(d) == np.isnan(a)).all()
        fin = np.isfinite(a)
        assert np.abs(d[fin] - a[fin]).max() <= max_error

    @pytest.mark.parametrize("codec", LOSSY)
    def test_lossy_edge_tiles(self, codec):
        # all-NaN block (a pure data gap) and the empty tile
        gap = np.full((16, 4), np.nan, np.float32)
        d = decode_tile(encode_tile(gap, codec, max_error=1e-3))
        assert np.isnan(d).all() and d.shape == gap.shape
        empty = np.empty((0, 4), np.float32)
        d = decode_tile(encode_tile(empty, codec, max_error=1e-3))
        assert d.shape == (0, 4)

    def test_lossy_inf_conditions_to_nan(self):
        """condition() and encode() agree on non-finite values: inf
        maps to NaN in BOTH, so conditioned rows roundtrip exactly
        (an inf that conditioned to inf would decode to NaN and
        break tails-vs-tile byte identity)."""
        codec = get_codec("quantize-deflate")
        a = np.array(
            [[1.0, np.inf], [-np.inf, np.nan]], np.float32
        )
        conditioned = codec.condition(a, max_error=1e-2)
        assert np.isnan(conditioned[0, 1])
        assert np.isnan(conditioned[1, 0])
        d = decode_tile(
            encode_tile(conditioned, "quantize-deflate",
                        max_error=1e-2)
        )
        assert d.tobytes() == conditioned.tobytes()

    def test_lossy_rejects_unresolvable_grid(self):
        # a bound finer than float32 resolution at the data magnitude
        # cannot be honored — refuse, never silently violate it
        a = np.full((4, 4), 3.0e7, np.float32)
        with pytest.raises(CodecError, match="resolution"):
            encode_tile(a, "quantize-deflate", max_error=1e-7)

    def test_header_self_describes(self):
        a = _tile_data((10, 3), 0)
        hdr = read_tile_header(
            encode_tile(a, "quantize-deflate", max_error=1e-2)
        )
        assert hdr["codec"] == "quantize-deflate"
        assert hdr["shape"] == [10, 3]
        assert hdr["params"]["max_error"] == 1e-2
        assert hdr["raw_nbytes"] == a.nbytes

    def test_tamper_and_truncation_detected(self):
        blob = bytearray(encode_tile(_tile_data((32, 8), 1), "deflate"))
        flipped = bytearray(blob)
        flipped[-3] ^= 0xFF
        assert verify_tile_blob(bytes(flipped)) == "torn"
        with pytest.raises(CodecError):
            decode_tile(bytes(flipped))
        assert verify_tile_blob(bytes(blob[:6])) == "corrupt"
        assert verify_tile_blob(b"not a tile at all") == "corrupt"

    def test_spec_parsing(self):
        assert parse_codec_spec(None) == (None, {})
        assert parse_codec_spec("raw") == (None, {})
        cid, params = parse_codec_spec(
            "quantize-deflate:max_error=1e-3,level=9"
        )
        assert cid == "quantize-deflate"
        assert params == {"max_error": 1e-3, "level": 9}
        with pytest.raises(CodecError):
            parse_codec_spec("no-such-codec")
        with pytest.raises(ValueError):
            parse_codec_spec("deflate:levelnine")


class TestCompressedStore:
    def _fill(self, folder, codec, chunks=(7, 13, 1, 29, 50)):
        rng = np.random.default_rng(11)
        data = rng.standard_normal((100, 5)).astype(np.float32)
        data[30:40] = np.nan  # an interior gap
        times = _grid(100)
        store = TileStore.create(
            folder, factor=4, tile_len=8, codec=codec
        )
        pos = 0
        for chunk in chunks:
            store.append(
                times[pos : pos + chunk], data[pos : pos + chunk]
            )
            pos += chunk
        return data

    def _arrays(self, folder):
        store = TileStore.open(folder)
        return {
            (lvl, agg): store.read(lvl, 0, store.n(lvl), agg=agg)
            for lvl in range(store.n_levels)
            for agg in ("mean", "min", "max")
        }

    @pytest.mark.parametrize("codec", LOSSLESS)
    def test_lossless_store_equals_raw(self, tmp_path, codec):
        """Chunked compressed == one-shot compressed == raw store,
        byte for byte, across every level and aggregate."""
        data = self._fill(str(tmp_path / "c"), codec)
        self._fill(str(tmp_path / "raw"), None, chunks=(100,))
        self._fill(str(tmp_path / "c1"), codec, chunks=(100,))
        raw = self._arrays(str(tmp_path / "raw"))
        chunked = self._arrays(str(tmp_path / "c"))
        oneshot = self._arrays(str(tmp_path / "c1"))
        assert raw.keys() == chunked.keys() == oneshot.keys()
        for key in raw:
            assert raw[key].tobytes() == chunked[key].tobytes(), key
            assert raw[key].tobytes() == oneshot[key].tobytes(), key
        np.testing.assert_array_equal(
            chunked[(0, "mean")], data
        )
        # the store really is compressed on disk
        assert glob.glob(str(tmp_path / "c" / ".tiles" / "L0" / "*.tpt"))
        assert not glob.glob(
            str(tmp_path / "c" / ".tiles" / "L0" / "*.npy")
        )

    def test_lossy_store_deterministic_and_bounded(self, tmp_path):
        spec = "quantize-deflate:max_error=1e-2"
        data = self._fill(str(tmp_path / "a"), spec)
        self._fill(str(tmp_path / "b"), spec, chunks=(100,))
        a, b = self._arrays(str(tmp_path / "a")), self._arrays(
            str(tmp_path / "b")
        )
        for key in a:
            assert a[key].tobytes() == b[key].tobytes(), key
        lv0 = a[(0, "mean")]
        assert (np.isnan(lv0) == np.isnan(data)).all()
        fin = np.isfinite(data)
        assert np.abs(lv0[fin] - data[fin]).max() <= 1e-2

    def test_manifest_records_codec_and_params(self, tmp_path):
        self._fill(
            str(tmp_path), "quantize-deflate:max_error=1e-2,level=9"
        )
        store = TileStore.open(str(tmp_path))
        assert store.codec == "quantize-deflate"
        assert store.codec_params == {"max_error": 1e-2, "level": 9}
        with open(store.manifest_path) as fh:
            raw = json.load(fh)
        assert raw["codec"] == "quantize-deflate"

    def test_raw_store_manifest_unchanged(self, tmp_path):
        """A raw store writes the exact pre-codec manifest schema —
        old readers keep working on new raw stores."""
        self._fill(str(tmp_path), None)
        with open(TileStore.open(str(tmp_path)).manifest_path) as fh:
            raw = json.load(fh)
        assert "codec" not in raw and "generation" not in raw

    def test_mixed_store_reads(self, tmp_path):
        """A store with SOME tiles still raw (a half-converted or
        half-upgraded tree) serves every tile, byte-identical."""
        data = self._fill(str(tmp_path), "bitshuffle-deflate")
        store = TileStore.open(str(tmp_path))
        # hand-convert one completed tile back to raw .npy
        blob_path = store.tile_blob_path(0, 1)
        arr = decode_tile(open(blob_path, "rb").read())
        from tpudas.integrity.checksum import write_npy_checksummed

        write_npy_checksummed(store.tile_path(0, 1), arr)
        os.remove(blob_path)
        reread = TileStore.open(str(tmp_path)).read(0, 0, 100)
        np.testing.assert_array_equal(reread, data)

    def test_crashed_append_resume_byte_identity(self, tmp_path):
        """The test_serve crashed-append scenario under a codec:
        tiles advanced on disk, manifest did not; resume slices the
        surplus invisible and re-appending converges byte-identically
        with an uninterrupted oracle."""
        rng = np.random.default_rng(5)
        data = rng.standard_normal((12, 2)).astype(np.float32)
        times = _grid(12)
        store = TileStore.create(
            str(tmp_path / "x"), factor=4, tile_len=8,
            codec="bitshuffle-deflate",
        )
        store.append(times[:6], data[:6])
        manifest_before = open(store.manifest_path).read()
        store.append(times[6:], data[6:])
        with open(store.manifest_path, "w") as fh:
            fh.write(manifest_before)
        resumed = TileStore.open(str(tmp_path / "x"))
        assert resumed.levels[0] == 6
        np.testing.assert_array_equal(resumed.read(0, 0, 6), data[:6])
        resumed.append(times[6:], data[6:])
        oracle = TileStore.create(
            str(tmp_path / "y"), factor=4, tile_len=8,
            codec="bitshuffle-deflate",
        )
        oracle.append(times, data)
        for lvl in range(len(oracle.levels)):
            assert (
                resumed.read(lvl, 0, resumed.n(lvl)).tobytes()
                == oracle.read(lvl, 0, oracle.n(lvl)).tobytes()
            )

    def test_unknown_manifest_codec_degrades(self, tmp_path):
        """A manifest naming a codec this build does not know reads
        as no-pyramid (the ladder), not a crash."""
        self._fill(str(tmp_path), "deflate")
        store = TileStore.open(str(tmp_path))
        with open(store.manifest_path) as fh:
            raw = json.load(fh)
        raw["codec"] = "futuristic-zstd"
        from tpudas.integrity.checksum import write_json_checksummed

        write_json_checksummed(store.manifest_path, raw)
        os.remove(store.manifest_path + ".prev")
        assert TileStore.open(str(tmp_path)) is None


def _write_outputs(folder, n_files=2, n_ch=4, seconds=20):
    os.makedirs(folder, exist_ok=True)
    t0 = to_datetime64(T0).astype("datetime64[ns]")
    for i in range(n_files):
        p = synthetic_patch(
            t0=t0 + np.timedelta64(i * seconds, "s"),
            duration=float(seconds), fs=1.0, n_ch=n_ch, seed=i,
        )
        write_patch(p, os.path.join(folder, f"LFDAS_{i:04d}.h5"))


class TestSyncRebuildCodec:
    def test_env_codec_applies_to_fresh_pyramid(self, tmp_path,
                                                monkeypatch):
        out = str(tmp_path / "out")
        _write_outputs(out)
        monkeypatch.setenv(
            "TPUDAS_CODEC", "quantize-deflate:max_error=1e-3"
        )
        rows = sync_pyramid(out, tile_len=8)
        assert rows == 40
        store = TileStore.open(out)
        assert store.codec == "quantize-deflate"
        assert store.codec_params["max_error"] == 1e-3
        # existing manifest wins over a changed env next sync
        monkeypatch.setenv("TPUDAS_CODEC", "deflate")
        sync_pyramid(out)
        assert TileStore.open(out).codec == "quantize-deflate"

    def test_rebuild_reencodes_and_bumps_generation(self, tmp_path):
        out = str(tmp_path / "out")
        _write_outputs(out)
        sync_pyramid(out, tile_len=8)  # raw build
        raw_store = TileStore.open(out)
        oracle = raw_store.read(0, 0, raw_store.n(0))
        assert raw_store.codec is None and raw_store.generation == 0
        rows = rebuild_pyramid(out, codec="bitshuffle-deflate")
        assert rows == 40
        store = TileStore.open(out)
        assert store.codec == "bitshuffle-deflate"
        assert store.generation == 1
        assert glob.glob(os.path.join(out, ".tiles", "L0", "*.tpt"))
        # lossless re-encode is content-identical
        np.testing.assert_array_equal(
            store.read(0, 0, store.n(0)), oracle
        )
        # rebuild with the default preserves the recorded codec
        rebuild_pyramid(out)
        store = TileStore.open(out)
        assert store.codec == "bitshuffle-deflate"
        assert store.generation == 2
        # ... and "raw" strips it
        rebuild_pyramid(out, codec="raw")
        store = TileStore.open(out)
        assert store.codec is None and store.generation == 3

    def test_reencode_invalidates_decoded_cache(self, tmp_path):
        """The ISSUE-11 LRU fix: a held QueryEngine must not serve
        pre-rebuild decoded arrays after a lossy re-encode (cache
        keys carry the manifest generation + codec)."""
        out = str(tmp_path / "out")
        _write_outputs(out)
        sync_pyramid(out, tile_len=8)
        eng = QueryEngine(out)
        store = eng.store
        lo = np.datetime64(store.t0_ns, "ns")
        hi = np.datetime64(store.head_ns - store.step_ns, "ns")
        before = eng.query(lo, hi).data.copy()
        # coarse lossy re-encode: content genuinely changes
        rebuild_pyramid(out, codec="quantize-deflate:max_error=0.5")
        after = eng.query(lo, hi).data
        assert after.tobytes() != before.tobytes()
        fin = np.isfinite(before)
        assert np.abs(after[fin] - before[fin]).max() <= 0.5


class TestFsckCodec:
    def test_torn_compressed_tile_rebuilt(self, tmp_path):
        out = str(tmp_path / "out")
        _write_outputs(out)
        sync_pyramid(out, tile_len=8, codec="bitshuffle-deflate")
        store = TileStore.open(out)
        oracle = {
            (lvl, agg): store.read(lvl, 0, store.n(lvl), agg=agg)
            .tobytes()
            for lvl in range(store.n_levels)
            for agg in ("mean", "min", "max")
        }
        tiles = sorted(
            glob.glob(os.path.join(out, ".tiles", "L0", "*.tpt"))
        )
        with open(tiles[0], "r+b") as fh:
            fh.seek(-4, 2)
            fh.write(b"\x00\x00\x00\x00")
        assert verify_tile_blob(open(tiles[0], "rb").read()) == "torn"
        report = audit(out)
        assert report["clean"]
        assert any(
            i["action"] == "rebuilt_pyramid" for i in report["issues"]
        )
        second = audit(out)
        assert second["clean"] and not second["issues"]
        rebuilt = TileStore.open(out)
        assert rebuilt.codec == "bitshuffle-deflate"  # format survived
        for (lvl, agg), want in oracle.items():
            got = rebuilt.read(lvl, 0, rebuilt.n(lvl), agg=agg)
            assert got.tobytes() == want, (lvl, agg)

    def test_orphan_compressed_tile_removed(self, tmp_path):
        out = str(tmp_path / "out")
        _write_outputs(out)
        sync_pyramid(out, tile_len=8, codec="deflate")
        store = TileStore.open(out)
        orphan = store.tile_blob_path(0, 40)
        with open(orphan, "wb") as fh:
            fh.write(b"TPTC garbage beyond the manifest head")
        report = audit(out)
        assert report["clean"]
        assert any(
            i["status"] == "orphan" and i["action"] == "removed"
            for i in report["issues"]
        )
        assert not os.path.isfile(orphan)


@pytest.fixture
def twin_stores(tmp_path):
    """The same output files under a raw and a (lossless) compressed
    pyramid — the byte-identity acceptance pair."""
    raw = str(tmp_path / "raw")
    comp = str(tmp_path / "comp")
    _write_outputs(raw, n_files=3)
    shutil.copytree(raw, comp)
    sync_pyramid(raw, tile_len=8)
    sync_pyramid(comp, tile_len=8, codec="bitshuffle-deflate")
    return raw, comp


class TestHTTPCaching:
    def _get(self, url, headers=None):
        req = urllib.request.Request(url, headers=headers or {})
        return urllib.request.urlopen(req, timeout=30)

    def test_compressed_store_http_byte_identity(self, twin_stores):
        """/query and /waterfall over a lossless compressed store are
        byte-identical to the raw store's responses."""
        from tpudas.serve.http import start_server

        raw, comp = twin_stores
        store = TileStore.open(raw)
        t0s = str(np.datetime64(store.t0_ns, "ns"))
        t1s = str(np.datetime64(store.head_ns - store.step_ns, "ns"))
        tails = (
            f"/query?t0={t0s}&t1={t1s}",
            f"/query?t0={t0s}&t1={t1s}&format=json",
            f"/waterfall?t0={t0s}&t1={t1s}&max_px=8",
        )
        with start_server(raw) as a, start_server(comp) as b:
            for tail in tails:
                ra = self._get(a.base_url + tail)
                rb = self._get(b.base_url + tail)
                assert ra.read() == rb.read(), tail
                assert (
                    ra.headers["X-Tpudas-Source"]
                    == rb.headers["X-Tpudas-Source"]
                )

    @pytest.mark.slow
    def test_etag_304_and_cache_control(self, twin_stores):
        from tpudas.serve.http import start_server

        _, comp = twin_stores
        store = TileStore.open(comp)
        t0 = store.t0_ns
        step = store.step_ns
        with start_server(comp) as srv:
            # inside completed full tiles -> immutable + strong ETag
            url = (
                f"{srv.base_url}/query?"
                f"t0={np.datetime64(t0, 'ns')}"
                f"&t1={np.datetime64(t0 + 7 * step, 'ns')}"
            )
            r = self._get(url)
            assert r.headers["Cache-Control"] == (
                "public, max-age=31536000, immutable"
            )
            etag = r.headers["ETag"]
            assert etag.startswith('"')
            with pytest.raises(urllib.error.HTTPError) as err:
                self._get(url, headers={"If-None-Match": etag})
            assert err.value.code == 304
            assert err.value.read() == b""
            assert err.value.headers["ETag"] == etag
            # touching the growing head -> must revalidate at origin
            head = self._get(
                f"{srv.base_url}/query?"
                f"t0={np.datetime64(t0, 'ns')}"
                f"&t1={np.datetime64(store.head_ns, 'ns')}"
            )
            assert head.headers["Cache-Control"] == "no-cache"

    @pytest.mark.slow
    def test_deflate_q0_is_refusal(self, twin_stores):
        from tpudas.serve.http import start_server

        _, comp = twin_stores
        store = TileStore.open(comp)
        url_tail = (
            f"/query?t0={np.datetime64(store.t0_ns, 'ns')}"
            f"&t1={np.datetime64(store.head_ns - store.step_ns, 'ns')}"
        )
        with start_server(comp) as srv:
            r = self._get(
                srv.base_url + url_tail,
                headers={"Accept-Encoding": "gzip, deflate;q=0"},
            )
            assert r.headers.get("Content-Encoding") is None

    def test_events_etag_and_no_cache(self, twin_stores):
        """/events is origin-only but ETag-revalidatable: a polling
        dashboard's unchanged ledger costs headers, not payload."""
        from tpudas.serve.http import start_server

        _, comp = twin_stores
        with start_server(comp) as srv:
            r = self._get(srv.base_url + "/events")
            assert r.headers["Cache-Control"] == "no-cache"
            etag = r.headers["ETag"]
            with pytest.raises(urllib.error.HTTPError) as err:
                self._get(
                    srv.base_url + "/events",
                    headers={"If-None-Match": etag},
                )
            assert err.value.code == 304

    @pytest.mark.slow
    def test_torn_tile_never_served_immutable(self, twin_stores):
        """A tile that fails its crc must 500, not be handed to a
        CDN with a year-long immutable header."""
        from tpudas.serve.http import start_server

        _, comp = twin_stores
        store = TileStore.open(comp)
        path = store.tile_blob_path(0, 0)
        with open(path, "r+b") as fh:
            fh.seek(-4, 2)
            fh.write(b"\x00\x00\x00\x00")
        with start_server(comp) as srv:
            for hdrs in ({}, {"Accept-Encoding": "x-tpt"}):
                with pytest.raises(urllib.error.HTTPError) as err:
                    self._get(
                        f"{srv.base_url}/tile?level=0&idx=0",
                        headers=hdrs,
                    )
                assert err.value.code == 500

    @pytest.mark.slow
    def test_deflate_negotiation(self, twin_stores):
        from tpudas.serve.http import start_server

        _, comp = twin_stores
        store = TileStore.open(comp)
        url_tail = (
            f"/query?t0={np.datetime64(store.t0_ns, 'ns')}"
            f"&t1={np.datetime64(store.head_ns - store.step_ns, 'ns')}"
        )
        with start_server(comp) as srv:
            plain = self._get(srv.base_url + url_tail)
            body = plain.read()
            assert plain.headers.get("Content-Encoding") is None
            assert plain.headers["Vary"] == "Accept-Encoding"
            enc = self._get(
                srv.base_url + url_tail,
                headers={"Accept-Encoding": "deflate"},
            )
            assert enc.headers["Content-Encoding"] == "deflate"
            assert zlib.decompress(enc.read()) == body

    def test_tile_endpoint(self, twin_stores):
        from tpudas.serve.http import start_server

        _, comp = twin_stores
        store = TileStore.open(comp)
        full_tiles = store.n(0) // store.tile_len
        with start_server(comp) as srv:
            # full tile: immutable npy by default
            r = self._get(f"{srv.base_url}/tile?level=0&idx=0")
            assert r.headers["Cache-Control"] == (
                "public, max-age=31536000, immutable"
            )
            assert r.headers["X-Tpudas-Codec"] == "bitshuffle-deflate"
            arr = np.load(io.BytesIO(r.read()))
            np.testing.assert_array_equal(
                arr, store.read(0, 0, store.tile_len)
            )
            # negotiated: the stored blob verbatim
            r = self._get(
                f"{srv.base_url}/tile?level=0&idx=0",
                headers={"Accept-Encoding": "x-tpt"},
            )
            assert r.headers["Content-Encoding"] == "x-tpt"
            blob = r.read()
            assert verify_tile_blob(blob) == "ok"
            np.testing.assert_array_equal(decode_tile(blob), arr)
            # 304 on the blob representation too
            with pytest.raises(urllib.error.HTTPError) as err:
                self._get(
                    f"{srv.base_url}/tile?level=0&idx=0",
                    headers={"Accept-Encoding": "x-tpt",
                             "If-None-Match": r.headers["ETag"]},
                )
            assert err.value.code == 304
            # the partial head tile: origin-only
            if store.n(0) % store.tile_len:
                r = self._get(
                    f"{srv.base_url}/tile?level=0&idx={full_tiles}"
                )
                assert r.headers["Cache-Control"] == "no-cache"
            # beyond the head: 404 with the level map
            with pytest.raises(urllib.error.HTTPError) as err:
                self._get(f"{srv.base_url}/tile?level=0&idx=10000")
            assert err.value.code == 404
            # bad params: 400
            with pytest.raises(urllib.error.HTTPError) as err:
                self._get(f"{srv.base_url}/tile?level=0")
            assert err.value.code == 400


class TestServePool:
    def test_merge_prometheus_labels(self):
        from tpudas.serve.pool import merge_prometheus

        merged = merge_prometheus({
            "0": "# TYPE m counter\nm 1\nn{a=\"b\"} 2\n",
            "1": "# TYPE m counter\nm 3\n",
        })
        lines = merged.splitlines()
        assert lines.count("# TYPE m counter") == 1
        assert 'm{worker="0"} 1' in lines
        assert 'n{worker="0",a="b"} 2' in lines
        assert 'm{worker="1"} 3' in lines

    def test_pool_shared_port_and_control_plane(self, twin_stores):
        from tpudas.serve.pool import ServePool, has_reuse_port

        if not has_reuse_port():
            pytest.skip("SO_REUSEPORT unavailable on this platform")
        _, comp = twin_stores
        store = TileStore.open(comp)
        t0s = str(np.datetime64(store.t0_ns, "ns"))
        t1s = str(np.datetime64(store.head_ns - store.step_ns, "ns"))
        with ServePool(comp, port=0, workers=2) as pool:
            url = f"{pool.base_url}/query?t0={t0s}&t1={t1s}"
            bodies = {
                urllib.request.urlopen(url, timeout=30).read()
                for _ in range(8)
            }
            assert len(bodies) == 1  # every worker serves the bytes
            health = json.loads(
                urllib.request.urlopen(
                    pool.control_url + "/healthz", timeout=30
                ).read()
            )
            assert health["status"] == "ok"
            assert len(health["workers"]) == 2
            metrics = urllib.request.urlopen(
                pool.control_url + "/metrics", timeout=30
            ).read().decode()
            assert 'worker="0"' in metrics
            assert 'worker="1"' in metrics
            assert 'worker="pool"' in metrics
            assert "tpudas_serve_requests_total" in metrics
