"""Native tdas stream format + C++ streamio runtime: roundtrips,
range reads, int16 quantization, native/numpy parity, window assembly,
and the full engine running on a tdas spool."""

import os

import numpy as np
import pytest

from tpudas import spool
from tpudas.io import tdas
from tpudas.io.registry import read_file, scan_file, write_patch
from tpudas.testing import make_synthetic_spool, synthetic_patch


@pytest.fixture()
def patch():
    return synthetic_patch(duration=10.0, fs=100.0, n_ch=12, noise=0.05)


class TestRoundtrip:
    def test_float32_exact(self, patch, tmp_path):
        path = str(tmp_path / "a.tdas")
        write_patch(patch, path, format="tdas")
        (back,) = read_file(path, format="tdas")
        assert np.array_equal(back.host_data(), patch.host_data())
        assert np.array_equal(back.coords["time"], patch.coords["time"])
        assert np.allclose(back.coords["distance"], patch.coords["distance"])

    def test_int16_quantized(self, patch, tmp_path):
        path = str(tmp_path / "a.tdas")
        write_patch(patch, path, format="tdas", dtype="int16")
        (back,) = read_file(path, format="tdas")
        hdr = tdas.read_tdas_header(path)
        assert hdr["dtype_code"] == 1
        # quantization error bounded by half an LSB
        err = np.abs(back.host_data() - patch.host_data()).max()
        assert err <= hdr["scale"] * 0.5 + 1e-7
        # int16 payload is half the size of the float32 one
        p32 = str(tmp_path / "b.tdas")
        write_patch(patch, p32, format="tdas")
        assert os.path.getsize(path) < 0.6 * os.path.getsize(p32)

    def test_unknown_dtype_code_rejected(self, patch, tmp_path):
        # a corrupt/future dtype code must fail identically in the
        # numpy and native readers, not decode as float32 garbage
        path = str(tmp_path / "bad.tdas")
        write_patch(patch, path, format="tdas")
        with open(path, "r+b") as fh:
            fh.seek(32)  # dtype_code field (<4sIQQII|I|fddQ)
            fh.write((7).to_bytes(4, "little"))
        with pytest.raises(ValueError, match="dtype code"):
            tdas.read_tdas_header(path)
        from tpudas.native import load_streamio

        lib = load_streamio()
        if lib is not None:
            import ctypes
            import errno

            u64, u32, f32, f64 = (
                ctypes.c_uint64, ctypes.c_uint32, ctypes.c_float,
                ctypes.c_double,
            )
            args = [u64(), u64(), u32(), u32(), u32(), f32(), f64(), f64()]
            rc = lib.tdas_read_header(
                os.fsencode(path), *(ctypes.byref(a) for a in args)
            )
            assert rc == errno.EINVAL

    def test_nonuniform_time_rejected(self, patch, tmp_path):
        coords = dict(patch.coords)
        t = coords["time"].copy()
        t[3] += np.timedelta64(1, "ms")
        coords["time"] = t
        bad = patch.new(coords=coords)
        with pytest.raises(ValueError, match="uniform time"):
            write_patch(bad, str(tmp_path / "x.tdas"), format="tdas")


class TestRangeReads:
    def test_time_range_matches_slice(self, patch, tmp_path):
        path = str(tmp_path / "a.tdas")
        write_patch(patch, path, format="tdas")
        t = patch.coords["time"]
        (sub,) = read_file(path, format="tdas", time=(t[100], t[399]))
        full = patch.host_data()
        assert sub.host_data().shape == (300, 12)
        assert np.array_equal(sub.host_data(), full[100:400])
        assert sub.coords["time"][0] == t[100]

    def test_distance_range(self, patch, tmp_path):
        path = str(tmp_path / "a.tdas")
        write_patch(patch, path, format="tdas")
        d = patch.coords["distance"]
        (sub,) = read_file(path, format="tdas", distance=(d[3], d[7]))
        assert sub.host_data().shape[1] == 5
        assert np.array_equal(sub.host_data(), patch.host_data()[:, 3:8])

    def test_block_out_of_bounds(self, patch, tmp_path):
        path = str(tmp_path / "a.tdas")
        write_patch(patch, path, format="tdas")
        with pytest.raises(ValueError, match="out of bounds"):
            tdas.read_tdas_block(path, 0, 10**6, 0, 1)


class TestNativeParity:
    def test_numpy_fallback_identical(self, patch, tmp_path, monkeypatch):
        path = str(tmp_path / "a.tdas")
        write_patch(patch, path, format="tdas", dtype="int16")
        native = tdas.read_tdas_block(path, 50, 750, 2, 11)
        monkeypatch.setattr(tdas, "load_streamio", lambda: None)
        fallback = tdas.read_tdas_block(path, 50, 750, 2, 11)
        assert np.array_equal(native, fallback)

    def test_write_fallback_readable_by_native(self, patch, tmp_path,
                                               monkeypatch):
        path = str(tmp_path / "a.tdas")
        monkeypatch.setattr(tdas, "load_streamio", lambda: None)
        tdas.write_tdas(patch, path)
        monkeypatch.undo()
        (back,) = read_file(path, format="tdas")
        assert np.array_equal(back.host_data(), patch.host_data())


class TestAssembleWindow:
    def test_multi_file_window(self, tmp_path):
        paths = make_synthetic_spool(
            tmp_path, n_files=3, file_duration=10.0, fs=100.0, n_ch=8,
            noise=0.05, format="tdas",
        )
        # window spanning the tail of file 0, all of file 1, head of 2
        segs = [
            (paths[0], 600, 1000, 0),
            (paths[1], 0, 1000, 400),
            (paths[2], 0, 300, 1400),
        ]
        win = tdas.assemble_window(segs, 1, 7, 1700)
        assert win.shape == (1700, 6)
        a = tdas.read_tdas_block(paths[0], 600, 1000, 1, 7)
        b = tdas.read_tdas_block(paths[1], 0, 1000, 1, 7)
        c = tdas.read_tdas_block(paths[2], 0, 300, 1, 7)
        assert np.array_equal(win, np.concatenate([a, b, c]))


class TestSpoolIntegration:
    def test_index_scan_and_select(self, tmp_path):
        make_synthetic_spool(
            tmp_path, n_files=4, file_duration=15.0, fs=50.0, n_ch=6,
            format="tdas",
        )
        sp = spool(str(tmp_path)).sort("time").update()
        assert len(sp) == 4
        df = sp.get_contents()
        assert set(df["format"]) == {"tdas"}
        merged = sp.chunk(time=None)
        assert len(merged) == 1
        assert merged[0].host_data().shape == (4 * 750, 6)

    def test_corrupt_file_skipped(self, tmp_path):
        make_synthetic_spool(
            tmp_path, n_files=2, file_duration=15.0, fs=50.0, n_ch=6,
            format="tdas",
        )
        with open(tmp_path / "junk.tdas", "wb") as fh:
            fh.write(b"not a tdas file at all")
        sp = spool(str(tmp_path)).update()
        assert len(sp) == 2

    def test_legacy_cache_version_discarded_and_rescanned(self, tmp_path):
        """A pre-dx index cache (version 1) must be discarded whole on
        load: mixed legacy/new records would fail the planner's
        geometry check and silently disable the native fast path."""
        import json

        from tpudas.io.index import INDEX_FILENAME, DirectoryIndex

        make_synthetic_spool(
            tmp_path, n_files=2, file_duration=10.0, fs=50.0, n_ch=8,
            d_ch=0.1, format="tdas",
        )
        DirectoryIndex(str(tmp_path)).update()
        cache = tmp_path / INDEX_FILENAME
        raw = json.loads(cache.read_text())
        # fabricate the legacy cache: version 1, no dx field
        raw["version"] = 1
        for rec in raw["files"].values():
            rec.pop("dx", None)
        cache.write_text(json.dumps(raw))
        sp = spool(str(tmp_path)).sort("time").update()
        df = sp.get_contents()
        assert len(df) == 2
        assert all(np.isfinite(v) for v in df["dx"])  # rescanned
        plan = sp.native_window_plan(
            np.datetime64("2023-03-22T00:00:02"),
            np.datetime64("2023-03-22T00:00:18"),
        )
        assert plan is not None  # fast path alive across the upgrade

    def test_v2_cache_discarded_so_int16_fast_path_fires(self, tmp_path):
        """A v2 cache (pre dtype_code/scale) must be discarded whole,
        or an int16 spool indexed before the upgrade would never plan
        the raw device-decode path (round-4 review)."""
        import json

        from tpudas.io.index import INDEX_FILENAME, DirectoryIndex

        make_synthetic_spool(
            tmp_path, n_files=2, file_duration=10.0, fs=100.0, n_ch=4,
            format="tdas", write_kwargs={"dtype": "int16", "scale": 1e-3},
        )
        DirectoryIndex(str(tmp_path)).update()
        cache = tmp_path / INDEX_FILENAME
        raw = json.loads(cache.read_text())
        raw["version"] = 2
        for rec in raw["files"].values():
            rec.pop("dtype_code", None)
            rec.pop("scale", None)
        cache.write_text(json.dumps(raw))
        sp = spool(str(tmp_path)).sort("time").update()
        plan = sp.native_window_plan(
            np.datetime64("2023-03-22T00:00:02"),
            np.datetime64("2023-03-22T00:00:18"),
        )
        assert plan is not None and plan["payload"] == "int16"

    def test_truncated_indexed_file_record_dropped(self, tmp_path):
        """A file that was indexed complete and later truncated in
        place must lose its (now stale) index record — not serve a
        short read at window-assembly time."""
        make_synthetic_spool(
            tmp_path, n_files=2, file_duration=10.0, fs=50.0, n_ch=4,
            format="tdas",
        )
        sp = spool(str(tmp_path)).update()
        assert len(sp) == 2
        victim = sorted(tmp_path.glob("*.tdas"))[0]
        full = victim.read_bytes()
        victim.write_bytes(full[: len(full) - 64])  # truncate in place
        assert len(spool(str(tmp_path)).update()) == 1
        victim.write_bytes(full)  # writer finishes: record returns
        assert len(spool(str(tmp_path)).update()) == 2

    def test_torn_file_rejected_then_indexed_when_complete(self, tmp_path):
        """A file whose payload is shorter than the header promises (an
        interrogator mid-write / torn copy) is rejected at scan time —
        not surfaced as a short read at window-assembly time — and is
        picked up once its bytes settle."""
        make_synthetic_spool(
            tmp_path, n_files=1, file_duration=10.0, fs=50.0, n_ch=4,
            format="tdas",
        )
        (name,) = [p for p in os.listdir(tmp_path) if p.endswith(".tdas")]
        full = (tmp_path / name).read_bytes()
        torn = tmp_path / "torn.tdas"
        torn.write_bytes(full[: len(full) - 128])
        with pytest.raises(ValueError, match="size mismatch"):
            scan_file(str(torn), format="tdas")
        sp = spool(str(tmp_path)).update()
        assert len(sp) == 1  # torn file skipped, valid one indexed
        torn.write_bytes(full)  # "interrogator finished writing"
        assert len(spool(str(tmp_path)).update()) == 2

    def test_scan_carries_exact_dx(self, tmp_path):
        """Scan records carry the header's exact dx: reconstructing it
        from (distance_max - d0)/(n-1) is ulp-inexact and moves exact
        channel-boundary selects (round-2 advisor finding)."""
        patch = synthetic_patch(
            duration=10.0, fs=50.0, n_ch=49, d_ch=0.1
        )
        path = str(tmp_path / "a.tdas")
        write_patch(patch, path, format="tdas")
        hdr = tdas.read_tdas_header(path)
        rec = scan_file(path, format="tdas")[0]
        assert rec["dx"] == hdr["dx"]
        recon = (rec["distance_max"] - rec["distance_min"]) / (
            rec["ndistance"] - 1
        )
        assert recon != hdr["dx"]  # the reconstruction really is off

    def test_plan_channel_bounds_match_reader_on_exact_boundary(
        self, tmp_path
    ):
        """A distance select landing exactly on a channel must pick the
        same channels through the planned fast path as through the
        per-file reader (byte parity on boundary selects)."""
        make_synthetic_spool(
            tmp_path, n_files=2, file_duration=10.0, fs=50.0, n_ch=49,
            d_ch=0.1, format="tdas",
        )
        first = sorted(
            p for p in os.listdir(tmp_path) if p.endswith(".tdas")
        )[0]
        hdr = tdas.read_tdas_header(str(tmp_path / first))
        dx = hdr["dx"]
        sel = (3 * dx, 40 * dx)  # k=3 flips under ulp-off dx
        sp = spool(str(tmp_path)).sort("time").update().select(distance=sel)
        t_lo = np.datetime64("2023-03-22T00:00:02")
        t_hi = np.datetime64("2023-03-22T00:00:18")
        plan = sp.native_window_plan(t_lo, t_hi)
        assert plan is not None
        fast = tdas.assemble_window_patch(plan)
        merged = spool(sp.select(time=(t_lo, t_hi))).chunk(time=None)[0]
        assert np.array_equal(fast.host_data(), merged.host_data())
        assert np.array_equal(
            fast.coords["distance"], merged.coords["distance"]
        )

    @pytest.mark.slow
    def test_lfproc_end_to_end_on_tdas(self, tmp_path):
        """The full chunked engine runs unchanged on a native-format
        spool and matches the dasdae-format result exactly."""
        from tpudas.proc.lfproc import LFProc

        results = {}
        for fmt in ("tdas", "dasdae"):
            src = tmp_path / fmt
            make_synthetic_spool(
                src, n_files=4, file_duration=30.0, fs=100.0, n_ch=6,
                noise=0.01, format=fmt,
            )
            lfp = LFProc(spool(str(src)).sort("time").update())
            lfp.update_processing_parameter(
                output_sample_interval=1.0, process_patch_size=50,
                edge_buff_size=10,
            )
            out = tmp_path / (fmt + "_out")
            lfp.set_output_folder(str(out), delete_existing=True)
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:02:00"),
            )
            results[fmt] = spool(str(out)).update().chunk(time=None)[0]
            # tdas spools must take the native window-assembly fast
            # path for every window; dasdae spools never do
            expect = {"tdas": lambda n: n > 0, "dasdae": lambda n: n == 0}
            assert expect[fmt](lfp.native_windows), (fmt, lfp.native_windows)
        assert np.array_equal(
            results["tdas"].host_data(), results["dasdae"].host_data()
        )


class TestWindowPlan:
    def test_plan_matches_merge(self, tmp_path):
        make_synthetic_spool(
            tmp_path, n_files=3, file_duration=10.0, fs=100.0, n_ch=8,
            noise=0.05, format="tdas",
        )
        sp = spool(str(tmp_path)).sort("time").update()
        t_lo = np.datetime64("2023-03-22T00:00:04")
        t_hi = np.datetime64("2023-03-22T00:00:27.5")
        plan = sp.native_window_plan(t_lo, t_hi)
        assert plan is not None
        assert len(plan["segments"]) == 3
        fast = tdas.assemble_window_patch(plan)
        merged = spool(sp.select(time=(t_lo, t_hi))).chunk(time=None)[0]
        assert np.array_equal(fast.host_data(), merged.host_data())
        assert np.array_equal(
            fast.coords["time"], merged.coords["time"]
        )
        assert np.allclose(
            fast.coords["distance"], merged.coords["distance"]
        )

    def test_plan_honors_distance_selection(self, tmp_path):
        make_synthetic_spool(
            tmp_path, n_files=2, file_duration=10.0, fs=100.0, n_ch=8,
            d_ch=5.0, format="tdas",
        )
        sp = spool(str(tmp_path)).update().select(distance=(10.0, 25.0))
        plan = sp.native_window_plan(
            np.datetime64("2023-03-22T00:00:00"),
            np.datetime64("2023-03-22T00:00:15"),
        )
        assert plan is not None
        assert (plan["c_lo"], plan["c_hi"]) == (2, 6)

    def test_plan_none_for_gap(self, tmp_path):
        make_synthetic_spool(
            tmp_path, n_files=1, file_duration=10.0, fs=100.0, n_ch=4,
            format="tdas",
        )
        make_synthetic_spool(
            tmp_path, n_files=1, file_duration=10.0, fs=100.0, n_ch=4,
            format="tdas", start="2023-03-22T00:01:00", prefix="late",
        )
        sp = spool(str(tmp_path)).sort("time").update()
        plan = sp.native_window_plan(
            np.datetime64("2023-03-22T00:00:00"),
            np.datetime64("2023-03-22T00:01:05"),
        )
        assert plan is None  # gap -> generic path decides on_gap policy

    def test_int16_plan_assembles_raw_with_scale(self, tmp_path):
        """Uniform-int16 spools plan a raw (device-decode) assembly:
        int16 payload + data_scale attr, byte-identical to the decoded
        read path after host-side dequantization."""
        make_synthetic_spool(
            tmp_path, n_files=3, file_duration=10.0, fs=100.0, n_ch=8,
            noise=0.05, format="tdas",
            write_kwargs={"dtype": "int16", "scale": 1e-3},
        )
        sp = spool(str(tmp_path)).sort("time").update()
        t_lo = np.datetime64("2023-03-22T00:00:04")
        t_hi = np.datetime64("2023-03-22T00:00:27.5")
        plan = sp.native_window_plan(t_lo, t_hi)
        assert plan is not None
        assert plan["payload"] == "int16"
        assert plan["scale"] == pytest.approx(1e-3)
        qpatch = tdas.assemble_window_patch(plan)
        assert qpatch.host_data().dtype == np.int16
        assert qpatch.attrs["data_scale"] == pytest.approx(1e-3)
        decoded = qpatch.host_data().astype(np.float32) * np.float32(
            plan["scale"]
        )
        merged = spool(sp.select(time=(t_lo, t_hi))).chunk(time=None)[0]
        assert np.array_equal(decoded, merged.host_data())

    def test_int16_raw_numpy_fallback_identical(self, tmp_path,
                                                monkeypatch):
        make_synthetic_spool(
            tmp_path, n_files=2, file_duration=10.0, fs=100.0, n_ch=8,
            noise=0.05, format="tdas",
            write_kwargs={"dtype": "int16", "scale": 2e-3},
        )
        sp = spool(str(tmp_path)).sort("time").update().select(
            distance=(10.0, 30.0)
        )
        plan = sp.native_window_plan(
            np.datetime64("2023-03-22T00:00:02"),
            np.datetime64("2023-03-22T00:00:18"),
        )
        assert plan is not None and plan["payload"] == "int16"
        native = tdas.assemble_window_raw(
            plan["segments"], plan["c_lo"], plan["c_hi"],
            plan["total_rows"], dtype_code=1,
        )
        monkeypatch.setattr(tdas, "load_streamio", lambda: None)
        fallback = tdas.assemble_window_raw(
            plan["segments"], plan["c_lo"], plan["c_hi"],
            plan["total_rows"], dtype_code=1,
        )
        assert native.dtype == np.int16
        assert np.array_equal(native, fallback)

    def test_mixed_scale_int16_falls_back_to_float32(self, tmp_path):
        # default int16 writing picks a per-file peak scale -> scales
        # differ -> the raw path must NOT fire (a single scale cannot
        # decode the window); decoded-f32 assembly still applies
        make_synthetic_spool(
            tmp_path, n_files=3, file_duration=10.0, fs=100.0, n_ch=4,
            noise=0.05, format="tdas", write_kwargs={"dtype": "int16"},
        )
        sp = spool(str(tmp_path)).sort("time").update()
        plan = sp.native_window_plan(
            np.datetime64("2023-03-22T00:00:02"),
            np.datetime64("2023-03-22T00:00:28"),
        )
        assert plan is not None
        assert plan["payload"] == "float32"
        patch = tdas.assemble_window_patch(plan)
        assert patch.host_data().dtype == np.float32

    @pytest.mark.slow
    def test_lfproc_device_decode_matches_host_decode(self, tmp_path):
        """The engine on a uniform-int16 spool (device decode) produces
        byte-identical output to the same engine fed host-decoded f32
        patches of the same quantized data."""
        from tpudas.io.spool import MemorySpool
        from tpudas.proc.lfproc import LFProc

        src = tmp_path / "q"
        make_synthetic_spool(
            src, n_files=4, file_duration=30.0, fs=100.0, n_ch=6,
            noise=0.01, format="tdas",
            write_kwargs={"dtype": "int16", "scale": 1e-3},
        )
        t0 = np.datetime64("2023-03-22T00:00:00")
        t1 = np.datetime64("2023-03-22T00:02:00")
        results = {}
        for label, sp in (
            ("device", spool(str(src)).sort("time").update()),
            (
                "host",
                MemorySpool(
                    list(spool(str(src)).sort("time").update())
                ),  # read path host-decodes to f32
            ),
        ):
            lfp = LFProc(sp)
            lfp.update_processing_parameter(
                output_sample_interval=1.0, process_patch_size=50,
                edge_buff_size=10,
            )
            out = tmp_path / f"out_{label}"
            lfp.set_output_folder(str(out), delete_existing=True)
            lfp.process_time_range(t0, t1)
            if label == "device":
                assert lfp.native_windows > 0  # raw fast path fired
            results[label] = (
                spool(str(out)).update().chunk(time=None)[0].host_data()
            )
        assert np.array_equal(results["device"], results["host"])

    def test_plan_none_for_dasdae(self, tmp_path):
        make_synthetic_spool(
            tmp_path, n_files=2, file_duration=10.0, fs=100.0, n_ch=4,
            format="dasdae",
        )
        sp = spool(str(tmp_path)).update()
        assert (
            sp.native_window_plan(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:00:15"),
            )
            is None
        )
