"""Worker process for the real 2-process DCN test (test_distributed).

Run with COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID in the
environment on the CPU backend (4 virtual devices per process). Builds
the global (2, 4) mesh across both processes and runs collectives in
both mesh directions — psum reductions and the ppermute halo exchange —
over the distributed runtime that jax.distributed.initialize set up.
"""

import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpudas.parallel.distributed import (  # noqa: E402
    global_mesh_devices,
    initialize_multihost,
    is_distributed,
)


def main():
    assert initialize_multihost() is True, "env config missing"

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpudas.parallel.compat import shard_map

    from tpudas.parallel.halo import exchange_halo_time

    assert jax.process_count() == 2, jax.process_count()
    assert is_distributed()
    devs = np.array(global_mesh_devices())
    assert devs.size == 8, devs
    # time axis spans the two processes: rows 0-3 on process 0, 4-7 on
    # process 1 — every "time" collective crosses the DCN boundary
    mesh = Mesh(devs.reshape(2, 4), ("time", "ch"))

    T, C = 16, 8
    global_data = np.arange(T * C, dtype=np.float32).reshape(T, C)
    sharding = NamedSharding(mesh, P("time", "ch"))
    arr = jax.make_array_from_callback(
        global_data.shape, sharding, lambda idx: global_data[idx]
    )

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("time", "ch"),),
        out_specs=P(),
        check_vma=False,
    )
    def total(block):
        return jax.lax.psum(jax.lax.psum(jnp.sum(block), "time"), "ch")

    val = float(total(arr))
    expected = float(global_data.sum())
    assert abs(val - expected) < 1e-3, (val, expected)

    halo = 2

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("time", "ch"),),
        out_specs=P("time", "ch"),
        check_vma=False,
    )
    def left_shift(block):
        padded = exchange_halo_time(block, halo, axis_name="time", n_shards=2)
        return padded[: block.shape[0]]

    out = multihost_utils.process_allgather(left_shift(arr), tiled=True)
    want = np.zeros_like(global_data)
    want[halo:] = global_data[:-halo]  # stream start receives zeros
    assert np.array_equal(out, want), (out[:4], want[:4])

    # the PRODUCT engine's sharded cascade across the DCN boundary:
    # the compiled shard_map step (time sharding spans the two
    # processes, so its halo ppermute crosses DCN) must be bit-equal
    # to the single-process cascade (BASELINE config 5)
    from tpudas.ops.fir import cascade_decimate, design_cascade
    from tpudas.parallel.pipeline import (
        _build_sharded_cascade_fn,
        sharded_cascade_layout,
    )

    plan = design_cascade(100.0, 20, 0.45, 4)
    n_out = 800  # each shard's halo (filter support) must fit its block
    Cc = 8
    layout = sharded_cascade_layout(
        mesh, plan, plan.delay, n_out,
        n_out * plan.ratio, n_ch_local=Cc // 4, engine="xla",
    )
    assert layout is not None, "2-shard layout must fit this window"
    n_loc, t_local, halo_c = layout
    T_target = 2 * t_local
    rng = np.random.default_rng(0)
    x_np = rng.standard_normal((T_target, Cc)).astype(np.float32)
    x_glob = jax.make_array_from_callback(
        x_np.shape, sharding, lambda idx: x_np[idx]
    )
    step = _build_sharded_cascade_fn(
        plan, n_loc, halo_c, "xla", mesh, "time", "ch"
    )
    got = multihost_utils.process_allgather(step(x_glob), tiled=True)
    ref = np.asarray(
        cascade_decimate(x_np, plan, plan.delay, 2 * n_loc, "xla")
    )
    assert got.shape == ref.shape, (got.shape, ref.shape)
    assert np.array_equal(got, ref), np.abs(got - ref).max()

    print(f"DCN_WORKER_OK pid={jax.process_index()}", flush=True)


if __name__ == "__main__":
    main()
