"""Sharded execution on the 8-device CPU mesh: channel sharding,
time-shard halo exchange, batched data parallelism — all must agree
with the single-device kernels."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudas.ops.filter import fft_pass_filter
from tpudas.ops.rolling import rolling_reduce
from tpudas.parallel.batch import batched_rolling_mean
from tpudas.parallel.mesh import make_mesh
from tpudas.parallel.pipeline import sharded_lowpass_decimate
from tpudas.parallel.sharding import shard_channels


@pytest.fixture(scope="module", autouse=True)
def require_8_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")


def _signal(T, C, fs, seed=0):
    rng = np.random.default_rng(seed)
    t = np.arange(T) / fs
    lf = np.sin(2 * np.pi * 0.05 * t)[:, None] * (1 + np.arange(C))[None, :]
    return (lf + 0.3 * rng.standard_normal((T, C))).astype(np.float32)


class TestMesh:
    def test_make_mesh_shapes(self):
        m = make_mesh(8, time_shards=2)
        assert m.shape["time"] == 2 and m.shape["ch"] == 4
        m1 = make_mesh(8)
        assert m1.shape["time"] == 1 and m1.shape["ch"] == 8

    def test_bad_factorization(self):
        with pytest.raises(ValueError):
            make_mesh(8, time_shards=3)


class TestChannelSharding:
    def test_zero_comm_filter_matches_single_device(self):
        fs = 100.0
        data = _signal(3000, 16, fs)
        ref = np.asarray(fft_pass_filter(data, 1 / fs, high=2.0))
        mesh = make_mesh(8)
        sharded = shard_channels(jnp.asarray(data), mesh)
        out = fft_pass_filter(sharded, 1 / fs, high=2.0)
        assert np.allclose(np.asarray(out), ref, atol=1e-4)


class TestShardedPipeline:
    fs = 100.0

    def _reference(self, data, corner, ratio, halo):
        """Single-device equivalent: zero-pad halo at the stream ends
        (matching the boundary shards' ppermute zeros), filter, trim,
        stride."""
        T = data.shape[0]
        padded = np.concatenate(
            [
                np.zeros((halo,) + data.shape[1:], data.dtype),
                data,
                np.zeros((halo,) + data.shape[1:], data.dtype),
            ]
        )
        filt = np.asarray(fft_pass_filter(padded, 1 / self.fs, high=corner))
        return filt[halo : halo + T : ratio]

    @pytest.mark.parametrize("time_shards", [1, 2, 4])
    def test_matches_interior_of_unsharded(self, time_shards):
        T, C, ratio, halo = 4000, 16, 10, 200
        data = _signal(T, C, self.fs, seed=1)
        corner = 2.0
        mesh = make_mesh(8, time_shards=time_shards)
        out = np.asarray(
            sharded_lowpass_decimate(
                mesh, data, 1 / self.fs, corner, ratio, halo
            )
        )
        assert out.shape == (T // ratio, C)
        ref = np.asarray(fft_pass_filter(data, 1 / self.fs, high=corner))[::ratio]
        # interior: away from every shard seam by > halo output samples
        # the halo is sized so seams are exact within filter leakage
        interior = slice(halo // ratio + 1, -(halo // ratio + 1))
        scale = np.abs(ref).max()
        assert (
            np.abs(out[interior] - ref[interior]).max() < 5e-3 * scale
        )

    def test_shard_seams_are_clean(self):
        """The samples at shard boundaries must not show discontinuities
        larger than the filter's leakage tolerance."""
        T, C, ratio, halo = 4000, 8, 10, 250
        data = _signal(T, C, self.fs, seed=2)
        mesh = make_mesh(8, time_shards=4)
        out = np.asarray(
            sharded_lowpass_decimate(mesh, data, 1 / self.fs, 2.0, ratio, halo)
        )
        ref = self._reference(data, 2.0, ratio, halo)
        # compare *everywhere* against the zero-padded single-device
        # reference, including across seams
        scale = np.abs(ref).max()
        assert np.abs(out - ref).max() < 5e-3 * scale

    def test_alignment_validation(self):
        mesh = make_mesh(8, time_shards=2)
        data = np.zeros((4001, 16), np.float32)
        with pytest.raises(ValueError, match="divisible"):
            sharded_lowpass_decimate(mesh, data, 0.01, 2.0, 10, 100)


class TestBatchedRolling:
    def test_matches_per_patch_kernel(self):
        B, T, C, w, s = 8, 500, 4, 50, 50
        rng = np.random.default_rng(3)
        batch = rng.standard_normal((B, T, C)).astype(np.float32)
        mesh = make_mesh(8)
        out = np.asarray(batched_rolling_mean(mesh, batch, w, s))
        for b in range(B):
            ref = np.asarray(rolling_reduce(batch[b], w, s, "mean"))
            assert np.allclose(out[b], ref, atol=1e-5, equal_nan=True)


class TestShardedCascade:
    """sharded_cascade_decimate must be bit-equal to the single-device
    cascade — the halo exchange and shard grid are layout, not math."""

    def _plan(self, fs=100.0, ratio=20):
        from tpudas.ops.fir import design_cascade

        return design_cascade(fs, ratio, 0.45, 4)

    @pytest.mark.parametrize("time_shards", [1, 2, 4])
    def test_bit_equal_to_single_device(self, time_shards):
        from tpudas.ops.fir import cascade_decimate
        from tpudas.parallel.pipeline import sharded_cascade_decimate

        plan = self._plan()
        mesh = make_mesh(8, time_shards=time_shards)
        T, C = 12000, 12  # C=12 not divisible by ch shards: pad path
        x = _signal(T, C, 100.0, seed=3)
        phase, n_out = 200, 110
        ref = np.asarray(cascade_decimate(x, plan, phase, n_out, "xla"))
        out = sharded_cascade_decimate(mesh, x, plan, phase, n_out)
        assert out is not None
        assert np.array_equal(np.asarray(out), ref)

    def test_unfit_layout_returns_none(self):
        from tpudas.parallel.pipeline import sharded_cascade_decimate

        plan = self._plan()
        mesh = make_mesh(8, time_shards=8)
        # tiny window: local blocks far smaller than the filter halo
        x = _signal(600, 4, 100.0)
        assert sharded_cascade_decimate(mesh, x, plan, 10, 8) is None

    def test_window_dp_matches_per_window(self):
        """batched_cascade_decimate (window DP + channel sharding) ==
        stacked per-window cascade_decimate, bit for bit."""
        from tpudas.ops.fir import cascade_decimate
        from tpudas.parallel.batch import batched_cascade_decimate

        plan = self._plan()
        mesh = make_mesh(8, time_shards=2)  # (time=2 -> DP axis, ch=4)
        rng = np.random.default_rng(9)
        W, T, C = 3, 9000, 6  # W not divisible by dp, C not by ch
        stack = rng.standard_normal((W, T, C)).astype(np.float32)
        phase, n_out = 150, 80
        out = np.asarray(
            batched_cascade_decimate(mesh, stack, plan, phase, n_out)
        )
        assert out.shape == (W, n_out, C)
        for wdx in range(W):
            ref = np.asarray(
                cascade_decimate(stack[wdx], plan, phase, n_out, "xla")
            )
            assert np.array_equal(out[wdx], ref), wdx

    def test_lfproc_window_dp_byte_equal(self, tmp_path):
        """LFProc with window_dp batches steady-state windows over the
        mesh "time" axis and stays byte-identical to the single-device
        serial run."""
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool
        from tpudas.utils.logging import set_log_handler

        d = tmp_path / "raw"
        make_synthetic_spool(
            d, n_files=6, file_duration=30.0, fs=100.0, n_ch=6, noise=0.01
        )
        t0 = np.datetime64("2023-03-22T00:00:00")
        t1 = np.datetime64("2023-03-22T00:03:00")
        events = []
        set_log_handler(events.append)
        try:
            results = {}
            for label, mesh, dp in (
                ("serial", None, False),
                ("dp", make_mesh(8, time_shards=2), True),
            ):
                lfp = LFProc(spool(str(d)).sort("time").update(), mesh=mesh)
                lfp.update_processing_parameter(
                    output_sample_interval=1.0,
                    process_patch_size=60,
                    edge_buff_size=10,
                    window_dp=dp,
                )
                out = tmp_path / f"out_{label}"
                lfp.set_output_folder(str(out), delete_existing=True)
                lfp.process_time_range(t0, t1)
                results[label] = (
                    spool(str(out)).update().chunk(time=None)[0].host_data()
                )
                if dp:
                    assert sum(lfp.engine_counts.values()) == 4
        finally:
            set_log_handler(None)
        batches = [e for e in events if e["event"] == "window_dp_batch"]
        assert batches, "no DP batch actually ran"
        assert sum(e["windows"] for e in batches) >= 2
        assert np.array_equal(results["serial"], results["dp"])

    def test_lfproc_window_dp_failure_latches_off(self, tmp_path,
                                                  monkeypatch):
        """One batch-compute failure disables window_dp for the rest
        of the run (no doomed stack transfer per batch) while the
        per-window path completes the work."""
        import tpudas.parallel.batch as batch_mod
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool
        from tpudas.utils.logging import set_log_handler

        d = tmp_path / "raw"
        make_synthetic_spool(
            d, n_files=6, file_duration=30.0, fs=100.0, n_ch=6, noise=0.01
        )

        def boom(*a, **k):
            raise RuntimeError("batch compute failure (synthetic)")

        monkeypatch.setattr(batch_mod, "batched_cascade_decimate", boom)
        events = []
        set_log_handler(events.append)
        try:
            lfp = LFProc(
                spool(str(d)).sort("time").update(),
                mesh=make_mesh(8, time_shards=2),
            )
            lfp.update_processing_parameter(
                output_sample_interval=1.0,
                process_patch_size=60,
                edge_buff_size=10,
                window_dp=True,
            )
            out = tmp_path / "out"
            lfp.set_output_folder(str(out), delete_existing=True)
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:03:00"),
            )
        finally:
            set_log_handler(None)
        assert not lfp._window_dp_ok
        falls = [e for e in events if e["event"] == "window_dp_fallback"]
        assert len(falls) == 1, falls  # latched after the first failure
        assert sum(lfp.engine_counts.values()) == 4  # all windows done
        assert len(list(out.iterdir())) == 4

    @pytest.mark.slow  # ~70 s: two full LFProc runs on the mesh
    def test_lfproc_window_dp_crosscheck_catches_silent_corruption(
        self, tmp_path, monkeypatch
    ):
        """A batched-lowering miscompile that RETURNS wrong numbers is
        caught by the first-batch cross-check; the batch resolves
        per-window (whose own chain lands on XLA), window-DP batching
        itself stays enabled and later batches run under XLA — and the
        emitted output is byte-equal to a serial run."""
        import tpudas.ops.fir as fir_mod
        import tpudas.ops.pallas_fir as pf_mod
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool
        from tpudas.utils.logging import set_log_handler

        d = tmp_path / "raw"
        # 8 files / 4 min -> five same-key interior windows: one batch
        # fails the cross-check, and at least one LATER batch must
        # still run (on XLA) to prove batching was not latched off
        make_synthetic_spool(
            d, n_files=8, file_duration=30.0, fs=100.0, n_ch=6, noise=0.01
        )

        real = pf_mod.fir_decimate_pallas

        def corrupt(x, hb, R, n_out, **kw):
            return real(x, hb, R, n_out=n_out, **kw) * 1.7

        monkeypatch.delenv("TPUDAS_PALLAS_IMPL", raising=False)
        fir_mod._layout_for.cache_clear()
        fir_mod._clear_cascade_caches()
        monkeypatch.setattr(
            fir_mod, "resolve_cascade_engine",
            lambda e="auto": "pallas" if e == "auto" else e,
        )
        monkeypatch.setattr(fir_mod, "_pallas_stage_ok", lambda *a: True)
        monkeypatch.setattr(pf_mod, "fir_decimate_pallas", corrupt)
        events = []
        set_log_handler(events.append)
        try:
            results = {}
            for label, mesh, dp in (
                ("dp", make_mesh(8, time_shards=2), True),
                ("serial", None, False),
            ):
                lfp = LFProc(spool(str(d)).sort("time").update(), mesh=mesh)
                lfp.update_processing_parameter(
                    output_sample_interval=1.0,
                    process_patch_size=60,
                    edge_buff_size=10,
                    window_dp=dp,
                )
                out = tmp_path / f"out_{label}"
                lfp.set_output_folder(str(out), delete_existing=True)
                lfp.process_time_range(
                    np.datetime64("2023-03-22T00:00:00"),
                    np.datetime64("2023-03-22T00:04:00"),
                )
                results[label] = (
                    spool(str(out)).update().chunk(time=None)[0].host_data()
                )
                if dp:
                    assert lfp._window_dp_ok  # batching NOT latched off
                    assert not lfp._pallas_ok  # the engine was
                    assert lfp.engine_counts["cascade-pallas"] == 0
        finally:
            os.environ.pop("TPUDAS_PALLAS_IMPL", None)
            set_log_handler(None)
            fir_mod._layout_for.cache_clear()
            fir_mod._clear_cascade_caches()
        fails = [
            e for e in events if e["event"] == "window_dp_crosscheck_fail"
        ]
        assert len(fails) == 1, fails
        assert "pallas-vs-xla rel err" in fails[0]["error"]
        # batching continued AFTER the failure, on the XLA engine
        later = [
            e for e in events
            if e["event"] == "window_dp_batch"
            and e["engine"] == "cascade-xla"
        ]
        assert later, "no XLA-engine batch ran after the cross-check"
        assert np.array_equal(results["dp"], results["serial"])

    def test_window_dp_custom_single_axis_mesh(self):
        """A 1-axis DP mesh (no channel axis) leaves channels
        unsharded instead of crashing on the spec."""
        import jax
        from jax.sharding import Mesh

        from tpudas.ops.fir import cascade_decimate
        from tpudas.parallel.batch import batched_cascade_decimate

        plan = self._plan()
        mesh = Mesh(np.array(jax.devices()[:4]), ("win",))
        rng = np.random.default_rng(11)
        stack = rng.standard_normal((4, 9000, 6)).astype(np.float32)
        out = np.asarray(
            batched_cascade_decimate(
                mesh, stack, plan, 150, 80, batch_axis="win"
            )
        )
        ref = np.asarray(cascade_decimate(stack[2], plan, 150, 80, "xla"))
        assert np.array_equal(out[2], ref)

    def test_window_dp_quantized(self):
        from tpudas.ops.fir import cascade_decimate
        from tpudas.parallel.batch import batched_cascade_decimate

        plan = self._plan()
        mesh = make_mesh(8, time_shards=4)
        rng = np.random.default_rng(10)
        q = rng.integers(-3000, 3000, size=(4, 9000, 8)).astype(np.int16)
        s = 1e-3
        out = np.asarray(
            batched_cascade_decimate(mesh, q, plan, 150, 80, qscale=s)
        )
        for wdx in range(4):
            ref = np.asarray(
                cascade_decimate(q[wdx], plan, 150, 80, "xla", qscale=s)
            )
            assert np.array_equal(out[wdx], ref), wdx

    def test_quantized_bit_equal_to_single_device(self):
        """Raw int16 windows shard undecoded (half the ICI halo bytes);
        the result matches the single-device quantized cascade bit for
        bit, which itself matches decode-then-cascade."""
        from tpudas.ops.fir import cascade_decimate
        from tpudas.parallel.pipeline import sharded_cascade_decimate

        plan = self._plan()
        mesh = make_mesh(8, time_shards=2)
        rng = np.random.default_rng(7)
        q = rng.integers(-3000, 3000, size=(12000, 12)).astype(np.int16)
        s = 1e-3
        phase, n_out = 200, 110
        ref = np.asarray(
            cascade_decimate(q, plan, phase, n_out, "xla", qscale=s)
        )
        out = sharded_cascade_decimate(
            mesh, q, plan, phase, n_out, qscale=s
        )
        assert out is not None
        assert np.array_equal(np.asarray(out), ref)


class TestLFProcMesh:
    """The product engine runs mesh-sharded end to end: output files
    must be byte-identical to the single-device run (VERDICT r3 #2)."""

    def _run(self, src, out_dir, mesh, engine="auto"):
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc

        lfp = LFProc(spool(str(src)).sort("time").update(), mesh=mesh)
        lfp.update_processing_parameter(
            output_sample_interval=1.0,
            process_patch_size=60,
            edge_buff_size=10,
            engine=engine,
        )
        lfp.set_output_folder(str(out_dir), delete_existing=True)
        lfp.process_time_range(
            np.datetime64("2023-03-22T00:00:00"),
            np.datetime64("2023-03-22T00:03:00"),
        )
        return lfp

    @pytest.fixture(scope="class")
    def src(self, tmp_path_factory):
        from tpudas.testing import make_synthetic_spool

        d = tmp_path_factory.mktemp("mesh_raw")
        make_synthetic_spool(
            d, n_files=6, file_duration=30.0, fs=100.0, n_ch=12, noise=0.01
        )
        return d

    @pytest.mark.parametrize(
        "time_shards,engine",
        [(1, "auto"), (2, "auto"), (4, "auto"), (1, "fft"), (2, "fft")],
    )
    def test_sharded_files_byte_identical(
        self, src, tmp_path, time_shards, engine
    ):
        from tpudas import spool

        single = self._run(src, tmp_path / "single", None, engine)
        mesh = make_mesh(8, time_shards=time_shards)
        sharded = self._run(src, tmp_path / "sharded", mesh, engine)
        a = spool(str(tmp_path / "single")).update().chunk(time=None)[0]
        b = spool(str(tmp_path / "sharded")).update().chunk(time=None)[0]
        assert np.array_equal(a.host_data(), b.host_data())
        assert np.array_equal(a.coords["time"], b.coords["time"])
        # same engines fired, just sharded
        assert sharded.engine_counts == single.engine_counts

    def test_streaming_driver_takes_mesh(self, src, tmp_path):
        from tpudas import spool
        from tpudas.proc.streaming import run_lowpass_realtime

        mesh = make_mesh(8, time_shards=2)
        out = tmp_path / "rt_out"
        rounds = run_lowpass_realtime(
            str(src),
            str(out),
            "2023-03-22T00:00:00",
            output_sample_interval=1.0,
            edge_buffer=10.0,
            process_patch_size=60,
            poll_interval=0.0,
            sleep_fn=lambda s: None,
            max_rounds=3,
            mesh=mesh,
        )
        assert rounds >= 1
        merged = spool(str(out)).update().chunk(time=None)
        assert len(merged) == 1  # seam-free under the mesh

    def test_mesh_without_ch_axis_rejected(self):
        from jax.sharding import Mesh

        from tpudas.proc.lfproc import LFProc

        bad = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("a", "b"))
        with pytest.raises(ValueError, match="'ch' axis"):
            LFProc(None, mesh=bad)


class TestRollingRealtimeMesh:
    def test_mesh_batched_equals_per_patch(self, tmp_path):
        """run_rolling_realtime(mesh=...) batches fresh patches over
        the mesh and must write byte-identical outputs to the
        per-patch path (DP over patches in the PRODUCT driver)."""
        from tpudas import spool
        from tpudas.core.units import s as sec
        from tpudas.proc.streaming import run_rolling_realtime
        from tpudas.testing import make_synthetic_spool

        src = tmp_path / "raw"
        make_synthetic_spool(
            src, n_files=5, file_duration=30.0, fs=100.0, n_ch=12,
            noise=0.05,
        )
        results = {}
        for label, mesh in (("plain", None), ("mesh", make_mesh(8))):
            out = tmp_path / f"out_{label}"
            rounds = run_rolling_realtime(
                str(src),
                str(out),
                window=1.0 * sec,
                step=1.0 * sec,
                scale=2.0,
                poll_interval=0.0,
                sleep_fn=lambda s: None,
                max_rounds=2,
                mesh=mesh,
            )
            assert rounds >= 1
            merged = spool(str(out)).sort("time").update().chunk(time=None)
            results[label] = [p.host_data() for p in merged]
        assert len(results["plain"]) == len(results["mesh"])
        for a, b in zip(results["plain"], results["mesh"]):
            assert np.array_equal(a, b, equal_nan=True)

    def test_non_uniform_batch_falls_back(self, tmp_path):
        # mixed channel counts cannot stack: the driver must fall back
        # to the per-patch path, not crash or drop patches
        from tpudas import spool
        from tpudas.core.units import s as sec
        from tpudas.proc.streaming import run_rolling_realtime
        from tpudas.testing import make_synthetic_spool

        src = tmp_path / "raw"
        make_synthetic_spool(
            src, n_files=2, file_duration=30.0, fs=100.0, n_ch=12
        )
        make_synthetic_spool(
            src, n_files=1, file_duration=30.0, fs=100.0, n_ch=8,
            start="2023-03-22T00:01:00", prefix="other",
        )
        out = tmp_path / "out"
        rounds = run_rolling_realtime(
            str(src), str(out), window=1.0 * sec, step=1.0 * sec,
            poll_interval=0.0, sleep_fn=lambda s: None, max_rounds=2,
            mesh=make_mesh(8),
        )
        assert rounds >= 1
        assert len(spool(str(out)).update()) == 3  # every patch written
