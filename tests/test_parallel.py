"""Sharded execution on the 8-device CPU mesh: channel sharding,
time-shard halo exchange, batched data parallelism — all must agree
with the single-device kernels."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudas.ops.filter import fft_pass_filter
from tpudas.ops.rolling import rolling_reduce
from tpudas.parallel.batch import batched_rolling_mean
from tpudas.parallel.mesh import make_mesh
from tpudas.parallel.pipeline import sharded_lowpass_decimate
from tpudas.parallel.sharding import shard_channels


@pytest.fixture(scope="module", autouse=True)
def require_8_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")


def _signal(T, C, fs, seed=0):
    rng = np.random.default_rng(seed)
    t = np.arange(T) / fs
    lf = np.sin(2 * np.pi * 0.05 * t)[:, None] * (1 + np.arange(C))[None, :]
    return (lf + 0.3 * rng.standard_normal((T, C))).astype(np.float32)


class TestMesh:
    def test_make_mesh_shapes(self):
        m = make_mesh(8, time_shards=2)
        assert m.shape["time"] == 2 and m.shape["ch"] == 4
        m1 = make_mesh(8)
        assert m1.shape["time"] == 1 and m1.shape["ch"] == 8

    def test_bad_factorization(self):
        with pytest.raises(ValueError):
            make_mesh(8, time_shards=3)


class TestChannelSharding:
    def test_zero_comm_filter_matches_single_device(self):
        fs = 100.0
        data = _signal(3000, 16, fs)
        ref = np.asarray(fft_pass_filter(data, 1 / fs, high=2.0))
        mesh = make_mesh(8)
        sharded = shard_channels(jnp.asarray(data), mesh)
        out = fft_pass_filter(sharded, 1 / fs, high=2.0)
        assert np.allclose(np.asarray(out), ref, atol=1e-4)


class TestShardedPipeline:
    fs = 100.0

    def _reference(self, data, corner, ratio, halo):
        """Single-device equivalent: zero-pad halo at the stream ends
        (matching the boundary shards' ppermute zeros), filter, trim,
        stride."""
        T = data.shape[0]
        padded = np.concatenate(
            [
                np.zeros((halo,) + data.shape[1:], data.dtype),
                data,
                np.zeros((halo,) + data.shape[1:], data.dtype),
            ]
        )
        filt = np.asarray(fft_pass_filter(padded, 1 / self.fs, high=corner))
        return filt[halo : halo + T : ratio]

    @pytest.mark.parametrize("time_shards", [1, 2, 4])
    @pytest.mark.slow
    def test_matches_interior_of_unsharded(self, time_shards):
        T, C, ratio, halo = 4000, 16, 10, 200
        data = _signal(T, C, self.fs, seed=1)
        corner = 2.0
        mesh = make_mesh(8, time_shards=time_shards)
        out = np.asarray(
            sharded_lowpass_decimate(
                mesh, data, 1 / self.fs, corner, ratio, halo
            )
        )
        assert out.shape == (T // ratio, C)
        ref = np.asarray(fft_pass_filter(data, 1 / self.fs, high=corner))[::ratio]
        # interior: away from every shard seam by > halo output samples
        # the halo is sized so seams are exact within filter leakage
        interior = slice(halo // ratio + 1, -(halo // ratio + 1))
        scale = np.abs(ref).max()
        assert (
            np.abs(out[interior] - ref[interior]).max() < 5e-3 * scale
        )

    def test_shard_seams_are_clean(self):
        """The samples at shard boundaries must not show discontinuities
        larger than the filter's leakage tolerance."""
        T, C, ratio, halo = 4000, 8, 10, 250
        data = _signal(T, C, self.fs, seed=2)
        mesh = make_mesh(8, time_shards=4)
        out = np.asarray(
            sharded_lowpass_decimate(mesh, data, 1 / self.fs, 2.0, ratio, halo)
        )
        ref = self._reference(data, 2.0, ratio, halo)
        # compare *everywhere* against the zero-padded single-device
        # reference, including across seams
        scale = np.abs(ref).max()
        assert np.abs(out - ref).max() < 5e-3 * scale

    def test_alignment_validation(self):
        mesh = make_mesh(8, time_shards=2)
        data = np.zeros((4001, 16), np.float32)
        with pytest.raises(ValueError, match="divisible"):
            sharded_lowpass_decimate(mesh, data, 0.01, 2.0, 10, 100)


class TestBatchedRolling:
    def test_matches_per_patch_kernel(self):
        B, T, C, w, s = 8, 500, 4, 50, 50
        rng = np.random.default_rng(3)
        batch = rng.standard_normal((B, T, C)).astype(np.float32)
        mesh = make_mesh(8)
        out = np.asarray(batched_rolling_mean(mesh, batch, w, s))
        for b in range(B):
            ref = np.asarray(rolling_reduce(batch[b], w, s, "mean"))
            assert np.allclose(out[b], ref, atol=1e-5, equal_nan=True)


class TestShardedCascade:
    """sharded_cascade_decimate must be bit-equal to the single-device
    cascade — the halo exchange and shard grid are layout, not math."""

    def _plan(self, fs=100.0, ratio=20):
        from tpudas.ops.fir import design_cascade

        return design_cascade(fs, ratio, 0.45, 4)

    @pytest.mark.parametrize("time_shards", [1, 2, 4])
    @pytest.mark.slow
    def test_bit_equal_to_single_device(self, time_shards):
        from tpudas.ops.fir import cascade_decimate
        from tpudas.parallel.pipeline import sharded_cascade_decimate

        plan = self._plan()
        mesh = make_mesh(8, time_shards=time_shards)
        T, C = 12000, 12  # C=12 not divisible by ch shards: pad path
        x = _signal(T, C, 100.0, seed=3)
        phase, n_out = 200, 110
        ref = np.asarray(cascade_decimate(x, plan, phase, n_out, "xla"))
        out = sharded_cascade_decimate(mesh, x, plan, phase, n_out)
        assert out is not None
        assert np.array_equal(np.asarray(out), ref)

    def test_unfit_layout_returns_none(self):
        from tpudas.parallel.pipeline import sharded_cascade_decimate

        plan = self._plan()
        mesh = make_mesh(8, time_shards=8)
        # tiny window: local blocks far smaller than the filter halo
        x = _signal(600, 4, 100.0)
        assert sharded_cascade_decimate(mesh, x, plan, 10, 8) is None

    @pytest.mark.slow
    def test_window_dp_matches_per_window(self):
        """batched_cascade_decimate (window DP + channel sharding) ==
        stacked per-window cascade_decimate, bit for bit."""
        from tpudas.ops.fir import cascade_decimate
        from tpudas.parallel.batch import batched_cascade_decimate

        plan = self._plan()
        mesh = make_mesh(8, time_shards=2)  # (time=2 -> DP axis, ch=4)
        rng = np.random.default_rng(9)
        W, T, C = 3, 9000, 6  # W not divisible by dp, C not by ch
        stack = rng.standard_normal((W, T, C)).astype(np.float32)
        phase, n_out = 150, 80
        out = np.asarray(
            batched_cascade_decimate(mesh, stack, plan, phase, n_out)
        )
        assert out.shape == (W, n_out, C)
        for wdx in range(W):
            ref = np.asarray(
                cascade_decimate(stack[wdx], plan, phase, n_out, "xla")
            )
            assert np.array_equal(out[wdx], ref), wdx

    def test_lfproc_window_dp_byte_equal(self, tmp_path):
        """LFProc with window_dp batches steady-state windows over the
        mesh "time" axis and stays byte-identical to the single-device
        serial run."""
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool
        from tpudas.utils.logging import set_log_handler

        d = tmp_path / "raw"
        make_synthetic_spool(
            d, n_files=6, file_duration=30.0, fs=100.0, n_ch=6, noise=0.01
        )
        t0 = np.datetime64("2023-03-22T00:00:00")
        t1 = np.datetime64("2023-03-22T00:03:00")
        events = []
        set_log_handler(events.append)
        try:
            results = {}
            for label, mesh, dp in (
                ("serial", None, False),
                ("dp", make_mesh(8, time_shards=2), True),
            ):
                lfp = LFProc(spool(str(d)).sort("time").update(), mesh=mesh)
                lfp.update_processing_parameter(
                    output_sample_interval=1.0,
                    process_patch_size=60,
                    edge_buff_size=10,
                    window_dp=dp,
                )
                out = tmp_path / f"out_{label}"
                lfp.set_output_folder(str(out), delete_existing=True)
                lfp.process_time_range(t0, t1)
                results[label] = (
                    spool(str(out)).update().chunk(time=None)[0].host_data()
                )
                if dp:
                    assert sum(lfp.engine_counts.values()) == 4
        finally:
            set_log_handler(None)
        batches = [e for e in events if e["event"] == "window_dp_batch"]
        assert batches, "no DP batch actually ran"
        assert sum(e["windows"] for e in batches) >= 2
        assert np.array_equal(results["serial"], results["dp"])

    def test_lfproc_window_dp_failure_latches_off(self, tmp_path,
                                                  monkeypatch):
        """One batch-compute failure disables window_dp for the rest
        of the run (no doomed stack transfer per batch) while the
        per-window path completes the work."""
        import tpudas.parallel.batch as batch_mod
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool
        from tpudas.utils.logging import set_log_handler

        d = tmp_path / "raw"
        make_synthetic_spool(
            d, n_files=6, file_duration=30.0, fs=100.0, n_ch=6, noise=0.01
        )

        def boom(*a, **k):
            raise RuntimeError("batch compute failure (synthetic)")

        monkeypatch.setattr(batch_mod, "batched_cascade_decimate", boom)
        events = []
        set_log_handler(events.append)
        try:
            lfp = LFProc(
                spool(str(d)).sort("time").update(),
                mesh=make_mesh(8, time_shards=2),
            )
            lfp.update_processing_parameter(
                output_sample_interval=1.0,
                process_patch_size=60,
                edge_buff_size=10,
                window_dp=True,
            )
            out = tmp_path / "out"
            lfp.set_output_folder(str(out), delete_existing=True)
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:03:00"),
            )
        finally:
            set_log_handler(None)
        assert not lfp._window_dp_ok
        falls = [e for e in events if e["event"] == "window_dp_fallback"]
        assert len(falls) == 1, falls  # latched after the first failure
        assert sum(lfp.engine_counts.values()) == 4  # all windows done
        assert len(list(out.iterdir())) == 4

    @pytest.mark.slow  # ~70 s: two full LFProc runs on the mesh
    def test_lfproc_window_dp_crosscheck_catches_silent_corruption(
        self, tmp_path, monkeypatch
    ):
        """A batched-lowering miscompile that RETURNS wrong numbers is
        caught by the first-batch cross-check; the batch resolves
        per-window (whose own chain lands on XLA), window-DP batching
        itself stays enabled and later batches run under XLA — and the
        emitted output is byte-equal to a serial run."""
        import tpudas.ops.fir as fir_mod
        import tpudas.ops.pallas_fir as pf_mod
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool
        from tpudas.utils.logging import set_log_handler

        d = tmp_path / "raw"
        # 8 files / 4 min -> five same-key interior windows: one batch
        # fails the cross-check, and at least one LATER batch must
        # still run (on XLA) to prove batching was not latched off
        make_synthetic_spool(
            d, n_files=8, file_duration=30.0, fs=100.0, n_ch=6, noise=0.01
        )

        real = pf_mod.fir_decimate_pallas

        def corrupt(x, hb, R, n_out, **kw):
            return real(x, hb, R, n_out=n_out, **kw) * 1.7

        monkeypatch.delenv("TPUDAS_PALLAS_IMPL", raising=False)
        fir_mod._layout_for.cache_clear()
        fir_mod._clear_cascade_caches()
        monkeypatch.setattr(
            fir_mod, "resolve_cascade_engine",
            lambda e="auto": "pallas" if e == "auto" else e,
        )
        monkeypatch.setattr(fir_mod, "_pallas_stage_ok", lambda *a: True)
        monkeypatch.setattr(pf_mod, "fir_decimate_pallas", corrupt)
        events = []
        set_log_handler(events.append)
        try:
            results = {}
            for label, mesh, dp in (
                ("dp", make_mesh(8, time_shards=2), True),
                ("serial", None, False),
            ):
                lfp = LFProc(spool(str(d)).sort("time").update(), mesh=mesh)
                lfp.update_processing_parameter(
                    output_sample_interval=1.0,
                    process_patch_size=60,
                    edge_buff_size=10,
                    window_dp=dp,
                )
                out = tmp_path / f"out_{label}"
                lfp.set_output_folder(str(out), delete_existing=True)
                lfp.process_time_range(
                    np.datetime64("2023-03-22T00:00:00"),
                    np.datetime64("2023-03-22T00:04:00"),
                )
                results[label] = (
                    spool(str(out)).update().chunk(time=None)[0].host_data()
                )
                if dp:
                    assert lfp._window_dp_ok  # batching NOT latched off
                    assert not lfp._pallas_ok  # the engine was
                    assert lfp.engine_counts["cascade-pallas"] == 0
        finally:
            os.environ.pop("TPUDAS_PALLAS_IMPL", None)
            set_log_handler(None)
            fir_mod._layout_for.cache_clear()
            fir_mod._clear_cascade_caches()
        fails = [
            e for e in events if e["event"] == "window_dp_crosscheck_fail"
        ]
        assert len(fails) == 1, fails
        assert "pallas-vs-xla rel err" in fails[0]["error"]
        # batching continued AFTER the failure, on the XLA engine
        later = [
            e for e in events
            if e["event"] == "window_dp_batch"
            and e["engine"] == "cascade-xla"
        ]
        assert later, "no XLA-engine batch ran after the cross-check"
        assert np.array_equal(results["dp"], results["serial"])

    def test_window_dp_custom_single_axis_mesh(self):
        """A 1-axis DP mesh (no channel axis) leaves channels
        unsharded instead of crashing on the spec."""
        import jax
        from jax.sharding import Mesh

        from tpudas.ops.fir import cascade_decimate
        from tpudas.parallel.batch import batched_cascade_decimate

        plan = self._plan()
        mesh = Mesh(np.array(jax.devices()[:4]), ("win",))
        rng = np.random.default_rng(11)
        stack = rng.standard_normal((4, 9000, 6)).astype(np.float32)
        out = np.asarray(
            batched_cascade_decimate(
                mesh, stack, plan, 150, 80, batch_axis="win"
            )
        )
        ref = np.asarray(cascade_decimate(stack[2], plan, 150, 80, "xla"))
        assert np.array_equal(out[2], ref)

    @pytest.mark.slow
    def test_window_dp_quantized(self):
        from tpudas.ops.fir import cascade_decimate
        from tpudas.parallel.batch import batched_cascade_decimate

        plan = self._plan()
        mesh = make_mesh(8, time_shards=4)
        rng = np.random.default_rng(10)
        q = rng.integers(-3000, 3000, size=(4, 9000, 8)).astype(np.int16)
        s = 1e-3
        out = np.asarray(
            batched_cascade_decimate(mesh, q, plan, 150, 80, qscale=s)
        )
        for wdx in range(4):
            ref = np.asarray(
                cascade_decimate(q[wdx], plan, 150, 80, "xla", qscale=s)
            )
            assert np.array_equal(out[wdx], ref), wdx

    def test_quantized_bit_equal_to_single_device(self):
        """Raw int16 windows shard undecoded (half the ICI halo bytes);
        the result matches the single-device quantized cascade bit for
        bit, which itself matches decode-then-cascade."""
        from tpudas.ops.fir import cascade_decimate
        from tpudas.parallel.pipeline import sharded_cascade_decimate

        plan = self._plan()
        mesh = make_mesh(8, time_shards=2)
        rng = np.random.default_rng(7)
        q = rng.integers(-3000, 3000, size=(12000, 12)).astype(np.int16)
        s = 1e-3
        phase, n_out = 200, 110
        ref = np.asarray(
            cascade_decimate(q, plan, phase, n_out, "xla", qscale=s)
        )
        out = sharded_cascade_decimate(
            mesh, q, plan, phase, n_out, qscale=s
        )
        assert out is not None
        assert np.array_equal(np.asarray(out), ref)


class TestLFProcMesh:
    """The product engine runs mesh-sharded end to end: output files
    must be byte-identical to the single-device run (VERDICT r3 #2)."""

    def _run(self, src, out_dir, mesh, engine="auto"):
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc

        lfp = LFProc(spool(str(src)).sort("time").update(), mesh=mesh)
        lfp.update_processing_parameter(
            output_sample_interval=1.0,
            process_patch_size=60,
            edge_buff_size=10,
            engine=engine,
        )
        lfp.set_output_folder(str(out_dir), delete_existing=True)
        lfp.process_time_range(
            np.datetime64("2023-03-22T00:00:00"),
            np.datetime64("2023-03-22T00:03:00"),
        )
        return lfp

    @pytest.fixture(scope="class")
    def src(self, tmp_path_factory):
        from tpudas.testing import make_synthetic_spool

        d = tmp_path_factory.mktemp("mesh_raw")
        make_synthetic_spool(
            d, n_files=6, file_duration=30.0, fs=100.0, n_ch=12, noise=0.01
        )
        return d

    @pytest.mark.parametrize(
        "time_shards,engine",
        [(1, "auto"), (2, "auto"), (4, "auto"), (1, "fft"), (2, "fft")],
    )
    @pytest.mark.slow
    def test_sharded_files_byte_identical(
        self, src, tmp_path, time_shards, engine
    ):
        from tpudas import spool

        single = self._run(src, tmp_path / "single", None, engine)
        mesh = make_mesh(8, time_shards=time_shards)
        sharded = self._run(src, tmp_path / "sharded", mesh, engine)
        a = spool(str(tmp_path / "single")).update().chunk(time=None)[0]
        b = spool(str(tmp_path / "sharded")).update().chunk(time=None)[0]
        assert np.array_equal(a.host_data(), b.host_data())
        assert np.array_equal(a.coords["time"], b.coords["time"])
        # same engines fired, just sharded
        assert sharded.engine_counts == single.engine_counts

    def test_streaming_driver_takes_mesh(self, src, tmp_path):
        from tpudas import spool
        from tpudas.proc.streaming import run_lowpass_realtime

        mesh = make_mesh(8, time_shards=2)
        out = tmp_path / "rt_out"
        rounds = run_lowpass_realtime(
            str(src),
            str(out),
            "2023-03-22T00:00:00",
            output_sample_interval=1.0,
            edge_buffer=10.0,
            process_patch_size=60,
            poll_interval=0.0,
            sleep_fn=lambda s: None,
            max_rounds=3,
            mesh=mesh,
        )
        assert rounds >= 1
        merged = spool(str(out)).update().chunk(time=None)
        assert len(merged) == 1  # seam-free under the mesh

    def test_mesh_without_ch_axis_rejected(self):
        from jax.sharding import Mesh

        from tpudas.proc.lfproc import LFProc

        bad = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("a", "b"))
        with pytest.raises(ValueError, match="'ch' axis"):
            LFProc(None, mesh=bad)


class TestRollingRealtimeMesh:
    def test_mesh_batched_equals_per_patch(self, tmp_path):
        """run_rolling_realtime(mesh=...) batches fresh patches over
        the mesh and must write byte-identical outputs to the
        per-patch path (DP over patches in the PRODUCT driver)."""
        from tpudas import spool
        from tpudas.core.units import s as sec
        from tpudas.proc.streaming import run_rolling_realtime
        from tpudas.testing import make_synthetic_spool

        src = tmp_path / "raw"
        make_synthetic_spool(
            src, n_files=5, file_duration=30.0, fs=100.0, n_ch=12,
            noise=0.05,
        )
        results = {}
        for label, mesh in (("plain", None), ("mesh", make_mesh(8))):
            out = tmp_path / f"out_{label}"
            rounds = run_rolling_realtime(
                str(src),
                str(out),
                window=1.0 * sec,
                step=1.0 * sec,
                scale=2.0,
                poll_interval=0.0,
                sleep_fn=lambda s: None,
                max_rounds=2,
                mesh=mesh,
            )
            assert rounds >= 1
            merged = spool(str(out)).sort("time").update().chunk(time=None)
            results[label] = [p.host_data() for p in merged]
        assert len(results["plain"]) == len(results["mesh"])
        for a, b in zip(results["plain"], results["mesh"]):
            assert np.array_equal(a, b, equal_nan=True)

    def test_non_uniform_batch_falls_back(self, tmp_path):
        # mixed channel counts cannot stack: the driver must fall back
        # to the per-patch path, not crash or drop patches
        from tpudas import spool
        from tpudas.core.units import s as sec
        from tpudas.proc.streaming import run_rolling_realtime
        from tpudas.testing import make_synthetic_spool

        src = tmp_path / "raw"
        make_synthetic_spool(
            src, n_files=2, file_duration=30.0, fs=100.0, n_ch=12
        )
        make_synthetic_spool(
            src, n_files=1, file_duration=30.0, fs=100.0, n_ch=8,
            start="2023-03-22T00:01:00", prefix="other",
        )
        out = tmp_path / "out"
        rounds = run_rolling_realtime(
            str(src), str(out), window=1.0 * sec, step=1.0 * sec,
            poll_interval=0.0, sleep_fn=lambda s: None, max_rounds=2,
            mesh=make_mesh(8),
        )
        assert rounds >= 1
        assert len(spool(str(out)).update()) == 3  # every patch written


# ---------------------------------------------------------------------------
# ISSUE 7: mesh-sharded realtime streaming


class TestHaloExchange:
    """Direct unit tests for tpudas.parallel.halo: the ppermute
    exchange against a host-padded reference, and the tap-derived halo
    width math."""

    def test_exchange_matches_padded_reference(self):
        from jax.sharding import PartitionSpec as P

        from tpudas.parallel.compat import shard_map
        from tpudas.parallel.halo import exchange_halo_time

        mesh = make_mesh(8, time_shards=4)
        T, C, halo = 64, 4, 5
        x = np.arange(T * C, dtype=np.float32).reshape(T, C)
        fn = shard_map(
            lambda b: exchange_halo_time(b, halo, n_shards=4),
            mesh=mesh,
            in_specs=P("time", "ch"),
            out_specs=P("time", "ch"),
            check_vma=False,
        )
        out = np.asarray(jax.jit(fn)(x))
        # reference: zero-pad the stream ends, then each shard's
        # extended block is a [T_loc + 2*halo] slice of the padded
        # stream — boundary shards see zeros, interior shards see
        # their neighbors' rows
        t_loc = T // 4
        padded = np.concatenate(
            [np.zeros((halo, C), np.float32), x,
             np.zeros((halo, C), np.float32)]
        )
        ref = np.concatenate(
            [padded[i * t_loc : i * t_loc + t_loc + 2 * halo]
             for i in range(4)]
        )
        assert np.array_equal(out, ref)

    def test_one_sided_exchange_halves_the_extension(self):
        from jax.sharding import PartitionSpec as P

        from tpudas.parallel.compat import shard_map
        from tpudas.parallel.halo import exchange_halo_time

        mesh = make_mesh(8, time_shards=4)
        T, C, halo = 64, 2, 4
        x = np.arange(T * C, dtype=np.float32).reshape(T, C)
        fn = shard_map(
            lambda b: exchange_halo_time(
                b, halo, n_shards=4, left=False
            ),
            mesh=mesh,
            in_specs=P("time", "ch"),
            out_specs=P("time", "ch"),
            check_vma=False,
        )
        out = np.asarray(jax.jit(fn)(x))
        t_loc = T // 4
        padded = np.concatenate([x, np.zeros((halo, C), np.float32)])
        ref = np.concatenate(
            [padded[i * t_loc : i * t_loc + t_loc + halo]
             for i in range(4)]
        )
        assert np.array_equal(out, ref)

    def test_halo_wider_than_shard_rejected(self):
        from tpudas.parallel.halo import exchange_halo_time

        with pytest.raises(ValueError, match="halo"):
            exchange_halo_time(jnp.zeros((8, 2)), 9, n_shards=2)

    def test_fir_halo_rows_from_taps(self):
        """fir_halo_rows == the cascade's exact look-ahead need: the
        telescoped (k + B - 1) * R input consumption minus the shard's
        own rows — and it matches the layout the sharded executor
        computes."""
        from tpudas.ops.fir import chain_layout, design_cascade
        from tpudas.parallel.halo import fir_halo_rows
        from tpudas.parallel.pipeline import sharded_cascade_layout

        plan = design_cascade(100.0, 20, 0.45, 4)
        for n_loc in (8, 55, 110):
            halo = fir_halo_rows(plan, n_loc)
            _, rows = chain_layout(plan, n_loc, 1, "auto")
            assert halo == rows - n_loc * plan.ratio
            assert halo > 0  # a causal FIR cascade always looks ahead
        mesh = make_mesh(8, time_shards=2)
        layout = sharded_cascade_layout(mesh, plan, 200, 110, 12000)
        assert layout is not None
        n_loc, t_local, halo = layout
        assert halo == fir_halo_rows(plan, n_loc)
        assert t_local == n_loc * plan.ratio


class TestPadMaskLayout:
    """sharding.py spec construction at non-divisible channel counts:
    the pad-and-mask layout (zero columns up to the shard multiple,
    trimmed back on gather)."""

    def test_channel_pad_values(self):
        from tpudas.parallel.sharding import channel_pad

        mesh = make_mesh(4)
        assert channel_pad(16, mesh) == 0
        assert channel_pad(10, mesh) == 2
        assert channel_pad(3, mesh) == 1
        assert channel_pad(1, mesh) == 3

    def test_pad_channels_host_and_device(self):
        from tpudas.parallel.sharding import pad_channels

        mesh = make_mesh(4)
        x = np.ones((5, 10), np.float32)
        p = pad_channels(x, mesh)
        assert isinstance(p, np.ndarray) and p.shape == (5, 12)
        assert np.array_equal(p[:, 10:], np.zeros((5, 2)))
        pj = pad_channels(jnp.asarray(x), mesh)
        assert pj.shape == (5, 12)
        assert np.array_equal(np.asarray(pj), p)
        # already divisible: returned untouched
        y = np.ones((5, 8), np.float32)
        assert pad_channels(y, mesh) is y

    def test_place_block_spec_and_gather_roundtrip(self):
        from jax.sharding import PartitionSpec as P

        from tpudas.parallel.sharding import (
            gather_leaves,
            is_device_resident,
            place_block,
        )

        mesh = make_mesh(4)
        x = np.random.default_rng(0).standard_normal(
            (32, 10)
        ).astype(np.float32)
        placed = place_block(x, mesh)
        assert is_device_resident(placed)
        assert placed.shape == (32, 12)  # padded to the shard multiple
        assert placed.sharding.spec == P(None, "ch")
        (back,) = gather_leaves((placed,), 10)
        assert isinstance(back, np.ndarray)
        assert np.array_equal(back, x)  # pad trimmed, bytes identical

    def test_transfer_accounting(self):
        """place/gather traffic lands in
        tpudas_parallel_transfer_bytes_total — what the bench reads to
        prove steady rounds stop round-tripping the carry."""
        from tpudas.obs.registry import MetricsRegistry, use_registry
        from tpudas.parallel.sharding import gather_leaves, place_block

        mesh = make_mesh(4)
        reg = MetricsRegistry()
        x = np.zeros((16, 8), np.float32)
        with use_registry(reg):
            placed = place_block(x, mesh)
            gather_leaves((placed,), 8)
            gather_leaves((np.zeros((4, 8), np.float32),), 8)  # host: free
        snap = reg.snapshot()["tpudas_parallel_transfer_bytes_total"]
        series = {
            tuple(sorted(labels.items())): value
            for labels, value in snap["series"]
        }
        assert series[(("direction", "place"),)] == x.nbytes
        assert series[(("direction", "gather"),)] == x.nbytes


class TestShardMapCompat:
    """tpudas.parallel.compat is the one blessed shard_map entrypoint;
    both replication-keyword spellings stay covered on any jax."""

    def test_rep_kwargs_both_spellings(self):
        from tpudas.parallel.compat import _rep_kwargs

        assert _rep_kwargs({"check_vma": None}, False) == {
            "check_vma": False
        }
        assert _rep_kwargs({"check_rep": None}, False) == {
            "check_rep": False
        }
        assert _rep_kwargs({"check_vma": None, "check_rep": None}, True) == {
            "check_vma": True
        }
        assert _rep_kwargs({}, True) == {}

    def test_wrapper_runs_on_installed_jax(self):
        from jax.sharding import PartitionSpec as P

        from tpudas.parallel.compat import shard_map

        mesh = make_mesh(4)
        fn = shard_map(
            lambda b: b * jax.lax.axis_index("ch").astype(jnp.float32),
            mesh=mesh,
            in_specs=P(None, "ch"),
            out_specs=P(None, "ch"),
            check_vma=False,
        )
        out = np.asarray(jax.jit(fn)(np.ones((2, 8), np.float32)))
        ref = np.repeat(np.arange(4, dtype=np.float32), 2)[None, :]
        assert np.array_equal(out, np.broadcast_to(ref, (2, 8)))

    def test_compat_is_the_only_entrypoint(self):
        """No tpudas module may import shard_map except the compat
        shim (the version-skew surface must stay one file wide)."""
        import re

        root = os.path.join(os.path.dirname(__file__), "..", "tpudas")
        offenders = []
        pat = re.compile(
            r"from\s+jax(\.experimental)?(\.shard_map)?\s+import"
            r"[^\n]*\bshard_map\b|jax\.experimental\.shard_map"
        )
        for dirpath, _dirs, files in os.walk(root):
            for name in files:
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                if os.path.basename(path) == "compat.py":
                    continue
                with open(path) as fh:
                    if pat.search(fh.read()):
                        offenders.append(os.path.relpath(path, root))
        assert not offenders, (
            f"import shard_map via tpudas.parallel.compat: {offenders}"
        )


class TestShardedStreamOps:
    """The sharded stream steps (cascade + fft) are byte-identical to
    the single-device steps, keep their carry resident on the mesh
    between calls, and trim the pad-and-mask columns on output."""

    @pytest.mark.parametrize("n_ch", [16, 10, 3])
    @pytest.mark.slow
    def test_cascade_stream_bit_equal_and_resident(self, n_ch):
        from jax.sharding import PartitionSpec as P

        from tpudas.ops.fir import (
            cascade_decimate_stream,
            cascade_stream_init,
            design_cascade,
        )
        from tpudas.parallel.sharding import is_device_resident

        mesh = make_mesh(4)
        plan = design_cascade(100.0, 20, 0.45, 4)
        rng = np.random.default_rng(5)
        blocks = [
            rng.standard_normal((t, n_ch)).astype(np.float32)
            for t in (400, 800, 400)
        ]
        ref_carry = cascade_stream_init(plan, n_ch)
        sh_carry = cascade_stream_init(plan, n_ch)
        for blk in blocks:
            y_ref, ref_carry = cascade_decimate_stream(
                blk, ref_carry, plan, "xla"
            )
            y_sh, sh_carry = cascade_decimate_stream(
                blk, sh_carry, plan, "xla", mesh=mesh
            )
            assert np.array_equal(np.asarray(y_ref), np.asarray(y_sh))
            for leaf in sh_carry:
                assert is_device_resident(leaf)
                assert leaf.sharding.spec == P(None, "ch")
                # padded to the shard multiple while resident
                assert leaf.shape[1] == n_ch + (-n_ch % 4)

    @pytest.mark.parametrize("n_ch", [16, 10])
    @pytest.mark.slow
    def test_fft_stream_bit_equal_and_resident(self, n_ch):
        from tpudas.ops.filter import (
            fft_pass_filter_stream,
            fft_stream_init,
        )
        from tpudas.parallel.sharding import is_device_resident

        mesh = make_mesh(4)
        rng = np.random.default_rng(6)
        blocks = [
            rng.standard_normal((t, n_ch)).astype(np.float32)
            for t in (256, 128)
        ]
        ref_carry = fft_stream_init(32, n_ch)
        sh_carry = fft_stream_init(32, n_ch)
        for blk in blocks:
            y_ref, ref_carry = fft_pass_filter_stream(
                blk, ref_carry, 0.01, high=2.0
            )
            y_sh, sh_carry = fft_pass_filter_stream(
                blk, sh_carry, 0.01, high=2.0, mesh=mesh
            )
            assert np.array_equal(np.asarray(y_ref), np.asarray(y_sh))
            assert is_device_resident(sh_carry)
        assert np.array_equal(
            np.asarray(ref_carry), np.asarray(sh_carry)[:, :n_ch]
        )

    def test_mismatched_carry_width_rejected(self):
        from tpudas.ops.fir import (
            cascade_decimate_stream,
            cascade_stream_init,
            design_cascade,
        )

        mesh = make_mesh(4)
        plan = design_cascade(100.0, 20, 0.45, 4)
        carry = cascade_stream_init(plan, 6)
        x = np.zeros((400, 16), np.float32)
        with pytest.raises(ValueError):
            cascade_decimate_stream(x, carry, plan, "xla", mesh=mesh)


class TestResolveMesh:
    def test_int_env_and_passthrough(self, monkeypatch):
        from tpudas.parallel.mesh import resolve_mesh

        monkeypatch.delenv("TPUDAS_MESH", raising=False)
        assert resolve_mesh(None) is None
        assert resolve_mesh(0) is None
        assert resolve_mesh(1) is None
        m = resolve_mesh(4)
        assert dict(m.shape) == {"time": 1, "ch": 4}
        assert resolve_mesh(m) is m
        monkeypatch.setenv("TPUDAS_MESH", "2")
        m2 = resolve_mesh(None)
        assert dict(m2.shape) == {"time": 1, "ch": 2}
        # explicit argument wins over the environment
        assert resolve_mesh(0, env="TPUDAS_MESH") is None

    def test_bad_counts_rejected(self, monkeypatch):
        from tpudas.parallel.mesh import resolve_mesh

        with pytest.raises(ValueError, match=">= 0"):
            resolve_mesh(-1)
        with pytest.raises(ValueError, match="exceeds"):
            resolve_mesh(len(jax.devices()) + 1)

    def test_shard_gauge_follows_resolution(self):
        from tpudas.obs.registry import MetricsRegistry, use_registry
        from tpudas.parallel.mesh import resolve_mesh

        reg = MetricsRegistry()
        with use_registry(reg):
            resolve_mesh(4)
        assert reg.get("tpudas_parallel_shards").value() == 4
        with use_registry(reg):
            resolve_mesh(None)
        assert reg.get("tpudas_parallel_shards").value() == 1


class TestShardedRealtimeEquivalence:
    """ISSUE 7 acceptance: a sharded realtime run on the CPU mesh
    produces outputs, saved carry, and pyramid/detect artifacts
    byte-identical to the single-device run over the same spool — and
    the serialized carry is layout-independent in both directions."""

    FS = 100.0
    N_CH = 10  # NOT divisible by 4: exercises the pad-and-mask layout
    FILE_SEC = 30.0
    T0 = np.datetime64("2023-03-22T00:00:00")
    # thresholds that actually fire events on the noisy synthetic spool
    # (an empty ledger would compare equal vacuously)
    DETECT_OPS = (
        ("stalta", {"sta": 2.0, "lta": 10.0, "on": 2.0, "off": 1.2}),
        ("rms", {"window": 5.0, "step": 2.0, "thresh": 1.5,
                 "baseline": 20.0}),
    )

    def _feed(self, src, first, n=1):
        from tpudas.testing import make_synthetic_spool

        make_synthetic_spool(
            src, n_files=n, file_duration=self.FILE_SEC, fs=self.FS,
            n_ch=self.N_CH, noise=0.05,
            start=self.T0
            + np.timedelta64(int(first * self.FILE_SEC * 1e9), "ns"),
            prefix=f"raw{first:03d}",
        )

    def _drive(self, src, out, mesh, engine="auto", feed_rounds=2,
               max_rounds=6, hooks=True, **kw):
        """One realtime run: 3 initial files, one more fed before each
        of ``feed_rounds`` subsequent polls, terminates on no-growth."""
        from tpudas.proc.streaming import run_lowpass_realtime

        if not os.path.isdir(src):
            self._feed(src, 0, 3)
        state = {"fed": 0}

        def sleep(_):
            if state["fed"] < feed_rounds:
                state["fed"] += 1
                self._feed(src, 2 + state["fed"])

        return run_lowpass_realtime(
            source=src, output_folder=out, start_time=str(self.T0),
            output_sample_interval=1.0, edge_buffer=10.0,
            process_patch_size=60, poll_interval=0.0, sleep_fn=sleep,
            max_rounds=max_rounds, mesh=mesh, engine=engine,
            pyramid=hooks, detect=hooks,
            detect_operators=self.DETECT_OPS if hooks else None,
            health=True, **kw,
        )

    # --- artifact comparisons ------------------------------------------

    def _merged(self, out):
        from tpudas import spool

        p = spool(str(out)).update().chunk(time=None)[0]
        return np.asarray(p.host_data()), np.asarray(p.coords["time"])

    def _carry_state(self, out):
        from tpudas.proc.stream import load_carry

        c = load_carry(str(out))
        assert c is not None
        return c

    def _assert_carries_equal(self, a, b):
        assert a._meta() == b._meta()
        assert len(a.bufs) == len(b.bufs)
        for x, y in zip(a.bufs, b.bufs):
            assert np.array_equal(np.asarray(x), np.asarray(y))
        if a.residual is None:
            assert b.residual is None
        else:
            assert np.array_equal(a.residual, b.residual)

    def _tree(self, out, sub):
        import hashlib

        root = os.path.join(str(out), sub)
        tree = {}
        for dirpath, _dirs, files in os.walk(root):
            for name in sorted(files):
                if ".prev" in name or ".tmp" in name:
                    continue
                path = os.path.join(dirpath, name)
                with open(path, "rb") as fh:
                    digest = hashlib.sha256(fh.read()).hexdigest()
                tree[os.path.relpath(path, root)] = digest
        return tree

    def _assert_all_artifacts_equal(self, ref_out, out):
        d_ref, t_ref = self._merged(ref_out)
        d, t = self._merged(out)
        assert np.array_equal(d_ref, d)
        assert np.array_equal(t_ref, t)
        self._assert_carries_equal(
            self._carry_state(ref_out), self._carry_state(out)
        )
        from tpudas.serve.tiles import TILE_DIRNAME

        ref_tiles = self._tree(ref_out, TILE_DIRNAME)
        assert ref_tiles and ref_tiles == self._tree(out, TILE_DIRNAME)
        from tpudas.detect.ledger import DETECT_DIRNAME, load_events

        ref_det = self._tree(ref_out, DETECT_DIRNAME)
        det = self._tree(out, DETECT_DIRNAME)
        # the detect carry .npz embeds zip timestamps: compare parsed
        from tpudas.detect.runner import load_detect_carry

        for key in list(ref_det):
            if key.endswith(".npz"):
                ref_det.pop(key), det.pop(key, None)
        assert ref_det == det
        ca, cb = load_detect_carry(str(ref_out)), load_detect_carry(str(out))
        assert (ca is None) == (cb is None)
        if ca is not None:
            assert ca["meta"] == cb["meta"]
            for sa, sb in zip(ca["states"], cb["states"]):
                assert sorted(sa) == sorted(sb)
                for k in sa:
                    assert np.array_equal(
                        np.asarray(sa[k]), np.asarray(sb[k])
                    )
        assert len(load_events(str(ref_out))) > 0  # not vacuous

    # --- the acceptance tests ------------------------------------------

    @pytest.mark.slow
    def test_sharded_run_byte_identical(self, tmp_path, cpu_mesh4,
                                        monkeypatch):
        """mesh=Mesh and TPUDAS_MESH=4 runs == the single-device run:
        outputs, carry .npz content, pyramid tiles, events ledger,
        score tiles, detect carry."""
        from tpudas.obs.health import read_health

        monkeypatch.delenv("TPUDAS_MESH", raising=False)
        legs = {"single": dict(mesh=None), "mesh": dict(mesh=cpu_mesh4)}
        for name, kw in legs.items():
            rounds = self._drive(
                tmp_path / f"src_{name}", tmp_path / f"out_{name}", **kw
            )
            assert rounds == 3
            health = read_health(str(tmp_path / f"out_{name}"))
            assert health["mode"] == "stateful"  # mesh kept the carry
        monkeypatch.setenv("TPUDAS_MESH", "4")
        assert self._drive(
            tmp_path / "src_env", tmp_path / "out_env", mesh=None
        ) == 3
        monkeypatch.delenv("TPUDAS_MESH")
        self._assert_all_artifacts_equal(
            tmp_path / "out_single", tmp_path / "out_mesh"
        )
        self._assert_all_artifacts_equal(
            tmp_path / "out_single", tmp_path / "out_env"
        )

    @pytest.mark.slow
    def test_sharded_fft_engine_byte_identical(self, tmp_path, cpu_mesh4):
        outs = {}
        for name, mesh in (("single", None), ("mesh", cpu_mesh4)):
            out = tmp_path / f"out_{name}"
            self._drive(
                tmp_path / f"src_{name}", out, mesh, engine="fft",
                hooks=False,
            )
            outs[name] = out
        d_ref, t_ref = self._merged(outs["single"])
        d, t = self._merged(outs["mesh"])
        assert np.array_equal(d_ref, d) and np.array_equal(t_ref, t)
        self._assert_carries_equal(
            self._carry_state(outs["single"]),
            self._carry_state(outs["mesh"]),
        )

    @pytest.mark.slow
    def test_carry_save_cadence(self, tmp_path, cpu_mesh4):
        """TPUDAS_CARRY_SAVE_EVERY > 1 skips the per-round gather+save
        (the steady round keeps the pytree on-device) and the clean
        shutdown flushes — end state byte-identical, fewer saves."""
        from tpudas.obs.registry import MetricsRegistry, use_registry

        saves = {}
        for name, every in (("each", 1), ("cadence", 4)):
            reg = MetricsRegistry()
            with use_registry(reg):
                self._drive(
                    tmp_path / f"src_{name}", tmp_path / f"out_{name}",
                    cpu_mesh4, hooks=False, carry_save_every=every,
                )
            saves[name] = reg.value("tpudas_stream_carry_saves_total")
        # each: open + one per processing round; cadence 4: open + the
        # final clean-termination flush only
        assert saves["cadence"] == 2
        assert saves["each"] == 4
        d_ref, t_ref = self._merged(tmp_path / "out_each")
        d, t = self._merged(tmp_path / "out_cadence")
        assert np.array_equal(d_ref, d) and np.array_equal(t_ref, t)
        self._assert_carries_equal(
            self._carry_state(tmp_path / "out_each"),
            self._carry_state(tmp_path / "out_cadence"),
        )

    @pytest.mark.slow
    def test_carry_is_layout_independent_across_restarts(
        self, tmp_path, cpu_mesh4
    ):
        """A run can stop sharded and resume single-device (or the
        reverse) from the same serialized carry, byte-identical to a
        control that never changed layout."""
        scenarios = {
            "ctrl": (None, None),
            "shard_then_single": (cpu_mesh4, None),
            "single_then_shard": (None, cpu_mesh4),
        }
        for name, (mesh1, mesh2) in scenarios.items():
            src = tmp_path / f"src_{name}"
            out = tmp_path / f"out_{name}"
            # leg 1: 3 initial files + 1 fed, stops after 2 rounds
            self._drive(src, out, mesh1, feed_rounds=1, max_rounds=2,
                        hooks=False)
            # leg 2: resumes the persisted carry, feeds 1 more file
            self._feed(src, 4)
            self._drive(src, out, mesh2, feed_rounds=0, hooks=False)
        d_ref, t_ref = self._merged(tmp_path / "out_ctrl")
        for name in ("shard_then_single", "single_then_shard"):
            d, t = self._merged(tmp_path / ("out_" + name))
            assert np.array_equal(d_ref, d), name
            assert np.array_equal(t_ref, t), name
            self._assert_carries_equal(
                self._carry_state(tmp_path / "out_ctrl"),
                self._carry_state(tmp_path / ("out_" + name)),
            )
