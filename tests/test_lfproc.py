"""LFProc engine: naming contracts, parameters, scheduling invariants,
edge calibration, seam-freeness, resume idempotency (SURVEY.md §4)."""

import os

import numpy as np
import pytest

from tpudas import spool
from tpudas.proc.edge import get_edge_effect_time
from tpudas.proc.lfproc import LFProc, schedule_windows
from tpudas.proc.memory import get_patch_time
from tpudas.proc.naming import get_filename, get_timestr
from tpudas.testing import lowfreq_truth, make_synthetic_spool

FS = 100.0
N_CH = 8
FILE_SEC = 30.0
N_FILES = 8  # 4 minutes of stream
DT_OUT = 1.0  # output interval (s): corner 0.45 Hz


@pytest.fixture(scope="module")
def spool_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("raw")
    make_synthetic_spool(
        d, n_files=N_FILES, file_duration=FILE_SEC, fs=FS, n_ch=N_CH,
        noise=0.01,
    )
    return str(d)


def run_lfproc(src, out_dir, t1, t2, patch_size=60, buff=10):
    lfp = LFProc(spool(src).sort("time").update())
    lfp.update_processing_parameter(
        output_sample_interval=DT_OUT,
        process_patch_size=patch_size,
        edge_buff_size=buff,
    )
    lfp.set_output_folder(str(out_dir), delete_existing=True)
    lfp.process_time_range(np.datetime64(t1), np.datetime64(t2))
    return lfp


class TestNaming:
    def test_timestr_exact_contract(self):
        # str(dt64[ms])[:21] with ":" removed (lf_das.py:23-26): one
        # sub-second digit survives
        t = np.datetime64("2023-01-02T03:04:05.123", "ms")
        assert get_timestr(t) == "2023-01-02T030405.1"

    def test_filename_exact_contract(self):
        t0 = np.datetime64("2023-01-02T03:04:05.123")
        t1 = np.datetime64("2023-01-02T03:05:45.900")
        assert (
            get_filename(t0, t1)
            == "LFDAS_2023-01-02T030405.1_2023-01-02T030545.9.h5"
        )


class TestMemoryModel:
    def test_closed_form(self):
        # 10 GB, 1 kHz, 1000 ch → 48 MB/s → ≈208 s (lf_das.py:98-106)
        t = get_patch_time(10000, 1000, 1000)
        assert abs(t - 10000 / 48.0) < 1e-9


class TestParameters:
    def test_defaults_and_frozen_view(self):
        lfp = LFProc()
        p = lfp.parameters
        assert p["output_sample_interval"] == 1.0
        assert p["process_patch_size"] == 100
        assert p["edge_buff_size"] == 10
        assert "data_gap_tolorance" in p  # reference-compat key
        with pytest.raises(TypeError):
            p["edge_buff_size"] = 3  # type: ignore[index]

    def test_unknown_key_warns_not_raises(self, capsys):
        lfp = LFProc()
        lfp.update_processing_parameter(bogus_key=1)
        assert "bogus_key is not default parameter key" in capsys.readouterr().out
        assert "bogus_key" not in lfp.parameters

    def test_output_folder_required(self):
        with pytest.raises(Exception, match="output folder"):
            LFProc().process_time_range(
                np.datetime64("2023-01-01"), np.datetime64("2023-01-02")
            )


class TestH2DStaging:
    def test_staged_run_byte_equal_to_unstaged(self, tmp_path, monkeypatch):
        """The prefetch-thread H2D staging (assemble -> stage ->
        compute -> write pipeline) must not change a single output
        byte vs the serial path (TPUDAS_H2D_STAGE=0)."""
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool

        d = tmp_path / "raw"
        make_synthetic_spool(
            d, n_files=4, file_duration=30.0, fs=100.0, n_ch=6, noise=0.01
        )
        results = {}
        for label, env in (("staged", "1"), ("serial", "0")):
            monkeypatch.setenv("TPUDAS_H2D_STAGE", env)
            lfp = LFProc(spool(str(d)).sort("time").update())
            lfp.update_processing_parameter(
                output_sample_interval=1.0,
                process_patch_size=60,
                edge_buff_size=10,
            )
            out = tmp_path / f"out_{label}"
            lfp.set_output_folder(str(out), delete_existing=True)
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:02:00"),
            )
            results[label] = (
                spool(str(out)).update().chunk(time=None)[0].host_data()
            )
        assert np.array_equal(results["staged"], results["serial"])

    def test_stage_skips_oversized_windows(self, tmp_path, monkeypatch):
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool

        d = tmp_path / "raw"
        make_synthetic_spool(
            d, n_files=2, file_duration=30.0, fs=100.0, n_ch=4, noise=0.01
        )
        monkeypatch.delenv("TPUDAS_H2D_STAGE", raising=False)
        lfp = LFProc(spool(str(d)).sort("time").update())
        monkeypatch.setattr(LFProc, "_STAGE_MAX_BYTES", 8)
        patch, staged = lfp._load_and_stage(
            np.datetime64("2023-03-22T00:00:00"),
            np.datetime64("2023-03-22T00:00:30"),
            "raise",
        )
        assert patch is not None
        assert staged is None  # over the two-resident-windows budget


class TestQuantizedFFTPath:
    def test_lowpass_resample_qscale_bitwise_matches_decoded(self):
        """The FFT engine's fused in-jit cast*scale is the same float
        op sequence as host decode — bit-identical results."""
        import jax.numpy as jnp

        from tpudas.proc.lfproc import lowpass_resample

        rng = np.random.default_rng(5)
        q = rng.integers(-3000, 3000, size=(4096, 8)).astype(np.int16)
        s = 2e-3
        idx = np.arange(0, 4095, 8, dtype=np.int32)
        w = np.zeros(idx.shape, np.float32)
        dec = q.astype(np.float32) * np.float32(s)
        ref = np.asarray(lowpass_resample(dec, 1e-3, 50.0, idx, w))
        got = np.asarray(
            lowpass_resample(jnp.asarray(q), 1e-3, 50.0, idx, w, qscale=s)
        )
        assert np.array_equal(got, ref)

    def test_lowpass_resample_qscale_dtype_validation(self):
        from tpudas.proc.lfproc import lowpass_resample

        idx = np.arange(0, 100, 8, dtype=np.int32)
        w = np.zeros(idx.shape, np.float32)
        with pytest.raises(ValueError, match="dtype"):
            lowpass_resample(
                np.zeros((512, 4), np.float32), 1e-3, 50.0, idx, w,
                qscale=0.5,
            )


class TestSchedule:
    def test_overlap_save_invariants(self):
        n, ps, buff = 500, 100, 10
        wins = schedule_windows(n, ps, buff)
        # emitted interiors tile [buff, ...) contiguously, no overlap
        assert wins[0][2] == buff
        for (pl, ph, el, eh), (nl, nh, nel, neh) in zip(wins, wins[1:]):
            assert nel == eh  # seamless
            assert nl == ph - 2 * buff  # window overlap = 2*buff
        # selections never exceed the grid
        assert all(0 <= a < b < n for a, b, _, _ in wins)

    def test_small_grid_shrinks_patch(self):
        wins = schedule_windows(50, 100, 5)
        assert wins[0][1] == 49

    def test_rejects_buffer_dominated_window(self):
        with pytest.raises(ValueError, match="edge_buff_size"):
            schedule_windows(500, 20, 10)


class TestEdgeCalibration:
    def test_probe_measures_fft_filter(self):
        edge = get_edge_effect_time(1 / FS, 60.0, tol=1e-3, freq=1 / DT_OUT)
        assert 0.5 < edge < 30.0

    def test_smaller_tol_wider_edge(self):
        e1 = get_edge_effect_time(1 / FS, 60.0, tol=1e-2, freq=1 / DT_OUT)
        e2 = get_edge_effect_time(1 / FS, 60.0, tol=1e-4, freq=1 / DT_OUT)
        assert e2 >= e1

    def test_chunk_too_small_raises(self):
        with pytest.raises(ValueError, match="edge_t value"):
            get_edge_effect_time(1 / FS, 4.0, tol=1e-9, freq=1.0)


class TestEndToEnd:
    def test_output_files_and_naming(self, spool_dir, tmp_path):
        out = tmp_path / "results"
        run_lfproc(
            spool_dir, out, "2023-03-22T00:00:00", "2023-03-22T00:04:00"
        )
        files = sorted(os.listdir(out))
        assert files and all(f.startswith("LFDAS_") and f.endswith(".h5") for f in files)

    def test_output_is_contiguous_and_decimated(self, spool_dir, tmp_path):
        out = tmp_path / "results"
        run_lfproc(
            spool_dir, out, "2023-03-22T00:00:00", "2023-03-22T00:04:00"
        )
        merged = spool(str(out)).update().chunk(time=None)
        assert len(merged) == 1
        p = merged[0]
        assert p.attrs["time_step"] == np.timedelta64(1, "s")
        steps = np.diff(p.coords["time"].astype(np.int64))
        assert np.all(steps == 1_000_000_000)

    def test_recovers_lowfreq_signal(self, spool_dir, tmp_path):
        out = tmp_path / "results"
        run_lfproc(
            spool_dir, out, "2023-03-22T00:00:00", "2023-03-22T00:04:00"
        )
        p = spool(str(out)).update().chunk(time=None)[0]
        data = p.host_data()
        truth_times = p.coords["time"]
        # rebuild the known LF component with the stream phase origin
        origin = np.datetime64("2023-03-22T00:00:00", "ns")
        t_sec = (truth_times - origin).astype(np.int64) / 1e9
        dists = p.coords["distance"]
        amp = 1.0 + dists / (dists.max() + 1.0)
        truth = np.sin(2 * np.pi * 0.05 * t_sec)[:, None] * amp[None, :]
        interior = slice(15, -15)
        err = np.abs(data[interior] - truth[interior])
        assert err.max() < 0.05

    def test_seam_freeness(self, spool_dir, tmp_path):
        """Chunked overlap-save output must equal single-shot whole-range
        processing — the invariant the scheduler exists to preserve."""
        chunked_dir = tmp_path / "chunked"
        single_dir = tmp_path / "single"
        t1, t2 = "2023-03-22T00:00:00", "2023-03-22T00:04:00"
        run_lfproc(spool_dir, chunked_dir, t1, t2, patch_size=60, buff=10)
        run_lfproc(spool_dir, single_dir, t1, t2, patch_size=239, buff=10)
        a = spool(str(chunked_dir)).update().chunk(time=None)[0]
        b = spool(str(single_dir)).update().chunk(time=None)[0]
        ta, tb = a.coords["time"], b.coords["time"]
        lo, hi = max(ta[0], tb[0]), min(ta[-1], tb[-1])
        asel = a.select(time=(lo, hi))
        bsel = b.select(time=(lo, hi))
        assert asel.shape == bsel.shape
        scale = np.abs(bsel.host_data()).max()
        assert np.abs(asel.host_data() - bsel.host_data()).max() < 5e-3 * scale

    def test_resume_with_overlap_is_seamless(self, spool_dir, tmp_path):
        """Kill-and-resume (the edge-loop contract, §3.2) must produce
        the same contiguous output as one uninterrupted run."""
        out_resumed = tmp_path / "resumed"
        out_full = tmp_path / "full"
        t1, tmid, t2 = (
            "2023-03-22T00:00:00",
            "2023-03-22T00:02:00",
            "2023-03-22T00:04:00",
        )
        buff = 10
        # phase 1: process the first half, then "crash"
        lfp = run_lfproc(spool_dir, out_resumed, t1, tmid, buff=buff)
        # phase 2: fresh engine resumes from output state with rewind
        lfp2 = LFProc(spool(spool_dir).sort("time").update())
        lfp2.update_processing_parameter(
            output_sample_interval=DT_OUT,
            process_patch_size=60,
            edge_buff_size=buff,
        )
        lfp2.set_output_folder(str(out_resumed), delete_existing=False)
        t_last = lfp2.get_last_processed_time()
        rewind = int((buff - 1) * DT_OUT)
        lfp2.process_time_range(
            t_last - np.timedelta64(rewind, "s"), np.datetime64(t2)
        )
        run_lfproc(spool_dir, out_full, t1, t2)
        a = spool(str(out_resumed)).update().chunk(time=None)
        assert len(a) == 1  # no seam, no gap
        b = spool(str(out_full)).update().chunk(time=None)[0]
        ta, tb = a[0].coords["time"], b.coords["time"]
        lo, hi = max(ta[0], tb[0]), min(ta[-1], tb[-1])
        asel = a[0].select(time=(lo, hi))
        bsel = b.select(time=(lo, hi))
        scale = np.abs(bsel.host_data()).max()
        assert np.abs(asel.host_data() - bsel.host_data()).max() < 5e-3 * scale

    def test_gap_skip_mode(self, tmp_path):
        d = tmp_path / "gappy"
        make_synthetic_spool(
            d, n_files=2, file_duration=30.0, fs=FS, n_ch=4, noise=0.0
        )
        make_synthetic_spool(
            d, n_files=2, file_duration=30.0, fs=FS, n_ch=4, noise=0.0,
            start="2023-03-22T00:02:00", prefix="late",
        )
        lfp = LFProc(spool(str(d)).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=DT_OUT,
            process_patch_size=40,
            edge_buff_size=5,
            on_gap="skip",
        )
        lfp.set_output_folder(str(tmp_path / "out"), delete_existing=True)
        lfp.process_time_range(
            np.datetime64("2023-03-22T00:00:00"),
            np.datetime64("2023-03-22T00:03:00"),
        )
        merged = spool(str(tmp_path / "out")).update().chunk(time=None)
        assert len(merged) >= 1  # produced output on both sides of the gap

    def test_cascade_single_sample_tail_window(self, spool_dir, tmp_path):
        # n_grid=142 with patch=60/buff=10 schedules a final window
        # emitting exactly ONE grid point; the forced cascade engine
        # must derive the ratio from the run-level grid step instead of
        # raising "grid not sample-aligned" mid-run (ADVICE r1, medium)
        from tpudas.proc.lfproc import schedule_windows

        wins = schedule_windows(142, 60, 10)
        assert wins[-1][3] - wins[-1][2] == 1  # precondition holds
        lfp = LFProc(spool(spool_dir).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=DT_OUT,
            process_patch_size=60,
            edge_buff_size=10,
            engine="cascade",
        )
        out = tmp_path / "tail1"
        lfp.set_output_folder(str(out), delete_existing=True)
        lfp.process_time_range(
            np.datetime64("2023-03-22T00:00:00"),
            np.datetime64("2023-03-22T00:02:22"),
        )
        merged = spool(str(out)).update().chunk(time=None)
        assert len(merged) == 1  # contiguous incl. the 1-sample tail
        times = merged[0].coords["time"]
        # emitted coverage = [first emit_lo, last emit_hi) of the schedule
        assert times.size == wins[-1][3] - wins[0][2]

    def test_gap_split_mode(self, tmp_path):
        # 60 s of data, a 60 s gap (> data_gap_tolorance), 60 s more:
        # on_gap="split" must emit one contiguous run per side of the
        # gap and never raise (lf_das.py:202's promised semantics)
        d = tmp_path / "gappy3"
        make_synthetic_spool(
            d, n_files=2, file_duration=30.0, fs=FS, n_ch=4, noise=0.0
        )
        make_synthetic_spool(
            d, n_files=2, file_duration=30.0, fs=FS, n_ch=4, noise=0.0,
            start="2023-03-22T00:02:00", prefix="late",
        )
        lfp = LFProc(spool(str(d)).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=DT_OUT,
            process_patch_size=40,
            edge_buff_size=5,
            on_gap="split",
            data_gap_tolorance=10.0,
        )
        out = tmp_path / "out3"
        lfp.set_output_folder(str(out), delete_existing=True)
        lfp.process_time_range(
            np.datetime64("2023-03-22T00:00:00"),
            np.datetime64("2023-03-22T00:03:00"),
        )
        merged = spool(str(out)).update().chunk(time=None)
        assert len(merged) == 2  # one contiguous run per segment
        runs = sorted(
            (p.coords["time"][0], p.coords["time"][-1]) for p in merged
        )
        # each run is interior to its segment (edge buffer trimmed at
        # the segment start, tail reaching the segment end)
        assert runs[0][0] == np.datetime64("2023-03-22T00:00:05")
        assert runs[0][1] <= np.datetime64("2023-03-22T00:01:00")
        assert runs[1][0] == np.datetime64("2023-03-22T00:02:05")
        assert runs[1][1] <= np.datetime64("2023-03-22T00:03:00")

    def test_gap_split_single_segment_matches_contiguous(self, spool_dir,
                                                         tmp_path):
        # with no gaps, split mode must be byte-identical to the default
        outs = {}
        for mode in ("raise", "split"):
            lfp = LFProc(spool(spool_dir).sort("time").update())
            lfp.update_processing_parameter(
                output_sample_interval=DT_OUT,
                process_patch_size=60,
                edge_buff_size=10,
                on_gap=mode,
            )
            out = tmp_path / f"split_{mode}"
            lfp.set_output_folder(str(out), delete_existing=True)
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:02:00"),
            )
            outs[mode] = spool(str(out)).update().chunk(time=None)[0]
        assert np.array_equal(
            outs["raise"].host_data(), outs["split"].host_data()
        )

    def test_invalid_on_gap_rejected(self):
        lfp = LFProc()
        with pytest.raises(ValueError, match="on_gap"):
            lfp.update_processing_parameter(on_gap="bogus")

    def test_window_timing_breakdown(self, spool_dir, tmp_path):
        # SURVEY §5 tracing row: per-phase wall breakdown on the
        # instance (assemble wait / device / HDF5 write)
        lfp = run_lfproc(
            spool_dir, tmp_path / "t", "2023-03-22T00:00:00",
            "2023-03-22T00:02:00",
        )
        t = lfp.timings
        assert set(t) == {"assemble_s", "device_s", "write_s"}
        assert t["device_s"] > 0 and t["write_s"] > 0
        assert all(v >= 0 for v in t.values())

    @pytest.mark.slow
    def test_trace_dir_writes_profile(self, spool_dir, tmp_path,
                                      monkeypatch):
        # TPUDAS_TRACE_DIR captures a jax.profiler device trace of the
        # whole run
        trace = tmp_path / "trace"
        monkeypatch.setenv("TPUDAS_TRACE_DIR", str(trace))
        run_lfproc(
            spool_dir, tmp_path / "out", "2023-03-22T00:00:00",
            "2023-03-22T00:01:00",
        )
        files = [f for _, _, fs in os.walk(trace) for f in fs]
        assert files, "no profiler trace written"

    def test_split_no_coverage_warns_loudly(self, spool_dir, tmp_path,
                                            capsys):
        # a split run whose range holds no data at all must say so —
        # silently completing looks like a successful run (round-2
        # advisor finding)
        lfp = LFProc(spool(spool_dir).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=DT_OUT,
            process_patch_size=60,
            edge_buff_size=10,
            on_gap="split",
        )
        out = tmp_path / "empty"
        lfp.set_output_folder(str(out), delete_existing=True)
        lfp.process_time_range(
            np.datetime64("2024-01-01T00:00:00"),  # a year off the data
            np.datetime64("2024-01-01T00:02:00"),
        )
        captured = capsys.readouterr().out
        assert "no data coverage" in captured
        assert not [f for f in os.listdir(out) if f.endswith(".h5")]

    def test_split_mode_invalid_patch_buff_raises(self, spool_dir,
                                                  tmp_path):
        # an invalid global config must fail loudly, not be swallowed
        # per segment as "too short"
        lfp = LFProc(spool(spool_dir).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=DT_OUT,
            process_patch_size=20,
            edge_buff_size=10,
            on_gap="split",
        )
        lfp.set_output_folder(str(tmp_path / "bad"), delete_existing=True)
        with pytest.raises(ValueError, match="process_patch_size"):
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:02:00"),
            )

    @pytest.mark.slow
    def test_10k_channel_window_config4_shapes(self, tmp_path):
        """BASELINE config 4 shapes on CPU: one overlap-save window of a
        10,000-channel 1 kHz stream through schedule_windows ->
        _process_window, both engines — exercises the static-shape /
        memory story at production channel count before hardware."""
        from tpudas.core.patch import Patch
        from tpudas.core.timeutils import build_time_grid

        fs, n_ch, d_t = 1000.0, 10_000, 1.0
        patch_size, buff = 16, 2
        t0 = np.datetime64("2023-03-22T00:00:00")
        grid = build_time_grid(
            t0, t0 + np.timedelta64(patch_size + 1, "s"), d_t
        )
        wins = schedule_windows(len(grid), patch_size, buff)
        assert len(wins) == 1
        sel_lo, sel_hi, emit_lo, emit_hi = wins[0]
        T = sel_hi * 1000 + 1  # rows covering [grid[0], grid[sel_hi]]
        rng = np.random.default_rng(0)
        data = rng.standard_normal((T, n_ch)).astype(np.float32)
        times = t0.astype("datetime64[ns]") + np.arange(T) * np.timedelta64(
            1_000_000, "ns"
        )
        window = Patch(
            data=data,
            coords={
                "time": times,
                "distance": np.arange(n_ch, dtype=np.float64),
            },
            dims=("time", "distance"),
        )
        corner = 1.0 / d_t / 2.0 * 0.9
        for engine in ("cascade", "fft"):
            lfp = LFProc()
            lfp.update_processing_parameter(engine=engine)
            out = tmp_path / f"big_{engine}"
            lfp.set_output_folder(str(out), delete_existing=True)
            lfp._process_window(
                window, grid[emit_lo:emit_hi], d_t, corner, 4
            )
            (fname,) = os.listdir(out)
            (result,) = spool(str(out)).update()
            assert result.host_data().shape == (emit_hi - emit_lo, n_ch)
            assert np.isfinite(result.host_data()).all()

    def test_gap_raise_mode(self, tmp_path):
        d = tmp_path / "gappy2"
        make_synthetic_spool(d, n_files=1, file_duration=30.0, fs=FS, n_ch=4)
        make_synthetic_spool(
            d, n_files=1, file_duration=30.0, fs=FS, n_ch=4,
            start="2023-03-22T00:02:00", prefix="late",
        )
        lfp = LFProc(spool(str(d)).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=DT_OUT, process_patch_size=40,
            edge_buff_size=5,
        )
        lfp.set_output_folder(str(tmp_path / "out2"), delete_existing=True)
        with pytest.raises(Exception, match="Gap in data exists"):
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:03:00"),
            )


class TestGapTolerance:
    """data_gap_tolorance's single meaning (the key the reference
    declares but never reads, lf_das.py:202): a hole of at most that
    many seconds between consecutive files is NOT a gap — the window
    merge bridges it by linear interpolation — while anything wider is
    a gap handled per on_gap."""

    def _gappy_spool(self, d, hole_s):
        # 2 files, a hole, 2 more files (contiguous inside each half)
        make_synthetic_spool(
            d, n_files=2, file_duration=30.0, fs=FS, n_ch=4, noise=0.0
        )
        t2 = np.datetime64("2023-03-22T00:01:00") + np.timedelta64(
            int(hole_s * 1e9), "ns"
        )
        make_synthetic_spool(
            d, n_files=2, file_duration=30.0, fs=FS, n_ch=4, noise=0.0,
            start=str(t2), prefix="late",
        )

    def test_sub_tolerance_hole_is_filled_not_raised(self, tmp_path):
        from tpudas.utils.logging import set_log_handler

        d = tmp_path / "gappy"
        self._gappy_spool(d, hole_s=5.0)  # < default tolerance 10 s
        lfp = LFProc(spool(str(d)).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=DT_OUT, process_patch_size=40,
            edge_buff_size=5,  # on_gap stays "raise" (the default)
        )
        out = tmp_path / "out"
        lfp.set_output_folder(str(out), delete_existing=True)
        events = []
        set_log_handler(events.append)
        try:
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:02:00"),
            )
        finally:
            set_log_handler(None)
        merged = spool(str(out)).update().chunk(time=None)
        assert len(merged) == 1  # contiguous output across the hole
        assert any(e["event"] == "gap_filled" for e in events)

    def test_tolerance_zero_restores_strict_raise(self, tmp_path):
        d = tmp_path / "gappy0"
        self._gappy_spool(d, hole_s=5.0)
        lfp = LFProc(spool(str(d)).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=DT_OUT, process_patch_size=40,
            edge_buff_size=5, data_gap_tolorance=0.0,
        )
        lfp.set_output_folder(str(tmp_path / "out"), delete_existing=True)
        with pytest.raises(Exception, match="Gap in data exists"):
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:02:00"),
            )

    def test_wider_than_tolerance_hole_still_raises(self, tmp_path):
        d = tmp_path / "gappy2"
        self._gappy_spool(d, hole_s=30.0)  # > default tolerance 10 s
        lfp = LFProc(spool(str(d)).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=DT_OUT, process_patch_size=40,
            edge_buff_size=5,
        )
        lfp.set_output_folder(str(tmp_path / "out"), delete_existing=True)
        with pytest.raises(Exception, match="Gap in data exists"):
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:02:30"),
            )

    def test_merge_fill_values_are_linear(self):
        from tpudas.core.patch import Patch
        from tpudas.io.spool import merge_patches

        def mk(t0, vals):
            vals = np.asarray(vals, dtype=np.float32)[:, None]
            times = np.datetime64(t0, "ns") + np.arange(
                len(vals)
            ) * np.timedelta64(100_000_000, "ns")  # 10 Hz
            return Patch(
                data=vals,
                coords={"time": times, "distance": np.array([0.0])},
                dims=("time", "distance"),
                attrs={"d_time": 0.1, "d_distance": 1.0},
            )

        a = mk("2023-01-01T00:00:00", [0.0, 1.0, 2.0])
        # hole of 3 missing samples: last a-sample at 0.2 s, b starts
        # at 0.6 s -> fills at 0.3/0.4/0.5 s, linear from 2.0 to 6.0
        b = mk("2023-01-01T00:00:00.6", [6.0, 7.0])
        out = merge_patches([a, b], max_fill=1.0)
        assert len(out) == 1
        got = out[0].host_data()[:, 0]
        np.testing.assert_allclose(
            got, [0, 1, 2, 3, 4, 5, 6, 7], rtol=1e-6
        )
        # off-grid hole (not a multiple of the step): NOT filled
        c = mk("2023-01-01T00:00:00.65", [6.0, 7.0])
        assert len(merge_patches([a, c], max_fill=1.0)) == 2
        # hole longer than max_fill: NOT filled
        d = mk("2023-01-01T00:00:01.6", [6.0, 7.0])
        assert len(merge_patches([a, d], max_fill=1.0)) == 2


class TestNorthStarWidthIngest:
    @pytest.mark.slow
    def test_10k_channel_full_product_path(self, tmp_path):
        """BASELINE config-4 WIDTH through the ENTIRE product path —
        tdas int16 spool -> index planning -> native C++ window
        assembly -> device kernel -> HDF5 emission -> merge — not just
        the window shapes. Slow CPU run at reduced rate/duration; the
        on-chip rate for this path is the campaign's e2e step."""
        from tpudas import spool

        fs, n_ch = 50.0, 10_000
        d = tmp_path / "raw"
        make_synthetic_spool(
            d, n_files=3, file_duration=30.0, fs=fs, n_ch=n_ch,
            noise=0.01, format="tdas",
            write_kwargs={"dtype": "int16", "scale": 1e-3},
        )
        lfp = LFProc(spool(str(d)).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=1.0,
            process_patch_size=40,
            edge_buff_size=5,
        )
        out = tmp_path / "out"
        lfp.set_output_folder(str(out), delete_existing=True)
        lfp.process_time_range(
            np.datetime64("2023-03-22T00:00:00"),
            np.datetime64("2023-03-22T00:01:30"),
        )
        # the native (C++ assembler) fast path must have carried the
        # windows — a silent fallback to per-file numpy merge at this
        # width is exactly what this test exists to catch
        assert lfp.native_windows == sum(lfp.engine_counts.values()) > 0
        merged = spool(str(out)).update().chunk(time=None)
        assert len(merged) == 1
        p = merged[0]
        assert p.host_data().shape[p.dims.index("distance")] == n_ch
        assert np.isfinite(p.host_data()).all()
