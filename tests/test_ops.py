"""Kernel golden tests: filter vs scipy, interp vs numpy, rolling vs
pandas, median vs scipy (SURVEY.md §4 test plan)."""

import numpy as np
import pandas as pd
import pytest
import scipy.ndimage
import scipy.signal

from tpudas.ops.filter import fft_pass_filter
from tpudas.ops.median import median_filter
from tpudas.ops.resample import interp_indices_weights, gather_lerp
from tpudas.ops.rolling import rolling_reduce
from tpudas.testing import synthetic_patch


class TestFFTFilter:
    fs = 200.0

    def _sig(self, n=4000, c=3, seed=0):
        rng = np.random.default_rng(seed)
        t = np.arange(n) / self.fs
        sig = (
            np.sin(2 * np.pi * 0.3 * t)[:, None]
            + 0.5 * np.sin(2 * np.pi * 30.0 * t)[:, None]
            + 0.05 * rng.standard_normal((n, c))
        )
        return sig.astype(np.float32), t

    def test_matches_sosfiltfilt_interior(self):
        """filtfilt magnitude is |H|^2 — our FFT filter must agree away
        from the chunk edges (tolerance-based: numerics differ)."""
        data, _ = self._sig()
        corner = 2.0
        ours = np.asarray(fft_pass_filter(data, 1 / self.fs, high=corner))
        sos = scipy.signal.butter(4, corner / (self.fs / 2), "lowpass", output="sos")
        ref = scipy.signal.sosfiltfilt(sos, data.astype(np.float64), axis=0)
        interior = slice(800, -800)
        err = np.abs(ours[interior] - ref[interior])
        assert err.max() < 2e-2 * np.abs(ref[interior]).max()

    def test_zero_phase_impulse(self):
        n = 2001
        x = np.zeros((n, 1), np.float32)
        x[n // 2] = 1.0
        h = np.asarray(fft_pass_filter(x, 1 / self.fs, high=5.0))[:, 0]
        # symmetric response around the impulse == zero phase
        assert np.allclose(h[: n // 2][::-1], h[n // 2 + 1 :], atol=1e-5)
        assert np.argmax(np.abs(h)) == n // 2

    def test_stopband_rejection_passband_unity(self):
        data, t = self._sig(c=1, seed=1)
        out = np.asarray(fft_pass_filter(data, 1 / self.fs, high=2.0))[:, 0]
        interior = slice(600, -600)
        lf = np.sin(2 * np.pi * 0.3 * t)[interior]
        # LF component preserved
        assert np.corrcoef(out[interior], lf)[0, 1] > 0.999
        # 30 Hz component crushed: residual power tiny
        resid = out[interior] - lf
        assert np.sqrt(np.mean(resid**2)) < 0.05

    def test_highpass_and_bandpass(self):
        data, t = self._sig(c=1)
        hp = np.asarray(fft_pass_filter(data, 1 / self.fs, low=10.0))[:, 0]
        interior = slice(600, -600)
        hf = 0.5 * np.sin(2 * np.pi * 30.0 * t)
        assert np.corrcoef(hp[interior], hf[interior])[0, 1] > 0.99
        bp = np.asarray(
            fft_pass_filter(data, 1 / self.fs, low=20.0, high=40.0)
        )[:, 0]
        assert np.corrcoef(bp[interior], hf[interior])[0, 1] > 0.99

    def test_patch_pass_filter_engines_agree(self):
        p = synthetic_patch(duration=20, fs=self.fs, n_ch=4, noise=0.1)
        a = p.pass_filter(time=(None, 2.0))
        b = p.pass_filter(time=(None, 2.0), engine="numpy")
        interior = slice(400, -400)
        assert (
            np.abs(
                np.asarray(a.data)[interior] - np.asarray(b.data)[interior]
            ).max()
            < 2e-2 * np.abs(np.asarray(b.data)).max()
        )

    def test_corner_validation(self):
        p = synthetic_patch(duration=5, fs=self.fs, n_ch=2)
        with pytest.raises(ValueError):
            p.pass_filter(time=(None, 1000.0))  # above Nyquist


class TestInterpolate:
    def test_matches_np_interp(self):
        rng = np.random.default_rng(0)
        src = np.sort(rng.uniform(0, 100, 200))
        src[0], src[-1] = 0.0, 100.0
        vals = rng.standard_normal(200).astype(np.float32)
        dst = rng.uniform(-5, 105, 500)  # includes out-of-range clamps
        idx, w = interp_indices_weights(src, dst)
        ours = np.asarray(gather_lerp(vals[:, None], idx, w))[:, 0]
        ref = np.interp(dst, src, vals)
        assert np.allclose(ours, ref, atol=1e-5)

    def test_datetime_axes_exact(self):
        p = synthetic_patch(duration=10, fs=100.0, n_ch=3)
        t = p.coords["time"]
        new_t = t[::10]
        q = p.interpolate(time=new_t)
        assert np.array_equal(q.coords["time"], new_t)
        # on-grid targets are exact sample picks
        assert np.allclose(q.host_data(), p.host_data()[::10], atol=1e-6)
        assert q.attrs["time_step"] == np.timedelta64(100, "ms")

    def test_patch_interp_engines_agree(self):
        p = synthetic_patch(duration=10, fs=100.0, n_ch=3, noise=0.2)
        t0 = p.coords["time"][0]
        new_t = t0 + np.arange(1, 90) * np.timedelta64(107, "ms")
        a = p.interpolate(time=new_t)
        b = p.interpolate(time=new_t, engine="numpy")
        assert np.allclose(np.asarray(a.data), np.asarray(b.data), atol=1e-5)


class TestRolling:
    @pytest.mark.parametrize("n,w,s", [(100, 10, 10), (101, 7, 3), (50, 12, 5), (30, 40, 10)])
    @pytest.mark.parametrize("op", ["mean", "sum", "min", "max"])
    def test_matches_pandas(self, n, w, s, op):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((n, 2)).astype(np.float32)
        ref = getattr(
            pd.DataFrame(x.astype(np.float64)).rolling(window=w, step=s), op
        )().to_numpy()
        ours_jax = rolling_reduce(x, w, s, op)
        ours_np = rolling_reduce(x, w, s, op, engine="numpy")
        assert ours_jax.shape == ref.shape
        assert np.allclose(np.asarray(ours_jax), ref, atol=1e-4, equal_nan=True)
        assert np.allclose(ours_np, ref, atol=1e-12, equal_nan=True)

    def test_patch_roller_decimation_semantics(self):
        # window == step == d_t: mean-decimation with NaN warm-up prefix
        from tpudas.core.units import s as sec

        p = synthetic_patch(duration=30, fs=100.0, n_ch=4)
        out = p.rolling(time=1.0 * sec, step=1.0 * sec, engine="numpy").mean()
        assert out.shape[0] == 30 * 100 // 100
        assert np.isnan(out.host_data()[0]).all()
        assert np.isfinite(out.host_data()[1:]).all()
        # time coord subsamples the input axis
        assert np.array_equal(out.coords["time"], p.coords["time"][::100])
        # dropna strips exactly the warm-up row
        assert out.dropna("time").shape[0] == out.shape[0] - 1

    def test_decimated_patch_attrs_refresh(self):
        # regression: rolling with step>1 must update time_step, or any
        # downstream Nyquist/window/contiguity math is 100x off
        from tpudas.core.units import s as sec

        p = synthetic_patch(duration=30, fs=100.0, n_ch=4)
        out = p.rolling(time=1.0 * sec, step=1.0 * sec).mean()
        assert out.attrs["time_step"] == np.timedelta64(1, "s")
        assert out.get_sample_step("time") == 1.0
        # merged spool of two consecutive rolling outputs stays contiguous
        from tpudas.io.spool import merge_patches

        t = p.coords["time"]
        a = p.select(time=(t[0], t[1499])).rolling(
            time=1.0 * sec, step=1.0 * sec
        ).mean()
        b = p.select(time=(t[1500], t[2999])).rolling(
            time=1.0 * sec, step=1.0 * sec
        ).mean()
        assert len(merge_patches([a, b])) == 1

    def test_jax_engine_matches_numpy_engine(self):
        from tpudas.core.units import s as sec

        p = synthetic_patch(duration=30, fs=100.0, n_ch=4, noise=0.3)
        a = p.rolling(time=1.0 * sec, step=1.0 * sec).mean()
        b = p.rolling(time=1.0 * sec, step=1.0 * sec, engine="numpy").mean()
        assert np.allclose(
            a.host_data(), b.host_data(), atol=1e-4, equal_nan=True
        )

    @pytest.mark.slow
    def test_std_matches_pandas(self):
        from tpudas.core.units import s as sec

        p = synthetic_patch(duration=30, fs=100.0, n_ch=3, noise=0.5)
        out = p.rolling(time=2.0 * sec, step=1.0 * sec).std()
        x = pd.DataFrame(p.host_data().astype(np.float64))
        ref = (
            x.rolling(window=200, step=100).std(ddof=0).to_numpy()
        )
        assert np.allclose(
            out.host_data(), ref, atol=1e-4, equal_nan=True
        )

    def test_std_survives_large_dc_offset(self):
        # regression (VERDICT r3 weak #4): the raw E[x^2]-E[x]^2
        # identity cancels catastrophically in f32 when the data rides
        # a large DC offset — raw counts commonly do
        from tpudas.core.units import s as sec

        p = synthetic_patch(duration=30, fs=100.0, n_ch=3, noise=0.5)
        data = p.host_data()
        shifted = p.new(data=data + np.float32(1e6))
        true_std = (
            pd.DataFrame(data.astype(np.float64))
            .rolling(window=200, step=100)
            .std(ddof=0)
            .to_numpy()
        )
        for engine in (None, "numpy"):
            out = shifted.rolling(
                time=2.0 * sec, step=1.0 * sec, engine=engine
            ).std()
            got = np.asarray(out.host_data(), np.float64)
            # the offset must not destroy the estimate (raw identity
            # yields ~0 or wild garbage here)
            err = np.nanmax(np.abs(got - true_std) / np.nanmax(true_std))
            assert err < 0.05, (engine, err)


class TestMedian:
    def test_1d_matches_scipy(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((200, 3)).astype(np.float32)
        ours = np.asarray(median_filter(x, 9, axes=(0,)))
        ref = scipy.ndimage.median_filter(x, size=(9, 1))
        assert np.allclose(ours, ref, atol=1e-6)

    def test_2d_matches_scipy(self):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((40, 30)).astype(np.float32)
        ours = np.asarray(median_filter(x, 5))
        ref = scipy.ndimage.median_filter(x, size=5)
        assert np.allclose(ours, ref, atol=1e-6)

    def test_patch_method(self):
        p = synthetic_patch(duration=5, fs=50.0, n_ch=4, noise=0.5)
        a = p.median_filter(size=5, dim="time")
        b = p.median_filter(size=5, dim="time", engine="scipy")
        assert np.allclose(a.host_data(), b.host_data(), atol=1e-6)


class TestMedianTupleSize:
    def test_per_axis_footprint_matches_scipy(self):
        import scipy.ndimage

        rng = np.random.default_rng(3)
        x = rng.standard_normal((40, 6)).astype(np.float32)
        ours = np.asarray(median_filter(x, (3, 1)))
        ref = scipy.ndimage.median_filter(x, size=(3, 1))
        assert np.abs(ours - ref).max() < 1e-6

    def test_even_size_rejected(self):
        import pytest

        with pytest.raises(ValueError, match="odd"):
            median_filter(np.zeros((8, 4), np.float32), (2, 1))
