"""tpudas.integrity: checksummed persistent state, the verified-read
degradation ladders, the startup audit/repair (fsck), disk-full
graceful degradation, and the process-level crash drill (ISSUE 5).

The acceptance bar: flipping one byte or truncating ANY durable
artifact (carry, quarantine ledger, pyramid manifest/tails/tiles,
index cache, health.json) is detected by a verified read and recovers
via the ladder — .prev double buffer, rebuild-from-outputs, rewind —
without killing the driver, with every fallback counted; an injected
ENOSPC sheds non-essential writers while core outputs keep flowing,
and recovery is automatic; SIGKILLing the driver process at seeded
random points leaves a folder that audits clean and resumes
byte-identically.
"""

import hashlib
import json
import os
import shutil

import numpy as np
import pytest

from tpudas.integrity import checksum as cks
from tpudas.integrity import resource as res
from tpudas.integrity.audit import audit
from tpudas.obs.health import read_health, write_health
from tpudas.obs.registry import MetricsRegistry, use_registry
from tpudas.proc.stream import CARRY_FILENAME, load_carry
from tpudas.proc.streaming import run_lowpass_realtime
from tpudas.resilience.faults import (
    FaultPlan,
    FaultSpec,
    RetryPolicy,
    classify_failure,
    install_fault_plan,
)
from tpudas.resilience.quarantine import QUARANTINE_FILENAME, QuarantineLedger
from tpudas.testing import (
    enospc_error,
    make_synthetic_spool,
    write_corrupt_file,
)
from tpudas.utils.atomicio import (
    atomic_write_bytes,
    atomic_write_text,
    is_tmp_name,
    tmp_path_for,
)

T0 = "2023-03-22T00:00:00"
FS = 50.0
FILE_SEC = 20.0
NCH = 4

FAST = RetryPolicy(base_delay=0.0, max_delay=0.0, jitter=0.0,
                   quarantine_after=2, quarantine_retry=900.0)


def _spool(src, n_files=2, start=T0, prefix="raw"):
    return make_synthetic_spool(
        src, n_files=n_files, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
        noise=0.01, start=start, prefix=prefix,
    )


def _append_one(src, index):
    from tpudas.core.timeutils import to_datetime64
    from tpudas.io.registry import write_patch
    from tpudas.testing import synthetic_patch

    t0 = to_datetime64(T0).astype("datetime64[ns]")
    step = np.timedelta64(int(round(1e9 / FS)), "ns")
    n = int(FILE_SEC * FS)
    p = synthetic_patch(
        t0=t0 + index * n * step, duration=FILE_SEC, fs=FS, n_ch=NCH,
        seed=index, phase_origin=t0, noise=0.01,
    )
    write_patch(p, os.path.join(src, f"raw_{index:04d}.h5"))


def _drive(src, out, policy=FAST, engine=None, feed_third=False, **kw):
    def sleep(_):
        if feed_third and not os.path.isfile(
            os.path.join(src, "raw_0002.h5")
        ):
            _append_one(src, 2)

    return run_lowpass_realtime(
        source=src,
        output_folder=out,
        start_time=T0,
        output_sample_interval=1.0,
        edge_buffer=5.0,
        process_patch_size=20,
        poll_interval=0.0,
        sleep_fn=sleep,
        fault_policy=policy,
        engine=engine,
        **kw,
    )


def _hashes(out):
    return {
        f: hashlib.sha256(
            open(os.path.join(out, f), "rb").read()
        ).hexdigest()
        for f in sorted(os.listdir(out))
        if f.endswith(".h5")
    }


def _flip_byte(path, offset=64):
    size = os.path.getsize(path)
    offset = min(offset, size - 1)
    with open(path, "r+b") as fh:
        fh.seek(offset)
        b = fh.read(1)
        fh.seek(offset)
        fh.write(bytes([b[0] ^ 0xFF]))


def _truncate(path, nbytes):
    with open(path, "r+b") as fh:
        fh.truncate(int(nbytes))


@pytest.fixture()
def clear_resource_state():
    res.clear_pressure("test setup")
    yield
    res.clear_pressure("test teardown")


@pytest.fixture(scope="module")
def rich(tmp_path_factory):
    """One fully populated output folder (copied per test): stateful
    carry with a .prev, quarantine ledger with an entry, health.json,
    index cache, and a multi-level tile pyramid with COMPLETED tiles
    (tiny tile_len so small runs finish tiles)."""
    td = tmp_path_factory.mktemp("rich")
    src, out = str(td / "src"), str(td / "out")
    _spool(src)
    write_corrupt_file(os.path.join(src, "raw_0099.h5"))
    os.environ["TPUDAS_PYRAMID_TILE_LEN"] = "8"
    os.environ["TPUDAS_PYRAMID_FACTOR"] = "4"
    try:
        rounds = _drive(
            src, out, feed_third=True, pyramid=True, health=True
        )
    finally:
        os.environ.pop("TPUDAS_PYRAMID_TILE_LEN", None)
        os.environ.pop("TPUDAS_PYRAMID_FACTOR", None)
    assert rounds >= 2
    # sanity: everything the tests damage is present
    assert os.path.isfile(os.path.join(out, CARRY_FILENAME))
    assert os.path.isfile(os.path.join(out, CARRY_FILENAME + ".prev"))
    assert os.path.isfile(os.path.join(out, QUARANTINE_FILENAME))
    assert os.path.isfile(os.path.join(out, "health.json"))
    assert os.path.isfile(os.path.join(out, ".tpudas_index.json"))
    assert os.path.isfile(os.path.join(out, ".tiles", "manifest.json"))
    assert os.path.isfile(os.path.join(out, ".tiles", "tails.npy"))
    assert os.path.isdir(os.path.join(out, ".tiles", "L0"))
    return td


@pytest.fixture()
def folder(rich, tmp_path):
    """A private copy of the rich fixture: src + out paths."""
    shutil.copytree(rich / "src", tmp_path / "src")
    shutil.copytree(rich / "out", tmp_path / "out")
    return str(tmp_path / "src"), str(tmp_path / "out")


# ---------------------------------------------------------------------------
# checksum primitives


class TestChecksum:
    def test_json_stamp_roundtrip_and_reserialize(self):
        obj = {"a": 1, "b": [1.5, None], "c": {"d": "x"}, "e": True}
        stamped = cks.stamp_json(obj)
        assert cks.verify_json_obj(stamped) == "ok"
        # the stamp survives pretty-printing and key reordering
        re = json.loads(json.dumps(stamped, indent=3, sort_keys=True))
        assert cks.verify_json_obj(re) == "ok"
        assert cks.strip_stamp(re) == obj

    def test_json_tamper_detected(self):
        stamped = cks.stamp_json({"a": 1})
        stamped["a"] = 2
        assert cks.verify_json_obj(stamped) == "mismatch"
        assert cks.verify_json_obj({"a": 1}) == "unstamped"
        assert cks.verify_json_obj([1, 2]) == "unstamped"

    def test_bytes_sidecar_roundtrip(self, tmp_path):
        p = str(tmp_path / "blob.bin")
        cks.write_bytes_checksummed(p, b"\x00" * 1000)
        assert cks.verify_file_checksum(p) == "ok"
        _flip_byte(p, 500)
        assert cks.verify_file_checksum(p) == "mismatch"
        # restamp repairs
        cks.write_sidecar_for(p)
        assert cks.verify_file_checksum(p) == "ok"
        # truncation = size mismatch
        _truncate(p, 10)
        assert cks.verify_file_checksum(p) == "mismatch"
        os.remove(p + cks.SIDECAR_SUFFIX)
        assert cks.verify_file_checksum(p) == "unstamped"

    def test_fallback_counts_metric_and_process_counter(self):
        reg = MetricsRegistry()
        n0 = cks.fallback_count()
        with use_registry(reg):
            cks.count_fallback("carry", "test")
            cks.count_fallback("tails", "test")
        assert cks.fallback_count() == n0 + 2
        assert reg.value(
            "tpudas_integrity_fallback_total", artifact="carry"
        ) == 1

    def test_rotate_prev_moves_payload_and_sidecar(self, tmp_path):
        p = str(tmp_path / "a.npz")
        cks.write_bytes_checksummed(p, b"one")
        cks.rotate_prev(p)
        cks.write_bytes_checksummed(p, b"two")
        assert open(p + ".prev", "rb").read() == b"one"
        assert cks.verify_file_checksum(p + ".prev") == "ok"
        assert cks.verify_file_checksum(p) == "ok"


class TestAtomicio:
    def test_tmp_names_are_per_pid_and_swept_pattern(self, tmp_path):
        p = str(tmp_path / "f.json")
        assert tmp_path_for(p).endswith(f".tmp.{os.getpid()}")
        assert is_tmp_name("x.json.tmp")
        assert is_tmp_name("x.json.tmp.12345")
        assert not is_tmp_name("x.json")
        assert not is_tmp_name("x.tmpy")

    def test_no_tmp_left_behind(self, tmp_path):
        p = str(tmp_path / "f.txt")
        atomic_write_text(p, "hello")
        atomic_write_bytes(str(tmp_path / "g.bin"), b"x")
        assert sorted(os.listdir(tmp_path)) == ["f.txt", "g.bin"]

    def test_durable_write(self, tmp_path):
        p = str(tmp_path / "d.txt")
        atomic_write_text(p, "fsynced", durable=True)
        assert open(p).read() == "fsynced"

    def test_enospc_fault_site(self, tmp_path):
        plan = FaultPlan(
            FaultSpec("fs.write_enospc", exc=enospc_error())
        )
        with install_fault_plan(plan):
            with pytest.raises(OSError) as ei:
                atomic_write_text(str(tmp_path / "z.txt"), "x")
        assert classify_failure(ei.value) == "resource"
        assert plan.fired


# ---------------------------------------------------------------------------
# the carry ladder (satellite: corrupt .npz must never kill the driver)


class TestCarryLadder:
    def test_torn_primary_falls_back_to_prev(self, folder):
        _, out = folder
        path = os.path.join(out, CARRY_FILENAME)
        good = load_carry(out)
        prev_meta = json.loads(
            str(np.load(path + ".prev")["meta"])
        )
        _flip_byte(path)
        reg = MetricsRegistry()
        with use_registry(reg):
            carry = load_carry(out)
        assert carry is not None  # landed on .prev
        assert carry.emitted == prev_meta["emitted"]
        assert carry.emitted <= good.emitted
        assert reg.value(
            "tpudas_integrity_fallback_total", artifact="carry"
        ) >= 1

    @pytest.mark.parametrize("cut", ["one", "quarter", "half", "minus1"])
    def test_truncated_at_every_boundary_rejected(self, folder, cut):
        _, out = folder
        path = os.path.join(out, CARRY_FILENAME)
        size = os.path.getsize(path)
        n = {"one": 1, "quarter": size // 4, "half": size // 2,
             "minus1": size - 1}[cut]
        _truncate(path, n)
        reg = MetricsRegistry()
        with use_registry(reg):
            carry = load_carry(out)
        assert carry is not None  # .prev rung
        assert reg.value(
            "tpudas_integrity_fallback_total", artifact="carry"
        ) >= 1

    def test_both_rungs_bad_degrades_to_none(self, folder):
        _, out = folder
        path = os.path.join(out, CARRY_FILENAME)
        _flip_byte(path)
        _flip_byte(path + ".prev")
        assert load_carry(out) is None

    def test_corrupt_meta_keyerror_never_escapes(self, tmp_path):
        """Satellite: a carry whose meta JSON parses but misses keys
        used to escape as a bare KeyError (constructed OUTSIDE the
        try) and kill the driver as a 'fatal' fault."""
        out = str(tmp_path)
        meta = {"version": 1, "n_bufs": 0}  # no start_ns etc.
        buf_path = os.path.join(out, CARRY_FILENAME)
        with open(buf_path, "wb") as fh:
            np.savez(fh, meta=np.asarray(json.dumps(meta)))
        reg = MetricsRegistry()
        with use_registry(reg):
            assert load_carry(out) is None  # not KeyError
        assert reg.value("tpudas_stream_carry_unreadable_total") >= 1

    def test_driver_survives_corrupt_carry(self, folder):
        """Flip a byte in the carry, re-run: the driver resumes from
        .prev, reconciles away the last round's outputs, regenerates
        them byte-identically, and health marks the run degraded with
        the fallback counted."""
        src, out = folder
        control = _hashes(out)
        _flip_byte(os.path.join(out, CARRY_FILENAME))
        # the audit would repair it before the round; disable it to
        # prove the RUNTIME ladder also holds
        os.environ["TPUDAS_INTEGRITY_AUDIT"] = "0"
        try:
            rounds = _drive(src, out, pyramid=True, health=True)
        finally:
            os.environ.pop("TPUDAS_INTEGRITY_AUDIT", None)
        assert rounds >= 1  # the reconciled span was reprocessed
        assert _hashes(out) == control
        health = read_health(out)
        assert health["integrity_fallbacks"] >= 1
        assert health["degraded"] is True


# ---------------------------------------------------------------------------
# torn-write ladders for the other artifacts


class TestTornArtifacts:
    @pytest.mark.parametrize("frac", [0.25, 0.5, 0.9])
    def test_ledger_truncated_falls_back(self, folder, frac):
        _, out = folder
        path = os.path.join(out, QUARANTINE_FILENAME)
        before = QuarantineLedger(out).quarantined_names()
        assert before  # the rich fixture quarantined raw_0099.h5
        _truncate(path, int(os.path.getsize(path) * frac))
        reg = MetricsRegistry()
        with use_registry(reg):
            led = QuarantineLedger(out)
        # .prev holds the previous save of the same entry set
        assert led.entry("raw_0099.h5") is not None
        assert reg.value(
            "tpudas_integrity_fallback_total", artifact="quarantine"
        ) >= 1

    def test_ledger_bit_flip_detected(self, folder):
        _, out = folder
        _flip_byte(os.path.join(out, QUARANTINE_FILENAME), 40)
        reg = MetricsRegistry()
        with use_registry(reg):
            QuarantineLedger(out)
        assert (
            reg.value(
                "tpudas_integrity_fallback_total", artifact="quarantine"
            ) >= 1
            or reg.value("tpudas_quarantine_ledger_unreadable_total") >= 1
        )

    @pytest.mark.parametrize("frac", [0.3, 0.8])
    def test_manifest_truncated_falls_back_to_prev(self, folder, frac):
        from tpudas.serve.tiles import TileStore

        _, out = folder
        man = os.path.join(out, ".tiles", "manifest.json")
        prev_levels = json.loads(
            open(man + ".prev").read()
        )["levels"]
        _truncate(man, int(os.path.getsize(man) * frac))
        reg = MetricsRegistry()
        with use_registry(reg):
            store = TileStore.open(out)
        assert store is not None
        assert store.levels == [int(n) for n in prev_levels]
        assert reg.value(
            "tpudas_integrity_fallback_total", artifact="manifest"
        ) >= 1

    def test_manifest_bit_flip_detected(self, folder):
        from tpudas.serve.tiles import TileStore

        _, out = folder
        man = os.path.join(out, ".tiles", "manifest.json")
        # flip a byte inside the levels array, keeping valid JSON
        # unlikely; any parse/crc failure must fall to .prev
        _flip_byte(man, 80)
        reg = MetricsRegistry()
        with use_registry(reg):
            store = TileStore.open(out)
        assert store is not None  # .prev rung
        assert reg.value(
            "tpudas_integrity_fallback_total", artifact="manifest"
        ) >= 1

    def test_tails_corruption_detected_then_rebuilt(self, folder):
        from tpudas.serve.tiles import CorruptStoreError, TileStore

        _, out = folder
        tails = os.path.join(out, ".tiles", "tails.npy")
        pre = open(tails, "rb").read()
        _flip_byte(tails, len(pre) // 2)
        store = TileStore.open(out)
        reg = MetricsRegistry()
        with use_registry(reg):
            with pytest.raises(CorruptStoreError):
                store._load_tails()
        assert reg.value(
            "tpudas_integrity_fallback_total", artifact="tails"
        ) >= 1
        # the ladder's last rung: rebuild from outputs, byte-identical
        rep = audit(out, repair=True)
        assert rep["clean"]
        assert open(tails, "rb").read() == pre

    def test_tile_corruption_detected_then_rebuilt(self, folder):
        from tpudas.serve.tiles import CorruptStoreError, TileStore

        _, out = folder
        l0 = os.path.join(out, ".tiles", "L0")
        tile = os.path.join(l0, sorted(os.listdir(l0))[0])
        assert tile.endswith(".npy")
        pre = open(tile, "rb").read()
        _flip_byte(tile, 200)
        store = TileStore.open(out)
        reg = MetricsRegistry()
        with use_registry(reg):
            with pytest.raises(CorruptStoreError):
                store.read(0, 0, store.n(0))
        assert reg.value(
            "tpudas_integrity_fallback_total", artifact="tile"
        ) >= 1
        rep = audit(out, repair=True)
        assert rep["clean"]
        assert open(tile, "rb").read() == pre

    @pytest.mark.parametrize("frac", [0.4, 0.95])
    def test_index_cache_truncated_falls_back(self, folder, frac):
        from tpudas.io.index import DirectoryIndex

        _, out = folder
        path = os.path.join(out, ".tpudas_index.json")
        _truncate(path, int(os.path.getsize(path) * frac))
        reg = MetricsRegistry()
        with use_registry(reg):
            idx = DirectoryIndex(out)
            idx._load_cache()
        assert reg.value(
            "tpudas_integrity_fallback_total", artifact="index"
        ) >= 1
        # rebuild rung: a full update() re-scans and re-persists
        idx.update()
        assert cks.verify_file_checksum(path) in ("ok", "unstamped")

    def test_health_bit_flip_falls_back_to_prev(self, folder):
        _, out = folder
        path = os.path.join(out, "health.json")
        prev_rounds = json.loads(open(path + ".prev").read())["rounds"]
        _flip_byte(path, 120)
        reg = MetricsRegistry()
        with use_registry(reg):
            got = read_health(out)
        assert got is not None and got["rounds"] == prev_rounds

    def test_health_truncation_counts_fallback(self, folder):
        """The torn-write case must be COUNTED, not just survived:
        a primary that fails to parse takes the .prev rung with
        tpudas_integrity_fallback_total{artifact=\"health\"} moving."""
        _, out = folder
        path = os.path.join(out, "health.json")
        prev_rounds = json.loads(open(path + ".prev").read())["rounds"]
        _truncate(path, os.path.getsize(path) // 2)
        reg = MetricsRegistry()
        with use_registry(reg):
            got = read_health(out)
        assert got is not None and got["rounds"] == prev_rounds
        assert reg.value(
            "tpudas_integrity_fallback_total", artifact="health"
        ) >= 1


# ---------------------------------------------------------------------------
# the integrity.verify fault site: deterministic mismatch drilling


class TestVerifyFaultSite:
    def test_truncate_at_verify_drills_the_ladder(self, folder):
        _, out = folder
        plan = FaultPlan(
            FaultSpec(
                "integrity.verify", action="truncate", nbytes=32,
                at=1, times=1, match=CARRY_FILENAME,
            )
        )
        reg = MetricsRegistry()
        with use_registry(reg), install_fault_plan(plan):
            carry = load_carry(out)
        assert plan.fired  # the primary was truncated mid-verify
        assert carry is not None  # .prev rung caught it
        assert reg.value(
            "tpudas_integrity_fallback_total", artifact="carry"
        ) >= 1


# ---------------------------------------------------------------------------
# audit / fsck


class TestAudit:
    def test_stale_tmp_swept(self, folder):
        _, out = folder
        for name in ("health.json.tmp", ".stream_carry.npz.tmp.999",
                     os.path.join(".tiles", "tails.npy.tmp.4242")):
            with open(os.path.join(out, name), "w") as fh:
                fh.write("junk")
        rep = audit(out, repair=True)
        assert rep["clean"]
        assert rep["counts"].get("stale_tmp") == 3
        assert not any(
            is_tmp_name(f)
            for _d, _s, fs in os.walk(out) for f in fs
        )

    def test_unstamped_artifacts_restamped(self, folder):
        _, out = folder
        carry = os.path.join(out, CARRY_FILENAME)
        os.remove(carry + cks.SIDECAR_SUFFIX)
        tails = os.path.join(out, ".tiles", "tails.npy")
        os.remove(tails + cks.SIDECAR_SUFFIX)
        rep = audit(out, repair=True)
        assert rep["clean"]
        assert cks.verify_file_checksum(carry) == "ok"
        assert cks.verify_file_checksum(tails) == "ok"

    def test_corrupt_carry_promoted_from_prev(self, folder):
        _, out = folder
        carry = os.path.join(out, CARRY_FILENAME)
        prev_bytes = open(carry + ".prev", "rb").read()
        _flip_byte(carry)
        rep = audit(out, repair=True)
        assert rep["clean"]
        assert any(
            i["artifact"] == "carry" and i["action"] == "promoted_prev"
            for i in rep["issues"]
        )
        assert open(carry, "rb").read() == prev_bytes
        assert cks.verify_file_checksum(carry) == "ok"

    def test_torn_output_file_removed(self, folder):
        _, out = folder
        torn = os.path.join(
            out, "LFDAS_2099-01-01T000000.0_2099-01-01T000100.0.h5"
        )
        with open(torn, "wb") as fh:
            fh.write(b"\x89HDF\r\n\x1a\ngarbage")
        rep = audit(out, repair=True)
        assert rep["clean"]
        assert not os.path.isfile(torn)
        assert any(
            i["artifact"] == "output" and i["action"] == "removed"
            for i in rep["issues"]
        )

    def test_orphan_garbage_tile_removed(self, folder):
        _, out = folder
        orphan = os.path.join(out, ".tiles", "L0", "00009999.npy")
        with open(orphan, "wb") as fh:
            fh.write(b"not a tile")
        rep = audit(out, repair=True)
        assert rep["clean"]
        assert not os.path.isfile(orphan)
        assert any(i["status"] == "orphan" for i in rep["issues"])

    def test_both_ledger_rungs_bad_leaves_no_corpse(self, folder):
        """Both .quarantine.json rungs corrupt: the repair must remove
        BOTH (not just the primary), so the next ledger load finds
        clean absence instead of tripping (counted, degraded) over the
        corrupt .prev after a 'clean' fsck."""
        _, out = folder
        path = os.path.join(out, QUARANTINE_FILENAME)
        _flip_byte(path, 40)
        _flip_byte(path + ".prev", 40)
        rep = audit(out, repair=True)
        assert rep["clean"]
        assert not os.path.isfile(path)
        assert not os.path.isfile(path + ".prev")
        reg = MetricsRegistry()
        with use_registry(reg):
            led = QuarantineLedger(out)
        assert led.quarantined_count == 0
        assert reg.value(
            "tpudas_integrity_fallback_total", artifact="quarantine"
        ) == 0  # no corpse to fall over

    def test_lone_prev_carry_promoted(self, folder):
        """Primary carry missing (crash between rotate and write):
        the audit promotes the .prev rung so nothing is left for the
        runtime ladder to count."""
        _, out = folder
        path = os.path.join(out, CARRY_FILENAME)
        os.remove(path)
        os.remove(path + cks.SIDECAR_SUFFIX)
        rep = audit(out, repair=True)
        assert rep["clean"]
        assert cks.verify_file_checksum(path) == "ok"
        reg = MetricsRegistry()
        with use_registry(reg):
            assert load_carry(out) is not None
        assert reg.value(
            "tpudas_integrity_fallback_total", artifact="carry"
        ) == 0

    def test_manifest_torn_no_prev_rebuilds_with_geometry(self, folder):
        """A manifest that fails verification with NO usable .prev
        must still trigger a pyramid rebuild — with the original
        factor/tile_len recovered from the rotted-but-parseable rung
        BEFORE the repair deletes it — not strand the tiles."""
        from tpudas.serve.tiles import TileStore

        _, out = folder
        man = os.path.join(out, ".tiles", "manifest.json")
        os.remove(man + ".prev")
        raw = json.loads(open(man).read())
        raw[cks.CRC_KEY] = "00000000"  # bit rot that still parses
        open(man, "w").write(json.dumps(raw, indent=1))
        tails_pre = open(
            os.path.join(out, ".tiles", "tails.npy"), "rb"
        ).read()
        rep = audit(out, repair=True)
        assert rep["clean"]
        assert any(
            i["action"] == "rebuilt_pyramid" for i in rep["issues"]
        )
        store = TileStore.open(out)
        assert store is not None
        # geometry survived the rebuild (the rich fixture's 8/4, not
        # the 256/4 env defaults) -> tails byte-identical
        assert (store.tile_len, store.factor) == (8, 4)
        assert open(
            os.path.join(out, ".tiles", "tails.npy"), "rb"
        ).read() == tails_pre

    def test_second_audit_is_clean_and_empty(self, folder):
        _, out = folder
        _flip_byte(os.path.join(out, CARRY_FILENAME))
        _truncate(
            os.path.join(out, ".tiles", "manifest.json"), 20
        )
        audit(out, repair=True)
        rep2 = audit(out, repair=True)
        assert rep2["clean"] and not rep2["issues"]

    def test_no_repair_reports_only(self, folder):
        _, out = folder
        carry = os.path.join(out, CARRY_FILENAME)
        pre = open(carry, "rb").read()
        _flip_byte(carry)
        damaged = open(carry, "rb").read()
        rep = audit(out, repair=False)
        assert not rep["clean"]
        assert open(carry, "rb").read() == damaged != pre

    def test_driver_startup_audit_runs_and_repairs(self, folder):
        src, out = folder
        _flip_byte(os.path.join(out, CARRY_FILENAME))
        _append_one(src, 3)
        reg = MetricsRegistry()
        with use_registry(reg):
            rounds = _drive(src, out, pyramid=True)
        assert rounds >= 1
        assert reg.value("tpudas_integrity_audit_runs_total") >= 1
        assert reg.value(
            "tpudas_integrity_audit_repairs_total", kind="promoted_prev"
        ) >= 1

    def test_fsck_cli_roundtrip(self, folder, tmp_path, capsys):
        from tools.fsck import main as fsck_main

        _, out = folder
        _flip_byte(os.path.join(out, CARRY_FILENAME))
        report_path = str(tmp_path / "fsck.json")
        rc = fsck_main([out, "--out", report_path])
        assert rc == 0  # repaired -> clean
        rep = json.loads(open(report_path).read())
        assert rep["clean"] and rep["repaired"] >= 1
        out_text = capsys.readouterr().out
        assert '"clean": true' in out_text
        # a second run has nothing to do
        assert fsck_main([out]) == 0


# ---------------------------------------------------------------------------
# disk-full degradation


class TestResourceDegradation:
    def test_classify_enospc_is_resource(self):
        assert classify_failure(enospc_error()) == "resource"
        import errno

        assert classify_failure(
            OSError(errno.EDQUOT, "quota")
        ) == "resource"
        assert classify_failure(OSError("plain")) == "transient"

    def test_is_resource_error_walks_cause_chain(self):
        try:
            try:
                raise enospc_error()
            except OSError as inner:
                raise RuntimeError("wrapped") from inner
        except RuntimeError as outer:
            assert res.is_resource_error(outer)
        assert not res.is_resource_error(ValueError("x"))

    def test_resource_patience_multiplies_retry_budget(self):
        from tpudas.resilience.faults import FaultBoundary

        reg = MetricsRegistry()
        with use_registry(reg):
            b = FaultBoundary(RetryPolicy(
                base_delay=0.0, jitter=0.0, max_consecutive=2,
                resource_patience=3,
            ))
            decisions = [
                b.on_failure(enospc_error()) for _ in range(7)
            ]
        assert [d.propagate for d in decisions] == (
            [False] * 6 + [True]
        )
        assert all(d.kind == "resource" for d in decisions)
        res.clear_pressure("test")

    def test_enospc_sheds_then_recovers(
        self, tmp_path, clear_resource_state
    ):
        """The acceptance drill: ENOSPC on every pyramid/prom/probe
        write for two rounds sheds those writers (counted, health
        degraded) while core outputs keep flowing; when the fault
        window lifts the probe succeeds and everything resumes."""
        from tpudas.serve.tiles import sync_pyramid

        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        csrc, cout = str(tmp_path / "csrc"), str(tmp_path / "cout")
        _spool(csrc)
        _drive(csrc, cout, feed_third=True, pyramid=True)
        control = _hashes(cout)

        _spool(src)
        plan = FaultPlan(
            FaultSpec("fs.write_enospc", at=1, times=10**6,
                      exc=enospc_error(), match=".tiles"),
            FaultSpec("fs.write_enospc", at=1, times=10**6,
                      exc=enospc_error(), match="metrics.prom"),
            FaultSpec("fs.write_enospc", at=1, times=10**6,
                      exc=enospc_error(), match=".space_probe"),
        )
        seen = []

        def on_round(rnd, lfp):
            h = read_health(out)
            if h is not None:
                seen.append((h["degraded"], h["resource_degraded"]))
            if rnd == 2:
                install_fault_plan(None)  # space returns

        reg = MetricsRegistry()
        with use_registry(reg), install_fault_plan(plan):
            rounds = _drive(
                src, out, feed_third=True, pyramid=True, health=True,
                on_round=on_round,
            )
        assert rounds >= 2
        assert (True, True) in seen  # degradation was visible mid-run
        assert reg.value(
            "tpudas_integrity_writes_shed_total", writer="prom"
        ) >= 1
        assert reg.value(
            "tpudas_integrity_writes_shed_total", writer="pyramid"
        ) >= 1
        assert reg.value(
            "tpudas_integrity_resource_events_total"
        ) == 1
        assert not res.is_degraded()  # recovered in-process
        final = read_health(out)
        assert final["resource_degraded"] is False
        # core outputs were never shed
        assert _hashes(out) == control
        # and the pyramid backfills to exactly the output head
        sync_pyramid(out)
        from tpudas.serve.tiles import TileStore

        store = TileStore.open(out)
        assert store is not None and store.n(0) > 0


# ---------------------------------------------------------------------------
# crash drill (process-level SIGKILL)


class TestCrashDrill:
    @pytest.mark.slow
    def test_smoke_seeded_kills_resume_clean(self):
        """Tier-1 smoke: 2 seeded SIGKILL cycles, cascade engine,
        pyramid on — audit clean, outputs + pyramid byte-identical to
        the uninterrupted control.  The full 25-cycle x 2-engine
        acceptance drill runs under -m slow (and as the
        tools/crash_drill.py CLI default)."""
        from tools.crash_drill import run_drill

        rep = run_drill(engine="cascade", cycles=2, seed=3)
        assert rep["audit_clean"], rep
        assert rep["outputs_match"], rep
        assert rep["pyramid_match"], rep
        assert rep["ok"]

    @pytest.mark.slow
    def test_smoke_mesh_drill_sharded_path(self):
        """Tier-1 smoke of the --mesh drill (ISSUE 7): a seeded
        SIGKILL cycle on the channel-sharded cascade ends audit-clean
        and byte-identical to the SINGLE-DEVICE control replay — the
        sharded path survives power cuts and stays bit-exact."""
        from tools.crash_drill import run_drill

        rep = run_drill(engine="cascade", cycles=1, seed=5, mesh=4)
        assert rep["mesh"] == 4
        assert rep["audit_clean"], rep
        assert rep["outputs_match"], rep
        assert rep["pyramid_match"], rep
        assert rep["detect_match"], rep
        assert rep["ok"]

    @pytest.mark.slow
    def test_smoke_fused_mesh_drill(self):
        """Tier-1 smoke of the fused-engine drill leg (ISSUE 10): a
        seeded SIGKILL cycle with ``engine="fused"`` on the
        channel-sharded path ends audit-clean and byte-identical to
        its own uninterrupted control — the fused carry save/resume
        cycle survives power cuts (the drill worker clears
        TPUDAS_FUSED_MIN_ELEMS so the small stream really runs the
        fused kernel)."""
        from tools.crash_drill import run_drill

        rep = run_drill(engine="fused", cycles=1, seed=7, mesh=4)
        assert rep["engine"] == "fused"
        assert rep["audit_clean"], rep
        assert rep["outputs_match"], rep
        assert rep["pyramid_match"], rep
        assert rep["detect_match"], rep
        assert rep["ok"]

    @pytest.mark.slow
    def test_smoke_async_ingest_drill(self):
        """Tier-1 smoke of the --async-ingest drill leg (ISSUE 15): a
        seeded SIGKILL cycle with the prefetch pipeline on (drilled
        workers run TPUDAS_INGEST_PREFETCH=2, the control replay runs
        the synchronous loop) ends audit-clean and byte-identical —
        prefetched-but-uncommitted slices are crash-equivalent to
        never-read, and the async path's durable bytes equal the
        sync path's."""
        from tools.crash_drill import run_drill

        rep = run_drill(
            engine="cascade", cycles=1, seed=9, async_ingest=True
        )
        assert rep["async_ingest"] is True
        assert rep["audit_clean"], rep
        assert rep["outputs_match"], rep
        assert rep["pyramid_match"], rep
        assert rep["detect_match"], rep
        assert rep["ok"]

    @pytest.mark.slow
    @pytest.mark.parametrize("engine", ["cascade", "fft", "fused"])
    @pytest.mark.parametrize("mesh", [0, 4])
    def test_full_drill(self, engine, mesh):
        from tools.crash_drill import run_drill

        rep = run_drill(engine=engine, cycles=25, seed=0, mesh=mesh)
        assert rep["kills"] >= 15, rep  # most cycles really died
        assert rep["ok"], rep

    @pytest.mark.slow
    def test_full_async_ingest_drill(self):
        from tools.crash_drill import run_drill

        rep = run_drill(
            engine="cascade", cycles=12, seed=0, async_ingest=True
        )
        assert rep["kills"] >= 6, rep
        assert rep["ok"], rep


class TestBackfillDrill:
    @pytest.mark.slow
    def test_smoke_two_workers_two_kills(self):
        """Tier-1 smoke of the cluster-backfill chaos drill
        (ISSUE 12): 2 worker processes against one queue, 2 seeded
        SIGKILLs plus injected claim/commit faults — the drained
        queue audits clean and the stitched result is byte-identical
        to a 1-worker uninterrupted control AND to a plain sequential
        realtime run.  The N=4 / >=6-kill acceptance drill runs under
        ``-m slow`` (and as the tools/backfill_drill.py CLI default,
        recorded in BENCH_pr12.json)."""
        from tools.backfill_drill import run_backfill_drill

        rep = run_backfill_drill(workers=2, kills=2, shards=4, seed=3)
        assert rep["kills"] >= 1, rep
        assert rep["audit_clean"], rep
        assert rep["parked"] == [], rep
        for key in (
            "outputs_match_control",
            "pyramid_match_control",
            "detect_match_control",
            "outputs_match_sequential",
            "pyramid_match_sequential",
            "detect_match_sequential",
        ):
            assert rep[key], (key, rep)
        assert rep["ok"], rep

    @pytest.mark.slow
    def test_full_backfill_drill(self):
        from tools.backfill_drill import run_backfill_drill

        rep = run_backfill_drill(workers=4, kills=6, shards=8, seed=0)
        assert rep["kills"] >= 6, rep
        assert rep["ok"], rep


# ---------------------------------------------------------------------------
# health schema v3 integration


class TestHealthIntegrity:
    def test_health_carries_integrity_fields(self, folder):
        _, out = folder
        h = read_health(out)
        assert h["schema"] == 3
        assert h["integrity_fallbacks"] == 0
        assert h["resource_degraded"] is False

    def test_written_health_is_stamped(self, tmp_path):
        with use_registry(MetricsRegistry()):
            write_health(str(tmp_path), {
                "rounds": 1, "polls": 1, "mode": "stateful",
                "realtime_factor": 1.0, "round_realtime_factor": 1.0,
                "head_lag_seconds": None, "redundant_ratio": 0.0,
                "carry_resume_count": 0,
                "last_round_wall_seconds": 0.0,
                "consecutive_failures": 0, "quarantined_files": 0,
                "degraded": False, "integrity_fallbacks": 0,
                "resource_degraded": False, "last_error": None,
            })
        raw = json.loads(open(tmp_path / "health.json").read())
        assert cks.verify_json_obj(raw) == "ok"
