"""tpudas.resilience: failure taxonomy, retry/backoff, quarantine
ledger, the per-round fault boundary in the realtime drivers, and the
crash-resume-equivalence acceptance tests (ISSUE 3).

The acceptance bar: for every FaultPlan site (spool read, index
update, round body, carry save) a transient fault is retried and the
final output folder is BYTE-identical to the fault-free run; a
persistently corrupt file ends quarantined with the driver still
alive, visible in health.json and the metrics registry.
"""

import hashlib
import json
import os
import warnings

import numpy as np
import pytest

from tpudas.io.registry import write_patch
from tpudas.core.timeutils import to_datetime64
from tpudas.obs.health import read_health
from tpudas.obs.registry import MetricsRegistry, use_registry
from tpudas.proc.streaming import run_lowpass_realtime, run_rolling_realtime
from tpudas.resilience.faults import (
    FAULT_SITES,
    FaultBoundary,
    RetryPolicy,
    SpoolReadError,
    TransientFaultError,
    classify_failure,
)
from tpudas.resilience.quarantine import QUARANTINE_FILENAME, QuarantineLedger
from tpudas.testing import (
    FaultPlan,
    FaultSpec,
    install_fault_plan,
    make_synthetic_spool,
    synthetic_patch,
    write_corrupt_file,
)

T0 = "2023-03-22T00:00:00"
FS = 50.0
FILE_SEC = 20.0
NCH = 4

# a fast policy for tests: no real sleeping, low thresholds
FAST = RetryPolicy(base_delay=0.0, max_delay=0.0, jitter=0.0,
                   quarantine_after=2, quarantine_retry=900.0)


def _spool(src, n_files=2, start=T0, prefix="raw"):
    return make_synthetic_spool(
        src, n_files=n_files, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
        noise=0.01, start=start, prefix=prefix,
    )


def _append_one(src, index):
    t0 = to_datetime64(T0).astype("datetime64[ns]")
    step = np.timedelta64(int(round(1e9 / FS)), "ns")
    n = int(FILE_SEC * FS)
    p = synthetic_patch(
        t0=t0 + index * n * step, duration=FILE_SEC, fs=FS, n_ch=NCH,
        seed=index, phase_origin=t0, noise=0.01,
    )
    write_patch(p, os.path.join(src, f"raw_{index:04d}.h5"))


def _drive(src, out, policy=FAST, engine=None, feed_third=False, **kw):
    """One realtime run over ``src`` into ``out``; ``feed_third``
    appends a third file via the injected sleep (a second round)."""
    def sleep(_):
        if feed_third and not os.path.isfile(
            os.path.join(src, "raw_0002.h5")
        ):
            _append_one(src, 2)

    return run_lowpass_realtime(
        source=src,
        output_folder=out,
        start_time=T0,
        output_sample_interval=1.0,
        edge_buffer=5.0,
        process_patch_size=20,
        poll_interval=0.0,
        sleep_fn=sleep,
        fault_policy=policy,
        engine=engine,
        **kw,
    )


def _hashes(out):
    """{basename: sha256} of the product files in ``out``."""
    return {
        f: hashlib.sha256(
            open(os.path.join(out, f), "rb").read()
        ).hexdigest()
        for f in sorted(os.listdir(out))
        if f.endswith(".h5")
    }


class TestClassify:
    def test_taxonomy(self):
        import errno

        assert classify_failure(OSError("nfs hiccup")) == "transient"
        assert classify_failure(TransientFaultError("x")) == "transient"
        assert classify_failure(TimeoutError("t")) == "transient"
        # disk-full on the OUTPUT side is its own kind (PR 5): retried
        # with extra patience + non-essential writers shed
        assert classify_failure(
            OSError(errno.ENOSPC, "no space left on device")
        ) == "resource"
        assert classify_failure(
            OSError(errno.EDQUOT, "quota exceeded")
        ) == "resource"
        # ...but ENOSPC surfacing through a SOURCE file read stays
        # file-attributed (transient, the interrogator side)
        assert classify_failure(
            SpoolReadError("/d/f.h5", OSError(errno.ENOSPC, "full"))
        ) == "transient"
        # file-attributed: OSError inside -> transient, decode -> corrupt
        assert classify_failure(
            SpoolReadError("/d/f.h5", OSError("short read"))
        ) == "transient"
        assert classify_failure(
            SpoolReadError("/d/f.h5", ValueError("not a dasdae file"))
        ) == "corrupt"
        # config/programming errors are fatal, as is the reference's
        # gap raise (a bare Exception)
        assert classify_failure(ValueError("bad param")) == "fatal"
        assert classify_failure(TypeError("bad call")) == "fatal"
        assert classify_failure(
            Exception("patch merge failed! Gap in data exists")
        ) == "fatal"
        assert classify_failure(MemoryError()) == "fatal"

    def test_spool_read_error_carries_path(self):
        e = SpoolReadError("/data/raw_0001.h5", ValueError("boom"))
        assert e.path == "/data/raw_0001.h5"
        assert "raw_0001.h5" in str(e) and "boom" in str(e)


class TestRetryPolicy:
    def test_backoff_deterministic_capped(self):
        p = RetryPolicy(base_delay=1.0, max_delay=8.0, multiplier=2.0,
                        jitter=0.1, seed=7)
        d = [p.delay(a) for a in range(6)]
        assert d == [p.delay(a) for a in range(6)]  # deterministic
        # capped exponential: base values 1,2,4,8,8,8 with <=10% jitter
        for got, base in zip(d, [1, 2, 4, 8, 8, 8]):
            assert base <= got <= base * 1.1
        # different seed -> different jitter (same bounds)
        assert [RetryPolicy(seed=8, jitter=0.1).delay(a) for a in range(6)] != d

    def test_zero_policy_for_tests(self):
        assert FAST.delay(0) == 0.0 and FAST.delay(5) == 0.0


class TestFaultPlan:
    def test_fires_on_nth_hit_only(self):
        plan = FaultPlan(FaultSpec("round.body", at=2))
        plan.hit("round.body", {})  # hit 1: no fire
        with pytest.raises(TransientFaultError):
            plan.hit("round.body", {})
        plan.hit("round.body", {})  # hit 3: window passed
        assert plan.fired == [("round.body", "raise", 2)]
        assert plan.hits["round.body"] == 3

    def test_exc_class_and_instance(self):
        with pytest.raises(RuntimeError):
            FaultPlan(FaultSpec("carry.save", exc=RuntimeError)).hit(
                "carry.save", {}
            )
        marker = ValueError("exact instance")
        plan = FaultPlan(FaultSpec("carry.save", exc=marker))
        with pytest.raises(ValueError) as ei:
            plan.hit("carry.save", {})
        assert ei.value is marker

    def test_truncate_and_delay_and_match(self, tmp_path):
        f = tmp_path / "x.h5"
        f.write_bytes(b"A" * 100)
        slept = []
        plan = FaultPlan(
            FaultSpec("spool.read", action="truncate", nbytes=10),
            FaultSpec("index.update", action="delay", seconds=0.25,
                      sleep_fn=slept.append),
            FaultSpec("round.body", at=1, times=99, match="only-this"),
        )
        plan.hit("spool.read", {"path": str(f)})
        assert f.stat().st_size == 10
        plan.hit("index.update", {"directory": str(tmp_path)})
        assert slept == [0.25]
        plan.hit("round.body", {"path": "something-else"})  # no raise
        with pytest.raises(TransientFaultError):
            plan.hit("round.body", {"path": "x/only-this/y"})

    def test_install_scopes(self):
        from tpudas.resilience.faults import fault_point

        plan = FaultPlan(FaultSpec("round.body"))
        with install_fault_plan(plan):
            with pytest.raises(TransientFaultError):
                fault_point("round.body")
        fault_point("round.body")  # uninstalled: no-op

    def test_unknown_site_and_action_rejected(self):
        with pytest.raises(ValueError, match="site"):
            FaultSpec("not.a.site")
        with pytest.raises(ValueError, match="action"):
            FaultSpec("round.body", action="explode")


class TestQuarantineLedger:
    def test_threshold_excludes_and_persists(self, tmp_path):
        led = QuarantineLedger(str(tmp_path))
        assert led.record_failure("/src/a.h5", "e1", now=100.0,
                                  threshold=2, retry_interval=60.0) is None
        assert led.quarantined_count == 0
        assert led.record_failure("/src/a.h5", "e2", now=110.0,
                                  threshold=2, retry_interval=60.0) == "added"
        assert led.quarantined_count == 1
        assert led.excluded(now=120.0) == {"a.h5"}
        # probe window opens at 110 + 60
        assert led.excluded(now=171.0) == frozenset()
        assert led.probe_open_names(now=171.0) == ["a.h5"]
        # reload from disk: same state
        led2 = QuarantineLedger(str(tmp_path))
        assert led2.quarantined_count == 1
        assert led2.entry("a.h5")["fails"] == 2

    def test_failed_probe_escalates_capped(self, tmp_path):
        led = QuarantineLedger(str(tmp_path))
        now = 0.0
        assert led.record_failure("b.h5", "e", now=now, threshold=1,
                                  retry_interval=100.0) == "added"
        waits = [led.entry("b.h5")["retry_at"] - now]
        for _ in range(5):
            now = led.entry("b.h5")["retry_at"]  # probe opens
            assert led.record_failure(
                "b.h5", "e", now=now, threshold=1, retry_interval=100.0
            ) == "requarantined"
            waits.append(led.entry("b.h5")["retry_at"] - now)
        assert waits == [100.0, 200.0, 400.0, 800.0, 800.0, 800.0]

    def test_probe_pending_survives_failure(self, tmp_path):
        led = QuarantineLedger(str(tmp_path))
        led.record_failure("c.h5", "e", now=0.0, threshold=1,
                           retry_interval=10.0, source="read")
        led.mark_probe_pending("c.h5")
        assert led.probe_pending_names() == ["c.h5"]
        # a failed probe read clears the flag AND keeps escalation
        led.record_failure("c.h5", "e2", now=11.0, threshold=1,
                           retry_interval=10.0, source="read")
        assert led.probe_pending_names() == []
        assert led.entry("c.h5")["rounds"] == 2

    def test_success_releases_clean_slate(self, tmp_path):
        led = QuarantineLedger(str(tmp_path))
        led.record_failure("c.h5", "e", now=0.0, threshold=1,
                           retry_interval=10.0)
        assert led.quarantined_count == 1
        assert led.record_success("/any/prefix/c.h5")
        assert led.quarantined_count == 0 and led.entry("c.h5") is None
        assert not led.record_success("c.h5")  # idempotent

    def test_corrupt_ledger_degrades_to_empty(self, tmp_path):
        (tmp_path / QUARANTINE_FILENAME).write_text("{not json")
        led = QuarantineLedger(str(tmp_path))
        assert led.quarantined_count == 0
        led.record_failure("d.h5", "e", now=0.0)  # and it can re-save
        assert json.load(open(tmp_path / QUARANTINE_FILENAME))["files"]


class TestTransientRetryByteIdentical:
    """Acceptance: for every fault site, one transient fault is
    retried and the final output folder is byte-identical to the
    fault-free run (stateful carry mode, the default)."""

    # carry.save at=2 is the nastiest case: the save AFTER round 1's
    # outputs fails, so the retry must reconcile the partial emission;
    # fs.write_enospc at=2 (PR 5) fails a checksummed atomic state
    # write mid-round — the round retries like any transient IO
    SPECS = {
        "spool.read": FaultSpec("spool.read", at=1),
        "index.update": FaultSpec("index.update", at=1),
        "round.body": FaultSpec("round.body", at=1),
        "carry.save": FaultSpec("carry.save", at=2),
        "fs.write_enospc": FaultSpec("fs.write_enospc", at=2),
    }

    @pytest.fixture(scope="class")
    def control(self, tmp_path_factory):
        td = tmp_path_factory.mktemp("control")
        src, out = str(td / "src"), str(td / "out")
        _spool(src)
        rounds = _drive(src, out)
        assert rounds >= 1
        return _hashes(out)

    @pytest.mark.parametrize("site", sorted(SPECS))
    def test_retried_and_identical(self, tmp_path, control, site):
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src)
        plan = FaultPlan(self.SPECS[site])
        reg = MetricsRegistry()
        with use_registry(reg), install_fault_plan(plan):
            rounds = _drive(src, out)
        assert rounds >= 1  # the driver survived
        assert plan.fired, f"fault at {site} never fired"
        assert reg.value(
            "tpudas_stream_round_failures_total", kind="transient"
        ) >= 1
        assert reg.value("tpudas_stream_retries_total") >= 1
        # after recovery the degradation gauges are back to healthy
        assert reg.value("tpudas_stream_consecutive_failures") == 0
        assert reg.value("tpudas_stream_degraded") == 0
        got = _hashes(out)
        assert got == control, f"outputs diverged after {site} fault"


class TestCrashResumeEquivalence:
    """Satellite: kill the driver (fatal injected fault) at each site
    mid-run, resume, and the outputs are byte-identical to an
    uninterrupted run — cascade and FFT engines."""

    # KeyboardInterrupt bypasses every `except Exception` (the fault
    # boundary included) exactly like a SIGINT kill on the edge box —
    # the truest mid-round crash the harness can inject
    SPECS = {
        "spool.read": FaultSpec("spool.read", at=2, exc=KeyboardInterrupt),
        "index.update": FaultSpec(
            "index.update", at=2, exc=KeyboardInterrupt
        ),
        "round.body": FaultSpec("round.body", at=2, exc=KeyboardInterrupt),
        "carry.save": FaultSpec("carry.save", at=2, exc=KeyboardInterrupt),
        # PR 5: die INSIDE an atomic state write (the checksummed
        # carry/health/index path) — the stamp + .prev ladder must
        # make the resume seam-free anyway
        "fs.write_enospc": FaultSpec(
            "fs.write_enospc", at=2, exc=KeyboardInterrupt
        ),
    }

    @pytest.fixture(scope="class")
    def controls(self, tmp_path_factory):
        out = {}
        for engine in ("cascade", "fft"):
            td = tmp_path_factory.mktemp(f"ctrl_{engine}")
            src, dst = str(td / "src"), str(td / "out")
            _spool(src)
            rounds = _drive(src, dst, engine=engine, feed_third=True)
            assert rounds == 2
            out[engine] = _hashes(dst)
        return out

    @pytest.mark.parametrize("engine", ["cascade", "fft"])
    @pytest.mark.parametrize("site", sorted(SPECS))
    def test_kill_resume_identical(self, tmp_path, controls, engine, site):
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src)
        plan = FaultPlan(self.SPECS[site])
        with install_fault_plan(plan):
            with pytest.raises(KeyboardInterrupt):
                _drive(src, out, engine=engine, feed_third=True)
        assert plan.fired  # it really died at the injected site
        # resume (no faults): same crash-only path a process restart takes
        rounds = _drive(src, out, engine=engine, feed_third=True)
        assert rounds >= 1
        assert _hashes(out) == controls[engine], (
            f"{engine}: resume after {site} kill diverged from "
            "uninterrupted run"
        )


class TestQuarantineEndToEnd:
    def test_scan_corrupt_file_quarantined_driver_alive(
        self, tmp_path, monkeypatch
    ):
        """A file that never scans (garbage bytes) is struck every
        poll, quarantined at the threshold, and the driver terminates
        normally with the skip visible in health.json, metrics, and
        the ledger."""
        monkeypatch.setenv("TPUDAS_HEALTH", "1")
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src)
        write_corrupt_file(os.path.join(src, "raw_0099.h5"))
        reg = MetricsRegistry()
        with use_registry(reg):
            rounds = _drive(src, out)
        assert rounds >= 1  # good files processed; driver alive
        led = QuarantineLedger(out)
        assert led.quarantined_names() == ["raw_0099.h5"]
        assert reg.value("tpudas_stream_quarantined_files") == 1
        assert reg.value("tpudas_stream_quarantine_added_total") == 1
        health = read_health(out)
        assert health is not None
        assert health["quarantined_files"] == 1
        assert health["degraded"] is True

    def test_payload_corrupt_file_quarantined_then_released(
        self, tmp_path
    ):
        """Scan passes but every payload read of ONE file raises a
        decode error: the round retries, the file is quarantined (the
        driver finishes on the good files), and after the slow-retry
        window a repaired file is released and processed."""
        clk = {"t": 1000.0}
        policy = RetryPolicy(
            base_delay=0.0, max_delay=0.0, jitter=0.0,
            quarantine_after=2, quarantine_retry=60.0,
            clock=lambda: clk["t"],
        )
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=3)
        plan = FaultPlan(
            FaultSpec("spool.read", at=1, times=9999, exc=ValueError,
                      match="raw_0002"),
        )
        reg = MetricsRegistry()
        with use_registry(reg), install_fault_plan(plan):
            rounds = _drive(src, out, policy=policy)
        assert rounds >= 1
        led = QuarantineLedger(out)
        assert led.quarantined_names() == ["raw_0002.h5"]
        assert reg.value(
            "tpudas_stream_round_failures_total", kind="corrupt"
        ) >= 2
        n_outputs_degraded = len(_hashes(out))
        assert n_outputs_degraded > 0  # files 0-1 were emitted
        # the "interrogator finished writing it late" path: the file is
        # fine now, the probe window opens, the driver releases and
        # processes it
        clk["t"] += 120.0
        with use_registry(reg):
            rounds2 = _drive(src, out, policy=policy)
        assert rounds2 >= 1
        assert QuarantineLedger(out).quarantined_count == 0
        assert reg.value("tpudas_stream_quarantine_released_total") == 1
        assert len(_hashes(out)) > n_outputs_degraded

    def test_still_corrupt_probe_escalates_not_released(self, tmp_path):
        """A probe read that fails again must re-quarantine WITH the
        entry's backoff history (doubled wait), not release-and-restart
        the strike cascade — and the release counter must not move."""
        clk = {"t": 1000.0}
        policy = RetryPolicy(
            base_delay=0.0, max_delay=0.0, jitter=0.0,
            quarantine_after=2, quarantine_retry=60.0,
            clock=lambda: clk["t"],
        )
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=3)

        def plan():
            return FaultPlan(
                FaultSpec("spool.read", at=1, times=9999, exc=ValueError,
                          match="raw_0002"),
            )

        reg = MetricsRegistry()
        with use_registry(reg), install_fault_plan(plan()):
            _drive(src, out, policy=policy)
        e = QuarantineLedger(out).entry("raw_0002.h5")
        assert e["quarantined"] and e["rounds"] == 1
        assert e["source"] == "read"
        corrupt_before = reg.value(
            "tpudas_stream_round_failures_total", kind="corrupt"
        )
        clk["t"] = e["retry_at"] + 1.0  # probe window opens
        with use_registry(reg), install_fault_plan(plan()):
            rounds2 = _drive(src, out, policy=policy)
        assert rounds2 >= 1  # driver alive, probe cost ONE failed round
        e2 = QuarantineLedger(out).entry("raw_0002.h5")
        assert e2["quarantined"] and e2["rounds"] == 2
        assert e2["retry_at"] - e2["last_failed_at"] == pytest.approx(120.0)
        assert reg.value(
            "tpudas_stream_quarantine_requarantined_total"
        ) == 1
        assert reg.value("tpudas_stream_quarantine_released_total") == 0
        assert reg.value(
            "tpudas_stream_round_failures_total", kind="corrupt"
        ) == corrupt_before + 1

    def test_quarantine_false_disables_ledger(self, tmp_path):
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src)
        write_corrupt_file(os.path.join(src, "raw_0099.h5"))
        rounds = _drive(src, out, quarantine=False)
        assert rounds >= 1
        assert not os.path.isfile(os.path.join(out, QUARANTINE_FILENAME))


class TestFatalAndExhaustion:
    def test_fatal_propagates_immediately(self, tmp_path):
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src)
        reg = MetricsRegistry()
        plan = FaultPlan(FaultSpec("round.body", exc=TypeError))
        with use_registry(reg), install_fault_plan(plan):
            with pytest.raises(TypeError):
                _drive(src, out)
        assert reg.value("tpudas_stream_retries_total") == 0
        assert reg.value(
            "tpudas_stream_round_failures_total", kind="fatal"
        ) == 1
        assert reg.value("tpudas_stream_errors_total") == 1

    def test_persistent_transient_exhausts_and_propagates(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("TPUDAS_HEALTH", "1")
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src)
        policy = RetryPolicy(base_delay=0.0, max_delay=0.0, jitter=0.0,
                             max_consecutive=2)
        plan = FaultPlan(
            FaultSpec("index.update", at=1, times=9999)
        )
        reg = MetricsRegistry()
        with use_registry(reg), install_fault_plan(plan):
            with pytest.raises(TransientFaultError):
                _drive(src, out, policy=policy)
        assert reg.value("tpudas_stream_retries_total") == 2
        health = read_health(out)
        assert health is not None and health["last_error"] is not None
        assert "TransientFaultError" in health["last_error"]

    def test_rolling_driver_retries_too(self, tmp_path):
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src)
        reg = MetricsRegistry()
        plan = FaultPlan(FaultSpec("round.body", at=1))
        from tpudas.core.units import s as sec

        with use_registry(reg), install_fault_plan(plan):
            rounds = run_rolling_realtime(
                source=src, output_folder=out, window=1.0 * sec,
                step=1.0 * sec, poll_interval=0.0,
                sleep_fn=lambda _: None, fault_policy=FAST,
            )
        assert rounds >= 1
        assert reg.value("tpudas_stream_retries_total") == 1
        assert len(_hashes(out)) == 2  # both patches still processed


class TestBoundaryUnit:
    def test_success_resets_consecutive(self):
        reg = MetricsRegistry()
        with use_registry(reg):
            b = FaultBoundary(RetryPolicy(base_delay=0.0, jitter=0.0))
            d1 = b.on_failure(OSError("x"))
            assert (d1.kind, d1.propagate) == ("transient", False)
            assert b.consecutive == 1 and b.degraded
            b.on_success()
            assert b.consecutive == 0 and not b.degraded
            assert b.last_error is None

    def test_health_degradation_fields_flow(self, tmp_path):
        """The boundary's state lands in health.json via the driver's
        _EdgeHealth (consecutive_failures while retrying)."""
        from tpudas.proc.streaming import _EdgeHealth
        from tpudas.utils.profiling import Counters

        reg = MetricsRegistry()
        with use_registry(reg):
            b = FaultBoundary(RetryPolicy(base_delay=0.0, jitter=0.0))
            b.on_failure(OSError("flaky mount"))
            eh = _EdgeHealth(str(tmp_path), True, b)
            eh.write(Counters(), 1, 2, "stateful", 0.0, None)
        got = read_health(str(tmp_path))
        assert got["consecutive_failures"] == 1
        assert got["degraded"] is True
        assert "flaky mount" in got["last_error"]


class TestGapToleranceAlias:
    def test_correct_spelling_accepted(self):
        from tpudas.proc.lfproc import LFProc

        lfp = LFProc()
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # no deprecation for the fix
            p = lfp.update_processing_parameter(data_gap_tolerance=7.5)
        assert p["data_gap_tolorance"] == 7.5  # storage keeps ref key

    def test_legacy_spelling_warns_once(self):
        import tpudas.proc.lfproc as lfproc_mod
        from tpudas.proc.lfproc import LFProc

        lfproc_mod._GAP_ALIAS_WARNED = False
        lfp = LFProc()
        with pytest.warns(DeprecationWarning, match="misspelling"):
            lfp.update_processing_parameter(data_gap_tolorance=3.0)
        assert lfp.parameters["data_gap_tolorance"] == 3.0
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # second use: silent
            lfp.update_processing_parameter(data_gap_tolorance=4.0)

    def test_conflicting_values_rejected(self, tmp_path):
        from tpudas.proc.lfproc import LFProc

        with pytest.raises(ValueError, match="disagree"):
            LFProc().update_processing_parameter(
                data_gap_tolerance=5.0, data_gap_tolorance=10.0
            )
        with pytest.raises(ValueError, match="disagree"):
            run_lowpass_realtime(
                source=str(tmp_path),
                output_folder=str(tmp_path / "out"),
                start_time=T0,
                output_sample_interval=1.0,
                edge_buffer=5.0,
                process_patch_size=20,
                data_gap_tolerance=5.0,
                data_gap_tolorance=10.0,
            )

    def test_agreeing_values_pass(self):
        from tpudas.proc.lfproc import LFProc

        p = LFProc().update_processing_parameter(
            data_gap_tolerance=5.0, data_gap_tolorance=5.0
        )
        assert p["data_gap_tolorance"] == 5.0

    def test_driver_forwards_correct_spelling(self, tmp_path):
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=1)
        seen = {}

        def on_round(r, lfp):
            seen["tol"] = lfp.parameters["data_gap_tolorance"]

        _drive(src, out, data_gap_tolerance=42.0, on_round=on_round)
        assert seen["tol"] == 42.0


class TestNarrowedLegacyProbe:
    def test_fresh_folder_probe_logs_no_outputs(self, tmp_path):
        """Satellite: the legacy-folder probe no longer swallows
        arbitrary exceptions — the expected empty-folder signal is
        logged as an event instead."""
        from tpudas.utils.logging import set_log_handler

        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=1)
        events = []
        set_log_handler(events.append)
        try:
            _drive(src, out)
        finally:
            set_log_handler(None)
        probes = [
            e for e in events if e["event"] == "stream_no_prior_outputs"
        ]
        assert probes and "IndexError" in probes[0]["reason"]
