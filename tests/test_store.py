"""tpudas.store: the object-store tile plane (ISSUE 18).

Backend contract (posix + fake through one parametrized surface),
scripted fault injection (5xx, lost response, torn upload, offline),
idempotency-aware retry with lost-CAS token-re-read recovery, the NVMe
read-through cache's stale-but-verified degradation ladder, and the
pyramid publisher / remote reader — including the race-matrix legs
that live at this layer: lost conditional put converging exactly-once,
and cache poisoning after a generation-bump CAS of the manifest.
"""

import os
import sys

import numpy as np
import pytest

from tpudas.obs.registry import MetricsRegistry, use_registry
from tpudas.serve.tiles import (
    MANIFEST_FILENAME,
    TileStore,
    rebuild_pyramid,
    sync_pyramid,
)
from tpudas.store import (
    CASConflictError,
    FakeObjectStore,
    FaultInjector,
    FaultRule,
    ObjectNotFoundError,
    PosixStore,
    PyramidPublisher,
    ReadThroughCache,
    RemotePyramid,
    RetryingStore,
    StoreError,
    StoreNetworkError,
    store_from_url,
    token_of,
)
from tpudas.testing import make_synthetic_spool

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _registry():
    reg = MetricsRegistry()
    return reg


@pytest.fixture(params=["posix", "fake"])
def backend(request, tmp_path):
    if request.param == "posix":
        return PosixStore(str(tmp_path / "store"))
    return FakeObjectStore()


class TestContract:
    def test_roundtrip_and_tokens(self, backend):
        token = backend.put("a/b/obj.bin", b"payload")
        assert token == backend.token_for(b"payload")
        data, token2 = backend.get("a/b/obj.bin")
        assert data == b"payload" and token2 == token
        assert backend.head("a/b/obj.bin") == token
        assert backend.exists("a/b/obj.bin")
        assert backend.head("a/b/missing") is None
        with pytest.raises(ObjectNotFoundError):
            backend.get("a/b/missing")

    def test_list_is_prefix_scoped_and_sorted(self, backend):
        for key in ("p/z", "p/a", "p/sub/x", "q/other"):
            backend.put(key, key.encode())
        assert backend.list("p") == ["p/a", "p/sub/x", "p/z"]
        assert backend.list("p/sub") == ["p/sub/x"]
        assert backend.list("p/su") == []  # prefix is path-segment-wise
        assert "q/other" in backend.list()

    def test_delete_is_idempotent(self, backend):
        backend.put("k", b"x")
        assert backend.delete("k") is True
        assert backend.delete("k") is False
        assert backend.head("k") is None

    def test_bad_keys_refused(self, backend):
        for key in ("", "/abs", "../up", "a/../../b", "a\\b"):
            with pytest.raises(StoreError):
                backend.put(key, b"x")

    def test_put_if_needs_exactly_one_precondition(self, backend):
        with pytest.raises(StoreError):
            backend.put_if("k", b"x")
        with pytest.raises(StoreError):
            backend.put_if("k", b"x", if_token="t", if_absent=True)

    def test_create_only_cas(self, backend):
        token = backend.put_if("lease", b"mine", if_absent=True)
        assert token == backend.token_for(b"mine")
        with pytest.raises(CASConflictError):
            backend.put_if("lease", b"rival", if_absent=True)
        assert backend.get("lease")[0] == b"mine"

    def test_if_match_cas(self, backend):
        t1 = backend.put("m", b"v1")
        t2 = backend.put_if("m", b"v2", if_token=t1)
        assert backend.get("m") == (b"v2", t2)
        # the stale token now loses, and the object is untouched
        with pytest.raises(CASConflictError):
            backend.put_if("m", b"v3", if_token=t1)
        assert backend.get("m")[0] == b"v2"
        # CAS against a missing object also loses
        with pytest.raises(CASConflictError):
            backend.put_if("absent", b"x", if_token=t1)

    def test_token_formula(self):
        assert token_of(b"") == "00000000-0"
        tok = token_of(b"abc")
        crc, _, length = tok.partition("-")
        assert len(crc) == 8 and length == "3"


class TestPosix:
    def test_tmp_files_invisible_but_listed_as_uploads(self, tmp_path):
        store = PosixStore(str(tmp_path))
        store.put("s/real", b"ok")
        # a crashed writer's tmp debris, planted directly
        debris = tmp_path / "s" / "half.tmp.999"
        debris.write_bytes(b"partial")
        assert store.list("s") == ["s/real"]
        assert store.list_uploads("s") == ["s/half.tmp.999"]
        assert store.abort_upload("s/half.tmp.999") is True
        assert store.list_uploads("s") == []
        assert store.abort_upload("s/real") is False  # not a tmp name
        assert store.get("s/real")[0] == b"ok"


class TestFakeFaults:
    def test_unavailable_fires_before_apply(self):
        store = FakeObjectStore(FaultInjector(
            FaultRule(kind="unavailable", op="put", match="victim"),
        ))
        with pytest.raises(StoreNetworkError):
            store.put("victim", b"x")
        assert store.head("victim") is None  # nothing applied
        store.put("victim", b"x")  # rule window passed
        assert store.get("victim")[0] == b"x"

    def test_lost_fires_after_apply(self):
        store = FakeObjectStore(FaultInjector(
            FaultRule(kind="lost", op="put", match="victim"),
        ))
        with pytest.raises(StoreNetworkError):
            store.put("victim", b"x")
        # the write LANDED; only the response was dropped
        assert store.get("victim")[0] == b"x"

    def test_torn_upload_leaves_debris_not_objects(self):
        store = FakeObjectStore(FaultInjector(
            FaultRule(kind="torn", op="put", match="victim"),
        ))
        with pytest.raises(StoreNetworkError):
            store.put("s/victim", b"x")
        assert store.list("s") == []  # readers never see partials
        assert store.list_uploads("s") == ["s/victim"]
        assert store.abort_upload("s/victim") is True
        assert store.list_uploads() == []

    def test_offline_fails_everything(self):
        store = FakeObjectStore()
        store.put("k", b"x")
        store.injector.set_offline(True)
        for call in (
            lambda: store.get("k"),
            lambda: store.head("k"),
            lambda: store.put("k2", b"y"),
            lambda: store.list(),
        ):
            with pytest.raises(StoreNetworkError):
                call()
        store.injector.set_offline(False)
        assert store.get("k")[0] == b"x"

    def test_latency_rule_sleeps(self):
        slept = []
        inj = FaultInjector(
            FaultRule(kind="latency", op="get", seconds=0.25),
            sleep_fn=slept.append,
        )
        store = FakeObjectStore(inj)
        store.put("k", b"x")
        store.get("k")
        assert slept == [0.25]

    def test_rule_hit_window(self):
        store = FakeObjectStore(FaultInjector(
            FaultRule(kind="unavailable", op="get", at=2, times=2),
        ))
        store.put("k", b"x")
        store.get("k")  # hit 1: clean
        for _ in range(2):  # hits 2-3: fire
            with pytest.raises(StoreNetworkError):
                store.get("k")
        store.get("k")  # hit 4: clean again

    def test_partition_scoped_to_prefix(self):
        """A partition severs ONE subtree; the rest keeps answering
        (how replication drills take down a single mirror's keys)."""
        store = FakeObjectStore()
        store.put("a/k", b"x")
        store.put("b/k", b"y")
        rule = store.injector.partition(match="a/")
        for call in (
            lambda: store.get("a/k"),
            lambda: store.put("a/k2", b"z"),
            lambda: store.head("a/k"),
        ):
            with pytest.raises(StoreNetworkError):
                call()
        # the unmatched subtree is untouched
        assert store.get("b/k")[0] == b"y"
        store.put("b/k2", b"z")
        # unbounded until healed — well past any hit-window default
        for _ in range(5):
            with pytest.raises(StoreNetworkError):
                store.head("a/k")
        assert store.injector.heal(rule) == 1
        assert store.get("a/k")[0] == b"x"
        assert store.head("a/k2") is None  # severed put never applied

    def test_partition_nothing_applied(self):
        store = FakeObjectStore()
        store.injector.partition(match="v")
        with pytest.raises(StoreNetworkError):
            store.put("v1", b"x")
        with pytest.raises(StoreNetworkError):
            store.put_if("v2", b"x", if_absent=True)
        store.injector.heal("v")
        assert store.list() == []

    def test_partition_whole_store_and_heal_by_match(self):
        store = FakeObjectStore()
        store.put("k", b"x")
        store.injector.partition()
        store.injector.partition()
        with pytest.raises(StoreNetworkError):
            store.get("k")
        # heal(None) lifts every match-everything partition at once
        assert store.injector.heal(None) == 2
        assert store.get("k")[0] == b"x"

    def test_partition_op_scoped(self):
        """op="put" severs writes only — reads still answer (an
        asymmetric partition, e.g. a read-only degraded mirror)."""
        store = FakeObjectStore()
        store.put("k", b"x")
        rule = store.injector.partition(op="put")
        with pytest.raises(StoreNetworkError):
            store.put("k2", b"y")
        assert store.get("k")[0] == b"x"
        store.injector.heal(rule)
        store.put("k2", b"y")

    def test_partition_fired_log(self):
        store = FakeObjectStore()
        store.injector.partition(match="p/")
        with pytest.raises(StoreNetworkError):
            store.put("p/k", b"x")
        kinds = [k for k, _op, _key, _hit in store.injector.fired]
        assert "partition" in kinds


class TestRetry:
    def _wrapped(self, *rules):
        sleeps = []
        store = RetryingStore(
            FakeObjectStore(FaultInjector(*rules)),
            sleep_fn=sleeps.append,
        )
        return store, sleeps

    def test_blind_retry_rides_out_a_5xx_storm(self):
        store, sleeps = self._wrapped(
            FaultRule(kind="unavailable", op="put", times=3),
        )
        with use_registry(_registry()) as reg:
            assert store.put("k", b"x") == token_of(b"x")
            assert reg.counter(
                "tpudas_store_retries_total", "",
                labelnames=("op", "backend"),
            ).value(op="put", backend="fake") == 3
        assert len(sleeps) == 3
        # capped-exponential backoff: non-decreasing, bounded
        assert sleeps == sorted(sleeps)
        assert all(0 < s <= store.policy.max_delay for s in sleeps)

    def test_patience_runs_out(self):
        store, _ = self._wrapped(
            FaultRule(kind="unavailable", op="get", times=99),
        )
        store.inner.put("k", b"x")
        with use_registry(_registry()) as reg:
            with pytest.raises(StoreNetworkError):
                store.get("k")
            # the member is down: counted per backend so a replicated
            # composite's failover is attributable in /metrics
            assert reg.counter(
                "tpudas_store_retry_exhausted_total", "",
                labelnames=("op", "backend"),
            ).value(op="get", backend="fake") == 1

    def test_lost_put_converges(self):
        store, _ = self._wrapped(FaultRule(kind="lost", op="put"))
        assert store.put("k", b"x") == token_of(b"x")
        assert store.inner.get("k")[0] == b"x"

    def test_lost_cas_recovered_by_token_reread(self):
        """The lost-conditional-put leg of the race matrix: the CAS
        applies, the response drops, and the retry layer must confirm
        its OWN write landed instead of re-issuing (which would
        conflict against itself and miscount a success as a lost
        race)."""
        store, sleeps = self._wrapped(FaultRule(kind="lost", op="cas"))
        with use_registry(_registry()) as reg:
            token = store.put_if("marker", b"mine", if_absent=True)
            assert token == token_of(b"mine")
            assert reg.counter(
                "tpudas_store_cas_recovered_total", "",
                labelnames=("backend",),
            ).value(backend="fake") == 1
        assert store.inner.get("marker")[0] == b"mine"
        assert sleeps == []  # recovery is one head, no backoff
        # and the marker still refuses a second writer: exactly-once
        with pytest.raises(CASConflictError):
            store.put_if("marker", b"rival", if_absent=True)

    def test_lost_cas_with_unreachable_reread_still_recovers(self):
        """Worst case: the response drops AND the confirm head fails.
        The eventual CASConflictError carrying our own token is the
        write confirming itself."""
        store, _ = self._wrapped(
            FaultRule(kind="lost", op="cas"),
            FaultRule(kind="unavailable", op="head", times=1),
        )
        assert store.put_if(
            "marker", b"mine", if_absent=True
        ) == token_of(b"mine")
        assert store.inner.get("marker")[0] == b"mine"

    def test_genuine_conflict_never_retried(self):
        store, sleeps = self._wrapped()
        store.put("m", b"theirs")
        with pytest.raises(CASConflictError):
            store.put_if("m", b"mine", if_absent=True)
        assert sleeps == []
        assert store.inner.get("m")[0] == b"theirs"

    def test_store_from_url_fake_is_shared_per_tag(self):
        a = store_from_url("fake:test-shared")
        b = store_from_url("fake:test-shared", retry=False)
        assert isinstance(a, RetryingStore)
        a.put("k", b"x")
        assert b.get("k")[0] == b"x"
        assert a.inner is b


class _CountingStore:
    """Forwarding wrapper that tallies ops — what the cache tests use
    to prove which calls did (not) reach the cold tier."""

    def __init__(self, inner):
        self.inner = inner
        self.calls = {"get": 0, "head": 0}

    def get(self, key):
        self.calls["get"] += 1
        return self.inner.get(key)

    def head(self, key):
        self.calls["head"] += 1
        return self.inner.head(key)


class TestCache:
    def test_miss_then_hit_then_freshness(self, tmp_path):
        remote = _CountingStore(FakeObjectStore())
        remote.inner.put("k", b"v1")
        cache = ReadThroughCache(str(tmp_path / "c"))
        assert cache.get_through(remote, "k") == (b"v1", token_of(b"v1"))
        assert remote.calls == {"get": 1, "head": 0}
        # hit: one freshness head, no get
        assert cache.get_through(remote, "k")[0] == b"v1"
        assert remote.calls == {"get": 1, "head": 1}
        # the object moved; the probe notices and refetches
        remote.inner.put("k", b"v2")
        assert cache.get_through(remote, "k")[0] == b"v2"
        assert remote.calls["get"] == 2

    def test_immutable_skips_the_probe(self, tmp_path):
        remote = _CountingStore(FakeObjectStore())
        remote.inner.put("t", b"tile")
        cache = ReadThroughCache(str(tmp_path / "c"))
        cache.get_through(remote, "t", immutable=True)
        cache.get_through(remote, "t", immutable=True)
        assert remote.calls == {"get": 1, "head": 0}

    def test_stale_but_verified_when_cold_tier_down(self, tmp_path):
        store = FakeObjectStore()
        store.put("k", b"warm")
        cache = ReadThroughCache(str(tmp_path / "c"))
        cache.get_through(store, "k")
        store.injector.set_offline(True)
        data, _tok = cache.get_through(store, "k")
        assert data == b"warm"
        assert cache.degraded()
        snap = cache.snapshot()
        assert snap["degraded"] and snap["stale_served"] == 1
        # a key never cached has nothing verified to serve
        with pytest.raises(StoreNetworkError):
            cache.get_through(store, "never-seen")
        store.injector.set_offline(False)
        cache.get_through(store, "k")
        assert not cache.degraded()

    def test_corrupt_entry_deleted_not_served(self, tmp_path):
        store = FakeObjectStore()
        store.put("k", b"good-bytes")
        cache = ReadThroughCache(str(tmp_path / "c"))
        cache.get_through(store, "k")
        # flip payload bits behind the cache's back
        (entry,) = [
            p for p in (tmp_path / "c").iterdir()
            if p.name.endswith(".obj")
        ]
        blob = bytearray(entry.read_bytes())
        blob[-1] ^= 0xFF
        entry.write_bytes(bytes(blob))
        store.injector.set_offline(True)
        with pytest.raises(StoreNetworkError):
            cache.get_through(store, "k")  # corrupt ≠ servable
        store.injector.set_offline(False)
        assert cache.get_through(store, "k")[0] == b"good-bytes"

    def test_lru_eviction_by_bytes(self, tmp_path):
        store = FakeObjectStore()
        for i in range(4):
            store.put(f"k{i}", bytes([i]) * 100)
        cache = ReadThroughCache(str(tmp_path / "c"), max_bytes=250)
        for i in range(4):
            cache.get_through(store, f"k{i}")
        snap = cache.snapshot()
        assert snap["entries"] == 2 and snap["bytes"] <= 250

    def test_invalidate_prefix(self, tmp_path):
        store = FakeObjectStore()
        for key in ("s/a", "s/b", "other/c"):
            store.put(key, b"x")
        cache = ReadThroughCache(str(tmp_path / "c"))
        for key in ("s/a", "s/b", "other/c"):
            cache.get_through(store, key)
        assert cache.invalidate_prefix("s") == 2
        assert cache.snapshot()["entries"] == 1

    def test_warm_restart_inherits_entries(self, tmp_path):
        store = FakeObjectStore()
        store.put("k", b"x")
        ReadThroughCache(str(tmp_path / "c")).get_through(store, "k")
        reborn = ReadThroughCache(str(tmp_path / "c"))
        assert reborn.snapshot()["entries"] == 1
        counting = _CountingStore(store)
        reborn.get_through(counting, "k", immutable=True)
        assert counting.calls == {"get": 0, "head": 0}


FS = 50.0
T0 = "2023-03-22T00:00:00"


@pytest.fixture(scope="module")
def pyramid_folder(tmp_path_factory):
    """A small real pyramid (one realtime run + sync) every tileplane
    test publishes from."""
    from tpudas.proc.streaming import run_lowpass_realtime

    src = str(tmp_path_factory.mktemp("tp_src") / "a")
    make_synthetic_spool(
        src, n_files=6, file_duration=20.0, fs=FS, n_ch=4,
        noise=0.01, start=np.datetime64(T0),
    )
    out = str(tmp_path_factory.mktemp("tp_out") / "out")
    run_lowpass_realtime(
        source=src, output_folder=out, start_time=T0,
        output_sample_interval=1.0, edge_buffer=5.0,
        process_patch_size=20, poll_interval=0.0,
        sleep_fn=lambda _s: None, pyramid=False,
    )
    sync_pyramid(out, tile_len=16)
    return out


def _remote(store, tmp_path, name):
    cache = ReadThroughCache(str(tmp_path / f"{name}-cache"))
    return RemotePyramid(
        store, "streams/a", cache, str(tmp_path / f"{name}-mirror"),
        min_refresh_s=0.0,
    )


class TestTilePlane:
    def test_publish_then_remote_read_byte_identical(
        self, pyramid_folder, tmp_path
    ):
        store = FakeObjectStore()
        pub = PyramidPublisher(store, "streams/a", pyramid_folder)
        first = pub.publish()
        assert first["tiles"] > 0 and first["manifest"]
        # steady state: nothing changed, nothing moves
        assert pub.publish() == {"tiles": 0, "manifest": False}

        local = TileStore.open(pyramid_folder)
        remote = _remote(store, tmp_path, "r1")
        for level in range(len(local.levels)):
            n = int(local.n(level))
            mine = remote.read(level, 0, n, "mean")
            theirs = local.read(level, 0, n, "mean")
            np.testing.assert_array_equal(mine, theirs)

    def test_restarted_publisher_reuploads_nothing(
        self, pyramid_folder, tmp_path
    ):
        store = FakeObjectStore()
        PyramidPublisher(store, "streams/a", pyramid_folder).publish()
        n_objects = len(store.snapshot_keys())
        reborn = PyramidPublisher(store, "streams/a", pyramid_folder)
        assert reborn.publish() == {"tiles": 0, "manifest": False}
        assert len(store.snapshot_keys()) == n_objects

    def test_restarted_publisher_catches_up_on_stale_token(
        self, pyramid_folder, tmp_path
    ):
        """A single stale token (our process restarted; the artifact
        is still single-writer) is NOT split-brain: the bounded
        re-read loop catches up and the publish lands."""
        import shutil

        work = str(tmp_path / "work")
        shutil.copytree(pyramid_folder, work)
        store = FakeObjectStore()
        PyramidPublisher(store, "streams/a", work).publish()
        manifest_key = f"streams/a/{MANIFEST_FILENAME}"
        pub = PyramidPublisher(store, "streams/a", work)
        pub._seed()
        # the object moves once behind our back (our own earlier
        # incarnation's write we never heard about) ...
        store.put(manifest_key, b'{"generation": 0, "old": true}')
        # ... and the local pyramid has moved on since
        local_manifest = os.path.join(pub.tiles_dir, MANIFEST_FILENAME)
        with open(local_manifest, "rb") as fh:
            moved_on = fh.read() + b"\n"
        with open(local_manifest, "wb") as fh:
            fh.write(moved_on)
        assert pub._publish_mutable() is True
        assert store.get(manifest_key)[0] == moved_on

    def test_second_writer_split_brain_surfaces_as_conflict(
        self, pyramid_folder, tmp_path
    ):
        """A rival that keeps moving the manifest (true split-brain:
        two live writers on one stream) must surface as
        CASConflictError, never be papered over."""

        class _RacingStore(FakeObjectStore):
            def _put_if(self, key, data, if_token, if_absent):
                if key.endswith(MANIFEST_FILENAME):
                    with self._lock:
                        prev = self._objects.get(key, b"{}")
                        self._objects[key] = prev + b" "
                return super()._put_if(key, data, if_token, if_absent)

        store = _RacingStore()
        pub = PyramidPublisher(store, "streams/a", pyramid_folder)
        with pytest.raises(CASConflictError):
            pub.publish()

    def test_cache_poisoning_after_generation_bump(
        self, pyramid_folder, tmp_path
    ):
        """Race-matrix leg: a rebuild re-encodes tiles under UNCHANGED
        names and CAS-bumps the manifest generation.  A reader holding
        pre-bump mirror/cache entries must drop them — serving the old
        bytes against the new manifest is the poisoning case."""
        import shutil

        work = str(tmp_path / "work")
        shutil.copytree(pyramid_folder, work)
        store = FakeObjectStore()
        pub = PyramidPublisher(store, "streams/a", work)
        pub.publish()
        remote = _remote(store, tmp_path, "r2")
        ts = remote.open()
        gen0 = remote._generation
        before = remote.read(0, 0, ts.tile_len, "mean")
        assert remote.cache.snapshot()["entries"] > 0

        # rebuild with a coarser pyramid: same tile names, new bytes
        rebuild_pyramid(work, factor=2, tile_len=16)
        pub2 = PyramidPublisher(store, "streams/a", work)
        pub2.publish()

        remote.refresh(force=True)
        assert remote._generation == gen0 + 1
        assert remote.cache.snapshot()["entries"] == 0  # flushed
        ts2 = remote.open()
        after = remote.read(0, 0, ts2.tile_len, "mean")
        np.testing.assert_array_equal(
            after, TileStore.open(work).read(0, 0, ts2.tile_len)
        )

    def test_remote_survives_outage_then_recovers(
        self, pyramid_folder, tmp_path
    ):
        store = FakeObjectStore()
        PyramidPublisher(store, "streams/a", pyramid_folder).publish()
        remote = _remote(store, tmp_path, "r3")
        ts = remote.open()
        warm = remote.read(0, 0, ts.tile_len, "mean")
        store.injector.set_offline(True)
        remote.refresh(force=True)
        assert remote.snapshot()["stale"] is True
        again = remote.read(0, 0, ts.tile_len, "mean")
        np.testing.assert_array_equal(again, warm)
        store.injector.set_offline(False)
        remote.refresh(force=True)
        assert remote.snapshot()["stale"] is False
