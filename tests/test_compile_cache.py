"""Persistent XLA compilation cache (tpudas.utils.compile_cache)."""

import glob
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp


@pytest.fixture
def restore_cache_config():
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    prev_size = jax.config.jax_persistent_cache_min_entry_size_bytes
    yield
    jax.config.update("jax_compilation_cache_dir", prev_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", prev_min
    )
    jax.config.update(
        "jax_persistent_cache_min_entry_size_bytes", prev_size
    )


def test_env_opt_in_populates_cache(
    tmp_path, monkeypatch, restore_cache_config
):
    import tpudas.utils.compile_cache as cc

    d = str(tmp_path / "cache")
    monkeypatch.setenv("TPUDAS_COMPILE_CACHE", d)
    monkeypatch.setattr(cc, "_ENABLED", False)
    assert cc.maybe_enable_from_env() == d
    assert os.path.isdir(d)
    # drop the entry thresholds so this tiny jit is cached
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    f = jax.jit(lambda x: jnp.sin(x) @ x.T)
    f(np.ones((32, 32), np.float32)).block_until_ready()
    assert len(glob.glob(os.path.join(d, "*"))) >= 1
    # idempotent second call reports the active dir
    assert cc.maybe_enable_from_env() == d


def test_disabled_without_env(monkeypatch, restore_cache_config):
    import tpudas.utils.compile_cache as cc

    monkeypatch.delenv("TPUDAS_COMPILE_CACHE", raising=False)
    monkeypatch.setattr(cc, "_ENABLED", False)
    assert cc.maybe_enable_from_env() is None
