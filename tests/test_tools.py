"""Tests for the campaign analysis tooling (tools/analyze_campaign.py).

The digest is what turns a scarce alive-window's logs into decisions
(winning geometry, Mosaic verdict, knob-vs-plain ranking), so its
parsing of the campaign2 formats — tagged sweep rows, conv rows, the
prefix relationship between tagged and untagged labels — is pinned
here.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SWEEP = """\
[05:00:00] sweep row: kb=128 cb=128 env=''
pallas f32 kb=128 cb=128              4.000 ms/win   50.00 G ch-samp/s  250.0 GB/s (30.5% peak)
pallas i16 kb=128 cb=128              3.000 ms/win   66.00 G ch-samp/s  200.0 GB/s (24.4% peak)
[05:05:00] sweep row: kb=512 cb=128 env='TPUDAS_PALLAS_GRID=ck'
pallas f32 kb=512 cb=128 [TPUDAS_PALLAS_GRID=ck]    3.500 ms/win   55.00 G ch-samp/s  275.0 GB/s (33.6% peak)
pallas f32 kb=512 cb=128              9.000 ms/win   20.00 G ch-samp/s  100.0 GB/s (12.2% peak)
conv-batch f32                        2.000 ms/win  100.00 G ch-samp/s  500.0 GB/s (61.1% peak)
conv-depthwise f32: error: grouped conv not supported
"""


def _digest(tmp_path, files):
    for name, content in files.items():
        (tmp_path / name).write_text(content)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "analyze_campaign.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    return proc.stdout


class TestAnalyzeCampaign:
    def test_tagged_rows_ranked_separately_from_plain(self, tmp_path):
        out = _digest(tmp_path, {"sweep.log": SWEEP})
        # plain best must be the untagged kb=128 row, NOT the tagged
        # kb=512 row (55 G) that numerically beats it
        assert "best f32: kb=128 cb=128 -> 50.00 G" in out
        assert "best i16: kb=128 cb=128 -> 66.00 G" in out
        assert ("best tagged f32: kb=512 cb=128 [TPUDAS_PALLAS_GRID=ck] "
                "-> 55.00 G") in out
        # a winning tagged row triggers the bake-the-knob note
        assert "beats every plain geometry" in out

    def test_conv_rows_reported(self, tmp_path):
        out = _digest(tmp_path, {"sweep.log": SWEEP})
        assert "conv-batch: 100.00 G ch-samp/s" in out
        # failed conv rows (no rate line) are simply absent
        assert "conv-depthwise:" not in out

    def test_bake_line_handles_single_stream(self, tmp_path):
        out = _digest(tmp_path, {"sweep.log": SWEEP})
        # kb=128 winner -> P=1 (not 0)
        assert "TPUDAS_PALLAS_P=1" in out
        assert "TPUDAS_PALLAS_CB=128" in out

    def test_chip_check_rates_surfaced(self, tmp_path):
        cc = (
            "backend=tpu\n"
            "stage0 pallas-vs-xla rel err: 5.16e-06 (OK)\n"
            "stage0 f32: 7.251 ms/win  37.04 G ch-samp/s  ~185 GB/s\n"
            "stage0 i16: 5.282 ms/win  50.85 G ch-samp/s\n"
            "chip_check done\n"
        )
        out = _digest(tmp_path, {"chip_check.log": cc})
        assert "v2 Mosaic verdict: ACCEPTED" in out
        assert "stage0 f32: 7.251 ms/win" in out

    def test_cpu_run_never_yields_mosaic_verdict(self, tmp_path):
        cc = (
            "backend=cpu\n"
            "stage0 pallas-vs-xla rel err: 0.00e+00 (OK)\n"
            "chip_check done (cpu: rate section skipped)\n"
        )
        out = _digest(tmp_path, {"chip_check.log": cc})
        assert "UNTESTED" in out


class TestKernelBench:
    @pytest.mark.slow
    def test_quick_bench_reports_fused_contract(self, tmp_path):
        """Tier-1 smoke of tools/kernel_bench.py (ISSUE 10): the
        --quick sweep runs all three engines at the smallest width,
        asserts the byte-identity/tolerance equivalence block, and
        counts fused rounds through the obs registry."""
        import tools.kernel_bench as kb

        out = str(tmp_path / "BENCH_quick.json")
        report = kb.run(out, quick=True)
        assert report["ok"]
        with open(out) as fh:
            on_disk = json.load(fh)
        assert on_disk["headline_source"] == "tpudas.obs.registry"
        point = report["sweep"][0]
        assert set(point["engines"]) == {
            "cascade", "fused-xla", "fused-pallas"
        }
        fx = point["engines"]["fused-xla"]
        assert fx["fused_rounds"] > 0  # registry witnessed the path
        assert fx["intermediate_bytes_saved_per_round"] > 0
        eq = report["acceptance"]["equivalence"]
        assert eq["fused_xla_output_byte_identical"]
        assert eq["fused_xla_carry_byte_identical"]
        assert (
            eq["fused_pallas_rel_err"]
            <= eq["fused_pallas_tolerance_pinned"]
        )
