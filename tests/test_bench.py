"""bench.py child-process logic, run in-process on the CPU backend
with tiny shapes: the JSON contract must stay parseable and honest
(requested-but-skipped compares recorded, engines map when budget
allows)."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _run_child(monkeypatch, capsys, **env):
    defaults = {
        "BENCH_CHILD": "1",
        "BENCH_T": "4096",
        "BENCH_C": "32",
        "BENCH_ITERS": "2",
        "BENCH_ENGINE": "cascade",
    }
    defaults.update(env)
    for k, v in defaults.items():
        monkeypatch.setenv(k, str(v))
    bench._child()
    lines = [
        ln for ln in capsys.readouterr().out.splitlines() if ln.startswith("{")
    ]
    assert lines, "child printed no JSON line"
    return json.loads(lines[-1])


class TestCompareVisibility:
    def test_budget_skipped_compare_is_recorded(self, monkeypatch, capsys):
        """BENCH_COMPARE=1 with no budget left must say so in the JSON,
        not silently omit the engines map (round-2 advisor finding)."""
        result = _run_child(
            monkeypatch, capsys, BENCH_COMPARE="1", BENCH_REMAINING="0"
        )
        assert "engines" not in result
        assert "budget" in result["engines_skipped"]

    def test_h2d_skipped_compare_is_recorded(self, monkeypatch, capsys):
        result = _run_child(
            monkeypatch,
            capsys,
            BENCH_COMPARE="1",
            BENCH_INCLUDE_H2D="1",
            BENCH_REMAINING="100000",
        )
        assert "engines" not in result
        assert "h2d" in result["engines_skipped"]

    def test_compare_runs_all_engines_when_budget_allows(
        self, monkeypatch, capsys
    ):
        result = _run_child(
            monkeypatch, capsys, BENCH_COMPARE="1", BENCH_REMAINING="100000"
        )
        engines = result["engines"]
        assert set(engines) == {"cascade-xla", "cascade-pallas", "fft"}
        for name, value in engines.items():
            assert isinstance(value, (int, float)), (name, value)
        assert "engines_skipped" not in result

    def test_stage_profile_breakdown(self, monkeypatch, capsys):
        result = _run_child(
            monkeypatch, capsys, BENCH_PROFILE="1", BENCH_T="30000",
            BENCH_C="16",
        )
        stages = result["stage_times_ms"]
        assert len(stages) == len(result["stages"])
        for eng, t_in, ms in stages:
            assert eng in ("pallas", "xla")
            assert isinstance(ms, float) and ms > 0, (eng, t_in, ms)
        # input sizes shrink monotonically through the cascade
        sizes = [t_in for _, t_in, _ in stages]
        assert sizes == sorted(sizes, reverse=True)

    def test_no_compare_no_keys(self, monkeypatch, capsys):
        result = _run_child(monkeypatch, capsys, BENCH_COMPARE="0")
        assert "engines" not in result
        assert "engines_skipped" not in result
        assert result["value"] > 0
        assert result["metric"] == "channel_samples_per_sec"

    def test_quantized_kernel_measured(self, monkeypatch, capsys):
        """BENCH_QUANT=1 records the raw-int16-payload kernel rate
        beside the f32 headline (the realistic interrogator payload)."""
        result = _run_child(
            monkeypatch, capsys, BENCH_QUANT="1", BENCH_REMAINING="100000"
        )
        sub = result["int16"]
        assert sub["value"] > 0
        assert sub["realtime_factor"] > 0
        assert "hbm_gbps" in sub

    def test_quantized_kernel_budget_skip_recorded(
        self, monkeypatch, capsys
    ):
        result = _run_child(
            monkeypatch, capsys, BENCH_QUANT="1", BENCH_COMPARE="0",
            BENCH_REMAINING="0",
        )
        assert "int16" not in result
        assert "budget" in result["int16_skipped"]

    @pytest.mark.slow
    def test_pallas_failure_falls_back_to_xla(self, monkeypatch, capsys):
        """A Mosaic/compile failure of the fast path must not cost the
        round's headline: the child re-measures on cascade-xla and
        records the error."""
        import tpudas.ops.fir as fir_mod
        import tpudas.ops.pallas_fir as pf_mod

        def boom(*a, **k):
            raise RuntimeError("mosaic compile failure (synthetic)")

        monkeypatch.delenv("TPUDAS_PALLAS_IMPL", raising=False)
        fir_mod._layout_for.cache_clear()
        fir_mod._clear_cascade_caches()
        monkeypatch.setattr(fir_mod, "_pallas_stage_ok", lambda *a: True)
        monkeypatch.setattr(pf_mod, "fir_decimate_pallas", boom)
        try:
            result = _run_child(
                monkeypatch, capsys, BENCH_PALLAS="1", BENCH_COMPARE="0",
                BENCH_QUANT="0",
            )
        finally:
            os.environ.pop("TPUDAS_PALLAS_IMPL", None)
            fir_mod._layout_for.cache_clear()
            fir_mod._clear_cascade_caches()
        assert result["value"] > 0
        assert result["engine"] == "cascade"
        assert "mosaic compile failure" in result["pallas_error"]

    def test_sweep_skipped_on_cpu_but_recorded(self, monkeypatch, capsys):
        """BENCH_SWEEP=1 on a CPU backend must not attempt the
        interpret-mode sweep (hours at these shapes) but the request
        must stay visible in the artifact."""
        result = _run_child(
            monkeypatch, capsys, BENCH_SWEEP="1", BENCH_COMPARE="0",
            BENCH_QUANT="0",
        )
        assert result["sweep"] == {"skipped": "cpu"}

    def test_clean_pallas_run_reports_impl_v2(self, monkeypatch, capsys):
        """A clean Pallas headline carries the explicit implementation
        verdict (pallas_impl: v2, no pallas_error) — VERDICT r4 item 1
        wants the verdict readable from the artifact alone."""
        import tpudas.ops.fir as fir_mod

        monkeypatch.delenv("TPUDAS_PALLAS_IMPL", raising=False)
        fir_mod._layout_for.cache_clear()
        fir_mod._clear_cascade_caches()
        monkeypatch.setattr(
            fir_mod, "_pallas_stage_ok",
            lambda k, R, n_ch, B: k >= 3000 and B <= 128,
        )
        try:
            result = _run_child(
                monkeypatch, capsys, BENCH_PALLAS="1", BENCH_COMPARE="0",
                BENCH_QUANT="0",
            )
        finally:
            fir_mod._layout_for.cache_clear()
            fir_mod._clear_cascade_caches()
        assert result["value"] > 0
        assert result["pallas_impl"] == "v2"
        assert "pallas_error" not in result

    def test_pallas_v2_failure_lands_on_v1(self, monkeypatch, capsys):
        """When only the v2 kernel body fails, the bench headline runs
        on the v1 Pallas implementation, not the XLA downgrade."""
        import tpudas.ops.fir as fir_mod
        import tpudas.ops.pallas_fir as pf_mod

        def boom(*a, **k):
            raise RuntimeError("v2 body rejected (synthetic)")

        monkeypatch.delenv("TPUDAS_PALLAS_IMPL", raising=False)
        fir_mod._layout_for.cache_clear()
        fir_mod._clear_cascade_caches()
        # admit only the full-rate stage: forcing EVERY stage onto
        # Pallas makes the 512-frame grid rounding inflate the chain
        # by orders of magnitude at this tiny T, and interpret mode
        # walks those grid cells in Python
        monkeypatch.setattr(
            fir_mod, "_pallas_stage_ok",
            lambda k, R, n_ch, B: k >= 3000 and B <= 128,
        )
        monkeypatch.setattr(pf_mod, "_kernel_body", boom)
        try:
            result = _run_child(
                monkeypatch, capsys, BENCH_PALLAS="1", BENCH_COMPARE="0",
                BENCH_QUANT="0", BENCH_REMAINING="100000",
            )
        finally:
            os.environ.pop("TPUDAS_PALLAS_IMPL", None)
            fir_mod._layout_for.cache_clear()
            fir_mod._clear_cascade_caches()
        assert result["value"] > 0
        assert result["engine"] == "cascade-pallas"
        assert result["pallas_impl"] == "v1"
        assert "v2 body rejected" in result["pallas_error"]


class TestE2EChild:
    def test_int16_payload_e2e(self, monkeypatch, capsys):
        """BENCH_E2E_DTYPE=int16 runs the quantized product path:
        raw native assembly + device decode, recorded in the JSON."""
        result = _run_child(
            monkeypatch,
            capsys,
            BENCH_MODE="e2e",
            BENCH_E2E_DTYPE="int16",
            BENCH_E2E_SEC="30",
            BENCH_C="16",
            BENCH_E2E_FS="200",
        )
        assert result["mode"] == "e2e"
        assert result["payload"] == "int16"
        assert result["native_windows"] >= 1
        assert result["realtime_factor"] > 0


class TestParentFlow:
    def test_kernel_line_carries_e2e_subobject(self):
        """One `python bench.py` run records BOTH the resident-kernel
        number and the full product-path (e2e) real-time factor
        (VERDICT r3 #5). Runs the real parent in a clean CPU env
        (hosting sitecustomize stripped, so no tunnel dependence)."""
        import subprocess

        import __graft_entry__ as g

        env = g._clean_cpu_env(1)
        env.update(
            BENCH_T="16384",
            BENCH_C="32",
            BENCH_ITERS="2",
            BENCH_E2E_SEC="30",
            BENCH_BUDGET="240",
            BENCH_E2E_TIMEOUT="120",
        )
        proc = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(bench.__file__),
                                          "bench.py")],
            env=env,
            capture_output=True,
            text=True,
            timeout=280,
        )
        lines = [
            ln for ln in proc.stdout.splitlines() if ln.startswith("{")
        ]
        assert proc.returncode == 0 and lines, proc.stderr[-500:]
        result = json.loads(lines[-1])
        assert result["value"] > 0
        assert result["stages"]  # layout ground truth present
        e2e = result["e2e"]
        assert "skipped" not in e2e and "error" not in e2e, (
            f"e2e child did not run: {e2e}"
        )
        assert e2e["mode"] == "e2e"
        assert e2e["realtime_factor"] > 0
        assert e2e["native_windows"] >= 1
        assert sum(e2e["engine_counts"].values()) >= 1


class TestMeshBench:
    def test_sharded_kernel_step(self, monkeypatch, capsys):
        """BENCH_MESH runs the cascade over a (time, ch) mesh — the
        sharded product step is benchable (VERDICT r3 #2)."""
        result = _run_child(
            monkeypatch,
            capsys,
            BENCH_MESH="8",
            BENCH_TIME_SHARDS="2",
            BENCH_T="66000",  # n_loc=33 -> halo (~27k rows) < t_local
            BENCH_C="32",
        )
        assert result["mesh"] == {"time": 2, "ch": 4}
        assert result["value"] > 0

    def test_channel_only_mesh(self, monkeypatch, capsys):
        result = _run_child(
            monkeypatch, capsys, BENCH_MESH="8", BENCH_T="8000", BENCH_C="16"
        )
        assert result["mesh"] == {"time": 1, "ch": 8}
        assert result["value"] > 0

    def test_channel_only_mesh_pads_uneven_c(self, monkeypatch, capsys):
        # C=12 on an 8-way ch axis: the pad-and-trim wrapper must fire
        result = _run_child(
            monkeypatch, capsys, BENCH_MESH="8", BENCH_T="8000", BENCH_C="12"
        )
        assert result["mesh"] == {"time": 1, "ch": 8}
        assert result["value"] > 0

    def test_non_cascade_engine_reports_no_mesh(self, monkeypatch, capsys):
        result = _run_child(
            monkeypatch, capsys, BENCH_MESH="8", BENCH_ENGINE="fft",
            BENCH_T="8000", BENCH_C="16",
        )
        assert "mesh" not in result  # it did not run sharded
