"""Multistage FIR cascade engine (tpudas.ops.fir / pallas_fir):
design-response match to the reference's Butterworth-squared filter,
XLA/Pallas agreement, and LFProc engine equivalence (SURVEY.md §4:
filter kernel vs golden outputs, tolerance-based)."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from tpudas.ops.filter import fft_pass_filter
from tpudas.ops.fir import (
    butter2_mag,
    cascade_decimate,
    design_cascade,
    edge_support_samples,
    factor_ratio,
    impulse_response,
)

FS = 1000.0
CORNER = 0.45


class TestDesign:
    def test_factor_ratio(self):
        assert factor_ratio(1000) == [8, 5, 5, 5]
        assert factor_ratio(100) == [5, 5, 4]
        assert factor_ratio(10) == [5, 2]
        assert factor_ratio(8) == [8]
        assert factor_ratio(1) == []

    def test_factor_ratio_large_prime_rejected(self):
        with pytest.raises(ValueError, match="prime factor"):
            factor_ratio(13)

    @pytest.mark.parametrize(
        "fs,ratio,corner",
        [(1000.0, 1000, 0.45), (100.0, 100, 0.45), (100.0, 10, 4.5)],
    )
    def test_composite_response_matches_butter2(self, fs, ratio, corner):
        """|H_cascade(f)| == butter2_mag(f) on the retained band to
        ~1e-4 — the engine-parity contract with tpudas.ops.filter."""
        plan = design_cascade(fs, ratio, corner, 4)
        h = impulse_response(plan)
        nfft = 1 << 18
        H = np.abs(np.fft.rfft(h, nfft))
        freqs = np.arange(nfft // 2 + 1) / nfft * fs
        band = freqs <= 0.5 * fs / ratio
        err = np.abs(H[band] - butter2_mag(freqs[band], corner, 4))
        assert err.max() < 1e-4

    def test_delay_is_symmetry_center(self):
        plan = design_cascade(FS, 1000, CORNER, 4)
        h = impulse_response(plan)
        # linear phase: response symmetric about the composite delay
        d = plan.delay
        w = min(d, len(h) - 1 - d)
        left = h[d - w : d]
        right = h[d + 1 : d + 1 + w][::-1]
        assert np.abs(left - right).max() < 1e-12
        assert plan.receptive_field == 2 * d + 1

    def test_edge_support_shrinks_with_looser_tol(self):
        plan = design_cascade(FS, 1000, CORNER, 4)
        assert edge_support_samples(plan, 1e-2) <= edge_support_samples(
            plan, 1e-4
        )
        # support is inside the receptive field
        assert edge_support_samples(plan, 1e-3) <= plan.delay


def _bandlimited(T, C, fs, seed=0):
    rng = np.random.default_rng(seed)
    t = np.arange(T) / fs
    x = np.zeros((T, C), np.float32)
    for c in range(C):
        for f, a in [(0.05, 1.0), (0.21, 0.7), (0.38, 0.4)]:
            x[:, c] += a * np.sin(
                2 * np.pi * f * t + rng.uniform(0, 2 * np.pi)
            ).astype(np.float32)
    x += rng.standard_normal((T, C)).astype(np.float32) * 0.1
    return x


class TestApply:
    def test_matches_fft_engine_interior(self):
        """Cascade output == FFT-engine zero-phase filter at the
        decimated sample points, away from edges."""
        ratio, T, C = 1000, 40000, 4
        plan = design_cascade(FS, ratio, CORNER, 4)
        x = _bandlimited(T, C, FS)
        ref_full = np.asarray(
            fft_pass_filter(jnp.asarray(x), 1.0 / FS, high=CORNER, order=4)
        )
        phase, n_out = 14000, 12
        ref = ref_full[phase : phase + n_out * ratio : ratio]
        got = np.asarray(cascade_decimate(x, plan, phase, n_out, engine="xla"))
        scale = np.abs(ref).max()
        assert np.abs(got - ref).max() < 1e-4 * scale

    def test_phase_shift_consistency(self):
        """Outputs at the same absolute sample index agree regardless of
        the window phase — the property that makes chunked processing
        seam-free."""
        ratio = 100
        plan = design_cascade(100.0, ratio, CORNER, 4)
        x = _bandlimited(8000, 3, 100.0, seed=1)
        a = np.asarray(cascade_decimate(x, plan, 3000, 10, engine="xla"))
        b = np.asarray(cascade_decimate(x, plan, 3000 + 2 * ratio, 8, engine="xla"))
        assert np.abs(a[2:] - b[:8]).max() < 1e-6

    @pytest.mark.slow
    def test_pallas_interpret_matches_xla(self):
        ratio = 100
        plan = design_cascade(100.0, ratio, CORNER, 4)
        x = _bandlimited(30000, 130, 100.0, seed=2)  # non-multiple C
        a = np.asarray(cascade_decimate(x, plan, 6000, 16, engine="xla"))
        b = np.asarray(cascade_decimate(x, plan, 6000, 16, engine="pallas"))
        assert np.abs(a - b).max() < 1e-6

    def test_left_pad_when_phase_before_delay(self):
        plan = design_cascade(100.0, 100, CORNER, 4)
        x = _bandlimited(4000, 2, 100.0, seed=3)
        out = np.asarray(cascade_decimate(x, plan, 0, 4, engine="xla"))
        assert out.shape == (4, 2)
        assert np.isfinite(out).all()


class TestPallasKernel:
    def test_strided_fir_exact(self):
        """Kernel output == direct numpy correlation at stride R."""
        from tpudas.ops.pallas_fir import fir_decimate_pallas

        rng = np.random.default_rng(0)
        T, C, R, L = 2048, 140, 8, 33
        x = rng.standard_normal((T, C)).astype(np.float32)
        h = rng.standard_normal(L).astype(np.float32)
        B = -(-L // R)
        hp = np.zeros(B * R, np.float32)
        hp[:L] = h
        n_out = T // R - B
        got = np.asarray(
            fir_decimate_pallas(
                jnp.asarray(x),
                jnp.asarray(hp.reshape(B, R)),
                R,
                n_out=n_out,
                interpret=True,
            )
        )
        ref = np.zeros((n_out, C), np.float32)
        for k in range(n_out):
            seg = x[k * R : k * R + L]
            ref[k] = (h[:, None] * seg).sum(0)
        assert np.abs(got - ref).max() < 1e-4 * np.abs(ref).max()

    def test_too_many_taps_rejected(self):
        from tpudas.ops.pallas_fir import fir_decimate_pallas

        x = jnp.zeros((4096, 128), jnp.float32)
        hb = jnp.zeros((200, 2), jnp.float32)  # 200 frames > 128 block
        with pytest.raises(ValueError, match="tap frames"):
            fir_decimate_pallas(x, hb, 2, n_out=64, interpret=True)

    @pytest.mark.parametrize(
        "env",
        [
            {"TPUDAS_PALLAS_GRID": "ck"},
            {"TPUDAS_PALLAS_DIMSEM": "parallel"},
            {"TPUDAS_PALLAS_DIMSEM": "arbitrary,parallel"},
            {
                "TPUDAS_PALLAS_GRID": "ck",
                "TPUDAS_PALLAS_DIMSEM": "arbitrary,arbitrary",
                "TPUDAS_PALLAS_VMEM_MB": "12",
            },
        ],
    )
    @pytest.mark.slow
    def test_mosaic_knob_variants_bit_equal(self, monkeypatch, env):
        """The Mosaic experiment knobs (grid order, dimension
        semantics, VMEM cap — swept on chip by chip_campaign2 step 5)
        must not change kernel OUTPUT, only its schedule: every
        variant is bit-equal to the default lowering."""
        from tpudas.ops.pallas_fir import (
            fir_decimate_pallas,
            stage_input_rows,
        )

        rng = np.random.default_rng(3)
        R, L, n_out = 8, 43, 512
        B = -(-L // R)
        hp = np.zeros(B * R, np.float32)
        hp[:L] = rng.standard_normal(L).astype(np.float32)
        hb = jnp.asarray(hp.reshape(B, R))
        T = stage_input_rows(B, R, n_out, 512)
        x = rng.standard_normal((T, 130)).astype(np.float32)
        base = np.asarray(
            fir_decimate_pallas(
                jnp.asarray(x), hb, R, n_out, interpret=True,
                kb=512, cb=128,
            )
        )
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        got = np.asarray(
            fir_decimate_pallas(
                jnp.asarray(x), hb, R, n_out, interpret=True,
                kb=512, cb=128,
            )
        )
        np.testing.assert_array_equal(got, base)

    def test_stage_ok_min_elems_env_override(self, monkeypatch):
        """TPUDAS_PALLAS_MIN_ELEMS applies a measured crossover
        without a code edit (tools/retune_stage_ok.py's output)."""
        from tpudas.ops.fir import _pallas_stage_ok
        from tpudas.ops.pallas_fir import kernel_quantum

        # k*R*n_ch = 2**19: below 2**24
        k, R, n_ch, B = kernel_quantum(), 8, 128, 6
        monkeypatch.delenv("TPUDAS_PALLAS_MIN_ELEMS", raising=False)
        assert not _pallas_stage_ok(k, R, n_ch, B)
        monkeypatch.setenv("TPUDAS_PALLAS_MIN_ELEMS", str(1 << 19))
        assert _pallas_stage_ok(k, R, n_ch, B)
        monkeypatch.setenv("TPUDAS_PALLAS_MIN_ELEMS", str(1 << 20))
        assert not _pallas_stage_ok(k, R, n_ch, B)

    def test_mosaic_knob_validation(self, monkeypatch):
        from tpudas.ops.pallas_fir import _mosaic_knobs

        monkeypatch.setenv("TPUDAS_PALLAS_GRID", "zz")
        with pytest.raises(ValueError, match="TPUDAS_PALLAS_GRID"):
            _mosaic_knobs()
        monkeypatch.setenv("TPUDAS_PALLAS_GRID", "kc")
        monkeypatch.setenv("TPUDAS_PALLAS_DIMSEM", "bogus")
        with pytest.raises(ValueError, match="TPUDAS_PALLAS_DIMSEM"):
            _mosaic_knobs()

    def test_env_geometry_knob_validation(self, monkeypatch):
        """TPUDAS_PALLAS_P/CB: empty means default; bad values fail
        fast naming the variable (not mid-run at a lazy import)."""
        from tpudas.ops.pallas_fir import _env_geom

        monkeypatch.delenv("TPUDAS_TEST_GEOM", raising=False)
        assert _env_geom("TPUDAS_TEST_GEOM", 4) == 4
        monkeypatch.setenv("TPUDAS_TEST_GEOM", "  ")
        assert _env_geom("TPUDAS_TEST_GEOM", 4) == 4
        monkeypatch.setenv("TPUDAS_TEST_GEOM", "8")
        assert _env_geom("TPUDAS_TEST_GEOM", 4) == 8
        monkeypatch.setenv("TPUDAS_TEST_GEOM", "abc")
        with pytest.raises(ValueError, match="TPUDAS_TEST_GEOM"):
            _env_geom("TPUDAS_TEST_GEOM", 4)
        monkeypatch.setenv("TPUDAS_TEST_GEOM", "0")
        with pytest.raises(ValueError, match="positive"):
            _env_geom("TPUDAS_TEST_GEOM", 4)
        monkeypatch.setenv("TPUDAS_TEST_GEOM", "100")
        with pytest.raises(ValueError, match="multiple"):
            _env_geom("TPUDAS_TEST_GEOM", 128, multiple_of=128)

    def test_3x_split_dot_accuracy(self):
        """The TPU kernel's 3-pass bf16 matmul emulation (interpret
        mode runs exact f32 instead, so this exercises the split
        arithmetic directly): ~1e-5 absolute on unit-scale data, well
        inside the cascade's 1e-4 design tolerance."""
        from tpudas.ops.pallas_fir import _dot_3x

        rng = np.random.default_rng(0)
        a = jnp.asarray(rng.standard_normal((64, 256)).astype(np.float32))
        x = jnp.asarray(rng.standard_normal((256, 128)).astype(np.float32))
        exact = np.asarray(a) @ np.asarray(x)
        got = np.asarray(_dot_3x(a, x))
        scale = np.abs(exact).max()
        assert np.abs(got - exact).max() < 1e-4 * scale

    @pytest.mark.slow
    def test_v1_impl_matches_v2(self, monkeypatch):
        """TPUDAS_PALLAS_IMPL=v1 (the proven-on-hardware VPU kernel)
        agrees with the default v2 MXU kernel in interpret mode."""
        from tpudas.ops.fir import _block_taps
        from tpudas.ops.pallas_fir import fir_decimate_pallas

        rng = np.random.default_rng(4)
        T, C, R, L = 6000, 70, 4, 19
        x = rng.standard_normal((T, C)).astype(np.float32)
        hb = _block_taps(rng.standard_normal(L).astype(np.float32), R)
        monkeypatch.delenv("TPUDAS_PALLAS_IMPL", raising=False)
        v2 = np.asarray(
            fir_decimate_pallas(jnp.asarray(x), hb, R, 600, interpret=True)
        )
        monkeypatch.setenv("TPUDAS_PALLAS_IMPL", "v1")
        v1 = np.asarray(
            fir_decimate_pallas(jnp.asarray(x), hb, R, 600, interpret=True)
        )
        scale = np.abs(v2).max()
        assert np.abs(v1 - v2).max() < 1e-5 * scale
        # int16 input path exists on both
        q = rng.integers(-3000, 3000, size=(T, C)).astype(np.int16)
        v1q = np.asarray(
            fir_decimate_pallas(jnp.asarray(q), hb, R, 600, interpret=True)
        )
        assert np.isfinite(v1q).all()

    def test_multi_stream_grid_quantum(self):
        """n_out that is not a multiple of the 512-frame grid quantum
        still yields exact results (pad + trim path)."""
        from tpudas.ops.fir import _block_taps
        from tpudas.ops.pallas_fir import fir_decimate_pallas

        rng = np.random.default_rng(1)
        T, C, R, L = 6000, 64, 4, 19
        x = rng.standard_normal((T, C)).astype(np.float32)
        h = rng.standard_normal(L).astype(np.float32)
        hb = _block_taps(h, R)
        n_out = 700  # crosses one 512-frame step, not a multiple
        got = np.asarray(
            fir_decimate_pallas(
                jnp.asarray(x), hb, R, n_out=n_out, interpret=True
            )
        )
        ref = np.zeros((n_out, C), np.float32)
        for k in range(n_out):
            seg = np.zeros((L, C), np.float32)
            avail = x[k * R : k * R + L]
            seg[: len(avail)] = avail
            ref[k] = (h[:, None] * seg).sum(0)
        assert np.abs(got - ref).max() < 1e-4 * np.abs(ref).max()


class TestQuantizedIngest:
    """int16 windows flow through the cascade undecoded: the first
    kernel dequantizes (Pallas: scale folded into the tap matrix;
    XLA: fused cast*scale) — tpudas.io.tdas raw ingest fast path."""

    def _quantized(self, T, C, seed=0, scale=1e-3):
        rng = np.random.default_rng(seed)
        q = rng.integers(-3000, 3000, size=(T, C)).astype(np.int16)
        return q, np.float32(scale)

    def test_pallas_kernel_raw_int16_matches_decoded(self):
        """The kernel filters the raw int16 payload (bare cast in
        VMEM); the caller scales the decimated output — linearity."""
        from tpudas.ops.fir import _block_taps
        from tpudas.ops.pallas_fir import fir_decimate_pallas

        rng = np.random.default_rng(2)
        T, C, R, L = 6000, 64, 4, 19
        q, s = self._quantized(T, C)
        h = rng.standard_normal(L).astype(np.float32)
        hb = _block_taps(h, R)
        dec = (q.astype(np.float32) * s).astype(np.float32)
        ref = np.asarray(
            fir_decimate_pallas(
                jnp.asarray(dec), hb, R, n_out=512, interpret=True
            )
        )
        got = s * np.asarray(
            fir_decimate_pallas(
                jnp.asarray(q), hb, R, n_out=512, interpret=True
            )
        )
        scale_ref = np.abs(ref).max()
        assert np.abs(got - ref).max() < 1e-6 * scale_ref

    def test_cascade_qscale_single_compile_across_scales(self):
        """Different quantization scales must NOT trigger distinct
        cascade compiles: the scale is a traced operand."""
        from tpudas.ops.fir import _build_cascade_fn

        plan = design_cascade(100.0, 20, CORNER, 4)
        q, _ = self._quantized(8000, 10, seed=4)
        _build_cascade_fn.cache_clear()
        for s in (1e-3, 2e-3, 5e-4):
            cascade_decimate(
                jnp.asarray(q), plan, 300, 200, "xla", qscale=s
            )
        info = _build_cascade_fn.cache_info()
        assert info.misses == 1, info

    def test_cascade_qscale_bitwise_matches_decoded(self):
        """On the XLA path the fused cast*scale is the same sequence of
        float ops as decode-then-cascade: results are bit-identical."""
        plan = design_cascade(100.0, 20, CORNER, 4)
        q, s = self._quantized(8000, 10, seed=3)
        dec = q.astype(np.float32) * s
        ref = np.asarray(cascade_decimate(dec, plan, 300, 200, "xla"))
        got = np.asarray(
            cascade_decimate(
                jnp.asarray(q), plan, 300, 200, "xla", qscale=float(s)
            )
        )
        assert np.array_equal(got, ref)

    def test_cascade_qscale_dtype_validation(self):
        plan = design_cascade(100.0, 20, CORNER, 4)
        with pytest.raises(ValueError, match="dtype"):
            cascade_decimate(
                np.zeros((4000, 4), np.float32), plan, 10, 8, "xla",
                qscale=0.5,
            )


class TestStageEngines:
    def test_decision_matches_build_predicate(self):
        from tpudas.ops.fir import design_cascade, stage_engines

        plan = design_cascade(1000.0, 1000, 0.45, 4)
        # big shapes: the full-rate stages qualify for the Pallas kernel
        eng = stage_engines(plan, 128, 2048, engine="pallas")
        assert eng[0] == "pallas", eng
        # tiny shapes never do; forced-xla never does
        assert set(stage_engines(plan, 4, 8, engine="pallas")) == {"xla"}
        assert set(stage_engines(plan, 128, 2048, engine="xla")) == {"xla"}
        # 'auto' resolves by backend: CPU under the test conftest
        assert set(stage_engines(plan, 128, 2048)) == {"xla"}

    @pytest.mark.slow
    def test_lfproc_engine_counts_ground_truth(self, tmp_path):
        """LFProc.engine_counts reports what actually ran, without the
        log handler — config 'auto' on CPU runs cascade-xla windows."""
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool

        d = tmp_path / "raw"
        make_synthetic_spool(
            d, n_files=6, file_duration=30.0, fs=100.0, n_ch=6, noise=0.01
        )
        for engine, expect_key in (("auto", "cascade-xla"), ("fft", "fft")):
            lfp = LFProc(spool(str(d)).sort("time").update())
            lfp.update_processing_parameter(
                output_sample_interval=1.0,
                process_patch_size=60,
                edge_buff_size=10,
                engine=engine,
            )
            out = tmp_path / f"counts_{engine}"
            lfp.set_output_folder(str(out), delete_existing=True)
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:03:00"),
            )
            assert lfp.engine_counts[expect_key] == 4, lfp.engine_counts
            assert sum(lfp.engine_counts.values()) == 4


class TestPallasFallback:
    @pytest.mark.slow
    def test_lfproc_catches_silently_wrong_pallas_numbers(
        self, tmp_path, monkeypatch, capsys
    ):
        """A Mosaic miscompile that RETURNS (no exception) wrong
        numbers is caught by the first-window cross-check against the
        XLA formulation and handled exactly like a compile failure:
        the run completes on the XLA cascade with correct output."""
        import tpudas.ops.fir as fir_mod
        import tpudas.ops.pallas_fir as pf_mod
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool
        from tpudas.utils.logging import set_log_handler

        d = tmp_path / "raw"
        make_synthetic_spool(
            d, n_files=4, file_duration=30.0, fs=100.0, n_ch=6, noise=0.01
        )

        real = pf_mod.fir_decimate_pallas

        def corrupt(x, hb, R, n_out, **kw):
            # silently wrong: scaled output, nothing raised (covers
            # both impls, so the v1 retry is caught by the same check)
            return real(x, hb, R, n_out=n_out, **kw) * 1.7

        monkeypatch.delenv("TPUDAS_PALLAS_IMPL", raising=False)
        fir_mod._layout_for.cache_clear()
        fir_mod._clear_cascade_caches()
        monkeypatch.setattr(
            fir_mod, "resolve_cascade_engine",
            lambda e="auto": "pallas" if e == "auto" else e,
        )
        monkeypatch.setattr(fir_mod, "_pallas_stage_ok", lambda *a: True)
        monkeypatch.setattr(pf_mod, "fir_decimate_pallas", corrupt)
        events = []
        set_log_handler(events.append)
        try:
            lfp = LFProc(spool(str(d)).sort("time").update())
            lfp.update_processing_parameter(
                output_sample_interval=1.0,
                process_patch_size=60,
                edge_buff_size=10,
            )
            out = tmp_path / "out"
            lfp.set_output_folder(str(out), delete_existing=True)
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:02:00"),
            )
        finally:
            os.environ.pop("TPUDAS_PALLAS_IMPL", None)
            set_log_handler(None)
            fir_mod._layout_for.cache_clear()
            fir_mod._clear_cascade_caches()
        assert not lfp._pallas_ok
        assert lfp.engine_counts["cascade-pallas"] == 0
        assert lfp.engine_counts["cascade-xla"] == sum(
            lfp.engine_counts.values()
        )
        falls = [e for e in events if e["event"] == "pallas_fallback"]
        assert len(falls) == 1
        assert "pallas-vs-xla rel err" in falls[0]["error"]
        # and the emitted output is the CORRECT numbers: re-run on a
        # clean processor (no corruption monkeypatch active on its
        # windows' engine choice would matter — it lands on XLA the
        # same way) and require byte-identical files
        lfp2 = LFProc(spool(str(d)).sort("time").update())
        lfp2.update_processing_parameter(
            output_sample_interval=1.0,
            process_patch_size=60,
            edge_buff_size=10,
        )
        out2 = tmp_path / "out2"
        lfp2.set_output_folder(str(out2), delete_existing=True)
        lfp2.process_time_range(
            np.datetime64("2023-03-22T00:00:00"),
            np.datetime64("2023-03-22T00:02:00"),
        )
        import filecmp

        files = sorted(p.name for p in out.iterdir())
        assert files == sorted(p.name for p in out2.iterdir())
        for name in files:
            assert filecmp.cmp(out / name, out2 / name, shallow=False)

    def test_lfproc_survives_pallas_compile_failure(
        self, tmp_path, monkeypatch, capsys
    ):
        """A Mosaic/compile failure of the Pallas fast path must not
        kill the run: LFProc permanently falls back to the XLA cascade
        (same numerics) and records the ground truth."""
        import tpudas.ops.fir as fir_mod
        import tpudas.ops.pallas_fir as pf_mod
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool

        d = tmp_path / "raw"
        make_synthetic_spool(
            d, n_files=4, file_duration=30.0, fs=100.0, n_ch=6, noise=0.01
        )

        def boom(*a, **k):
            raise RuntimeError("mosaic compile failure (synthetic)")

        monkeypatch.delenv("TPUDAS_PALLAS_IMPL", raising=False)
        fir_mod._layout_for.cache_clear()
        fir_mod._clear_cascade_caches()
        monkeypatch.setattr(
            fir_mod, "resolve_cascade_engine",
            lambda e="auto": "pallas" if e == "auto" else e,
        )
        monkeypatch.setattr(
            fir_mod, "_pallas_stage_ok", lambda *a: True
        )
        monkeypatch.setattr(pf_mod, "fir_decimate_pallas", boom)
        try:
            lfp = LFProc(spool(str(d)).sort("time").update())
            lfp.update_processing_parameter(
                output_sample_interval=1.0,
                process_patch_size=60,
                edge_buff_size=10,
            )
            out = tmp_path / "out"
            lfp.set_output_folder(str(out), delete_existing=True)
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:02:00"),
            )
        finally:
            os.environ.pop("TPUDAS_PALLAS_IMPL", None)
            fir_mod._layout_for.cache_clear()
            fir_mod._clear_cascade_caches()
        assert not lfp._pallas_ok
        assert lfp.engine_counts["cascade-pallas"] == 0
        assert lfp.engine_counts["cascade-xla"] == sum(
            lfp.engine_counts.values()
        )
        assert len(list(out.iterdir())) > 0
        assert "falling back to the XLA" in capsys.readouterr().out


    @pytest.mark.slow
    def test_lfproc_falls_back_to_v1_impl(self, tmp_path, monkeypatch,
                                          capsys):
        """When only the v2 kernel body fails, the engine continues on
        the v1 implementation — still Pallas, no XLA downgrade."""
        import tpudas.ops.fir as fir_mod
        import tpudas.ops.pallas_fir as pf_mod
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool
        from tpudas.utils.logging import set_log_handler

        d = tmp_path / "raw"
        make_synthetic_spool(
            d, n_files=4, file_duration=30.0, fs=100.0, n_ch=6, noise=0.01
        )

        def boom(*a, **k):
            raise RuntimeError("v2 body rejected (synthetic)")

        monkeypatch.delenv("TPUDAS_PALLAS_IMPL", raising=False)
        fir_mod._layout_for.cache_clear()
        fir_mod._clear_cascade_caches()
        monkeypatch.setattr(
            fir_mod, "resolve_cascade_engine",
            lambda e="auto": "pallas" if e == "auto" else e,
        )
        monkeypatch.setattr(fir_mod, "_pallas_stage_ok", lambda *a: True)
        monkeypatch.setattr(pf_mod, "_kernel_body", boom)
        events = []
        set_log_handler(events.append)
        try:
            lfp = LFProc(spool(str(d)).sort("time").update())
            lfp.update_processing_parameter(
                output_sample_interval=1.0,
                process_patch_size=60,
                edge_buff_size=10,
            )
            out = tmp_path / "out"
            lfp.set_output_folder(str(out), delete_existing=True)
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:02:00"),
            )
        finally:
            os.environ.pop("TPUDAS_PALLAS_IMPL", None)
            set_log_handler(None)
            fir_mod._layout_for.cache_clear()
            fir_mod._clear_cascade_caches()
        assert lfp._pallas_ok  # never downgraded to XLA
        assert lfp.engine_counts["cascade-xla"] == 0
        assert lfp.engine_counts["cascade-pallas"] == sum(
            lfp.engine_counts.values()
        )
        impls = [e for e in events if e["event"] == "pallas_impl_fallback"]
        assert len(impls) == 1 and impls[0]["impl"] == "v1"
        assert "continuing on the v1" in capsys.readouterr().out


class TestLFProcEngines:
    def test_cascade_equals_fft_engine(self, tmp_path):
        """Full chunked runs with engine='fft' vs engine='cascade' agree
        on the interior — engine choice is an implementation detail."""
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool

        d = tmp_path / "raw"
        make_synthetic_spool(
            d, n_files=6, file_duration=30.0, fs=100.0, n_ch=6, noise=0.01
        )
        outs = {}
        for engine in ("fft", "cascade"):
            lfp = LFProc(spool(str(d)).sort("time").update())
            lfp.update_processing_parameter(
                output_sample_interval=1.0,
                process_patch_size=60,
                edge_buff_size=10,
                engine=engine,
            )
            out_dir = tmp_path / engine
            lfp.set_output_folder(str(out_dir), delete_existing=True)
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:03:00"),
            )
            outs[engine] = spool(str(out_dir)).update().chunk(time=None)[0]
        a, b = outs["fft"], outs["cascade"]
        lo = max(a.coords["time"][0], b.coords["time"][0])
        hi = min(a.coords["time"][-1], b.coords["time"][-1])
        da = a.select(time=(lo, hi)).host_data()
        db = b.select(time=(lo, hi)).host_data()
        scale = np.abs(da).max()
        assert np.abs(da - db).max() < 5e-3 * scale

    def test_cascade_engine_rejects_misaligned(self, tmp_path):
        """engine='cascade' on a non-sample-aligned grid raises with
        guidance (engine='auto' would silently fall back to FFT)."""
        from tpudas import spool
        from tpudas.proc.lfproc import LFProc
        from tpudas.testing import make_synthetic_spool

        d = tmp_path / "raw"
        make_synthetic_spool(
            d, n_files=2, file_duration=30.0, fs=100.0, n_ch=4, noise=0.0
        )
        lfp = LFProc(spool(str(d)).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=0.333,  # 333 ms: non-integer ratio
            process_patch_size=60,
            edge_buff_size=10,
            engine="cascade",
        )
        lfp.set_output_folder(str(tmp_path / "out"), delete_existing=True)
        with pytest.raises(ValueError, match="cascade"):
            lfp.process_time_range(
                np.datetime64("2023-03-22T00:00:00"),
                np.datetime64("2023-03-22T00:01:00"),
            )
