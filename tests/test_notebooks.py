"""The four workflow notebooks execute end-to-end (the reference's
de-facto integration-test strategy — notebooks ARE the tests,
SURVEY.md §4.1). Cells run unmodified in-process on small synthetic
configs injected via TPUDAS_NB_* env knobs."""

import json
import os

import pytest

pytestmark = pytest.mark.slow

NB_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "notebooks")

NOTEBOOKS = [
    "low_pass_tpudas.ipynb",
    "rolling_mean_tpudas.ipynb",
    "low_pass_tpudas_edge.ipynb",
    "rolling_mean_tpudas_edge.ipynb",
]


def _code_cells(path):
    with open(path) as f:
        nb = json.load(f)
    for cell in nb["cells"]:
        if cell["cell_type"] == "code":
            yield "".join(cell["source"])


@pytest.mark.parametrize("name", NOTEBOOKS)
def test_notebook_executes(name, tmp_path, monkeypatch):
    monkeypatch.setenv("TPUDAS_NB_WORKDIR", str(tmp_path / "wd"))
    monkeypatch.setenv("TPUDAS_NB_NCH", "8")
    monkeypatch.setenv("TPUDAS_NB_FS", "100.0")
    monkeypatch.setenv("TPUDAS_NB_POLL", "0.5")
    ns = {"__name__": "__main__"}
    for i, src in enumerate(_code_cells(os.path.join(NB_DIR, name))):
        try:
            exec(compile(src, f"{name}:cell{i}", "exec"), ns)
        except Exception as e:  # pragma: no cover - diagnostic
            pytest.fail(f"{name} cell {i} failed: {e}\n---\n{src[:800]}")
    import matplotlib.pyplot as plt

    plt.close("all")
