"""tools/check_codecs.py wired into tier-1: every codec id the
registry accepts must appear in the roundtrip test matrix — a tile
format that registers but is never round-tripped in tests would be
first READ during an incident."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_codecs  # noqa: E402


def test_repo_is_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_codecs.py")],
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "check_codecs: OK" in proc.stdout


def test_registry_covers_the_issue11_family():
    """The shipped codec set is part of the lint surface: silently
    unregistering one would also silently shrink the lint, so pin the
    ids here."""
    ids = check_codecs.registered_ids()
    for cid in ("deflate", "bitshuffle-deflate", "quantize-deflate"):
        assert cid in ids


def test_untested_codec_detected(tmp_path):
    """A registered id missing from the test sources is flagged."""
    (tmp_path / "tests").mkdir()
    (tmp_path / "tests" / "test_x.py").write_text(
        'CODECS = ["deflate"]\n'
    )
    problems = check_codecs.lint(str(tmp_path))
    assert problems
    assert any("quantize-deflate" in p for p in problems)
