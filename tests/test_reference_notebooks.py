"""Execute the four REFERENCE notebooks verbatim against the tpudas shims.

This is the SURVEY.md §0 acceptance gate: the `.ipynb` files are loaded
from ``/root/reference`` and every code cell is executed UNMODIFIED,
except each notebook's cell 1, where only the three user-config path
assignments (``data_path``, ``output_data_folder``,
``output_figure_folder`` — `low_pass_dascore.ipynb:73-75`) are pointed
at pytest tmp dirs; the rest of that cell (spool construction,
get_contents) still runs as written.  Every test reports how many cells
ran verbatim.

The synthetic spool is shaped so the *hard-coded* notebook values work
unchanged: timestamps on 2023-03-22 (cells reference
'2023-03-22T03:00:00'..'07:00:00' and '06:00:00' literally), 1500
channels (cells index ``coords['distance'][1400]`` and channel 1330),
600-second files, at a 1 Hz sample rate (no notebook asserts the rate;
1 Hz keeps 4 hours of 1500-channel data at ~86 MB).

Reference quirks preserved on purpose:

- ``low_pass_dascore.ipynb`` cells 8 and 9 call ``waterfall_plot`` with
  10 positional args, but ``lf_das.py:110-122`` requires 12 — those
  cells raise TypeError against the reference itself (the notebook
  predates two added parameters).  The harness executes them verbatim,
  asserts the reference-faithful TypeError, then proves the QC path
  works by making the correct 12-arg call.
- ``rolling_mean_dascore.ipynb`` cell 3 writes results into
  ``output_figure_folder`` while cell 4 reads ``output_data_folder``
  (`rolling_mean_dascore.ipynb:153-156` vs `:174`, the latent notebook
  bug noted in SURVEY.md §2.1 C16).  Pointing both config vars at the
  same tmp dir — a pure path choice — lets the whole notebook run
  verbatim.
- The ``*_edge`` notebooks sleep ``time_step_for_processing`` (>=125 s)
  between polling rounds; the harness patches ``time.sleep`` to a
  feeder that appends the next interrogator files instead, exercising
  the real multi-round resume path at test speed.
"""

import json
import os

import numpy as np
import pytest

from tpudas.testing import make_synthetic_spool

pytestmark = pytest.mark.slow

REF = "/root/reference"
PATH_VARS = ("data_path", "output_data_folder", "output_figure_folder")

# spool geometry matching the notebooks' hard-coded values (see module doc)
N_CH = 1500
FS = 1.0
FILE_SEC = 600.0
SIG = dict(fs=FS, n_ch=N_CH, lf_freq=0.01, hf_freq=0.2, noise=0.01)


def load_code_cells(name):
    with open(os.path.join(REF, name)) as fh:
        nb = json.load(fh)
    return [
        "".join(c["source"])
        for c in nb["cells"]
        if c["cell_type"] == "code"
    ]


def sub_paths(src, mapping):
    """Replace ONLY the three path-assignment lines of the config cell."""
    lines, n = [], 0
    for line in src.splitlines():
        key = line.split("=")[0].strip() if "=" in line else None
        if key in PATH_VARS:
            lines.append(f"{key} = {mapping[key]!r}")
            n += 1
        else:
            lines.append(line)
    assert n == len(PATH_VARS), f"config cell drifted: {n} path lines"
    return "\n".join(lines)


def run_notebook(name, paths, expect_typeerror=()):
    """Execute all code cells; cell 1 gets path substitution only."""
    cells = load_code_cells(name)
    ns = {"__name__": "__main__"}
    verbatim = 0
    for i, src in enumerate(cells):
        if i == 1:
            src = sub_paths(src, paths)
        else:
            verbatim += 1
        code = compile(src, f"{name}[cell {i}]", "exec")
        if i in expect_typeerror:
            with pytest.raises(TypeError):
                exec(code, ns)
        else:
            exec(code, ns)
    print(
        f"{name}: {verbatim}/{len(cells)} cells verbatim "
        f"(cell 1: 3 path lines substituted)"
    )
    return ns


def nb_paths(data_dir, out_tmp, shared_fig=False):
    """Config paths: spool input at ``data_dir``, outputs under the
    test's own ``out_tmp`` (never shared between tests)."""
    out = out_tmp / "results"
    fig = out if shared_fig else out_tmp / "figures"
    out.mkdir(exist_ok=True)
    fig.mkdir(exist_ok=True)
    return {
        "data_path": str(data_dir),
        "output_data_folder": str(out),
        "output_figure_folder": str(fig),
    }


@pytest.fixture(scope="module")
def batch_spool(tmp_path_factory):
    """4 h x 1500 ch covering the notebooks' literal 03:00-07:00 range."""
    d = tmp_path_factory.mktemp("nbdata") / "data"
    make_synthetic_spool(
        d, n_files=24, file_duration=FILE_SEC,
        start="2023-03-22T03:00:00", **SIG,
    )
    return d


class TestLowPassBatch:
    def test_verbatim(self, batch_spool, tmp_path):
        paths = nb_paths(batch_spool, tmp_path)
        # cells 8/9: reference-faithful TypeError (see module doc)
        ns = run_notebook(
            "low_pass_dascore.ipynb", paths, expect_typeerror={8, 9}
        )
        # the engine produced one contiguous merged result
        assert len(ns["sp_result"]) == 1
        n_samples = ns["sp_result"][0].data.shape[0]
        assert n_samples * ns["d_t"] > 13990  # covers cell 8's max_sec
        # figures from cells 6/7 were written
        figs = os.listdir(paths["output_figure_folder"])
        assert sum(f.endswith(".jpeg") for f in figs) >= 2
        # prove the QC waterfall works when called per lf_das.py:110-122
        ns["waterfall_plot"](
            ns["demeaned_scaled_data"].T, 0, 13990, 0, 955,
            ns["ch_start"], ns["channel_spacing"], 1185, 1 / ns["d_t"],
            ns["fig_title"], paths["output_figure_folder"], "qc_12arg",
        )
        assert os.path.exists(
            os.path.join(paths["output_figure_folder"], "qc_12arg.jpeg")
        )


class TestRollingBatch:
    def test_verbatim(self, batch_spool, tmp_path):
        # shared fig/data dir neutralizes the notebook's write-into-
        # figure-folder bug without touching any non-path cell
        paths = nb_paths(batch_spool, tmp_path, shared_fig=True)
        ns = run_notebook("rolling_mean_dascore.ipynb", paths)
        # cell 4's own assert passed; check the merged result is real
        assert ns["time_no_nans"].shape[0] > 0
        assert (
            ns["rolling_merged_patch_no_nans"].data.shape[0]
            == ns["time_no_nans"].shape[0]
        )
        files = os.listdir(paths["output_data_folder"])
        assert sum(f.startswith("LFDAS_") for f in files) == 24


def _edge_feeder(monkeypatch, data_dir, batches):
    """Patch time.sleep so each polling-round sleep appends the next
    batch of interrogator files instead of wall-waiting."""
    import time as time_mod

    calls = []

    def fake_sleep(seconds):
        calls.append(seconds)
        if batches:
            start, n = batches.pop(0)
            make_synthetic_spool(
                data_dir, n_files=n, file_duration=FILE_SEC,
                start=start, prefix=f"feed{len(calls)}", **SIG,
            )

    monkeypatch.setattr(time_mod, "sleep", fake_sleep)
    return calls


class TestLowPassEdge:
    def test_verbatim(self, tmp_path, monkeypatch):
        data = tmp_path / "data"
        # initial files 05:50-06:30; start_processing_time is the
        # notebook's literal 2023-03-22T06:00:00
        make_synthetic_spool(
            data, n_files=4, file_duration=FILE_SEC,
            start="2023-03-22T05:50:00", **SIG,
        )
        sleeps = _edge_feeder(
            monkeypatch, data, [("2023-03-22T06:30:00", 2)]
        )
        paths = nb_paths(data, tmp_path)
        ns = run_notebook("low_pass_dascore_edge.ipynb", paths)
        assert ns["i"] == 2  # two processing rounds ran
        assert len(sleeps) == 2  # slept after each round, then broke
        from tpudas import spool

        merged = spool(paths["output_data_folder"]).update().chunk(
            time=None
        )
        assert len(merged) == 1  # resume-with-overlap left no seam
        times = merged[0].coords["time"]
        assert times[0] >= np.datetime64("2023-03-22T06:00:00")
        assert times[-1] >= np.datetime64("2023-03-22T06:45:00")


class TestRollingEdge:
    def test_verbatim(self, tmp_path, monkeypatch):
        data = tmp_path / "data"
        make_synthetic_spool(
            data, n_files=3, file_duration=FILE_SEC,
            start="2023-03-22T06:00:00", **SIG,
        )
        sleeps = _edge_feeder(
            monkeypatch, data, [("2023-03-22T06:30:00", 2)]
        )
        paths = nb_paths(data, tmp_path)
        ns = run_notebook("rolling_mean_dascore_edge.ipynb", paths)
        assert ns["i"] == 2
        assert len(sleeps) == 2
        files = os.listdir(paths["output_data_folder"])
        # 3 initial + 2 fed patches, one output file each
        assert sum(f.startswith("LFDAS_") for f in files) == 5
