"""tools/check_metrics.py wired into tier-1: the metric/span-name
catalog in OBSERVABILITY.md can never drift from the code."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_metrics  # noqa: E402


def test_repo_is_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_metrics.py")],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "check_metrics: OK" in proc.stdout


def test_collect_names_matches_call_styles():
    src = (
        'reg.counter("tpudas_a_total", "h").inc()\n'
        "reg.histogram(\n"
        '    "tpudas_b_seconds",\n'
        '    "h",\n'
        ").observe(1)\n"
        "with span(\n"
        '    "stream.round", mode="x"\n'
        "):\n"
        "    pass\n"
    )
    metrics, spans = check_metrics.collect_names(src)
    assert ("counter", "tpudas_a_total") in metrics
    assert ("histogram", "tpudas_b_seconds") in metrics
    assert spans == ["stream.round"]


@pytest.mark.parametrize(
    "bad", ["Tpudas_x_total", "tpudas_X", "other_total", "tpudas-x"]
)
def test_lint_flags_bad_names(bad):
    problems = check_metrics.lint(
        {"f.py": f'reg.counter("{bad}", "h").inc()'},
        catalog_text=f"`{bad}`",
    )
    assert problems and "does not match" in problems[0]


def test_lint_flags_uncatalogued():
    problems = check_metrics.lint(
        {"f.py": 'reg.gauge("tpudas_mystery_gauge").set(1)'},
        catalog_text="# empty catalog",
    )
    assert problems and "not catalogued" in problems[0]
    # catalogued -> clean
    assert (
        check_metrics.lint(
            {"f.py": 'reg.gauge("tpudas_mystery_gauge").set(1)'},
            catalog_text="| `tpudas_mystery_gauge` | gauge |",
        )
        == []
    )


def test_lint_flags_uncatalogued_span():
    problems = check_metrics.lint(
        {"f.py": 'with span("secret.phase"):\n    pass'},
        catalog_text="# empty catalog",
    )
    assert problems and "span name" in problems[0]
