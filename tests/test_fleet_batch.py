"""Ragged-batched fleet execution (ISSUE 16).

Stacked-step byte-identity at the ops layer (mixed channel widths,
both STACKED_ENGINES, quantized int16, FFT overlap-save), carry
slice-out/slice-in roundtrips across solo<->stacked transitions, the
BatchGroupFormer's memoized signatures, the BatchStepExecutor
rendezvous (wave partition, leave-shrink), and the batched FleetEngine
end-to-end: byte-identity against single-stream controls, park/fault
mid-round batch shrink, and KI-kill resume under ``batched=True``.
"""

import hashlib
import os
import subprocess
import sys
import threading
from types import SimpleNamespace

import numpy as np
import pytest

from tpudas.core.timeutils import to_datetime64
from tpudas.fleet import FleetEngine, StreamConfig, StreamSpec
from tpudas.fleet.batch import BatchGroupFormer, BatchStepExecutor
from tpudas.io.registry import write_patch
from tpudas.obs.registry import MetricsRegistry, use_registry
from tpudas.testing import (
    FaultPlan,
    FaultSpec,
    install_fault_plan,
    synthetic_patch,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FS = 100.0
FILE_SEC = 30.0
T0 = "2023-03-22T00:00:00"
WIDTHS = {"s0": 6, "s1": 10, "s2": 6}
NOISES = {"s0": 0.005, "s1": 0.01, "s2": 0.02}


def _feed(directory, start_index, count, noise=0.01, n_ch=6):
    os.makedirs(directory, exist_ok=True)
    t0 = to_datetime64(T0).astype("datetime64[ns]")
    step = np.timedelta64(int(round(1e9 / FS)), "ns")
    n = int(FILE_SEC * FS)
    for i in range(start_index, start_index + count):
        p = synthetic_patch(
            t0=t0 + i * n * step, duration=FILE_SEC, fs=FS, n_ch=n_ch,
            seed=i, phase_origin=t0, noise=noise,
        )
        write_patch(p, os.path.join(directory, f"raw_{i:04d}.h5"))


def _lowpass_config(**overrides):
    base = dict(
        kind="lowpass",
        start_time=T0,
        output_sample_interval=1.0,
        edge_buffer=8.0,
        process_patch_size=40,
        poll_interval=0.0,
        poll_jitter=0.0,
    )
    base.update(overrides)
    return StreamConfig(**base)


def _run_control(source, out, feed_fn=None, **overrides):
    from tpudas.proc.streaming import run_lowpass_realtime

    state = {"called": False}

    def sleep(_):
        if not state["called"]:
            state["called"] = True
            if feed_fn is not None:
                feed_fn()

    kwargs = dict(
        source=source,
        output_folder=out,
        start_time=T0,
        output_sample_interval=1.0,
        edge_buffer=8.0,
        process_patch_size=40,
        poll_interval=0.0,
        sleep_fn=sleep,
    )
    kwargs.update(overrides)
    return run_lowpass_realtime(**kwargs)


def _output_shas(folder) -> dict:
    out = {}
    for name in sorted(os.listdir(folder)):
        if name.startswith("LFDAS_") and name.endswith(".h5"):
            with open(os.path.join(folder, name), "rb") as fh:
                out[name] = hashlib.sha256(fh.read()).hexdigest()
    return out


def _pyramid_shas(folder) -> dict:
    from tpudas.serve.tiles import TILE_DIRNAME
    from tpudas.utils.atomicio import is_tmp_name

    tiles = os.path.join(folder, TILE_DIRNAME)
    out = {}
    for dirpath, _d, filenames in os.walk(tiles):
        for name in sorted(filenames):
            if ".prev" in name or is_tmp_name(name):
                continue
            path = os.path.join(dirpath, name)
            with open(path, "rb") as fh:
                out[os.path.relpath(path, tiles)] = hashlib.sha256(
                    fh.read()
                ).hexdigest()
    return out


# ---------------------------------------------------------------------------
# ops layer: stacked steps vs solo, byte for byte


class TestStackedCascadeOps:
    # both resolved stacked engines must stay in the matrix — the
    # tools/check_engines.py lint walks this file for the literals
    @pytest.mark.slow
    @pytest.mark.parametrize("engine", ["xla", "fused-xla"])
    def test_mixed_width_multi_round_byte_identity(self, engine):
        """Ragged packing (widths 5/8/3) over 3 carry-fed rounds: every
        stream's output and carry leaves byte-equal the solo path."""
        from tpudas.ops.fir import (
            cascade_decimate_stream,
            cascade_decimate_stream_stacked,
            cascade_stream_init,
            design_cascade,
        )

        plan = design_cascade(100.0, 10, 0.45, 4)
        widths = (5, 8, 3)
        rng = np.random.default_rng(7)
        stacked_c = [cascade_stream_init(plan, w) for w in widths]
        solo_c = [cascade_stream_init(plan, w) for w in widths]
        for _round in range(3):
            blocks = [
                rng.standard_normal((200, w)).astype(np.float32)
                for w in widths
            ]
            res = cascade_decimate_stream_stacked(
                blocks, stacked_c, plan, engine
            )
            stacked_c = [c for _y, c in res]
            for i, b in enumerate(blocks):
                y_solo, solo_c[i] = cascade_decimate_stream(
                    b, solo_c[i], plan, engine
                )
                assert np.array_equal(
                    np.asarray(res[i][0]), np.asarray(y_solo)
                ), f"member {i} output diverged ({engine})"
                for a, bb in zip(stacked_c[i], solo_c[i]):
                    assert np.array_equal(np.asarray(a), np.asarray(bb))

    @pytest.mark.slow
    def test_quantized_int16_stacked(self):
        """A stacked int16 wave with a shared qscale dequantizes
        in-kernel, byte-identical to the solo quantized path."""
        from tpudas.ops.fir import (
            cascade_decimate_stream,
            cascade_decimate_stream_stacked,
            cascade_stream_init,
            design_cascade,
        )

        plan = design_cascade(100.0, 10, 0.45, 4)
        scale = 2.5e-4
        rng = np.random.default_rng(11)
        widths = (4, 7)
        blocks = [
            rng.integers(-3000, 3000, (200, w)).astype(np.int16)
            for w in widths
        ]
        res = cascade_decimate_stream_stacked(
            blocks,
            [cascade_stream_init(plan, w) for w in widths],
            plan, "xla", qscale=scale,
        )
        for b, w, (y, _c) in zip(blocks, widths, res):
            y_solo, _ = cascade_decimate_stream(
                b, cascade_stream_init(plan, w), plan, "xla",
                qscale=scale,
            )
            assert np.array_equal(np.asarray(y), np.asarray(y_solo))

    def test_carry_slice_roundtrip_solo_stacked_solo(self):
        """A stream moves solo -> stacked -> solo; the carries sliced
        out of the stacked step feed the solo step with no drift."""
        from tpudas.ops.fir import (
            cascade_decimate_stream,
            cascade_decimate_stream_stacked,
            cascade_stream_init,
            design_cascade,
        )

        plan = design_cascade(100.0, 10, 0.45, 4)
        widths = (5, 8)
        rng = np.random.default_rng(3)
        rounds = [
            [
                rng.standard_normal((200, w)).astype(np.float32)
                for w in widths
            ]
            for _ in range(3)
        ]
        # reference: all-solo
        ref_c = [cascade_stream_init(plan, w) for w in widths]
        ref_y = [[], []]
        for blocks in rounds:
            for i, b in enumerate(blocks):
                y, ref_c[i] = cascade_decimate_stream(b, ref_c[i], plan)
                ref_y[i].append(np.asarray(y))
        # candidate: solo round, stacked round, solo round
        c = [cascade_stream_init(plan, w) for w in widths]
        got_y = [[], []]
        for i, b in enumerate(rounds[0]):
            y, c[i] = cascade_decimate_stream(b, c[i], plan)
            got_y[i].append(np.asarray(y))
        res = cascade_decimate_stream_stacked(rounds[1], c, plan, "xla")
        c = [cc for _y, cc in res]
        for i, (y, _cc) in enumerate(res):
            got_y[i].append(np.asarray(y))
        for i, b in enumerate(rounds[2]):
            y, c[i] = cascade_decimate_stream(b, c[i], plan)
            got_y[i].append(np.asarray(y))
        for i in range(len(widths)):
            for a, b in zip(got_y[i], ref_y[i]):
                assert np.array_equal(a, b)
            for a, b in zip(c[i], ref_c[i]):
                assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_stacked_validation(self):
        from tpudas.ops.fir import (
            cascade_decimate_stream_stacked,
            cascade_stream_init,
            design_cascade,
        )

        plan = design_cascade(100.0, 10, 0.45, 4)
        c4 = cascade_stream_init(plan, 4)
        b4 = np.zeros((200, 4), np.float32)
        with pytest.raises(ValueError, match="stacked engine"):
            cascade_decimate_stream_stacked(
                [b4], [c4], plan, "pallas-stream"
            )
        with pytest.raises(ValueError, match="shared T"):
            cascade_decimate_stream_stacked(
                [b4, np.zeros((100, 4), np.float32)], [c4, c4],
                plan, "xla",
            )
        with pytest.raises(ValueError, match="carry width"):
            cascade_decimate_stream_stacked(
                [np.zeros((200, 5), np.float32)], [c4], plan, "xla"
            )


class TestStackedFFTOps:
    def test_mixed_width_multi_round_byte_identity(self):
        from tpudas.ops.filter import (
            fft_pass_filter_stream,
            fft_pass_filter_stream_stacked,
            fft_stream_init,
        )

        widths = (5, 8, 3)
        rng = np.random.default_rng(5)
        stacked_c = [fft_stream_init(64, w) for w in widths]
        solo_c = [fft_stream_init(64, w) for w in widths]
        for _round in range(3):
            blocks = [
                rng.standard_normal((512, w)).astype(np.float32)
                for w in widths
            ]
            res = fft_pass_filter_stream_stacked(
                blocks, stacked_c, 0.01, high=0.45
            )
            stacked_c = [c for _y, c in res]
            for i, b in enumerate(blocks):
                y_solo, solo_c[i] = fft_pass_filter_stream(
                    b, solo_c[i], 0.01, high=0.45
                )
                assert np.array_equal(
                    np.asarray(res[i][0]), np.asarray(y_solo)
                ), f"member {i} FFT output diverged"
                assert np.array_equal(
                    np.asarray(stacked_c[i]), np.asarray(solo_c[i])
                )

    def test_stacked_validation(self):
        from tpudas.ops.filter import (
            fft_pass_filter_stream_stacked,
            fft_stream_init,
        )

        c = fft_stream_init(64, 4)
        with pytest.raises(ValueError, match="length mismatch"):
            fft_pass_filter_stream_stacked(
                [np.zeros((512, 4), np.float32)], [c, c], 0.01,
                high=0.45,
            )
        with pytest.raises(ValueError, match="does not match"):
            fft_pass_filter_stream_stacked(
                [np.zeros((512, 5), np.float32)], [c], 0.01, high=0.45
            )


# ---------------------------------------------------------------------------
# the group former


def _fake_runner(**over):
    cfg = SimpleNamespace(
        engine=over.pop("engine", None),
        filter_order=over.pop("filter_order", 4),
        on_gap=over.pop("on_gap", "interpolate"),
    )
    r = SimpleNamespace(
        kind="lowpass",
        stateful=True,
        mesh=None,
        spec=SimpleNamespace(config=cfg),
        d_t=1.0,
        buff_out=8,
        process_patch_size=40,
        carry=None,
    )
    for k, v in over.items():
        setattr(r, k, v)
    return r


class TestBatchGroupFormer:
    def test_group_key_determinism(self):
        """Same-config streams get equal signatures; any grouping-
        relevant difference (engine request, filter order, cadence)
        splits them."""
        f = BatchGroupFormer()
        a = f.signature("a", _fake_runner())
        b = f.signature("b", _fake_runner())
        assert a is not None and a == b
        assert f.signature("c", _fake_runner(engine="fused-xla")) != a
        assert f.signature("d", _fake_runner(filter_order=6)) != a
        assert f.signature("e", _fake_runner(d_t=2.0)) != a
        # recomputing from an identical runner state is stable
        assert f.signature("a", _fake_runner()) == a

    def test_solo_only_streams_get_none(self):
        f = BatchGroupFormer()
        assert f.signature("a", None) is None
        assert f.signature("b", _fake_runner(kind="rolling")) is None
        assert f.signature("c", _fake_runner(stateful=False)) is None
        assert f.signature("d", _fake_runner(mesh=object())) is None

    def test_memo_hit_miss_and_invalidate(self):
        reg = MetricsRegistry()
        f = BatchGroupFormer()
        r = _fake_runner()
        with use_registry(reg):
            f.signature("a", r)
            f.signature("a", r)  # same runner, same token -> hit
            f.invalidate("a")
            f.signature("a", r)  # invalidated -> recompute
        assert reg.value(
            "tpudas_fleet_batch_sig_memo_total", result="hit"
        ) == 1
        assert reg.value(
            "tpudas_fleet_batch_sig_memo_total", result="miss"
        ) == 2

    def test_carry_change_invalidates_token(self):
        """An engine crossover mutates the carry's engine fields; the
        memo token sees it and recomputes (no stale plan keys)."""
        reg = MetricsRegistry()
        f = BatchGroupFormer()
        carry = SimpleNamespace(
            kind="cascade", engine_req="auto", pallas_ok=False,
            d_ns=10_000_000_000, ratio=100, edge_in=800, order=4,
        )
        r = _fake_runner(carry=carry)
        with use_registry(reg):
            s1 = f.signature("a", r)
            carry.engine_req = "fused-xla"
            s2 = f.signature("a", r)
        assert s1 != s2
        assert reg.value(
            "tpudas_fleet_batch_sig_memo_total", result="miss"
        ) == 2


# ---------------------------------------------------------------------------
# the rendezvous executor


class TestBatchStepExecutor:
    def _run_members(self, ex, fns):
        """Run one callable per member on its own thread (bind/leave
        contract included); returns {member: result-or-exception}."""
        out = {}

        def runner(m, fn):
            ex.bind(m)
            try:
                out[m] = fn()
            except BaseException as exc:  # noqa: BLE001
                out[m] = exc
            finally:
                ex.leave(m)

        threads = [
            threading.Thread(target=runner, args=(m, fn))
            for m, fn in fns.items()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        return out

    def test_same_key_wave_stacks_and_matches_solo(self):
        from tpudas.ops.fir import (
            cascade_decimate_stream,
            cascade_stream_init,
            design_cascade,
        )

        plan = design_cascade(100.0, 10, 0.45, 4)
        rng = np.random.default_rng(2)
        widths = {"a": 5, "b": 8, "c": 3}
        blocks = {
            m: rng.standard_normal((200, w)).astype(np.float32)
            for m, w in widths.items()
        }
        reg = MetricsRegistry()
        ex = BatchStepExecutor(widths)
        with use_registry(reg):
            res = self._run_members(ex, {
                m: (lambda m=m: ex.cascade_step(
                    blocks[m], cascade_stream_init(plan, widths[m]),
                    plan, "xla",
                ))
                for m in widths
            })
        assert reg.value(
            "tpudas_fleet_batch_stacked_launches_total"
        ) == 1
        assert reg.value(
            "tpudas_fleet_batch_stacked_members_total"
        ) == 3
        for m, w in widths.items():
            y, _carry = res[m]
            y_solo, _ = cascade_decimate_stream(
                blocks[m], cascade_stream_init(plan, w), plan, "xla"
            )
            assert np.array_equal(np.asarray(y), np.asarray(y_solo))

    def test_mixed_keys_partition_into_waves(self):
        """Members whose exact stack key differs (here: block length)
        split into a stacked pair plus a solo dispatch."""
        from tpudas.ops.fir import cascade_stream_init, design_cascade

        plan = design_cascade(100.0, 10, 0.45, 4)
        rng = np.random.default_rng(4)
        reg = MetricsRegistry()
        ex = BatchStepExecutor(["a", "b", "c"])
        mk = lambda t, w: rng.standard_normal((t, w)).astype(np.float32)
        with use_registry(reg):
            res = self._run_members(ex, {
                "a": lambda: ex.cascade_step(
                    mk(200, 5), cascade_stream_init(plan, 5), plan, "xla"
                ),
                "b": lambda: ex.cascade_step(
                    mk(200, 8), cascade_stream_init(plan, 8), plan, "xla"
                ),
                "c": lambda: ex.cascade_step(
                    mk(400, 5), cascade_stream_init(plan, 5), plan, "xla"
                ),
            })
        assert reg.value(
            "tpudas_fleet_batch_stacked_launches_total"
        ) == 1
        assert reg.value("tpudas_fleet_batch_solo_launches_total") == 1
        assert np.shape(np.asarray(res["c"][0]))[0] == 40

    def test_leave_shrinks_rendezvous(self):
        """A member that leaves without submitting (fault before its
        device dispatch) must not deadlock the others."""
        from tpudas.ops.fir import cascade_stream_init, design_cascade

        plan = design_cascade(100.0, 10, 0.45, 4)
        rng = np.random.default_rng(6)
        ex = BatchStepExecutor(["a", "b", "c"])

        def faulty():
            raise ValueError("pre-dispatch fault")

        res = self._run_members(ex, {
            "a": lambda: ex.cascade_step(
                rng.standard_normal((200, 5)).astype(np.float32),
                cascade_stream_init(plan, 5), plan, "xla",
            ),
            "b": lambda: ex.cascade_step(
                rng.standard_normal((200, 5)).astype(np.float32),
                cascade_stream_init(plan, 5), plan, "xla",
            ),
            "c": faulty,
        })
        assert isinstance(res["c"], ValueError)
        for m in ("a", "b"):
            y, carry = res[m]
            assert np.shape(np.asarray(y)) == (20, 5)
            assert len(carry) > 0


# ---------------------------------------------------------------------------
# the batched fleet, end to end


def _batched_specs(tmp_path, **cfg_overrides):
    specs = []
    for sid, w in WIDTHS.items():
        src = str(tmp_path / f"src_{sid}")
        _feed(src, 0, 2, noise=NOISES[sid], n_ch=w)
        specs.append(
            StreamSpec(
                stream_id=sid, source=src,
                config=_lowpass_config(**cfg_overrides),
            )
        )
    return specs


def _assert_streams_match_controls(tmp_path, root, pyramid=True,
                                   sids=None, feed_more=True):
    for sid in (sids or WIDTHS):
        ctrl_src = str(tmp_path / f"ctrl_src_{sid}")
        _feed(ctrl_src, 0, 2, noise=NOISES[sid], n_ch=WIDTHS[sid])
        ctrl_out = str(tmp_path / f"ctrl_out_{sid}")
        feed_fn = None
        if feed_more:
            feed_fn = lambda s=ctrl_src, sid=sid: _feed(
                s, 2, 1, noise=NOISES[sid], n_ch=WIDTHS[sid]
            )
        _run_control(ctrl_src, ctrl_out, feed_fn=feed_fn,
                     pyramid=pyramid)
        assert _output_shas(os.path.join(root, sid)) == (
            _output_shas(ctrl_out)
        ), f"stream {sid} outputs differ from solo control"
        if pyramid:
            assert _pyramid_shas(os.path.join(root, sid)) == (
                _pyramid_shas(ctrl_out)
            ), f"stream {sid} pyramid differs from solo control"


class TestFleetBatched:
    @pytest.mark.slow
    def test_mixed_width_byte_identity_and_metrics(self, tmp_path):
        """3 mixed-width streams (6/10/6 ch) through the batched
        scheduler: every dispatch stacks (ragged packing), outputs and
        pyramids byte-identical to per-stream controls, and the
        batch metrics account for every round."""
        root = str(tmp_path / "root")
        specs = _batched_specs(tmp_path, pyramid=True)
        fed = {"done": False}

        def fleet_sleep(_):
            if not fed["done"]:
                fed["done"] = True
                for sid, w in WIDTHS.items():
                    _feed(
                        str(tmp_path / f"src_{sid}"), 2, 1,
                        noise=NOISES[sid], n_ch=w,
                    )

        reg = MetricsRegistry()
        with use_registry(reg):
            summary = FleetEngine(
                root, specs, sleep_fn=fleet_sleep, batched=True
            ).run()
        assert summary["rounds_total"] == 6
        assert summary["parked"] == []
        # zero jitter -> every poll (2 processing rounds + the final
        # termination poll) services as one 3-member group
        assert reg.value("tpudas_fleet_batch_groups_total") == 3
        assert reg.value("tpudas_fleet_batch_members_total") == 9
        assert reg.value(
            "tpudas_fleet_batch_stacked_launches_total"
        ) > 0
        assert reg.value(
            "tpudas_fleet_batch_solo_launches_total"
        ) == 0
        stacked = reg.value(
            "tpudas_fleet_batch_stacked_members_total"
        )
        launches = reg.value(
            "tpudas_fleet_batch_stacked_launches_total"
        )
        assert stacked == 3 * launches  # every wave carried all 3
        _assert_streams_match_controls(tmp_path, root)

    def test_env_var_enables_batching(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPUDAS_FLEET_BATCHED", "1")
        root = str(tmp_path / "root")
        specs = _batched_specs(tmp_path)
        eng = FleetEngine(root, specs, sleep_fn=lambda _s: None)
        assert eng.batched is True
        monkeypatch.setenv("TPUDAS_FLEET_BATCHED", "0")
        eng2 = FleetEngine(root, specs, sleep_fn=lambda _s: None)
        assert eng2.batched is False

    @pytest.mark.slow
    def test_fault_mid_round_shrinks_batch_not_fleet(self, tmp_path):
        """A stream faulting mid-round drops out of its batch group
        and parks; the surviving members' outputs stay byte-identical
        to their solo controls (the stacked carries slice back out
        intact)."""
        root = str(tmp_path / "root")
        specs = _batched_specs(tmp_path, pyramid=True)
        # carry.save's ctx is the stream's output folder (root/s1);
        # hit counting is global across streams, so the window must
        # span the whole run and `match` does the targeting
        plan = FaultPlan(
            FaultSpec(
                "carry.save", exc=ValueError, at=1, times=50,
                match=os.sep + "s1",
            )
        )
        reg = MetricsRegistry()
        with use_registry(reg), install_fault_plan(plan):
            summary = FleetEngine(
                root, specs, sleep_fn=lambda _s: None, batched=True
            ).run()
        assert summary["streams"]["s1"]["status"] == "parked"
        for sid in ("s0", "s2"):
            assert summary["streams"][sid]["status"] == "terminated"
        assert reg.value("tpudas_fleet_batch_groups_total") >= 1
        _assert_streams_match_controls(
            tmp_path, root, sids=("s0", "s2"), feed_more=False
        )
        # the parked stream's carry survived: a fresh engine (no
        # fault plan) finishes it byte-identical to its own control
        summary2 = FleetEngine(
            root, specs, sleep_fn=lambda _s: None, batched=True
        ).run()
        assert summary2["streams"]["s1"]["status"] == "terminated"
        _assert_streams_match_controls(
            tmp_path, root, sids=("s1",), feed_more=False
        )

    @pytest.mark.slow
    def test_ki_mid_batched_fleet_resumes_byte_identical(self, tmp_path):
        """KeyboardInterrupt mid-round under batched execution (the
        in-process stand-in for SIGKILL; tools/crash_drill.py
        --batched drills the real signal) kills the engine; a fresh
        batched engine resumes every stream byte-identical to its
        uninterrupted solo control."""
        root = str(tmp_path / "root")
        specs = _batched_specs(tmp_path, pyramid=True)
        plan = FaultPlan(
            FaultSpec("round.body", exc=KeyboardInterrupt, at=2)
        )
        with install_fault_plan(plan):
            with pytest.raises(KeyboardInterrupt):
                FleetEngine(
                    root, specs, sleep_fn=lambda _s: None, batched=True
                ).run()
        summary = FleetEngine(
            root, specs, sleep_fn=lambda _s: None, batched=True
        ).run()
        assert summary["parked"] == []
        _assert_streams_match_controls(tmp_path, root, feed_more=False)


@pytest.mark.slow
class TestCrashDrillBatched:
    def test_drill_batched_leg(self, tmp_path):
        """The SIGKILL crash drill's batched leg: kill -9 mid-fleet
        with TPUDAS_FLEET_BATCHED=1, resume, byte-identity."""
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "crash_drill.py"),
                "--streams", "3", "--batched", "--cycles", "2",
                "--engines", "cascade",
                "--workdir", str(tmp_path),
            ],
            capture_output=True,
            text=True,
            timeout=900,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "OK" in proc.stdout
