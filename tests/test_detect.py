"""tpudas.detect: the streaming-operator subsystem (ISSUE 6).

The acceptance bar: STA/LTA + rolling-RMS operators run in both
realtime drivers with O(1) carries that make retry == restart
byte-identical — a kill at any detect fault site, a skipped operator
round, or a full state reset all converge to the SAME events ledger,
score tiles, and operator carries an uninterrupted control produces;
``GET /events`` serves the integrity-verified results; the startup
audit classifies and repairs every detect artifact.
"""

import hashlib
import json
import os
import urllib.request

import numpy as np
import pytest

from tpudas.core.timeutils import to_datetime64
from tpudas.detect.ledger import (
    ScoreStore,
    event_line,
    ledger_status_text,
    load_events,
    write_events,
)
from tpudas.detect.operators import make_operator, operator_names
from tpudas.detect.runner import DetectPipeline, load_detect_carry
from tpudas.integrity.audit import audit
from tpudas.io.registry import write_patch
from tpudas.obs.registry import MetricsRegistry, use_registry
from tpudas.proc.streaming import run_lowpass_realtime, run_rolling_realtime
from tpudas.resilience.faults import RetryPolicy
from tpudas.testing import (
    FaultPlan,
    FaultSpec,
    install_fault_plan,
    make_synthetic_spool,
    synthetic_patch,
)

T0 = "2023-03-22T00:00:00"
FS = 50.0
FILE_SEC = 20.0
NCH = 4
STEP_NS = 1_000_000_000

# thresholds tuned so the noisy synthetic stream actually produces
# ledger events (empty ledgers would make equivalence tests vacuous)
OPS = [
    ("stalta", {"sta": 2.0, "lta": 10.0, "on": 2.0, "off": 1.2}),
    ("rms", {"window": 5.0, "step": 2.0, "thresh": 1.5, "baseline": 20.0}),
]

FAST = RetryPolicy(base_delay=0.0, max_delay=0.0, jitter=0.0)


def _spool(src, n_files=2):
    return make_synthetic_spool(
        src, n_files=n_files, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
        noise=0.01,
    )


def _append_one(src, index):
    t0 = to_datetime64(T0).astype("datetime64[ns]")
    step = np.timedelta64(int(round(1e9 / FS)), "ns")
    n = int(FILE_SEC * FS)
    p = synthetic_patch(
        t0=t0 + index * n * step, duration=FILE_SEC, fs=FS, n_ch=NCH,
        seed=index, phase_origin=t0, noise=0.01,
    )
    write_patch(p, os.path.join(src, f"raw_{index:04d}.h5"))


def _drive(src, out, feed_third=False, **kw):
    def sleep(_):
        if feed_third and not os.path.isfile(
            os.path.join(src, "raw_0002.h5")
        ):
            _append_one(src, 2)

    kw.setdefault("detect", True)
    kw.setdefault("detect_operators", OPS)
    kw.setdefault("pyramid", True)
    return run_lowpass_realtime(
        source=src,
        output_folder=out,
        start_time=T0,
        output_sample_interval=1.0,
        edge_buffer=5.0,
        process_patch_size=20,
        poll_interval=0.0,
        sleep_fn=sleep,
        fault_policy=FAST,
        **kw,
    )


def _detect_sig(out):
    """(ledger bytes sha, carry content sha, scores content sha) — the
    crash-equivalence comparison key.  The carry is compared by parsed
    content (the npz container embeds zip timestamps)."""
    with open(os.path.join(out, ".detect", "events.jsonl"), "rb") as fh:
        ledger = hashlib.sha256(fh.read()).hexdigest()
    carry = load_detect_carry(out)
    assert carry is not None
    h = hashlib.sha256()
    h.update(json.dumps(carry["meta"], sort_keys=True).encode())
    for st in carry["states"]:
        for key in sorted(st):
            arr = np.asarray(st[key])
            h.update(key.encode())
            h.update(str(arr.dtype).encode())
            h.update(arr.tobytes())
    store = ScoreStore.open(out)
    t, v = store.read()
    scores = hashlib.sha256(t.tobytes() + v.tobytes()).hexdigest()
    return ledger, h.hexdigest(), scores


def _event_key(ev):
    return (ev["t_end_ns"], ev["channel"], ev["t_ns"], ev["op"])


# ---------------------------------------------------------------------------
# operators


class TestOperatorContract:
    def test_registry(self):
        assert "stalta" in operator_names()
        assert "rms" in operator_names()
        op = make_operator({"name": "stalta", "on": 5.0})
        assert op.on == 5.0
        assert make_operator(op) is op
        with pytest.raises(ValueError, match="unknown detect operator"):
            make_operator("nope")

    def test_two_score_operators_rejected(self, tmp_path):
        """The single-level score store holds ONE row track: a second
        score-producing operator must be rejected up front, not
        silently interleaved."""
        with pytest.raises(ValueError, match="score-producing"):
            DetectPipeline.open(str(tmp_path), operators=[
                ("rms", {"window": 5.0, "step": 2.0}),
                ("rms", {"window": 30.0, "step": 2.0}),
            ])

    def test_param_validation(self):
        with pytest.raises(ValueError):
            make_operator(("stalta", {"sta": 5.0, "lta": 1.0}))
        with pytest.raises(ValueError):
            make_operator(("stalta", {"on": 2.0, "off": 3.0}))
        with pytest.raises(ValueError):
            make_operator(("rms", {"window": 0.0}))

    @pytest.mark.parametrize(
        "spec",
        [("stalta", {"sta": 2.0, "lta": 10.0, "on": 2.0, "off": 1.2}),
         ("rms", {"window": 5.0, "step": 2.0, "thresh": 2.0,
                  "baseline": 20.0})],
        ids=["stalta", "rms"],
    )
    @pytest.mark.slow
    def test_chunk_invariance(self, spec):
        """The contract's rule 1: any chunking of the same row stream
        produces bit-identical events, scores, and final state."""
        rng = np.random.default_rng(0)
        T, C = 500, 3
        rows = (0.1 * rng.standard_normal((T, C))).astype(np.float32)
        rows[250:280, 1] += 5.0  # a burst
        t_ns = np.arange(T, dtype=np.int64) * STEP_NS
        op = make_operator(spec)
        st_a = op.init_state(C, STEP_NS)
        res_a, st_a = op.process(rows, t_ns, STEP_NS, st_a)
        st_b = op.init_state(C, STEP_NS)
        evs, scores, times = [], [], []
        cuts = sorted(
            rng.choice(np.arange(1, T), size=9, replace=False).tolist()
        )
        for lo, hi in zip([0] + cuts, cuts + [T]):
            r, st_b = op.process(rows[lo:hi], t_ns[lo:hi], STEP_NS, st_b)
            evs.extend(r.events)
            if r.scores is not None and r.scores.size:
                scores.append(r.scores)
                times.append(r.score_t_ns)
        assert sorted(res_a.events, key=_event_key) == sorted(
            evs, key=_event_key
        )
        if res_a.scores is not None:
            assert np.array_equal(res_a.scores, np.concatenate(scores))
            assert np.array_equal(
                res_a.score_t_ns, np.concatenate(times)
            )
        for key in st_a:
            assert np.array_equal(
                np.asarray(st_a[key]), np.asarray(st_b[key])
            ), key

    def test_stalta_detects_burst_and_carries_open_events(self):
        rng = np.random.default_rng(1)
        T, C = 400, 2
        rows = (0.05 * rng.standard_normal((T, C))).astype(np.float32)
        rows[200:230, 0] += 3.0
        t_ns = np.arange(T, dtype=np.int64) * STEP_NS
        op = make_operator(OPS[0])
        st = op.init_state(C, STEP_NS)
        # split INSIDE the burst so the trigger is open at the seam
        r1, st = op.process(rows[:210], t_ns[:210], STEP_NS, st)
        assert bool(np.asarray(st["in_event"])[0])
        r2, st = op.process(rows[210:], t_ns[210:], STEP_NS, st)
        trig = [e for e in r1.events + r2.events
                if e["channel"] == 0 and e["t_ns"] >= 195 * STEP_NS]
        assert trig, "burst trigger missing"
        assert trig[0]["t_peak_ns"] >= trig[0]["t_ns"]
        assert trig[0]["t_end_ns"] > trig[0]["t_ns"]
        assert trig[0]["score"] >= op.on
        # closed events leave a canonical (zeroed) carry — channels
        # not currently in an event hold zeros (an open noise trigger
        # on the other channel may legitimately ride the carry)
        closed = ~np.asarray(st["in_event"], bool)
        assert not np.asarray(st["peak"])[closed].any()
        assert not np.asarray(st["t_on"])[closed].any()

    def test_rms_scores_on_global_grid(self):
        op = make_operator(OPS[1])  # w=5 rows, s=2 rows at 1 Hz
        rows = np.ones((20, 2), np.float32)
        t_ns = np.arange(20, dtype=np.int64) * STEP_NS
        st = op.init_state(2, STEP_NS)
        res, st = op.process(rows, t_ns, STEP_NS, st)
        # pandas alignment: positions 0,2,4... valid from p >= w-1 = 4
        assert list(res.score_t_ns) == [
            int(p * STEP_NS) for p in range(4, 20, 2)
        ]
        assert np.allclose(res.scores, 1.0)

    def test_nan_rows_are_inert(self):
        rng = np.random.default_rng(2)
        rows = (0.1 * rng.standard_normal((100, 3))).astype(np.float32)
        rows[40:50] = np.nan
        t_ns = np.arange(100, dtype=np.int64) * STEP_NS
        for spec in OPS:
            op = make_operator(spec)
            st = op.init_state(3, STEP_NS)
            res, st = op.process(rows, t_ns, STEP_NS, st)
            for key, val in st.items():
                arr = np.asarray(val)
                if arr.dtype.kind == "f" and key != "ring":
                    assert np.isfinite(arr).all(), (op.name, key)
            assert all(np.isfinite(e["score"]) for e in res.events)


# ---------------------------------------------------------------------------
# durable artifacts


class TestLedger:
    EV = {"op": "stalta", "kind": "trigger", "channel": 1,
          "t_ns": 10, "t_peak_ns": 11, "t_end_ns": 12, "score": 3.5,
          "seq": 0}

    def test_roundtrip_stamped(self, tmp_path):
        evs = [dict(self.EV), {**self.EV, "seq": 1, "channel": 2}]
        write_events(str(tmp_path), evs)
        assert load_events(str(tmp_path)) == evs
        raw = open(tmp_path / ".detect" / "events.jsonl").read()
        assert '"_crc32"' in raw  # every line is stamped

    def test_torn_line_falls_back_to_prev(self, tmp_path):
        write_events(str(tmp_path), [dict(self.EV)])
        write_events(str(tmp_path), [dict(self.EV),
                                     {**self.EV, "seq": 1}])
        path = tmp_path / ".detect" / "events.jsonl"
        with open(path, "a") as fh:
            fh.write('{"torn": tru')  # a half-written tail line
        # ladder: primary torn -> .prev (one commit back)
        assert load_events(str(tmp_path)) == [dict(self.EV)]

    def test_write_event_lines_matches_write_events(self, tmp_path):
        """The commit path caches serialized lines so a rewrite stamps
        only NEW events — the cached-line file must be byte-identical
        to the from-events serialization."""
        from tpudas.detect.ledger import write_event_lines

        evs = [dict(self.EV), {**self.EV, "seq": 1, "channel": 2}]
        a, b = tmp_path / "a", tmp_path / "b"
        write_events(str(a), evs)
        write_event_lines(str(b), [event_line(e) for e in evs])
        pa = a / ".detect" / "events.jsonl"
        pb = b / ".detect" / "events.jsonl"
        assert pa.read_bytes() == pb.read_bytes()
        assert load_events(str(b)) == evs

    def test_status_classification(self):
        good = event_line(self.EV)
        assert ledger_status_text(good + "\n")[0] == "ok"
        assert ledger_status_text("")[0] == "ok"
        unstamped = json.dumps(self.EV)
        assert ledger_status_text(unstamped + "\n")[0] == "unstamped"
        assert ledger_status_text("not json\n")[0] == "torn"
        # tampered payload: stamp no longer matches
        tampered = good.replace('"channel":1', '"channel":3')
        assert ledger_status_text(tampered + "\n")[0] == "torn"
        # seq gap
        gap = event_line({**self.EV, "seq": 5})
        assert ledger_status_text(gap + "\n")[0] == "torn"


class TestScoreStore:
    def _mk(self, tmp_path, tile_len=4, n_ch=2):
        return ScoreStore.create(
            str(tmp_path), epoch_ns=1000, n_ch=n_ch, tile_len=tile_len
        )

    def test_append_read_across_tiles(self, tmp_path):
        store = self._mk(tmp_path)
        t = np.arange(10, dtype=np.int64) * 2_000 + 1000
        v = np.arange(20, dtype=np.float64).reshape(10, 2)
        store.append(t[:3], v[:3])
        store.append(t[3:], v[3:])
        assert store.n_rows == 10
        # 2 full tiles + 2 tail rows on disk
        names = sorted(os.listdir(ScoreStore.scores_dir(str(tmp_path))))
        assert "00000000.npy" in names and "00000001.npy" in names
        re_t, re_v = ScoreStore.open(str(tmp_path)).read()
        assert np.array_equal(re_t, t)
        assert np.array_equal(re_v, v)
        # windowed read
        re_t, re_v = ScoreStore.open(str(tmp_path)).read(t[4], t[8])
        assert np.array_equal(re_t, t[4:8])

    def test_truncate_into_completed_tile(self, tmp_path):
        store = self._mk(tmp_path)
        t = np.arange(10, dtype=np.int64) * 2_000 + 1000
        v = np.ones((10, 2))
        store.append(t, v)
        store.truncate_to(6)  # into tile 1
        assert store.n_rows == 6
        re = ScoreStore.open(str(tmp_path))
        re_t, _ = re.read()
        assert np.array_equal(re_t, t[:6])
        with pytest.raises(Exception):
            store.truncate_to(99)  # ahead of the store: unreconcilable

    def test_crash_before_manifest_recovers_from_head_tile(
        self, tmp_path
    ):
        """The real crash window: tiles and tails landed, the manifest
        rename did not (append order is tiles -> tails -> manifest).
        The stale manifest's partial region is recovered from the
        completed-but-uncommitted head tile FILE, not from the
        re-based tails (the pyramid's partial-read trick)."""
        store = self._mk(tmp_path)
        t = np.arange(10, dtype=np.int64) * 2_000 + 1000
        v = np.arange(20, dtype=np.float64).reshape(10, 2)
        store.append(t[:3], v[:3])
        manifest_before = open(store.manifest_path).read()
        # this append completes tile 0 AND leaves 3 re-based tail rows
        # (>= the stale manifest's 3), the ambiguous case
        store.append(t[3:], v[3:])
        with open(store.manifest_path, "w") as fh:
            fh.write(manifest_before)  # the crash: manifest is stale
        re = ScoreStore.open(str(tmp_path))
        assert re.n_rows == 3
        re_t, re_v = re.read()
        assert np.array_equal(re_t, t[:3])
        assert np.array_equal(re_v, v[:3])


# ---------------------------------------------------------------------------
# driver integration


class TestDriverIntegration:
    @pytest.mark.slow
    def test_artifacts_events_metrics_health(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPUDAS_HEALTH", "1")
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=3)
        reg = MetricsRegistry()
        with use_registry(reg):
            rounds = _drive(src, out)
        assert rounds >= 1
        evs = load_events(out)
        assert evs, "the tuned thresholds must produce events"
        assert [e["seq"] for e in evs] == list(range(len(evs)))
        assert {e["op"] for e in evs} <= {"stalta", "rms"}
        # ledger order: close time, then operator, then channel
        keys = [(e["t_end_ns"],) for e in evs]
        assert keys == sorted(keys)
        store = ScoreStore.open(out)
        assert store is not None and store.n_rows > 0
        t, v = store.read()
        assert v.shape == (store.n_rows, NCH)
        assert reg.value("tpudas_detect_rounds_total") >= 1
        assert reg.value("tpudas_detect_rows_total") > 0
        assert reg.value("tpudas_detect_ledger_events") == len(evs)
        assert reg.value("tpudas_detect_errors_total") == 0
        # the multi-subscriber emit hook served pyramid AND detect
        from tpudas.serve.tiles import TileStore

        assert TileStore.open(out) is not None
        from tpudas.obs.health import read_health

        health = read_health(out)
        assert health["detect"]["ledger_events"] == len(evs)
        assert health["detect"]["operators"] == ["stalta", "rms"]
        # a second run over the same folder resumes, no reset
        reg2 = MetricsRegistry()
        with use_registry(reg2):
            _drive(src, out)
        assert reg2.value("tpudas_detect_carry_resumes_total") == 1
        assert reg2.value("tpudas_detect_resets_total") == 0

    def test_detect_off_leaves_no_artifacts(self, tmp_path):
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src)
        _drive(src, out, detect=False)
        assert not os.path.isdir(os.path.join(out, ".detect"))

    def test_enabling_later_catches_up_from_files(self, tmp_path):
        """Detect switched on over a folder with prior outputs:
        the file-backed catch-up recomputes the FULL history, equal to
        an always-on control."""
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        src2, out2 = str(tmp_path / "src2"), str(tmp_path / "out2")
        _spool(src)
        _spool(src2)
        _drive(src, out, detect=False, feed_third=True)
        reg = MetricsRegistry()
        with use_registry(reg):
            _drive(src, out, feed_third=True)  # detect on, no new data?
        # control: detect on from the start
        _drive(src2, out2, feed_third=True)
        assert _detect_sig(out) == _detect_sig(out2)
        assert reg.value("tpudas_detect_catchup_rows_total") > 0

    def test_operator_config_change_resets_and_recomputes(
        self, tmp_path
    ):
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=3)
        _drive(src, out)
        sig = _detect_sig(out)
        reg = MetricsRegistry()
        with use_registry(reg):
            _drive(src, out, detect_operators=[OPS[0]])  # drop rms
        assert reg.value("tpudas_detect_resets_total") == 1
        assert load_events(out)  # recomputed under the new config
        assert all(e["op"] == "stalta" for e in load_events(out))
        # switching back recomputes the original state exactly
        _drive(src, out)
        assert _detect_sig(out) == sig

    def test_grid_step_change_resets(self, tmp_path):
        """The output grid step is operator geometry (recurrence
        alphas, window row counts): a restart with a different step
        must reset and recompute, not silently adopt the stale
        step."""
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=3)
        _drive(src, out)
        reg0 = MetricsRegistry()
        with use_registry(reg0):
            DetectPipeline.open(out, operators=OPS, step_sec=1.0)
        assert reg0.value("tpudas_detect_carry_resumes_total") == 1
        assert reg0.value("tpudas_detect_resets_total") == 0
        reg = MetricsRegistry()
        with use_registry(reg):
            DetectPipeline.open(out, operators=OPS, step_sec=2.0)
        assert reg.value("tpudas_detect_resets_total") == 1

    def test_channel_count_change_resets(self, tmp_path):
        """A restart with different channel geometry must reset and
        recompute deterministically — not fail every round forever on
        a stale carry whose per-channel states can never consume the
        new rows."""
        from tpudas.detect.runner import run_detect_round

        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=3)
        _drive(src, out)
        sig = _detect_sig(out)
        upto = int(load_detect_carry(out)["meta"]["upto_ns"])
        alien = synthetic_patch(
            t0=np.datetime64(upto + STEP_NS, "ns"), duration=10.0,
            fs=1.0, n_ch=NCH + 2, seed=7, noise=0.01,
        )
        state = {}
        reg = MetricsRegistry()
        with use_registry(reg):
            run_detect_round(out, 1, [alien], state, operators=OPS,
                             step_sec=1.0)
        assert reg.value("tpudas_detect_resets_total") == 1
        assert reg.value("tpudas_detect_errors_total") == 0
        assert state["summary"]["ok"] is True
        # the reset recomputed the whole history from the files
        assert _detect_sig(out) == sig

    def test_rolling_driver_parity(self, tmp_path):
        """Satellite: run_rolling_realtime has the same emit capture +
        pyramid/detect path."""
        from tpudas.core.units import s as sec

        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src)
        reg = MetricsRegistry()
        with use_registry(reg):
            rounds = run_rolling_realtime(
                source=src, output_folder=out, window=1.0 * sec,
                step=1.0 * sec, poll_interval=0.0,
                sleep_fn=lambda _: None, fault_policy=FAST,
                pyramid=True, detect=True,
                detect_operators=[
                    ("rms", {"window": 5.0, "step": 2.0,
                             "thresh": 1.5, "baseline": 10.0})],
            )
        assert rounds >= 1
        assert reg.value("tpudas_detect_rounds_total") >= 1
        store = ScoreStore.open(out)
        assert store is not None and store.n_rows > 0
        from tpudas.serve.tiles import TileStore

        assert TileStore.open(out) is not None


# ---------------------------------------------------------------------------
# crash equivalence (the acceptance bar)


class TestCrashResumeEquivalence:
    """Kill the driver at each detect-relevant site mid-run, resume,
    and the events ledger / operator carries / score tiles are
    byte-identical to an uninterrupted control — the extension of
    test_resilience.TestCrashResumeEquivalence to the detect state."""

    SPECS = {
        "detect.op": FaultSpec("detect.op", at=1, exc=KeyboardInterrupt),
        "detect.ledger_write": FaultSpec(
            "detect.ledger_write", at=1, exc=KeyboardInterrupt
        ),
        "carry.save": FaultSpec("carry.save", at=2,
                                exc=KeyboardInterrupt),
        "round.body": FaultSpec("round.body", at=2,
                                exc=KeyboardInterrupt),
        "fs.write_enospc": FaultSpec(
            "fs.write_enospc", at=4, exc=KeyboardInterrupt
        ),
    }

    @pytest.fixture(scope="class")
    def control(self, tmp_path_factory):
        td = tmp_path_factory.mktemp("detect_ctrl")
        src, out = str(td / "src"), str(td / "out")
        _spool(src)
        rounds = _drive(src, out, feed_third=True)
        assert rounds == 2
        assert load_events(out), "control must have events"
        return _detect_sig(out)

    @pytest.mark.parametrize("site", sorted(SPECS))
    def test_kill_resume_identical(self, tmp_path, control, site):
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src)
        plan = FaultPlan(self.SPECS[site])
        with install_fault_plan(plan):
            with pytest.raises(KeyboardInterrupt):
                _drive(src, out, feed_third=True)
        assert plan.fired, f"fault at {site} never fired"
        rounds = _drive(src, out, feed_third=True)
        assert rounds >= 1
        assert _detect_sig(out) == control, (
            f"detect state diverged after {site} kill"
        )

    def test_operator_failure_skipped_then_converges(self, tmp_path,
                                                     control):
        """An operator that raises is counted and skipped — the stream
        survives, and the NEXT round's catch-up replays the rows so
        the final state still matches the control."""
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src)
        plan = FaultPlan(FaultSpec("detect.op", at=1, exc=RuntimeError))
        reg = MetricsRegistry()
        with use_registry(reg), install_fault_plan(plan):
            rounds = _drive(src, out, feed_third=True)
        assert rounds == 2  # the stream never noticed
        assert plan.fired
        assert reg.value("tpudas_detect_errors_total") == 1
        assert reg.value(
            "tpudas_detect_op_errors_total", op="stalta"
        ) == 1
        assert _detect_sig(out) == control

    def test_full_reset_recomputes_identically(self, tmp_path, control):
        import shutil

        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src)
        _drive(src, out, feed_third=True)
        shutil.rmtree(os.path.join(out, ".detect"))
        reg = MetricsRegistry()
        with use_registry(reg):
            _drive(src, out, feed_third=True)
        assert _detect_sig(out) == control


# ---------------------------------------------------------------------------
# audit (fsck) classification + repair


class TestDetectAudit:
    @pytest.fixture()
    def folder(self, tmp_path):
        # 3 files: a single round over 2 files emits too few decimated
        # rows for the operators to warm up (no events => vacuous test)
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=3)
        _drive(src, out)
        assert load_events(out)
        return src, out

    def test_clean_folder_audits_clean(self, folder):
        _, out = folder
        rep = audit(out, repair=True)
        assert rep["clean"] and not rep["issues"]

    def test_surplus_ledger_truncated(self, folder):
        _, out = folder
        ledger = os.path.join(out, ".detect", "events.jsonl")
        before = open(ledger).read()
        evs = load_events(out)
        fake = dict(evs[-1])
        fake["seq"] = len(evs)
        with open(ledger, "a") as fh:
            fh.write(event_line(fake) + "\n")
        rep = audit(out, repair=True)
        assert any(i["action"] == "truncated" for i in rep["issues"])
        assert open(ledger).read() == before
        rep2 = audit(out, repair=True)
        assert rep2["clean"] and not rep2["issues"]

    def test_torn_ledger_no_prev_resets_then_recomputes(self, folder):
        src, out = folder
        sig = _detect_sig(out)
        ledger = os.path.join(out, ".detect", "events.jsonl")
        with open(ledger, "a") as fh:
            fh.write('{"torn": tru')
        for prev in (ledger + ".prev",):
            if os.path.isfile(prev):
                os.remove(prev)
        rep = audit(out, repair=True)
        assert any(
            i["action"] == "reset_detect" for i in rep["issues"]
        )
        assert not os.path.isdir(os.path.join(out, ".detect"))
        _drive(src, out)  # deterministic recompute from the outputs
        assert _detect_sig(out) == sig

    def test_zero_event_state_audits_clean(self, tmp_path):
        """Quiet data: a committed carry + score tiles with NO
        events.jsonl at all (a commit that has never seen an event
        never writes one) is a healthy state — the startup audit must
        not reset it on every restart."""
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=3)
        quiet = [
            ("stalta", {"sta": 2.0, "lta": 10.0, "on": 999.0,
                        "off": 1.2}),
            ("rms", {"window": 5.0, "step": 2.0, "thresh": 999.0,
                     "baseline": 20.0}),
        ]
        _drive(src, out, detect_operators=quiet)
        assert load_detect_carry(out) is not None
        assert not os.path.isfile(
            os.path.join(out, ".detect", "events.jsonl")
        )
        rep = audit(out, repair=True)
        assert rep["clean"] and not rep["issues"]
        reg = MetricsRegistry()
        with use_registry(reg):  # restart: startup fsck + resume
            _drive(src, out, detect_operators=quiet)
        assert reg.value("tpudas_detect_resets_total") == 0
        assert reg.value("tpudas_detect_carry_resumes_total") == 1

    def test_torn_tails_resets_not_crashes(self, folder):
        """Committed partial score rows whose tails.npy is torn (and
        no completed head tile to recover from): ScoreStore.open
        raises — the audit must classify and reset, never crash the
        fsck."""
        src, out = folder
        sig = _detect_sig(out)
        tails = os.path.join(out, ".detect", "scores", "tails.npy")
        data = open(tails, "rb").read()
        with open(tails, "wb") as fh:
            fh.write(data[: len(data) // 2])
        rep = audit(out, repair=True)  # must not raise
        assert any(
            i["action"] == "reset_detect" for i in rep["issues"]
        )
        rep2 = audit(out, repair=True)
        assert rep2["clean"] and not rep2["issues"]
        _drive(src, out)  # deterministic recompute from the outputs
        assert _detect_sig(out) == sig

    def test_unreadable_carry_resets(self, folder):
        _, out = folder
        carry = os.path.join(out, ".detect", "carry.npz")
        with open(carry, "wb") as fh:
            fh.write(b"not a zip")
        for prev in (carry + ".prev", carry + ".prev.crc"):
            if os.path.isfile(prev):
                os.remove(prev)
        rep = audit(out, repair=True)
        assert any(
            i["action"] == "reset_detect" for i in rep["issues"]
        )
        rep2 = audit(out, repair=True)
        assert rep2["clean"] and not rep2["issues"]

    def test_startup_fsck_runs_before_detect(self, folder, monkeypatch):
        """The driver's own startup audit repairs a surplus ledger
        before the pipeline loads it (no reconcile counter fires)."""
        src, out = folder
        evs = load_events(out)
        fake = dict(evs[-1])
        fake["seq"] = len(evs)
        ledger = os.path.join(out, ".detect", "events.jsonl")
        with open(ledger, "a") as fh:
            fh.write(event_line(fake) + "\n")
        reg = MetricsRegistry()
        with use_registry(reg):
            _drive(src, out)
        assert reg.value("tpudas_integrity_audit_repairs_total",
                         kind="truncated") == 1
        assert reg.value(
            "tpudas_detect_reconcile_truncated_total"
        ) == 0


# ---------------------------------------------------------------------------
# the /events query plane


class TestEventsEndpoint:
    @pytest.fixture(scope="class")
    def server(self, tmp_path_factory):
        from tpudas.serve.http import start_server

        td = tmp_path_factory.mktemp("events_srv")
        src, out = str(td / "src"), str(td / "out")
        _spool(src, n_files=3)
        _drive(src, out, health=True)
        with start_server(out) as srv:
            yield srv, out

    def _get(self, srv, path):
        with urllib.request.urlopen(srv.base_url + path) as resp:
            return resp.status, json.loads(resp.read().decode()), resp

    def test_all_events_verified(self, server):
        srv, out = server
        status, body, resp = self._get(srv, "/events")
        assert status == 200
        evs = load_events(out)
        assert body["ledger_events"] == len(evs)
        assert body["events"] == evs
        assert resp.headers["X-Tpudas-Events-Total"] == str(len(evs))

    def test_filters(self, server):
        srv, out = server
        _, body, _ = self._get(srv, "/events?min_score=2.2&op=stalta")
        assert all(
            e["score"] >= 2.2 and e["op"] == "stalta"
            for e in body["events"]
        )
        _, body, _ = self._get(srv, "/events?c0=1&c1=2")
        assert all(1 <= e["channel"] <= 2 for e in body["events"])
        _, body, _ = self._get(srv, "/events?limit=2")
        assert body["count"] <= 2
        assert body["events"] == load_events(out)[-2:]  # newest kept
        evs = load_events(out)
        t_mid = evs[len(evs) // 2]["t_ns"]
        _, body, _ = self._get(srv, f"/events?t0={t_mid}")
        assert all(e["t_ns"] >= t_mid for e in body["events"])

    def test_scores_window(self, server):
        srv, out = server
        _, body, _ = self._get(srv, "/events?scores=1&c0=1&c1=2")
        sc = body["scores"]
        assert sc["channel0"] == 1
        store = ScoreStore.open(out)
        assert len(sc["times_ns"]) == store.n_rows
        assert len(sc["values"][0]) == 2  # channels 1..2

    def test_bad_limit_is_400(self, server):
        srv, _ = server
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._get(srv, "/events?limit=0")
        assert ei.value.code == 400

    def test_healthz_surfaces_detect(self, server):
        srv, out = server
        status, body, _ = self._get(srv, "/healthz")
        assert status == 200
        assert body["detect"]["ledger_events"] == len(load_events(out))
        assert body["detect"]["ok"] is True

    def test_scores_limit_caps_response(self, server):
        srv, out = server
        store = ScoreStore.open(out)
        assert store.n_rows > 3
        _, body, _ = self._get(srv, "/events?scores=1&scores_limit=3")
        sc = body["scores"]
        assert len(sc["times_ns"]) == 3
        assert sc["truncated"] is True
        assert sc["rows_total"] == store.n_rows
        t, _v = store.read()
        assert sc["times_ns"] == [int(x) for x in t[-3:]]  # newest
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._get(srv, "/events?scores=1&scores_limit=0")
        assert ei.value.code == 400

    def test_ledger_cache_invalidates_on_commit(self, tmp_path):
        """/events serves from the stat-keyed parsed-ledger cache; a
        new commit (atomic file replace) must invalidate it."""
        from tpudas.serve.http import start_server

        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=3)
        _drive(src, out)
        evs = load_events(out)
        with start_server(out) as srv:
            _, body, _ = self._get(srv, "/events?limit=100000")
            assert body["ledger_events"] == len(evs)
            _, body2, _ = self._get(srv, "/events?limit=100000")
            assert body2["events"] == body["events"]  # cached hit
            fake = dict(evs[-1])
            fake["seq"] = len(evs)
            write_events(out, evs + [fake])
            _, body3, _ = self._get(srv, "/events?limit=100000")
            assert body3["ledger_events"] == len(evs) + 1

    @pytest.mark.slow
    def test_scores_degrade_on_torn_store(self, tmp_path):
        """Committed partial rows with a torn tails.npy make
        ScoreStore.open raise; ``/events?scores=1`` must degrade to
        ``scores: null`` (200) — the events themselves were perfectly
        readable, the response must not 500."""
        from tpudas.serve.http import start_server

        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=3)
        _drive(src, out)
        tails = os.path.join(out, ".detect", "scores", "tails.npy")
        data = open(tails, "rb").read()
        with open(tails, "wb") as fh:
            fh.write(data[: len(data) // 2])
        with start_server(out) as srv:
            status, body, _ = self._get(srv, "/events?scores=1")
        assert status == 200
        assert body["scores"] is None
        assert body["events"] == load_events(out)


# ---------------------------------------------------------------------------
# disk-pressure shedding


class TestDetectShedding:
    def test_shed_then_catchup(self, tmp_path):
        """A disk-full episode that hits the detect writes: the first
        failure notes pressure (swallowed), subsequent rounds SHED the
        detect hook (counted), and once space returns the catch-up
        replays everything — the state converges to an unshed
        control."""
        from tpudas.integrity import resource as _resource
        from tpudas.testing import enospc_error

        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        src2, out2 = str(tmp_path / "src2"), str(tmp_path / "out2")
        _spool(src)
        _spool(src2)
        _drive(src2, out2, feed_third=True)  # control, no pressure
        # ENOSPC on every detect-artifact write AND on the recovery
        # probe: pressure flips in round 1 and STAYS (the probe keeps
        # failing), so round 2 sheds the hook
        plan = FaultPlan(
            FaultSpec("fs.write_enospc", at=1, times=9999,
                      exc=enospc_error(), match=".detect"),
            FaultSpec("fs.write_enospc", at=1, times=9999,
                      exc=enospc_error(), match=".space_probe"),
        )
        reg = MetricsRegistry()
        try:
            with use_registry(reg), install_fault_plan(plan):
                rounds = _drive(src, out, feed_third=True)
        finally:
            _resource.clear_pressure("test done")
        assert rounds == 2  # the stream itself never noticed
        assert reg.value("tpudas_detect_errors_total") == 1
        assert reg.value(
            "tpudas_integrity_writes_shed_total", writer="detect"
        ) >= 1
        # space returns: the next run's catch-up replays everything
        reg2 = MetricsRegistry()
        with use_registry(reg2):
            _drive(src, out, feed_third=True)
        assert reg2.value("tpudas_detect_catchup_rows_total") > 0
        assert _detect_sig(out) == _detect_sig(out2)


class TestSummaryStatus:
    def test_failure_and_shed_flip_ok(self, tmp_path):
        """A failing or shed detect hook must flip the republished
        health summary to ``ok: false`` (with ``last_error`` /
        ``shed``) instead of leaving the last good round's numbers in
        place forever."""
        import shutil

        from tpudas.detect.runner import (
            mark_detect_shed,
            run_detect_round,
        )

        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=3)
        _drive(src, out)
        shutil.rmtree(os.path.join(out, ".detect"))
        state = {}
        with install_fault_plan(FaultPlan(FaultSpec("detect.op", at=1))):
            run_detect_round(out, 1, [], state, operators=OPS,
                             step_sec=1.0)
        assert state["pipe"] is None
        assert state["summary"]["ok"] is False
        assert state["summary"]["last_error"]
        mark_detect_shed(state)
        assert state["summary"]["shed"] is True
        assert state["summary"]["ok"] is False
        # the replayed round converges and flips the status back
        run_detect_round(out, 2, [], state, operators=OPS,
                         step_sec=1.0)
        s = state["summary"]
        assert s["ok"] is True and s["shed"] is False
        assert s["last_error"] is None
        assert s["ledger_events"] > 0


class TestDefaultOff:
    def test_env_gate(self, tmp_path, monkeypatch):
        monkeypatch.delenv("TPUDAS_DETECT", raising=False)
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _spool(src, n_files=1)
        _drive(src, out, detect=None, pyramid=False)
        assert not os.path.isdir(os.path.join(out, ".detect"))
