"""Async pipelined ingest (ISSUE 15): the bounded prefetch pipeline.

Pins the PR-15 contracts:

- pipeline semantics: the producer never runs more than ``depth``
  slices ahead (bounded-queue backpressure), the feed order is
  deterministic under a slow producer, and a speculation miss is a
  counted perf event that degrades to a synchronous re-read — never a
  correctness event;
- byte identity: the async path's outputs AND serialized carry equal
  the synchronous loop's, for both engines, for f32 and raw-int16
  payloads, single-device and under a 4-way CPU mesh with
  ``engine="fused"`` (the acceptance smoke);
- in-kernel dequant: feeding raw int16 + qscale through the stream
  kernels is bit-identical to feeding host-dequantized float32 —
  unit level (cascade / fused / fft, mesh and single) and end to end
  (an int16 tdas spool vs the equivalent pre-dequantized f32 spool);
- gap-slice and no-progress paths flow through the async loop
  identically to the sync loop;
- crash equivalence: a ``KeyboardInterrupt`` landed at the
  ``stream.prefetch`` fault site (a kill with prefetched-but-unfed
  slices in flight) resumes byte-identically to a never-interrupted
  control — prefetched == never-read.
"""

import hashlib
import os
import threading
import time

import numpy as np
import pytest

from tpudas.core.timeutils import to_datetime64
from tpudas.io.registry import write_patch
from tpudas.io.spool import spool
from tpudas.proc.ingest import SlicePrefetcher, decode_payload, ingest_depth
from tpudas.proc.streaming import run_lowpass_realtime
from tpudas.testing import make_synthetic_spool, synthetic_patch

FS = 100.0
FILE_SEC = 30.0
NCH = 6
T0 = np.datetime64("2023-03-22T00:00:00")
SCALE = 1e-3


def _drive(src, out, engine=None, feed=0, mesh=None, n_init=6, **kw):
    """One realtime run; ``feed`` appends 2 files per injected sleep
    (continuing the spool after its ``n_init`` seed files) so the run
    spans several rounds."""
    state = {"fed": 0}

    def sleep(_):
        if state["fed"] < feed:
            state["fed"] += 1
            _append_files(src, n_init + (state["fed"] - 1) * 2, 2,
                          prefix=f"raw{state['fed']}")

    return run_lowpass_realtime(
        source=src,
        output_folder=out,
        start_time=T0,
        output_sample_interval=1.0,
        edge_buffer=10.0,
        process_patch_size=20,
        poll_interval=0.0,
        file_duration=0.0,
        sleep_fn=sleep,
        max_rounds=feed + 3,
        engine=engine,
        mesh=mesh,
        **kw,
    )


def _append_files(directory, start_index, count, prefix="raw",
                  fmt="dasdae", write_kwargs=None):
    make_synthetic_spool(
        directory, n_files=count, file_duration=FILE_SEC, fs=FS,
        n_ch=NCH, noise=0.01, format=fmt, prefix=prefix,
        write_kwargs=write_kwargs,
        start=T0 + np.timedelta64(int(start_index * FILE_SEC * 1e9), "ns"),
    )


def _folder_state(out):
    """(merged-content sha, carry-file sha): everything durable.
    Content is hashed per merged segment (a gap-skip run legitimately
    emits seams), independent of emission file boundaries."""
    h = hashlib.sha256()
    for p in spool(out).sort("time").update().chunk(time=None):
        h.update(
            np.asarray(p.coords["time"]).astype("datetime64[ns]")
            .tobytes()
        )
        h.update(
            np.ascontiguousarray(p.host_data(), dtype=np.float32)
            .tobytes()
        )
    carry = os.path.join(out, ".stream_carry.npz")
    with open(carry, "rb") as fh:
        return h.hexdigest(), hashlib.sha256(fh.read()).hexdigest()


# ---------------------------------------------------------------------------
# pipeline semantics against a scripted loader (no engine involved)


class _FakePatch:
    """The minimal patch surface the prefetcher touches."""

    def __init__(self, t_ns):
        self.coords = {"time": np.asarray(t_ns, np.int64).astype(
            "datetime64[ns]"
        )}

    def get_sample_step(self, _):
        return 0.01


class _FakeLFP:
    """Scripted ``_load_window``: contiguous 10 ms samples, one load
    log entry per call, optional per-call delay/hook."""

    def __init__(self, t0_ns=0, d_ns=10_000_000, delay=0.0):
        self.t0_ns = t0_ns
        self.d_ns = d_ns
        self.delay = delay
        self.loads = []
        self.timings = {"assemble_s": 0.0}
        self.on_load = None
        self._lock = threading.Lock()

    def _load_window(self, t_lo, t_hi, on_gap):
        lo = int(np.datetime64(t_lo, "ns").astype(np.int64))
        hi = int(np.datetime64(t_hi, "ns").astype(np.int64))
        with self._lock:
            self.loads.append((lo, hi, time.perf_counter()))
        if self.on_load is not None:
            self.on_load(lo, hi)
        if self.delay:
            time.sleep(self.delay)
        k0 = -(-(lo - self.t0_ns) // self.d_ns)  # first sample >= lo
        t = self.t0_ns + self.d_ns * np.arange(
            k0, hi // self.d_ns + 1, dtype=np.int64
        )
        t = t[(t >= lo) & (t <= hi)]
        return _FakePatch(t)

    def _time_major_payload(self, patch):
        n = len(patch.coords["time"])
        return np.zeros((n, 2), np.float32), None


class TestPrefetcherSemantics:
    SLICE = 1_000_000_000  # 1 s slices

    def _windows(self, fake, t2_ns, slice_ns):
        """The synchronous slice schedule over the scripted loader."""
        out = []
        cursor = 0
        while cursor <= t2_ns:
            hi = min(t2_ns, cursor + slice_ns)
            patch = fake._load_window(
                np.datetime64(cursor, "ns"), np.datetime64(hi, "ns"),
                "raise",
            )
            t = patch.coords["time"].astype(np.int64)
            nxt = int(t[-1]) + fake.d_ns if t.size else hi + 1
            cursor_next = hi + 1 if nxt <= cursor else nxt
            out.append((cursor, hi))
            cursor = cursor_next
        return out

    def test_backpressure_never_exceeds_depth(self):
        fake = _FakeLFP()
        ref = self._windows(_FakeLFP(), 10 * self.SLICE, self.SLICE)
        depth = 2
        pf = SlicePrefetcher(
            fake, 10 * self.SLICE, self.SLICE, "raise", depth, 0
        )
        try:
            consumed = 0
            for lo, hi in ref:
                # slow consumer: the producer must park at the bound
                time.sleep(0.02)
                item = pf.get(lo, hi)
                assert item is not None, "speculation missed on a " \
                    "contiguous stream"
                consumed += 1
                # invariant AT EVERY STEP: loads started never exceed
                # consumed + depth
                assert len(fake.loads) <= consumed + depth
            assert pf.stats["hits"] == len(ref)
            assert pf.stats["misses"] == 0
            assert pf.stats["max_ahead"] <= depth
        finally:
            pf.close()

    def test_feed_order_deterministic_under_slow_producer(self):
        fake = _FakeLFP(delay=0.02)  # producer slower than consumer
        ref = self._windows(_FakeLFP(), 6 * self.SLICE, self.SLICE)
        pf = SlicePrefetcher(
            fake, 6 * self.SLICE, self.SLICE, "raise", 3, 0
        )
        try:
            got = []
            for lo, hi in ref:
                item = pf.get(lo, hi)
                assert item is not None
                got.append((item.t_lo_ns, item.t_hi_ns))
            assert got == ref  # exact synchronous schedule, in order
            assert pf.stats["stall_s"] > 0  # consumer really waited
            assert fake.timings["assemble_s"] > 0  # charged to reader
        finally:
            pf.close()

    def test_miss_resync_recovers(self):
        fake = _FakeLFP()
        pf = SlicePrefetcher(
            fake, 10 * self.SLICE, self.SLICE, "raise", 2, 0
        )
        try:
            item = pf.get(0, self.SLICE)
            assert item is not None
            # consumer diverges from the speculated chain (as a gap
            # reset or rate change would): ask for a window the
            # producer did not predict
            weird_lo = 3 * self.SLICE + 777
            assert pf.get(weird_lo, weird_lo + self.SLICE) is None
            assert pf.stats["misses"] == 1
            # after resync, the chain re-establishes from the cursor
            pf.resync(weird_lo, fake.d_ns)
            item = pf.get(weird_lo, weird_lo + self.SLICE)
            assert item is not None and item.t_lo_ns == weird_lo
        finally:
            pf.close()

    def test_producer_error_is_raised_on_matching_window_only(self):
        fake = _FakeLFP()
        boom = RuntimeError("disk detached")

        def on_load(lo, hi):
            if lo == 0:
                raise boom

        fake.on_load = on_load
        pf = SlicePrefetcher(
            fake, 4 * self.SLICE, self.SLICE, "raise", 2, 0
        )
        try:
            with pytest.raises(RuntimeError, match="disk detached"):
                pf.get(0, self.SLICE)
        finally:
            pf.close()

    def test_depth_env_knob(self, monkeypatch):
        monkeypatch.delenv("TPUDAS_INGEST_PREFETCH", raising=False)
        assert ingest_depth() == 2
        monkeypatch.setenv("TPUDAS_INGEST_PREFETCH", "0")
        assert ingest_depth() == 0
        monkeypatch.setenv("TPUDAS_INGEST_PREFETCH", "5")
        assert ingest_depth() == 5
        monkeypatch.setenv("TPUDAS_INGEST_PREFETCH", "junk")
        assert ingest_depth() == 2


# ---------------------------------------------------------------------------
# async == sync byte identity, end to end


class TestAsyncSyncIdentity:
    @pytest.mark.parametrize("engine", ["auto", "fft"])
    @pytest.mark.slow
    def test_outputs_and_carry_identical(self, tmp_path, monkeypatch,
                                         engine):
        states = {}
        for mode, depth in (("sync", "0"), ("async", "3")):
            monkeypatch.setenv("TPUDAS_INGEST_PREFETCH", depth)
            src = str(tmp_path / f"src_{mode}_{engine}")
            out = str(tmp_path / f"out_{mode}_{engine}")
            _append_files(src, 0, 6)
            _drive(src, out, engine=engine, feed=2)
            states[mode] = _folder_state(out)
        assert states["sync"] == states["async"]

    @pytest.mark.slow
    def test_int16_spool_identical(self, tmp_path, monkeypatch):
        states = {}
        for mode, depth in (("sync", "0"), ("async", "3")):
            monkeypatch.setenv("TPUDAS_INGEST_PREFETCH", depth)
            src = str(tmp_path / f"src_{mode}")
            out = str(tmp_path / f"out_{mode}")
            _append_files(
                src, 0, 6, fmt="tdas",
                write_kwargs={"dtype": "int16", "scale": SCALE},
            )
            _drive(src, out, feed=2)
            states[mode] = _folder_state(out)
        assert states["sync"] == states["async"]

    @pytest.mark.slow
    def test_fused_mesh_smoke(self, tmp_path, monkeypatch):
        """The tier-1 acceptance smoke: async == sync on a 4-way CPU
        mesh with engine='fused' over a raw-int16 spool."""
        monkeypatch.setenv("TPUDAS_FUSED_MIN_ELEMS", "0")
        states = {}
        for mode, depth in (("sync", "0"), ("async", "2")):
            monkeypatch.setenv("TPUDAS_INGEST_PREFETCH", depth)
            src = str(tmp_path / f"src_{mode}")
            out = str(tmp_path / f"out_{mode}")
            _append_files(
                src, 0, 4, fmt="tdas",
                write_kwargs={"dtype": "int16", "scale": SCALE},
            )
            _drive(src, out, engine="fused", feed=1, mesh=4, n_init=4)
            states[mode] = _folder_state(out)
        assert states["sync"] == states["async"]

    def test_pipeline_metrics_emitted(self, tmp_path, monkeypatch):
        from tpudas.obs.phases import ingest_pipeline_snapshot
        from tpudas.obs.registry import MetricsRegistry, use_registry

        monkeypatch.setenv("TPUDAS_INGEST_PREFETCH", "2")
        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _append_files(src, 0, 6)
        reg = MetricsRegistry()
        with use_registry(reg):
            _drive(src, out, feed=1)
        snap = ingest_pipeline_snapshot(reg)
        assert snap["depth"] == 2
        assert snap["prefetched"] >= 1
        assert snap["hits"] >= 1
        assert 0 < snap["queue_peak"] <= 2


# ---------------------------------------------------------------------------
# in-kernel dequant: raw int16 == host-dequantized float32, bitwise


class TestInt16InKernelDequant:
    @pytest.fixture(scope="class")
    def block(self):
        rng = np.random.default_rng(7)
        raw = rng.integers(-3000, 3000, size=(4000, 12), dtype=np.int16)
        return raw, raw.astype(np.float32) * np.float32(SCALE)

    @pytest.mark.parametrize("engine", ["auto", "fused-xla"])
    @pytest.mark.parametrize("mesh_n", [0, 4])
    @pytest.mark.slow
    def test_cascade_stream_bitexact(self, block, engine, mesh_n):
        from tpudas.ops.fir import (
            cascade_decimate_stream,
            cascade_stream_init,
            design_cascade,
        )
        from tpudas.parallel.mesh import make_mesh

        raw, host = block
        mesh = make_mesh(mesh_n) if mesh_n else None
        plan = design_cascade(100.0, 10, 0.45, 4)
        y1, b1 = cascade_decimate_stream(
            host, cascade_stream_init(plan, 12), plan, engine, mesh=mesh
        )
        y2, b2 = cascade_decimate_stream(
            raw, cascade_stream_init(plan, 12), plan, engine, mesh=mesh,
            qscale=SCALE,
        )
        assert np.array_equal(np.asarray(y1), np.asarray(y2))
        for a, b in zip(b1, b2):
            assert np.array_equal(
                np.asarray(a)[:, :12], np.asarray(b)[:, :12]
            )

    @pytest.mark.parametrize("mesh_n", [0, 4])
    def test_fft_stream_bitexact(self, block, mesh_n):
        from tpudas.ops.filter import (
            fft_pass_filter_stream,
            fft_stream_init,
        )
        from tpudas.parallel.mesh import make_mesh

        raw, host = block
        mesh = make_mesh(mesh_n) if mesh_n else None
        a1, c1 = fft_pass_filter_stream(
            host[:1024], fft_stream_init(64, 12), 0.01, high=0.45,
            mesh=mesh,
        )
        a2, c2 = fft_pass_filter_stream(
            raw[:1024], fft_stream_init(64, 12), 0.01, high=0.45,
            mesh=mesh, qscale=SCALE,
        )
        assert np.array_equal(np.asarray(a1), np.asarray(a2))
        assert np.array_equal(
            np.asarray(c1)[:, :12], np.asarray(c2)[:, :12]
        )

    def test_qscale_rejects_non_int16(self):
        from tpudas.ops.fir import (
            cascade_decimate_stream,
            cascade_stream_init,
            design_cascade,
        )

        plan = design_cascade(100.0, 10, 0.45, 4)
        with pytest.raises(ValueError, match="qscale"):
            cascade_decimate_stream(
                np.zeros((100, 4), np.float32),
                cascade_stream_init(plan, 4), plan, "auto", qscale=1e-3,
            )

    def test_end_to_end_int16_matches_f32_spool(self, tmp_path,
                                                monkeypatch):
        """An int16 tdas spool streams byte-identically to a dasdae
        f32 spool holding the SAME (pre-dequantized) values — the
        in-kernel dequant is invisible in the product."""
        monkeypatch.setenv("TPUDAS_INGEST_PREFETCH", "2")
        src_q = str(tmp_path / "src_q")
        src_f = str(tmp_path / "src_f")
        os.makedirs(src_f)
        _append_files(
            src_q, 0, 5, fmt="tdas",
            write_kwargs={"dtype": "int16", "scale": SCALE},
        )
        # the f32 control: identical values, pre-dequantized on host
        t0 = to_datetime64(T0).astype("datetime64[ns]")
        step = np.timedelta64(int(round(1e9 / FS)), "ns")
        n = int(FILE_SEC * FS)
        for i in range(5):
            p = synthetic_patch(
                t0=t0 + i * n * step, duration=FILE_SEC, fs=FS,
                n_ch=NCH, seed=i, phase_origin=t0, noise=0.01,
            )
            data = np.asarray(p.host_data(), np.float32)
            quant = np.clip(
                np.round(data / SCALE), -32768, 32767
            ).astype(np.int16)
            deq = quant.astype(np.float32) * np.float32(SCALE)
            write_patch(
                p.new(data=deq), os.path.join(src_f, f"raw_{i:04d}.h5")
            )
        out_q = str(tmp_path / "out_q")
        out_f = str(tmp_path / "out_f")
        _drive(src_q, out_q)
        _drive(src_f, out_f)
        assert _folder_state(out_q)[0] == _folder_state(out_f)[0]


# ---------------------------------------------------------------------------
# gap-slice / no-progress paths through the async loop


class TestGapAndNoProgress:
    def test_gap_skip_identical_and_counted(self, tmp_path, monkeypatch):
        from tpudas.obs.registry import MetricsRegistry, use_registry

        states, counts = {}, {}
        for mode, depth in (("sync", "0"), ("async", "3")):
            monkeypatch.setenv("TPUDAS_INGEST_PREFETCH", depth)
            src = str(tmp_path / f"src_{mode}")
            out = str(tmp_path / f"out_{mode}")
            # files 0-1, a 2-file hole (60 s >> tolerance), files 4-5
            _append_files(src, 0, 2)
            _append_files(src, 4, 2, prefix="rawb")
            reg = MetricsRegistry()
            with use_registry(reg):
                _drive(src, out, on_gap="skip")
            states[mode] = _folder_state(out)[0]
            counts[mode] = reg.value("tpudas_stream_gap_skips_total")
        assert states["sync"] == states["async"]
        assert counts["sync"] == counts["async"] > 0

    def test_no_progress_slice_identical(self, tmp_path, monkeypatch):
        """A slice that yields only already-consumed samples forces
        the cursor forward identically in both modes (the
        stream_no_progress path; the forced skip then reads as a gap
        at the next slice, so the run needs the tolerant policy)."""
        from tpudas.proc.lfproc import LFProc
        from tpudas.utils.logging import set_log_handler

        orig = LFProc._load_window
        t0_ns = int(to_datetime64(T0).astype("datetime64[ns]")
                    .astype(np.int64))
        # the second 20 s slice of round 1 starts just past t0+20s;
        # replay the FIRST slice's window for it (old, already-consumed
        # samples only) — keyed by the requested window, so producer
        # and consumer see the same quirk deterministically
        sec = 1_000_000_000

        def quirky(self, t_lo, t_hi, on_gap):
            lo = int(np.datetime64(t_lo, "ns").astype(np.int64))
            if t0_ns + 20 * sec <= lo < t0_ns + 21 * sec:
                return orig(
                    self,
                    np.datetime64(t0_ns, "ns"),
                    np.datetime64(t0_ns + 10 * sec, "ns"),
                    on_gap,
                )
            return orig(self, t_lo, t_hi, on_gap)

        monkeypatch.setattr(LFProc, "_load_window", quirky)
        states, saw = {}, {}
        for mode, depth in (("sync", "0"), ("async", "3")):
            monkeypatch.setenv("TPUDAS_INGEST_PREFETCH", depth)
            src = str(tmp_path / f"src_{mode}")
            out = str(tmp_path / f"out_{mode}")
            _append_files(src, 0, 4)
            events = []
            set_log_handler(events.append)
            try:
                _drive(src, out, on_gap="skip")
            finally:
                set_log_handler(None)
            states[mode] = _folder_state(out)[0]
            saw[mode] = any(
                e["event"] == "stream_no_progress" for e in events
            )
        assert saw["sync"] and saw["async"]
        assert states["sync"] == states["async"]


# ---------------------------------------------------------------------------
# crash equivalence: a kill at stream.prefetch == never-read


class TestPrefetchCrashEquivalence:
    @pytest.mark.slow
    def test_ki_kill_at_prefetch_resumes_identically(self, tmp_path,
                                                     monkeypatch):
        from tpudas.resilience.faults import (
            FaultPlan,
            FaultSpec,
            install_fault_plan,
        )

        monkeypatch.setenv("TPUDAS_INGEST_PREFETCH", "3")
        # control: never interrupted
        ctrl_src = str(tmp_path / "ctrl_src")
        ctrl_out = str(tmp_path / "ctrl_out")
        _append_files(ctrl_src, 0, 6)
        _drive(ctrl_src, ctrl_out, feed=1)
        control = _folder_state(ctrl_out)

        src, out = str(tmp_path / "src"), str(tmp_path / "out")
        _append_files(src, 0, 6)
        plan = FaultPlan(
            FaultSpec("stream.prefetch", at=2, exc=KeyboardInterrupt)
        )
        with install_fault_plan(plan):
            with pytest.raises(KeyboardInterrupt):
                _drive(src, out, feed=1)
        assert plan.fired  # it really died mid-prefetch, slices queued
        # resume (no faults): prefetched-but-unfed slices must be
        # crash-equivalent to never-read
        _drive(src, out, feed=1)
        assert _folder_state(out) == control
