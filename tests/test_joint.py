"""JointProc (BASELINE config 5): one ingest pass, two products.

The LF product must be byte-identical to a plain LFProc run; the
rolling product must tile seam-free across window boundaries and equal
the pandas-semantics trailing mean computed on the merged raw stream.
"""

import os

import numpy as np
import pytest

from tpudas import spool
from tpudas.proc.joint import JointProc
from tpudas.proc.lfproc import LFProc
from tpudas.testing import make_synthetic_spool

FS = 100.0
T1 = np.datetime64("2023-03-22T00:00:00")
T2 = np.datetime64("2023-03-22T00:03:00")


@pytest.fixture(scope="module")
def raw_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("jointraw")
    make_synthetic_spool(
        d, n_files=6, file_duration=30.0, fs=FS, n_ch=6, noise=0.01
    )
    return str(d)


def run_joint(src, out_lf, out_roll, mesh=None, t2=T2, **params):
    cfg = dict(
        output_sample_interval=1.0,
        process_patch_size=60,
        edge_buff_size=10,
        rolling_window=2.0,
        rolling_step=1.0,
    )
    cfg.update(params)
    lfp = JointProc(spool(src).sort("time").update(), mesh=mesh)
    lfp.update_processing_parameter(**cfg)
    lfp.set_output_folder(str(out_lf), delete_existing=True)
    lfp.set_rolling_output_folder(str(out_roll), delete_existing=True)
    lfp.process_time_range(T1, t2)
    return lfp


def host_trailing_mean(data, taxis, w, s, emit_times):
    """float64-free reference: trailing mean at the emitted times."""
    out = []
    for t in emit_times:
        i = int(
            round(
                (t - taxis[0]) / np.timedelta64(1, "ns")
                / ((taxis[1] - taxis[0]) / np.timedelta64(1, "ns"))
            )
        )
        out.append(data[i - w + 1 : i + 1].mean(axis=0))
    return np.stack(out)


class TestJoint:
    def test_lf_product_byte_identical_to_plain_lfproc(
        self, raw_dir, tmp_path
    ):
        import filecmp

        jl = run_joint(raw_dir, tmp_path / "lf", tmp_path / "roll")
        assert jl.rolling_windows == sum(jl.engine_counts.values()) > 0
        plain = LFProc(spool(raw_dir).sort("time").update())
        plain.update_processing_parameter(
            output_sample_interval=1.0,
            process_patch_size=60,
            edge_buff_size=10,
        )
        plain.set_output_folder(str(tmp_path / "lf2"), delete_existing=True)
        plain.process_time_range(T1, T2)
        a = sorted(os.listdir(tmp_path / "lf"))
        b = sorted(os.listdir(tmp_path / "lf2"))
        assert a == b
        for n in a:
            assert filecmp.cmp(
                tmp_path / "lf" / n, tmp_path / "lf2" / n, shallow=False
            )

    def test_rolling_product_seam_free_and_correct(self, raw_dir, tmp_path):
        run_joint(raw_dir, tmp_path / "lf", tmp_path / "roll")
        merged = spool(str(tmp_path / "roll")).update().chunk(time=None)
        assert len(merged) == 1, "rolling product has a seam"
        p = merged[0]
        times = p.coords["time"]
        steps = np.diff(times) / np.timedelta64(1, "s")
        assert np.allclose(steps, 1.0)  # rolling_step
        # positions sit on the run's global grid (origin = bgtime)
        off = (times - T1.astype("datetime64[ns]")) / np.timedelta64(1, "s")
        assert np.allclose(off, np.round(off))
        # values equal the trailing mean over the merged raw stream
        raw = spool(raw_dir).update().chunk(time=None)[0]
        rax = raw.coords["time"]
        w = int(round(2.0 * FS))
        ref = host_trailing_mean(
            raw.host_data().astype(np.float64), rax, w, None, times
        )
        got = p.host_data()
        assert np.abs(got - ref).max() < 1e-5 * np.abs(ref).max() + 1e-7

    def test_rolling_product_is_complete_windows_only(
        self, raw_dir, tmp_path
    ):
        """Every emitted rolling sample has a COMPLETE trailing window
        (incomplete warm-up rows are never emitted — the baked-in
        equivalent of the reference's dropna("time")), even at the
        largest window the halo supports."""
        run_joint(raw_dir, tmp_path / "lf", tmp_path / "roll",
                  rolling_window=10.0)  # 1000 samples == the 10 s halo
        merged = spool(str(tmp_path / "roll")).update().chunk(time=None)
        assert len(merged) == 1
        p = merged[0]
        assert np.isfinite(p.host_data()).all()
        # first emitted sample sits a full window past the data start
        raw0 = spool(raw_dir).update()[0].attrs["time_min"]
        lead = (
            p.coords["time"][0].astype("datetime64[ns]")
            - raw0.astype("datetime64[ns]")
        ) / np.timedelta64(1, "s")
        assert lead >= 10.0 - 1.0 / FS

    def test_interior_window_halo_violation_raises(self, raw_dir, tmp_path):
        lfp = JointProc(spool(raw_dir).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=1.0,
            process_patch_size=60,
            edge_buff_size=2,       # 2 s halo
            rolling_window=5.0,     # needs 5 s of trailing history
        )
        lfp.set_output_folder(str(tmp_path / "lf"), delete_existing=True)
        lfp.set_rolling_output_folder(
            str(tmp_path / "roll"), delete_existing=True
        )
        with pytest.raises(ValueError, match="edge_buff_size"):
            lfp.process_time_range(T1, T2)

    @pytest.mark.slow
    def test_int16_payload_matches_f32(self, tmp_path):
        outs = {}
        for label, wk in (
            ("f32", None),
            ("i16", {"dtype": "int16", "scale": 1e-3}),
        ):
            d = tmp_path / f"raw_{label}"
            make_synthetic_spool(
                d, n_files=4, file_duration=30.0, fs=FS, n_ch=4,
                noise=0.01, format="tdas", write_kwargs=wk,
            )
            run_joint(
                str(d), tmp_path / f"lf_{label}", tmp_path / f"r_{label}",
                t2=np.datetime64("2023-03-22T00:02:00"),
            )
            outs[label] = (
                spool(str(tmp_path / f"r_{label}"))
                .update()
                .chunk(time=None)[0]
                .host_data()
            )
        scale = np.abs(outs["f32"]).max()
        # int16 quantization error bound: ~scale/2 per sample, averaged
        assert np.abs(outs["f32"] - outs["i16"]).max() < 2e-3 * scale + 1e-3

    @pytest.mark.slow
    def test_mesh_run_matches_single_device(self, raw_dir, tmp_path):
        from tpudas.parallel.mesh import make_mesh

        run_joint(raw_dir, tmp_path / "lf1", tmp_path / "r1")
        run_joint(
            raw_dir, tmp_path / "lf2", tmp_path / "r2",
            mesh=make_mesh(8),
        )
        a = (
            spool(str(tmp_path / "r1")).update().chunk(time=None)[0]
        ).host_data()
        b = (
            spool(str(tmp_path / "r2")).update().chunk(time=None)[0]
        ).host_data()
        # the sharded compilation may pick a different (but equally
        # valid) reduce_window summation tree than the single-device
        # one — near-equality, unlike the LF product's byte-equality
        assert np.abs(a - b).max() < 1e-6 * np.abs(a).max()


@pytest.mark.slow
def test_config5_width_50k_channels(tmp_path):
    """BASELINE config 5 WIDTH: the joint pipeline at 50,000 channels
    through the full product path (tdas int16 spool -> native assembly
    -> both device products -> HDF5), channels shardable over the
    8-device mesh. Reduced rate/duration on CPU; rate on silicon is
    the campaign's business."""
    from tpudas.parallel.mesh import make_mesh

    fs, n_ch = 25.0, 50_000
    d = tmp_path / "raw"
    make_synthetic_spool(
        d, n_files=2, file_duration=30.0, fs=fs, n_ch=n_ch, noise=0.01,
        format="tdas", write_kwargs={"dtype": "int16", "scale": 1e-3},
    )
    lfp = JointProc(
        spool(str(d)).sort("time").update(), mesh=make_mesh(8)
    )
    lfp.update_processing_parameter(
        output_sample_interval=1.0,
        process_patch_size=30,
        edge_buff_size=5,
        rolling_window=2.0,
        rolling_step=1.0,
    )
    lfp.set_output_folder(str(tmp_path / "lf"), delete_existing=True)
    lfp.set_rolling_output_folder(
        str(tmp_path / "roll"), delete_existing=True
    )
    lfp.process_time_range(
        np.datetime64("2023-03-22T00:00:00"),
        np.datetime64("2023-03-22T00:01:00"),
    )
    assert lfp.native_windows == sum(lfp.engine_counts.values()) > 0
    assert lfp.rolling_windows == lfp.native_windows
    for folder in ("lf", "roll"):
        merged = spool(str(tmp_path / folder)).update().chunk(time=None)
        assert len(merged) == 1
        p = merged[0]
        assert p.host_data().shape[p.dims.index("distance")] == n_ch
        assert np.isfinite(p.host_data()).all()


@pytest.mark.slow
def test_window_dp_carries_rolling_product(tmp_path):
    """The window-DP batched path emits the rolling product too (the
    per-window hook is bypassed; the DP flush loop calls it), with
    output equal to the serial joint run."""
    from tpudas.parallel.mesh import make_mesh
    from tpudas.utils.logging import set_log_handler

    d = tmp_path / "raw"
    make_synthetic_spool(
        d, n_files=6, file_duration=30.0, fs=FS, n_ch=6, noise=0.01
    )
    events = []
    set_log_handler(events.append)
    try:
        run_joint(str(d), tmp_path / "lf1", tmp_path / "r1")
        run_joint(
            str(d), tmp_path / "lf2", tmp_path / "r2",
            mesh=make_mesh(8, time_shards=2), window_dp=True,
        )
    finally:
        set_log_handler(None)
    assert [e for e in events if e["event"] == "window_dp_batch"], \
        "no DP batch actually ran"
    a = spool(str(tmp_path / "r1")).update().chunk(time=None)
    b = spool(str(tmp_path / "r2")).update().chunk(time=None)
    assert len(a) == 1 and len(b) == 1
    assert np.abs(
        a[0].host_data() - b[0].host_data()
    ).max() < 1e-6 * np.abs(a[0].host_data()).max()
