"""Graft entry contract: jittable single-chip step + multichip dryrun."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import __graft_entry__ as ge


def test_entry_jits_and_runs():
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    out = np.asarray(out)
    assert out.shape == (1024, 256)
    assert np.isfinite(out).all()


@pytest.mark.slow  # the round driver exercises this path on every run
@pytest.mark.parametrize("n", [4, 8])
def test_dryrun_multichip(n):
    # no device-count gate: the dryrun spawns its own clean-env child
    # with n virtual CPU devices, independent of this process's backend
    ge.dryrun_multichip(n)
