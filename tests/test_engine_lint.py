"""tools/check_engines.py wired into tier-1: every engine literal the
dispatch layers accept (LFProc config, stream-step kernels, batch
kernels) must appear in the test matrix — a selector that parses but
is never exercised cannot land."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_engines  # noqa: E402


def test_repo_is_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_engines.py")],
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "check_engines: OK" in proc.stdout


def test_accepted_sets_cover_the_fused_family():
    """The ISSUE-10 selector literals are part of the lint surface:
    dropping one from the dispatch tables silently would also drop it
    from the lint, so pin them here."""
    sets = check_engines.accepted_literals()
    assert "fused" in sets["LFProc._ENGINES"]
    for name in ("fused", "fused-xla", "fused-pallas"):
        assert name in sets["tpudas.ops.fir.STREAM_ENGINES"]


def test_untested_literal_detected(tmp_path, monkeypatch):
    """An accepted literal missing from the test sources is flagged."""
    (tmp_path / "tests").mkdir()
    (tmp_path / "tests" / "test_x.py").write_text(
        'ENGINES = ["auto", "fft"]\n'
    )
    problems = check_engines.lint(str(tmp_path))
    assert problems
    assert any("cascade" in p for p in problems)
