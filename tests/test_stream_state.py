"""Stateful streaming: the carried-filter-state execution mode.

Pins the PR-01 contracts:
- ops level: round-by-round stateful output equals the one-shot batch
  path across block boundaries, for BOTH engines (FIR cascade carry and
  FFT overlap-save carry);
- proc level: LFProc's resumable stream path matches the batch oracle
  and resumes seam-free from a serialized carry without rewinding;
- driver level: run_lowpass_realtime's stateful mode matches rewind
  mode numerically, eliminates the redundant re-reads the rewind pays,
  and survives kill/resume on O(1) state.
"""

import json
import os

import numpy as np
import pytest

from tpudas.io.spool import spool
from tpudas.core.timeutils import to_datetime64
from tpudas.io.registry import write_patch
from tpudas.proc.lfproc import LFProc
from tpudas.proc.streaming import run_lowpass_realtime
from tpudas.testing import make_synthetic_spool, synthetic_patch

FS = 100.0
FILE_SEC = 30.0
NCH = 6
T0 = np.datetime64("2023-03-22T00:00:00")


def _append_files(directory, start_index, count):
    t0 = T0.astype("datetime64[ns]")
    step = np.timedelta64(int(round(1e9 / FS)), "ns")
    n = int(FILE_SEC * FS)
    for i in range(start_index, start_index + count):
        p = synthetic_patch(
            t0=t0 + i * n * step, duration=FILE_SEC, fs=FS, n_ch=NCH,
            seed=i, phase_origin=t0, noise=0.01,
        )
        write_patch(p, os.path.join(directory, f"raw_{i:04d}.h5"))


def _common_interior(a, b):
    lo = max(a.coords["time"][0], b.coords["time"][0])
    hi = min(a.coords["time"][-1], b.coords["time"][-1])
    av = a.select(time=(lo, hi)).host_data()
    bv = b.select(time=(lo, hi)).host_data()
    assert av.shape == bv.shape and av.size > 0
    return av, bv


class TestCascadeStreamOps:
    @pytest.mark.parametrize("fs,ratio", [(100.0, 100), (200.0, 40),
                                          (50.0, 7)])
    @pytest.mark.slow
    def test_stream_matches_batch_across_blocks(self, fs, ratio):
        """Concatenated streamed outputs equal the one-shot causal
        cascade after the warm-up, across uneven block boundaries."""
        from tpudas.ops.fir import (
            cascade_decimate,
            cascade_decimate_stream,
            cascade_stream_init,
            design_cascade,
            stream_warmup_outputs,
        )

        plan = design_cascade(fs, ratio, 0.45 * fs / ratio, 4)
        warm = stream_warmup_outputs(plan)
        rng = np.random.default_rng(0)
        blocks = [
            rng.standard_normal((n * ratio, 3)).astype(np.float32)
            for n in (50, 13, 1, 27, 40)
        ]
        x = np.concatenate(blocks, axis=0)
        carry = cascade_stream_init(plan, 3)
        outs = []
        for b in blocks:
            y, carry = cascade_decimate_stream(b, carry, plan)
            outs.append(np.asarray(y))
        ys = np.concatenate(outs, axis=0)
        n_cmp = ys.shape[0] - warm
        assert n_cmp > 20  # the warm-up must not consume the test
        ref = np.asarray(
            cascade_decimate(x, plan, plan.delay, n_cmp, engine="xla")
        )
        err = np.abs(ys[warm:] - ref).max() / np.abs(ref).max()
        assert err < 1e-5

    def test_warmup_is_one_receptive_field_minus_one_output(self):
        """The carry's mechanical lag telescopes to the receptive field
        minus one output step (+ grid-alignment pad)."""
        from tpudas.ops.fir import design_cascade, stream_warmup_outputs

        plan = design_cascade(100.0, 100, 0.45, 4)
        warm = stream_warmup_outputs(plan)
        min_lag = plan.receptive_field - 1 - (plan.ratio - 1)
        assert warm * plan.ratio >= min_lag
        assert warm * plan.ratio < min_lag + plan.ratio

    def test_block_and_carry_validation(self):
        from tpudas.ops.fir import (
            cascade_decimate_stream,
            cascade_stream_init,
            design_cascade,
        )

        plan = design_cascade(100.0, 10, 4.5, 4)
        carry = cascade_stream_init(plan, 2)
        with pytest.raises(ValueError, match="multiple of"):
            cascade_decimate_stream(np.zeros((15, 2), np.float32), carry,
                                    plan)
        bad = tuple(b[:-1] for b in carry)
        with pytest.raises(ValueError, match="carry"):
            cascade_decimate_stream(np.zeros((20, 2), np.float32), bad,
                                    plan)


class TestFFTStreamOps:
    def test_overlap_save_matches_batch(self):
        from tpudas.ops.filter import (
            fft_pass_filter,
            fft_pass_filter_stream,
            fft_stream_init,
        )

        rng = np.random.default_rng(1)
        edge = 400
        blocks = [
            rng.standard_normal((n, 4)).astype(np.float32)
            for n in (900, 512, 777, 1200)
        ]
        x = np.concatenate(blocks)
        carry = fft_stream_init(edge, 4)
        outs = []
        for b in blocks:
            y, carry = fft_pass_filter_stream(
                b, carry, 0.01, high=5.0, order=4
            )
            outs.append(np.asarray(y))
        ys = np.concatenate(outs)
        # streamed position i lags the input by `edge`; skip the
        # stream-start region in both (each has its own edge there)
        ref = np.asarray(fft_pass_filter(x, 0.01, high=5.0, order=4))
        a = ys[2 * edge:]
        b = ref[edge : edge + a.shape[0]]
        assert np.abs(a - b).max() / np.abs(b).max() < 1e-4


class TestLFProcStream:
    @pytest.fixture()
    def source(self, tmp_path):
        src = str(tmp_path / "src")
        make_synthetic_spool(
            src, n_files=5, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        return src

    def _batch(self, source, out, **params):
        sp = spool(source).sort("time").update()
        tmax = np.datetime64(sp.get_contents()["time_max"].max())
        lfp = LFProc(sp)
        lfp.update_processing_parameter(**params)
        lfp.set_output_folder(out, delete_existing=True)
        lfp.process_time_range(T0, tmax)
        return spool(out).update().chunk(time=None)[0], tmax

    @pytest.mark.parametrize(
        "dt,tol,kind",
        [
            (1.0, 1e-4, "cascade"),  # ratio 100: sample-aligned grid
            (1.1, 2e-3, "fft"),  # ratio 110 = 2*5*11: prime > 8
        ],
    )
    @pytest.mark.slow
    def test_incremental_matches_batch_oracle(self, source, tmp_path, dt,
                                              tol, kind):
        params = dict(
            output_sample_interval=dt,
            process_patch_size=40,
            edge_buff_size=8,
        )
        ref, tmax = self._batch(source, str(tmp_path / "batch"), **params)
        sp = spool(source).sort("time").update()
        lfp = LFProc(sp)
        lfp.update_processing_parameter(**params)
        out = str(tmp_path / "stream")
        lfp.set_output_folder(out, delete_existing=True)
        carry = lfp.open_stream(T0)
        for t2 in (
            T0 + np.timedelta64(50, "s"),
            T0 + np.timedelta64(100, "s"),
            tmax,
        ):
            lfp.process_stream_increment(carry, t2)
        assert carry.kind == kind
        merged = spool(out).update().chunk(time=None)
        assert len(merged) == 1, "incremental output has seams"
        av, bv = _common_interior(merged[0], ref)
        assert np.abs(av - bv).max() / np.abs(bv).max() < tol

    def test_serialized_resume_is_seam_free(self, source, tmp_path):
        from tpudas.proc.stream import load_carry, save_carry

        params = dict(
            output_sample_interval=1.0,
            process_patch_size=40,
            edge_buff_size=8,
        )
        ref, tmax = self._batch(source, str(tmp_path / "batch"), **params)
        out = str(tmp_path / "stream")
        sp = spool(source).sort("time").update()
        lfp = LFProc(sp)
        lfp.update_processing_parameter(**params)
        lfp.set_output_folder(out, delete_existing=True)
        carry = lfp.open_stream(T0)
        lfp.process_stream_increment(carry, T0 + np.timedelta64(80, "s"))
        save_carry(carry, out)

        # a fresh process: new LFProc, carry reloaded from disk
        c2 = load_carry(out)
        assert c2 is not None
        assert c2.kind == carry.kind
        assert c2.next_emit_ns == carry.next_emit_ns
        assert c2.next_ingest_ns == carry.next_ingest_ns
        for a, b in zip(c2.bufs, carry.bufs):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        lfp2 = LFProc(spool(source).sort("time").update())
        lfp2.update_processing_parameter(**params)
        lfp2.set_output_folder(out, delete_existing=False)
        lfp2.process_stream_increment(c2, tmax)
        merged = spool(out).update().chunk(time=None)
        assert len(merged) == 1, "resumed output has a seam"
        av, bv = _common_interior(merged[0], ref)
        assert np.abs(av - bv).max() / np.abs(bv).max() < 1e-4


class TestStatefulRealtime:
    def _run(self, src, out, stateful, fed_state=None, counters=None,
             events=None):
        from tpudas.utils.logging import set_log_handler

        state = fed_state if fed_state is not None else {"fed": 1}

        def fake_sleep(_):
            if state["fed"] < 1:
                _append_files(src, 3, 2)
                state["fed"] += 1

        if events is not None:
            set_log_handler(events.append)
        try:
            return run_lowpass_realtime(
                source=src,
                output_folder=out,
                start_time=str(T0),
                output_sample_interval=1.0,
                edge_buffer=8.0,
                process_patch_size=40,
                poll_interval=0.0,
                file_duration=0.0,
                sleep_fn=fake_sleep,
                counters=counters,
                stateful=stateful,
            )
        finally:
            if events is not None:
                set_log_handler(None)

    @pytest.mark.slow
    def test_stateful_matches_rewind_and_kills_redundancy(self, tmp_path):
        from tpudas.utils.profiling import Counters

        outs = {}
        ctr = {}
        for mode, flag in (("rewind", False), ("stateful", True)):
            src = str(tmp_path / f"raw_{mode}")
            make_synthetic_spool(
                src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
                noise=0.01,
            )
            out = str(tmp_path / mode)
            ctr[mode] = Counters()
            rounds = self._run(
                src, out, flag, fed_state={"fed": 0}, counters=ctr[mode]
            )
            assert rounds == 2
            merged = spool(out).update().chunk(time=None)
            assert len(merged) == 1
            outs[mode] = merged[0]
        # the structural claim: rewind re-reads the edge buffer every
        # resumed round, the carry reads nothing twice
        assert ctr["rewind"].samples_redundant > 0
        assert ctr["rewind"].redundant_ratio > 0.1
        assert ctr["stateful"].samples_redundant == 0
        assert ctr["stateful"].redundant_ratio == 0.0
        av, bv = _common_interior(outs["stateful"], outs["rewind"])
        assert np.abs(av - bv).max() / np.abs(bv).max() < 1e-4

    def test_kill_and_resume_does_not_rewind(self, tmp_path):
        """Two separate driver invocations (process kill/restart): the
        second resumes from the serialized carry — no rewind, no
        re-read — and the joined output is seam-free and matches the
        one-shot batch oracle."""
        from tpudas.utils.profiling import Counters

        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        # run 1 processes the initial 90 s, then the "process dies"
        assert self._run(src, out, True) == 1
        assert os.path.isfile(os.path.join(out, ".stream_carry.npz"))
        n_files_run1 = len(
            [f for f in os.listdir(out) if f.endswith(".h5")]
        )
        assert n_files_run1 > 0

        # two more files arrive while it was down; run 2 resumes
        _append_files(src, 3, 2)
        events = []
        ctr = Counters()
        assert self._run(src, out, True, counters=ctr, events=events) >= 1
        rt = [e for e in events if e["event"] == "realtime_round"]
        assert all(e["mode"] == "stateful" for e in rt)
        assert [e for e in events if e["event"] == "stream_resume"]
        # no rewind: run 2 ingested only the NEW 60 s (ns-jitter slack)
        assert ctr.data_seconds <= 61.0
        assert ctr.samples_redundant == 0

        merged = spool(out).update().chunk(time=None)
        assert len(merged) == 1, "resumed stream has a seam"
        steps = np.diff(merged[0].coords["time"].astype(np.int64))
        assert np.all(steps == 1_000_000_000)

        # oracle: one-shot batch run over the final stream
        sp = spool(src).sort("time").update()
        lfp = LFProc(sp)
        lfp.update_processing_parameter(
            output_sample_interval=1.0,
            process_patch_size=40,
            edge_buff_size=8,
        )
        lfp.set_output_folder(str(tmp_path / "batch"), delete_existing=True)
        lfp.process_time_range(
            T0, np.datetime64(sp.get_contents()["time_max"].max())
        )
        ref = spool(str(tmp_path / "batch")).update().chunk(time=None)[0]
        av, bv = _common_interior(merged[0], ref)
        assert np.abs(av - bv).max() / np.abs(bv).max() < 1e-4

    def test_crash_between_write_and_save_reconciles(self, tmp_path):
        """Output files newer than the carry (crash after the round's
        writes, before its carry save) are deleted on resume and
        regenerated identically — the crash-only contract on O(1)
        state."""
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        assert self._run(src, out, True) == 1
        from tpudas.proc.stream import load_carry

        carry = load_carry(out)
        # simulate the crashed round's partial emission: a file past
        # the carry's recorded head
        stray_t0 = np.datetime64(int(carry.last_emit_ns), "ns") + \
            np.timedelta64(3600, "s")
        stray = synthetic_patch(
            t0=stray_t0, duration=5.0, fs=1.0, n_ch=NCH, seed=9
        )
        stray_name = "LFDAS_2023-03-23T000000.0_2023-03-23T000005.0.h5"
        write_patch(stray, os.path.join(out, stray_name))
        _append_files(src, 3, 2)
        events = []
        assert self._run(src, out, True, events=events) >= 1
        assert not os.path.exists(os.path.join(out, stray_name))
        assert [
            e for e in events if e["event"] == "stream_reconcile_removed"
        ]
        merged = spool(out).update().chunk(time=None)
        assert len(merged) == 1

    def test_resume_with_changed_config_is_rejected(self, tmp_path):
        """A persisted carry continues ITS grid — restarting with a
        moved start_time (or another engine) must raise instead of
        silently ignoring the new setting."""
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        assert self._run(src, out, True) == 1
        _append_files(src, 3, 1)
        with pytest.raises(ValueError, match="different start_time"):
            run_lowpass_realtime(
                source=src,
                output_folder=out,
                start_time=str(T0 + np.timedelta64(30, "s")),
                output_sample_interval=1.0,
                edge_buffer=8.0,
                process_patch_size=40,
                poll_interval=0.0,
                sleep_fn=lambda _: None,
                stateful=True,
            )

    @pytest.mark.slow
    def test_rewind_write_invalidates_stale_carry(self, tmp_path):
        """A rewind-mode round over a stateful folder removes the
        persisted carry (a later stateful resume must not reconcile
        valid rewind-written outputs away against stale state) and
        CONTINUES from the folder head — no stateful-era product is
        deleted or rewritten, and the joined stream stays seam-free."""
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        assert self._run(src, out, True) == 1
        assert os.path.isfile(os.path.join(out, ".stream_carry.npz"))
        stateful_files = {
            f for f in os.listdir(out) if f.endswith(".h5")
        }
        # new data processed by a rewind-mode run (e.g. the operator
        # flipped TPUDAS_STREAM_STATEFUL=0): the carry must go, the
        # stateful-era outputs must all survive
        _append_files(src, 3, 1)
        assert self._run(src, out, False) == 1
        assert not os.path.isfile(os.path.join(out, ".stream_carry.npz"))
        files_after_rewind = {
            f for f in os.listdir(out) if f.endswith(".h5")
        }
        assert stateful_files <= files_after_rewind
        assert len(spool(out).update().chunk(time=None)) == 1
        # back to stateful: legacy fallback, and still no deletions
        _append_files(src, 4, 1)
        assert self._run(src, out, True) == 1
        remaining = {f for f in os.listdir(out) if f.endswith(".h5")}
        assert files_after_rewind <= remaining
        merged = spool(out).update().chunk(time=None)
        assert len(merged) == 1

    def test_legacy_folder_without_carry_falls_back_to_rewind(
        self, tmp_path
    ):
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        # a rewind-mode run leaves outputs but no carry
        assert self._run(src, out, False) == 1
        assert not os.path.exists(os.path.join(out, ".stream_carry.npz"))
        _append_files(src, 3, 2)
        events = []
        assert self._run(src, out, True, events=events) >= 1
        rt = [e for e in events if e["event"] == "realtime_round"]
        assert rt and all(e["mode"] == "rewind" for e in rt)
        assert [e for e in events if e["event"] == "stream_legacy_rewind"]
        merged = spool(out).update().chunk(time=None)
        assert len(merged) == 1  # the rewind resume is still seam-free

    def test_env_flag_restores_rewind(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPUDAS_STREAM_STATEFUL", "0")
        src = str(tmp_path / "raw")
        out = str(tmp_path / "results")
        make_synthetic_spool(
            src, n_files=3, file_duration=FILE_SEC, fs=FS, n_ch=NCH,
            noise=0.01,
        )
        events = []
        assert self._run(src, out, None, events=events) == 1
        rt = [e for e in events if e["event"] == "realtime_round"]
        assert rt and all(e["mode"] == "rewind" for e in rt)
        assert not os.path.exists(os.path.join(out, ".stream_carry.npz"))


class TestStreamBench:
    @pytest.mark.slow
    def test_bench_reports_the_structural_win(self, tmp_path):
        """The PR's acceptance bench: >= 1.5x fewer full-rate samples
        per steady-state round, matching outputs, zero redundancy in
        stateful mode."""
        import tools.stream_bench as sb

        out = str(tmp_path / "BENCH_stream.json")
        report = sb.run(out, rounds=3, files_per_round=2)
        assert os.path.isfile(out)
        with open(out) as fh:
            on_disk = json.load(fh)
        assert on_disk["samples_ratio"] == report["samples_ratio"]
        assert report["samples_ratio"] >= 1.5
        assert report["outputs_match"]
        assert report["redundant_ratio_stateful"] == 0.0
        assert report["redundant_ratio_rewind"] > 0.2
        assert report["config"]["edge_over_window"] >= 0.5
