"""dascore.utils shim."""

from dascore.utils import mapping

__all__ = ["mapping"]
