"""dascore.utils.mapping shim (``FrozenDict`` — reference lf_das.py:12)."""

from tpudas.core.mapping import FrozenDict

__all__ = ["FrozenDict"]
