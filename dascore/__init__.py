"""DASCore-compatible API shim backed by tpudas.

The four reference notebooks (and lf_das.py itself) consume DASCore as
``import dascore as dc`` (SURVEY.md §2.3). This package re-exports the
tpudas implementations under that name so those workflows run unchanged
against the TPU engine. No DASCore code is used — everything resolves to
tpudas.
"""

from tpudas import (
    Patch,
    spool,
    to_datetime64,
    to_timedelta64,
    __version__,
)
from dascore import units, utils

__all__ = [
    "Patch",
    "spool",
    "to_datetime64",
    "to_timedelta64",
    "units",
    "utils",
    "__version__",
]
