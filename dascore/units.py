"""dascore.units shim → tpudas.core.units (``from dascore.units import s``)."""

from tpudas.core.units import Quantity, Unit, ns, us, ms, s, minute, h, get_seconds

__all__ = ["Quantity", "Unit", "ns", "us", "ms", "s", "minute", "h", "get_seconds"]
