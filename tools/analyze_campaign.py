"""Digest a chip-campaign run (chip_r05/) into decisions.

Parses the campaign logs and prints: the Mosaic verdict on the v2
kernel, the numerics table, the winning stage-0 geometry per payload
(and the env defaults to bake), the bench headline vs the 29.06 G
record and the roofline, the e2e bottleneck breakdown, and the
pallas/xla crossover recommendation for ``_pallas_stage_ok``.

Run after ``tools/chip_campaign.sh``: ``python tools/analyze_campaign.py``
"""

from __future__ import annotations

import json
import os
import re
import sys

OUT = sys.argv[1] if len(sys.argv) > 1 else "chip_r05"


def _read(name: str) -> str:
    try:
        with open(os.path.join(OUT, name)) as fh:
            return fh.read()
    except OSError:
        return ""


def main() -> None:
    if not os.path.isdir(OUT):
        print(f"no {OUT}/ directory — run tools/chip_campaign.sh first")
        return

    print(f"=== campaign digest ({OUT}) ===\n")

    # 1. chip_check: numerics verdicts
    cc = _read("chip_check.log")
    if cc:
        fails = [ln for ln in cc.splitlines() if "FAIL" in ln]
        oks = [ln for ln in cc.splitlines() if "(OK)" in ln]
        print("chip_check:")
        for ln in oks + fails:
            print("  " + ln.strip())
        for ln in cc.splitlines():
            if re.match(r"stage0 (f32|i16):", ln.strip()):
                print("  " + ln.strip())
        if "Mosaic is NOT exercised" in cc or "backend=cpu" in cc:
            # interpret-mode numbers say nothing about the compiled
            # kernel — never report a Mosaic verdict off them
            print("  => v2 Mosaic verdict: UNTESTED (cpu/interpret "
                  "run — the log itself disclaims it)\n")
        else:
            # any FAIL from the v2 checks disqualifies (int16 and the
            # cascade exercise the same kernel); only the v1
            # fallback-tier lines are excluded from the verdict
            v2_fails = [ln for ln in fails if "stage0 v1" not in ln]
            v2_ok = bool(oks) and not v2_fails
            print(f"  => v2 Mosaic verdict: "
                  f"{'ACCEPTED' if v2_ok else 'REJECTED/FAILED'}\n")
    else:
        print("chip_check: no log\n")

    # 2. stage-0 sweep: best geometry per payload.  Reads both the
    # campaign1 single-log form (perf_stage0.log) and the campaign2
    # tagged per-row form (sweep.log); tagged rows carry the knob/impl
    # experiment envs in [brackets] and are ranked separately from the
    # plain geometry rows (only the latter drive the bake line).
    ps = _read("perf_stage0.log") + "\n" + _read("sweep.log")
    if ps.strip():
        best: dict = {}
        best_tagged: dict = {}
        for m in re.finditer(
            r"pallas (f32|i16) kb=(\d+) cb=(\d+)(?: \[([^\]]*)\])?"
            r"\s+[\d.]+ ms/win\s+"
            r"([\d.]+) G ch-samp/s\s+([\d.]+) GB/s",
            ps,
        ):
            pay, kb, cb, tag, gsps, gbps = m.groups()
            rec = (float(gsps), int(kb), int(cb), float(gbps), tag or "")
            target = best_tagged if tag else best
            if pay not in target or rec[0] > target[pay][0]:
                target[pay] = rec
        ceiling = re.search(
            r"read-ceiling \(sum\)\s+[\d.]+ ms/win\s+[\d.]+ G ch-samp/s"
            r"\s+([\d.]+) GB/s", ps,
        )
        print("stage-0 sweep:")
        if ceiling:
            print(f"  harness read ceiling: {ceiling.group(1)} GB/s")
        for pay, (gsps, kb, cb, gbps, _) in sorted(best.items()):
            print(f"  best {pay}: kb={kb} cb={cb} -> {gsps:.2f} G "
                  f"ch-samp/s ({gbps:.0f} GB/s)")
        for pay, (gsps, kb, cb, gbps, tag) in sorted(best_tagged.items()):
            print(f"  best tagged {pay}: kb={kb} cb={cb} [{tag}] -> "
                  f"{gsps:.2f} G ch-samp/s ({gbps:.0f} GB/s)")
        for m in re.finditer(
            r"(conv-\w+) f32\s+[\d.]+ ms/win\s+([\d.]+) G ch-samp/s"
            r"\s+([\d.]+) GB/s", ps,
        ):
            print(f"  {m.group(1)}: {m.group(2)} G ch-samp/s "
                  f"({m.group(3)} GB/s)")
        if "f32" in best:
            gsps, kb, cb, gbps, _ = best["f32"]
            print(f"  => bake: TPUDAS_PALLAS_P={max(kb // 128, 1)} "
                  f"TPUDAS_PALLAS_CB={cb}")
            if "f32" in best_tagged and best_tagged["f32"][0] > gsps:
                tg = best_tagged["f32"]
                print(f"  => NOTE: tagged row [{tg[4]}] beats every "
                      f"plain geometry ({tg[0]:.2f} > {gsps:.2f} G) — "
                      "consider baking that knob as default")
            print(f"  => P-stream hypothesis "
                  f"{'HOLDS' if gbps > 230 else 'does NOT hold'} "
                  f"(target >230 GB/s; single-stream wall ~185)")
        print()
    else:
        print("perf_stage0/sweep: no log\n")

    # 3. bench headline
    for name, label in (("bench_stdout.log", "bench headline"),
                        ("e2e10k.log", "e2e @10k int16"),
                        ("e2e_joint.log", "e2e joint")):
        txt = _read(name)
        line = None
        for ln in txt.splitlines():
            if ln.startswith("{") and '"metric"' in ln:
                line = ln
        if not line:
            print(f"{label}: no JSON\n")
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            print(f"{label}: unparseable JSON\n")
            continue
        print(f"{label}:")
        print(f"  value: {d.get('value'):.4g} {d.get('unit', '')} "
              f"({d.get('vs_baseline')}x baseline)")
        if "hbm_frac" in d:
            print(f"  hbm: {d.get('hbm_gbps')} GB/s "
                  f"({100 * d['hbm_frac']:.1f}% of peak; "
                  "VERDICT r4 target: >=40% => >=60 G ch-samp/s)")
            v = d.get("value", 0)
            print(f"  vs r04 record 29.06e9: {v / 29.06e9:.2f}x")
        if "engines" in d:
            print(f"  engines: {d['engines']}")
        if "int16" in d:
            print(f"  int16: {d['int16']}")
        if "phase_rates" in d:
            print(f"  phase rates: {d['phase_rates']}")
        if "error" in d:
            print(f"  ERROR: {d['error']}")
        print()

    # 4. crossover
    rt = _read("retune.log")
    if rt:
        tail = [ln for ln in rt.splitlines()
                if "pallas win" in ln or "xla win" in ln
                or "threshold" in ln]
        print("pallas/xla crossover (retune _pallas_stage_ok):")
        for ln in tail:
            print("  " + ln.strip())
        print()

    # 5. HBM per window
    hp = _read("hbm_probe.log")
    if hp:
        worst = re.search(r"worst measured processing factor: ([\d.]+)", hp)
        print("hbm probe:")
        for ln in hp.splitlines():
            if ln.startswith("{"):
                print("  " + ln.strip())
        if worst:
            print(f"  => worst factor {worst.group(1)} vs the memory "
                  "model's 5 x 1.2 — fill PERF.md §7's table")
        print()

    print("next: bake winning defaults into tpudas/ops/pallas_fir.py, "
          "retune _pallas_stage_ok if the crossover moved, update "
          "PERF.md §3/§7, commit BENCH_r05_midround.json")


if __name__ == "__main__":
    main()
