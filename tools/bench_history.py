"""Benchmark trajectory across the PR sequence + a regression gate.

Every perf PR leaves a ``BENCH_pr*.json`` at the repo root — each with
its own schema (the metric IS the PR's story), which is why nothing so
far could answer "did PR N regress what PR N-3 won?".  This tool gives
the BENCH_pr*.json trail two read sides:

**Trajectory** (default)::

    python tools/bench_history.py            # table over BENCH_pr*.json
    python tools/bench_history.py --json     # machine-readable

  One row per BENCH file: the PR tag, its metric/bench name, the
  ``ok`` flag, bench wall seconds, and the file's *headline figures* —
  numeric leaves whose key matches the well-known perf vocabulary
  (``realtime_factor``, ``*speedup*``, ``overhead_pct``,
  ``utilization``, ...) — so the cross-PR trend is one table even
  though every schema differs.

**Gate** (``--gate NEW --against OLD``)::

    python tools/bench_history.py --gate BENCH_pr17.json \
        --against BENCH_pr16.json --tolerance 0.15

  Compares every headline path the two files SHARE, with direction
  inferred from the key: ``speedup`` / ``realtime`` / ``factor`` /
  ``utilization`` / ``throughput`` are higher-is-better; ``overhead``
  / ``seconds`` / ``wall`` / ``lag`` / ``spread`` lower-is-better;
  ambiguous keys are reported but never gate.  Exit 1 when any shared
  figure is worse by more than ``--tolerance`` (relative), exit 0
  otherwise — cheap enough for CI, honest enough to catch a perf PR
  quietly unwinding an earlier one.  Disjoint schemas simply share
  nothing: the gate passes and says so.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

__all__ = [
    "compare_headlines",
    "extract_headlines",
    "load_bench",
    "trajectory",
]

# the perf vocabulary: key regex -> direction ("up" = higher is
# better, "down" = lower is better, None = report-only)
_HEADLINE_PATTERNS = (
    (re.compile(r"speedup", re.I), "up"),
    (re.compile(r"realtime", re.I), "up"),
    (re.compile(r"rt_factor|_rt$|^rt$", re.I), "up"),
    (re.compile(r"throughput", re.I), "up"),
    (re.compile(r"qps", re.I), "up"),
    (re.compile(r"hit_rate", re.I), "up"),
    (re.compile(r"utilization", re.I), "up"),
    (re.compile(r"overhead", re.I), "down"),
    (re.compile(r"lag", re.I), "down"),
    (re.compile(r"drain", re.I), "up"),
    (re.compile(r"repair", re.I), "up"),
    (re.compile(r"spread", re.I), "down"),
    (re.compile(r"(^|_)p(50|90|95|99)(_|$)", re.I), "down"),
    (re.compile(r"(wall|_seconds|_s)$", re.I), "down"),
)
# structural keys never treated as headlines even when numeric
_SKIP_KEYS = re.compile(
    r"^(fs|fs_hz|n_ch|channels|rounds|streams|seed|order|ratio|"
    r"cycles|epochs|kills|window|limit|depth|width|widths|N|n)$"
)


def _direction(key: str):
    for pat, d in _HEADLINE_PATTERNS:
        if pat.search(key):
            return d
    return None


def extract_headlines(doc, prefix="") -> dict:
    """``{dotted.path: (value, direction)}`` for every numeric leaf
    whose own key matches the perf vocabulary.  Lists index as
    ``path[i]`` so sweep legs stay distinct and comparable."""
    out: dict = {}
    if isinstance(doc, dict):
        for k, v in doc.items():
            path = f"{prefix}.{k}" if prefix else str(k)
            if isinstance(v, (dict, list)):
                out.update(extract_headlines(v, path))
            elif isinstance(v, bool):
                continue
            elif isinstance(v, (int, float)):
                if _SKIP_KEYS.match(str(k)):
                    continue
                d = _direction(str(k))
                if d is not None:
                    out[path] = (float(v), d)
    elif isinstance(doc, list):
        for i, v in enumerate(doc):
            out.update(extract_headlines(v, f"{prefix}[{i}]"))
    return out


def load_bench(path: str) -> dict:
    with open(path) as fh:
        return json.load(fh)


def _pr_tag(path: str) -> str:
    name = os.path.basename(path)
    m = re.match(r"BENCH_(pr\d+|r\d+\w*)\.json", name)
    return m.group(1) if m else name


def _bench_name(doc: dict) -> str:
    for key in ("metric", "bench", "name"):
        v = doc.get(key)
        if isinstance(v, str):
            return v
    return "?"


def trajectory(paths) -> list:
    """One summary row per BENCH file, PR order."""
    rows = []
    for path in paths:
        try:
            doc = load_bench(path)
        except (OSError, ValueError) as exc:
            rows.append({"pr": _pr_tag(path), "error": str(exc)[:120]})
            continue
        heads = extract_headlines(doc)
        # surface the few most informative figures: top-level first,
        # then shallowest paths
        picked = sorted(
            heads.items(), key=lambda kv: (kv[0].count("."), kv[0])
        )[:6]
        rows.append({
            "pr": _pr_tag(path),
            "name": _bench_name(doc),
            "ok": doc.get("ok"),
            "bench_wall_s": doc.get("bench_wall_s"),
            "headlines": {k: v[0] for k, v in picked},
            "headline_count": len(heads),
        })
    return rows


def compare_headlines(new_doc: dict, old_doc: dict,
                      tolerance: float) -> dict:
    """Gate verdict comparing every headline path the two docs share.
    ``regressions`` lists shared directional figures worse (relative)
    by more than ``tolerance``; ``passed`` is False iff any exist."""
    new_h = extract_headlines(new_doc)
    old_h = extract_headlines(old_doc)
    shared = sorted(set(new_h) & set(old_h))
    regressions = []
    improved = []
    for path in shared:
        new_v, direction = new_h[path]
        old_v, _ = old_h[path]
        if direction is None or old_v == 0:
            continue
        # relative change signed so that positive = better
        rel = (new_v - old_v) / abs(old_v)
        if direction == "down":
            rel = -rel
        entry = {
            "path": path, "old": old_v, "new": new_v,
            "direction": direction, "relative_change": round(rel, 4),
        }
        if rel < -tolerance:
            regressions.append(entry)
        elif rel > tolerance:
            improved.append(entry)
    return {
        "shared_paths": len(shared),
        "tolerance": tolerance,
        "regressions": regressions,
        "improved": improved,
        "passed": not regressions,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="repo root holding BENCH_pr*.json (default: repo)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--gate", default=None, metavar="NEW",
                    help="regression-gate mode: the candidate BENCH "
                         "json")
    ap.add_argument("--against", default=None, metavar="OLD",
                    help="baseline BENCH json for --gate (default: "
                         "the newest BENCH_pr*.json before NEW)")
    ap.add_argument("--tolerance", type=float, default=0.15,
                    help="relative regression tolerance (default "
                         "0.15 — benches on shared CI hosts are "
                         "noisy)")
    args = ap.parse_args(argv)

    if args.gate is not None:
        against = args.against
        if against is None:
            peers = sorted(
                p for p in glob.glob(
                    os.path.join(args.root, "BENCH_pr*.json"))
                if os.path.abspath(p) != os.path.abspath(args.gate)
            )
            if not peers:
                print("bench_history: no baseline BENCH_pr*.json "
                      "found; gate passes vacuously")
                return 0
            against = peers[-1]
        verdict = compare_headlines(
            load_bench(args.gate), load_bench(against), args.tolerance
        )
        verdict["candidate"] = args.gate
        verdict["baseline"] = against
        if args.json:
            print(json.dumps(verdict, indent=2))
        else:
            print(f"gate: {args.gate} vs {against} "
                  f"(tolerance {args.tolerance:.0%}, "
                  f"{verdict['shared_paths']} shared figures)")
            for e in verdict["regressions"]:
                print(f"  REGRESSED {e['path']}: {e['old']} -> "
                      f"{e['new']} ({e['relative_change']:+.1%})")
            for e in verdict["improved"]:
                print(f"  improved  {e['path']}: {e['old']} -> "
                      f"{e['new']} ({e['relative_change']:+.1%})")
            print("PASS" if verdict["passed"] else "FAIL")
        return 0 if verdict["passed"] else 1

    paths = sorted(glob.glob(os.path.join(args.root, "BENCH_pr*.json")))
    rows = trajectory(paths)
    if args.json:
        print(json.dumps(rows, indent=2))
        return 0
    print(f"{'pr':<8}{'bench':<28}{'ok':>4}{'wall_s':>9}  headlines")
    print("-" * 100)
    for r in rows:
        if "error" in r:
            print(f"{r['pr']:<8}{'<unreadable>':<28}     "
                  f"    {r['error']}")
            continue
        heads = "  ".join(
            f"{k}={v:g}" for k, v in r["headlines"].items()
        )
        ok = {True: "ok", False: "NO", None: "-"}[r["ok"]]
        wall = ("-" if r["bench_wall_s"] is None
                else f"{r['bench_wall_s']:.1f}")
        print(f"{r['pr']:<8}{r['name'][:27]:<28}{ok:>4}{wall:>9}  "
              f"{heads[:120]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
