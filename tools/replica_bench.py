"""Replicated-store plane benchmark: steady write-through overhead,
hinted-handoff drain, anti-entropy scrub, and replication lag.

Produces ``BENCH_pr20.json`` (ISSUE 20 acceptance artifact):

- ``steady_overhead`` — what mirroring every committed write to two
  extra backends COSTS on the steady path: the pyramid publish is run
  against a single ``file://`` store and against the same store
  wrapped in a 3-way :class:`~tpudas.store.replica.ReplicatedStore`,
  and the added wall is amortized over the steady processing round
  the publisher piggybacks on (the lowpass driver pass, same
  denominator as ``BENCH_pr18.json``'s retry leg).  Acceptance:
  < 2%.
- ``handoff_drain`` — a mirror is partitioned mid-publish so every
  write it misses lands in the hinted-handoff journal; after heal the
  drain pass is timed (``handoff_drain_rate`` objects/s), re-run to
  prove idempotence (zero re-uploads), and the sever→converged wall
  is recorded as ``replication_lag_s``.
- ``scrub`` — a deterministic divergence matrix (8 missing, 4
  mismatched, 1 primary-lost object) repaired by one anti-entropy
  pass; ``scrub_repairs`` is the repair count (deterministic by
  construction) and the trees must verify byte-identical after.

Gate it against the trail with::

    JAX_PLATFORMS=cpu python tools/replica_bench.py
    python tools/bench_history.py --gate BENCH_pr20.json

Run from the repo root (CPU is fine).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tpudas.obs.registry import (  # noqa: E402
    MetricsRegistry,
    use_registry,
)
from tpudas.proc.streaming import run_lowpass_realtime  # noqa: E402
from tpudas.serve.tiles import sync_pyramid  # noqa: E402
from tpudas.store import (  # noqa: E402
    FakeObjectStore,
    PyramidPublisher,
    store_from_url,
)
from tpudas.store.replica import ReplicatedStore  # noqa: E402
from tpudas.testing import make_synthetic_spool  # noqa: E402

T0 = "2023-03-22T00:00:00"
FS = 100.0
FILE_SEC = 30.0
N_FILES = 10
N_CH = 128
DT_OUT = 0.1
TILE_LEN = 128
PREFIX = "streams/a"
PUBLISH_ROUNDS = 5


def _counter_value(reg, name, labelnames=(), **labels) -> float:
    try:
        metric = reg.counter(name, "", labelnames=tuple(labelnames))
    except ValueError:
        return 0.0
    try:
        return float(metric.value(**labels))
    except (KeyError, ValueError):
        return 0.0


def build_pyramid(workdir: str) -> tuple:
    """Synthesize the archive, run the lowpass driver, build the tile
    pyramid; returns ``(stream_folder, driver_wall_s)`` — the steady
    processing round that is the overhead denominator."""
    src = os.path.join(workdir, "raw")
    out = os.path.join(workdir, "stream")
    make_synthetic_spool(
        src, n_files=N_FILES, file_duration=FILE_SEC, fs=FS,
        n_ch=N_CH, noise=0.01,
    )
    t0 = time.perf_counter()
    run_lowpass_realtime(
        source=src, output_folder=out, start_time=T0,
        output_sample_interval=DT_OUT, edge_buffer=5.0,
        process_patch_size=64, poll_interval=0.0,
        sleep_fn=lambda _s: None, pyramid=False,
    )
    driver_wall = time.perf_counter() - t0
    sync_pyramid(out, tile_len=TILE_LEN)
    return out, driver_wall


def bench_steady_overhead(stream: str, workdir: str,
                          steady_round_wall: float) -> dict:
    """Publish into a bare ``file://`` store vs a 3-way replicated
    one; the added wall amortized over the steady round must stay
    under 2%."""

    def publish_rounds(make_store) -> float:
        walls = []
        for i in range(PUBLISH_ROUNDS):
            base = tempfile.mkdtemp(prefix="replica-bench-pub-",
                                    dir=workdir)
            store = make_store(base)
            t0 = time.perf_counter()
            PyramidPublisher(store, PREFIX, stream).publish()
            walls.append(time.perf_counter() - t0)
            shutil.rmtree(base, ignore_errors=True)
        walls.sort()
        return walls[len(walls) // 2]  # median

    single_wall = publish_rounds(
        lambda base: store_from_url(f"file://{base}/bucket")
    )
    journal = os.path.join(workdir, "overhead-journal")
    repl_wall = publish_rounds(
        lambda base: store_from_url(
            f"replica:file://{base}/bucket,"
            f"file://{base}/m1,file://{base}/m2"
        )
    )
    shutil.rmtree(journal, ignore_errors=True)
    added = max(repl_wall - single_wall, 0.0)
    frac = added / steady_round_wall if steady_round_wall else 0.0
    return {
        "publish_rounds": PUBLISH_ROUNDS,
        "steady_round_wall_s": round(steady_round_wall, 3),
        "single_publish_wall_s": round(single_wall, 4),
        "replicated_publish_wall_s": round(repl_wall, 4),
        "added_wall_s": round(added, 4),
        "replication_overhead_fraction": round(frac, 5),
        "accept_under_2pct": frac < 0.02,
    }


def bench_handoff_drain(stream: str, workdir: str) -> dict:
    """Partition a mirror mid-publish, heal, drain; drain rate,
    idempotence, and sever→converged lag."""
    reg = MetricsRegistry()
    with use_registry(reg):
        raws = [FakeObjectStore() for _ in range(3)]
        repl = ReplicatedStore(
            raws[0], raws[1:],
            journal_dir=os.path.join(workdir, "drain-journal"),
        )
        rule = raws[1].injector.partition()
        t_sever = time.perf_counter()
        PyramidPublisher(repl, PREFIX, stream).publish()
        journaled = _counter_value(
            reg, "tpudas_store_replica_handoff_journaled_total",
            labelnames=("mirror",), mirror="m0",
        )
        raws[1].injector.heal(rule)
        t0 = time.perf_counter()
        first = repl.drain_handoff()
        drain_wall = time.perf_counter() - t0
        lag = time.perf_counter() - t_sever
        second = repl.drain_handoff()
        scrub = repl.scrub("", repair=True)
    resolved = first["copied"] + first["deleted"] + first["vanished"]
    rate = resolved / drain_wall if drain_wall else 0.0
    return {
        "journaled_writes": int(journaled),
        "first_drain": first,
        "drain_wall_s": round(drain_wall, 4),
        "handoff_drain_rate": round(rate, 1),
        "replication_lag_s": round(lag, 4),
        "second_drain": second,
        "accept_drain_idempotent": not any(
            second[k] for k in ("copied", "deleted", "failed")
        ),
        "accept_zero_failed": first["failed"] == 0,
        "accept_converged": bool(scrub["clean"]),
    }


def bench_scrub(workdir: str) -> dict:
    """A deterministic divergence matrix repaired by one anti-entropy
    pass: 8 missing + 4 mismatched on the mirror, 1 object the
    primary lost."""
    reg = MetricsRegistry()
    with use_registry(reg):
        raws = [FakeObjectStore() for _ in range(2)]
        repl = ReplicatedStore(
            raws[0], raws[1:],
            journal_dir=os.path.join(workdir, "scrub-journal"),
        )
        for i in range(24):
            repl.put(f"{PREFIX}/obj-{i:03d}", b"x" * 512 + bytes([i]))
        # fabricate divergence behind the journal's back
        for i in range(8):
            raws[1]._objects.pop(f"{PREFIX}/obj-{i:03d}")
        for i in range(8, 12):
            raws[1]._objects[f"{PREFIX}/obj-{i:03d}"] = b"stale"
        raws[1]._objects[f"{PREFIX}/lost"] = b"only-on-mirror"
        t0 = time.perf_counter()
        report = repl.scrub("", repair=True)
        scrub_wall = time.perf_counter() - t0
        repairs = sum(report["repairs"].values())
        identical = repl.verify_identical()
    return {
        "objects": report["objects"],
        "scrub_wall_s": round(scrub_wall, 4),
        "repair_matrix": report["repairs"],
        "scrub_repairs": int(repairs),
        "accept_clean": bool(report["clean"]),
        "accept_identical_after": bool(identical),
    }


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    out_path = argv[0] if argv else os.path.join(
        REPO, "BENCH_pr20.json"
    )
    workdir = tempfile.mkdtemp(prefix="replica-bench-")
    bench_t0 = time.perf_counter()
    try:
        stream, driver_wall = build_pyramid(workdir)
        overhead = bench_steady_overhead(stream, workdir, driver_wall)
        drain = bench_handoff_drain(stream, workdir)
        scrub = bench_scrub(workdir)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    doc = {
        "bench": "replicated_store_plane",
        "config": {
            "fs": FS, "n_files": N_FILES, "file_sec": FILE_SEC,
            "n_ch": N_CH, "dt_out": DT_OUT, "tile_len": TILE_LEN,
            "mirrors": 2, "publish_rounds": PUBLISH_ROUNDS,
        },
        "steady_overhead": overhead,
        "handoff_drain": drain,
        "scrub": scrub,
        "ok": bool(
            overhead["accept_under_2pct"]
            and drain["accept_drain_idempotent"]
            and drain["accept_zero_failed"]
            and drain["accept_converged"]
            and scrub["accept_clean"]
            and scrub["accept_identical_after"]
        ),
        "bench_wall_s": round(time.perf_counter() - bench_t0, 1),
    }
    with open(out_path, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=False)
        fh.write("\n")
    print(json.dumps(doc, indent=1))
    print(f"\nwrote {out_path}; ok={doc['ok']}")
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
