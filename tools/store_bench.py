"""Object-store plane benchmark: publish dedup, cache-hidden cold
reads, stateless-replica QPS scaling, retry/degradation overhead, and
a fault-drill leg.

Produces ``BENCH_pr18.json`` (ISSUE 18 acceptance artifact):

- ``publish``        — wall time to mirror the pyramid into a
  ``file://`` store, then a RESTARTED publisher's re-publish: it must
  re-upload ZERO objects (token-dedup'd catch-up).
- ``cache``          — in-process :class:`RemotePyramid` reads through
  the NVMe read-through cache: cold pass (every tile off the cold
  tier), REPLICA-RESTART pass (fresh mirror + warm cache: hit rate
  ~1.0, no tile or sidecar gets), hydrated-mirror pass; then the cold
  tier goes OFFLINE and reads must keep answering
  (stale-but-verified).
- ``qps``            — the stateless serving replica:
  :class:`tpudas.serve.pool.ServePool` mounted on ``store_url`` with
  workers in {1, 2, 4}, hammered from client processes; cold pass
  (mirror + cache empty — cold-tier reads hidden behind first touch)
  then warm pass.  Acceptance: warm QPS at 4 workers >= 2x 1 worker.
- ``retry_overhead`` — the measured cost of a transient cold-tier
  5xx (one per steady round, ~100x the op-volume-scaled real-world
  rate) absorbed by the retry layer — backoff sleep + duplicate
  attempt — as a fraction of the steady processing round the plane
  rides on.  Acceptance: < 2%.
- ``fault_drill``    — the 2-worker fake-backend fault matrix
  (5xx storms, lost CAS responses, torn uploads, latency spikes) from
  :mod:`tools.backfill_drill`: byte-identity vs a POSIX-store control
  and a clean audit, recorded with the fired-fault census.

Run from the repo root (CPU is fine)::

    JAX_PLATFORMS=cpu python tools/store_bench.py [out.json]
        [--skip-drill]
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tpudas.obs.registry import (  # noqa: E402
    MetricsRegistry,
    use_registry,
)
from tpudas.proc.streaming import run_lowpass_realtime  # noqa: E402
from tpudas.serve.tiles import TileStore, sync_pyramid  # noqa: E402
from tpudas.store import (  # noqa: E402
    FakeObjectStore,
    FaultInjector,
    FaultRule,
    PyramidPublisher,
    ReadThroughCache,
    RemotePyramid,
    RetryingStore,
    store_from_url,
)
from tpudas.testing import make_synthetic_spool  # noqa: E402

T0 = "2023-03-22T00:00:00"
FS = 100.0
FILE_SEC = 30.0
N_FILES = 10
N_CH = 128
DT_OUT = 0.1
TILE_LEN = 128
PREFIX = "streams/a"

QPS_WORKER_COUNTS = (1, 2, 4)
QPS_MEASURE_S = 6.0


def _counter_value(reg, name, **labels) -> float:
    """Counter value; without labels, the sum over every series."""
    m = reg.get(name)
    if m is None:
        return 0.0
    if labels:
        return float(m.value(**labels))
    return float(sum(v for _lbl, v in m._series()))


def build_pyramid(workdir: str) -> tuple:
    """Synthesize the archive, run the lowpass driver, build the tile
    pyramid; returns ``(stream_folder, driver_wall_s)`` — the driver
    wall is the steady processing round the publisher piggybacks on
    (the denominator of the retry-overhead budget)."""
    src = os.path.join(workdir, "raw")
    out = os.path.join(workdir, "stream")
    make_synthetic_spool(
        src, n_files=N_FILES, file_duration=FILE_SEC, fs=FS,
        n_ch=N_CH, noise=0.01,
    )
    t0 = time.perf_counter()
    run_lowpass_realtime(
        source=src, output_folder=out, start_time=T0,
        output_sample_interval=DT_OUT, edge_buffer=5.0,
        process_patch_size=64, poll_interval=0.0,
        sleep_fn=lambda _s: None, pyramid=False,
    )
    driver_wall = time.perf_counter() - t0
    sync_pyramid(out, tile_len=TILE_LEN)
    return out, driver_wall


def bench_publish(stream: str, bucket: str) -> dict:
    """First publish wall + restarted-publisher dedup (zero
    re-uploads)."""
    reg = MetricsRegistry()
    with use_registry(reg):
        store = store_from_url(f"file://{bucket}")
        t0 = time.perf_counter()
        PyramidPublisher(store, PREFIX, stream).publish()
        first_wall = time.perf_counter() - t0
        puts_first = _counter_value(
            reg, "tpudas_store_ops_total", op="put"
        )
        tiles = _counter_value(
            reg, "tpudas_store_published_tiles_total"
        )
        # a RESTARTED publisher: fresh memo, same store — the seed
        # pass must recognize every object by token and re-upload none
        t0 = time.perf_counter()
        PyramidPublisher(store, PREFIX, stream).publish()
        second_wall = time.perf_counter() - t0
        puts_second = _counter_value(
            reg, "tpudas_store_ops_total", op="put"
        ) - puts_first
    return {
        "first_publish_wall_s": round(first_wall, 3),
        "published_tiles": int(tiles),
        "unconditional_puts": int(puts_first),
        "restart_republish_wall_s": round(second_wall, 3),
        "restart_reuploads": int(puts_second),
        "accept_zero_reuploads": puts_second == 0,
    }


def _read_round(remote) -> float:
    """One steady read round: every level, full width, through
    :meth:`RemotePyramid.read` so the cache and cold tier are on the
    path."""
    t0 = time.perf_counter()
    remote.refresh(force=True)
    ts = remote.open()
    for level in range(ts.n_levels):
        remote.read(level, 0, ts.n(level))
    return time.perf_counter() - t0


def bench_cache(bucket: str, workdir: str) -> dict:
    """The NVMe read-through cache's three tiers: cold (every tile
    off the cold tier), REPLICA RESTART (fresh mirror + warm cache —
    every materialization a cache hit, zero cold-tier gets), mirror
    (already hydrated), then the cold tier goes OFFLINE and reads
    must keep answering."""
    reg = MetricsRegistry()
    with use_registry(reg):
        store = store_from_url(f"file://{bucket}")
        base = os.path.join(workdir, "replica")
        cache_dir = os.path.join(base, "cache")

        def _replica(mirror_name):
            return RemotePyramid(
                store, PREFIX, ReadThroughCache(cache_dir),
                os.path.join(base, mirror_name), min_refresh_s=0.0,
            )

        remote = _replica("mirror-cold")
        cold_wall = _read_round(remote)
        gets0 = _counter_value(
            reg, "tpudas_store_ops_total", op="get"
        )
        hits0 = _counter_value(
            reg, "tpudas_store_cache_events_total", event="hit"
        )
        miss0 = _counter_value(
            reg, "tpudas_store_cache_events_total", event="miss"
        )
        # replica restart: the mirror is gone, the NVMe cache is not —
        # every tile materializes from cache, the cold tier sees only
        # the manifest/meta probes
        restarted = _replica("mirror-restart")
        restart_wall = _read_round(restarted)
        restart_gets = _counter_value(
            reg, "tpudas_store_ops_total", op="get"
        ) - gets0
        hits1 = _counter_value(
            reg, "tpudas_store_cache_events_total", event="hit"
        )
        miss1 = _counter_value(
            reg, "tpudas_store_cache_events_total", event="miss"
        )
        warm_hits = hits1 - hits0
        warm_miss = miss1 - miss0
        hit_rate = (
            warm_hits / (warm_hits + warm_miss)
            if warm_hits + warm_miss else 0.0
        )
        mirror_wall = _read_round(restarted)
        # cold tier down: probes fail; the hydrated replica keeps
        # serving its mirror (flagged stale), no exception escapes
        offline_ok = True
        offline_wall = None
        dead = store_from_url("fake:store-bench-dead", retry=False)
        dead.injector.set_offline(True)
        restarted.store = dead
        try:
            t0 = time.perf_counter()
            restarted.refresh(force=True)
            ts = restarted.open()
            restarted.read(0, 0, ts.n(0))
            offline_wall = time.perf_counter() - t0
        except Exception as exc:
            # the offline leg *is* the measurement: a raise here is
            # the reported result, not a bench bug to hide
            print(f"store_bench: offline read raised: {exc!r}")
            offline_ok = False
        snap = restarted.snapshot()
    return {
        "cold_round_wall_s": round(cold_wall, 4),
        "restart_round_wall_s": round(restart_wall, 4),
        "mirror_round_wall_s": round(mirror_wall, 4),
        "restart_speedup": (
            round(cold_wall / restart_wall, 2) if restart_wall
            else None
        ),
        "hit_rate": round(hit_rate, 4),
        "restart_hits": int(warm_hits),
        "restart_misses": int(warm_miss),
        "restart_cold_tier_gets": int(restart_gets),
        "offline_reads_keep_answering": offline_ok,
        "offline_round_wall_s": (
            None if offline_wall is None else round(offline_wall, 4)
        ),
        "snapshot": snap,
        # the only cold-tier gets a restarted replica may pay are
        # the tiny mutable artifacts (manifest, tails, sidecar) — no
        # tile payload or checksum round trips
        "accept_cache_hides_cold": bool(
            hit_rate >= 0.9 and restart_gets <= 4 and offline_ok
        ),
    }


# One hammer client PROCESS: stdlib-only (no jax import on the
# measurement path), a few keep-alive connections walking the window
# set for its OWN measured duration, JSON report on stdout.
_CLIENT_SRC = r"""
import http.client, json, sys, threading, time
host, tails_json, duration, n_threads = (
    sys.argv[1], sys.argv[2], float(sys.argv[3]), int(sys.argv[4])
)
tails = json.loads(tails_json)
ok, shed, errs = [0], [0], [0]
lats = []
lock = threading.Lock()
start = time.time()
def worker(offset):
    conn = http.client.HTTPConnection(host, timeout=30)
    i = offset
    while time.time() < start + duration:
        tail = tails[i % len(tails)]
        i += 1
        t0 = time.perf_counter()
        try:
            conn.request("GET", tail)
            r = conn.getresponse()
            r.read()
            dt = time.perf_counter() - t0
            with lock:
                if r.status == 503:
                    shed[0] += 1
                elif r.status == 200:
                    ok[0] += 1
                    lats.append(dt)
                else:
                    errs[0] += 1
        except Exception:
            conn.close()
            conn = http.client.HTTPConnection(host, timeout=30)
            with lock:
                errs[0] += 1
    conn.close()
threads = [
    threading.Thread(target=worker, args=(j,))
    for j in range(n_threads)
]
for t in threads:
    t.start()
for t in threads:
    t.join()
elapsed = time.time() - start
print(json.dumps({
    "ok": ok[0], "shed": shed[0], "errs": errs[0],
    "elapsed": elapsed, "lats": lats,
}))
"""

QPS_CLIENT_PROCS = 6
QPS_THREADS_PER_PROC = 4
RETRY_ROUNDS = 3


def _hammer(base_url, url_tails, duration_s) -> dict:
    """Hammer from stdlib-only client subprocesses; each measures its
    own window, so the aggregate rate is the sum of per-client
    rates."""
    import subprocess
    import urllib.parse

    host = urllib.parse.urlsplit(base_url).netloc
    procs = [
        subprocess.Popen(
            [
                sys.executable, "-c", _CLIENT_SRC, host,
                json.dumps(url_tails), str(duration_s),
                str(QPS_THREADS_PER_PROC),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for _ in range(QPS_CLIENT_PROCS)
    ]
    results = []
    for p in procs:
        out, err = p.communicate(timeout=duration_s * 4 + 60)
        if p.returncode != 0:
            raise RuntimeError(
                f"hammer client failed: {err.decode()[:500]}"
            )
        results.append(json.loads(out))
    ok = sum(r["ok"] for r in results)
    lats = sorted(
        lat for r in results for lat in r["lats"]
    ) or [0.0]

    def pct(p):
        return lats[min(len(lats) - 1, int(p * (len(lats) - 1)))]

    return {
        "ok": int(ok),
        "shed_503": int(sum(r["shed"] for r in results)),
        "errors": int(sum(r["errs"] for r in results)),
        "qps": round(
            sum(r["ok"] / r["elapsed"] for r in results
                if r["elapsed"]), 1
        ),
        "p50_ms": round(pct(0.50) * 1e3, 2),
        "p99_ms": round(pct(0.99) * 1e3, 2),
    }


def bench_qps(bucket: str, workdir: str) -> dict:
    """The stateless replica under load: ServePool on store_url,
    workers in QPS_WORKER_COUNTS, process-based hammer clients, cold
    then warm pass."""
    from tpudas.serve.pool import ServePool

    store = store_from_url(f"file://{bucket}")
    mirror = os.path.join(workdir, "probe-mirror")
    probe = RemotePyramid(
        store, PREFIX,
        ReadThroughCache(os.path.join(workdir, "probe-cache")),
        mirror, min_refresh_s=0.0,
    )
    probe.refresh(force=True)
    local = TileStore.open(mirror)
    lo = local.t0_ns
    hi = local.head_ns - local.step_ns
    span = hi - lo
    url_tails = []
    for w in range(8):
        a = lo + (w * span) // 10
        b = lo + ((w + 2) * span) // 10
        url_tails.append(f"/query?t0={a}&t1={b}&max_samples=64")
        url_tails.append(f"/query?t0={a}&t1={b}")
    per_workers: dict = {}
    for n in QPS_WORKER_COUNTS:
        cache_dir = os.path.join(workdir, f"qps-cache-{n}")
        with ServePool(
            port=0, workers=n, store_url=f"file://{bucket}",
            store_prefix=PREFIX, cache_dir=cache_dir,
        ) as pool:
            cold = _hammer(
                pool.base_url, url_tails, QPS_MEASURE_S
            )
            warm = _hammer(
                pool.base_url, url_tails, QPS_MEASURE_S
            )
        per_workers[str(n)] = {"cold": cold, "warm": warm}
        print(
            f"  [qps] workers={n}: warm {warm['qps']} qps "
            f"(p99 {warm['p99_ms']} ms), cold {cold['qps']} qps",
            flush=True,
        )
    base = per_workers[str(QPS_WORKER_COUNTS[0])]["warm"]["qps"]
    peak = per_workers[str(QPS_WORKER_COUNTS[-1])]["warm"]["qps"]
    # worker scaling needs at least as many cores as the peak worker
    # count plus the hammer clients; on a starved box the workers
    # timeshare one core and the ratio measures the scheduler, not
    # the pool — report the ratio but do not gate on it
    cores = os.cpu_count() or 1
    measurable = cores >= QPS_WORKER_COUNTS[-1]
    if not measurable:
        print(
            f"  [qps] only {cores} core(s) — scaling acceptance "
            f"not measurable, reporting ratio ungated", flush=True,
        )
    return {
        "workers": per_workers,
        "cores": cores,
        "scaling_measurable": measurable,
        "scaling_speedup_warm": (
            round(peak / base, 2) if base else None
        ),
        "accept_2x_scaling": bool(
            not measurable or (base and peak / base >= 2.0)
        ),
    }


def bench_retry_overhead(stream: str, steady_round_wall: float) -> (
    dict
):
    """What a transient cold-tier fault (one 5xx per
    ``RETRY_ROUNDS`` steady rounds — still ~30x the op-volume-scaled
    real-world 5xx rate) actually COSTS: measured backoff sleep +
    duplicate-attempt wall, amortized over the steady processing
    rounds the store plane rides on (the driver pass measured by
    :func:`build_pyramid`).  Acceptance: < 2%."""
    sleeps: list = []

    def sleep_and_log(s):
        sleeps.append(s)
        time.sleep(s)

    def run_round(faulted: bool, tag: str) -> float:
        # clean publish first; the storm only hits the serving round
        raw = FakeObjectStore()
        store = RetryingStore(
            raw, sleep_fn=sleep_and_log if faulted else time.sleep
        )
        PyramidPublisher(store, PREFIX, stream).publish()
        raw.injector.add(
            FaultRule(kind="latency", op="get", seconds=0.002,
                      times=10**9)
        )
        if faulted:
            # one transient 5xx across RETRY_ROUNDS steady rounds —
            # pessimistic: those rounds' ~300 store ops at real-world
            # 5xx rates (~1e-4 per op) would see ~0.03 faults
            raw.injector.add(
                FaultRule(kind="unavailable", op="get", at=10,
                          times=1)
            )
        base = tempfile.mkdtemp(prefix=f"store-bench-retry-{tag}-")
        remote = RemotePyramid(
            store, PREFIX,
            ReadThroughCache(os.path.join(base, "cache")),
            os.path.join(base, "mirror"), min_refresh_s=0.0,
        )
        t0 = time.perf_counter()
        for _ in range(RETRY_ROUNDS):
            _read_round(remote)
        wall = time.perf_counter() - t0
        shutil.rmtree(base, ignore_errors=True)
        return wall

    reg = MetricsRegistry()
    with use_registry(reg):
        clean_wall = run_round(False, "clean")
        faulted_wall = run_round(True, "faulted")
        retries = _counter_value(reg, "tpudas_store_retries_total")
    backoff_s = float(sum(sleeps))
    added_s = max(faulted_wall - clean_wall, backoff_s)
    denom = steady_round_wall * RETRY_ROUNDS
    frac = added_s / denom if denom else 0.0
    return {
        "rounds": RETRY_ROUNDS,
        "steady_round_wall_s": round(steady_round_wall, 3),
        "clean_serve_round_wall_s": round(clean_wall, 4),
        "faulted_serve_round_wall_s": round(faulted_wall, 4),
        "retries": int(retries),
        "backoff_sleep_s": round(backoff_s, 4),
        "added_wall_s": round(added_s, 4),
        "overhead_fraction": round(frac, 5),
        "accept_under_2pct": frac < 0.02,
    }


def bench_fault_drill(workdir: str) -> dict:
    """The 2-worker fake-backend fault matrix vs a POSIX-store
    control, via the drill's own harness."""
    from tools.backfill_drill import (
        FILE_SEC as D_FILE_SEC,
        SHARD_SEC as D_SHARD_SEC,
        _build_archive,
        _run_store_control,
        run_store_fault_matrix,
    )

    shards = 2
    n_files = int(round(shards * D_SHARD_SEC / D_FILE_SEC))
    root = os.path.join(workdir, "fault-drill")
    src = os.path.join(root, "src")
    os.makedirs(root, exist_ok=True)
    _build_archive(src, n_files)
    ctrl = _run_store_control(
        os.path.join(root, "bucket_ctrl"), src, n_files,
        os.path.join(root, "ctrl-scratch"), 600.0,
    )
    return run_store_fault_matrix(src, n_files, root, ctrl, 600.0)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    skip_drill = "--skip-drill" in argv
    argv = [a for a in argv if a != "--skip-drill"]
    out_path = argv[0] if argv else os.path.join(
        REPO, "BENCH_pr18.json"
    )
    workdir = tempfile.mkdtemp(prefix="store_bench_")
    try:
        print("building stream + pyramid ...", flush=True)
        stream, steady_wall = build_pyramid(workdir)
        bucket = os.path.join(workdir, "bucket")
        print("publish leg ...", flush=True)
        publish = bench_publish(stream, bucket)
        print("cache leg ...", flush=True)
        cache = bench_cache(bucket, workdir)
        print("qps leg ...", flush=True)
        qps = bench_qps(bucket, workdir)
        print("retry-overhead leg ...", flush=True)
        retry = bench_retry_overhead(stream, steady_wall)
        drill = None
        if not skip_drill:
            print("fault-drill leg ...", flush=True)
            drill = bench_fault_drill(workdir)
        report = {
            "bench": "object_store_plane",
            "config": {
                "fs": FS, "n_files": N_FILES, "file_sec": FILE_SEC,
                "n_ch": N_CH, "dt_out": DT_OUT,
                "tile_len": TILE_LEN,
                "qps_workers": list(QPS_WORKER_COUNTS),
            },
            "publish": publish,
            "cache": cache,
            "qps": qps,
            "retry_overhead": retry,
        }
        if drill is not None:
            report["fault_drill"] = drill
        accepts = [
            publish["accept_zero_reuploads"],
            cache["accept_cache_hides_cold"],
            qps["accept_2x_scaling"],
            retry["accept_under_2pct"],
        ]
        if drill is not None:
            accepts += [
                drill["audit_clean"],
                drill["outputs_match_posix_control"],
                drill["pyramid_match_posix_control"],
            ]
        report["ok"] = all(accepts)
        with open(out_path, "w") as fh:
            json.dump(report, fh, indent=1)
            fh.write("\n")
        print(json.dumps(report, indent=1))
        print(
            f"store_bench: {'OK' if report['ok'] else 'FAILED'} "
            f"-> {out_path}"
        )
        return 0 if report["ok"] else 1
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
