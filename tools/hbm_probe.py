"""Measure peak HBM per processed window across memory-model configs.

Backs the ``get_patch_time`` docstring's claim (reference
lf_das.py:90-107: ``processing_factor=5`` with safety 1.2) with device
data: for each (rate, n_ch, patch_sec) config the probe runs one full
cascade window exactly as LFProc dispatches it and reports the device
allocator's peak, the raw-window bytes, and their ratio — the measured
processing factor.  Each config runs in a fresh subprocess so the
per-device peak counter starts clean.

Run on a live chip: ``python tools/hbm_probe.py``
One config (subprocess mode): ``python tools/hbm_probe.py <fs> <C> <sec>``
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

CONFIGS = [
    # (fs_hz, n_ch, patch_seconds) — patch_seconds chosen near the
    # memory model's own answer for a 14000 MB budget (f32: bpe=4)
    (1000.0, 2048, 131.0),
    (1000.0, 2048, 262.0),
    (1000.0, 10000, 55.0),   # BASELINE config 4 width
    (500.0, 5000, 110.0),
]


def _one(fs: float, n_ch: int, sec: float) -> None:
    import numpy as np

    import jax

    from tpudas.ops.fir import cascade_decimate, design_cascade

    dev = jax.devices()[0]
    plan = design_cascade(fs, int(round(fs)), 0.45, 4)
    T = int(round(sec * fs))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((T, n_ch)).astype(np.float32)
    n_out = max(int(sec) - 2 * 10, 1)
    base = (dev.memory_stats() or {}).get("peak_bytes_in_use", 0)
    out = np.asarray(cascade_decimate(x, plan, plan.delay, n_out, "auto"))
    stats = dev.memory_stats() or {}
    peak = stats.get("peak_bytes_in_use", 0)
    print(
        json.dumps(
            {
                "fs": fs,
                "n_ch": n_ch,
                "patch_sec": sec,
                "window_mb": round(x.nbytes / 1e6, 1),
                "peak_hbm_mb": round(peak / 1e6, 1),
                "baseline_mb": round(base / 1e6, 1),
                "measured_factor": round(peak / max(x.nbytes, 1), 2),
                "out_shape": list(out.shape),
            }
        ),
        flush=True,
    )


def main() -> None:
    if len(sys.argv) == 4:
        _one(float(sys.argv[1]), int(sys.argv[2]), float(sys.argv[3]))
        return
    here = os.path.dirname(os.path.abspath(__file__))
    rows = []
    for fs, c, sec in CONFIGS:
        r = subprocess.run(
            [sys.executable, os.path.join(here, "hbm_probe.py"),
             str(fs), str(c), str(sec)],
            capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(here),
        )
        lines = r.stdout.strip().splitlines()
        if r.returncode != 0 or not lines:
            print(f"config ({fs},{c},{sec}) failed (rc={r.returncode}): "
                  f"{r.stderr.strip()[-300:]}", flush=True)
            continue
        try:
            rows.append(json.loads(lines[-1]))
            print(lines[-1], flush=True)
        except json.JSONDecodeError:
            print(f"config ({fs},{c},{sec}) failed: "
                  f"{r.stderr.strip()[-300:]}", flush=True)
    if rows:
        worst = max(r["measured_factor"] for r in rows)
        print(f"\nworst measured processing factor: {worst} "
              "(memory model uses 5 * 1.2 safety)")


if __name__ == "__main__":
    main()
