#!/bin/bash
# Poll the tunneled TPU backend until it answers; exit 0 when alive.
# Each probe is a fresh subprocess with a hard timeout so a wedged
# backend init can never hang the watcher itself.
for i in $(seq 1 70); do
  if timeout 120 python -c "
import jax
assert jax.default_backend() != 'cpu'
import jax.numpy as jnp
x = jnp.ones((128, 128))
assert float((x @ x).sum()) == 128.0 * 128 * 128
print('TPU ALIVE:', jax.devices())
" 2>/dev/null; then
    echo "tpu came up on probe $i at $(date -u +%H:%M:%S)"
    exit 0
  fi
  echo "probe $i: backend unresponsive at $(date -u +%H:%M:%S)"
  sleep 600
done
echo "gave up"
exit 1
