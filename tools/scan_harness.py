"""Shared resident-scan measurement harness for the tools/ probes.

bench.py's kernel-mode methodology, standalone: NW distinct resident
windows, the whole timed loop one device dispatch (lax.scan), RNG
outside the timer, best-of-2.  Single source so every probe measures
the same way; see PERF.md §2 for why each element is there.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp


def measure(fn, T, C, iters=96, dtype="float32"):
    """Best-of-2 seconds per (T, C) window through ``fn``."""
    es = 2 if dtype == "int16" else 4
    nw = max(1, min(6, int(9e9 // (T * C * es))))
    rep = max(1, -(-iters // nw))
    if dtype == "int16":
        gen = jax.jit(
            lambda key: jax.random.randint(
                key, (nw, T, C), -3000, 3000, jnp.int16
            )
        )
    else:
        gen = jax.jit(
            lambda key: jax.random.normal(key, (nw, T, C), jnp.float32)
        )
    stack = gen(jax.random.PRNGKey(0))
    jax.block_until_ready(stack)

    @jax.jit
    def run(st):
        def body(tot, w):
            return tot + jnp.sum(jnp.abs(fn(w)).astype(jnp.float32)), None

        def outer(tot, _):
            t, _ = jax.lax.scan(body, tot, st)
            return t, None

        tot, _ = jax.lax.scan(
            outer, jnp.zeros((), jnp.float32), None, length=rep
        )
        return tot

    assert np.isfinite(float(run(stack)))
    best = 1e30
    for _ in range(2):
        t0 = time.perf_counter()
        assert np.isfinite(float(run(stack)))
        best = min(best, time.perf_counter() - t0)
    return best / (nw * rep)
