"""Backfill scaling bench: wall time over worker count for a
multi-hour synthetic archive (BENCH_pr12.json).

The embarrassingly-parallel second workload every future perf PR can
bench against (ROADMAP item 5): one archive, one plan per run, N
worker subprocesses draining the queue.  Records:

- the worker-count scaling curve (wall seconds + speedup vs 1 worker
  for the DRAIN phase, stitch reported separately — the stitch is a
  single-writer tail by design);
- the lease/claim/renew/commit overhead fraction summed from the done
  markers (acceptance budget: < 2% of shard wall);
- cross-N result digests (every worker count must produce the same
  stitched bytes — scaling must not buy divergence).

CLI::

    JAX_PLATFORMS=cpu python tools/backfill_bench.py \
        [--hours 2.0] [--workers 1,2,4] [--shard-sec 600] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

T0 = "2023-03-22T00:00:00"
FS = 50.0
FILE_SEC = 20.0
N_CH = 8
DT_OUT = 1.0
EDGE_SEC = 5.0
PATCH_OUT = 40


def _bench_one(workdir, src, n_files, shard_sec, n_workers,
               log_fh=None) -> dict:
    import numpy as np

    from tools.backfill_drill import _spawn
    from tpudas.backfill import BackfillQueue, plan_backfill
    from tpudas.backfill.queue import RESULT_DONE_FILENAME
    from tpudas.integrity.audit import audit_backfill

    root = os.path.join(workdir, f"queue_w{n_workers}")
    t_end = np.datetime64(T0) + np.timedelta64(
        int(n_files * FILE_SEC * 1e9), "ns"
    )
    plan = plan_backfill(
        root, src, T0, t_end, shard_seconds=float(shard_sec),
        output_sample_interval=DT_OUT, edge_buffer=EDGE_SEC,
        process_patch_size=PATCH_OUT, pyramid=True, detect=False,
        ingest_limit_sec=120.0,
    )
    queue = BackfillQueue(root, worker="bench-parent", settle=0.0)
    t0 = time.time()
    # a 5 ms claim settle is ample local-FS write visibility; the
    # drill keeps 20 ms (it races real SIGKILLs over slower paths)
    procs = [
        _spawn(root, f"b{i:02d}", "", log_fh, settle=0.005)
        for i in range(n_workers)
    ]
    t_drained = None
    while True:
        if t_drained is None and queue.all_done():
            t_drained = time.time()
        if all(p.poll() is not None for p in procs):
            break
        if time.time() - t0 > 3600:
            for p in procs:
                p.kill()
            raise TimeoutError("backfill bench run exceeded 1h")
        time.sleep(0.1)
    t_done = time.time()
    if t_drained is None:
        t_drained = t_done
    for p in procs:
        if p.returncode != 0:
            raise RuntimeError(
                f"bench worker exited rc={p.returncode} (see --log)"
            )
    if not os.path.isfile(os.path.join(root, RESULT_DONE_FILENAME)):
        raise RuntimeError("bench queue drained but never stitched")
    report = audit_backfill(root, repair=True)
    from tools.backfill_drill import _overhead_fraction
    from tools.crash_drill import _content_hash, _pyramid_tree

    over_s, wall_s = _overhead_fraction(root)
    res = os.path.join(root, "result")
    return {
        "workers": int(n_workers),
        "shards": len(plan["shards"]),
        "drain_wall_s": round(t_drained - t0, 3),
        "total_wall_s": round(t_done - t0, 3),
        "shard_wall_sum_s": round(wall_s, 3),
        "overhead_s": round(over_s, 4),
        "overhead_fraction": (
            round(over_s / wall_s, 5) if wall_s else None
        ),
        "audit_clean": bool(report["clean"]),
        "result_content_sha": _content_hash(res),
        "result_pyramid_files": len(_pyramid_tree(res)),
    }


def run_bench(hours=2.0, workers=(1, 2, 4), shard_sec=600.0,
              workdir=None, log_path=None) -> dict:
    from tools.backfill_drill import _build_archive

    workdir = workdir or tempfile.mkdtemp(prefix="backfill_bench_")
    src = os.path.join(workdir, "src")
    n_files = int(round(hours * 3600.0 / FILE_SEC))
    log_fh = open(log_path, "ab") if log_path else None
    try:
        import numpy as np

        from tpudas.testing import make_synthetic_spool

        make_synthetic_spool(
            src, n_files=n_files, file_duration=FILE_SEC, fs=FS,
            n_ch=N_CH, noise=0.01, start=np.datetime64(T0),
        )
        runs = []
        for n in workers:
            print(f"backfill_bench: workers={n} ...")
            runs.append(
                _bench_one(workdir, src, n_files, shard_sec, int(n),
                           log_fh)
            )
            r = runs[-1]
            print(
                f"backfill_bench: workers={n} drain={r['drain_wall_s']}s "
                f"overhead={r['overhead_fraction']}"
            )
        base = runs[0]["drain_wall_s"]
        for r in runs:
            r["speedup_vs_1"] = round(base / r["drain_wall_s"], 3)
        shas = {r["result_content_sha"] for r in runs}
        return {
            "archive_hours": float(hours),
            "archive_files": n_files,
            "channels": N_CH,
            "fs_hz": FS,
            "shard_seconds": float(shard_sec),
            "runs": runs,
            "results_identical_across_workers": len(shas) == 1,
            "max_overhead_fraction": max(
                r["overhead_fraction"] or 0.0 for r in runs
            ),
            "ok": bool(
                len(shas) == 1
                and all(r["audit_clean"] for r in runs)
                and max(
                    r["overhead_fraction"] or 0.0 for r in runs
                ) < 0.02
            ),
            "workdir": workdir,
        }
    finally:
        if log_fh is not None:
            log_fh.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--hours", type=float, default=2.0)
    ap.add_argument("--workers", default="1,2,4")
    ap.add_argument("--shard-sec", type=float, default=600.0)
    ap.add_argument("--out", default=None)
    ap.add_argument("--log", default=None)
    args = ap.parse_args(argv)
    rep = run_bench(
        hours=args.hours,
        workers=[int(w) for w in args.workers.split(",") if w],
        shard_sec=args.shard_sec,
        log_path=args.log,
    )
    print(json.dumps(
        {k: v for k, v in rep.items() if k != "workdir"}, indent=1
    ))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(rep, fh, indent=1)
    print(f"backfill_bench: {'OK' if rep['ok'] else 'FAILED'}")
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
