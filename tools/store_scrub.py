"""Operator CLI for the replicated store: scrub, drain, promote.

The anti-entropy surface of :mod:`tpudas.store.replica`, standalone
(``tools/fsck.py --store replica:...`` runs the same scrub as part of
a full backfill-job audit; this tool is the store-only view for
cron/runbook use):

    JAX_PLATFORMS=cpu python tools/store_scrub.py replica:URL_A,URL_B[,...] [opts]

Default action is one full **scrub**: drain the hinted-handoff
journal, diff every replica against the primary by content token,
repair mirrors from the primary, restore primary-lost objects from
mirrors, sweep torn-upload debris everywhere.  Exit 0 when the trees
converged (report ``clean``), 1 otherwise.

Options:
    --prefix P      scrub only keys under prefix P (default: all)
    --no-repair     report divergence, change nothing
    --drain         drain the handoff journal only (no full diff) —
                    the cheap post-recovery fast path
    --promote K     disaster recovery: the old primary is LOST;
                    reconcile the other members onto member index K
                    (0-based position in the replica: spec, so 1 = the
                    first mirror) and report.  After promotion,
                    restart every component with the promoted member
                    FIRST in the replica: spec and run a normal scrub.
                    Conflicting keys keep the promotion target's copy
                    (counted in the report) — promote the most
                    caught-up mirror.
    --out PATH      also write the JSON report to PATH

The journal location must match the writers': point
``TPUDAS_REPLICA_JOURNAL`` at the same directory the serving/backfill
processes used, or their deferred writes are invisible to --drain
(a full scrub finds the divergence regardless — the journal is an
optimization, the token diff is the truth).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "url", help="replica:urlA,urlB,... store spec (primary first)"
    )
    ap.add_argument("--prefix", default="", help="scrub this key prefix only")
    ap.add_argument(
        "--no-repair", action="store_true",
        help="report divergence; change nothing",
    )
    ap.add_argument(
        "--drain", action="store_true",
        help="drain the handoff journal only (skip the full diff)",
    )
    ap.add_argument(
        "--promote", type=int, default=None, metavar="K",
        help="reconcile survivors onto member K (0-based; the old "
             "primary is lost)",
    )
    ap.add_argument("--out", default=None, help="write JSON report here")
    args = ap.parse_args(argv)

    from tpudas.store import store_from_url
    from tpudas.store.replica import find_replicated, promote

    store = store_from_url(args.url)
    repl = find_replicated(store)
    if repl is None:
        ap.error(f"not a replica: spec: {args.url!r}")

    if args.promote is not None:
        members = [repl.primary, *repl.mirrors]
        if not 0 <= args.promote < len(members):
            ap.error(
                f"--promote {args.promote} out of range "
                f"(members: {len(members)})"
            )
        target = members[args.promote]
        survivors = [
            m for i, m in enumerate(members) if i != args.promote
        ]
        report = promote(
            target, survivors, prefix=args.prefix,
            repair=not args.no_repair,
        )
        clean = not report["unreachable"]
    elif args.drain:
        report = {
            "drained": repl.drain_handoff(),
            "handoff_pending": repl.journal.pending_counts(),
        }
        clean = (
            report["drained"]["failed"] == 0
            and not any(report["handoff_pending"].values())
        )
        report["clean"] = clean
    else:
        report = repl.scrub(args.prefix, repair=not args.no_repair)
        clean = report["clean"]

    text = json.dumps(report, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    return 0 if clean else 1


if __name__ == "__main__":
    sys.exit(main())
