"""Probe: Pallas pipeline read bandwidth vs block geometry.

The main-block DMA for a (ROWS, CB) block of a (T, 2048) f32 array
moves ROWS chunks of CB*4 contiguous bytes (row stride 8 KB).  Measures
how achieved HBM read bandwidth depends on chunk width and grid order.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

C = 2048
T = 129024  # 16128 * 8


sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from scan_harness import measure as _measure


def measure(fn, T, iters=96):
    return _measure(fn, T, C, iters)


def copy_kernel(rows, cb, k_fastest=False):
    """Read (rows, cb) blocks, emit head (rows//8, cb) rows."""
    nk = T // rows
    nc = C // cb
    out_rows = rows // 8

    def body(xm_ref, out_ref):
        out_ref[:] = xm_ref[:out_rows]

    if k_fastest:
        grid = (nc, nk)
        in_map = lambda c, k: (k, c)
        out_map = lambda c, k: (k, c)
    else:
        grid = (nk, nc)
        in_map = lambda k, c: (k, c)
        out_map = lambda k, c: (k, c)

    def fn(x):
        return pl.pallas_call(
            body,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (rows, cb), in_map, memory_space=pltpu.VMEM
                )
            ],
            out_specs=pl.BlockSpec(
                (out_rows, cb), out_map, memory_space=pltpu.VMEM
            ),
            out_shape=jax.ShapeDtypeStruct(
                (T // 8, C), jnp.float32
            ),
        )(x)

    return fn


def copy_kernel_pstream(P, rows, cb):
    """P parallel input streams — the v2 kernel's exact input pattern:
    each grid step reads P (rows, cb) blocks at consecutive k-indices
    through P separate inputs, so P auto-pipelined DMAs are in flight
    per step.  Pure copy (no compute): isolates whether multiple
    streams lift the ~185 GB/s single-stream wall toward the ~510 GB/s
    harness read ceiling — the central hypothesis behind v2's P=4
    design (PERF.md §4)."""
    nk = T // (rows * P)
    nc = C // cb
    out_rows = rows // 8
    # rows actually read per call: T may not divide by rows*P (e.g.
    # P=8 at T=129024), and crediting unread bytes would inflate
    # exactly the P-scaling comparison this probe exists to settle —
    # so the output is sized to the read coverage and the caller
    # reports bandwidth over t_eff, not T
    t_eff = nk * rows * P

    def body(*refs):
        mains = refs[:P]
        out_ref = refs[P]
        for j in range(P):
            out_ref[j * out_rows : (j + 1) * out_rows] = (
                mains[j][:out_rows]
            )

    def fn(x):
        return pl.pallas_call(
            body,
            grid=(nk, nc),
            in_specs=[
                pl.BlockSpec(
                    (rows, cb),
                    (lambda k, c, j=j: (k * P + j, c)),
                    memory_space=pltpu.VMEM,
                )
                for j in range(P)
            ],
            out_specs=pl.BlockSpec(
                (P * out_rows, cb),
                lambda k, c: (k, c),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct((t_eff // 8, C), jnp.float32),
        )(*([x] * P))

    return fn, t_eff


def main():
    for rows, cb, kf in [
        (1024, 128, False),
        (1024, 512, False),
        (1024, 1024, False),
        (512, 2048, False),
        (256, 2048, False),
        (1024, 2048, False),
        (2048, 2048, False),
        (1024, 128, True),
        (1024, 512, True),
    ]:
        try:
            dt = measure(copy_kernel(rows, cb, kf), T)
            gbps = T * C * 4 / dt / 1e9
            print(
                f"rows={rows:5d} cb={cb:5d} kfast={int(kf)}  "
                f"{dt * 1e3:7.3f} ms  {gbps:6.1f} GB/s "
                f"({gbps / 819 * 100:4.1f}%)",
                flush=True,
            )
        except Exception as exc:
            print(
                f"rows={rows} cb={cb} kfast={int(kf)}: {str(exc)[:120]}",
                flush=True,
            )

    # the P-stream question, isolated from all compute
    for P, rows, cb in [
        (1, 1024, 128),
        (2, 1024, 128),
        (4, 1024, 128),
        (8, 1024, 128),
        (4, 512, 128),
        (4, 1024, 256),
    ]:
        try:
            fn, t_eff = copy_kernel_pstream(P, rows, cb)
            dt = measure(fn, T)
            gbps = t_eff * C * 4 / dt / 1e9
            print(
                f"P={P} rows={rows:5d} cb={cb:4d}       "
                f"{dt * 1e3:7.3f} ms  {gbps:6.1f} GB/s "
                f"({gbps / 819 * 100:4.1f}%)",
                flush=True,
            )
        except Exception as exc:
            print(
                f"P={P} rows={rows} cb={cb}: {str(exc)[:120]}",
                flush=True,
            )


if __name__ == "__main__":
    main()
