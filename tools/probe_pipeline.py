"""Probe: Pallas pipeline read bandwidth vs block geometry.

The main-block DMA for a (ROWS, CB) block of a (T, 2048) f32 array
moves ROWS chunks of CB*4 contiguous bytes (row stride 8 KB).  Measures
how achieved HBM read bandwidth depends on chunk width and grid order.
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

C = 2048
T = 129024  # 16128 * 8


def measure(fn, T, iters=96):
    nw = max(1, min(6, int(9e9 // (T * C * 4))))
    rep = max(1, -(-iters // nw))
    stack = jax.jit(
        lambda key: jax.random.normal(key, (nw, T, C), jnp.float32)
    )(jax.random.PRNGKey(0))
    jax.block_until_ready(stack)

    @jax.jit
    def run(st):
        def body(tot, w):
            return tot + jnp.sum(jnp.abs(fn(w))), None

        def outer(tot, _):
            t, _ = jax.lax.scan(body, tot, st)
            return t, None

        tot, _ = jax.lax.scan(
            outer, jnp.zeros((), jnp.float32), None, length=rep
        )
        return tot

    assert np.isfinite(float(run(stack)))
    best = 1e30
    for _ in range(2):
        t0 = time.perf_counter()
        assert np.isfinite(float(run(stack)))
        best = min(best, time.perf_counter() - t0)
    return best / (nw * rep)


def copy_kernel(rows, cb, k_fastest=False):
    """Read (rows, cb) blocks, emit head (rows//8, cb) rows."""
    nk = T // rows
    nc = C // cb
    out_rows = rows // 8

    def body(xm_ref, out_ref):
        out_ref[:] = xm_ref[:out_rows]

    if k_fastest:
        grid = (nc, nk)
        in_map = lambda c, k: (k, c)
        out_map = lambda c, k: (k, c)
    else:
        grid = (nk, nc)
        in_map = lambda k, c: (k, c)
        out_map = lambda k, c: (k, c)

    def fn(x):
        return pl.pallas_call(
            body,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (rows, cb), in_map, memory_space=pltpu.VMEM
                )
            ],
            out_specs=pl.BlockSpec(
                (out_rows, cb), out_map, memory_space=pltpu.VMEM
            ),
            out_shape=jax.ShapeDtypeStruct(
                (T // 8, C), jnp.float32
            ),
        )(x)

    return fn


def main():
    for rows, cb, kf in [
        (1024, 128, False),
        (1024, 512, False),
        (1024, 1024, False),
        (512, 2048, False),
        (256, 2048, False),
        (1024, 2048, False),
        (2048, 2048, False),
        (1024, 128, True),
        (1024, 512, True),
    ]:
        try:
            dt = measure(copy_kernel(rows, cb, kf), T)
            gbps = T * C * 4 / dt / 1e9
            print(
                f"rows={rows:5d} cb={cb:5d} kfast={int(kf)}  "
                f"{dt * 1e3:7.3f} ms  {gbps:6.1f} GB/s "
                f"({gbps / 819 * 100:4.1f}%)",
                flush=True,
            )
        except Exception as exc:
            print(
                f"rows={rows} cb={cb} kfast={int(kf)}: {str(exc)[:120]}",
                flush=True,
            )


if __name__ == "__main__":
    main()
