"""Detect-subsystem bench: per-round operator overhead + throughput.

Measures what ISSUE 6 promises:

1. **Per-round overhead** — realtime driver rounds with the detect
   hook on (STA/LTA + rolling RMS, pyramid on, the production edge
   configuration), jit warm: the fraction of the full round body
   (``tpudas_stream_round_body_seconds``) spent inside the detect
   hook (``tpudas_span_seconds{name="detect.round"}``).  Acceptance:
   **< 2%** of a steady round.
2. **Operator throughput** — decimated rows/second through each
   operator's ``process`` (warm, steady 256-row blocks), plus the
   end-to-end detect row rate observed in the driver run.

The driver run feeds one interrogator file per round through the
injected ``sleep_fn`` (the streaming tests' pattern), so every round
after the first is a steady single-file round; a separate warm-up run
in the same process compiles the jitted kernels first, keeping
compile time out of the measured rounds.

CLI:

    JAX_PLATFORMS=cpu python tools/detect_bench.py [--out BENCH_pr06.json]
        [--rounds 4] [--channels 256]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

T0 = "2023-03-22T00:00:00"
# a production-shaped steady round: the reference's poll clamp is
# >= 125 s, so one round ingests ~2 minutes of full-rate data from an
# interrogator-scale array (1 kHz, 256 channels — the ROADMAP/
# SNIPPETS scale direction) — measuring the detect hook against a toy
# 20 s / 16-channel round would overstate the relative overhead ~100x
# (the hook's cost is per DECIMATED row + a constant commit, the
# round's cost is per full-rate sample)
FS = 1000.0
FILE_SEC = 120.0
N_CH = 256
DT_OUT = 1.0
EDGE_SEC = 5.0
PATCH_OUT = 60

OPS = (
    ("stalta", {"sta": 2.0, "lta": 10.0, "on": 3.0, "off": 1.5}),
    ("rms", {"window": 5.0, "step": 2.0, "thresh": 3.0,
             "baseline": 20.0}),
)


def _feed_file(src, index, n_ch):
    import numpy as np

    from tpudas.testing import make_synthetic_spool

    make_synthetic_spool(
        src, n_files=1, file_duration=FILE_SEC, fs=FS, n_ch=n_ch,
        noise=0.01,
        start=np.datetime64(T0)
        + np.timedelta64(int(index * FILE_SEC * 1e9), "ns"),
        prefix=f"raw{index:04d}",
    )


def _drive(src, out, n_ch, rounds, detect):
    """One realtime run: a fresh file lands in ``src`` on every poll
    sleep, so each processing round is a steady single-file round."""
    from tpudas.proc.streaming import run_lowpass_realtime

    fed = {"n": 2}

    def sleep(_s):
        if fed["n"] < rounds + 1:
            _feed_file(src, fed["n"], n_ch)
            fed["n"] += 1

    return run_lowpass_realtime(
        source=src, output_folder=out, start_time=T0,
        output_sample_interval=DT_OUT, edge_buffer=EDGE_SEC,
        process_patch_size=PATCH_OUT, poll_interval=0.0,
        sleep_fn=sleep, pyramid=True, detect=detect,
        detect_operators=list(OPS) if detect else None,
    )


def _hist(reg, metric, **labels):
    m = reg.get(metric)
    if m is None:
        return {"count": 0, "sum": 0.0}
    snap = m.snapshot(**labels)
    return {"count": snap["count"], "sum": snap["sum"]}


def bench_driver(n_ch=N_CH, rounds=4, workdir=None) -> dict:
    from tpudas.obs.registry import MetricsRegistry, use_registry

    workdir = workdir or tempfile.mkdtemp(prefix="detect_bench_")
    # warm-up run: compiles the filter cascade AND the detect kernels
    warm_src = os.path.join(workdir, "warm_src")
    _feed_file(warm_src, 0, n_ch)
    _feed_file(warm_src, 1, n_ch)
    _drive(warm_src, os.path.join(workdir, "warm_out"), n_ch, 2, True)
    # measured run, fresh registry
    src = os.path.join(workdir, "src")
    _feed_file(src, 0, n_ch)
    _feed_file(src, 1, n_ch)
    reg = MetricsRegistry()
    with use_registry(reg):
        n_rounds = _drive(
            src, os.path.join(workdir, "out"), n_ch, rounds, True
        )
    body = _hist(reg, "tpudas_stream_round_body_seconds")
    det = _hist(reg, "tpudas_span_seconds", name="detect.round")
    rows = reg.value("tpudas_detect_rows_total")
    events = reg.value("tpudas_detect_ledger_events")
    body_mean = body["sum"] / max(body["count"], 1)
    det_mean = det["sum"] / max(det["count"], 1)
    overhead_pct = 100.0 * det["sum"] / body["sum"] if body["sum"] else 0.0
    return {
        "channels": n_ch,
        "rounds": int(n_rounds),
        "round_body_s_mean": round(body_mean, 5),
        "detect_round_s_mean": round(det_mean, 5),
        "detect_overhead_pct": round(overhead_pct, 3),
        "driver_rows_total": int(rows),
        "driver_rows_per_s": (
            round(rows / det["sum"], 1) if det["sum"] else None
        ),
        "ledger_events": int(events),
        "op_seconds": {
            op: _hist(reg, "tpudas_detect_op_seconds", op=op)
            for op in ("stalta", "rms")
        },
    }


def bench_operators(n_ch=N_CH, n_rows=200_000, block=256) -> dict:
    """Warm steady-block throughput of each operator in isolation."""
    import numpy as np

    from tpudas.detect.operators import make_operator

    rng = np.random.default_rng(0)
    step_ns = int(DT_OUT * 1e9)
    out = {}
    for spec in OPS:
        op = make_operator(spec)
        data = (0.1 * rng.standard_normal((n_rows, n_ch))).astype(
            np.float32
        )
        t_ns = np.arange(n_rows, dtype=np.int64) * step_ns
        state = op.init_state(n_ch, step_ns)
        # warm: one block through (jit compile)
        _res, state = op.process(
            data[:block], t_ns[:block], step_ns, state
        )
        t0 = time.perf_counter()
        fed = block
        n_events = 0
        while fed + block <= n_rows:
            res, state = op.process(
                data[fed:fed + block], t_ns[fed:fed + block], step_ns,
                state,
            )
            n_events += len(res.events)
            fed += block
        wall = time.perf_counter() - t0
        out[op.name] = {
            "rows": int(fed - block),
            "wall_s": round(wall, 4),
            "rows_per_s": round((fed - block) / wall, 1),
            "channel_samples_per_s": round(
                (fed - block) * n_ch / wall, 1
            ),
            "events": int(n_events),
        }
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None, help="write JSON report here")
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--channels", type=int, default=N_CH)
    ap.add_argument("--op-rows", type=int, default=200_000)
    args = ap.parse_args(argv)
    driver = bench_driver(n_ch=args.channels, rounds=args.rounds)
    ops = bench_operators(n_ch=args.channels, n_rows=args.op_rows)
    ok = driver["detect_overhead_pct"] < 2.0
    payload = {
        "bench": "detect (PR 6)",
        "config": {
            "fs_hz": FS, "file_sec": FILE_SEC, "dt_out_s": DT_OUT,
            "operators": [list(o) for o in OPS],
        },
        "driver": driver,
        "operators": ops,
        "acceptance_overhead_lt_pct": 2.0,
        "ok": bool(ok),
    }
    text = json.dumps(payload, indent=1, default=str)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    print(text)
    print(
        f"detect_bench: overhead={driver['detect_overhead_pct']}% "
        f"of a steady round ({'OK' if ok else 'FAILED'}, bar 2%)"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
