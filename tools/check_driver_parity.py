"""Driver-parity lint: the legacy realtime drivers and the fleet
round engine accept the same ``StreamConfig`` fields.

``run_lowpass_realtime`` / ``run_rolling_realtime`` are thin shims
over :class:`tpudas.fleet.StreamConfig` + the runners (ISSUE 8).  A
shim stays compatible only while the three surfaces agree, so this
lint asserts, by introspection:

1. every :class:`StreamConfig` field is claimed by exactly the field
   sets (``COMMON_FIELDS`` + ``LOWPASS_ONLY_FIELDS`` +
   ``ROLLING_ONLY_FIELDS``) — no orphan fields, no phantom names;
2. each driver's signature = its kind's config fields + the declared
   run-control parameters (``source`` / ``output_folder`` /
   ``max_rounds`` / ``sleep_fn`` / ...), nothing more, nothing less —
   a config kwarg added to a driver but not to ``StreamConfig`` (or
   vice versa) fails here, so the shim cannot drift;
3. both runner classes construct from a ``StreamConfig`` of their
   kind (the constructors consume config by attribute, so a field
   rename breaks loudly at build time — checked with a minimal spec).

Run from anywhere:

    python tools/check_driver_parity.py

Exit code 0 = clean; 1 = violations (printed one per line).  Wired
into tier-1 via tests/test_fleet.py.
"""

from __future__ import annotations

import inspect
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def lint() -> list:
    """Returns a list of violation strings (empty = clean)."""
    from dataclasses import fields

    from tpudas.fleet.config import (
        COMMON_FIELDS,
        LOWPASS_FIELDS,
        LOWPASS_ONLY_FIELDS,
        ROLLING_FIELDS,
        ROLLING_ONLY_FIELDS,
        RUN_CONTROL_PARAMS,
        StreamConfig,
    )
    from tpudas.proc.streaming import (
        run_lowpass_realtime,
        run_rolling_realtime,
    )

    problems = []

    # 1. field sets exactly partition the dataclass (minus `kind`)
    declared = (
        set(COMMON_FIELDS) | set(LOWPASS_ONLY_FIELDS)
        | set(ROLLING_ONLY_FIELDS)
    )
    actual = {f.name for f in fields(StreamConfig)} - {"kind"}
    for name in sorted(actual - declared):
        problems.append(
            f"StreamConfig field {name!r} is not claimed by any of "
            "COMMON/LOWPASS_ONLY/ROLLING_ONLY_FIELDS"
        )
    for name in sorted(declared - actual):
        problems.append(
            f"declared field {name!r} does not exist on StreamConfig"
        )
    overlap = (
        (set(LOWPASS_ONLY_FIELDS) & set(ROLLING_ONLY_FIELDS))
        | (set(COMMON_FIELDS) & set(LOWPASS_ONLY_FIELDS))
        | (set(COMMON_FIELDS) & set(ROLLING_ONLY_FIELDS))
    )
    for name in sorted(overlap):
        problems.append(
            f"field {name!r} appears in more than one field set"
        )

    # 2. driver signature == kind fields + run-control, exactly
    for fn, kind_fields, kind in (
        (run_lowpass_realtime, LOWPASS_FIELDS, "lowpass"),
        (run_rolling_realtime, ROLLING_FIELDS, "rolling"),
    ):
        params = set(inspect.signature(fn).parameters)
        config_params = params - RUN_CONTROL_PARAMS
        for name in sorted(config_params - set(kind_fields)):
            problems.append(
                f"{fn.__name__} kwarg {name!r} is not a {kind} "
                "StreamConfig field (add it to tpudas/fleet/config.py "
                "or declare it in RUN_CONTROL_PARAMS)"
            )
        for name in sorted(set(kind_fields) - config_params):
            problems.append(
                f"{kind} StreamConfig field {name!r} is missing from "
                f"the {fn.__name__} signature (the legacy shim must "
                "accept every config field of its kind)"
            )

    # 3. the runners construct from a minimal config of their kind —
    # the constructors consume config by attribute, so a field rename
    # that slipped past 1-2 (sets and signatures updated consistently)
    # still breaks loudly here
    import tempfile

    try:
        from tpudas.fleet.config import StreamSpec
        from tpudas.fleet.engine import build_runner

        lp = StreamConfig(
            kind="lowpass",
            start_time="2023-01-01",
            output_sample_interval=1.0,
            edge_buffer=4.0,
            process_patch_size=16,
        )
        rl = StreamConfig(kind="rolling", window=1.0, step=1.0)
        with tempfile.TemporaryDirectory(
            prefix="parity_lint_"
        ) as root:
            for cfg in (lp, rl):
                build_runner(
                    StreamSpec(
                        stream_id="lint", source=root, config=cfg
                    ),
                    root=root,
                )
    except Exception as exc:
        problems.append(
            "runner/config construction check failed: "
            f"{type(exc).__name__}: {exc}"
        )
    return problems


def main(argv=None) -> int:
    problems = lint()
    for p in problems:
        print(p)
    if not problems:
        print("check_driver_parity: OK (drivers and StreamConfig agree)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
