"""Observability bench (ISSUE 13): flight-recorder + phase-timeline
overhead, and cluster-rollup wall time.

Two acceptance numbers for BENCH_pr13.json:

1. **Instrumentation overhead < 1% of the steady round body.**  A
   real realtime drive (flight recorder + phase timeline + health on)
   establishes the steady-state round-body floor and the per-round
   instrumentation volume (spans captured into the flight ring per
   round); a deterministic bundle replay then measures exactly the
   added work — 8 phase measures + the histogram finish, the span /
   round records (2x-overcounted volume), and the per-round flush —
   the same methodology as BENCH_pr02's obs overhead (whole-drive A/B
   cannot resolve a sub-percent effect under shared-CPU scheduler
   noise; the replay measures the added instructions).
2. **Rollup wall time over an 8-stream fleet.**  Synthesizes a fleet
   root (per-stream `health.json` + a flight ring of round records)
   and times `tpudas.obs.collect.cluster_snapshot` — the cost of one
   `tools/obs_report.py` / `GET /slo` evaluation.

    JAX_PLATFORMS=cpu python tools/obs_bench.py [--out BENCH_pr13.json]
        [--rounds N] [--streams 8] [--flight-rounds 120]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

FS = 100.0
FILE_SEC = 30.0
N_CH = 8
DT_OUT = 1.0
EDGE_SEC = 8.0
PATCH_OUT = 40
T0 = "2023-03-22T00:00:00"


def _drive_instrumented(td, rounds, fs=FS, n_ch=N_CH,
                        file_sec=FILE_SEC, patch_out=PATCH_OUT,
                        subdir=""):
    """One realtime drive with the full ISSUE-13 instrumentation on.
    Returns (per-round body walls, spans-per-round, flight stats)."""
    from tpudas.obs.registry import MetricsRegistry, use_registry
    from tpudas.proc.streaming import run_lowpass_realtime
    from tpudas.testing import make_synthetic_spool

    src = os.path.join(td, subdir, "src")
    out = os.path.join(td, subdir, "out")
    n_init = 2
    make_synthetic_spool(
        src, n_files=n_init, file_duration=file_sec, fs=fs, n_ch=n_ch,
        noise=0.01,
    )
    state = {"fed": 0}

    def feed(_):
        if state["fed"] < rounds - 1:
            state["fed"] += 1
            make_synthetic_spool(
                src, n_files=1, file_duration=file_sec, fs=fs,
                n_ch=n_ch, noise=0.01,
                start=np.datetime64(T0) + np.timedelta64(
                    int((n_init + state["fed"] - 1) * file_sec * 1e9),
                    "ns",
                ),
                prefix=f"raw{state['fed']}",
            )

    reg = MetricsRegistry()
    bodies = []

    def on_round(rnd, _lfp):
        hist = reg.get("tpudas_stream_round_body_seconds")
        if hist is not None:
            snap = hist.snapshot()
            bodies.append((snap["count"], snap["sum"]))

    with use_registry(reg):
        run_lowpass_realtime(
            source=src, output_folder=out, start_time=T0,
            output_sample_interval=DT_OUT, edge_buffer=EDGE_SEC,
            process_patch_size=patch_out, poll_interval=0.0,
            sleep_fn=feed, max_rounds=rounds + 2, on_round=on_round,
            health=True, pyramid=True, detect=False, flight=True,
        )
    walls = [
        bodies[i][1] - bodies[i - 1][1] for i in range(1, len(bodies))
    ]
    n_rounds = bodies[-1][0] if bodies else 0
    spans = reg.value("tpudas_obs_flight_records_total", kind="span")
    flight = {
        "records_span": int(spans),
        "records_round": int(
            reg.value("tpudas_obs_flight_records_total", kind="round")
        ),
        "bytes": int(reg.value("tpudas_obs_flight_bytes_total")),
        "drops": 0,
    }
    spans_per_round = int(np.ceil(spans / max(n_rounds, 1)))
    return walls, n_rounds, spans_per_round, flight


def _replay_cost(td, spans_per_round, reps=300):
    """Deterministic per-round cost of the ISSUE-13 instrumentation:
    the phase timeline (8 measures + histogram finish) plus the
    flight records (2x-overcounted span volume + the round record)
    and the per-round flush."""
    from tpudas.obs.flight import FlightRecorder
    from tpudas.obs.phases import PHASES, RoundPhases
    from tpudas.obs.registry import MetricsRegistry, use_registry

    folder = os.path.join(td, "replay")
    os.makedirs(folder, exist_ok=True)
    rec = FlightRecorder(folder)
    reg = MetricsRegistry()
    n_spans = 2 * max(spans_per_round, 1)
    with use_registry(reg):
        t0 = time.perf_counter()
        for i in range(reps):
            ph = RoundPhases()
            for phase in PHASES:
                with ph.measure(phase):
                    pass
            for j in range(n_spans):
                rec.record(
                    "span", stream="bench", name="op.cascade_stream",
                    depth=2, dur_s=0.01, rows=3200, round=i,
                )
            rec.record(
                "round", stream="bench", round=i, mode="stateful",
                data_seconds=30.0, realtime_factor=100.0,
                head_lag=10.0, phases=ph.finish(reg),
            )
            rec.flush()
        per_round = (time.perf_counter() - t0) / reps
    return per_round, n_spans


def _devprof_replay_cost(reps=2000):
    """Deterministic per-round cost of the devprof plane (ISSUE 17):
    the warm-key ``note_kernel`` + the ``note_launch`` bracket per
    dispatch, plus one ``round_collect`` at the boundary — measured on
    a ready jit result so the bracket takes its fast path, exactly the
    steady-state shape.  Replay methodology as BENCH_pr02/pr13: A/B
    whole-drive cannot resolve sub-percent effects, so measure the
    added instructions directly."""
    import jax
    import jax.numpy as jnp

    from tpudas.obs import devprof
    from tpudas.obs.registry import MetricsRegistry, use_registry

    fn = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros(64, jnp.float32)
    out = fn(x)
    out.block_until_ready()
    devprof.note_kernel("obs_bench", (64,), ())  # key now warm
    cost = devprof.kernel_cost("obs_bench", (64,), fn, (x,))
    # a live registry scope: the measured path must include the real
    # counter increments, not the TPUDAS_OBS=0 no-op registry
    with use_registry(MetricsRegistry()), \
            devprof.stream_scope("obs_bench"):
        t0 = time.perf_counter()
        for _ in range(reps):
            devprof.note_kernel("obs_bench", (64,), ())
            t_launch = time.perf_counter()
            devprof.note_launch("xla", t_launch, out, cost=cost)
        per_launch = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        for _ in range(reps):
            devprof.round_collect("obs_bench")
        per_collect = (time.perf_counter() - t0) / reps
    return per_launch, per_collect


def _synthesize_fleet(root, streams, flight_rounds):
    """A fleet root of `streams` synthetic members, each with a valid
    health.json and a flight ring of `flight_rounds` round records —
    what the rollup actually reads."""
    from tpudas.obs.flight import FlightRecorder
    from tpudas.obs.health import write_health
    from tpudas.obs.phases import PHASES

    for i in range(streams):
        folder = os.path.join(root, f"s{i:02d}")
        os.makedirs(folder, exist_ok=True)
        write_health(folder, {
            "rounds": flight_rounds, "polls": flight_rounds,
            "mode": "stateful", "realtime_factor": 50.0,
            "round_realtime_factor": 50.0,
            "head_lag_seconds": 20.0 + i, "redundant_ratio": 0.0,
            "carry_resume_count": 1, "last_round_wall_seconds": 0.05,
            "consecutive_failures": 0, "quarantined_files": 0,
            "degraded": False, "integrity_fallbacks": 0,
            "resource_degraded": False, "last_error": None,
        })
        rec = FlightRecorder(folder)
        for r in range(flight_rounds):
            rec.record(
                "round", stream=f"s{i:02d}", round=r + 1,
                mode="stateful", data_seconds=30.0,
                realtime_factor=50.0,
                head_lag=20.0 + (5.0 if r % 37 == 0 else 0.0),
                phases={p: 0.01 for p in PHASES},
                devprof={"launches": 1.0, "device_execute_s": 0.004,
                         "bound": "launch_bound", "utilization": 0.3},
            )
            if r % 4 == 3:
                rec.flush()
        rec.flush()


def run(out_path, rounds=6, streams=8, flight_rounds=120):
    import tempfile

    from tpudas.obs.collect import cluster_snapshot

    t_bench0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        walls, n_rounds, spans_per_round, flight = _drive_instrumented(
            td, rounds
        )
        steady = walls[1:] or walls
        floor = min(steady) if steady else 0.0
        per_round, n_spans = _replay_cost(td, spans_per_round)
        overhead_pct = (
            round(100.0 * per_round / floor, 3) if floor else None
        )

        # ISSUE 17 acceptance leg: devprof overhead < 1% of the steady
        # 1 kHz x 256 ch round.  The heavy drive establishes that
        # round's body floor; the replay measures the telemetry
        # plane's per-round added instructions (2 dispatch brackets +
        # 1 round_collect — the lowpass round's steady shape).
        heavy_walls, _hn, _hs, _hf = _drive_instrumented(
            td, rounds=4, fs=1000.0, n_ch=256, file_sec=10.0,
            patch_out=10, subdir="heavy",
        )
        heavy_steady = heavy_walls[1:] or heavy_walls
        heavy_floor = min(heavy_steady) if heavy_steady else 0.0
        per_launch, per_collect = _devprof_replay_cost()
        devprof_per_round = 2 * per_launch + per_collect
        devprof_overhead_pct = (
            round(100.0 * devprof_per_round / heavy_floor, 4)
            if heavy_floor else None
        )

        fleet_root = os.path.join(td, "fleet")
        _synthesize_fleet(fleet_root, streams, flight_rounds)
        rollup_walls = []
        snap = None
        for _ in range(5):
            t0 = time.perf_counter()
            snap = cluster_snapshot(fleet_root=fleet_root)
            rollup_walls.append(time.perf_counter() - t0)
        assert snap is not None and len(snap["fleet"]["streams"]) == streams

    report = {
        "metric": "obs_flight_phase_overhead",
        "config": {
            "fs": FS, "n_ch": N_CH, "file_sec": FILE_SEC,
            "rounds": rounds, "streams": streams,
            "flight_rounds_per_stream": flight_rounds,
        },
        "drive": {
            "rounds": int(n_rounds),
            "steady_round_body_s": [round(w, 5) for w in steady],
            "steady_round_body_floor_s": round(floor, 5),
            "spans_per_round": spans_per_round,
            "flight": flight,
        },
        "instrumentation": {
            "replayed_spans_per_round": n_spans,
            "per_round_cost_s": round(per_round, 6),
            # the acceptance number: flight + phase instrumentation as
            # a fraction of the steady round body (2x-overcounted span
            # volume; replay includes the per-round flush write)
            "overhead_pct": overhead_pct,
            "acceptance": "overhead_pct < 1.0",
        },
        "devprof": {
            "heavy_round": {"fs": 1000.0, "n_ch": 256,
                            "patch_out_s": 10.0},
            "steady_round_body_s": [round(w, 5) for w in heavy_steady],
            "steady_round_body_floor_s": round(heavy_floor, 5),
            "per_launch_cost_s": round(per_launch, 8),
            "per_round_collect_cost_s": round(per_collect, 8),
            "per_round_cost_s": round(devprof_per_round, 8),
            "overhead_pct": devprof_overhead_pct,
            "acceptance": "overhead_pct < 1.0 (ISSUE 17)",
        },
        "rollup": {
            "streams": streams,
            "wall_s": [round(w, 5) for w in rollup_walls],
            "wall_min_s": round(min(rollup_walls), 5),
            "wall_mean_s": round(
                sum(rollup_walls) / len(rollup_walls), 5
            ),
            "status": snap["status"],
        },
        "bench_wall_s": round(time.perf_counter() - t_bench0, 2),
        "ok": bool(
            overhead_pct is not None and overhead_pct < 1.0
            and devprof_overhead_pct is not None
            and devprof_overhead_pct < 1.0
        ),
    }
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=1)
        fh.write("\n")
    print(json.dumps(report))
    return report


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(REPO, "BENCH_pr13.json"))
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--streams", type=int, default=8)
    ap.add_argument("--flight-rounds", type=int, default=120)
    args = ap.parse_args()
    report = run(
        args.out, rounds=args.rounds, streams=args.streams,
        flight_rounds=args.flight_rounds,
    )
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()
