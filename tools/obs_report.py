"""Cluster observability report: one snapshot over a fleet root, a
backfill queue root, and a serve-pool control plane.

The operator CLI over :mod:`tpudas.obs.collect` (ISSUE 13).  Reads the
crash-only on-disk formats directly — per-stream ``health.json``,
flight-recorder rings, the backfill queue's plan/lease/done markers —
plus (optionally) a live ServePool's ``/pool/healthz``.  No process
cooperation needed: point it at a live cluster or a post-mortem copy.

    python tools/obs_report.py --fleet /data/fleet \
        [--backfill /data/backfill] [--pool http://host:9100] \
        [--slo-head-lag 300] [--objective 0.99] [--json] [--strict]

Text mode prints a per-stream table (status, rounds, realtime factor,
head lag, SLO status + error-budget burn, last error) and the
backfill/pool summaries; ``--json`` dumps the full snapshot.
``--strict`` exits 1 unless the overall status is ``ok`` — wire it
into a cron for a cluster-wide liveness check.  See OBSERVABILITY.md
"Cluster rollup" for the runbook.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _fmt(value, width=9):
    if value is None:
        return "-".rjust(width)
    if isinstance(value, float):
        return f"{value:.2f}".rjust(width)
    return str(value).rjust(width)


def print_text(snap: dict) -> None:
    print(f"cluster status: {snap['status']}")
    fleet = snap.get("fleet")
    if fleet is not None:
        print(
            f"\nfleet: {fleet['status']}  "
            f"(streams: {len(fleet['streams'])}, "
            f"health {fleet.get('counts')}, slo {fleet.get('slo_counts')})"
        )
        header = (
            f"{'stream':<16}{'status':>10}{'rounds':>8}"
            f"{'rt_factor':>10}{'head_lag':>10}{'slo':>10}"
            f"{'burn':>7}{'dev_util':>9}{'bound':>14}  last_error"
        )
        print(header)
        print("-" * len(header))
        for sid, e in sorted(fleet["streams"].items()):
            slo = e.get("slo", {})
            dev = e.get("devprof") or {}
            err = e.get("last_error") or ""
            fleet_ev = e.get("fleet")
            if fleet_ev:
                ev_at = fleet_ev.get(f"{fleet_ev.get('event')}_at")
                err = err or f"[{fleet_ev.get('event')} at {ev_at}]"
            print(
                f"{sid:<16}{e['status']:>10}"
                f"{_fmt(e.get('rounds'), 8)}"
                f"{_fmt(e.get('realtime_factor'), 10)}"
                f"{_fmt(e.get('head_lag_seconds'), 10)}"
                f"{slo.get('status', '-'):>10}"
                f"{_fmt(slo.get('error_budget_burn'), 7)}"
                f"{_fmt(dev.get('utilization'), 9)}"
                f"{str(dev.get('bound') or '-'):>14}  "
                f"{str(err)[:48]}"
            )
    bf = snap.get("backfill")
    if bf is not None:
        print(f"\nbackfill: {bf['status']}")
        if "shards" in bf:
            print(
                f"  shards: {bf['shards']} of {bf['shards_total']} "
                f"({100.0 * bf['done_fraction']:.1f}% done)"
            )
            if bf["workers"]:
                print(f"  live workers: {', '.join(bf['workers'])}")
            if bf["parked"]:
                print(f"  PARKED: {', '.join(bf['parked'])} "
                      "(tools/fsck.py --backfill; see RESILIENCE.md)")
            print(f"  result committed: {bf['result_done']}")
        else:
            print(f"  {bf.get('error', '')}")
    pool = snap.get("pool")
    if pool is not None:
        print(f"\nserve pool: {pool.get('status')}  ({pool.get('url')})")
        if pool.get("status") == "unreachable":
            print(f"  {pool.get('error', '')}")
        else:
            body = {k: v for k, v in pool.items()
                    if k not in ("url", "status")}
            print(f"  {json.dumps(body)[:200]}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fleet", default=None,
                    help="fleet root (one stream folder per stream)")
    ap.add_argument("--stream", default=None,
                    help="one single-stream output folder (reported as "
                         "a fleet of one)")
    ap.add_argument("--backfill", default=None,
                    help="backfill queue root (tpudas.backfill)")
    ap.add_argument("--pool", default=None,
                    help="ServePool control-plane base URL")
    ap.add_argument("--slo-head-lag", type=float, default=None,
                    help="freshness target in stream-seconds "
                         "(default TPUDAS_SLO_HEAD_LAG or 300)")
    ap.add_argument("--objective", type=float, default=0.99)
    ap.add_argument("--window", type=int, default=200,
                    help="flight rounds in the error-budget window")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--out", default=None, help="also write JSON here")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 unless overall status is ok")
    args = ap.parse_args(argv)
    if not (args.fleet or args.stream or args.backfill or args.pool):
        ap.error("nothing to report: pass --fleet, --stream, "
                 "--backfill, and/or --pool")

    from tpudas.obs.collect import (
        SLOPolicy,
        cluster_snapshot,
        overall_status,
        stream_snapshot,
        worst_status,
    )

    policy = SLOPolicy(
        head_lag_target_s=args.slo_head_lag,
        objective=args.objective,
        window=args.window,
    )
    snap = cluster_snapshot(
        fleet_root=args.fleet,
        backfill_root=args.backfill,
        pool_url=args.pool,
        policy=policy,
    )
    if args.stream:
        entry = stream_snapshot(args.stream, policy)
        fleet = snap.setdefault(
            "fleet", {"status": "ok", "streams": {}, "counts": {},
                      "slo_counts": {}},
        )
        sid = os.path.basename(os.path.normpath(args.stream))
        fleet["streams"][sid] = entry
        fleet["counts"][entry["status"]] = (
            fleet["counts"].get(entry["status"], 0) + 1
        )
        slo_s = entry["slo"]["status"]
        fleet["slo_counts"][slo_s] = (
            fleet["slo_counts"].get(slo_s, 0) + 1
        )
        fleet["status"] = worst_status(
            [e["status"] for e in fleet["streams"].values()]
            + [e["slo"]["status"] for e in fleet["streams"].values()]
        )
        snap["status"] = overall_status(snap)
    if args.as_json:
        print(json.dumps(snap, indent=1, default=str))
    else:
        print_text(snap)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(snap, fh, indent=1, default=str)
            fh.write("\n")
    if args.strict and snap["status"] != "ok":
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
