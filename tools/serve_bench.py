"""Serving-stack benchmark: cold/warm query latency, cache hit rate,
sustained QPS + load shed, and per-round pyramid-append overhead.

Produces ``BENCH_pr04.json`` (ISSUE 4 acceptance artifact):

- ``query_latency``  — cold (empty LRU, tiles off disk) vs warm
  (cache-resident) latency for the same window; acceptance:
  warm >= 10x better than cold.
- ``cache``          — hit rate over a repeated-window workload.
- ``qps``            — sustained 200-QPS from concurrent clients
  against a healthy gate, then a saturated gate (max_inflight=1 with
  the leader parked inside a tile read) to demonstrate 503 shedding.
- ``pyramid_append`` — per-round tile-pyramid append wall time as a
  percentage of the steady processing round; acceptance: < 2%.

Run from the repo root (CPU is fine):

    JAX_PLATFORMS=cpu python tools/serve_bench.py [out.json]

ISSUE 11 adds the horizontal-scale + codec sweep, producing
``BENCH_pr11.json``:

    JAX_PLATFORMS=cpu python tools/serve_bench.py --pr11 [out.json]

- ``codec_savings`` — bytes-on-disk across a FLEET of stores, per
  codec (raw vs lossless bitshuffle-deflate vs controlled-lossy
  quantize-deflate), with ratios;
- ``scaling``       — QPS + P50/P99 latency sweep over the
  :mod:`tpudas.serve.pool` worker pool (workers in {1, 2, 4, 8},
  cold- and hot-cache passes, raw vs compressed store), hammered
  from client PROCESSES so the measurement is not client-GIL-bound.
  Acceptance: >= 4x hot QPS at 8 workers vs 1.
"""

from __future__ import annotations

import io
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpudas.core.timeutils import to_datetime64  # noqa: E402
from tpudas.io.registry import write_patch  # noqa: E402
from tpudas.obs.registry import MetricsRegistry, use_registry  # noqa: E402
from tpudas.proc.streaming import run_lowpass_realtime  # noqa: E402
from tpudas.serve.query import QueryEngine  # noqa: E402
from tpudas.serve.tiles import TileStore  # noqa: E402
from tpudas.serve.http import start_server  # noqa: E402
from tpudas.testing import (  # noqa: E402
    FaultPlan,
    FaultSpec,
    install_fault_plan,
    make_synthetic_spool,
    synthetic_patch,
)

T0 = "2023-03-22T00:00:00"
FS = 200.0
FILE_SEC = 30.0
NCH = 512
FILES_PER_ROUND = 12  # 360 s of stream per steady round (slow-cadence / backlog-catchup config)


def _feed(directory, start_index, count):
    t0 = to_datetime64(T0).astype("datetime64[ns]")
    step = np.timedelta64(int(round(1e9 / FS)), "ns")
    n = int(FILE_SEC * FS)
    for i in range(start_index, start_index + count):
        p = synthetic_patch(
            t0=t0 + i * n * step, duration=FILE_SEC, fs=FS, n_ch=NCH,
            seed=i, phase_origin=t0, noise=0.01,
        )
        write_patch(p, os.path.join(directory, f"raw_{i:04d}.h5"))


def _append_hist_sum(reg) -> float:
    h = reg.get("tpudas_serve_pyramid_append_seconds")
    return h.snapshot()["sum"] if h is not None else 0.0


def _body_hist_sum(reg) -> float:
    h = reg.get("tpudas_stream_round_body_seconds")
    return h.snapshot()["sum"] if h is not None else 0.0


def build_stream(workdir, reg) -> tuple:
    """One long-running realtime invocation (pyramid on), fed one file
    batch per poll; per-round walls come from the driver's own
    ``tpudas_stream_round_body_seconds`` histogram (full round body:
    index update through health write, pyramid append included),
    snapshotted at each ``on_round``.  Round 1 (cold compile +
    whole-history backfill) is tagged so the overhead acceptance can
    exclude it.  Returns (output_folder, round_measurements)."""
    src = os.path.join(workdir, "raw")
    out = os.path.join(workdir, "results")
    make_synthetic_spool(
        src, n_files=FILES_PER_ROUND, file_duration=FILE_SEC, fs=FS,
        n_ch=NCH, noise=0.01,
    )
    feeds = [(FILES_PER_ROUND * (i + 1), FILES_PER_ROUND)
             for i in range(3)]
    marks = []

    def on_round(rnd, _lfp):
        marks.append(
            {"round": rnd, "body": _body_hist_sum(reg),
             "append": _append_hist_sum(reg)}
        )

    def fake_sleep(_):
        if feeds:
            _feed(src, *feeds.pop(0))

    with use_registry(reg):
        run_lowpass_realtime(
            source=src,
            output_folder=out,
            start_time=T0,
            output_sample_interval=1.0,
            edge_buffer=8.0,
            process_patch_size=60,
            poll_interval=0.0,
            file_duration=0.0,
            sleep_fn=fake_sleep,
            on_round=on_round,
            pyramid=True,
        )
    rounds = []
    prev_b = prev_a = 0.0
    for m in marks:
        rounds.append({
            "kind": "backfill" if m["round"] == 1 else "steady",
            "round_wall_s": m["body"] - prev_b,
            "append_wall_s": m["append"] - prev_a,
        })
        prev_b, prev_a = m["body"], m["append"]
    return out, rounds


def bench_latency(out, workdir, reg) -> dict:
    """Cold (fresh engine, empty cache, tiles off disk) vs warm (same
    engine, same window) query latency — on a tile-granular rebuild
    (``tile_len=32``) so a full-stream window spans many tiles, the
    shape a long-lived deployment has."""
    import glob as _glob
    import shutil as _shutil

    from tpudas.serve.tiles import sync_pyramid

    folder = os.path.join(workdir, "latency")
    os.makedirs(folder)
    for f in _glob.glob(os.path.join(out, "*.h5")):
        _shutil.copy(f, folder)
    sync_pyramid(folder, tile_len=32)
    store = TileStore.open(folder)
    lo = np.datetime64(store.t0_ns, "ns")
    hi = np.datetime64(store.head_ns - store.step_ns, "ns")
    with use_registry(reg):
        engine = QueryEngine(folder)
        t0 = time.perf_counter()
        cold_result = engine.query(lo, hi)
        cold_s = time.perf_counter() - t0
        warm = []
        for _ in range(100):
            t0 = time.perf_counter()
            engine.query(lo, hi)
            warm.append(time.perf_counter() - t0)
    warm_s = float(np.median(warm))
    return {
        "window_samples": int(cold_result.n_samples),
        "window_channels": int(cold_result.distance.size),
        "tiles_in_window": -(-int(cold_result.n_samples) // 32),
        "cold_ms": round(cold_s * 1e3, 3),
        "warm_ms_median": round(warm_s * 1e3, 3),
        "speedup": round(cold_s / warm_s, 1) if warm_s else None,
        "accept_10x": bool(warm_s and cold_s / warm_s >= 10.0),
    }


def bench_cache(out, reg) -> dict:
    """Hit rate over a dashboard-like workload: 8 distinct windows,
    each queried 16 times at mixed zooms."""
    store = TileStore.open(out)
    span_ns = store.head_ns - store.t0_ns
    with use_registry(reg):
        engine = QueryEngine(out)
        for rep in range(16):
            for w in range(8):
                lo = store.t0_ns + (w * span_ns) // 10
                hi = store.t0_ns + ((w + 2) * span_ns) // 10
                engine.query(
                    np.datetime64(lo, "ns"), np.datetime64(hi, "ns"),
                    max_samples=64 if w % 2 else None,
                )
        hits = reg.value("tpudas_serve_cache_hits_total")
        misses = reg.value("tpudas_serve_cache_misses_total")
    total = hits + misses
    return {
        "queries": 16 * 8,
        "tile_hits": int(hits),
        "tile_misses": int(misses),
        "hit_rate": round(hits / total, 4) if total else None,
    }


def bench_qps(out, reg) -> dict:
    """Concurrent clients against a healthy gate (sustained 200-QPS),
    then against a saturated gate (503 shedding demonstrated)."""
    url_tail = "/query?t0=2023-03-22T00:00:20&t1=2023-03-22T00:01:20"

    def hammer(base_url, n_threads, duration_s):
        stop = time.time() + duration_s
        ok, shed, errs = [0], [0], [0]
        lock = threading.Lock()

        def client():
            while time.time() < stop:
                try:
                    r = urllib.request.urlopen(base_url + url_tail,
                                               timeout=10)
                    r.read()
                    with lock:
                        ok[0] += 1
                except urllib.error.HTTPError as e:
                    with lock:
                        (shed if e.code == 503 else errs)[0] += 1
                except OSError:
                    with lock:
                        errs[0] += 1

        threads = [threading.Thread(target=client)
                   for _ in range(n_threads)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        return ok[0], shed[0], errs[0], elapsed

    with use_registry(reg):
        with start_server(out, max_inflight=8) as srv:
            ok, shed, errs, elapsed = hammer(srv.base_url, 8, 2.0)
        healthy = {
            "threads": 8, "max_inflight": 8,
            "ok": ok, "shed_503": shed, "errors": errs,
            "qps_ok": round(ok / elapsed, 1),
        }
        # saturation: one worker slot, its leader parked in a tile
        # read while clients keep arriving -> immediate 503s
        release = threading.Event()

        def park(_):
            release.wait(timeout=10)

        plan = FaultPlan(
            FaultSpec(site="serve.tile_read", action="delay", at=1,
                      times=1, seconds=0.0, sleep_fn=park)
        )
        with install_fault_plan(plan), start_server(
            out, max_inflight=1, cache_tiles=2
        ) as srv:
            timer = threading.Timer(0.5, release.set)
            timer.start()
            ok, shed, errs, elapsed = hammer(srv.base_url, 4, 1.0)
            timer.cancel()
            release.set()
        saturated = {
            "threads": 4, "max_inflight": 1,
            "ok": ok, "shed_503": shed, "errors": errs,
        }
    return {"healthy": healthy, "saturated": saturated,
            "sheds_under_saturation": bool(saturated["shed_503"] > 0)}


def pyramid_overhead(round_measurements) -> dict:
    """Pyramid-append wall time as % of a steady round's FULL wall
    (poll + index update + read + filter + write + carry + health +
    the append itself).  The backfill round (compile warm-up + whole-
    history catch-up) is reported but excluded from the acceptance
    figure — it is a one-time cost, not the per-round cost."""
    steady = [r for r in round_measurements if r["kind"] == "steady"]
    backfill = [r for r in round_measurements if r["kind"] == "backfill"]
    round_s = sum(r["round_wall_s"] for r in steady)
    append_s = sum(r["append_wall_s"] for r in steady)
    pct = (append_s / round_s * 100.0) if round_s else None
    return {
        "backfill_round_wall_s": round(
            sum(r["round_wall_s"] for r in backfill), 4
        ),
        "backfill_append_wall_s": round(
            sum(r["append_wall_s"] for r in backfill), 4
        ),
        "steady_rounds": len(steady),
        "steady_round_wall_s": round(round_s, 4),
        "steady_append_wall_s": round(append_s, 4),
        "steady_data_seconds_per_round": FILES_PER_ROUND * FILE_SEC,
        "overhead_pct": round(pct, 3) if pct is not None else None,
        "accept_lt_2pct": bool(pct is not None and pct < 2.0),
    }


# ---------------------------------------------------------------------------
# ISSUE 11: worker-pool scaling sweep + fleet codec savings

PR11_CODECS = (
    ("raw", None),
    ("bitshuffle-deflate", "bitshuffle-deflate"),
    ("quantize-deflate@1e-3", "quantize-deflate:max_error=1e-3"),
)
PR11_WORKER_COUNTS = (1, 2, 4, 8)
PR11_FLEET_STORES = 3
PR11_MEASURE_S = 2.0
PR11_CLIENT_PROCS = 8
PR11_THREADS_PER_PROC = 4


def _pr11_outputs(folder, seed, n_ch=256, seconds=480, fs=4.0):
    """One synthetic processed-output stream (what the realtime
    driver would have written) — codec input that looks like real
    decimated DAS: band-limited signal + noise, with a gap."""
    from tpudas.testing import synthetic_patch

    os.makedirs(folder, exist_ok=True)
    t0 = to_datetime64(T0).astype("datetime64[ns]")
    n_files, file_s = 4, seconds // 4
    for i in range(n_files):
        if i == 2:
            continue  # a missing span: NaN-gap tiles are part of the job
        p = synthetic_patch(
            t0=t0 + np.timedelta64(int(i * file_s), "s"),
            duration=float(file_s), fs=fs, n_ch=n_ch, seed=seed * 17 + i,
            noise=0.05,
        )
        write_patch(p, os.path.join(folder, f"LFDAS_{i:04d}.h5"))


def _tree_bytes(path: str) -> int:
    total = 0
    for dirpath, _dirs, files in os.walk(path):
        for fn in files:
            total += os.path.getsize(os.path.join(dirpath, fn))
    return total


def bench_codec_savings(workdir: str) -> tuple:
    """Build PR11_FLEET_STORES stream stores per codec from the same
    outputs; report aggregate ``.tiles/`` bytes + ratios.  Returns
    (report, {codec_label: [store_folder, ...]}) so the scaling sweep
    reuses the built stores."""
    from tpudas.serve.tiles import sync_pyramid

    sources = []
    for s in range(PR11_FLEET_STORES):
        src = os.path.join(workdir, f"src_{s}")
        _pr11_outputs(src, seed=s)
        sources.append(src)
    report = {"fleet_stores": PR11_FLEET_STORES, "per_codec": {}}
    folders: dict = {}
    raw_bytes = None
    for label, spec in PR11_CODECS:
        folders[label] = []
        total = 0
        t0 = time.perf_counter()
        for s, src in enumerate(sources):
            folder = os.path.join(workdir, f"store_{label}_{s}")
            shutil.copytree(src, folder)
            sync_pyramid(folder, tile_len=256, codec=spec)
            total += _tree_bytes(os.path.join(folder, ".tiles"))
            folders[label].append(folder)
        entry = {
            "tiles_bytes": total,
            "encode_wall_s": round(time.perf_counter() - t0, 2),
        }
        if label == "raw":
            raw_bytes = total
        else:
            entry["ratio_vs_raw"] = round(raw_bytes / total, 3)
            entry["savings_pct"] = round(
                (1 - total / raw_bytes) * 100, 1
            )
        report["per_codec"][label] = entry
    return report, folders


def _pr11_client(base_url, url_tails, stop_at, out_q):
    """One hammer CLIENT PROCESS: a few threads, each holding ONE
    persistent (keep-alive) connection and walking the window set
    until the deadline — the CDN/edge connection shape, and the only
    client that can actually saturate an 8-worker pool.  Reports
    (ok, shed_503, errors, latencies)."""
    import http.client as _hc
    import threading as _threading
    import time as _time
    import urllib.parse as _up

    host = _up.urlsplit(base_url).netloc
    ok, shed, errs = [0], [0], [0]
    lats: list = []
    lock = _threading.Lock()

    def worker(offset):
        conn = _hc.HTTPConnection(host, timeout=30)
        i = offset
        while _time.time() < stop_at:
            tail = url_tails[i % len(url_tails)]
            i += 1
            t0 = _time.perf_counter()
            try:
                conn.request("GET", tail)
                r = conn.getresponse()
                r.read()
                dt = _time.perf_counter() - t0
                with lock:
                    if r.status == 503:
                        shed[0] += 1
                    elif r.status == 200:
                        ok[0] += 1
                        lats.append(dt)
                    else:
                        errs[0] += 1
            except Exception:
                conn.close()
                conn = _hc.HTTPConnection(host, timeout=30)
                with lock:
                    errs[0] += 1
        conn.close()

    threads = [
        _threading.Thread(target=worker, args=(j,))
        for j in range(PR11_THREADS_PER_PROC)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    out_q.put((ok[0], shed[0], errs[0], lats))


def _pr11_hammer(base_url, url_tails, duration_s) -> dict:
    """Hammer from PR11_CLIENT_PROCS separate processes (the client
    must not be the GIL bottleneck when 8 server workers scale)."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    out_q = ctx.Queue()
    stop_at = time.time() + duration_s + 1.0  # workers start inside
    procs = [
        ctx.Process(
            target=_pr11_client,
            args=(base_url, url_tails, stop_at, out_q),
        )
        for _ in range(PR11_CLIENT_PROCS)
    ]
    t0 = time.perf_counter()
    for p in procs:
        p.start()
    results = [out_q.get(timeout=120) for _ in procs]
    for p in procs:
        p.join(timeout=30)
    elapsed = time.perf_counter() - t0
    ok = sum(r[0] for r in results)
    shed = sum(r[1] for r in results)
    errs = sum(r[2] for r in results)
    lats = np.concatenate(
        [np.asarray(r[3]) for r in results if r[3]]
    ) if any(r[3] for r in results) else np.asarray([0.0])
    return {
        "ok": int(ok),
        "shed_503": int(shed),
        "errors": int(errs),
        "wall_s": round(elapsed, 2),
        "qps": round(ok / elapsed, 1),
        "p50_ms": round(float(np.percentile(lats, 50)) * 1e3, 2),
        "p99_ms": round(float(np.percentile(lats, 99)) * 1e3, 2),
    }


def bench_scaling(folders: dict) -> dict:
    """QPS/P99 over the SO_REUSEPORT worker pool: workers in
    PR11_WORKER_COUNTS x {cold, hot} cache x {raw, compressed}
    store."""
    from tpudas.serve.pool import ServePool
    from tpudas.serve.tiles import TileStore

    report: dict = {}
    for label in ("raw", "bitshuffle-deflate"):
        folder = folders[label][0]
        store = TileStore.open(folder)
        lo = store.t0_ns
        hi = store.head_ns - store.step_ns
        span = hi - lo
        # a dashboard-shaped window set: 8 panes x 2 zooms
        url_tails = []
        for w in range(8):
            a = lo + (w * span) // 10
            b = lo + ((w + 2) * span) // 10
            url_tails.append(
                f"/query?t0={a}&t1={b}&max_samples=64"
            )
            url_tails.append(f"/query?t0={a}&t1={b}")
        per_workers: dict = {}
        for n in PR11_WORKER_COUNTS:
            with ServePool(folder, port=0, workers=n) as pool:
                # cold pass: every worker's LRU empty — the first
                # touch of each (tile, worker) pays the disk+decode
                cold = _pr11_hammer(
                    pool.base_url, url_tails, PR11_MEASURE_S
                )
                hot = _pr11_hammer(
                    pool.base_url, url_tails, PR11_MEASURE_S
                )
            per_workers[str(n)] = {"cold": cold, "hot": hot}
            print(
                f"  [{label}] workers={n}: hot {hot['qps']} qps "
                f"(p99 {hot['p99_ms']} ms), cold {cold['qps']} qps",
                flush=True,
            )
        base = per_workers[str(PR11_WORKER_COUNTS[0])]["hot"]["qps"]
        peak_n = str(PR11_WORKER_COUNTS[-1])
        peak = per_workers[peak_n]["hot"]["qps"]
        report[label] = {
            "workers": per_workers,
            "speedup_8v1_hot": round(peak / base, 2) if base else None,
            "accept_4x": bool(base and peak / base >= 4.0),
        }
    return report


def main_pr11(out_path: str) -> int:
    t_start = time.time()
    with tempfile.TemporaryDirectory() as workdir:
        print("building fleet stores per codec ...", flush=True)
        savings, folders = bench_codec_savings(workdir)
        print(json.dumps(savings, indent=1), flush=True)
        print("scaling sweep ...", flush=True)
        scaling = bench_scaling(folders)
    result = {
        "bench": "serve_pool_codec",
        "pr": 11,
        "config": {
            "fleet_stores": PR11_FLEET_STORES,
            "worker_counts": list(PR11_WORKER_COUNTS),
            "client_procs": PR11_CLIENT_PROCS,
            "threads_per_proc": PR11_THREADS_PER_PROC,
            "measure_seconds": PR11_MEASURE_S,
            "baseline": "BENCH_pr04.json qps.healthy (~120 qps, one "
                        "ThreadingHTTPServer process)",
        },
        "codec_savings": savings,
        "scaling": scaling,
        "wall_seconds": round(time.time() - t_start, 1),
    }
    with open(out_path, "w") as fh:
        json.dump(result, fh, indent=1)
        fh.write("\n")
    print(json.dumps(result, indent=1))
    ok = all(v["accept_4x"] for v in scaling.values())
    print(f"serve_bench --pr11: {'OK' if ok else 'ACCEPTANCE FAILED'} "
          f"-> {out_path}")
    return 0 if ok else 1


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if argv and argv[0] == "--pr11":
        out = (
            argv[1] if len(argv) > 1
            else os.path.join(REPO, "BENCH_pr11.json")
        )
        return main_pr11(out)
    out_path = argv[0] if argv else os.path.join(REPO, "BENCH_pr04.json")
    reg = MetricsRegistry()
    t_start = time.time()
    with tempfile.TemporaryDirectory() as workdir:
        folder, round_meas = build_stream(workdir, reg)
        store = TileStore.open(folder)
        result = {
            "bench": "serve",
            "pr": 4,
            "config": {
                "fs": FS, "n_ch": NCH, "file_seconds": FILE_SEC,
                "files": FILES_PER_ROUND * 4,
                "files_per_round": FILES_PER_ROUND,
                "pyramid_levels": store.levels,
                "pyramid_factor": store.factor,
                "tile_len": store.tile_len,
            },
            "query_latency": bench_latency(folder, workdir, reg),
            "cache": bench_cache(folder, reg),
            "qps": bench_qps(folder, reg),
            "pyramid_append": pyramid_overhead(round_meas),
        }
    result["wall_seconds"] = round(time.time() - t_start, 1)
    with open(out_path, "w") as fh:
        json.dump(result, fh, indent=1)
        fh.write("\n")
    print(json.dumps(result, indent=1))
    ok = (
        result["query_latency"]["accept_10x"]
        and result["pyramid_append"]["accept_lt_2pct"]
        and result["qps"]["sheds_under_saturation"]
    )
    print(f"serve_bench: {'OK' if ok else 'ACCEPTANCE FAILED'} "
          f"-> {out_path}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
