"""Codec-matrix lint: every registered tile codec is exercised by the
test suite.

ISSUE 11 made the tile format pluggable (:mod:`tpudas.codec`): a
codec id that registers but is never round-tripped in tests is
exactly how a format rots — its tiles would be written in production
and first *read* during an incident.  Same pattern as
``tools/check_engines.py``: the accepted id set is imported from the
registry itself (a new codec is flagged the moment it registers) and
each id must appear as a quoted string somewhere under ``tests/`` —
the roundtrip test matrix must name every codec it claims to cover.

Run from anywhere:

    python tools/check_codecs.py

Exit code 0 = clean; 1 = violations (printed one per line).  Wired
into tier-1 via tests/test_codec_lint.py.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TESTS_DIR = "tests"

# the lint's own tier-1 wrapper quotes ids while testing the LINT —
# counting those would make the check vacuously green
EXCLUDE_TESTS = ("test_codec_lint.py",)


def registered_ids() -> tuple:
    """The codec ids the registry accepts, read from the registry
    itself (import, not regex — a rename breaks the lint loudly)."""
    from tpudas.codec import codec_ids

    return codec_ids()


def tested_literals(tests_root: str) -> set:
    """Every quoted string literal appearing in the test sources —
    the test matrix's vocabulary."""
    seen = set()
    lit = re.compile(r"['\"]([A-Za-z0-9_-]+)['\"]")
    for dirpath, _dirs, files in os.walk(tests_root):
        for fn in sorted(files):
            if not fn.endswith(".py") or fn in EXCLUDE_TESTS:
                continue
            with open(os.path.join(dirpath, fn)) as fh:
                seen.update(lit.findall(fh.read()))
    return seen


def lint(repo: str = REPO) -> list:
    tests_root = os.path.join(repo, TESTS_DIR)
    if not os.path.isdir(tests_root):
        return [f"missing tests directory at {tests_root}"]
    seen = tested_literals(tests_root)
    problems = []
    for cid in registered_ids():
        if cid not in seen:
            problems.append(
                f"codec id {cid!r} (registered in tpudas.codec) "
                f"never appears in {TESTS_DIR}/ — add it to the "
                "roundtrip test matrix or unregister it"
            )
    return problems


def main(argv=None) -> int:
    repo = (argv or [None])[1] if argv and len(argv) > 1 else REPO
    problems = lint(repo)
    for p in problems:
        print(p)
    if not problems:
        print(
            f"check_codecs: OK ({len(registered_ids())} codec ids "
            "covered)"
        )
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
