"""Broad-except lint: no NEW silent ``except Exception`` blocks.

The robustness PR's guard rail: a handler that catches ``Exception``
(or ``BaseException``, or is a bare ``except:``) and neither re-raises
nor logs is a black hole — exactly the pattern that made real IO
errors read as "no outputs" in the realtime driver
(tpudas/proc/streaming.py legacy-folder probe, fixed in PR 3).  This
lint parses every source under ``tpudas/``, ``tools/`` and
``bench.py`` with ``ast`` and fails on any such handler that is not in
the checked-in allowlist of pre-existing sites
(``tools/except_allowlist.txt``, one ``path::qualname`` per line).

"Logs" means the handler body (recursively) performs any of: a
``raise``; a call to ``log_event`` / ``print`` / ``warnings.warn`` /
``_record_drop``; a metric update (``.inc`` / ``.observe`` / ``.set``
on anything); or a ``logging``-style ``.warning/.error/.exception``
call.  The allowlist is keyed by enclosing-function qualname (not line
number) so unrelated edits to a file do not churn it.

Run from anywhere:

    python tools/check_excepts.py

Exit code 0 = clean; 1 = violations (printed one per line).  Wired
into tier-1 via tests/test_excepts_lint.py.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCAN_ROOTS = ("tpudas", "tools")
SCAN_FILES = ("bench.py",)
ALLOWLIST = os.path.join("tools", "except_allowlist.txt")

_BROAD_NAMES = {"Exception", "BaseException"}
# a call to any of these names counts as "the failure was surfaced"
_LOG_FUNC_NAMES = {"log_event", "print", "_record_drop"}
# ...as does a method call with any of these attribute names (metric
# updates, logging loggers, stderr writes)
_LOG_ATTR_NAMES = {
    "inc", "observe", "set", "warn", "warning", "error", "exception",
    "write", "log_event",
}


def iter_source_files(repo: str = REPO):
    for root_name in SCAN_ROOTS:
        for dirpath, _dirnames, filenames in os.walk(
            os.path.join(repo, root_name)
        ):
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)
    for fn in SCAN_FILES:
        path = os.path.join(repo, fn)
        if os.path.isfile(path):
            yield path


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except:
        return True
    names = []
    for node in [t] + (list(t.elts) if isinstance(t, ast.Tuple) else []):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return any(n in _BROAD_NAMES for n in names)


def _handles(handler: ast.ExceptHandler) -> bool:
    """True when the handler body re-raises or logs (see module doc)."""
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id in _LOG_FUNC_NAMES:
                return True
            if isinstance(f, ast.Attribute) and f.attr in _LOG_ATTR_NAMES:
                return True
    return False


def _qualnames(tree: ast.AST) -> dict:
    """{node id: dotted qualname of the enclosing def/class chain}."""
    out = {}

    def visit(node, stack):
        for child in ast.iter_child_nodes(node):
            s = stack
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                s = stack + [child.name]
            out[id(child)] = ".".join(s) or "<module>"
            visit(child, s)

    out[id(tree)] = "<module>"
    visit(tree, [])
    return out


def lint_source(rel: str, text: str, allowed: set) -> list:
    """Violation strings for one source file (empty = clean)."""
    try:
        tree = ast.parse(text)
    except SyntaxError as exc:
        return [f"{rel}: unparseable ({exc})"]
    quals = _qualnames(tree)
    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node) or _handles(node):
            continue
        key = f"{rel}::{quals.get(id(node), '<module>')}"
        if key in allowed:
            continue
        problems.append(
            f"{key}: silent broad except at line {node.lineno} — "
            "re-raise, log_event, or add the site to "
            f"{ALLOWLIST} with a justification"
        )
    return problems


def load_allowlist(repo: str = REPO) -> set:
    path = os.path.join(repo, ALLOWLIST)
    allowed = set()
    if os.path.isfile(path):
        with open(path) as fh:
            for line in fh:
                line = line.split("#", 1)[0].strip()
                if line:
                    allowed.add(line)
    return allowed


def main(argv=None) -> int:
    repo = (argv or [None, REPO])[1] if argv and len(argv) > 1 else REPO
    allowed = load_allowlist(repo)
    problems = []
    n_files = 0
    for path in iter_source_files(repo):
        rel = os.path.relpath(path, repo).replace(os.sep, "/")
        with open(path) as fh:
            text = fh.read()
        problems.extend(lint_source(rel, text, allowed))
        n_files += 1
    for p in problems:
        print(p)
    if not problems:
        print(
            f"check_excepts: OK ({n_files} files, "
            f"{len(allowed)} allowlisted sites)"
        )
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
