#!/bin/bash
# Watcher for the REORDERED campaign (tools/chip_campaign2.sh):
# probe the tunneled backend until it answers, then immediately spend
# the alive-window on the judge-critical artifacts (bench first).
# campaign2 exits 0 only when ALL steps have .done markers, so a
# mid-campaign tunnel wedge resumes watching and the next alive-window
# picks up at the first incomplete step.
#
# Probe cadence: LONG quiet periods with backoff.  Wedge forensics
# (NOTES_r05): in 12 h of history the tunnel recovered exactly once —
# during the only probe-free hour — while 10+ h of 9-minute probing
# never saw a recovery.  If killed probe clients reset the server's
# cleanup, frequent probing PREVENTS recovery; the quiet-period
# schedule bets on that mechanism while still catching a scheduled
# restart within ~40 min.  Probe timeout is 45 s (healthy init takes
# 8-12 s) so a doomed probe holds its connection as briefly as
# possible.
cd "$(dirname "$0")/.."
# Expire well before the round driver's own end-of-round bench run: a
# campaign starting late would hold a second tunnel client open during
# the official BENCH_r05.json capture.  Override: WATCH_EXPIRE_AT=<epoch>.
EXPIRE_AT=${WATCH_EXPIRE_AT:-$(( $(date +%s) + 28800 ))}  # 8h default
# Quiet schedule: the only observed recovery in 13+ h of wedge history
# followed a ~76-minute probe-free gap, while 9-minute and 40-minute
# cadences never saw one — so the steady state is 75-minute quiets
# (override: WATCH_SLEEPS="s1 s2 ...").
SLEEPS=(${WATCH_SLEEPS:-420 900 2400 4500 4500})
si=0
# WATCH_DELAY_FIRST: seconds of quiet BEFORE the first probe — lets a
# restarted watcher finish out the quiet period already in progress
# instead of resetting it with an immediate probe.
if [ -n "${WATCH_DELAY_FIRST:-}" ]; then
  echo "initial quiet ${WATCH_DELAY_FIRST}s before first probe"
  sleep "$WATCH_DELAY_FIRST"
fi
for i in $(seq 1 90); do
  if [ "$(date +%s)" -ge "$EXPIRE_AT" ]; then
    echo "watch window expired at $(date -u +%H:%M:%S) — exiting"
    exit 1
  fi
  if timeout 45 python -c "
import jax
assert jax.default_backend() != 'cpu'
import jax.numpy as jnp
assert float((jnp.ones((128,128)) @ jnp.ones((128,128))).sum()) == 128.0*128*128
print('TPU ALIVE:', jax.devices())
" 2>/dev/null; then
    echo "tpu up on probe $i at $(date -u +%H:%M:%S) — starting campaign2"
    mkdir -p chip_r05
    bash tools/chip_campaign2.sh 2>&1 | tee -a chip_r05/campaign2.log
    rc=${PIPESTATUS[0]}
    if [ "$rc" -eq 0 ]; then
      echo "campaign2 complete at $(date -u +%H:%M:%S)"
      exit 0
    fi
    # tunnel flapped mid-campaign: the probe WAS alive, so re-probe
    # after a short breather, then fall back into the quiet schedule
    echo "campaign2 rc=$rc at $(date -u +%H:%M:%S) — re-probing shortly"
    si=0
    sleep 90
    continue
  fi
  d=${SLEEPS[$si]}
  [ "$si" -lt $(( ${#SLEEPS[@]} - 1 )) ] && si=$(( si + 1 ))
  echo "probe $i: dead at $(date -u +%H:%M:%S); quiet ${d}s"
  sleep "$d"
done
echo "gave up after $i probes"
exit 1
