"""fsck for a tpudas output folder: audit (and repair) durable state.

Operator CLI over :func:`tpudas.integrity.audit`, the same scan the
realtime drivers run automatically before their first round.  Checks
every durable artifact beside the stream — carry, quarantine ledger,
health snapshot, directory-index cache, tile pyramid — verifies
checksums, classifies defects (unstamped / torn / corrupt / stale-tmp
/ orphan tile), and repairs via the degradation ladder (restamp,
promote ``.prev``, remove, rebuild the pyramid from the outputs).

    JAX_PLATFORMS=cpu python tools/fsck.py OUTPUT_FOLDER [options]

Options:
    --no-repair     report only; change nothing on disk
    --no-rebuild    repair everything except pyramid rebuilds
    --fleet         treat the folder as a fleet root: audit every
                    <root>/<stream_id>/ independently and aggregate
                    (tpudas.integrity.audit.audit_fleet, FLEET.md)
    --backfill      treat the folder as a backfill queue root: sweep
                    stale leases / orphan stagings, finish crashed
                    commits, audit committed shards + the stitched
                    result (tpudas.integrity.audit.audit_backfill,
                    RESILIENCE.md "Cluster backfill")
    --store URL     audit an OBJECT-STORE backfill job instead: the
                    positional argument is the job prefix inside the
                    store named by URL (file:///path, s3://bucket/...,
                    fake:tag, replica:urlA,urlB,...); classifies torn
                    markers/leases, crashed commits, orphan objects,
                    and torn partial uploads from list() +
                    content-token verification
                    (tpudas.integrity.audit.audit_backfill_store).
                    A replica: URL additionally runs the anti-entropy
                    scrub (drain handoff journal, repair divergent
                    mirrors, sweep debris on every replica) and folds
                    its verdict into "clean" — see also
                    tools/store_scrub.py for scrub/promotion alone
    --out PATH      also write the JSON report to PATH

Run only while the driver is stopped: the stale-tmp sweep cannot tell
a crashed writer's leftovers from a live writer's in-flight file.

Exit code 0 when the folder is clean after the run (every issue
repaired, or no issues), 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("folder", help="output folder to audit")
    ap.add_argument(
        "--no-repair", action="store_true",
        help="report only; change nothing on disk",
    )
    ap.add_argument(
        "--no-rebuild", action="store_true",
        help="repair everything except pyramid rebuilds",
    )
    ap.add_argument(
        "--fleet", action="store_true",
        help="audit every <folder>/<stream_id>/ as a fleet root",
    )
    ap.add_argument(
        "--backfill", action="store_true",
        help="audit the folder as a tpudas.backfill queue root",
    )
    ap.add_argument(
        "--store", default=None, metavar="URL",
        help="audit an object-store backfill job: FOLDER is the job "
             "prefix inside this store URL",
    )
    ap.add_argument("--out", default=None, help="write JSON report here")
    args = ap.parse_args(argv)
    if args.fleet and args.backfill:
        ap.error("--fleet and --backfill are mutually exclusive")
    if args.store and args.fleet:
        ap.error("--store and --fleet are mutually exclusive")

    from tpudas.integrity.audit import (
        audit,
        audit_backfill,
        audit_backfill_store,
        audit_fleet,
    )

    if args.store:
        from tpudas.store import store_from_url

        report = audit_backfill_store(
            store_from_url(args.store),
            args.folder,
            repair=not args.no_repair,
        )
    elif args.backfill:
        report = audit_backfill(
            args.folder,
            repair=not args.no_repair,
            rebuild=not args.no_rebuild,
        )
    else:
        report = (audit_fleet if args.fleet else audit)(
            args.folder,
            repair=not args.no_repair,
            rebuild=not args.no_rebuild,
        )
    text = json.dumps(report, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    return 0 if report["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
