"""Measure the HOST half of the e2e path at north-star width — no TPU.

The 10k-channel e2e breakdown (VERDICT r4 item 3) has two independent
halves: the C++ windowed assembly (tdas index -> threaded read ->
merged window) and the device cascade.  The device half is measured by
bench.py on the chip; this tool measures the assembly half on whatever
host it runs on, so the bottleneck table in PERF.md §6 can be filled
in even when the TPU tunnel is down.

Methodology: synthesize an int16 tdas spool at (HAR_FS, HAR_C) for
HAR_SEC seconds of stream, then assemble the same overlap-save windows
LFProc would schedule (HAR_PATCH patch + 2*HAR_EDGE halo) and report
channel-samples/sec and MB/s of assembled window bytes.  Synthesis is
excluded from the timed region.  NOTE the host core count in the
output: the assembler is thread-parallel, so single-digit-core dev
boxes report a lower bound.

Run: python tools/host_assembly_rate.py
Env: HAR_C (10000), HAR_SEC (60), HAR_FS (1000), HAR_PATCH (60),
     HAR_EDGE (10), HAR_DTYPE (int16)
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, ".")


def main() -> None:
    C = int(os.environ.get("HAR_C", 10000))
    sec = int(os.environ.get("HAR_SEC", 60))
    fs = float(os.environ.get("HAR_FS", 1000.0))
    patch = float(os.environ.get("HAR_PATCH", 60.0))
    edge = float(os.environ.get("HAR_EDGE", 10.0))
    dtype = os.environ.get("HAR_DTYPE", "int16")

    from tpudas import spool as make_spool
    from tpudas.io.tdas import assemble_window_patch
    from tpudas.native import load_streamio
    from tpudas.testing import make_synthetic_spool

    native = load_streamio() is not None
    ncpu = os.cpu_count() or 1
    print(f"host: {ncpu} cores, native streamio: {native}", flush=True)

    file_sec = 30.0
    n_files = max(1, round(sec / file_sec))
    sec = n_files * file_sec
    wk = {"dtype": "int16", "scale": 1e-3} if dtype == "int16" else None
    with tempfile.TemporaryDirectory() as td:
        t0 = time.perf_counter()
        make_synthetic_spool(
            td, n_files=n_files, file_duration=file_sec, fs=fs, n_ch=C,
            noise=0.01, lf_freq=0.05, format="tdas", write_kwargs=wk,
        )
        print(f"synthesized {sec:.0f}s x {C}ch @ {fs:.0f}Hz {dtype} in "
              f"{time.perf_counter() - t0:.1f}s", flush=True)
        sp = make_spool(td).sort("time").update()
        frame = sp.get_contents()
        t_start = frame["time_min"].min()
        t_end = frame["time_max"].max()

        window = patch + 2 * edge
        starts = []
        t = t_start
        while t < t_end:
            starts.append(t)
            t = t + np.timedelta64(int(patch * 1e9), "ns")

        total_rows = 0
        total_bytes = 0
        w0 = time.perf_counter()
        for s in starts:
            e = s + np.timedelta64(int(window * 1e9), "ns")
            plan = sp.native_window_plan(s, min(e, t_end))
            assert plan is not None, "native fast path did not apply"
            p = assemble_window_patch(plan)
            total_rows += p.data.shape[0]
            total_bytes += p.data.nbytes
        elapsed = time.perf_counter() - w0

    rate = total_rows * C / elapsed
    print(
        f"assembled {len(starts)} windows ({total_rows} rows, "
        f"{total_bytes / 1e9:.2f} GB f32-out) in {elapsed:.2f}s",
        flush=True,
    )
    print(
        f"host assembly rate: {rate / 1e9:.2f} G ch-samp/s  "
        f"({total_bytes / elapsed / 1e9:.2f} GB/s out)  "
        f"[{ncpu} cores, {dtype} payload]",
        flush=True,
    )
    # realtime factor of the ASSEMBLY phase alone at this (fs, C)
    print(
        f"assembly-alone realtime factor @ {C}ch/{fs:.0f}Hz: "
        f"{rate / (fs * C):.2f}x",
        flush=True,
    )


if __name__ == "__main__":
    main()
