"""Per-round compute bench for the fused streaming cascade (ISSUE 10)
-> BENCH_pr10.json.

Times the carry-threaded STREAM STEP — the unit one realtime round
dispatches per block — across interrogator widths (256 / 2048 / 10000
channels) and block sizes, for every engine in the stream dispatch
matrix:

- ``cascade``: the per-stage chain (each stage materializes its
  full-rate intermediate before the next consumes it);
- ``fused-xla``: the lax.scan formulation (all stage states threaded
  through one jitted step; intermediates exist only at chunk size);
- ``fused-pallas``: the v3 VMEM-resident kernel — interpret mode off
  TPU, so off-TPU it is benched only at the smallest width as a
  correctness-shaped data point, clearly flagged (interpret-mode times
  say nothing about silicon).

Headline counters come from the obs registry (``use_registry`` scope:
``tpudas_fir_fused_rounds_total`` proves the fused path really ran,
``tpudas_fir_fused_intermediate_bytes_saved_total`` is the HBM-traffic
proxy — the per-stage intermediate bytes the fused path never
materializes, re-read traffic excluded).  Equivalence is asserted in
the run: fused-xla output and carry byte-identical to the cascade
chain on a verification block.

    JAX_PLATFORMS=cpu python tools/kernel_bench.py [--out BENCH_pr10.json]
        [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from tpudas.obs.registry import MetricsRegistry, use_registry  # noqa: E402
from tpudas.ops.fir import (  # noqa: E402
    cascade_decimate_stream,
    cascade_stream_init,
    design_cascade,
    fused_chunk_outputs,
    fused_intermediate_bytes,
    fused_min_elems,
)

# the flagship workload: 1 kHz interrogator -> 1 Hz low-frequency
FS_IN = 1000.0
RATIO = 1000
CHANNELS = (256, 2048, 10000)
BLOCKS = (16, 64)  # output samples per stream step
TARGET_10K = 1.3  # acceptance: fused >= 1.3x at 10k ch


def _measure(plan, n_out, C, engine, iters):
    """Best-of wall seconds per carry-threaded step, measured warm
    (compile excluded), carry fed back each round — through the REAL
    dispatch surface (cascade_decimate_stream), so the obs counters
    the report cites witness exactly the measured rounds."""
    T = n_out * plan.ratio
    carry = cascade_stream_init(plan, C)
    rng = np.random.default_rng(0)
    x_host = rng.standard_normal((T, C)).astype(np.float32)
    # the step donates its input on accelerator backends — there a
    # fresh device buffer is required per round; on CPU (no donation)
    # the block is reused, as the realtime driver's pool slices are
    donating = jax.default_backend() not in ("cpu",)
    x = jnp.asarray(x_host)
    y, carry = cascade_decimate_stream(x, carry, plan, engine)
    jax.block_until_ready(y)
    best = 1e30
    for _ in range(iters):
        if donating:
            x = jnp.asarray(x_host)
        t0 = time.perf_counter()
        y, carry = cascade_decimate_stream(x, carry, plan, engine)
        jax.block_until_ready(y)
        best = min(best, time.perf_counter() - t0)
    return best


def _equivalence(plan, n_ch=8) -> dict:
    """fused-xla == cascade byte-identity on a multi-block feed, and
    the fused-pallas interpret tolerance — recorded, not just claimed
    (tests/test_fused.py pins the same contracts in tier-1)."""
    rng = np.random.default_rng(7)
    blocks = [
        rng.standard_normal((n * plan.ratio, n_ch)).astype(np.float32)
        for n in (16, 5, 11)
    ]

    def run(engine):
        carry = cascade_stream_init(plan, n_ch)
        outs = []
        for b in blocks:
            y, carry = cascade_decimate_stream(b, carry, plan, engine)
            outs.append(np.asarray(y))
        return np.concatenate(outs), tuple(np.asarray(c) for c in carry)

    y0, c0 = run("xla")
    y1, c1 = run("fused-xla")
    out_eq = bool(np.array_equal(y0, y1))
    carry_eq = all(np.array_equal(a, b) for a, b in zip(c0, c1))
    y2, c2 = run("fused-pallas")
    scale = float(np.abs(y0).max())
    pallas_rel = float(np.abs(y0 - y2).max() / scale)
    return {
        "fused_xla_output_byte_identical": out_eq,
        "fused_xla_carry_byte_identical": bool(carry_eq),
        "fused_pallas_rel_err": pallas_rel,
        "fused_pallas_tolerance_pinned": 5e-7,
    }


def run(out_path, quick=False) -> dict:
    backend = jax.default_backend()
    plan = design_cascade(FS_IN, RATIO, 0.45, 4)
    on_tpu = backend in ("tpu", "axon")
    channels = CHANNELS if not quick else (256,)
    blocks = BLOCKS if not quick else (16,)
    iters = 4 if quick else 6
    sweep = []
    for C in channels:
        for n_out in blocks:
            T = n_out * plan.ratio
            engines = ["cascade", "fused-xla"]
            # off-TPU the v3 kernel runs interpret mode: time it only
            # at the smallest point, flagged — interpret wall time is
            # not a kernel statement
            if on_tpu or (C == min(channels) and n_out == min(blocks)):
                engines.append("fused-pallas")
            point = {
                "n_ch": C,
                "n_out": n_out,
                "rows": T,
                "elems": T * C,
                "chunk_out": fused_chunk_outputs(plan, n_out),
                "engines": {},
            }
            for eng in engines:
                reg = MetricsRegistry()
                real = "xla" if eng == "cascade" else eng
                with use_registry(reg):
                    dt = _measure(plan, n_out, C, real, iters)
                rec = {
                    "seconds_per_round": dt,
                    "channel_samples_per_sec": T * C / dt,
                    "interpret_mode": bool(
                        eng == "fused-pallas" and not on_tpu
                    ),
                }
                if eng != "cascade":
                    # the registry is the witness the fused path ran
                    # and the HBM-traffic proxy source
                    rec["fused_rounds"] = reg.value(
                        "tpudas_fir_fused_rounds_total", engine=real
                    )
                    rec["intermediate_bytes_saved_per_round"] = (
                        fused_intermediate_bytes(plan, T, C)
                    )
                else:
                    rec["intermediate_bytes_per_round"] = (
                        fused_intermediate_bytes(plan, T, C)
                    )
                point["engines"][eng] = rec
                print(
                    f"kernel_bench: C={C} n_out={n_out} {eng}: "
                    f"{dt * 1e3:.2f} ms/round"
                    + (" (interpret)" if rec["interpret_mode"] else ""),
                    flush=True,
                )
            cas = point["engines"]["cascade"]["seconds_per_round"]
            fx = point["engines"]["fused-xla"]["seconds_per_round"]
            point["speedup_fused_xla"] = cas / fx
            sweep.append(point)
    big = [p for p in sweep if p["n_ch"] >= 2048]
    ten_k = [p for p in sweep if p["n_ch"] >= 10000]
    acceptance = {
        # None when the sweep did not reach the width (--quick)
        "fused_beats_cascade_at_2048plus": (
            all(p["speedup_fused_xla"] > 1.0 for p in big)
            if big else None
        ),
        "best_speedup_10k": max(
            (p["speedup_fused_xla"] for p in ten_k), default=None
        ),
        "target_speedup_10k": TARGET_10K,
        "equivalence": _equivalence(plan),
        # structural: the fused scan's largest live intermediate is
        # one CHUNK, never the block — zero per-stage full-rate HBM
        # intermediates by construction
        "fused_max_live_intermediate_rows": (
            max(p["chunk_out"] for p in sweep) * plan.ratio
        ),
    }
    report = {
        "bench": "kernel_bench (ISSUE 10 fused streaming cascade)",
        "backend": backend,
        "host_cpus": os.cpu_count(),
        "plan": {
            "fs_in": FS_IN,
            "ratio": RATIO,
            "stages": [[int(R), len(h)] for R, h in plan.stages],
        },
        "fused_min_elems": fused_min_elems(),
        "headline_source": "tpudas.obs.registry",
        "sweep": sweep,
        "acceptance": acceptance,
    }
    ok = acceptance["fused_beats_cascade_at_2048plus"] is not False and (
        not ten_k or acceptance["best_speedup_10k"] >= TARGET_10K
    )
    eq = acceptance["equivalence"]
    ok = ok and eq["fused_xla_output_byte_identical"]
    ok = ok and eq["fused_xla_carry_byte_identical"]
    ok = ok and eq["fused_pallas_rel_err"] <= eq[
        "fused_pallas_tolerance_pinned"
    ]
    report["ok"] = bool(ok)
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(report, fh, indent=1)
        print(f"kernel_bench: wrote {out_path}")
    print(f"kernel_bench: {'OK' if ok else 'FAILED'}")
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(REPO, "BENCH_pr10.json"))
    ap.add_argument(
        "--quick", action="store_true",
        help="smallest width only (the tier-1 smoke)",
    )
    args = ap.parse_args(argv)
    report = run(args.out, quick=args.quick)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
