"""Process-level crash drill: SIGKILL the realtime driver at seeded
random points, then prove the folder audits clean and resumes
byte-identically.

The missing end-to-end proof behind the crash-only claims: PR 3/4
killed the driver with injected exceptions at chosen fault sites; this
drill kills the *process* (``SIGKILL`` — no handlers, no cleanup, the
power-cut model) at points drawn from a seeded RNG, so the kill can
land inside any write: mid-``np.savez``, between a tile and its
manifest, halfway through an HDF5 output flush.

One drill (per engine):

1. seed a source spool, run uninterrupted worker cycles to calibrate
   the processing wall time;
2. for each of N cycles: feed one more interrogator file — but only
   when the PREVIOUS cycle ran to completion (epoch gating, below) —
   spawn the driver in a fresh subprocess (pyramid + health +
   stateful carry + detect operators on), SIGKILL it
   ``uniform(0.02, 0.95 * calib)`` seconds after it becomes ready;
3. right after the kill cycles — BEFORE the drain — assert the
   on-disk flight recorder (ISSUE 13, ``tpudas.obs.flight``) replays
   the final committed round: its ``round`` record carries all eight
   phases and is preceded by that round's spans (``stream.round``
   included) in the surviving ring;
4. run one final uninterrupted cycle to drain, then assert
   ``tpudas.integrity.audit`` reports **clean** (each worker already
   audited + repaired at startup — this run must find nothing left);
5. replay the SAME epoch schedule uninterrupted into a fresh control
   folder and assert:

   - the merged OUTPUT CONTENT (time grid + float32 samples) is
     byte-identical — output *file boundaries* are round-schedule
     dependent, so files are compared by merged content, not name;
   - the tile pyramid is byte-identical file-by-file (tiles, tails,
     manifest);
   - the detect state matches: the events ledger byte-identical, the
     score tiles byte-identical file-by-file, and the operator
     carries content-identical (meta + every state array — the
     ``.npz`` container embeds zip timestamps, so the parsed content
     is the comparable form).

**Epoch gating.**  The carry only advances when a round completes, so
every processing attempt spans exactly [end of last completed epoch →
end of fed data]: holding the fed data fixed until a cycle completes
it makes the killed run's effective consumption schedule identical to
an uninterrupted run over the same epochs — which is precisely what
crash-only resume promises, and the strongest claim that CAN hold
byte-for-byte: the FFT engine's per-block frequency masking is
chunk-schedule dependent by design (a cascade-only drill without the
gating also passes, because the FIR cascade is bit-exact under any
chunking).

CLI (the full acceptance drill — ``BENCH_pr05.json`` records a run):

    JAX_PLATFORMS=cpu python tools/crash_drill.py \
        [--cycles 25] [--seed 0] [--engines cascade,fft] [--out PATH] \
        [--mesh 4]

``--engines`` accepts any LFProc engine literal; ``fused`` (ISSUE 10)
drills the fused streaming kernel — the worker clears the fused size
threshold (``TPUDAS_FUSED_MIN_ELEMS=0``) so the tiny drill stream
actually runs the fused path, and the control replay runs it too, so
the byte-identity claim covers the fused carry save/resume cycle.

``--mesh N`` (ISSUE 7) channel-shards every drilled cycle over N
CPU-virtualized devices (``TPUDAS_MESH`` resolution in the driver)
while the control replay stays single-device: one run then proves
both that SIGKILL cycles on the SHARDED path end audit-clean and that
the sharded path is byte-identical to the unsharded engines.

``--streams N`` (ISSUE 8) drills the FLEET: every cycle spawns one
process running a :class:`tpudas.fleet.FleetEngine` over N streams
(identical per-epoch feeds into N separate source spools, per-stream
state under ``out/<stream_id>/``), SIGKILLs it mid-interleave, and at
the end asserts ``tpudas.integrity.audit.audit_fleet`` is clean and
EVERY stream's merged outputs, pyramid tree, and detect state are
byte-identical to a SINGLE-STREAM control replay of the same epoch
schedule — the fleet scheduler may interleave N carries, quarantines,
and pyramids through one process and one SIGKILL, but each stream
must crash-resume exactly as if it ran alone.  (``--streams`` and
``--mesh`` are mutually exclusive.)

``--batched`` (ISSUE 16) runs the DRILLED fleet cycles with the
ragged-batched scheduler (``TPUDAS_FLEET_BATCHED=1``: same-plan
streams stacked into one device program per wave) while the
single-stream control replay is by construction unbatched — SIGKILLs
land mid-stacked-launch, proving the batched path's durable bytes
equal the solo path's.  Requires ``--streams``.

``--async-ingest`` (ISSUE 15) drills the ASYNC PIPELINED INGEST
path: every drilled cycle runs with ``TPUDAS_INGEST_PREFETCH=2`` (so
SIGKILLs land with prefetched-but-uncommitted slices in flight and
with deferred-sync blocks pending) while the control replay runs the
synchronous slice loop — the byte-identity comparison then proves
both that a prefetched slice is crash-equivalent to a never-read one
AND that the async path's durable bytes equal the sync path's.

``--live`` (ISSUE 19) drills the LIVE PUSH PLANE: every drilled cycle
runs with ``TPUDAS_LIVE=1`` and a roster of in-process subscribers
attached from round 2 on (``TPUDAS_CRASH_DRILL_SUBS``, never drained —
so SIGKILLs land while the degrade ladder is mid-shed) while the
control replay runs live-off.  The live plane holds ZERO durable
state, so the existing byte-identity comparisons (outputs, pyramid,
detect state) are exactly the crash-only claim for it: publishing to
a thousand slow clients and dying mid-fanout must leave the same
bytes as never having had a subscriber.  Not supported with
``--streams``.

``tests/test_integrity.py`` runs a small seeded smoke in tier-1 and
the full drill under ``-m slow``; ``tests/test_fleet.py`` smokes the
fleet drill.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

T0 = "2023-03-22T00:00:00"
FS = 50.0
FILE_SEC = 20.0
N_CH = 4
DT_OUT = 1.0
EDGE_SEC = 5.0
PATCH_OUT = 20
# thresholds tuned so the drill's noisy synthetic stream actually
# produces ledger events (an empty ledger would vacuously "match")
DETECT_OPS = (
    ("stalta", {"sta": 2.0, "lta": 10.0, "on": 2.0, "off": 1.2}),
    ("rms", {"window": 5.0, "step": 2.0, "thresh": 1.5,
             "baseline": 20.0}),
)


# ---------------------------------------------------------------------------
# the worker (runs in the subprocess being killed)

def _worker(src: str, out: str, engine: str) -> int:
    import time as _t

    if engine == "fused":
        # the drill stream is tiny (4 ch); drop the fused size
        # threshold so the drilled path IS the fused kernel, not the
        # per-stage fallback the crossover gate would pick
        os.environ.setdefault("TPUDAS_FUSED_MIN_ELEMS", "0")

    from tpudas.proc.streaming import run_lowpass_realtime

    # --live leg: attach a never-drained subscriber roster once the
    # hub exists, so SIGKILLs land while the degrade ladder is
    # mid-shed (the live plane is memory-only; nothing durable may
    # change because of it)
    n_subs = int(os.environ.get("TPUDAS_CRASH_DRILL_SUBS", "0"))
    attached = {"subs": None}

    def _attach(_rnd, _lfp):
        if attached["subs"] is not None:
            return
        from tpudas.live.hub import find_hub

        hub = find_hub(folder=out)
        if hub is not None:
            attached["subs"] = [
                hub.subscribe() for _ in range(n_subs)
            ]

    # ready marker BESIDE the output folder: the parent starts its
    # kill timer only after the interpreter/jax warm-up is done, so
    # kills land in processing, not in `import jax`
    os.makedirs(out, exist_ok=True)
    with open(out + ".ready", "w") as fh:
        fh.write(str(os.getpid()))
    run_lowpass_realtime(
        source=src,
        output_folder=out,
        start_time=T0,
        output_sample_interval=DT_OUT,
        edge_buffer=EDGE_SEC,
        process_patch_size=PATCH_OUT,
        poll_interval=0.0,
        sleep_fn=lambda _s: _t.sleep(0.01),
        engine=engine,
        pyramid=True,
        health=True,
        detect=True,
        detect_operators=DETECT_OPS,
        max_rounds=8,
        on_round=_attach if n_subs else None,
    )
    return 0


def _fleet_worker(src_root: str, out: str, engine: str,
                  n_streams: int) -> int:
    """The fleet drill's subprocess: one FleetEngine over N streams,
    same per-stream config as :func:`_worker` (so each stream's
    single-stream control is the plain worker)."""
    import time as _t

    from tpudas.fleet import FleetEngine, StreamConfig, StreamSpec

    os.makedirs(out, exist_ok=True)
    config = StreamConfig(
        kind="lowpass",
        start_time=T0,
        output_sample_interval=DT_OUT,
        edge_buffer=EDGE_SEC,
        process_patch_size=PATCH_OUT,
        poll_interval=0.0,
        engine=engine,
        pyramid=True,
        health=True,
        detect=True,
        detect_operators=DETECT_OPS,
    )
    specs = [
        StreamSpec(
            stream_id=f"s{i:02d}",
            source=os.path.join(src_root, f"s{i:02d}"),
            config=config,
        )
        for i in range(int(n_streams))
    ]
    with open(out + ".ready", "w") as fh:
        fh.write(str(os.getpid()))
    FleetEngine(
        out, specs, max_rounds=8,
        sleep_fn=lambda _s: _t.sleep(0.01),
    ).run()
    return 0


# ---------------------------------------------------------------------------
# the parent harness

def _feed(src: str, first_index: int, n_files: int) -> None:
    import numpy as np

    from tpudas.testing import make_synthetic_spool

    make_synthetic_spool(
        src, n_files=n_files, file_duration=FILE_SEC, fs=FS, n_ch=N_CH,
        noise=0.01,
        start=np.datetime64(T0)
        + np.timedelta64(int(first_index * FILE_SEC * 1e9), "ns"),
        prefix=f"raw{first_index:04d}",
    )


def _rm_ready(out: str) -> None:
    try:
        os.remove(out + ".ready")
    except OSError:
        pass


def _run_cycle(src, out, engine, kill_after, log_fh=None,
               mesh=0, streams=0, env_extra=None) -> dict:
    """One worker subprocess; ``kill_after`` seconds after READY send
    SIGKILL (None = let it finish).  ``mesh`` > 0 runs the worker
    channel-sharded over that many CPU-virtualized devices
    (``TPUDAS_MESH`` + ``--xla_force_host_platform_device_count``) —
    the driver resolves the env var itself.  ``streams`` > 0 runs the
    FLEET worker (``src`` is then the source root holding one spool
    per stream).  ``env_extra`` overlays the worker environment (the
    async-ingest leg pins ``TPUDAS_INGEST_PREFETCH`` per side).
    Returns {killed, wall}."""
    _rm_ready(out)
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    env["JAX_PLATFORMS"] = "cpu"
    if mesh:
        env["TPUDAS_MESH"] = str(int(mesh))
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={int(mesh)}"
            ).strip()
    else:
        env.pop("TPUDAS_MESH", None)
    # share one persistent XLA cache across worker processes: after
    # the cold calibration cycle every worker warm-starts, so kills
    # land in real processing/write windows instead of jit compiles
    env.setdefault(
        "TPUDAS_COMPILE_CACHE",
        os.path.join(os.path.dirname(out), "xla_cache"),
    )
    argv = (
        ["--fleet-worker", src, out, engine, str(int(streams))]
        if streams
        else ["--worker", src, out, engine]
    )
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), *argv],
        env=env,
        stdout=log_fh if log_fh is not None else subprocess.DEVNULL,
        stderr=subprocess.STDOUT if log_fh is not None else (
            subprocess.DEVNULL
        ),
    )
    t0 = time.time()
    ready = out + ".ready"
    while not os.path.isfile(ready):
        if proc.poll() is not None:
            raise RuntimeError(
                f"crash-drill worker exited rc={proc.returncode} "
                "before becoming ready (see --log)"
            )
        if time.time() - t0 > 300:
            proc.kill()
            raise RuntimeError("crash-drill worker never became ready")
        time.sleep(0.01)
    t_ready = time.time()
    killed = False
    if kill_after is None:
        proc.wait(timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                f"uninterrupted crash-drill worker failed "
                f"rc={proc.returncode}"
            )
    else:
        while proc.poll() is None and time.time() - t_ready < kill_after:
            time.sleep(0.002)
        if proc.poll() is None:
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait()
            killed = True
    return {"killed": killed, "wall": round(time.time() - t_ready, 3)}


def _content_hash(folder: str) -> str:
    """sha256 of the merged output content: the ns time grid plus the
    float32 samples, independent of how emission chunked the files."""
    import numpy as np

    from tpudas.io.spool import spool as make_spool

    h = hashlib.sha256()
    sp = make_spool(folder).sort("time").update()
    for patch in sp.chunk(time=None):
        d = patch.host_data()
        ax = patch.axis_of("time")
        if ax != 0:
            d = np.moveaxis(d, ax, 0)
        times = (
            np.asarray(patch.coords["time"])
            .astype("datetime64[ns]")
            .astype(np.int64)
        )
        h.update(times.tobytes())
        h.update(
            np.ascontiguousarray(np.asarray(d, np.float32)).tobytes()
        )
    return h.hexdigest()


def _pyramid_tree(folder: str) -> dict:
    """{relpath: sha256} of the pyramid files (``.prev`` history and
    tmp leftovers excluded — they are append-schedule dependent)."""
    from tpudas.serve.tiles import TILE_DIRNAME
    from tpudas.utils.atomicio import is_tmp_name

    tiles = os.path.join(folder, TILE_DIRNAME)
    out = {}
    for dirpath, _dirnames, filenames in os.walk(tiles):
        for name in sorted(filenames):
            if ".prev" in name or is_tmp_name(name):
                continue
            path = os.path.join(dirpath, name)
            with open(path, "rb") as fh:
                digest = hashlib.sha256(fh.read()).hexdigest()
            out[os.path.relpath(path, tiles)] = digest
    return out


def _detect_state(folder: str) -> dict:
    """The committed detect state, comparison-ready: the ledger's raw
    bytes (deterministic canonical lines), a digest of every score
    tile/tails file, and a digest of the PARSED carry (meta + array
    bytes — the ``.npz`` container embeds zip timestamps, so raw
    bytes cannot be compared across runs).  ``.prev`` rungs are
    commit-schedule dependent and excluded, like the pyramid's."""
    from tpudas.detect.ledger import DETECT_DIRNAME, ScoreStore
    from tpudas.detect.runner import load_detect_carry
    from tpudas.utils.atomicio import is_tmp_name

    det = os.path.join(folder, DETECT_DIRNAME)
    out: dict = {"present": os.path.isdir(det)}
    if not out["present"]:
        return out
    ledger = os.path.join(det, "events.jsonl")
    if os.path.isfile(ledger):
        with open(ledger, "rb") as fh:
            out["ledger_sha"] = hashlib.sha256(fh.read()).hexdigest()
    carry = load_detect_carry(folder)
    if carry is not None:
        h = hashlib.sha256()
        h.update(
            json.dumps(carry["meta"], sort_keys=True).encode()
        )
        for st in carry["states"]:
            for key in sorted(st):
                import numpy as np

                arr = np.asarray(st[key])
                h.update(key.encode())
                h.update(str(arr.dtype).encode())
                h.update(arr.tobytes())
        out["carry_sha"] = h.hexdigest()
    scores = ScoreStore.scores_dir(folder)
    tree = {}
    if os.path.isdir(scores):
        for name in sorted(os.listdir(scores)):
            if ".prev" in name or is_tmp_name(name):
                continue
            path = os.path.join(scores, name)
            if not os.path.isfile(path):
                continue
            with open(path, "rb") as fh:
                tree[name] = hashlib.sha256(fh.read()).hexdigest()
    out["scores"] = tree
    return out


def _flight_replay_check(folder: str) -> dict:
    """The ISSUE 13 flight-recorder leg: called right after the kill
    cycles (BEFORE the drain), so it asserts what the on-disk ring
    holds at the moment an operator would arrive at a SIGKILLed box —
    the final committed round's record (all phases present) preceded
    by that round's spans.  The recorder flushes a round's spans and
    its ``round`` record in one write, so any surviving round record
    implies its spans survived too; this verifies that end to end."""
    from tpudas.obs.flight import read_flight
    from tpudas.obs.phases import PHASES

    recs = read_flight(folder)
    rounds = [r for r in recs if r.get("kind") == "round"]
    if not rounds:
        return {"ok": False, "rounds": 0,
                "reason": "no committed round records in the ring"}
    last = rounds[-1]
    spans = [
        r for r in recs
        if r.get("kind") == "span" and r.get("round") == last["round"]
    ]
    has_round_span = any(r.get("name") == "stream.round" for r in spans)
    phases_complete = sorted(last.get("phases", {})) == sorted(PHASES)
    return {
        "ok": bool(has_round_span and phases_complete),
        "rounds": len(rounds),
        "last_round": last.get("round"),
        "last_round_spans": len(spans),
        "phases_complete": phases_complete,
        "records_total": len(recs),
    }


def run_drill(
    engine: str = "cascade",
    cycles: int = 25,
    seed: int = 0,
    workdir: str | None = None,
    files_init: int = 2,
    files_per_cycle: int = 1,
    log_path: str | None = None,
    mesh: int = 0,
    async_ingest: bool = False,
    live: bool = False,
    live_subs: int = 32,
) -> dict:
    """One full drill for ``engine``; returns the report dict with
    ``ok`` True when the audit is clean and both comparisons match.

    ``mesh`` > 0 (ISSUE 7) runs every DRILLED cycle channel-sharded
    over that many CPU-virtualized devices while the CONTROL replay
    stays single-device — so one drill proves both that SIGKILL
    cycles on the sharded path end audit-clean AND that the sharded
    path is byte-identical to the unsharded cascade/fft.

    ``async_ingest`` (ISSUE 15) runs every DRILLED cycle with the
    async pipelined ingest on (``TPUDAS_INGEST_PREFETCH=2``) while
    the CONTROL replay runs the synchronous slice loop — SIGKILLs
    land with prefetched-but-uncommitted slices in flight, and the
    byte-identity comparison then proves a prefetched slice is
    crash-equivalent to a never-read one.

    ``live`` (ISSUE 19) runs every DRILLED cycle with the live push
    plane on and ``live_subs`` never-drained in-process subscribers
    (``TPUDAS_LIVE=1`` + ``TPUDAS_CRASH_DRILL_SUBS``) while the
    CONTROL replay runs live-off — the comparison proves fanning out
    to stalled clients and dying mid-publish changes no durable
    byte."""
    import numpy as np

    from tpudas.integrity.audit import audit

    tag = f"crash_drill_{engine}_mesh{mesh}_" if mesh else (
        f"crash_drill_{engine}_"
    )
    if async_ingest:
        tag = tag[:-1] + "_async_"
    if live:
        tag = tag[:-1] + "_live_"
    workdir = workdir or tempfile.mkdtemp(prefix=tag)
    src = os.path.join(workdir, "src")
    out = os.path.join(workdir, "out")
    ctrl = os.path.join(workdir, "ctrl")
    log_fh = open(log_path, "ab") if log_path else None
    drill_env: dict = {}
    ctrl_env: dict = {}
    if async_ingest:
        drill_env["TPUDAS_INGEST_PREFETCH"] = "2"
        ctrl_env["TPUDAS_INGEST_PREFETCH"] = "0"
    if live:
        drill_env["TPUDAS_LIVE"] = "1"
        drill_env["TPUDAS_CRASH_DRILL_SUBS"] = str(int(live_subs))
        ctrl_env["TPUDAS_LIVE"] = "0"
    drill_env = drill_env or None
    ctrl_env = ctrl_env or None
    try:
        # epochs: every feed event, replayed verbatim for the control
        epochs = [(0, files_init)]
        _feed(src, 0, files_init)
        # cold calibration: seeds the carry AND the shared XLA cache
        cold = _run_cycle(src, out, engine, None, log_fh, mesh=mesh,
                          env_extra=drill_env)
        # warm calibration: the est the kill distribution draws from
        epochs.append((files_init, files_per_cycle))
        _feed(src, files_init, files_per_cycle)
        warm = _run_cycle(src, out, engine, None, log_fh, mesh=mesh,
                          env_extra=drill_env)
        est = max(warm["wall"], 0.2)
        rng = np.random.default_rng(seed)
        n_files = files_init + files_per_cycle
        kills = 0
        cycle_log = []
        advance = True  # the last cycle completed its epoch
        for _c in range(int(cycles)):
            if advance:
                epochs.append((n_files, files_per_cycle))
                _feed(src, n_files, files_per_cycle)
                n_files += files_per_cycle
            kill_after = float(rng.uniform(0.02, est * 0.95))
            r = _run_cycle(src, out, engine, kill_after, log_fh,
                           mesh=mesh, env_extra=drill_env)
            kills += int(r["killed"])
            advance = not r["killed"]
            if not r["killed"]:
                # the worker outran the timer: track the real wall so
                # later draws keep landing inside the work window
                est = max(0.5 * est + 0.5 * r["wall"], 0.2)
            cycle_log.append({"kill_after": round(kill_after, 3), **r})
        # flight-recorder replay (ISSUE 13): inspected NOW, after the
        # SIGKILL cycles and before the drain — the on-disk ring must
        # already replay the final committed round's spans + phases
        flight = _flight_replay_check(out)
        # drain: the resumed run finishes everything the kills left
        _run_cycle(src, out, engine, None, log_fh, mesh=mesh,
                   env_extra=drill_env)
        # the drained folder must audit clean (each worker already
        # audited at startup; this run may not find anything new)
        report = audit(out, repair=True)
        # control: replay the SAME epoch schedule, uninterrupted — and
        # ALWAYS single-device, so a mesh drill also pins
        # sharded == unsharded byte-identity end to end
        ctrl_src = os.path.join(workdir, "ctrl_src")
        for first, count in epochs:
            _feed(ctrl_src, first, count)
            _run_cycle(ctrl_src, ctrl, engine, None, log_fh,
                       env_extra=ctrl_env)
        outputs_match = _content_hash(out) == _content_hash(ctrl)
        pyr_out, pyr_ctrl = _pyramid_tree(out), _pyramid_tree(ctrl)
        pyramid_match = pyr_out == pyr_ctrl
        det_out, det_ctrl = _detect_state(out), _detect_state(ctrl)
        detect_match = det_out == det_ctrl
        detect_events = 0
        if det_out.get("ledger_sha"):
            from tpudas.detect.ledger import load_events

            detect_events = len(load_events(out))
        return {
            "engine": engine,
            "mesh": int(mesh),
            "async_ingest": bool(async_ingest),
            "live": bool(live),
            "live_subs": int(live_subs) if live else 0,
            "cycles": int(cycles),
            "seed": int(seed),
            "kills": kills,
            "epochs": len(epochs),
            "cold_wall_s": cold["wall"],
            "warm_wall_s": warm["wall"],
            "audit_clean": bool(report["clean"]),
            "audit_issues": len(report["issues"]),
            "outputs_match": bool(outputs_match),
            "pyramid_match": bool(pyramid_match),
            "pyramid_files": len(pyr_out),
            "detect_match": bool(detect_match),
            "detect_events": int(detect_events),
            "flight": flight,
            "cycle_log": cycle_log,
            "workdir": workdir,
            "ok": bool(
                report["clean"] and outputs_match and pyramid_match
                and detect_match and flight["ok"]
            ),
        }
    finally:
        if log_fh is not None:
            log_fh.close()


def run_fleet_drill(
    engine: str = "cascade",
    streams: int = 4,
    cycles: int = 12,
    seed: int = 0,
    workdir: str | None = None,
    files_init: int = 2,
    files_per_cycle: int = 1,
    log_path: str | None = None,
    batched: bool = False,
) -> dict:
    """The fleet drill (ISSUE 8): SIGKILL a ``streams``-wide
    :class:`tpudas.fleet.FleetEngine` mid-interleave for ``cycles``
    seeded cycles, then prove ``audit_fleet`` is clean and EVERY
    stream's post-crash state is byte-identical to a single-stream
    control replay of the same epoch schedule.

    Every stream is fed the SAME synthetic files each epoch (separate
    source spools, identical bytes), so ONE single-stream control
    covers all N comparisons; epoch gating holds the feed until a
    cycle runs uninterrupted, exactly as :func:`run_drill` does (and
    for the same chunk-schedule reason).

    ``batched`` overlays ``TPUDAS_FLEET_BATCHED=1`` on every DRILLED
    cycle (the ragged-batched scheduler, ISSUE 16); the single-stream
    control replay never batches, so the comparison pins the batched
    path's crash-surviving bytes to the solo path's."""
    import numpy as np

    from tpudas.integrity.audit import audit_fleet

    drill_env = {"TPUDAS_FLEET_BATCHED": "1"} if batched else None
    streams = int(streams)
    workdir = workdir or tempfile.mkdtemp(
        prefix=f"crash_drill_fleet{streams}_{engine}_"
    )
    src_root = os.path.join(workdir, "src")
    out = os.path.join(workdir, "out")
    ctrl = os.path.join(workdir, "ctrl")
    log_fh = open(log_path, "ab") if log_path else None
    sids = [f"s{i:02d}" for i in range(streams)]

    def feed_all(first, count):
        for sid in sids:
            _feed(os.path.join(src_root, sid), first, count)

    try:
        epochs = [(0, files_init)]
        feed_all(0, files_init)
        cold = _run_cycle(src_root, out, engine, None, log_fh,
                          streams=streams, env_extra=drill_env)
        epochs.append((files_init, files_per_cycle))
        feed_all(files_init, files_per_cycle)
        warm = _run_cycle(src_root, out, engine, None, log_fh,
                          streams=streams, env_extra=drill_env)
        est = max(warm["wall"], 0.2)
        rng = np.random.default_rng(seed)
        n_files = files_init + files_per_cycle
        kills = 0
        cycle_log = []
        advance = True
        for _c in range(int(cycles)):
            if advance:
                epochs.append((n_files, files_per_cycle))
                feed_all(n_files, files_per_cycle)
                n_files += files_per_cycle
            kill_after = float(rng.uniform(0.02, est * 0.95))
            r = _run_cycle(src_root, out, engine, kill_after, log_fh,
                           streams=streams, env_extra=drill_env)
            kills += int(r["killed"])
            advance = not r["killed"]
            if not r["killed"]:
                est = max(0.5 * est + 0.5 * r["wall"], 0.2)
            cycle_log.append({"kill_after": round(kill_after, 3), **r})
        # drain, then the whole fleet root must audit clean
        _run_cycle(src_root, out, engine, None, log_fh, streams=streams,
                   env_extra=drill_env)
        report = audit_fleet(out, repair=True)
        # ONE single-stream control (identical feeds): the plain
        # worker over the same epoch schedule
        ctrl_src = os.path.join(workdir, "ctrl_src")
        for first, count in epochs:
            _feed(ctrl_src, first, count)
            _run_cycle(ctrl_src, ctrl, engine, None, log_fh)
        ctrl_hash = _content_hash(ctrl)
        ctrl_pyr = _pyramid_tree(ctrl)
        ctrl_det = _detect_state(ctrl)
        detect_events = 0
        if ctrl_det.get("ledger_sha"):
            from tpudas.detect.ledger import load_events

            detect_events = len(load_events(ctrl))
        per_stream = {}
        all_match = True
        for sid in sids:
            sdir = os.path.join(out, sid)
            entry = {
                "outputs_match": _content_hash(sdir) == ctrl_hash,
                "pyramid_match": _pyramid_tree(sdir) == ctrl_pyr,
                "detect_match": _detect_state(sdir) == ctrl_det,
            }
            entry["ok"] = all(entry.values())
            all_match = all_match and entry["ok"]
            per_stream[sid] = entry
        return {
            "engine": engine,
            "streams": streams,
            "batched": bool(batched),
            "cycles": int(cycles),
            "seed": int(seed),
            "kills": kills,
            "epochs": len(epochs),
            "cold_wall_s": cold["wall"],
            "warm_wall_s": warm["wall"],
            "audit_clean": bool(report["clean"]),
            "audit_issues": report["issues_total"],
            "streams_match": per_stream,
            "detect_events": int(detect_events),
            "cycle_log": cycle_log,
            "workdir": workdir,
            "ok": bool(report["clean"] and all_match),
        }
    finally:
        if log_fh is not None:
            log_fh.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cycles", type=int, default=25)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--engines", default="cascade,fft",
        help="comma-separated engine list",
    )
    ap.add_argument("--out", default=None, help="write JSON report here")
    ap.add_argument("--log", default=None, help="worker stdout log file")
    ap.add_argument(
        "--mesh", type=int, default=0,
        help="channel-shard the DRILLED cycles over N CPU-virtualized "
        "devices (the control replay stays single-device)",
    )
    ap.add_argument(
        "--streams", type=int, default=0,
        help="drill a FLEET of N streams in one process per cycle "
        "(each stream compared to a single-stream control replay); "
        "mutually exclusive with --mesh",
    )
    ap.add_argument(
        "--codec", default=None,
        help="tile-codec spec (e.g. bitshuffle-deflate or "
        "quantize-deflate:max_error=1e-3): sets TPUDAS_CODEC for "
        "BOTH the drilled workers and the control replay, so the "
        "pyramid byte-identity claim covers the compressed store "
        "(ISSUE 11)",
    )
    ap.add_argument(
        "--batched", action="store_true",
        help="run the DRILLED fleet cycles under the ragged-batched "
        "scheduler (TPUDAS_FLEET_BATCHED=1) while the single-stream "
        "control replay stays solo — SIGKILLs land mid-stacked-launch "
        "(ISSUE 16); requires --streams",
    )
    ap.add_argument(
        "--workdir", default=None,
        help="drill scratch directory (default: a fresh mkdtemp)",
    )
    ap.add_argument(
        "--async-ingest", action="store_true",
        help="run the DRILLED cycles with async pipelined ingest "
        "(TPUDAS_INGEST_PREFETCH=2) while the control replay stays "
        "synchronous — SIGKILLs land with prefetched-but-uncommitted "
        "slices in flight, proving prefetched == never-read "
        "(ISSUE 15); not supported with --streams",
    )
    ap.add_argument(
        "--live", action="store_true",
        help="run the DRILLED cycles with the live push plane on "
        "(TPUDAS_LIVE=1) and --live-subs never-drained subscribers "
        "attached, while the control replay runs live-off — SIGKILLs "
        "land mid-fanout with the degrade ladder shedding, proving "
        "the memory-only push plane changes no durable byte "
        "(ISSUE 19); not supported with --streams",
    )
    ap.add_argument(
        "--live-subs", type=int, default=32,
        help="in-process subscribers per drilled cycle for --live",
    )
    args = ap.parse_args(argv)
    if args.streams and args.live:
        ap.error("--live drills the single-stream worker; combine "
                 "with --mesh or plain engines")
    if args.streams and args.async_ingest:
        ap.error("--async-ingest drills the single-stream worker; "
                 "combine with --mesh or plain engines")
    if args.streams and args.mesh:
        ap.error("--streams and --mesh are mutually exclusive")
    if args.batched and not args.streams:
        ap.error("--batched drills the fleet scheduler; requires "
                 "--streams")
    if args.codec:
        # workers inherit os.environ (_run_cycle copies it), so one
        # assignment covers every drilled cycle AND the control
        os.environ["TPUDAS_CODEC"] = args.codec
    results = {}
    ok = True
    for engine in [e for e in args.engines.split(",") if e]:
        # a shared --workdir gets one subdirectory per engine leg
        wd = (
            os.path.join(args.workdir, engine) if args.workdir else None
        )
        if args.streams:
            print(
                f"crash_drill: engine={engine} cycles={args.cycles} "
                f"seed={args.seed} streams={args.streams} "
                f"batched={int(args.batched)}"
            )
            rep = run_fleet_drill(
                engine=engine, streams=args.streams,
                cycles=args.cycles, seed=args.seed, log_path=args.log,
                workdir=wd, batched=args.batched,
            )
            results[engine] = rep
            ok = ok and rep["ok"]
            matched = sum(
                 1 for s in rep["streams_match"].values() if s["ok"]
            )
            print(
                f"crash_drill: {engine}: kills={rep['kills']} "
                f"audit_clean={rep['audit_clean']} "
                f"streams_match={matched}/{rep['streams']} "
                f"(events={rep['detect_events']})"
            )
            continue
        print(f"crash_drill: engine={engine} cycles={args.cycles} "
              f"seed={args.seed} mesh={args.mesh} "
              f"async_ingest={args.async_ingest} live={args.live}")
        rep = run_drill(
            engine=engine, cycles=args.cycles, seed=args.seed,
            log_path=args.log, mesh=args.mesh,
            async_ingest=args.async_ingest, workdir=wd,
            live=args.live, live_subs=args.live_subs,
        )
        results[engine] = rep
        ok = ok and rep["ok"]
        print(
            f"crash_drill: {engine}: kills={rep['kills']} "
            f"audit_clean={rep['audit_clean']} "
            f"outputs_match={rep['outputs_match']} "
            f"pyramid_match={rep['pyramid_match']} "
            f"detect_match={rep['detect_match']} "
            f"flight_replay={rep['flight']['ok']} "
            f"(events={rep['detect_events']}, "
            f"flight_rounds={rep['flight']['rounds']})"
        )
    payload = {"cycles": args.cycles, "seed": args.seed,
               "mesh": args.mesh, "streams": args.streams,
               "batched": args.batched, "codec": args.codec,
               "async_ingest": args.async_ingest, "live": args.live,
               "ok": ok, "engines": results}
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(payload, fh, indent=1)
    print(f"crash_drill: {'OK' if ok else 'FAILED'}")
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) >= 5 and sys.argv[1] == "--worker":
        sys.exit(_worker(sys.argv[2], sys.argv[3], sys.argv[4]))
    if len(sys.argv) >= 6 and sys.argv[1] == "--fleet-worker":
        sys.exit(
            _fleet_worker(
                sys.argv[2], sys.argv[3], sys.argv[4], int(sys.argv[5])
            )
        )
    sys.exit(main())
