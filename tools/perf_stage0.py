"""Stage-0 kernel harness: measure the product Pallas FIR on the chip.

The flagship cascade's first stage (R=8 guard FIR at full rate)
carries ~85% of the window's HBM traffic, so it is the tuning target.
This harness measures, under bench.py's resident scan methodology:

  read-ceiling    jnp.sum over the resident window — the practical
                  HBM read bandwidth visible to this harness (~500
                  GB/s of the v5e's 819 on the 2026-07-30 session)
  pallas stage0   the product kernel (tpudas.ops.pallas_fir) across
                  (kb, cb) grid geometries, f32 and raw int16 input
  xla stage0      the XLA polyphase formulation for reference

History (documented in PERF.md §4): the v1 VPU kernel measured
compute-bound at ~174 GB/s; single-stream auto-pipelined DMA capped at
~185 GB/s regardless of block geometry (probe_pipeline.py), which
motivated the v2 MXU banded-matmul kernel with P parallel input
streams.

Run: python tools/perf_stage0.py   (on the TPU; each config compiles)
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from scan_harness import measure as _measure
from tpudas.ops.fir import _block_taps, design_cascade, _polyphase_stage_xla
from tpudas.ops.pallas_fir import fir_decimate_pallas, stage_input_rows

C = 2048


def measure(fn, T, iters=96, dtype="float32"):
    return _measure(fn, T, C, iters, dtype)


def report(name, T, dt, in_bytes=4.0, extra_bytes_per_in=0.0):
    gsps = T * C / dt / 1e9
    gbps = T * C * (in_bytes + extra_bytes_per_in) / dt / 1e9
    print(
        f"{name:34s} {dt * 1e3:8.3f} ms/win  {gsps:7.2f} G ch-samp/s  "
        f"{gbps:6.1f} GB/s ({gbps / 819 * 100:4.1f}% peak)",
        flush=True,
    )


def main():
    print(f"backend={jax.default_backend()}", flush=True)
    plan = design_cascade(1000.0, 1000, 0.45, 4)
    R, h0 = plan.stages[0]
    hb = _block_taps(np.asarray(h0), R)
    B = int(hb.shape[0])
    print(f"stage0: R={R} taps={len(h0)} B={B}", flush=True)

    # STAGE0_QUICK=1 (the per-geometry-subprocess sweep mode of
    # tools/chip_campaign2.sh) skips the read-ceiling and XLA
    # reference sections so each subprocess spends its tunnel time on
    # the one geometry it was asked for.
    quick = os.environ.get("STAGE0_QUICK", "0") == "1"
    T0 = 129088
    if not quick:
        dt = measure(lambda x: jnp.sum(x, axis=0), T0)
        report("read-ceiling (sum)", T0, dt)

    # STAGE0_CONV=1: measure the pure-XLA conv formulations of stage 0
    # instead of the Pallas geometries — if XLA's native conv emitter
    # streams anywhere near the ~510 GB/s its reduce does, it beats
    # the Pallas path without any Mosaic tuning.  Two mappings of the
    # same depthwise-with-shared-taps op (taps identical per channel):
    #   conv-batch:     channels as the conv BATCH dim (N=C, feat=1)
    #   conv-depthwise: channels as grouped FEATURES (groups=C)
    if os.environ.get("STAGE0_CONV", "0") == "1":
        taps_full = jnp.asarray(np.asarray(hb, np.float32).reshape(-1))
        L = int(taps_full.shape[0])
        n_out = 16128
        T = (n_out - 1) * R + L

        def conv_batch(x, _t=taps_full, _R=R, _n=n_out, _L=L):
            lhs = x.T[:, None, :]  # (C, 1, T): N=C, feature=1
            rhs = _t[None, None, :]  # (O=1, I=1, L)
            y = jax.lax.conv_general_dilated(
                lhs, rhs, window_strides=(_R,), padding="VALID",
                dimension_numbers=("NCH", "OIH", "NCH"),
            )
            return y[:, 0, :_n].T

        def conv_depthwise(x, _t=taps_full, _R=R, _n=n_out):
            Cx = x.shape[1]
            lhs = x.T[None, :, :]  # (1, C, T)
            rhs = jnp.broadcast_to(
                taps_full[None, None, :], (Cx, 1, taps_full.shape[0])
            )
            y = jax.lax.conv_general_dilated(
                lhs, rhs, window_strides=(_R,), padding="VALID",
                dimension_numbers=("NCH", "OIH", "NCH"),
                feature_group_count=Cx,
            )
            return y[0, :, :_n].T

        for name, fn in (
            ("conv-batch", conv_batch),
            ("conv-depthwise", conv_depthwise),
        ):
            try:
                dt = measure(fn, T)
                report(f"{name} f32", T, dt, 4.0, 2 * 4 / 8)
            except Exception as exc:
                print(f"{name} f32: {str(exc)[:120]}", flush=True)
        return

    # product kernel: (kb, cb) sweep; kb=512 is the product default
    # (P=4 parallel 128-frame sub-blocks per grid step).  Geometry
    # lists are env-overridable so a live session can widen or narrow
    # the sweep without code edits: STAGE0_KBS / STAGE0_CBS are
    # comma-separated (all kb x cb combinations are measured).
    kbs = [int(v) for v in os.environ.get(
        "STAGE0_KBS", "256,512,1024").split(",")]
    cbs = [int(v) for v in os.environ.get(
        "STAGE0_CBS", "128,256").split(",")]
    geoms = [(kb, cb) for kb in kbs for cb in cbs]
    # STAGE0_TAG labels experiment rows (e.g. the Mosaic-knob A/Bs the
    # campaign sweep runs via TPUDAS_PALLAS_* envs) so log lines from
    # different configurations at the same geometry stay distinct
    tag = os.environ.get("STAGE0_TAG", "").strip()
    tag = f" [{tag}]" if tag else ""
    for kb, cb in geoms:
        n_out = -(-16000 // kb) * kb
        T = stage_input_rows(B, R, n_out, kb)
        try:
            dt = measure(
                lambda x, kb=kb, cb=cb, n_out=n_out: fir_decimate_pallas(
                    x, hb, R, n_out=n_out, kb=kb, cb=cb
                ),
                T,
            )
            report(f"pallas f32 kb={kb} cb={cb}{tag}", T, dt,
                   4.0, 2 * 4 / 8)
        except Exception as exc:
            print(f"pallas kb={kb} cb={cb}{tag}: {str(exc)[:120]}",
                  flush=True)

    # raw int16 payload (the quantized tdas ingest): half the read —
    # swept over the same geometries (the winning f32 geometry is not
    # necessarily the winning int16 one: the DMA is half-width but the
    # in-kernel cast adds VPU work)
    for kb, cb in geoms:
        n_out = -(-16000 // kb) * kb
        T = stage_input_rows(B, R, n_out, kb)
        try:
            dt = measure(
                lambda x, kb=kb, cb=cb, n_out=n_out: fir_decimate_pallas(
                    x, hb, R, n_out=n_out, kb=kb, cb=cb
                ),
                T,
                dtype="int16",
            )
            report(f"pallas i16 kb={kb} cb={cb}{tag}", T, dt,
                   2.0, 2 * 4 / 8)
        except Exception as exc:
            print(f"pallas i16 kb={kb} cb={cb}{tag}: {str(exc)[:120]}",
                  flush=True)

    # XLA polyphase reference
    if not quick:
        n_out = 16128
        T = (n_out + B) * R
        dt = measure(lambda x: _polyphase_stage_xla(x, hb, R, n_out), T)
        report("xla polyphase", T, dt, 4.0, 2 * 4 / 8)


if __name__ == "__main__":
    main()
