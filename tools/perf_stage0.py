"""Stage-0 kernel harness: measure the product Pallas FIR on the chip.

The flagship cascade's first stage (R=8 guard FIR at full rate)
carries ~85% of the window's HBM traffic, so it is the tuning target.
This harness measures, under bench.py's resident scan methodology:

  read-ceiling    jnp.sum over the resident window — the practical
                  HBM read bandwidth visible to this harness (~500
                  GB/s of the v5e's 819 on the 2026-07-30 session)
  pallas stage0   the product kernel (tpudas.ops.pallas_fir) across
                  (kb, cb) grid geometries, f32 and raw int16 input
  xla stage0      the XLA polyphase formulation for reference

History (documented in PERF.md §5): the v1 VPU kernel measured
compute-bound at ~174 GB/s; single-stream auto-pipelined DMA capped at
~185 GB/s regardless of block geometry (probe_pipeline.py), which
motivated the v2 MXU banded-matmul kernel with P parallel input
streams.

Run: python tools/perf_stage0.py   (on the TPU; each config compiles)
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from tpudas.ops.fir import _block_taps, design_cascade, _polyphase_stage_xla
from tpudas.ops.pallas_fir import fir_decimate_pallas, stage_input_rows

C = 2048
ITERS = 96


def measure(fn, T, iters=ITERS, dtype="float32"):
    """bench.py's resident scan loop, standalone."""
    es = 2 if dtype == "int16" else 4
    nw = max(1, min(6, int(9e9 // (T * C * es))))
    rep = max(1, -(-iters // nw))
    if dtype == "int16":
        gen = jax.jit(
            lambda key: jax.random.randint(
                key, (nw, T, C), -3000, 3000, jnp.int16
            )
        )
    else:
        gen = jax.jit(
            lambda key: jax.random.normal(key, (nw, T, C), jnp.float32)
        )
    stack = gen(jax.random.PRNGKey(0))
    jax.block_until_ready(stack)

    @jax.jit
    def run(st):
        def body(tot, w):
            return tot + jnp.sum(jnp.abs(fn(w)).astype(jnp.float32)), None

        def outer(tot, _):
            t, _ = jax.lax.scan(body, tot, st)
            return t, None

        tot, _ = jax.lax.scan(
            outer, jnp.zeros((), jnp.float32), None, length=rep
        )
        return tot

    assert np.isfinite(float(run(stack)))
    best = 1e30
    for _ in range(2):
        t0 = time.perf_counter()
        assert np.isfinite(float(run(stack)))
        best = min(best, time.perf_counter() - t0)
    return best / (nw * rep)


def report(name, T, dt, in_bytes=4.0, extra_bytes_per_in=0.0):
    gsps = T * C / dt / 1e9
    gbps = T * C * (in_bytes + extra_bytes_per_in) / dt / 1e9
    print(
        f"{name:34s} {dt * 1e3:8.3f} ms/win  {gsps:7.2f} G ch-samp/s  "
        f"{gbps:6.1f} GB/s ({gbps / 819 * 100:4.1f}% peak)",
        flush=True,
    )


def main():
    print(f"backend={jax.default_backend()}", flush=True)
    plan = design_cascade(1000.0, 1000, 0.45, 4)
    R, h0 = plan.stages[0]
    hb = _block_taps(np.asarray(h0), R)
    B = int(hb.shape[0])
    print(f"stage0: R={R} taps={len(h0)} B={B}", flush=True)

    T0 = 129088
    dt = measure(lambda x: jnp.sum(x, axis=0), T0)
    report("read-ceiling (sum)", T0, dt)

    # product kernel: (kb, cb) sweep; kb=512 is the product default
    # (P=4 parallel 128-frame sub-blocks per grid step)
    for kb, cb in [(512, 128), (512, 256), (1024, 128), (256, 128)]:
        n_out = -(-16000 // kb) * kb
        T = stage_input_rows(B, R, n_out, kb)
        try:
            dt = measure(
                lambda x, kb=kb, cb=cb, n_out=n_out: fir_decimate_pallas(
                    x, hb, R, n_out=n_out, kb=kb, cb=cb
                ),
                T,
            )
            report(f"pallas f32 kb={kb} cb={cb}", T, dt, 4.0, 2 * 4 / 8)
        except Exception as exc:
            print(f"pallas kb={kb} cb={cb}: {str(exc)[:120]}", flush=True)

    # raw int16 payload (the quantized tdas ingest): half the read
    n_out = 16384
    T = stage_input_rows(B, R, n_out, 512)
    try:
        dt = measure(
            lambda x: fir_decimate_pallas(x, hb, R, n_out=n_out),
            T,
            dtype="int16",
        )
        report("pallas int16 kb=512 cb=128", T, dt, 2.0, 2 * 4 / 8)
    except Exception as exc:
        print(f"pallas int16: {str(exc)[:120]}", flush=True)

    # XLA polyphase reference
    n_out = 16128
    T = (n_out + B) * R
    dt = measure(lambda x: _polyphase_stage_xla(x, hb, R, n_out), T)
    report("xla polyphase", T, dt, 4.0, 2 * 4 / 8)


if __name__ == "__main__":
    main()
