"""Live-plane bench: fan-out at scale + slow-client shedding + round overhead.

Measures what ISSUE 19 promises for ``tpudas/live``:

1. **Fan-out** — one :class:`LiveHub` pushing round frames to >= 1000
   concurrent in-process subscribers (a drainer pool keeps them read),
   reporting the per-delivery publish->drain latency P50/P99 (the same
   ``note_fanout`` samples the SSE loop feeds) and the per-publish
   wall P99 across the whole roster.
2. **Stall injection** — the same roster never reads a byte.  The
   degrade ladder must fire deterministically (depth D queued, then
   ``max_level`` degrades each shedding the oldest frame, then a
   counted ``slow`` drop) and the publish wall must stay flat: slow
   clients degrade and drop, the producer never stalls (PR 4
   shed-don't-queue, applied to the push plane).
3. **Round overhead** — a real ``run_lowpass_realtime`` run with
   ``live=True`` and >= 1000 drained subscribers attached from round
   2 on.  The fraction of the round body
   (``tpudas_stream_round_body_seconds``) spent in the ``live`` phase
   (``tpudas_stream_round_phase_seconds{phase="live"}``) must be
   **< 2%**; a live-off control run of the same stream is reported
   alongside as the A/B wall check.

Acceptance (the ``ok`` flag): >= 1000 subscribers in every leg, a
measured fan-out P99, stall leg sheds (degrades == max_level * subs,
drops == subs) with publish P99 bounded, and live round overhead
< 2%.

CLI:

    JAX_PLATFORMS=cpu python tools/live_bench.py [--out BENCH_pr19.json]
        [--subs 1200] [--frames 24] [--rounds 5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

T0 = "2023-03-22T00:00:00"
# frame shape for the synthetic legs: one steady round's decimated
# output at interrogator scale (dt_out=1 s over a ~1 min round,
# 256 channels) — the fan-out cost is per-subscriber bookkeeping, not
# per-byte, but the payload should still be production-shaped
FRAME_ROWS = 60
FRAME_CH = 256
STEP_NS = 1_000_000_000

# driver leg: a steady single-file round per poll (detect_bench's
# feeding pattern), small enough for CI but real enough that the live
# phase is measured against a genuine round body
FS = 500.0
FILE_SEC = 60.0
N_CH = 64
DT_OUT = 1.0
EDGE_SEC = 5.0
PATCH_OUT = 30


def _make_frame(seq: int):
    import numpy as np

    from tpudas.live.hub import LiveFrame

    rng = np.random.default_rng(seq)
    t0 = np.datetime64(T0).astype("datetime64[ns]").astype(np.int64)
    times = (
        t0 + seq * FRAME_ROWS * STEP_NS
        + np.arange(FRAME_ROWS, dtype=np.int64) * STEP_NS
    )
    data = (0.1 * rng.standard_normal(
        (FRAME_ROWS, FRAME_CH))).astype(np.float32)
    return LiveFrame(seq, seq, times, data, [], STEP_NS)


class _DrainerPool:
    """A few threads sweeping many subscriptions: each drained frame
    feeds ``hub.note_fanout`` with its publish->drain latency, exactly
    what the SSE write loop reports per client."""

    def __init__(self, hub, subs, n_threads=4):
        self.hub = hub
        self.subs = list(subs)
        self.delivered = 0
        self._stop = threading.Event()
        self._lock = threading.Lock()
        chunk = max(1, (len(subs) + n_threads - 1) // n_threads)
        self._threads = [
            threading.Thread(
                target=self._run, args=(self.subs[i:i + chunk],),
                daemon=True,
            )
            for i in range(0, len(subs), chunk)
        ]

    def _run(self, subs):
        while not self._stop.is_set():
            moved = 0
            for sub in subs:
                while True:
                    frame = sub.next(timeout=0)
                    if frame is None:
                        break
                    self.hub.note_fanout(
                        time.perf_counter() - frame.published_perf
                    )
                    moved += 1
            if moved:
                with self._lock:
                    self.delivered += moved
            else:
                # idle sweep: yield so the publisher gets the core
                self._stop.wait(0.002)

    def start(self):
        for t in self._threads:
            t.start()
        return self

    def stop(self, settle: float = 0.5):
        # let the queues empty before tearing down
        deadline = time.perf_counter() + settle
        while time.perf_counter() < deadline:
            if all(s.qsize() == 0 for s in self.subs):
                break
            time.sleep(0.01)
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)


def bench_fanout(n_subs: int, n_frames: int) -> dict:
    """Leg 1: publish wall + delivery latency across a drained roster."""
    import numpy as np

    from tpudas.live.hub import LiveHub

    hub = LiveHub(
        "bench_fanout", queue_depth=32, max_level=2,
        max_subscribers=n_subs + 16,
    )
    subs = [hub.subscribe() for _ in range(n_subs)]
    assert all(s is not None for s in subs)
    pool = _DrainerPool(hub, subs).start()
    publish_wall = []
    try:
        for seq in range(1, n_frames + 1):
            frame = _make_frame(seq)
            t0 = time.perf_counter()
            hub.inject(frame)
            publish_wall.append(time.perf_counter() - t0)
            time.sleep(0.01)  # realistic inter-round gap (scaled down)
    finally:
        pool.stop()
    p99 = hub.fanout_p99()
    window = np.asarray(publish_wall)
    return {
        "subscribers": n_subs,
        "frames": n_frames,
        "frame_shape": [FRAME_ROWS, FRAME_CH],
        "delivered": pool.delivered,
        "published": hub.published,
        "degrades": hub.degrades,
        "frames_dropped": hub.frames_dropped,
        "subscribers_dropped": hub.subs_dropped,
        "fanout_p50_s": round(
            float(np.percentile(
                np.asarray(list(hub._fanout_s)), 50)), 6)
        if hub._fanout_s else None,
        "fanout_p99_s": None if p99 is None else round(p99, 6),
        "publish_wall_p99_s": round(float(np.percentile(window, 99)), 6),
        "publish_wall_mean_s": round(float(window.mean()), 6),
        "ok": bool(
            hub.published == n_frames
            and p99 is not None
            and pool.delivered > 0
        ),
    }


def bench_stall(n_subs: int, n_frames: int) -> dict:
    """Leg 2: nobody reads.  The ladder must shed deterministically
    and the publish wall must stay flat — the producer never blocks on
    a slow client."""
    import numpy as np

    from tpudas.live.hub import LiveHub

    depth, max_level = 8, 2
    hub = LiveHub(
        "bench_stall", queue_depth=depth, max_level=max_level,
        max_subscribers=n_subs + 16,
    )
    subs = [hub.subscribe() for _ in range(n_subs)]
    publish_wall = []
    for seq in range(1, n_frames + 1):
        frame = _make_frame(seq)
        t0 = time.perf_counter()
        hub.inject(frame)
        publish_wall.append(time.perf_counter() - t0)
    window = np.asarray(publish_wall)
    # ladder determinism at roster scale: every stalled client takes
    # exactly max_level degrade steps then one counted slow drop
    want_degrades = max_level * n_subs
    all_slow = all(s.dropped == "slow" for s in subs)
    p99 = float(np.percentile(window, 99))
    return {
        "subscribers": n_subs,
        "frames": n_frames,
        "queue_depth": depth,
        "max_level": max_level,
        "degrades": hub.degrades,
        "frames_dropped": hub.frames_dropped,
        "subscribers_dropped": hub.subs_dropped,
        "publish_wall_p99_s": round(p99, 6),
        "publish_wall_mean_s": round(float(window.mean()), 6),
        "ok": bool(
            hub.degrades == want_degrades
            and hub.subs_dropped == n_subs
            and all_slow
            and hub.n_subscribers() == 0
            and p99 < 0.25
        ),
    }


def _feed_file(src, index):
    import numpy as np

    from tpudas.testing import make_synthetic_spool

    make_synthetic_spool(
        src, n_files=1, file_duration=FILE_SEC, fs=FS, n_ch=N_CH,
        noise=0.01,
        start=np.datetime64(T0)
        + np.timedelta64(int(index * FILE_SEC * 1e9), "ns"),
        prefix=f"raw{index:04d}",
    )


def _drive(src, out, rounds, live, on_round=None):
    from tpudas.proc.streaming import run_lowpass_realtime

    fed = {"n": 2}

    def sleep(_s):
        if fed["n"] < rounds + 1:
            _feed_file(src, fed["n"])
            fed["n"] += 1

    return run_lowpass_realtime(
        source=src, output_folder=out, start_time=T0,
        output_sample_interval=DT_OUT, edge_buffer=EDGE_SEC,
        process_patch_size=PATCH_OUT, poll_interval=0.0,
        sleep_fn=sleep, live=live, on_round=on_round,
    )


def _hist(reg, metric, **labels):
    m = reg.get(metric)
    if m is None:
        return {"count": 0, "sum": 0.0}
    snap = m.snapshot(**labels)
    return {"count": snap["count"], "sum": snap["sum"]}


def bench_overhead(n_subs: int, rounds: int, workdir=None) -> dict:
    """Leg 3: live round overhead against a real driver run."""
    from tpudas.live.hub import find_hub, reset_hubs
    from tpudas.obs.registry import MetricsRegistry, use_registry

    workdir = workdir or tempfile.mkdtemp(prefix="live_bench_")
    # warm-up run: compiles the filter cascade out of the measurement
    warm_src = os.path.join(workdir, "warm_src")
    _feed_file(warm_src, 0)
    _feed_file(warm_src, 1)
    _drive(warm_src, os.path.join(workdir, "warm_out"), 2, False)

    # control: identical stream, live off
    src_a = os.path.join(workdir, "src_a")
    _feed_file(src_a, 0)
    _feed_file(src_a, 1)
    reg_a = MetricsRegistry()
    with use_registry(reg_a):
        _drive(src_a, os.path.join(workdir, "out_a"), rounds, False)
    body_a = _hist(reg_a, "tpudas_stream_round_body_seconds")

    # measured: live on, the roster attached from round 2 on
    reset_hubs()
    src_b = os.path.join(workdir, "src_b")
    out_b = os.path.join(workdir, "out_b")
    _feed_file(src_b, 0)
    _feed_file(src_b, 1)
    state = {"pool": None, "subs": []}

    def attach(_rnd, _lfp):
        if state["pool"] is not None:
            return
        hub = find_hub(folder=out_b)
        if hub is None:
            return
        state["subs"] = [hub.subscribe() for _ in range(n_subs)]
        state["pool"] = _DrainerPool(
            hub, [s for s in state["subs"] if s is not None]
        ).start()

    reg_b = MetricsRegistry()
    try:
        with use_registry(reg_b):
            _drive(src_b, out_b, rounds, True, on_round=attach)
    finally:
        if state["pool"] is not None:
            state["pool"].stop()
    body_b = _hist(reg_b, "tpudas_stream_round_body_seconds")
    live_b = _hist(
        reg_b, "tpudas_stream_round_phase_seconds", phase="live"
    )
    hub = find_hub(folder=out_b)
    overhead_pct = (
        100.0 * live_b["sum"] / body_b["sum"] if body_b["sum"] else 0.0
    )
    return {
        "subscribers": n_subs,
        "rounds": rounds,
        "fs_hz": FS, "channels": N_CH, "file_sec": FILE_SEC,
        "round_body_s_mean_live_off": round(
            body_a["sum"] / max(body_a["count"], 1), 5),
        "round_body_s_mean_live_on": round(
            body_b["sum"] / max(body_b["count"], 1), 5),
        "live_phase_s_total": round(live_b["sum"], 5),
        "live_overhead_pct": round(overhead_pct, 3),
        "frames_published": 0 if hub is None else hub.published,
        "degrades": 0 if hub is None else hub.degrades,
        "subscribers_dropped": 0 if hub is None else hub.subs_dropped,
        "fanout_p99_s": (
            None if hub is None or hub.fanout_p99() is None
            else round(hub.fanout_p99(), 6)
        ),
        "acceptance_overhead_lt_pct": 2.0,
        "ok": bool(
            overhead_pct < 2.0
            and (hub is not None and hub.published >= rounds - 1)
        ),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None, help="write JSON report here")
    ap.add_argument("--subs", type=int, default=1200,
                    help="concurrent subscribers per leg (>= 1000)")
    ap.add_argument("--frames", type=int, default=24)
    ap.add_argument("--rounds", type=int, default=5)
    args = ap.parse_args(argv)

    fanout = bench_fanout(args.subs, args.frames)
    stall = bench_stall(args.subs, args.frames)
    overhead = bench_overhead(args.subs, args.rounds)
    ok = bool(
        fanout["ok"] and stall["ok"] and overhead["ok"]
        and args.subs >= 1000
    )
    payload = {
        "bench": "live push plane (PR 19)",
        "config": {"subs": args.subs, "frames": args.frames,
                   "rounds": args.rounds},
        "fanout": fanout,
        "stall": stall,
        "overhead": overhead,
        "ok": ok,
    }
    text = json.dumps(payload, indent=1, default=str)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    print(text)
    print(
        f"live_bench: {args.subs} subscribers, fan-out "
        f"p99={fanout['fanout_p99_s']}s, stall degrades="
        f"{stall['degrades']}/drops={stall['subscribers_dropped']}, "
        f"live overhead={overhead['live_overhead_pct']}% "
        f"({'OK' if ok else 'FAILED'}, bar 2%)"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
