"""Steady-state streaming bench (CPU): stateful carry vs edge-buffer
rewind.

Drives ``run_lowpass_realtime`` twice over the same growing synthetic
spool — once in the classic rewind mode, once with the carried filter
state — and reports the structural win the stateful mode claims:

- ``samples_ratio``: full-rate samples processed per steady-state
  round, rewind / stateful (>= 1.5 at the representative config below,
  where the edge buffer is >= 0.5x the per-round data window);
- ``redundant_ratio_rewind``: fraction of rewind-mode samples that
  were re-reads (tpudas.utils.profiling.Counters.redundant_ratio);
- ``rounds_per_sec`` and mean per-round wall latency for both modes;
- ``first_output_latency_s``: wall time from driver start to the first
  output file landing on disk;
- ``head_lag_s``: stream-seconds between the newest input sample and
  the newest emitted output at the end of the run (how far behind live
  each mode's product sits);
- ``outputs_match``: max relative difference between the two modes'
  outputs over their common interior (the rewind mode is the oracle).

Since ISSUE 2 the per-mode headline numbers are read from the
tpudas.obs metrics registry (each drive runs under a fresh registry
via ``use_registry``; see ``tpudas.obs.registry.headline``) rather
than ad-hoc locals, so BENCH_*.json and a run's ``metrics.prom`` can
never disagree.  The report also measures the observability overhead:
an extra stateful drive with ``TPUDAS_OBS=0`` (instrumentation
no-oped, health off) vs one with full instrumentation +
``TPUDAS_HEALTH=1``; ``obs_overhead.overhead_pct`` is the steady-state
round-time cost (acceptance: < 2%).

Writes one JSON artifact (default ``BENCH_pr02.json`` at the repo
root) and prints it.  Pure CPU — no TPU tunnel, no subprocess dance —
so CI can run it anywhere:

    JAX_PLATFORMS=cpu python tools/stream_bench.py [--out PATH]
        [--rounds N] [--files-per-round K]

Also reachable as ``BENCH_MODE=stream python bench.py``.

Scale mode (ISSUE 7, ``BENCH_pr07.json``): ``--channels`` switches the
bench to the interrogator-scale sweep — per-width single-device vs
mesh-sharded realtime rounds over a 1 kHz synthetic spool, up to the
10,000-channel target:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python tools/stream_bench.py --channels 512,2048,10000 --mesh 4 \
        [--out BENCH_pr07.json]

Per width it reports steady-round wall, realtime factor, head lag and
a single-vs-sharded byte-identity check on the merged outputs; at the
widest configuration it additionally measures the device-resident
carry claim: host-transfer bytes per round
(``tpudas_parallel_transfer_bytes_total``) under the every-round save
cadence (the PR 6 behavior) vs ``TPUDAS_CARRY_SAVE_EVERY`` — steady
non-save rounds must move ZERO carry bytes to host (the
no-host-sync-per-round check).

Async pipelined ingest (ISSUE 15): ``--async 0|1`` pins
``TPUDAS_INGEST_PREFETCH`` for any mode (the one-command overlap
re-measurement), and ``--pr15`` runs the acceptance matrix —
``engine="fused"`` + channel mesh, sync vs async at each ``--channels``
width (default 2048,10000), per-mode round-phase breakdown tables and
merged-output byte identity — into ``BENCH_pr15.json``:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python tools/stream_bench.py --pr15 --mesh 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the representative geometry: per-round window = FILES_PER_ROUND *
# FILE_SEC seconds of new data; EDGE_SEC >= 0.5x that window, so the
# rewind re-reads >= ~half a window of full-rate data every round
FS = 100.0
FILE_SEC = 30.0
N_CH = 16
DT_OUT = 1.0
EDGE_SEC = 40.0
PATCH_OUT = 100


def _drive(src, out, rounds, files_per_round, stateful, feed,
           health=False):
    """One realtime run under a FRESH obs registry: ``feed(round_index)``
    appends that round's files before each poll.  Returns the per-round
    metrics; the headline counters come from the registry
    (tpudas.obs.registry.headline), not ad-hoc locals."""
    from tpudas.obs.registry import (
        MetricsRegistry,
        headline,
        obs_enabled,
        use_registry,
    )
    from tpudas.proc.streaming import run_lowpass_realtime
    from tpudas.utils.logging import set_log_handler
    from tpudas.utils.profiling import Counters

    events = []
    set_log_handler(events.append)
    counters = Counters()
    state = {"fed": 0, "first_out": None, "t0": time.perf_counter()}

    def fake_sleep(_):
        if state["first_out"] is None and any(
            f.endswith(".h5") for f in os.listdir(out)
        ):
            state["first_out"] = time.perf_counter() - state["t0"]
        if state["fed"] < rounds - 1:
            state["fed"] += 1
            feed(state["fed"])

    # an explicit use_registry scope overrides TPUDAS_OBS=0 (benches
    # that install a registry want numbers), so the obs_off overhead
    # baseline must NOT install one — the kill-switch then no-ops the
    # instrumentation end to end
    import contextlib

    reg = MetricsRegistry()
    scope = use_registry(reg) if obs_enabled() else contextlib.nullcontext()
    try:
        with scope:
            n_rounds = run_lowpass_realtime(
                source=src,
                output_folder=out,
                start_time="2023-03-22T00:00:00",
                output_sample_interval=DT_OUT,
                edge_buffer=EDGE_SEC,
                process_patch_size=PATCH_OUT,
                poll_interval=0.0,
                file_duration=0.0,
                sleep_fn=fake_sleep,
                max_rounds=rounds + 2,
                counters=counters,
                stateful=stateful,
                health=health,
            )
    finally:
        set_log_handler(None)
    if state["first_out"] is None and any(
        f.endswith(".h5") for f in os.listdir(out)
    ):
        state["first_out"] = time.perf_counter() - state["t0"]
    per_round = [
        e for e in events if e["event"] == "realtime_round"
    ]
    # headline numbers from the registry the run just filled; under
    # TPUDAS_OBS=0 (the overhead baseline) the registry is no-oped, so
    # fall back to the per-run Counters accumulator
    h = headline(reg)
    if not obs_enabled():
        h = {
            "channel_samples": counters.channel_samples,
            "samples_redundant": counters.samples_redundant,
            "redundant_ratio": counters.redundant_ratio,
            "realtime_factor": counters.realtime_factor,
        }
    span_hist = reg.get("tpudas_span_seconds")
    span_count = (
        sum(s[1]["count"] for s in reg.snapshot()["tpudas_span_seconds"]["series"])
        if span_hist is not None
        else 0
    )
    from tpudas.obs.phases import phase_seconds_snapshot

    return {
        "phase_seconds": phase_seconds_snapshot(reg),
        "rounds": n_rounds,
        "mode": per_round[-1]["mode"] if per_round else None,
        "obs_span_count": span_count,
        "data_seconds": [e["data_seconds"] for e in per_round],
        "wall_seconds": [e["wall_seconds"] for e in per_round],
        "counters": {
            "channel_samples": int(h["channel_samples"]),
            "samples_redundant": int(h["samples_redundant"]),
            "redundant_ratio": round(h["redundant_ratio"], 4),
            "realtime_factor": round(h["realtime_factor"], 2),
        },
        "first_output_latency_s": (
            None
            if state["first_out"] is None
            else round(state["first_out"], 3)
        ),
    }


def _instr_cost_per_round(spans_per_round, reg_ops_per_round, folder):
    """Directly measured deterministic cost of one steady round's
    instrumentation, as ``(in_round_s, health_s)``:

    - ``in_round_s`` replays what executes INSIDE the measured round —
      nested spans (with a live log handler, as the drive runs) and
      registry counter/gauge/histogram updates;
    - ``health_s`` is the per-round health.json + metrics.prom write,
      which the driver performs AFTER the measured round, in the
      inter-round idle (production rounds are separated by a >= 125 s
      poll sleep, so it never delays processing).

    Whole-drive A/B cannot resolve a percent-level effect under
    shared-CPU scheduler noise; the bundle replay measures exactly the
    added instructions."""
    from tpudas.obs.health import write_health, write_prom
    from tpudas.obs.registry import (
        MetricsRegistry,
        get_registry,
        use_registry,
    )
    from tpudas.obs.trace import span
    from tpudas.utils.logging import set_log_handler

    payload = {
        "rounds": 1, "polls": 1, "mode": "stateful",
        "realtime_factor": 100.0, "round_realtime_factor": 100.0,
        "head_lag_seconds": 10.0, "redundant_ratio": 0.0,
        "carry_resume_count": 0, "last_round_wall_seconds": 0.05,
        "consecutive_failures": 0, "quarantined_files": 0,
        "degraded": False, "last_error": None,
    }
    os.makedirs(folder, exist_ok=True)
    sink = []
    reg = MetricsRegistry()
    n = 200
    set_log_handler(sink.append)
    try:
        with use_registry(reg):
            t0 = time.perf_counter()
            for _ in range(n):
                with span("stream.round", mode="stateful", round=1):
                    with span("stream.increment", upto="t"):
                        for _ in range(max(1, spans_per_round - 2)):
                            with span(
                                "op.cascade_stream", rows=3200,
                                engine="auto",
                            ):
                                pass
                        for _ in range(reg_ops_per_round // 3 + 1):
                            # resolve get_registry() per op, exactly
                            # as real instrumentation sites do (the
                            # env lookup is part of the cost)
                            get_registry().counter(
                                "tpudas_stream_blocks_total",
                                labelnames=("engine",),
                            ).inc(engine="cascade-xla")
                            get_registry().histogram(
                                "tpudas_stream_block_seconds",
                                labelnames=("engine",),
                            ).observe(0.01, engine="cascade-xla")
                            get_registry().gauge(
                                "tpudas_stream_realtime_factor"
                            ).set(100.0)
            in_round = (time.perf_counter() - t0) / n
            t0 = time.perf_counter()
            for _ in range(n):
                write_health(folder, dict(payload))
                write_prom(folder)
            health = (time.perf_counter() - t0) / n
    finally:
        set_log_handler(None)
    return in_round, health


def _merged(out):
    from tpudas.io.spool import spool

    merged = spool(out).update().chunk(time=None)
    assert len(merged) == 1, f"output of {out} has seams"
    return merged[0]


def run(out_path, rounds=4, files_per_round=2):
    import tempfile

    from tpudas.testing import make_synthetic_spool

    t_bench0 = time.perf_counter()
    results = {}
    # the rewind mode's window schedule needs its first grid to exceed
    # patch > 2*edge points, so the initial backlog must cover more
    # than PATCH_OUT output steps; steady-state rounds then add
    # files_per_round * FILE_SEC each
    n_init = max(
        files_per_round, int(np.ceil((PATCH_OUT + 20) * DT_OUT / FILE_SEC))
    )
    with tempfile.TemporaryDirectory() as td:
        srcs = {}
        for mode in ("rewind", "stateful"):
            src = os.path.join(td, f"src_{mode}")
            make_synthetic_spool(
                src,
                n_files=n_init,
                file_duration=FILE_SEC,
                fs=FS,
                n_ch=N_CH,
                noise=0.01,
            )
            srcs[mode] = src

        def feeder(mode):
            def feed(r):
                make_synthetic_spool(
                    srcs[mode],
                    n_files=files_per_round,
                    file_duration=FILE_SEC,
                    fs=FS,
                    n_ch=N_CH,
                    noise=0.01,
                    start=np.datetime64("2023-03-22T00:00:00")
                    + np.timedelta64(
                        int(
                            (n_init + (r - 1) * files_per_round)
                            * FILE_SEC
                            * 1e9
                        ),
                        "ns",
                    ),
                    prefix=f"raw{r}",
                )

            return feed

        outs = {}
        for mode, stateful in (("rewind", False), ("stateful", True)):
            out = os.path.join(td, f"out_{mode}")
            t0 = time.perf_counter()
            results[mode] = _drive(
                srcs[mode], out, rounds, files_per_round, stateful,
                feeder(mode),
            )
            results[mode]["total_wall_s"] = round(
                time.perf_counter() - t0, 3
            )
            outs[mode] = out
            # head lag: newest input vs newest output
            from tpudas.io.spool import spool

            t_in = np.datetime64(
                spool(srcs[mode]).update().get_contents()["time_max"].max()
            ).astype("datetime64[ns]")
            p = _merged(out)
            t_out = np.datetime64(
                p.coords["time"][-1], "ns"
            )
            results[mode]["head_lag_s"] = round(
                float((t_in - t_out) / np.timedelta64(1, "s")), 3
            )
            results[mode]["output_rows"] = int(p.shape[0])

        # cross-mode numeric agreement over the common interior
        a = _merged(outs["stateful"])
        b = _merged(outs["rewind"])
        lo = max(a.coords["time"][0], b.coords["time"][0])
        hi = min(a.coords["time"][-1], b.coords["time"][-1])
        av = a.select(time=(lo, hi)).host_data()
        bv = b.select(time=(lo, hi)).host_data()
        rel = float(np.abs(av - bv).max() / np.abs(bv).max())

        # instrumentation overhead: the same stateful drive with the
        # obs kill-switch on (TPUDAS_OBS=0, health off) vs fully
        # instrumented + per-round health.json/metrics.prom writes.
        # A steady round is tens of ms on shared CPU, where scheduler
        # noise dwarfs the instrumentation, so estimate the
        # DETERMINISTIC cost floor: the MIN steady-state round over
        # several interleaved repetitions per mode (noise only ever
        # inflates a round; the floor is the honest per-round cost).
        ov_rounds = max(rounds, 8)
        ov_reps = 3
        obs_walls = {"obs_off": [], "obs_on": []}
        for rep in range(ov_reps):
            for tag, env_val, health in (
                ("obs_off", "0", False),
                ("obs_on", "1", True),
            ):
                key = f"{tag}{rep}"
                src = os.path.join(td, f"src_{key}")
                make_synthetic_spool(
                    src, n_files=n_init, file_duration=FILE_SEC, fs=FS,
                    n_ch=N_CH, noise=0.01,
                )
                srcs[key] = src
                prev = os.environ.get("TPUDAS_OBS")
                os.environ["TPUDAS_OBS"] = env_val
                try:
                    r = _drive(
                        src, os.path.join(td, f"out_{key}"), ov_rounds,
                        files_per_round, True, feeder(key),
                        health=health,
                    )
                finally:
                    if prev is None:
                        os.environ.pop("TPUDAS_OBS", None)
                    else:
                        os.environ["TPUDAS_OBS"] = prev
                walls = r["wall_seconds"][1:]  # steady: skip backlog
                if walls:
                    obs_walls[tag].append(min(walls))
                if tag == "obs_on":
                    last_on = r
        floor = {k: min(v) if v else 0.0 for k, v in obs_walls.items()}
        # per-round instrumentation volume observed by the last
        # instrumented drive, overcounted 2x for safety
        spans_pr = 2 * max(
            1,
            int(
                last_on["obs_span_count"]
                / max(last_on["rounds"], 1)
            ),
        )
        in_round_s, health_s = _instr_cost_per_round(
            spans_pr, 3 * spans_pr, os.path.join(td, "instr_bundle")
        )
        obs_overhead = {
            "steady_round_wall_s": {
                k: round(v, 5) for k, v in floor.items()
            },
            "rounds": ov_rounds,
            "reps": ov_reps,
            "ab_floor_delta_pct": (
                round(
                    100.0 * (floor["obs_on"] - floor["obs_off"])
                    / floor["obs_off"],
                    2,
                )
                if floor.get("obs_off")
                else None
            ),
            # the acceptance number: deterministic replay of the
            # IN-ROUND instrumentation (2x overcounted span/registry
            # volume) as a fraction of the uninstrumented steady
            # round — whole-drive A/B (ab_floor_delta_pct) is
            # noise-bound on shared CPU.  The health.json/metrics.prom
            # write runs AFTER the measured round in the inter-round
            # idle (>= 125 s poll sleep in production) and is reported
            # separately.
            "in_round_instr_s": round(in_round_s, 6),
            "health_write_s_off_path": round(health_s, 6),
            "spans_per_round_replayed": spans_pr,
            "overhead_pct": (
                round(100.0 * in_round_s / floor["obs_off"], 2)
                if floor.get("obs_off")
                else None
            ),
            "note": (
                "ab_floor_delta_pct swings +-8% (incl. negative) "
                "across runs on this shared CPU — a ~40 ms round "
                "cannot resolve a sub-ms effect; overhead_pct is the "
                "deterministic bundle replay (2x-overcounted op "
                "volume, get_registry() resolved per op like real "
                "sites)"
            ),
        }

    # steady-state per-round workload: skip round 1 (both modes chew
    # the identical initial backlog there)
    def steady(d):
        ds = d["data_seconds"][1:]
        return sum(ds) / len(ds) if ds else 0.0

    sr, ss = steady(results["rewind"]), steady(results["stateful"])
    per_round_wall = {
        m: (
            sum(results[m]["wall_seconds"]) / len(results[m]["wall_seconds"])
            if results[m]["wall_seconds"]
            else 0.0
        )
        for m in results
    }
    report = {
        "metric": "stream_redundancy",
        "config": {
            "fs": FS,
            "n_ch": N_CH,
            "dt_out": DT_OUT,
            "edge_sec": EDGE_SEC,
            "file_sec": FILE_SEC,
            "files_per_round": files_per_round,
            "rounds": rounds,
            "edge_over_window": round(
                EDGE_SEC / (files_per_round * FILE_SEC), 3
            ),
        },
        # the acceptance number: full-rate samples per steady round,
        # rewind / stateful (>= 1.5 means the carry eliminated at
        # least a third of the rewind mode's per-round work)
        "samples_ratio": round(sr / ss, 3) if ss else None,
        "steady_round_data_seconds": {
            "rewind": round(sr, 3),
            "stateful": round(ss, 3),
        },
        "redundant_ratio_rewind": results["rewind"]["counters"][
            "redundant_ratio"
        ],
        "redundant_ratio_stateful": results["stateful"]["counters"][
            "redundant_ratio"
        ],
        "rounds_per_sec": {
            m: (
                round(results[m]["rounds"] / results[m]["total_wall_s"], 3)
                if results[m]["total_wall_s"]
                else None
            )
            for m in results
        },
        "round_latency_s": {
            m: round(per_round_wall[m], 4) for m in per_round_wall
        },
        "first_output_latency_s": {
            m: results[m]["first_output_latency_s"] for m in results
        },
        "head_lag_s": {m: results[m]["head_lag_s"] for m in results},
        "outputs_match_rel_err": round(rel, 8),
        "outputs_match": rel < 1e-4,
        # the round-phase timeline (ISSUE 13): where the stateful
        # mode's wall time goes, per phase — the baseline ROADMAP
        # item 1 (async pipelined ingest) must beat
        "phase_breakdown": results["stateful"]["phase_seconds"],
        "headline_source": "tpudas.obs.registry",
        "obs_overhead": obs_overhead,
        "modes": results,
        "bench_wall_s": round(time.perf_counter() - t_bench0, 2),
    }
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=1)
        fh.write("\n")
    # human-readable phase table (the JSON keeps the full numbers)
    phases = report["phase_breakdown"]
    if phases:
        total = sum(p["sum"] for p in phases.values()) or 1.0
        print("round-phase breakdown (stateful mode):")
        print(f"  {'phase':<15}{'mean_s':>10}{'share':>8}")
        for name, p in phases.items():
            print(
                f"  {name:<15}{p['mean']:>10.4f}"
                f"{100.0 * p['sum'] / total:>7.1f}%"
            )
    print(json.dumps(report))
    return report


# ---------------------------------------------------------------------------
# scale mode (ISSUE 7): interrogator-width single-vs-sharded sweep

SCALE_FS = 1000.0  # the paper's kHz interrogator rate
SCALE_FILE_SEC = 8.0
SCALE_DT_OUT = 1.0  # 1000x decimation, the flagship config
SCALE_EDGE_SEC = 16.0


def _drive_scale(src, out, rounds, mesh, save_every=1,
                 feed=None, on_round_extra=None, engine=None,
                 patch=64, prefetch=None):
    """One scale-mode realtime run under a fresh registry.  Returns
    (registry, per-round samples): each sample holds the round's wall
    seconds, data seconds, and the cumulative host-transfer counters
    read INSIDE on_round — the per-round deltas are the
    no-host-sync-per-round evidence.

    ``engine`` forwards to the driver (the --pr15 mode runs "fused");
    ``prefetch`` pins ``TPUDAS_INGEST_PREFETCH`` for this drive only
    (None = leave the environment alone) — the async-ingest A/B."""
    from tpudas.obs.registry import MetricsRegistry, use_registry
    from tpudas.proc.streaming import run_lowpass_realtime
    from tpudas.utils.logging import set_log_handler
    from tpudas.utils.profiling import Counters

    events = []
    set_log_handler(events.append)
    counters = Counters()
    state = {"fed": 0}

    def fake_sleep(_):
        if state["fed"] < rounds - 1:
            state["fed"] += 1
            feed(state["fed"])

    reg = MetricsRegistry()
    samples = []

    def on_round(rnd, _lfp):
        samples.append({
            "round": rnd,
            "gather_bytes": reg.value(
                "tpudas_parallel_transfer_bytes_total",
                direction="gather",
            ),
            "place_bytes": reg.value(
                "tpudas_parallel_transfer_bytes_total",
                direction="place",
            ),
            "carry_saves": reg.value("tpudas_stream_carry_saves_total"),
        })
        if on_round_extra is not None:
            on_round_extra(rnd)

    prev_prefetch = os.environ.get("TPUDAS_INGEST_PREFETCH")
    if prefetch is not None:
        os.environ["TPUDAS_INGEST_PREFETCH"] = str(int(prefetch))
    try:
        with use_registry(reg):
            run_lowpass_realtime(
                source=src,
                output_folder=out,
                start_time="2023-03-22T00:00:00",
                output_sample_interval=SCALE_DT_OUT,
                edge_buffer=SCALE_EDGE_SEC,
                process_patch_size=patch,
                poll_interval=0.0,
                file_duration=0.0,
                sleep_fn=fake_sleep,
                max_rounds=rounds + 2,
                counters=counters,
                mesh=mesh,
                engine=engine,
                carry_save_every=save_every,
                on_round=on_round,
                health=False,
                pyramid=False,
                detect=False,
            )
    finally:
        set_log_handler(None)
        if prefetch is not None:
            if prev_prefetch is None:
                os.environ.pop("TPUDAS_INGEST_PREFETCH", None)
            else:
                os.environ["TPUDAS_INGEST_PREFETCH"] = prev_prefetch
    per_round = [e for e in events if e["event"] == "realtime_round"]
    for s, e in zip(samples, per_round):
        s["wall_s"] = e["wall_seconds"]
        s["data_s"] = e["data_seconds"]
    return reg, samples


def _scale_feeder(src, n_init, files_per_round, n_ch):
    from tpudas.testing import make_synthetic_spool

    def feed(r):
        make_synthetic_spool(
            src,
            n_files=files_per_round,
            file_duration=SCALE_FILE_SEC,
            fs=SCALE_FS,
            n_ch=n_ch,
            noise=0.01,
            format="tdas",
            write_kwargs={"dtype": "int16", "scale": 1e-3},
            start=np.datetime64("2023-03-22T00:00:00")
            + np.timedelta64(
                int(
                    (n_init + (r - 1) * files_per_round)
                    * SCALE_FILE_SEC * 1e9
                ),
                "ns",
            ),
            prefix=f"raw{r}",
        )

    return feed


def run_scale(out_path, channels, mesh_n, rounds=4, save_every=4):
    """The ISSUE 7 sweep: per-width single-device vs mesh-sharded
    realtime throughput + head lag, byte-identity of the merged
    outputs, and — at the widest configuration — per-round host
    transfer under both carry-save cadences."""
    import tempfile

    from tpudas.testing import make_synthetic_spool

    # the host-transfer section compares this cadence against the
    # every-round baseline; 1 would collide the two measurement tags
    save_every = max(2, int(save_every))
    t_bench0 = time.perf_counter()
    try:
        n_cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        n_cores = os.cpu_count() or 1
    widths = []
    n_init = 2
    for n_ch in channels:
        with tempfile.TemporaryDirectory() as td:
            per_mode = {}
            # mesh=0 (not None) for the baseline: an explicit argument
            # beats a TPUDAS_MESH in the caller's environment, so the
            # "single" leg can never silently run sharded
            for mode, mesh in (("single", 0), ("sharded", mesh_n)):
                src = os.path.join(td, f"src_{mode}")
                out = os.path.join(td, f"out_{mode}")
                make_synthetic_spool(
                    src, n_files=n_init, file_duration=SCALE_FILE_SEC,
                    fs=SCALE_FS, n_ch=n_ch, noise=0.01, format="tdas",
                    write_kwargs={"dtype": "int16", "scale": 1e-3},
                )
                t0 = time.perf_counter()
                reg, samples = _drive_scale(
                    src, out, rounds, mesh,
                    save_every=save_every,
                    feed=_scale_feeder(src, n_init, 1, n_ch),
                )
                total = time.perf_counter() - t0
                steady = [s["wall_s"] for s in samples[1:]]
                steady_wall = min(steady) if steady else None
                data_s = samples[-1]["data_s"] if samples else 0.0
                p = _merged(out)
                t_in = SCALE_FILE_SEC * (n_init + rounds - 1)
                t_out = (
                    np.datetime64(p.coords["time"][-1], "ns")
                    - np.datetime64("2023-03-22T00:00:00", "ns")
                ) / np.timedelta64(1, "s")
                per_mode[mode] = {
                    "steady_round_wall_s": (
                        None if steady_wall is None
                        else round(steady_wall, 3)
                    ),
                    "round_data_seconds": round(data_s, 3),
                    "realtime_factor": (
                        None if not steady_wall
                        else round(data_s / steady_wall, 2)
                    ),
                    "head_lag_s": round(float(t_in - t_out), 3),
                    "total_wall_s": round(total, 2),
                    "channel_samples_per_s": (
                        None if not steady_wall
                        else int(data_s * SCALE_FS * n_ch / steady_wall)
                    ),
                    "rounds": len(samples),
                    "gather_bytes_total": samples[-1]["gather_bytes"]
                    if samples else 0,
                }
                per_mode[mode]["_patch"] = p
            a = per_mode["single"].pop("_patch")
            b = per_mode["sharded"].pop("_patch")
            identical = bool(
                np.array_equal(a.host_data(), b.host_data())
                and np.array_equal(a.coords["time"], b.coords["time"])
            )
            f_single = per_mode["single"]["realtime_factor"] or 0
            f_shard = per_mode["sharded"]["realtime_factor"] or 0
            widths.append({
                "n_ch": n_ch,
                **{m: per_mode[m] for m in per_mode},
                "outputs_byte_identical": identical,
                "sharded_speedup": (
                    round(f_shard / f_single, 3) if f_single else None
                ),
            })
            print(json.dumps(widths[-1]))

    # device-resident carry: per-round host transfer at the widest
    # width, every-round save cadence (the PR 6 behavior: the whole
    # pytree serialized each round) vs the deferred cadence
    n_ch = max(channels)
    transfer = {}
    with tempfile.TemporaryDirectory() as td:
        for tag, every in (("save_every_1", 1), (f"save_every_{save_every}",
                                                 save_every)):
            src = os.path.join(td, f"src_{tag}")
            out = os.path.join(td, f"out_{tag}")
            make_synthetic_spool(
                src, n_files=n_init, file_duration=SCALE_FILE_SEC,
                fs=SCALE_FS, n_ch=n_ch, noise=0.01, format="tdas",
                write_kwargs={"dtype": "int16", "scale": 1e-3},
            )
            reg, samples = _drive_scale(
                src, out, rounds, mesh_n, save_every=every,
                feed=_scale_feeder(src, n_init, 1, n_ch),
            )
            deltas = [
                samples[i]["gather_bytes"] - samples[i - 1]["gather_bytes"]
                for i in range(1, len(samples))
            ]
            saves = [
                samples[i]["carry_saves"] - samples[i - 1]["carry_saves"]
                for i in range(1, len(samples))
            ]
            transfer[tag] = {
                "gather_bytes_per_round": deltas,
                "carry_saves_per_round": saves,
                "mean_gather_bytes_per_round": (
                    int(sum(deltas) / len(deltas)) if deltas else 0
                ),
                "non_save_rounds_move_zero_bytes": all(
                    d == 0 for d, s in zip(deltas, saves) if s == 0
                ),
            }
    base = transfer["save_every_1"]["mean_gather_bytes_per_round"]
    tail = transfer[f"save_every_{save_every}"][
        "mean_gather_bytes_per_round"
    ]
    # deferred-cadence steady rounds gathering ZERO bytes reads as a
    # reduction by the full baseline (max(tail, 1) keeps it finite)
    transfer["reduction_factor"] = round(base / max(tail, 1), 2)

    ten_k = next((w for w in widths if w["n_ch"] >= 10000), None)
    report = {
        "metric": "sharded_streaming_scale",
        "config": {
            "fs": SCALE_FS,
            "dt_out": SCALE_DT_OUT,
            "file_sec": SCALE_FILE_SEC,
            "rounds": rounds,
            "mesh": mesh_n,
            "carry_save_every": save_every,
            "host_cores": n_cores,
            "spool_format": "tdas int16",
        },
        "widths": widths,
        "host_transfer": transfer,
        "headline_source": "tpudas.obs.registry",
        "all_outputs_byte_identical": all(
            w["outputs_byte_identical"] for w in widths
        ),
        "realtime_factor_10k": (
            None if ten_k is None
            else {m: ten_k[m]["realtime_factor"]
                  for m in ("single", "sharded")}
        ),
        "note": (
            "sharded_speedup needs spare cores: with <= mesh-width "
            "physical cores the single-device XLA run already "
            "saturates the machine and channel sharding can only tie "
            "it (PERF.md 'Sharded streaming: when sharding loses')"
        ),
        "bench_wall_s": round(time.perf_counter() - t_bench0, 2),
    }
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=1)
        fh.write("\n")
    print(json.dumps(report))
    return report


# ---------------------------------------------------------------------------
# pr15 mode (ISSUE 15): fused + mesh + ASYNC PIPELINED INGEST A/B

# several slices per round so the prefetch pipeline has lookahead to
# exploit: 4 files x 8 s per round, 8-output (8 s) ingest slices
PR15_FILES_PER_ROUND = 4
PR15_PATCH_OUT = 8


def _print_phase_table(title, phases):
    if not phases:
        return
    total = sum(p["sum"] for p in phases.values()) or 1.0
    print(f"round-phase breakdown ({title}):")
    print(f"  {'phase':<15}{'mean_s':>10}{'share':>8}")
    for name, p in phases.items():
        print(
            f"  {name:<15}{p['mean']:>10.4f}"
            f"{100.0 * p['sum'] / total:>7.1f}%"
        )


def run_pr15(out_path, channels, mesh_n, rounds=4):
    """The ISSUE 15 acceptance bench: engine="fused" + channel mesh +
    async pipelined ingest, A/B against the synchronous slice loop
    (``TPUDAS_INGEST_PREFETCH=0``) at each width, with per-mode
    round-phase breakdown tables (the before/after evidence that
    read_decode/place overlapped into compute) and merged-output byte
    identity between the two modes."""
    import tempfile

    from tpudas.obs.phases import (
        ingest_pipeline_snapshot,
        phase_seconds_snapshot,
    )
    from tpudas.proc.ingest import ingest_depth
    from tpudas.testing import make_synthetic_spool

    depth = max(2, ingest_depth())
    t_bench0 = time.perf_counter()
    try:
        n_cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        n_cores = os.cpu_count() or 1
    n_init = PR15_FILES_PER_ROUND
    widths = []
    for n_ch in channels:
        with tempfile.TemporaryDirectory() as td:
            per_mode = {}
            for mode, pf in (("sync", 0), ("async", depth)):
                src = os.path.join(td, f"src_{mode}")
                out = os.path.join(td, f"out_{mode}")
                make_synthetic_spool(
                    src, n_files=n_init, file_duration=SCALE_FILE_SEC,
                    fs=SCALE_FS, n_ch=n_ch, noise=0.01, format="tdas",
                    write_kwargs={"dtype": "int16", "scale": 1e-3},
                )
                t0 = time.perf_counter()
                reg, samples = _drive_scale(
                    src, out, rounds, mesh_n, save_every=4,
                    feed=_scale_feeder(
                        src, n_init, PR15_FILES_PER_ROUND, n_ch
                    ),
                    engine="fused", patch=PR15_PATCH_OUT, prefetch=pf,
                )
                total = time.perf_counter() - t0
                steady = [s["wall_s"] for s in samples[1:]]
                steady_wall = min(steady) if steady else None
                data_s = samples[-1]["data_s"] if samples else 0.0
                p = _merged(out)
                per_mode[mode] = {
                    "steady_round_wall_s": (
                        None if steady_wall is None
                        else round(steady_wall, 3)
                    ),
                    "round_data_seconds": round(data_s, 3),
                    "realtime_factor": (
                        None if not steady_wall
                        else round(data_s / steady_wall, 2)
                    ),
                    "rounds": len(samples),
                    "total_wall_s": round(total, 2),
                    "fused_rounds": reg.value(
                        "tpudas_fir_fused_rounds_total",
                        engine="fused-xla",
                    ),
                    "phase_seconds": phase_seconds_snapshot(reg),
                    "ingest": ingest_pipeline_snapshot(reg),
                }
                per_mode[mode]["_patch"] = p
            a = per_mode["sync"].pop("_patch")
            b = per_mode["async"].pop("_patch")
            identical = bool(
                np.array_equal(a.host_data(), b.host_data())
                and np.array_equal(a.coords["time"], b.coords["time"])
            )
            f_sync = per_mode["sync"]["realtime_factor"] or 0
            f_async = per_mode["async"]["realtime_factor"] or 0
            widths.append({
                "n_ch": n_ch,
                **per_mode,
                "outputs_byte_identical": identical,
                "async_speedup": (
                    round(f_async / f_sync, 3) if f_sync else None
                ),
            })
            print(f"--- n_ch={n_ch} ---")
            _print_phase_table(
                f"{n_ch} ch sync", per_mode["sync"]["phase_seconds"]
            )
            _print_phase_table(
                f"{n_ch} ch async", per_mode["async"]["phase_seconds"]
            )
            print(json.dumps({
                k: v for k, v in widths[-1].items()
                if k in ("n_ch", "outputs_byte_identical",
                         "async_speedup")
            }))
    ten_k = next((w for w in widths if w["n_ch"] >= 10000), None)
    two_k = next((w for w in widths if w["n_ch"] == 2048), None)
    report = {
        "metric": "async_pipelined_ingest",
        "config": {
            "fs": SCALE_FS,
            "dt_out": SCALE_DT_OUT,
            "file_sec": SCALE_FILE_SEC,
            "files_per_round": PR15_FILES_PER_ROUND,
            "patch_out": PR15_PATCH_OUT,
            "rounds": rounds,
            "mesh": mesh_n,
            "engine": "fused",
            "prefetch_depth": depth,
            "host_cores": n_cores,
            "spool_format": "tdas int16 (in-kernel dequant)",
        },
        "widths": widths,
        "all_outputs_byte_identical": all(
            w["outputs_byte_identical"] for w in widths
        ),
        "realtime_factor_10k_async": (
            None if ten_k is None
            else ten_k["async"]["realtime_factor"]
        ),
        "async_speedup_10k": (
            None if ten_k is None else ten_k["async_speedup"]
        ),
        "async_speedup_2048": (
            None if two_k is None else two_k["async_speedup"]
        ),
        "headline_source": "tpudas.obs.registry",
        "note": (
            "the overlap win is bounded by spare host cores: the "
            "prefetch thread and the XLA compute compete for the same "
            "core when host_cores is small, so on a 1-core host the "
            "win reduces to the work the pipeline ELIMINATES (raw "
            "int16 ships to the device and dequantizes in-kernel — no "
            "host astype+scale copy — and the deferred per-block sync "
            "removes bounce latency); on multi-core edge hardware the "
            "read_decode phase overlaps into compute entirely"
        ),
        "bench_wall_s": round(time.perf_counter() - t_bench0, 2),
    }
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=1)
        fh.write("\n")
    print(json.dumps({
        k: report[k] for k in (
            "realtime_factor_10k_async", "async_speedup_10k",
            "async_speedup_2048", "all_outputs_byte_identical",
        )
    }))
    return report


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None)
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--files-per-round", type=int, default=2)
    ap.add_argument(
        "--channels", default=None,
        help="comma-separated channel widths: switches to the "
        "ISSUE 7 scale sweep (BENCH_pr07.json)",
    )
    ap.add_argument(
        "--mesh", type=int, default=4,
        help="channel-shard count for the scale sweep's sharded mode",
    )
    ap.add_argument(
        "--save-every", type=int, default=4,
        help="deferred carry-save cadence measured by the scale sweep",
    )
    ap.add_argument(
        "--async", dest="async_ingest", type=int, choices=(0, 1),
        default=None,
        help="pin async pipelined ingest on/off for this run "
        "(TPUDAS_INGEST_PREFETCH; default: inherit the environment) — "
        "the one-command A/B for the overlap win",
    )
    ap.add_argument(
        "--pr15", action="store_true",
        help="ISSUE 15 acceptance bench: engine='fused' + mesh + "
        "async-ingest A/B per width with round-phase breakdown "
        "tables (BENCH_pr15.json)",
    )
    args = ap.parse_args()
    if args.async_ingest is not None:
        os.environ["TPUDAS_INGEST_PREFETCH"] = (
            "0" if args.async_ingest == 0 else "2"
        )
    if args.pr15:
        channels = [
            int(c) for c in (args.channels or "2048,10000").split(",")
            if c
        ]
        report = run_pr15(
            args.out or os.path.join(REPO, "BENCH_pr15.json"),
            channels, args.mesh, rounds=args.rounds,
        )
        sys.exit(0 if report["all_outputs_byte_identical"] else 1)
    if args.channels:
        if args.save_every < 2:
            ap.error(
                "--save-every must be >= 2 in scale mode: the "
                "host-transfer section compares it against the "
                "every-round baseline"
            )
        channels = [int(c) for c in args.channels.split(",") if c]
        report = run_scale(
            args.out or os.path.join(REPO, "BENCH_pr07.json"),
            channels, args.mesh, rounds=args.rounds,
            save_every=args.save_every,
        )
        ok = (
            report["all_outputs_byte_identical"]
            and report["host_transfer"][
                f"save_every_{args.save_every}"
            ]["non_save_rounds_move_zero_bytes"]
            and (report["host_transfer"]["reduction_factor"] or 0) > 1.0
        )
        sys.exit(0 if ok else 1)
    report = run(
        args.out or os.path.join(REPO, "BENCH_pr02.json"),
        rounds=args.rounds, files_per_round=args.files_per_round
    )
    # loud, parseable verdict for CI
    ok = (
        report["outputs_match"]
        and (report["samples_ratio"] or 0) >= 1.5
        and report["redundant_ratio_stateful"] == 0.0
    )
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
